"""paddle.distributed-style collective API.

Parity surface: /root/reference/python/paddle/distributed/ (launch.py,
collective wrappers fluid/layers/collective.py:20-172) and the c_* op
family (operators/collective/).

TPU-native design: a "process group" is a named mesh axis. Collectives are
the jax.lax primitives over that axis; they run inside a manual-SPMD region
(`shard_map` over the mesh), which is how the reference's per-rank SPMD
program view maps onto single-controller JAX. Two usage levels:

1. In-shard functions (all_reduce, all_gather, ...): call inside a
   shard_map body — the direct analog of calling c_allreduce_sum inside a
   per-rank program.
2. `collective(fn, mesh, in_specs, out_specs)`: wrap a per-rank function
   over global arrays (builds the shard_map), the analog of running a
   transpiled per-rank program under the launcher.

Multi-host bootstrap (reference launch.py + gen_nccl_id) is
`init_parallel_env()` → jax.distributed.initialize.
"""
from __future__ import annotations

from typing import Optional, Sequence

from ..parallel.env import get_rank, get_world_size, init_parallel_env  # noqa: F401
from ..parallel import create_mesh  # noqa: F401
from ..parallel.ring_attention import ring_attention, ring_attention_global  # noqa: F401


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"


def all_reduce(tensor, op: str = ReduceOp.SUM, group: str = "dp"):
    """Reduce across the `group` mesh axis (in-shard; reference
    c_allreduce_{sum,max,min,prod}_op)."""
    import jax.numpy as jnp
    from jax import lax

    if op == ReduceOp.SUM:
        return lax.psum(tensor, group)
    if op == ReduceOp.MAX:
        return lax.pmax(tensor, group)
    if op == ReduceOp.MIN:
        return lax.pmin(tensor, group)
    if op == ReduceOp.PROD:
        # no lax.pprod primitive: gather then reduce (exp(psum(log)) would
        # NaN on negatives and lose precision)
        return jnp.prod(lax.all_gather(tensor, group, axis=0), axis=0)
    raise ValueError(f"unknown reduce op {op!r}")


def all_gather(tensor, group: str = "dp", axis: int = 0):
    """Concatenate every participant's tensor along `axis` (reference
    c_allgather_op)."""
    from jax import lax

    return lax.all_gather(tensor, group, axis=axis, tiled=True)


def reduce_scatter(tensor, group: str = "dp", axis: int = 0):
    """Sum across participants, scatter blocks of `axis` (reference
    c_reducescatter_op)."""
    from jax import lax

    return lax.psum_scatter(tensor, group, scatter_dimension=axis, tiled=True)


def broadcast(tensor, src: int = 0, group: str = "dp"):
    """Every participant gets rank `src`'s tensor (reference c_broadcast_op)."""
    import jax.numpy as jnp
    from jax import lax

    idx = lax.axis_index(group)
    return lax.psum(jnp.where(idx == src, tensor, jnp.zeros_like(tensor)), group)


def reduce(tensor, dst: int = 0, op: str = ReduceOp.SUM, group: str = "dp"):
    """Reduce to rank `dst`; other ranks get zeros (reference c_reduce_op)."""
    import jax.numpy as jnp
    from jax import lax

    total = all_reduce(tensor, op, group)
    idx = lax.axis_index(group)
    return jnp.where(idx == dst, total, jnp.zeros_like(total))


def scatter(tensor, src: int = 0, group: str = "dp", axis: int = 0):
    """Rank `src`'s tensor is split along `axis`; rank i gets block i
    (reference c_scatter_op)."""
    import jax.numpy as jnp
    from jax import lax

    full = broadcast(tensor, src, group)
    n = lax.psum(1, group)
    idx = lax.axis_index(group)
    if full.shape[axis] % n != 0:
        raise ValueError(
            f"scatter: dim {axis} of size {full.shape[axis]} is not "
            f"divisible by the group size {n}"
        )
    block = full.shape[axis] // n
    return lax.dynamic_slice_in_dim(full, idx * block, block, axis)


def send_recv(tensor, perm: Sequence, group: str = "dp"):
    """Point-to-point ring exchange: perm is [(src, dst), ...] pairs
    (lax.ppermute; the analog of the reference's send/recv ops on ICI)."""
    from jax import lax

    return lax.ppermute(tensor, group, list(perm))


def barrier(group: str = "dp"):
    """Reference barrier op: under single-program XLA the whole step is one
    synchronized computation, so this is a no-op kept for API parity."""
    return None


def collective(fn, mesh, in_specs, out_specs, check_vma: bool = False):
    """Run per-rank `fn` over global arrays on `mesh` (shard_map wrapper).
    check_vma keeps its public name; compat maps it onto whatever the
    installed jax calls the replication check."""
    from ..compat import shard_map

    return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     check=check_vma)


def get_group(axis: str = "dp"):
    """Parity helper: a 'group' is just the mesh axis name."""
    return axis
