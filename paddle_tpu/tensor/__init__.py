"""paddle.tensor 2.0-preview namespace (reference python/paddle/tensor/:
creation.py, math.py, manipulation.py, search.py, logic.py, linalg.py,
stat.py — ~5.7k LoC of re-exports and signature modernization over
fluid.layers).

Same role here: 2.0-style names/signatures (axis= instead of dim=,
keepdim= instead of keep_dim=) emitting the same registered ops in
STATIC-GRAPH mode. For eager code use paddle_tpu.nn.functional (its
emitter dispatches per mode) or the dygraph VarBase operators.
"""
from __future__ import annotations

from ..fluid import layers as L
from ..fluid.layers import (  # noqa: F401 — direct re-exports
    cast, concat, gather, gather_nd, scatter, scatter_nd_add, reshape,
    transpose, squeeze, unsqueeze, stack, unstack, split, expand_as, tile,
    flip, roll, where, argsort, clip, zeros, ones, zeros_like, ones_like,
    full_like, linspace, eye, arange, meshgrid, diag, tril, triu, cumsum,
    index_select, one_hot, topk, matmul, dot, kron, addmm, trace, cholesky,
    inverse, matrix_power, allclose, equal, not_equal, less_than, less_equal,
    greater_than, greater_equal, logical_and, logical_or, logical_xor,
    logical_not, isfinite_v2 as isfinite, isnan_v2 as isnan, isinf_v2 as isinf,
    abs, exp, log, log2, log10, log1p, sqrt, rsqrt, square, sign, sin, cos,
    tan, asin, acos, atan, sinh, cosh, erf, floor, ceil, round, reciprocal,
    tanh, sigmoid, increment, unbind, take_along_axis, flatten,
)


def full(shape, fill_value, dtype="float32", name=None):
    return L.fill_constant(shape, dtype, fill_value)


def add(x, y, name=None):
    return L.elementwise_add(x, y)


def subtract(x, y, name=None):
    return L.elementwise_sub(x, y)


def multiply(x, y, name=None):
    return L.elementwise_mul(x, y)


def divide(x, y, name=None):
    return L.elementwise_div(x, y)


def floor_divide(x, y, name=None):
    return L.elementwise_floordiv(x, y)


def remainder(x, y, name=None):
    return L.elementwise_mod(x, y)


mod = remainder


def pow(x, y, name=None):
    if isinstance(y, (int, float)):
        return L.pow(x, factor=float(y))
    return L.elementwise_pow(x, y)


def maximum(x, y, name=None):
    return L.elementwise_max(x, y)


def minimum(x, y, name=None):
    return L.elementwise_min(x, y)


def _axes(axis):
    if axis is None:
        return None
    return [axis] if isinstance(axis, int) else list(axis)


def _reduce(fn, x, axis, keepdim):
    return fn(x, dim=_axes(axis), keep_dim=keepdim)


def sum(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return _reduce(L.reduce_sum, x, axis, keepdim)


def mean(x, axis=None, keepdim=False, name=None):
    return _reduce(L.reduce_mean, x, axis, keepdim)


def max(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return _reduce(L.reduce_max, x, axis, keepdim)


def min(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return _reduce(L.reduce_min, x, axis, keepdim)


def prod(x, axis=None, keepdim=False, name=None):
    return _reduce(L.reduce_prod, x, axis, keepdim)


def all(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return _reduce(L.reduce_all, x, axis, keepdim)


def any(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return _reduce(L.reduce_any, x, axis, keepdim)


def argmax(x, axis=0, keepdim=False, name=None):
    return L.argmax(x, axis=axis)


def argmin(x, axis=0, keepdim=False, name=None):
    return L.argmin(x, axis=axis)


def norm(x, p=2.0, axis=None, keepdim=False, name=None):
    from ..fluid.layer_helper import LayerHelper

    if axis is None:
        helper = LayerHelper("frobenius_norm", name=name)
        out = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op(
            type="frobenius_norm", inputs={"X": [x]}, outputs={"Out": [out]},
            attrs={"reduce_all": True, "keep_dim": keepdim},
        )
        return out
    helper = LayerHelper("p_norm", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="p_norm", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"porder": float(p), "axis": int(axis), "keepdim": keepdim},
    )
    return out


def logsumexp(x, axis=None, keepdim=False, name=None):
    from ..fluid.layer_helper import LayerHelper

    helper = LayerHelper("logsumexp", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="logsumexp", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"axis": _axes(axis) or [], "keepdim": keepdim},
    )
    return out


def bmm(x, y, name=None):
    return L.matmul(x, y)


def t(x, name=None):
    return L.transpose(x, list(range(len(x.shape)))[::-1])


def numel(x, name=None):
    import numpy as np

    dims = list(x.shape or ())
    if any(d < 0 for d in dims):
        raise ValueError(
            f"numel needs fully static dims, got {tuple(dims)}"
        )
    return L.fill_constant([1], "int64", int(np.prod(dims)))


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    return L.scale(x, scale=scale, bias=bias, bias_after_scale=bias_after_scale,
                   act=act)
