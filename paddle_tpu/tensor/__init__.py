"""paddle.tensor 2.0-preview namespace (reference python/paddle/tensor/:
creation.py, math.py, manipulation.py, search.py, logic.py, linalg.py,
stat.py — ~5.7k LoC of re-exports and signature modernization over
fluid.layers).

Same role here: 2.0-style names/signatures (axis= instead of dim=,
keepdim= instead of keep_dim=) emitting the same registered ops in
STATIC-GRAPH mode. For eager code use paddle_tpu.nn.functional (its
emitter dispatches per mode) or the dygraph VarBase operators.
"""
from __future__ import annotations

from ..fluid import layers as L
from ..fluid.layers import (  # noqa: F401 — direct re-exports
    cast, concat, gather, gather_nd, scatter, scatter_nd_add, reshape,
    transpose, squeeze, unsqueeze, stack, unstack, split, expand_as, tile,
    flip, roll, where, argsort, clip, zeros, ones, zeros_like, ones_like,
    full_like, linspace, eye, arange, meshgrid, diag, tril, triu, cumsum,
    index_select, one_hot, topk, matmul, dot, kron, addmm, trace, cholesky,
    inverse, matrix_power, allclose, equal, not_equal, less_than, less_equal,
    greater_than, greater_equal, logical_and, logical_or, logical_xor,
    logical_not, isfinite_v2 as isfinite, isnan_v2 as isnan, isinf_v2 as isinf,
    abs, exp, log, log2, log10, log1p, sqrt, rsqrt, square, sign, sin, cos,
    tan, asin, acos, atan, sinh, cosh, erf, floor, ceil, round, reciprocal,
    tanh, sigmoid, increment, unbind, take_along_axis, flatten,
)


def full(shape, fill_value, dtype="float32", name=None):
    return L.fill_constant(shape, dtype, fill_value)


def add(x, y, name=None):
    return L.elementwise_add(x, y)


def subtract(x, y, name=None):
    return L.elementwise_sub(x, y)


def multiply(x, y, name=None):
    return L.elementwise_mul(x, y)


def divide(x, y, name=None):
    return L.elementwise_div(x, y)


def floor_divide(x, y, name=None):
    return L.elementwise_floordiv(x, y)


def remainder(x, y, name=None):
    return L.elementwise_mod(x, y)


mod = remainder


def pow(x, y, name=None):
    if isinstance(y, (int, float)):
        return L.pow(x, factor=float(y))
    return L.elementwise_pow(x, y)


def maximum(x, y, name=None):
    return L.elementwise_max(x, y)


def minimum(x, y, name=None):
    return L.elementwise_min(x, y)


def _axes(axis):
    if axis is None:
        return None
    return [axis] if isinstance(axis, int) else list(axis)


def _reduce(fn, x, axis, keepdim):
    return fn(x, dim=_axes(axis), keep_dim=keepdim)


def sum(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return _reduce(L.reduce_sum, x, axis, keepdim)


def mean(x, axis=None, keepdim=False, name=None):
    return _reduce(L.reduce_mean, x, axis, keepdim)


def max(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return _reduce(L.reduce_max, x, axis, keepdim)


def min(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return _reduce(L.reduce_min, x, axis, keepdim)


def prod(x, axis=None, keepdim=False, name=None):
    return _reduce(L.reduce_prod, x, axis, keepdim)


def all(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return _reduce(L.reduce_all, x, axis, keepdim)


def any(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return _reduce(L.reduce_any, x, axis, keepdim)


def argmax(x, axis=0, keepdim=False, name=None):
    return L.argmax(x, axis=axis)


def argmin(x, axis=0, keepdim=False, name=None):
    return L.argmin(x, axis=axis)


def norm(x, p=2.0, axis=None, keepdim=False, name=None):
    from ..fluid.layer_helper import LayerHelper

    if axis is None:
        helper = LayerHelper("frobenius_norm", name=name)
        out = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op(
            type="frobenius_norm", inputs={"X": [x]}, outputs={"Out": [out]},
            attrs={"reduce_all": True, "keep_dim": keepdim},
        )
        return out
    helper = LayerHelper("p_norm", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="p_norm", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"porder": float(p), "axis": int(axis), "keepdim": keepdim},
    )
    return out


def logsumexp(x, axis=None, keepdim=False, name=None):
    from ..fluid.layer_helper import LayerHelper

    helper = LayerHelper("logsumexp", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="logsumexp", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"axis": _axes(axis) or [], "keepdim": keepdim},
    )
    return out


def bmm(x, y, name=None):
    return L.matmul(x, y)


def t(x, name=None):
    return L.transpose(x, list(range(len(x.shape)))[::-1])


def numel(x, name=None):
    import numpy as np

    dims = list(x.shape or ())
    if any(d < 0 for d in dims):
        raise ValueError(
            f"numel needs fully static dims, got {tuple(dims)}"
        )
    return L.fill_constant([1], "int64", int(np.prod(dims)))


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    return L.scale(x, scale=scale, bias=bias, bias_after_scale=bias_after_scale,
                   act=act)


# ---------------------------------------------------------------------------
# 2.0 breadth: aliases + new tensor ops (reference python/paddle/tensor/)
# ---------------------------------------------------------------------------

from ..fluid.layers import (  # noqa: F401,E402 — 1.x names kept in 2.0
    elementwise_add, elementwise_sub, elementwise_mul, elementwise_div,
    elementwise_floordiv, elementwise_mod, elementwise_pow,
    elementwise_max, elementwise_min, reduce_all, reduce_any, reduce_max,
    reduce_min, reduce_mean, reduce_prod, reduce_sum, fill_constant,
    multiplex, rank, is_empty, crop_tensor, expand, assign, mul,
    create_tensor, has_inf, has_nan, beam_search, beam_search_decode,
    gaussian_random, uniform_random,
)
from ..fluid.layers.misc import load  # noqa: F401,E402


def clamp(x, min=None, max=None, name=None):
    """2.0 alias of clip."""
    lo = -3.4e38 if min is None else min
    hi = 3.4e38 if max is None else max
    return L.clip(x, lo, hi)


def mm(input, mat2, name=None):
    return L.matmul(input, mat2)


def div(x, y, name=None):
    return L.elementwise_div(x, y)


def elementwise_sum(inputs, name=None):
    return L.sum(inputs)


def addcmul(input, tensor1, tensor2, value=1.0, name=None):
    """input + value * tensor1 * tensor2 (reference tensor/math.py)."""
    return L.elementwise_add(
        input, L.scale(L.elementwise_mul(tensor1, tensor2), scale=value))


def cross(x, y, axis=None, name=None):
    """3-D cross product along `axis` (default: first dim of size 3)."""
    shape = x.shape
    if axis is None:
        axis = next(i for i, s in enumerate(shape) if s == 3)

    def comp(i):
        return L.squeeze(L.slice(x, [axis], [i], [i + 1]), [axis]), \
            L.squeeze(L.slice(y, [axis], [i], [i + 1]), [axis])

    (x0, y0), (x1, y1), (x2, y2) = comp(0), comp(1), comp(2)
    c0 = L.elementwise_sub(L.elementwise_mul(x1, y2), L.elementwise_mul(x2, y1))
    c1 = L.elementwise_sub(L.elementwise_mul(x2, y0), L.elementwise_mul(x0, y2))
    c2 = L.elementwise_sub(L.elementwise_mul(x0, y1), L.elementwise_mul(x1, y0))
    return L.stack([c0, c1, c2], axis=axis)


def dist(x, y, p=2, name=None):
    """p-norm of (x - y) (reference tensor/linalg.py dist)."""
    d = L.elementwise_sub(x, y)
    if p == 0:
        nz = L.cast(L.not_equal(d, L.zeros_like(d)), "float32")
        return L.reduce_sum(nz)
    if p == float("inf"):
        return L.reduce_max(L.abs(d))
    if p == float("-inf"):
        return L.reduce_min(L.abs(d))
    powd = L.elementwise_pow(
        L.abs(d), L.fill_constant([1], "float32", float(p)))
    return L.elementwise_pow(
        L.reduce_sum(powd), L.fill_constant([1], "float32", 1.0 / p))


def histogram(input, bins=100, min=0, max=0, name=None):
    """Histogram with static bins (reference tensor/linalg.py histogram);
    min == max == 0 uses the data range like the reference."""
    from ..fluid.layer_helper import emit_op

    return emit_op("histogram", {"X": [input]},
                   {"bins": int(bins), "min": float(min), "max": float(max)},
                   out_dtype="int32")


def index_sample(x, index):
    """Per-row gather: out[i, j] = x[i, index[i, j]] (reference
    tensor/search.py index_sample)."""
    return L.take_along_axis(x, index, axis=1)


def nonzero(x, as_tuple=False):
    """Indices of non-zero elements. STATIC-shape contract: returns
    ([numel, ndim] padded with -1 rows, count) — XLA cannot emit
    data-dependent shapes; slice host-side with the count."""
    from ..fluid.layer_helper import emit_op

    out, count = emit_op("nonzero_static", {"X": [x]}, {},
                         out_slots=("Out", "Count"), out_dtype="int32")
    if as_tuple:
        raise NotImplementedError("nonzero(as_tuple=True): use the padded "
                                  "[numel, ndim] form on TPU")
    return out, count


def equal_all(x, y, name=None):
    return L.reduce_all(L.cast(L.equal(x, y), "bool"))


def rand(shape, dtype="float32", name=None):
    return L.uniform_random(shape, dtype, min=0.0, max=1.0)


def randn(shape, dtype="float32", name=None):
    return L.gaussian_random(shape, mean=0.0, std=1.0, dtype=dtype)


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    u = L.uniform_random(list(shape), "float32", min=float(low),
                         max=float(high))
    # floor, not trunc: int cast truncates toward zero, which doubles the
    # mass at 0 and starves `low` whenever low < 0
    return L.cast(L.floor(u), dtype)


def randperm(n, dtype="int64", name=None):
    from ..fluid.layer_helper import emit_op
    from ..fluid.layers.nn import _rng_salt_counter

    _rng_salt_counter[0] += 1
    return emit_op("randperm", {}, {"n": int(n), "dtype": dtype,
                                    "rng_salt": _rng_salt_counter[0]},
                   out_dtype=dtype)


from ..fluid.layers import (  # noqa: F401,E402
    scatter_nd, shard_index, slice, strided_slice, stanh, unique,
    unique_with_counts, shape, reverse, sum as sums,
)


def sort(x, axis=-1, descending=False, name=None):
    """Returns (sorted values, indices) like the reference tensor.sort."""
    sorted_x, idx = L.argsort(x, axis=axis, descending=descending)
    return sorted_x, idx


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return L.sqrt(var(x, axis=axis, unbiased=unbiased, keepdim=keepdim))


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    import numpy as _np

    dims = (list(range(len(x.shape))) if axis is None
            else [axis] if isinstance(axis, int) else list(axis))
    n = int(_np.prod([x.shape[d] for d in dims]))
    mean = L.reduce_mean(x, dim=dims, keep_dim=True)
    sq = L.square(L.elementwise_sub(x, mean))
    out = L.reduce_mean(sq, dim=dims, keep_dim=keepdim)
    if unbiased and n > 1:
        out = L.scale(out, scale=n / (n - 1))
    return out


def shuffle(x, name=None):
    """Random row permutation (reference paddle.shuffle)."""
    perm = randperm(x.shape[0])
    return L.gather(x, perm)


def save(x, path):
    """Persist one tensor to an .npy file (reference tensor save op)."""
    import numpy as _np

    _np.save(path, _np.asarray(x))
