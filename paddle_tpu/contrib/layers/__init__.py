"""fluid.contrib.layers (reference python/paddle/fluid/contrib/layers/
nn.py) — the contrib op surface. Currently: tree_conv (TBCNN)."""
from __future__ import annotations

from ...fluid.layer_helper import LayerHelper
from ...fluid.param_attr import ParamAttr

__all__ = ["tree_conv"]


def tree_conv(nodes_vector, edge_set, output_size, num_filters=1,
              max_depth=2, act="tanh", param_attr=None, bias_attr=None,
              name=None):
    """Tree-based convolution over (NodesVector [B, N, FS], EdgeSet
    [B, E, 2]) — reference contrib/layers/nn.py tree_conv over
    tree_conv_op.cc. Returns [B, N, output_size, num_filters]."""
    helper = LayerHelper("tree_conv", name=name, bias_attr=bias_attr,
                         act=act)
    feature_size = nodes_vector.shape[2]
    w = helper.create_parameter(
        ParamAttr._to_attr(param_attr),
        shape=[feature_size, 3, output_size, num_filters],
        dtype="float32",
    )
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="tree_conv",
        inputs={"NodesVector": [nodes_vector], "EdgeSet": [edge_set],
                "Filter": [w]},
        outputs={"Out": [out]},
        attrs={"max_depth": int(max_depth)},
    )
    out = helper.append_bias_op(out, dim_start=3)
    return helper.append_activation(out)
