"""AMP op lists.

Parity: /root/reference/python/paddle/fluid/contrib/mixed_precision/fp16_lists.py.
White = compute in low precision (MXU ops), black = keep float32
(reductions / loss / normalization statistics), gray = follow neighbors
(here: left untouched; mixed-dtype elementwise promotes to f32 naturally).
"""
from __future__ import annotations


class AutoMixedPrecisionLists:
    def __init__(self, custom_white_list=None, custom_black_list=None):
        self.white_list = set(white_list)
        self.black_list = set(black_list)
        if custom_white_list:
            self.white_list |= set(custom_white_list)
            self.black_list -= set(custom_white_list)
        if custom_black_list:
            self.black_list |= set(custom_black_list)
            self.white_list -= set(custom_black_list)


white_list = {
    "matmul",
    "matmul_v2",
    "mul",
    "conv2d",
    "conv3d",
    "depthwise_conv2d",
    "conv2d_transpose",
    "fused_multihead_attention",
    # the whole fused stack runs in bf16; its emitter keeps layer_norm and
    # softmax internals in f32 (ops/encoder_stack.py) so this is safe
    "fused_encoder_stack",
    "fused_decoder_stack",
    "fc",
    # these emitters compute statistics in f32 internally (ops/nn_ops.py),
    # so bf16 in/out only halves the residual-stream bandwidth
    "layer_norm",
    "batch_norm",
    # fused conv+BN(+relu): conv on the MXU in bf16, statistics and the
    # normalize chain in f32 inside the kernel (ops/pallas/conv_bn.py)
    "fused_conv_bn",
}

black_list = {
    "softmax_with_cross_entropy",
    "cross_entropy",
    "cross_entropy2",
    "group_norm",
    "instance_norm",
    "reduce_sum",
    "reduce_mean",
    "mean",
    "sum",
    "softmax",
    "log_softmax",
    "exp",
    "square",
    "sigmoid_cross_entropy_with_logits",
    "bce_loss",
    "squared_l2_norm",
}
