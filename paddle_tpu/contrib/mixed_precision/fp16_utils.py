"""AMP graph rewrite: insert casts around white/black-listed ops.

Parity: /root/reference/python/paddle/fluid/contrib/mixed_precision/fp16_utils.py
(rewrite_program:190, update_loss_scaling helpers :333).

TPU-native notes: the low-precision dtype defaults to bfloat16 (MXU
native; no loss scaling needed). float16 is kept for parity and uses the
same dynamic loss scaling protocol as the reference. Master weights are
implicit: parameters stay float32 and are cast at use — the cast's vjp
accumulates gradients back in float32, which is exactly the
master-weight contract.
"""
from __future__ import annotations

from typing import Set

from ...fluid import framework
from ...fluid.dtypes import convert_dtype


def _is_float(dtype) -> bool:
    import numpy as np

    return np.dtype(dtype).kind == "f" and np.dtype(dtype).itemsize >= 2


def rewrite_program(program, amp_lists, dest_dtype="bfloat16"):
    """Walk block-0 ops; before each white op insert casts of its float32
    inputs to `dest_dtype`, before each black op casts of low-precision
    inputs back to float32. Shapes/dtypes of downstream vars are re-inferred
    op by op as the rewrite proceeds."""
    import numpy as np

    block = program.global_block()
    dest = convert_dtype(dest_dtype)
    f32 = np.dtype("float32")

    # walk in program order, re-inferring each op after its (possible)
    # input rewiring — downstream cast decisions then see current dtypes
    # (a white op's bf16 output decides where black-op casts fire)
    i = 0
    while i < len(block.ops):
        op = block.ops[i]
        if op.type == "cast":
            i += 1
            continue
        if op.type in amp_lists.white_list:
            i += _cast_op_inputs(block, i, op, want=dest, source_kind=f32)
        elif op.type in amp_lists.black_list:
            i += _cast_op_inputs(block, i, op, want=f32, source_kind=dest)
        framework.infer_op_outputs(block, op)
        i += 1
    program._amp_enabled = True
    program._bump_version()


# input slots AMP must NEVER down-cast on white-listed ops: running
# statistics and affine params whose f32 state is written back each step
# (casting Mean/Variance would quantize the moving averages to bf16
# every step, and an is_test pass would clobber the f32 stat params)
_KEEP_F32_SLOTS = {
    "batch_norm": {"Mean", "Variance", "Scale", "Bias"},
    "fused_conv_bn": {"Mean", "Variance", "Scale", "Bias"},
    "layer_norm": {"Scale", "Bias"},
}


def _cast_op_inputs(block, idx, op, want, source_kind) -> int:
    """Insert cast ops before block.ops[idx] for inputs of dtype
    source_kind; rewires op inputs. Returns #ops inserted."""
    import numpy as np

    from ...fluid import unique_name

    keep = _KEEP_F32_SLOTS.get(op.type, ())
    inserted = 0
    for slot, names in list(op.inputs.items()):
        if slot in keep and np.dtype(want) != np.dtype(np.float32):
            continue
        new_names = []
        for n in names:
            v = block._find_var_recursive(n)
            if v is None or v.dtype is None or np.dtype(v.dtype) != np.dtype(source_kind):
                new_names.append(n)
                continue
            cast_name = unique_name.generate(f"{n}.cast_{np.dtype(want).name}")
            block.create_var(
                name=cast_name, shape=v.shape, dtype=want, stop_gradient=v.stop_gradient
            )
            block._insert_op(
                idx + inserted,
                type="cast",
                inputs={"X": [n]},
                outputs={"Out": [cast_name]},
                attrs={"in_dtype": v.dtype, "out_dtype": np.dtype(want)},
                infer=False,
            )
            new_names.append(cast_name)
            inserted += 1
        op.inputs[slot] = new_names
    return inserted


def cast_parameters_to_bf16(program):  # parity helper (reference fp16_utils)
    raise NotImplementedError(
        "parameters stay float32 (implicit master weights); pure-bf16 "
        "serving uses save_inference_model + a bf16 rewrite of the pruned graph"
    )
