"""AMP optimizer decorator.

Parity: /root/reference/python/paddle/fluid/contrib/mixed_precision/decorator.py
(decorate:218, OptimizerWithMixedPrecision:27, backward:112).

bfloat16 is the TPU default (no loss scaling: bf16 has the f32 exponent
range). float16 mode keeps the reference's dynamic loss-scaling protocol:
scale the loss, unscale grads, detect inf/nan, grow/shrink the scale, and
zero the grads on overflow so the whole step stays one XLA program
(branch-free; the reference conditionally skips the update instead).
"""
from __future__ import annotations

from typing import Optional

from ...fluid import framework, layers
from ...fluid.initializer import ConstantInitializer
from .fp16_lists import AutoMixedPrecisionLists
from .fp16_utils import rewrite_program


class OptimizerWithMixedPrecision:
    def __init__(
        self,
        optimizer,
        amp_lists: Optional[AutoMixedPrecisionLists] = None,
        init_loss_scaling: float = 2.0 ** 15,
        use_dynamic_loss_scaling: bool = True,
        incr_every_n_steps: int = 1000,
        decr_every_n_nan_or_inf: int = 2,
        incr_ratio: float = 2.0,
        decr_ratio: float = 0.8,
        use_bf16: bool = True,
    ):
        self._optimizer = optimizer
        self._amp_lists = amp_lists or AutoMixedPrecisionLists()
        self._dest_dtype = "bfloat16" if use_bf16 else "float16"
        self._use_dynamic_loss_scaling = use_dynamic_loss_scaling and not use_bf16
        self._init_loss_scaling = init_loss_scaling if not use_bf16 else 1.0
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._loss_scaling = None

    def get_loss_scaling(self):
        return self._loss_scaling

    def _create_scaling_state(self):
        def persist(name, value):
            main_block = framework.default_main_program().global_block()
            v = main_block.create_var(
                name=name, shape=(1,), dtype="float32", persistable=True
            )
            sblock = framework.default_startup_program().global_block()
            sv = sblock.create_var(
                name=name, shape=(1,), dtype="float32", persistable=True
            )
            ConstantInitializer(value)(sv, sblock)
            return v

        from ...fluid import unique_name

        self._loss_scaling = persist(
            unique_name.generate("loss_scaling"), self._init_loss_scaling
        )
        if self._use_dynamic_loss_scaling:
            self._good_steps = persist(unique_name.generate("good_steps"), 0.0)
            self._bad_steps = persist(unique_name.generate("bad_steps"), 0.0)
            # numerics observability (ISSUE 12): the scale var already
            # rides the step's state outputs, so growth/backoff events
            # become countable host-side without any graph change —
            # numerics_amp_scale_{growths,backoffs}_total counters +
            # kind="numerics" amp_scale sink records with step numbers
            from ...telemetry import numerics as _numerics

            _numerics.register_amp_scale(
                self._loss_scaling.name,
                good_name=self._good_steps.name,
                bad_name=self._bad_steps.name)

    def backward(self, loss, startup_program=None, parameter_list=None, no_grad_set=None, callbacks=None):
        program = loss.block.program
        # fuse BEFORE the cast rewrite: the matcher sees the raw
        # conv2d->batch_norm[->relu] triples, and the fused op then takes
        # its own white-list casts (Input/Filter bf16, stats kept f32)
        from ...fluid.fusion_pass import maybe_apply_conv_bn_fusion

        maybe_apply_conv_bn_fusion(program)
        rewrite_program(program, self._amp_lists, self._dest_dtype)
        self._create_scaling_state()
        with framework.program_guard(program, startup_program or framework.default_startup_program()):
            scaled_loss = layers.elementwise_mul(loss, self._loss_scaling)
        params_grads = self._optimizer.backward(
            scaled_loss, startup_program, parameter_list, no_grad_set, callbacks
        )
        return scaled_loss, params_grads

    def apply_gradients(self, params_grads):
        return self._apply(params_grads)

    def _apply(self, params_grads):
        if self._dest_dtype == "bfloat16" and not self._use_dynamic_loss_scaling:
            # bf16 has f32 exponent range: scale stays 1.0 and overflow
            # can't occur from the cast itself, so the unscale +
            # found_inf pass (a full extra read of every gradient) is
            # pure overhead — feed f32 grads straight to the optimizer
            with framework.program_guard(
                params_grads[0][0].block.program,
                framework.default_startup_program(),
            ):
                final = []
                for p, g in params_grads:
                    if g is not None and str(g.dtype) != "float32":
                        g = layers.cast(g, "float32")
                    final.append((p, g))
                return self._optimizer.apply_gradients(final)
        grads = [g for _, g in params_grads if g is not None]
        with framework.program_guard(
            params_grads[0][0].block.program, framework.default_startup_program()
        ):
            inv = layers.elementwise_div(
                layers.fill_constant([1], "float32", 1.0), self._loss_scaling
            )
            # found_inf = any grad non-finite (after cast to f32)
            found_inf = layers.fill_constant([1], "bool", 0.0)
            new_pgs = []
            for p, g in params_grads:
                if g is None:
                    new_pgs.append((p, g))
                    continue
                g32 = layers.cast(g, "float32") if str(g.dtype) != "float32" else g
                bad = layers.logical_not(
                    layers.reduce_all(layers.isfinite_v2(g32))
                )
                found_inf = layers.logical_or(found_inf, bad)
                new_pgs.append((p, g32))
            keep = layers.cast(layers.logical_not(found_inf), "float32")
            zero = layers.fill_constant([1], "float32", 0.0)
            final = []
            for p, g in new_pgs:
                if g is None:
                    final.append((p, g))
                    continue
                # zero-on-overflow must SELECT, not multiply: inf * 0
                # is NaN, so the old keep-multiply poisoned the params
                # with NaN on the very overflow step it meant to skip
                # (found while unifying the bad-step guard, ISSUE 12).
                # where() drops the non-finite entries first; the keep
                # factor then kills the rest of the overflowed step.
                g = layers.where(layers.isfinite_v2(g), g, zero)
                g = layers.elementwise_mul(g, layers.elementwise_mul(inv, keep))
                final.append((p, g))
            if self._use_dynamic_loss_scaling:
                self._update_loss_scaling(found_inf)
            return self._optimizer.apply_gradients(final)

    def _update_loss_scaling(self, found_inf):
        """Branch-free grow/shrink of the scale (reference
        fp16_utils.update_loss_scaling:333 semantics)."""
        bad = layers.cast(found_inf, "float32")
        good = layers.scale(bad, scale=-1.0, bias=1.0)
        # counters
        new_good = layers.elementwise_mul(
            layers.increment(self._good_steps, 1.0, in_place=False), good
        )
        new_bad = layers.elementwise_mul(
            layers.increment(self._bad_steps, 1.0, in_place=False), bad
        )
        grow = layers.cast(
            layers.greater_equal(
                new_good, layers.fill_constant([1], "float32", float(self._incr_every_n_steps))
            ),
            "float32",
        )
        shrink = layers.cast(
            layers.greater_equal(
                new_bad, layers.fill_constant([1], "float32", float(self._decr_every_n_nan_or_inf))
            ),
            "float32",
        )
        factor = (
            1.0
            + grow * (self._incr_ratio - 1.0)
        )
        factor = layers.elementwise_mul(
            factor, layers.scale(shrink, scale=self._decr_ratio - 1.0, bias=1.0)
        )
        new_scale = layers.elementwise_mul(self._loss_scaling, factor)
        layers.assign(new_scale, self._loss_scaling)
        from ...fluid.flags import flag as _flag

        if _flag("FLAGS_check_numerics"):
            # unified bad-step guard (ISSUE 12): FLAGS_check_numerics
            # used to watch fp32 grads only while AMP kept its own
            # zero-and-shrink protocol with no terminal condition. Here
            # an overflow step that pushes the scale BELOW the floor
            # (FLAGS_check_numerics_amp_scale_floor) means backoff is
            # EXHAUSTED — the model produces non-finite values at any
            # scale — so a check_numerics_bad_amp_* guard var trips,
            # Executor.run raises BadStepError and the NaN-provenance
            # doctor dumps a numrec for the AMP run too. Transient
            # overflows (scale still above the floor) keep AMP's skip
            # semantics: the guard stays 0 and training continues.
            from ...fluid import unique_name as _un
            from ...fluid.initializer import ConstantInitializer as _CI

            floor = float(_flag("FLAGS_check_numerics_amp_scale_floor"))
            floor_c = layers.fill_constant([1], "float32", floor)
            exhausted = layers.logical_and(
                found_inf, layers.less_than(new_scale, floor_c))
            name = _un.generate("check_numerics_bad_amp")
            main_block = framework.default_main_program().global_block()
            guard = main_block.create_var(
                name=name, shape=(1,), dtype="float32",
                persistable=True, stop_gradient=True)
            sblock = framework.default_startup_program().global_block()
            sv = sblock.create_var(
                name=name, shape=(1,), dtype="float32", persistable=True)
            _CI(0.0)(sv, sblock)
            layers.assign(layers.cast(exhausted, "float32"), guard)
        # reset counters when they fire
        layers.assign(
            layers.elementwise_mul(new_good, layers.scale(grow, scale=-1.0, bias=1.0)),
            self._good_steps,
        )
        layers.assign(
            layers.elementwise_mul(new_bad, layers.scale(shrink, scale=-1.0, bias=1.0)),
            self._bad_steps,
        )

    def apply_optimize(self, loss, startup_program, params_grads):
        """Same contract as Optimizer.apply_optimize — THIS level's
        apply_gradients (unscale/f32-cast), not the inner's. Lets
        backward-then-apply callers (fleet's hybrid_dcn wrappers, which
        insert c_dcn_grad_sync between the two) compose with AMP without
        __getattr__ silently bypassing the gradient post-processing."""
        with framework.program_guard(
            loss.block.program,
            startup_program or framework.default_startup_program(),
        ):
            return self.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        scaled_loss, params_grads = self.backward(
            loss, startup_program, parameter_list, no_grad_set
        )
        with framework.program_guard(
            loss.block.program,
            startup_program or framework.default_startup_program(),
        ):
            optimize_ops = self.apply_gradients(params_grads)
        return optimize_ops, params_grads

    def __getattr__(self, item):
        return getattr(self._optimizer, item)


def decorate(
    optimizer,
    amp_lists=None,
    init_loss_scaling=2.0 ** 15,
    incr_every_n_steps=1000,
    decr_every_n_nan_or_inf=2,
    incr_ratio=2.0,
    decr_ratio=0.8,
    use_dynamic_loss_scaling=True,
    use_bf16=True,
):
    """reference decorator.py:218 — wrap an optimizer with AMP."""
    return OptimizerWithMixedPrecision(
        optimizer,
        amp_lists=amp_lists,
        init_loss_scaling=init_loss_scaling,
        use_dynamic_loss_scaling=use_dynamic_loss_scaling,
        incr_every_n_steps=incr_every_n_steps,
        decr_every_n_nan_or_inf=decr_every_n_nan_or_inf,
        incr_ratio=incr_ratio,
        decr_ratio=decr_ratio,
        use_bf16=use_bf16,
    )
