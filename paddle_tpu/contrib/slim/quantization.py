"""Quantization passes (reference contrib/slim/quantization/
quantization_pass.py QuantizationTransformPass +
post_training_quantization.py PostTrainingQuantization, ~6k LoC).

Two entry points:

  quant_aware(program, startup)      — QAT: rewrite the program so every
      quantizable op sees quantize-dequantized weights (abs-max of the
      live value) and activations (EMA abs-max state var); training
      converges with int8 error in the loop, gradients flow via STE.

  PostTrainingQuantization           — PTQ: run calibration batches
      through the float program, record per-tensor abs-max for the
      inputs of quantizable ops, then emit a program with fixed-scale
      quant-dequant ops (save with save_inference_model as usual).

Simulated-int8 design note: on TPU the MXU executes int8 natively; the
fake-quant form keeps the program float (XLA fuses the qdq into the
matmul) and preserves exact reference semantics for scale search.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ...fluid import framework, unique_name
from ...fluid.initializer import ConstantInitializer

QUANTIZABLE_OP_TYPES = ("mul", "matmul", "matmul_v2", "conv2d",
                        "depthwise_conv2d")

# op type -> (activation input slot, weight input slot)
_SLOTS = {
    "mul": ("X", "Y"),
    "matmul": ("X", "Y"),
    "matmul_v2": ("X", "Y"),
    "conv2d": ("Input", "Filter"),
    "depthwise_conv2d": ("Input", "Filter"),
}


def _is_param(block, name):
    v = block._find_var_recursive(name)
    return isinstance(v, framework.Parameter)


def quant_aware(program, startup_program=None, weight_bits=8,
                activation_bits=8, moving_rate=0.9,
                quantizable_op_types=QUANTIZABLE_OP_TYPES,
                for_test=False):
    """QAT rewrite (reference QuantizationTransformPass.apply). Must run
    BEFORE append_backward/minimize so grad ops see the quantized graph."""
    startup = startup_program or framework.default_startup_program()
    block = program.global_block()
    quantized: Dict[str, str] = {}  # src var -> its qdq output (reference
    # QuantizationTransformPass.dequantized_vars: a tensor feeding N
    # quantizable ops gets ONE qdq op and one scale state)
    i = 0
    while i < len(block.ops):
        op = block.ops[i]
        if op.type not in quantizable_op_types or op.attr("__quantized__"):
            i += 1
            continue
        op._set_attr("__quantized__", True)
        act_slot, w_slot = _SLOTS[op.type]
        inserted = 0
        for slot, bits, is_weight in (
            (act_slot, activation_bits, False),
            (w_slot, weight_bits, True),
        ):
            names = op.inputs.get(slot)
            if not names:
                continue
            src = names[0]
            if src in quantized:
                op.inputs[slot] = [quantized[src]] + list(names[1:])
                continue
            v = block._find_var_recursive(src)
            if is_weight and (v is None or not _is_param(block, src)):
                continue  # only quantize real parameters on the weight side
            q_name = unique_name.generate(f"{src}.quantized")
            block.create_var(name=q_name, shape=getattr(v, "shape", None),
                             dtype=getattr(v, "dtype", "float32"))
            if is_weight:
                scale_out = unique_name.generate(f"{src}.quant_scale_out")
                block.create_var(name=scale_out, shape=(1,), dtype="float32")
                block._insert_op(
                    i + inserted,
                    type="fake_quantize_dequantize_abs_max",
                    inputs={"X": [src]},
                    outputs={"Out": [q_name], "OutScale": [scale_out]},
                    attrs={"bit_length": bits},
                )
            else:
                accum = unique_name.generate(f"{src}.quant_accum")
                state = unique_name.generate(f"{src}.quant_state")
                scale_out = unique_name.generate(f"{src}.quant_scale")
                block.create_var(name=scale_out, shape=(1,), dtype="float32")
                st_block = startup.global_block()
                for n in (accum, state):
                    block.create_var(name=n, shape=(1,), dtype="float32",
                                     persistable=True)
                    s_init = st_block.create_var(
                        name=n, shape=(1,), dtype="float32", persistable=True
                    )
                    ConstantInitializer(0.0)(s_init, st_block)
                block._insert_op(
                    i + inserted,
                    type="fake_quantize_dequantize_moving_average_abs_max",
                    inputs={"X": [src], "InAccum": [accum], "InState": [state]},
                    outputs={"Out": [q_name], "OutAccum": [accum],
                             "OutState": [state], "OutScale": [scale_out]},
                    attrs={"bit_length": bits, "moving_rate": moving_rate,
                           "is_test": for_test},
                )
            quantized[src] = q_name
            op.inputs[slot] = [q_name] + list(names[1:])
            inserted += 1
        i += 1 + inserted
    program._bump_version()
    return program


def convert(program):
    """Freeze a QAT program for inference (reference
    QuantizationFreezePass-lite): flip every moving-average qdq op to
    is_test so scales stop updating. Idempotent."""
    for block in program.blocks:
        for op in block.ops:
            if op.type == "fake_quantize_dequantize_moving_average_abs_max":
                op._set_attr("is_test", True)
    program._bump_version()
    return program


def _kl_scale(hist, amax, levels=128):
    """Entropy-calibration threshold (TensorRT algorithm; reference
    post_training_quantization.py KL path): choose the clip bin i that
    minimizes KL(P || Q) where P = hist[:i] with outliers folded into
    the last bin and Q = P quantized to `levels` buckets and re-expanded
    over P's nonzero support. Returns the SCALE (clip threshold)."""
    hist = np.asarray(hist, np.float64)
    nbins = hist.shape[0]
    best_i, best_kl = nbins, np.inf
    total = hist.sum()
    if total <= 0:
        return float(amax)
    for i in range(levels, nbins + 1):
        p = hist[:i].copy()
        p[i - 1] += hist[i:].sum()
        psum = p.sum()
        if psum <= 0:
            continue
        # quantize to `levels` buckets, expand uniformly over nonzeros
        q = np.zeros(i, np.float64)
        edges = np.linspace(0, i, levels + 1).astype(np.int64)
        ref = hist[:i]
        for b in range(levels):
            lo, hi = edges[b], edges[b + 1]
            if hi <= lo:
                continue
            nz = ref[lo:hi] > 0
            cnt = int(nz.sum())
            if cnt:
                q[lo:hi][nz] = p[lo:hi].sum() / cnt
        mask = p > 0
        # smooth empty q cells so KL stays finite (standard eps trick)
        qm = np.where(q[mask] > 0, q[mask], 1e-12)
        kl = float(np.sum(p[mask] / psum * np.log(p[mask] / psum
                                                  / (qm / q.sum()))))
        if kl < best_kl:
            best_kl, best_i = kl, i
    return float(amax) * best_i / nbins


class PostTrainingQuantization:
    """reference post_training_quantization.PostTrainingQuantization:
    calibrate activation scales on sample data, then emit a fixed-scale
    quantized program."""

    def __init__(self, executor, program, feed_names, fetch_vars,
                 calibration_data, algo="abs_max", weight_bits=8,
                 activation_bits=8,
                 quantizable_op_types=QUANTIZABLE_OP_TYPES,
                 scope=None):
        if algo not in ("abs_max", "KL"):
            raise NotImplementedError(
                f"PTQ algo {algo!r}: supported are 'abs_max' and 'KL'")
        self._algo = algo
        self._exe = executor
        # work on a clone: the user's float program must stay intact
        # (reference PTQ loads its own copy of the model)
        self._program = program.clone()
        self._feed_names = list(feed_names)
        self._fetch_vars = list(fetch_vars)
        self._data = calibration_data
        self._wbits = weight_bits
        self._abits = activation_bits
        self._op_types = quantizable_op_types
        self._scope = scope

    def _collect_targets(self):
        """(op index, slot, var name, is_weight) for quantizable inputs."""
        block = self._program.global_block()
        out = []
        for idx, op in enumerate(block.ops):
            if op.type not in self._op_types:
                continue
            act_slot, w_slot = _SLOTS[op.type]
            for slot, is_w in ((act_slot, False), (w_slot, True)):
                names = op.inputs.get(slot)
                if names:
                    out.append((idx, slot, names[0], is_w))
        return out

    def quantize(self):
        from ...fluid import executor as executor_mod

        targets = self._collect_targets()
        act_names = sorted({n for _, _, n, w in targets if not w})
        scales: Dict[str, float] = {}

        scope = self._scope or executor_mod.global_scope()
        with executor_mod.scope_guard(scope):
            # weight scales straight from the scope
            for _, _, n, is_w in targets:
                if is_w and n not in scales:
                    scales[n] = float(np.abs(np.asarray(scope.find_var(n))).max())
            # activation scales from calibration batches
            data = list(self._data)  # KL needs a second pass
            for batch in data:
                vals = self._exe.run(
                    self._program, feed=batch, fetch_list=act_names,
                )
                for n, v in zip(act_names, vals):
                    m = float(np.abs(np.asarray(v)).max())
                    scales[n] = max(scales.get(n, 0.0), m)
            if self._algo == "KL":
                # second pass: histograms over [0, abs_max], then the
                # entropy-calibration threshold (reference
                # post_training_quantization.py _get_kl_scaling_factor)
                nbins = 2048
                hists = {n: np.zeros(nbins, np.int64) for n in act_names}
                for batch in data:
                    vals = self._exe.run(
                        self._program, feed=batch, fetch_list=act_names,
                    )
                    for n, v in zip(act_names, vals):
                        if scales[n] <= 0.0:
                            continue
                        h, _ = np.histogram(
                            np.abs(np.asarray(v)).ravel(),
                            bins=nbins, range=(0.0, scales[n]))
                        hists[n] += h
                for n in act_names:
                    if scales[n] > 0.0:
                        scales[n] = _kl_scale(
                            hists[n], scales[n], 2 ** (self._abits - 1))

        # rewrite: fixed-scale qdq before each quantizable input
        block = self._program.global_block()
        # walk with explicit index bookkeeping (inserts shift positions)
        i = 0
        while i < len(block.ops):
            op = block.ops[i]
            if op.type not in self._op_types:
                i += 1
                continue
            act_slot, w_slot = _SLOTS[op.type]
            inserted = 0
            for slot, bits in ((act_slot, self._abits), (w_slot, self._wbits)):
                names = op.inputs.get(slot)
                if not names or names[0] not in scales:
                    continue
                src = names[0]
                v = block._find_var_recursive(src)
                q_name = unique_name.generate(f"{src}.ptq")
                block.create_var(name=q_name, shape=getattr(v, "shape", None),
                                 dtype=getattr(v, "dtype", "float32"))
                block._insert_op(
                    i + inserted,
                    type="fake_quant_dequant_fixed_scale",
                    inputs={"X": [src]},
                    outputs={"Out": [q_name]},
                    attrs={"bit_length": bits, "scale": scales[src]},
                )
                op.inputs[slot] = [q_name] + list(names[1:])
                inserted += 1
            i += 1 + inserted
        self._program._bump_version()
        self._scales = scales
        return self._program

    def save_quantized_model(self, save_model_path):
        from ...fluid import io

        io.save_inference_model(
            save_model_path, self._feed_names, self._fetch_vars, self._exe,
            main_program=self._program,
        )
