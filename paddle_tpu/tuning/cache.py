"""Persistent per-chip kernel-tuning cache (ISSUE 13).

One versioned JSON file per chip kind holds the winning kernel configs
the search harness (tuning/search.py, tools/autotune.py) measured for
each (kernel, shape, dtype) key. The Pallas kernels consult the ACTIVE
cache at trace time behind FLAGS_kernel_autotune; a missing entry falls
back to the hand-picked heuristic, so an empty cache is behaviorally
identical to the flag being off.

Resolution order of the active cache (later layers override earlier):

  1. in-repo defaults   paddle_tpu/tuning/defaults/<chip>.json
                        (checked in — the v5e winners the round-3/5
                        hand measurements already established)
  2. user cache         $XDG_CACHE_HOME|~/.cache/paddle_tpu/autotune/<chip>.json
                        (where `tools/autotune.py search` persists)
  3. explicit override  $PADDLE_AUTOTUNE_CACHE (a file path — CI and
                        tests pin the search to a scratch file)

A file whose `version` does not match CACHE_VERSION or whose `chip`
does not match the running chip is IGNORED (stale caches from another
software rev or another accelerator must never supply configs), with a
one-line stderr notice.

Schema (canonical dump: sorted keys, indent 1, trailing newline — the
byte-stable form the CI cache-reuse assertion compares):

    {
      "version": 1,
      "chip": "v5e",
      "entries": {
        "<kernel>": {
          "<canonical key>": {
            "config": {...},        # what the kernel's resolver reads
            "us": 123.4,            # objective at search time (optional)
            "source": "op_profile"  # how it was measured (optional)
          }
        }
      }
    }

stdlib-only on purpose: tools/autotune.py `show`/`diff` and the
launcher-side consumers must work with no accelerator runtime.
"""
from __future__ import annotations

import hashlib
import json
import os
import sys
from typing import Any, Dict, Optional, Tuple

CACHE_VERSION = 1

_DEFAULTS_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "defaults")


def canonical_key(key: Dict[str, Any]) -> str:
    """Deterministic string form of a kernel lookup key: 'a=1,b=x'
    sorted by field name. Values are rendered compactly (ints stay
    ints, dtypes are str()'d) so the same logical key always produces
    the same string."""
    parts = []
    for k in sorted(key):
        v = key[k]
        if isinstance(v, bool):
            v = int(v)
        elif not isinstance(v, (int, float, str)):
            # dtype-likes: np.dtype has .name, scalar-type classes have
            # __name__ — 'float32' either way, so jnp.float32,
            # np.dtype('float32') and 'float32' all key identically
            v = (getattr(v, "name", None) or getattr(v, "__name__", None)
                 or str(v))
        parts.append(f"{k}={v}")
    return ",".join(parts)


def chip_kind() -> str:
    """Normalized chip family for cache naming ('v5e', 'v4', 'cpu',
    ...). PADDLE_AUTOTUNE_CHIP overrides (tests, offline tooling);
    without a usable jax backend the answer is 'cpu'."""
    forced = os.environ.get("PADDLE_AUTOTUNE_CHIP")
    if forced:
        return forced
    try:
        import jax

        kind = jax.devices()[0].device_kind.lower()
    except Exception:  # noqa: BLE001 — offline tooling has no backend
        return "cpu"
    for tag in ("v5 lite", "v5e"):
        if tag in kind:
            return "v5e"
    for tag in ("v5p", "v6", "v4", "v3", "v2"):
        if tag in kind:
            return tag
    if "tpu" in kind:
        return kind.replace(" ", "_")
    return "cpu"


def user_cache_path(chip: Optional[str] = None) -> str:
    """~/.cache/paddle_tpu/autotune/<chip>.json (XDG-aware) — where
    `tools/autotune.py search` persists winners by default."""
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache")
    return os.path.join(base, "paddle_tpu", "autotune",
                        f"{chip or chip_kind()}.json")


def default_cache_path(chip: Optional[str] = None) -> str:
    """The file search results are WRITTEN to: $PADDLE_AUTOTUNE_CACHE
    when set (CI / tests pin the whole search to one scratch file),
    else the user cache."""
    return os.environ.get("PADDLE_AUTOTUNE_CACHE") or user_cache_path(chip)


def repo_default_path(chip: str) -> str:
    return os.path.join(_DEFAULTS_DIR, f"{chip}.json")


class TuningCache:
    """In-memory view of one cache layer (or the merged active view)."""

    def __init__(self, chip: str, entries: Optional[Dict] = None,
                 path: Optional[str] = None):
        self.chip = chip
        self.entries: Dict[str, Dict[str, Dict[str, Any]]] = entries or {}
        self.path = path

    # -- access ---------------------------------------------------------
    def get(self, kernel: str, key: str) -> Optional[Dict[str, Any]]:
        return self.entries.get(kernel, {}).get(key)

    def put(self, kernel: str, key: str, entry: Dict[str, Any]) -> None:
        self.entries.setdefault(kernel, {})[key] = entry

    def merge_from(self, other: "TuningCache") -> None:
        """Overlay `other`'s entries on top of self (other wins)."""
        for kernel, keys in other.entries.items():
            for key, entry in keys.items():
                self.put(kernel, key, entry)

    def __len__(self) -> int:
        return sum(len(v) for v in self.entries.values())

    # -- persistence ----------------------------------------------------
    def to_blob(self) -> str:
        """THE canonical byte form (fingerprint + CI byte-identity both
        hash/compare exactly this)."""
        return json.dumps(
            {"version": CACHE_VERSION, "chip": self.chip,
             "entries": self.entries},
            sort_keys=True, indent=1) + "\n"

    def fingerprint(self) -> str:
        return hashlib.sha256(self.to_blob().encode()).hexdigest()[:16]

    def save(self, path: Optional[str] = None) -> str:
        path = path or self.path or default_cache_path(self.chip)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(self.to_blob())
        os.replace(tmp, path)
        self.path = path
        return path

    @classmethod
    def load(cls, path: str, expect_chip: Optional[str] = None,
             ) -> Tuple[Optional["TuningCache"], Optional[str]]:
        """(cache, None) on success; (None, reason) when the file is
        absent, unreadable, from another cache version, or from another
        chip — every rejection reason is a string the caller may
        surface."""
        if not os.path.exists(path):
            return None, "absent"
        try:
            with open(path) as f:
                raw = json.load(f)
        except (OSError, ValueError) as e:
            return None, f"unreadable ({e})"
        if not isinstance(raw, dict):
            return None, "malformed (not an object)"
        if raw.get("version") != CACHE_VERSION:
            return None, (f"version mismatch (file {raw.get('version')!r}, "
                          f"want {CACHE_VERSION})")
        chip = raw.get("chip")
        if expect_chip is not None and chip != expect_chip:
            return None, f"chip mismatch (file {chip!r}, running {expect_chip!r})"
        entries = raw.get("entries")
        if not isinstance(entries, dict):
            return None, "malformed (entries not an object)"
        return cls(chip or (expect_chip or "cpu"), entries, path=path), None


def load_active_cache(chip: Optional[str] = None,
                      verbose: bool = False) -> TuningCache:
    """Merge the cache layers for the running chip: repo defaults <-
    user cache <- $PADDLE_AUTOTUNE_CACHE. Invalid layers are skipped
    (version/chip mismatch = stale; never a hard error)."""
    chip = chip or chip_kind()
    merged = TuningCache(chip)
    layers = [repo_default_path(chip), user_cache_path(chip)]
    env = os.environ.get("PADDLE_AUTOTUNE_CACHE")
    if env:
        layers.append(env)
    for path in layers:
        cache, reason = TuningCache.load(path, expect_chip=chip)
        if cache is None:
            if verbose and reason != "absent":
                print(f"# autotune cache {path} ignored: {reason}",
                      file=sys.stderr)
            continue
        merged.merge_from(cache)
    return merged
