"""VMEM-footprint models — the feasibility gate candidates must pass
BEFORE they are ever timed (a config that OOMs scoped VMEM wastes a
compile + a device fault; rejecting it up front is free).

These are the SAME models the kernels' hand-picked fallback choosers
use (the kernel modules import the budgets and estimators from here so
the two can never drift): calibrated on v5e against Mosaic's
scoped-vmem report — see the per-function notes. All pure stdlib math;
nothing here imports jax.

The HBM side of the gate is tools/memtop.py --budget (the static
live-range peak over the whole program); tuning/search.py applies it
through the `hbm_gate` hook for candidates that add HBM-resident
tensors (e.g. a materialized dropout mask).
"""
from __future__ import annotations

from typing import Any, Dict, List, Tuple

# scoped-VMEM budgets (bytes). The BSH flash kernels raise Mosaic's
# scoped limit to 112MB of the 128MB/core (whole-sequence residency is
# the design); the row-blocked kernels stay under the default ~16MB.
BSH_VMEM_LIMIT = 112 * 1024 * 1024
LN_VMEM_BUDGET = 10 * 1024 * 1024
CONV_BN_VMEM_BUDGET = 12 * 1024 * 1024
PAGED_ATTN_VMEM_BUDGET = 8 * 1024 * 1024

_DTYPE_BYTES = {
    "float32": 4, "f32": 4, "bfloat16": 2, "bf16": 2, "float16": 2,
    "f16": 2, "float64": 8,
}


def dtype_bytes(dtype: Any) -> int:
    return _DTYPE_BYTES.get(str(dtype), 4)


class NoFeasibleConfig(ValueError):
    """No candidate configuration can serve this kernel shape.

    Subclasses ValueError so pre-existing `except ValueError` dispatch
    guards keep working; carries the candidates that were considered
    and why each was rejected, so 'not tileable' errors name what was
    actually tried instead of a bare complaint."""

    def __init__(self, kernel: str, key: Dict[str, Any],
                 tried: List[Tuple[Any, str]], detail: str = ""):
        self.kernel = kernel
        self.key = dict(key)
        self.tried = list(tried)
        head = f"{kernel}: no feasible kernel config for {key}"
        if detail:
            head += f" ({detail})"
        if tried:
            head += "; tried: " + "; ".join(
                f"{cfg} -> {why}" for cfg, why in tried[:8])
            if len(tried) > 8:
                head += f"; ... {len(tried) - 8} more"
        super().__init__(head)


# ---------------------------------------------------------------------------
# flash attention, BSH layout
# ---------------------------------------------------------------------------


def flash_bsh_fwd_vmem_bytes(sq: int, skv: int, h: int, bq: int,
                             bk: int) -> int:
    """Forward kernel footprint: k/v whole-sequence resident
    (double-buffered, <=2B elems -> 8 B/elem), q/o blocks, plus the
    per-tile f32 score temporaries (~40 B per bq*bk tile element — the
    calibration that reproduces the '~40MB of 1024-tile temporaries'
    v5e measurement in ops/pallas/flash_attention.py)."""
    return 8 * skv * h + 8 * bq * h + 40 * bq * bk


def flash_bsh_bwd_vmem_bytes(sq: int, skv: int, h: int, bq: int,
                             bk: int) -> int:
    """Backward kernel footprint: q/do double-buffered bf16 + the dq
    f32 revisited accumulator (~12 B/elem of the full sq*h residency
    — reproduces the measured 124MB at (s8192, h768, bq1024) vs the
    112MB limit), k/v/dk/dv blocks, score temporaries."""
    return 12 * sq * h + 8 * bk * h + 40 * bq * bk


def flash_bsh_ok(sq: int, skv: int, h: int, bq: int, bk: int,
                 *, limit: int = BSH_VMEM_LIMIT) -> Tuple[bool, str]:
    """(feasible, reason). A config serves BOTH passes (PRNG dropout
    must regenerate identical per-block masks in fwd and bwd), so both
    footprints must fit."""
    if bq < 128 or bk < 128:
        return False, "block below the 128 tiling minimum"
    if sq % bq or skv % bk:
        return False, f"blocks ({bq},{bk}) do not tile (sq={sq}, skv={skv})"
    f = flash_bsh_fwd_vmem_bytes(sq, skv, h, bq, bk)
    if f > limit:
        return False, f"fwd VMEM estimate {f} > {limit}"
    b = flash_bsh_bwd_vmem_bytes(sq, skv, h, bq, bk)
    if b > limit:
        return False, f"bwd VMEM estimate {b} > {limit}"
    return True, "ok"


# ---------------------------------------------------------------------------
# fused residual-add + LayerNorm
# ---------------------------------------------------------------------------


def ln_vmem_bytes(rows: int, h: int) -> int:
    """x, y, out row blocks double-buffered bf16-worst + ~4 f32
    temporaries per row element (the ops/pallas/add_ln.py model)."""
    return rows * h * (3 * 2 * 2 + 4 * 4)


def ln_rows_ok(r: int, h: int, rows: int,
               *, budget: int = LN_VMEM_BUDGET) -> Tuple[bool, str]:
    if rows < 1 or r % rows:
        return False, f"row block {rows} does not tile r={r}"
    est = ln_vmem_bytes(rows, h)
    if est > budget:
        return False, f"VMEM estimate {est} > {budget}"
    return True, "ok"


# ---------------------------------------------------------------------------
# fused conv + batch-norm
# ---------------------------------------------------------------------------


def conv_bn_row_bytes(rows: int, width: int, bytes_per_row_unit: int) -> int:
    """Row-blocked passes (1x1 matmul / normalize / backward sweeps):
    in+out blocks double-buffered + the f32 accumulator, expressed as
    bytes per row*width unit exactly as ops/pallas/conv_bn.py sizes
    them."""
    return rows * width * bytes_per_row_unit


def conv_bn_rows_ok(r: int, width: int, rows: int, bytes_per_row_unit: int,
                    *, budget: int = CONV_BN_VMEM_BUDGET) -> Tuple[bool, str]:
    if rows < 1 or r % rows:
        return False, f"row block {rows} does not tile r={r}"
    est = conv_bn_row_bytes(rows, width, bytes_per_row_unit)
    if est > budget:
        return False, f"VMEM estimate {est} > {budget}"
    return True, "ok"


def paged_attention_vmem_bytes(page: int, kv_heads: int, head_dim: int,
                               dtype: Any = "float32") -> int:
    """Per-grid-step footprint of the serving paged-attention kernel
    (ops/pallas/paged_attention.py): one KV page streamed per step —
    k+v page blocks double-buffered — plus the q/o head blocks and the
    f32 online-softmax scratch (running max, running denominator, and
    the [h, d] weighted-value accumulator). MHA-only kernel, so the q/o
    head count equals kv_heads."""
    b = dtype_bytes(dtype)
    kv_pages = 2 * 2 * page * kv_heads * head_dim * b
    q_out = 2 * 2 * kv_heads * head_dim * b
    scratch = 4 * (kv_heads + kv_heads + kv_heads * head_dim)
    return kv_pages + q_out + scratch


def paged_page_ok(page: int, kv_heads: int, head_dim: int,
                  dtype: Any = "float32", max_seq: int = 0,
                  *, budget: int = PAGED_ATTN_VMEM_BUDGET
                  ) -> Tuple[bool, str]:
    """(feasible, reason) for a paged-attention page size. The tuned
    page size doubles as the KV pool's page granularity (the kernel
    streams pool pages directly), so a page longer than the model's
    max sequence can never fill and only wastes pool bytes."""
    if page < 1:
        return False, "page size must be >= 1"
    if max_seq and page > max_seq:
        return False, f"page {page} exceeds max_seq {max_seq}"
    est = paged_attention_vmem_bytes(page, kv_heads, head_dim, dtype)
    if est > budget:
        return False, f"VMEM estimate {est} > {budget}"
    return True, "ok"


def conv_bn_s2d_per_image_bytes(hp: int, wp: int, c: int, o: int,
                                kh: int, kw: int) -> int:
    """Per-image footprint of the space-to-depth lowering of a stride-2
    kxk conv: the phase image is [hp/2, wp/2, 4c], the filter becomes
    ceil(k/2)^2 taps over 4c channels, outputs shrink to the strided
    grid. Same cost model as conv_bn_shapes_ok's k>1 path, on the
    transformed dims."""
    hp2, wp2 = (hp + 1) // 2, (wp + 1) // 2
    k2h, k2w = (kh + 1) // 2, (kw + 1) // 2
    ho, wo = hp2 - k2h + 1, wp2 - k2w + 1
    if ho <= 0 or wo <= 0:
        return 1 << 62
    return (
        2 * 2 * hp2 * wp2 * 4 * c      # phase image block, double-buffered
        + 2 * 2 * ho * wo * o          # y block
        + 4 * ho * wo * o              # f32 accumulator
        + 2 * k2h * k2w * 4 * c * o    # rearranged weights (resident)
    )
