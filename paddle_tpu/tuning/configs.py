"""Candidate config spaces per tunable kernel (pure, deterministic).

Each enumerator returns the ORDERED list of feasible candidate configs
for one (kernel, shape, dtype) key — the order is the deterministic
tie-break the search harness applies when two candidates measure
identically (first enumerated wins), so enumeration order is part of
the reproducibility contract: largest blocks first, axes varied
inner-to-outer, never dependent on dict/hash order.

Infeasible candidates are returned separately with their rejection
reasons (the feasibility gate's audit trail: NoFeasibleConfig carries
them when nothing survives).
"""
from __future__ import annotations

from typing import Any, Dict, List, Tuple

from . import feasible

# block-size menu shared by the flash axes (the kernels' tiling minimum
# is 128; 1024 is the largest tile the s4096 hand measurements reached)
_FLASH_BLOCKS = (1024, 512, 256, 128)
_LN_ROWS = (2048, 1024, 512, 256, 128, 64, 32, 16, 8)
_CONV_ROWS = (2048, 1024, 512, 256, 128, 64, 32, 16, 8, 4, 2, 1)
# paged-attention page sizes: fewer grid steps (large pages) first; the
# tuned page doubles as the KV pool page granularity, so small pages
# trade kernel overhead for finer pool packing
_PAGED_PAGES = (64, 32, 16, 8)

Rejects = List[Tuple[Dict[str, Any], str]]


def flash_bsh_candidates(sq: int, skv: int, h: int, dtype: str = "bfloat16",
                         dropout: bool = False,
                         ) -> Tuple[List[Dict[str, Any]], Rejects]:
    """(bq, bk) tile pairs feasible for BOTH passes (one config serves
    fwd and bwd so PRNG dropout regenerates identical masks), plus the
    dropout-mask axis when the target config applies dropout: 'regen'
    (in-kernel PRNG, zero HBM traffic) vs 'materialize' (precomputed
    [B,nh,Sq,Skv] mask in HBM — only ever wins when the HBM gate says
    the mask fits and the VPU PRNG is the bottleneck)."""
    ok: List[Dict[str, Any]] = []
    rejects: Rejects = []
    for bq in _FLASH_BLOCKS:
        for bk in _FLASH_BLOCKS:
            cfg = {"bq": bq, "bk": bk}
            feas, why = feasible.flash_bsh_ok(sq, skv, h, bq, bk)
            if not feas:
                rejects.append((cfg, why))
                continue
            if dropout:
                ok.append({**cfg, "mask": "regen"})
                ok.append({**cfg, "mask": "materialize"})
            else:
                ok.append(cfg)
    return ok, rejects


def add_ln_candidates(r: int, h: int, dtype: str = "float32",
                      ) -> Tuple[List[Dict[str, Any]], Rejects]:
    ok: List[Dict[str, Any]] = []
    rejects: Rejects = []
    for rows in _LN_ROWS:
        cfg = {"block_rows": rows}
        feas, why = feasible.ln_rows_ok(r, h, rows)
        (ok if feas else rejects).append(cfg if feas else (cfg, why))
    return ok, rejects


# bytes-per-row-unit by pass kind, exactly as ops/pallas/conv_bn.py
# sizes its row blocks: the 1x1 matmul holds x+y double-buffered + the
# f32 accumulator over width c+o; the elementwise sweeps hold three
# <=4B tensors over width o
CONV_BN_ROW_UNIT = {"mm": 2 * 2 + 4, "apply": 3 * 4}


def conv_bn_candidates(kind: str, r: int, width: int,
                       dtype: str = "float32",
                       ) -> Tuple[List[Dict[str, Any]], Rejects]:
    unit = CONV_BN_ROW_UNIT[kind]
    ok: List[Dict[str, Any]] = []
    rejects: Rejects = []
    for rows in _CONV_ROWS:
        cfg = {"block_rows": rows}
        feas, why = feasible.conv_bn_rows_ok(r, width, rows, unit)
        (ok if feas else rejects).append(cfg if feas else (cfg, why))
    return ok, rejects


def paged_attention_candidates(kv_heads: int, head_dim: int,
                               dtype: str = "float32", max_seq: int = 0,
                               ) -> Tuple[List[Dict[str, Any]], Rejects]:
    """Page-size axis for the serving paged-attention kernel. One page
    of KV streams through VMEM per grid step, so the page size is the
    kernel's block size AND the pool's allocation granularity —
    kv_cache.from_budget consults the tuned winner when no explicit
    page size is configured."""
    ok: List[Dict[str, Any]] = []
    rejects: Rejects = []
    for page in _PAGED_PAGES:
        cfg = {"page_size": page}
        feas, why = feasible.paged_page_ok(page, kv_heads, head_dim,
                                           dtype, max_seq)
        (ok if feas else rejects).append(cfg if feas else (cfg, why))
    return ok, rejects


def conv_bn_s2d_candidates(n: int, hp: int, wp: int, c: int, o: int,
                           kh: int, kw: int, strides: Tuple[int, int],
                           dtype: str = "float32",
                           ) -> Tuple[List[Dict[str, Any]], Rejects]:
    """The space-to-depth axis for kxk stride-2 convs (hp/wp already
    padded): {'space_to_depth': 1} vs the XLA reference lowering
    {'space_to_depth': 0}. Candidates exist only when the rearranged
    stride-1 problem fits the per-image VMEM budget and the output-size
    identity holds (even padded extent, or odd kernel)."""
    rejects: Rejects = []
    if tuple(strides) != (2, 2) or (kh, kw) == (1, 1):
        rejects.append(({"space_to_depth": 1},
                        "only kxk stride-2 convs have an s2d lowering"))
        return [], rejects
    for ext, k in ((hp, kh), (wp, kw)):
        if ext % 2 and k % 2 == 0:
            rejects.append(({"space_to_depth": 1},
                            f"odd padded extent {ext} with even kernel {k} "
                            "changes the output size"))
            return [], rejects
    est = feasible.conv_bn_s2d_per_image_bytes(hp, wp, c, o, kh, kw)
    if est > feasible.CONV_BN_VMEM_BUDGET:
        rejects.append(({"space_to_depth": 1},
                        f"per-image VMEM estimate {est} > "
                        f"{feasible.CONV_BN_VMEM_BUDGET}"))
        return [], rejects
    return [{"space_to_depth": 0}, {"space_to_depth": 1}], rejects
