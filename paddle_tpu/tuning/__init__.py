"""Pallas kernel autotuner (ISSUE 13): per-(kernel, shape, dtype, chip)
config search with a persistent tuning cache.

The three Pallas kernels (flash attention BSH, fused add+LayerNorm,
fused conv+BN) consult this package at trace time behind
FLAGS_kernel_autotune. Lookups resolve against the merged active cache
(tuning/cache.py: repo defaults <- user cache <- $PADDLE_AUTOTUNE_CACHE)
and every decision — cache hit or hand-picked fallback — is recorded so
bench rows can report exactly which configs produced a number.

Contracts the rest of the system relies on:
  * flag OFF: no lookup runs, the kernels use their hand-picked
    choosers — emitted programs are bit-identical to a build without
    this package.
  * flag ON + empty cache: `maybe_lookup` returns None and the kernels
    fall back to the same hand-picked configs (no behavior cliff).
  * the chosen-config surface rides the Executor compile-cache key via
    `cache_signature()`, so editing the cache (or tuning.override in
    tests/search) retraces instead of silently reusing a stale step.

Search side: tuning/search.py (harness), tools/autotune.py (CLI),
tools/op_bench.py (the shared single-op measurement fence).
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Dict, Optional

from .cache import (  # noqa: F401 — public API re-exports
    CACHE_VERSION,
    TuningCache,
    canonical_key,
    chip_kind,
    default_cache_path,
    load_active_cache,
    user_cache_path,
)
from .feasible import NoFeasibleConfig  # noqa: F401

_lock = threading.Lock()
_active: Optional[TuningCache] = None
_choices: Dict[str, Dict[str, Any]] = {}


def enabled() -> bool:
    """FLAGS_kernel_autotune. Imported lazily: tuning must be usable by
    offline tools with no framework import."""
    try:
        from ..fluid.flags import flag
    except Exception:  # noqa: BLE001 — standalone/offline use
        return False
    return bool(flag("FLAGS_kernel_autotune"))


def active_cache() -> TuningCache:
    """The merged cache for this process, loaded once (reload() after
    editing cache files or env vars mid-process)."""
    global _active
    with _lock:
        if _active is None:
            _active = load_active_cache()
        return _active


def reload() -> TuningCache:
    global _active
    with _lock:
        _active = None
    return active_cache()


def cache_fingerprint() -> str:
    return active_cache().fingerprint()


def cache_signature() -> Optional[str]:
    """What the Executor folds into its compile-cache key: None when
    the flag is off (key unchanged vs a build without this package),
    else the active cache fingerprint."""
    if not enabled():
        return None
    return cache_fingerprint()


def maybe_lookup(kernel: str, key: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """The kernels' trace-time hook: None when the flag is off or the
    cache has no entry (callers then use their hand-picked chooser);
    else a copy of the winning config dict. Every flag-on resolution is
    recorded for `chosen_configs()` — callers that REJECT a returned
    config (failed validation) should re-record via note_choice."""
    if not enabled():
        return None
    ck = canonical_key(key)
    entry = active_cache().get(kernel, ck)
    if entry is None:
        note_choice(kernel, ck, None, "default")
        return None
    cfg = entry.get("config")
    if not isinstance(cfg, dict):
        note_choice(kernel, ck, None, "default")
        return None
    note_choice(kernel, ck, dict(cfg), "cache")
    return dict(cfg)


def note_choice(kernel: str, key: Any, config: Optional[Dict[str, Any]],
                source: str) -> None:
    """Record the config actually used for (kernel, key) this process —
    source 'cache' (tuned) or 'default' (hand-picked fallback)."""
    ck = key if isinstance(key, str) else canonical_key(key)
    with _lock:
        _choices[f"{kernel}[{ck}]"] = {
            "kernel": kernel, "key": ck, "config": config, "source": source,
        }


def chosen_configs() -> Dict[str, Dict[str, Any]]:
    """Per-kernel chosen configs recorded during tracing — bench rows
    persist this next to the autotune cache hash so perf numbers stay
    reproducible."""
    with _lock:
        return {k: dict(v) for k, v in _choices.items()}


def clear_choices() -> None:
    with _lock:
        _choices.clear()


@contextlib.contextmanager
def override(entries: Dict[str, Dict[str, Dict[str, Any]]],
             chip: Optional[str] = None):
    """Swap the active cache for a synthetic one ({kernel: {key:
    entry}}) for the duration — the search harness measures each
    candidate through EXACTLY the production lookup path this way, and
    tests pin configs without touching disk. Entries may be either the
    full {'config': {...}} schema or a bare config dict."""
    global _active
    norm: Dict[str, Dict[str, Dict[str, Any]]] = {}
    for kernel, keys in entries.items():
        norm[kernel] = {}
        for key, entry in keys.items():
            if "config" not in entry:
                entry = {"config": dict(entry)}
            norm[kernel][key] = entry
    with _lock:
        prev = _active
        _active = TuningCache(chip or chip_kind(), norm)
    try:
        yield _active
    finally:
        with _lock:
            _active = prev
