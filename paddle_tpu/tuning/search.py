"""The autotune search harness (ISSUE 13 tentpole).

Per (kernel, shape, dtype, chip) target: enumerate candidate configs
(tuning/configs.py), reject infeasible ones up front — the VMEM
estimators already filtered enumeration; the HBM side applies the
candidate's extra resident bytes against the budget (the same contract
as `tools/memtop.py --budget`, and PADDLE_HBM_BUDGET_BYTES is honored
as the default budget) — measure the survivors through an injected
`measure` callable, and persist the winner in the per-chip cache.

The measure callable owns the actual timing (tools/autotune.py wires
the tools/op_bench.py single-op fence with the per-op device-time
objective from telemetry/cost.py; tests inject a mocked timer), so the
harness itself is pure and deterministic: winner selection is
min((time, enumeration_index)) — ties break to the FIRST enumerated
candidate, which configs.py orders largest-blocks-first.
"""
from __future__ import annotations

import dataclasses
import hashlib
import os
import sys
from typing import Any, Callable, Dict, List, Optional, Tuple

from .cache import TuningCache, canonical_key
from .feasible import NoFeasibleConfig

Config = Dict[str, Any]
MeasureFn = Callable[["SearchTarget", Config], float]


@dataclasses.dataclass
class SearchTarget:
    """One search unit: a kernel key, its candidate set, and whatever
    the measure callable needs to build the single-op program."""

    kernel: str
    key: Dict[str, Any]
    candidates: List[Config]
    rejected: List[Tuple[Config, str]] = dataclasses.field(
        default_factory=list)
    spec: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # candidate -> extra HBM-resident bytes it introduces (e.g. a
    # materialized dropout mask); None = no extra residency
    hbm_bytes: Optional[Callable[[Config], int]] = None

    @property
    def canonical(self) -> str:
        return canonical_key(self.key)


@dataclasses.dataclass
class SearchResult:
    kernel: str
    key: str
    winner: Optional[Config]
    us: Optional[float]
    measured: List[Tuple[Config, float]]
    rejected: List[Tuple[Config, str]]
    cache_hit: bool

    def to_json(self) -> dict:
        return {
            "kernel": self.kernel, "key": self.key, "winner": self.winner,
            "us": self.us, "cache_hit": self.cache_hit,
            "measured": [{"config": c, "us": round(u, 3)}
                         for c, u in self.measured],
            "rejected": [{"config": c, "reason": r}
                         for c, r in self.rejected],
        }


def mock_measure(target: SearchTarget, config: Config) -> float:
    """Deterministic pseudo-timer (tests, dry runs): a stable hash of
    (kernel, key, config) — no backend, no noise, same winner on every
    machine."""
    blob = f"{target.kernel}|{target.canonical}|{canonical_key(config)}"
    h = hashlib.sha256(blob.encode()).hexdigest()
    return 100.0 + int(h[:8], 16) % 10_000 / 10.0


class Searcher:
    """Drives targets through measure() and persists winners."""

    def __init__(self, cache: TuningCache, measure: MeasureFn,
                 *, hbm_budget_bytes: Optional[int] = None,
                 log: Optional[Callable[[str], None]] = None):
        self.cache = cache
        self.measure = measure
        if hbm_budget_bytes is None:
            env = os.environ.get("PADDLE_HBM_BUDGET_BYTES")
            hbm_budget_bytes = int(env) if env else None
        self.hbm_budget_bytes = hbm_budget_bytes
        self.log = log or (lambda msg: print(msg, file=sys.stderr))

    # -- gates ----------------------------------------------------------
    def _hbm_gate(self, target: SearchTarget,
                  ) -> Tuple[List[Config], List[Tuple[Config, str]]]:
        if target.hbm_bytes is None or self.hbm_budget_bytes is None:
            return list(target.candidates), []
        ok: List[Config] = []
        rejected: List[Tuple[Config, str]] = []
        for cfg in target.candidates:
            extra = target.hbm_bytes(cfg)
            if extra > self.hbm_budget_bytes:
                rejected.append(
                    (cfg, f"HBM gate: extra {extra} B > budget "
                          f"{self.hbm_budget_bytes} B"))
            else:
                ok.append(cfg)
        return ok, rejected

    # -- search ---------------------------------------------------------
    def search(self, target: SearchTarget, force: bool = False,
               ) -> SearchResult:
        ck = target.canonical
        existing = self.cache.get(target.kernel, ck)
        if existing is not None and not force:
            self.log(f"# autotune {target.kernel}[{ck}]: cache hit "
                     f"-> {existing.get('config')}")
            return SearchResult(
                kernel=target.kernel, key=ck,
                winner=existing.get("config"), us=existing.get("us"),
                measured=[], rejected=[], cache_hit=True)

        candidates, hbm_rejected = self._hbm_gate(target)
        rejected = list(target.rejected) + hbm_rejected
        if not candidates:
            raise NoFeasibleConfig(target.kernel, target.key, rejected)

        measured: List[Tuple[Config, float]] = []
        for idx, cfg in enumerate(candidates):
            us = float(self.measure(target, cfg))
            measured.append((cfg, us))
            self.log(f"# autotune {target.kernel}[{ck}] "
                     f"{idx + 1}/{len(candidates)} {cfg} -> {us:.1f} us")
        best_idx = min(range(len(measured)),
                       key=lambda i: (measured[i][1], i))
        winner, us = measured[best_idx]
        self.cache.put(target.kernel, ck, {
            "config": winner, "us": round(us, 3),
            "source": getattr(self.measure, "source", "measured"),
        })
        self.log(f"# autotune {target.kernel}[{ck}]: winner {winner} "
                 f"({us:.1f} us over {len(measured)} candidates, "
                 f"{len(rejected)} rejected infeasible)")
        return SearchResult(
            kernel=target.kernel, key=ck, winner=winner, us=us,
            measured=measured, rejected=rejected, cache_hit=False)

    def search_all(self, targets: List[SearchTarget], force: bool = False,
                   ) -> List[SearchResult]:
        return [self.search(t, force=force) for t in targets]
