"""hapi vision models (reference python/paddle/incubate/hapi/vision/
models/: lenet.py, vgg.py, resnet.py): dygraph Layer classes usable
directly or through hapi Model(...).fit. Static-graph users should use
paddle_tpu.models.resnet (builder-style, bench-grade)."""
from __future__ import annotations

from ..fluid.dygraph import (
    BatchNorm, Conv2D, Layer, Linear, Pool2D, Sequential,
)
from ..fluid.dygraph.base import _trace_op


def _relu(x):
    return _trace_op("relu", {"X": [x]}, {}, ["Out"])[0]


__all__ = ["LeNet", "VGG", "vgg16", "ResNet", "resnet18", "resnet50"]


class LeNet(Layer):
    """Reference hapi/vision/models/lenet.py: 2 conv-pool + 3 fc."""

    def __init__(self, num_classes=10):
        super().__init__()
        self.features = Sequential(
            Conv2D(1, 6, 3, padding=1, act="relu"),
            Pool2D(2, "max", 2),
            Conv2D(6, 16, 5, act="relu"),
            Pool2D(2, "max", 2),
        )
        self.fc = Sequential(
            Linear(400, 120, act="relu"),
            Linear(120, 84, act="relu"),
            Linear(84, num_classes),
        )

    def forward(self, x):
        h = self.features(x)
        return self.fc(h.reshape([x.shape[0], -1]))


_VGG_CFGS = {
    11: [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    13: [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
         512, 512, "M"],
    16: [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
         512, 512, 512, "M"],
    19: [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
         512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
}


class VGG(Layer):
    """Reference hapi/vision/models/vgg.py (batch-norm variant)."""

    def __init__(self, depth=16, num_classes=1000, input_size=224):
        super().__init__()
        if depth not in _VGG_CFGS:
            raise ValueError(f"VGG depth must be one of {list(_VGG_CFGS)}")
        blocks = []
        c_in = 3
        for v in _VGG_CFGS[depth]:
            if v == "M":
                blocks.append(Pool2D(2, "max", 2))
            else:
                blocks.append(Conv2D(c_in, v, 3, padding=1))
                blocks.append(BatchNorm(v, act="relu"))
                c_in = v
        self.features = Sequential(*blocks)
        spatial = input_size // 32
        self.classifier = Sequential(
            Linear(512 * spatial * spatial, 4096, act="relu"),
            Linear(4096, 4096, act="relu"),
            Linear(4096, num_classes),
        )

    def forward(self, x):
        h = self.features(x)
        return self.classifier(h.reshape([x.shape[0], -1]))


def vgg16(num_classes=1000, **kwargs):
    return VGG(16, num_classes, **kwargs)


class _ConvBN(Layer):
    def __init__(self, c_in, c_out, k, stride=1, act=None):
        super().__init__()
        self.conv = Conv2D(c_in, c_out, k, stride=stride,
                           padding=(k - 1) // 2, bias_attr=False)
        self.bn = BatchNorm(c_out, act=act)

    def forward(self, x):
        return self.bn(self.conv(x))


class _BasicBlock(Layer):
    expansion = 1

    def __init__(self, c_in, c_out, stride=1):
        super().__init__()
        self.conv1 = _ConvBN(c_in, c_out, 3, stride, act="relu")
        self.conv2 = _ConvBN(c_out, c_out, 3)
        self.short = (None if stride == 1 and c_in == c_out
                      else _ConvBN(c_in, c_out, 1, stride))

    def forward(self, x):
        h = self.conv2(self.conv1(x))
        s = x if self.short is None else self.short(x)
        return _relu(h + s)


class _Bottleneck(Layer):
    expansion = 4

    def __init__(self, c_in, c_mid, stride=1):
        super().__init__()
        c_out = c_mid * 4
        self.conv1 = _ConvBN(c_in, c_mid, 1, act="relu")
        self.conv2 = _ConvBN(c_mid, c_mid, 3, stride, act="relu")
        self.conv3 = _ConvBN(c_mid, c_out, 1)
        self.short = (None if stride == 1 and c_in == c_out
                      else _ConvBN(c_in, c_out, 1, stride))

    def forward(self, x):
        h = self.conv3(self.conv2(self.conv1(x)))
        s = x if self.short is None else self.short(x)
        return _relu(h + s)


_RESNET_CFGS = {
    18: (_BasicBlock, [2, 2, 2, 2]),
    34: (_BasicBlock, [3, 4, 6, 3]),
    50: (_Bottleneck, [3, 4, 6, 3]),
    101: (_Bottleneck, [3, 4, 23, 3]),
    152: (_Bottleneck, [3, 8, 36, 3]),
}


class ResNet(Layer):
    """Reference hapi/vision/models/resnet.py."""

    def __init__(self, depth=50, num_classes=1000):
        super().__init__()
        if depth not in _RESNET_CFGS:
            raise ValueError(f"ResNet depth must be one of {list(_RESNET_CFGS)}")
        block, counts = _RESNET_CFGS[depth]
        self.stem = _ConvBN(3, 64, 7, 2, act="relu")
        self.pool = Pool2D(3, "max", 2, pool_padding=1)
        stages = []
        c_in = 64
        for i, (c_mid, n) in enumerate(zip([64, 128, 256, 512], counts)):
            for j in range(n):
                stride = 2 if (i > 0 and j == 0) else 1
                stages.append(block(c_in, c_mid, stride))
                c_in = c_mid * block.expansion
        self.stages = Sequential(*stages)
        self.out_pool = Pool2D(global_pooling=True, pool_type="avg")
        self.fc = Linear(c_in, num_classes)

    def forward(self, x):
        h = self.stages(self.pool(self.stem(x)))
        h = self.out_pool(h)
        return self.fc(h.reshape([x.shape[0], -1]))


def resnet18(num_classes=1000):
    return ResNet(18, num_classes)


def resnet50(num_classes=1000):
    return ResNet(50, num_classes)
