"""hapi: the Keras-like high-level API (`Model.fit/evaluate/predict`).

Parity surface: reference python/paddle/incubate/hapi/model.py
(Model:664, prepare:1062, fit:1119, evaluate:1320, predict:1417,
Input:50, StaticGraphAdapter:84).

TPU-native design: one static Program per mode (train/eval/test) built
from a user network callable over symbolic inputs; the whole train step
(fwd+bwd+opt) is a single XLA computation via the Executor. The
reference's DynamicGraphAdapter is unnecessary — static is the fast path
on TPU, and dygraph models reach it through dygraph-to-static.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from .. import fluid
from ..fluid import layers
from . import callbacks as callbacks_mod
from .callbacks import Callback, EarlyStopping, ModelCheckpoint, ProgBarLogger  # noqa: F401
from .metrics import Accuracy, Metric  # noqa: F401
from . import datasets, text, vision  # noqa: F401

__all__ = [
    "Input", "Model", "Callback", "ProgBarLogger", "ModelCheckpoint",
    "EarlyStopping", "Metric", "Accuracy",
]


class Input:
    """Symbolic input spec (reference hapi Input:50)."""

    def __init__(self, name, shape=None, dtype="float32"):
        self.name = name
        self.shape = list(shape or [])
        self.dtype = dtype


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


# op types whose semantics switch on the is_test attr (the set the
# reference's Program.clone(for_test=True) _inference_optimize flips)
_TEST_MODE_OPS = {
    "dropout", "batch_norm", "fused_multihead_attention",
    "fused_encoder_stack", "fused_decoder_stack", "instance_norm",
}


def _flip_to_test_mode(program):
    """Eval/test programs run inference semantics: dropout off, batch_norm
    on the running statistics (reference StaticGraphAdapter builds eval
    programs via clone(for_test=True))."""
    for block in program.blocks:
        for op in block.ops:
            if op.type in _TEST_MODE_OPS:
                op._set_attr("is_test", True)


class Model:
    """Static-graph Model (reference hapi Model:664).

    network: callable taking the input Variables (not labels) and
    returning the output Variable(s). inputs/labels: Input specs.
    """

    def __init__(self, network: Callable, inputs, labels=None):
        self._network = network
        self._inputs = _to_list(inputs)
        self._labels = _to_list(labels)
        if not self._inputs:
            raise ValueError("Model needs at least one Input spec")
        self._optimizer = None
        self._loss_function = None
        self._metrics: List[Metric] = []
        self._progs: Dict[str, tuple] = {}
        self._exe = fluid.Executor()
        self._scope = fluid.executor.Scope()
        self._prepared = False

    # ------------------------------------------------------------------
    def prepare(self, optimizer=None, loss_function=None, metrics=None):
        self._optimizer = optimizer
        self._loss_function = loss_function
        self._metrics = _to_list(metrics)
        startup = fluid.Program()
        for mode in ("train", "eval", "test"):
            if mode == "train" and (optimizer is None or loss_function is None):
                continue
            if mode == "eval" and loss_function is None:
                continue
            self._progs[mode] = self._build_program(mode, startup)
        self._startup = startup
        from ..fluid.flags import flag

        if flag("FLAGS_program_verify"):
            # cross-program lint of the clone family (fluid/analysis/
            # crosscheck.py): startup must initialize every persistable
            # the train program reads, and the eval/test clones must
            # share Parameters by name, run is_test semantics, and carry
            # no optimizer/@GRAD ops. A violated clone contract raises
            # HERE, naming the layer, not as a wrong number mid-fit.
            from ..fluid.analysis import assert_pair_valid

            train = self._progs.get("train")
            for mode in ("eval", "test"):
                if mode not in self._progs:
                    continue
                clone, feed_names = self._progs[mode][0], self._progs[mode][1]
                assert_pair_valid(
                    clone, startup=startup,
                    feed_names=feed_names,
                    where=f"Model.prepare {mode} clone "
                          f"(FLAGS_program_verify)")
                if train is not None:
                    assert_pair_valid(
                        train[0], eval_program=clone,
                        where=f"Model.prepare train/{mode} pair "
                              f"(FLAGS_program_verify)")
            if train is not None:
                assert_pair_valid(
                    train[0], startup=startup, feed_names=train[1],
                    where="Model.prepare train (FLAGS_program_verify)")
        with fluid.scope_guard(self._scope):
            self._exe.run(startup)
        self._prepared = True
        return self

    def _build_program(self, mode, startup):
        from ..fluid import unique_name

        main = fluid.Program()
        # every mode rebuilds the same network: reset the name generator so
        # parameters share names (and therefore scope storage) across the
        # train/eval/test programs — reference StaticGraphAdapter._make_program
        with unique_name.guard(), fluid.program_guard(main, startup):
            in_vars = [
                layers.data(i.name, i.shape, dtype=i.dtype, append_batch_size=False)
                for i in self._inputs
            ]
            lbl_vars = [
                layers.data(l.name, l.shape, dtype=l.dtype, append_batch_size=False)
                for l in self._labels
            ] if mode != "test" else []
            outs = _to_list(self._network(*in_vars))
            fetches = list(outs)
            loss_var = None
            if mode in ("train", "eval") and self._loss_function is not None:
                loss_var = self._loss_function(*(outs + lbl_vars))
                if isinstance(loss_var, (list, tuple)):
                    loss_var = loss_var[0]
                if tuple(loss_var.shape or ()) not in ((), (1,)):
                    loss_var = layers.mean(loss_var)
                fetches = [loss_var] + fetches
            if mode == "train":
                self._optimizer.minimize(loss_var)
        if mode != "train":
            _flip_to_test_mode(main)
        feed_names = [i.name for i in self._inputs] + (
            [l.name for l in self._labels] if mode != "test" else []
        )
        return main, feed_names, fetches, loss_var

    # ------------------------------------------------------------------
    def _run_batch(self, mode, inputs, labels=None):
        if not self._prepared:
            raise RuntimeError("call prepare() first")
        main, feed_names, fetches, loss_var = self._progs[mode]
        vals = _to_list(inputs) + _to_list(labels)
        feed = {n: np.asarray(v) for n, v in zip(feed_names, vals)}
        with fluid.scope_guard(self._scope):
            return self._exe.run(main, feed=feed, fetch_list=fetches)

    def train_batch(self, inputs, labels=None):
        return self._run_batch("train", inputs, labels)

    def eval_batch(self, inputs, labels=None):
        return self._run_batch("eval", inputs, labels)

    def test_batch(self, inputs):
        return self._run_batch("test", inputs)

    # ------------------------------------------------------------------
    @staticmethod
    def _materialize(data):
        """Resolve data ONCE per fit/evaluate/predict call: a reader
        creator (callable returning a sample generator) or a one-shot
        iterator of prepared batches is consumed a single time, so
        multi-epoch fit never re-iterates or exhausts it."""
        if callable(data):
            samples = list(data())
            if not samples:
                raise ValueError("empty dataset")
            return [
                np.asarray([s[i] for s in samples]) for i in range(len(samples[0]))
            ]
        data = list(data)
        if not data:
            raise ValueError("empty dataset")
        return data

    @staticmethod
    def _batches(data, batch_size, shuffle, seed):
        """data: output of _materialize — full column arrays or a list of
        prepared batches. Returns a list of per-batch array lists."""
        if all(isinstance(a, np.ndarray) for a in data):
            n = data[0].shape[0]
            idx = np.arange(n)
            if shuffle:
                np.random.RandomState(seed).shuffle(idx)
            out = []
            for s in range(0, n - n % batch_size or n, batch_size):
                sel = idx[s: s + batch_size]
                if len(sel) < batch_size:
                    break
                out.append([a[sel] for a in data])
            return out
        return data  # already a list of batches

    def _checkpoint_manager(self, dirname, keep_last_n=3):
        """One CheckpointManager per checkpoint root, bound to the train
        program and this model's scope (shared by fit(resume=...) and
        the step-frequency ModelCheckpoint callback)."""
        import os

        from ..fluid import checkpoint as ckpt_mod

        if not self._prepared:
            raise RuntimeError("call prepare() first")
        key = os.path.abspath(dirname)
        mgrs = getattr(self, "_ckpt_mgrs", None)
        if mgrs is None:
            mgrs = self._ckpt_mgrs = {}
        if key not in mgrs:
            mode = "train" if "train" in self._progs else \
                next(iter(self._progs))
            mgrs[key] = ckpt_mod.CheckpointManager(
                dirname, keep_last_n=keep_last_n,
                program=self._progs[mode][0], scope=self._scope)
        return mgrs[key]

    def fit(
        self,
        train_data,
        eval_data=None,
        batch_size=32,
        epochs=1,
        eval_freq=1,
        log_freq=10,
        save_dir=None,
        save_freq=1,
        verbose=2,
        shuffle=True,
        callbacks=None,
        checkpoint_dir=None,
        checkpoint_freq=0,
        checkpoint_keep=3,
        resume=False,
        reshard=None,
    ):
        """reference hapi fit:1119, plus the preemption-safe layer
        (fluid/checkpoint.py):

        checkpoint_dir   arm a CheckpointManager there; every
                         `checkpoint_freq` train steps (0 = only on
                         preemption) the FULL training state — params,
                         optimizer moments, AMP loss scale, RNG key,
                         (epoch, step) position, loss history — is
                         committed atomically with checkpoint_keep
                         retained.
        resume           True: restore the newest VALID checkpoint from
                         checkpoint_dir and continue mid-epoch with a
                         bit-identical loss trace (a torn latest
                         checkpoint falls back to the previous one). A
                         path string doubles as checkpoint_dir. Empty
                         dir = fresh start.
        SIGTERM          (or checkpoint.request_preemption()) is honored
                         at the next step boundary: final checkpoint,
                         then checkpoint.Preempted is raised — exit with
                         checkpoint.PREEMPTED_EXIT_CODE so a supervisor
                         respawns + auto-resumes.
        FLAGS_check_numerics  a non-finite-grad step is SKIPPED (scope
                         untouched); after FLAGS_check_numerics_max_bad_steps
                         consecutive bad steps fit rolls back to the
                         last checkpoint and re-trains from there (one
                         rollback without an intervening good step —
                         then the error propagates).
        reshard          elastic resume across a world-size change
                         (launcher resize): None defaults to
                         PADDLE_ELASTIC_RESHARD. False (and env unset):
                         a checkpoint from a different world size is
                         REFUSED (checkpoint.WorldSizeMismatchError).
                         True: resume proceeds and the mid-epoch
                         position is re-split — the per-rank step is
                         scaled by old_world/new_world so the global
                         sample offset carries over (exact when the
                         global batch divides both world sizes).
        """
        from ..fluid import checkpoint as ckpt_mod
        from ..fluid.flags import flag

        if isinstance(resume, str):
            checkpoint_dir = checkpoint_dir or resume
        mgr = (self._checkpoint_manager(checkpoint_dir, checkpoint_keep)
               if checkpoint_dir else None)
        if mgr is not None:
            ckpt_mod.install_preemption_handler()

        cb_list = (_to_list(callbacks)
                   or ([ProgBarLogger(log_freq, verbose=verbose)]
                       if verbose else []))
        from .. import telemetry

        if telemetry.enabled() and not any(
                isinstance(c, callbacks_mod.MetricsLogger) for c in cb_list):
            # PADDLE_METRICS_PATH armed the sink: fit reports through the
            # same registry/JSONL path as the executor and bench (ISSUE 4)
            cb_list = list(cb_list) + [callbacks_mod.MetricsLogger()]
        cbks = callbacks_mod.CallbackList(cb_list)
        cbks.set_model(self)
        cbks.on_train_begin()
        history = {"loss": []}
        train_data = self._materialize(train_data)
        if eval_data is not None:
            eval_data = self._materialize(eval_data)

        epoch, resume_step, pending_losses, global_step = 0, 0, [], 0
        if mgr is not None and resume:
            st = mgr.restore(allow_reshard=reshard)
            if st is not None:
                ex = st["extra"]
                epoch = int(ex.get("epoch", 0))
                resume_step = int(ex.get("step", 0))
                pending_losses = list(ex.get("epoch_losses", []))
                history = {k: list(v)
                           for k, v in ex.get("history", history).items()}
                global_step = int(ex.get("global_step", 0))
                ckpt_ws = st.get("world_size")
                if (ckpt_ws and mgr.world_size
                        and int(ckpt_ws) != int(mgr.world_size)):
                    # elastic resize: preserve the GLOBAL sample offset
                    # by scaling the per-rank position; the per-rank
                    # loss history from the old split is not comparable
                    # to the new shard, so the epoch restarts its
                    # running-mean bookkeeping at the re-split point
                    import warnings as _warnings

                    scaled = (resume_step * int(ckpt_ws)) // int(
                        mgr.world_size)
                    if (resume_step * int(ckpt_ws)) % int(mgr.world_size):
                        _warnings.warn(
                            f"elastic resume: per-rank step "
                            f"{resume_step}x{ckpt_ws} does not divide "
                            f"the new world {mgr.world_size}; rounding "
                            f"the resume position down", RuntimeWarning,
                            stacklevel=2)
                    _warnings.warn(
                        f"elastic resume: checkpoint world size "
                        f"{ckpt_ws} -> {mgr.world_size}; resuming epoch "
                        f"{epoch} at re-split step {scaled} (was "
                        f"{resume_step})", RuntimeWarning, stacklevel=2)
                    resume_step = scaled
                    pending_losses = []

        def _position(step, losses):
            return {"epoch": epoch, "step": step,
                    "epoch_losses": list(losses),
                    "history": {k: list(v) for k, v in history.items()},
                    "global_step": global_step}

        max_bad = max(1, int(flag("FLAGS_check_numerics_max_bad_steps")))
        bad_streak, last_rollback_sig = 0, None
        n_in = len(self._inputs)
        stop = False
        while epoch < epochs and not stop:
            cbks.on_epoch_begin(epoch)
            batches = self._batches(train_data, batch_size, shuffle,
                                    seed=epoch)
            losses = pending_losses if resume_step else []
            step = resume_step
            pending_losses, resume_step = [], 0
            rolled_back = False
            while step < len(batches):
                if mgr is not None:
                    # a failed background (async) checkpoint write
                    # latched in the writer — surface it at the step
                    # boundary, not from a silent gap in the chain
                    mgr.raise_if_async_failed()
                if mgr is not None and ckpt_mod.preemption_requested():
                    # final checkpoint is SYNCHRONOUS: it supersedes any
                    # queued async snapshot, waits out an in-flight
                    # write, and commits before the process exits
                    mgr.save(global_step,
                             extra_state=_position(step, losses),
                             async_=False)
                    raise ckpt_mod.Preempted(
                        f"preemption requested: checkpointed at global "
                        f"step {global_step} in {checkpoint_dir!r}")
                batch = batches[step]
                cbks.on_batch_begin("train", step)
                try:
                    outs = self.train_batch(batch[:n_in], batch[n_in:])
                except ckpt_mod.BadStepError:
                    bad_streak += 1
                    if bad_streak >= max_bad:
                        # a streak starting at the SAME position as the
                        # last rollback means the replay re-diverged
                        # deterministically — rolling back again would
                        # loop forever, so the error propagates
                        sig = (epoch, step - bad_streak + 1)
                        if (mgr is None or mgr.latest_step() is None
                                or sig == last_rollback_sig):
                            raise
                        last_rollback_sig = sig
                        st = mgr.restore(allow_reshard=reshard)
                        ex = st["extra"]
                        epoch = int(ex.get("epoch", 0))
                        resume_step = int(ex.get("step", 0))
                        pending_losses = list(ex.get("epoch_losses", []))
                        history = {
                            k: list(v)
                            for k, v in ex.get("history", {}).items()
                        } or history
                        global_step = int(ex.get("global_step", 0))
                        bad_streak = 0
                        rolled_back = True
                        break
                    step += 1  # skip the poisoned batch
                    global_step += 1
                    continue
                bad_streak = 0
                loss = float(np.asarray(outs[0]).reshape(()))
                losses.append(loss)
                cbks.on_batch_end("train", step, {"loss": loss})
                step += 1
                global_step += 1
                if (mgr is not None and checkpoint_freq
                        and global_step % checkpoint_freq == 0):
                    mgr.save(global_step,
                             extra_state=_position(step, losses))
            if rolled_back:
                continue  # re-enter at the restored (epoch, step)
            logs = {"loss": float(np.mean(losses))}
            history["loss"].append(logs["loss"])
            if eval_data is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_data, batch_size, verbose=0)
                logs.update({f"val_{k}": v for k, v in eval_logs.items()})
                history.setdefault("val_loss", []).append(eval_logs.get("loss"))
            if save_dir and (epoch + 1) % save_freq == 0:
                import os

                self.save(os.path.join(save_dir, f"epoch_{epoch}"))
            if cbks.on_epoch_end(epoch, logs):
                stop = True
            epoch += 1
        cbks.on_train_end()
        if mgr is not None:
            # fit returns with its checkpoints ON DISK: wait out any
            # queued/in-flight async write (and surface its failure)
            mgr.drain()
        return history

    def evaluate(self, eval_data, batch_size=32, log_freq=10, verbose=2,
                 callbacks=None):
        """reference hapi evaluate:1320 — returns {loss, metric values}."""
        for m in self._metrics:
            m.reset()
        losses = []
        n_in = len(self._inputs)
        eval_data = self._materialize(eval_data)
        for batch in self._batches(eval_data, batch_size, False, 0):
            outs = self.eval_batch(batch[:n_in], batch[n_in:])
            losses.append(float(np.asarray(outs[0]).reshape(())))
            preds = outs[1:]
            for m in self._metrics:
                # Keras-style binding: (first output, first label). Metrics
                # over multi-output networks should subclass and override.
                m.update(np.asarray(preds[0]), np.asarray(batch[n_in]))
        logs = {"loss": float(np.mean(losses)) if losses else float("nan")}
        for m in self._metrics:
            logs[m.name()] = m.accumulate()
        return logs

    def predict(self, test_data, batch_size=32, stack_outputs=True,
                callbacks=None):
        """reference hapi predict:1417."""
        outs_all: List[List[np.ndarray]] = []
        n_in = len(self._inputs)
        test_data = self._materialize(test_data)
        for batch in self._batches(test_data, batch_size, False, 0):
            outs = self.test_batch(batch[:n_in])
            outs_all.append([np.asarray(o) for o in outs])
        n_out = len(outs_all[0])
        cols = [[b[i] for b in outs_all] for i in range(n_out)]
        if stack_outputs:
            cols = [np.concatenate(c, axis=0) for c in cols]
        return cols

    # ------------------------------------------------------------------
    def save(self, path):
        """Persistables of the train (or first) program -> '<path>.pdparams'
        (reference hapi save:892 writes the same split)."""
        import os

        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        main = next(iter(self._progs.values()))[0]
        with fluid.scope_guard(self._scope):
            fluid.io.save_persistables(self._exe, path + ".pdparams", main)

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        main = next(iter(self._progs.values()))[0]
        with fluid.scope_guard(self._scope):
            fluid.io.load_persistables(self._exe, path + ".pdparams", main)

    def parameters(self):
        main = next(iter(self._progs.values()))[0]
        with fluid.scope_guard(self._scope):
            scope = fluid.global_scope()
            return {
                v.name: np.asarray(scope.find_var(v.name))
                for v in main.list_vars()
                if isinstance(v, fluid.framework.Parameter)
                and scope.find_var(v.name) is not None
            }
