"""hapi metrics (reference python/paddle/incubate/hapi/metrics.py:
Metric base + Accuracy)."""
from __future__ import annotations

import numpy as np


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError


class Accuracy(Metric):
    """Top-k accuracy over (pred_logits, label) batches."""

    def __init__(self, topk=1, name="acc"):
        self.topk = topk
        self._name = name
        self.reset()

    def reset(self):
        self.correct = 0
        self.total = 0

    def update(self, pred, label, *rest):
        pred = np.asarray(pred)
        label = np.asarray(label).reshape(-1)
        idx = np.argsort(-pred, axis=-1)[:, : self.topk]
        self.correct += int((idx == label[:, None]).any(axis=1).sum())
        self.total += label.shape[0]

    def accumulate(self):
        return self.correct / max(self.total, 1)

    def name(self):
        return self._name
