"""hapi.text — NLP building blocks for hapi.Model networks.

Parity surface: reference python/paddle/incubate/hapi/text/text.py
(BasicLSTMCell:186, BasicGRUCell:321, RNN:476, BidirectionalRNN:1006,
Conv1dPoolLayer:1980, CNNEncoder:2109, TransformerEncoder:3061,
TransformerDecoder:3314, DynamicDecode:1762, LinearChainCRF:3506,
CRFDecoding:3655, SequenceTagging:3832).

TPU-native redesign: the reference classes are dygraph Layers running
per-step Python; here each block is a static-graph builder whose
__call__ EMITS ops into the current program, so hapi.Model traces it
once and XLA compiles the whole network:
- recurrent blocks ride the scanned StaticRNN/recurrent op
  (fluid/layers/rnn.py) — one lax.scan, not a Python time loop;
- TransformerEncoder/Decoder wrap the fused scan-over-layers stack ops
  (ops/encoder_stack.py, ops/decoder_stack.py: Pallas flash attention,
  O(1)-in-depth compile);
- seq2seq attention is computed over the WHOLE teacher-forced target
  sequence at once through the rectangular fused attention op — a
  [B,Tq,H]x[B,Tk,H] kernel per decode layer instead of the reference's
  per-step attention matmuls.

Instances are reusable and isolated: every block namespaces its
parameters under a unique (or user-given) prefix, so two encoders in
one network do not share weights, and hapi.Model's per-mode program
rebuild (under unique_name.guard) reproduces identical names.
"""
from __future__ import annotations

from typing import Optional, Sequence

from ..fluid import layers, unique_name
from ..fluid.initializer import ConstantInitializer, NormalInitializer
from ..fluid.layer_helper import LayerHelper
from ..fluid.param_attr import ParamAttr

__all__ = [
    "BasicLSTMCell", "BasicGRUCell", "StackedRNNCell", "StackedLSTMCell",
    "StackedGRUCell", "LSTM", "GRU", "BidirectionalLSTM",
    "BidirectionalGRU", "RNN", "BidirectionalRNN", "Conv1dPoolLayer",
    "CNNEncoder", "PrePostProcessLayer", "MultiHeadAttention", "FFN",
    "TransformerEncoder", "TransformerDecoder", "TransformerCell",
    "TransformerBeamSearchDecoder", "DynamicDecode",
    "LinearChainCRF", "CRFDecoding", "SequenceTagging", "Seq2SeqEncoder",
    "Seq2SeqDecoder",
]


# ---------------------------------------------------------------------------
# recurrent cells / runners
# ---------------------------------------------------------------------------


class BasicLSTMCell(layers.LSTMCell):
    """Reference BasicLSTMCell (text.py:186): single fused gate matmul,
    forget-gate bias. `input_size` is accepted for signature parity but
    inferred from the data at build time."""

    def __init__(self, input_size=None, hidden_size=128, param_attr=None,
                 bias_attr=None, gate_activation=None, activation=None,
                 forget_bias=1.0, dtype="float32", name=None):
        super().__init__(
            hidden_size, param_attr=param_attr, bias_attr=bias_attr,
            gate_activation=gate_activation, activation=activation,
            forget_bias=forget_bias, dtype=dtype,
            name=name or unique_name.generate("basic_lstm_cell"))
        self.input_size = input_size


class BasicGRUCell(layers.GRUCell):
    """Reference BasicGRUCell (text.py:321)."""

    def __init__(self, input_size=None, hidden_size=128, param_attr=None,
                 bias_attr=None, gate_activation=None, activation=None,
                 dtype="float32", name=None):
        super().__init__(
            hidden_size, param_attr=param_attr, bias_attr=bias_attr,
            gate_activation=gate_activation, activation=activation,
            dtype=dtype, name=name or unique_name.generate("basic_gru_cell"))
        self.input_size = input_size


class StackedRNNCell(layers.RNNCell):
    """Reference StackedRNNCell (text.py:639): a stack of cells behaving
    as ONE cell — step input flows cell 0 -> 1 -> ... The composite
    state is FLAT ([h0, c0, h1, c1, ...]): the scanned runner
    (layers.rnn / StaticRNN) carries one memory per state Variable, so
    nesting must not reach it."""

    def __init__(self, cells):
        self.cells = list(cells)

    def _counts(self):
        # states-per-cell from the declared shapes ([..]=1, [[..],..]=n)
        out = []
        for c in self.cells:
            s = c.state_shape
            out.append(len(s) if isinstance(s[0], (list, tuple)) else 1)
        return out

    @property
    def state_shape(self):
        flat = []
        for c in self.cells:
            s = c.state_shape
            flat.extend(s if isinstance(s[0], (list, tuple)) else [s])
        return flat

    def call(self, inputs, states):
        new_states = []
        out = inputs
        i = 0
        for cell, n in zip(self.cells, self._counts()):
            st = states[i:i + n]
            out, ns = cell.call(out, st[0] if n == 1 else st)
            new_states.extend(ns if isinstance(ns, (list, tuple)) else [ns])
            i += n
        return out, new_states


class StackedLSTMCell(StackedRNNCell):
    """Reference StackedLSTMCell (text.py:734)."""

    def __init__(self, input_size=None, hidden_size=128, num_layers=1,
                 forget_bias=1.0, dtype="float32", name=None):
        name = name or unique_name.generate("stacked_lstm")
        super().__init__([
            BasicLSTMCell(hidden_size=hidden_size,
                          forget_bias=forget_bias, dtype=dtype,
                          name=f"{name}.l{i}")
            for i in range(num_layers)
        ])


class StackedGRUCell(StackedRNNCell):
    """Reference StackedGRUCell (text.py:1337)."""

    def __init__(self, input_size=None, hidden_size=128, num_layers=1,
                 dtype="float32", name=None):
        name = name or unique_name.generate("stacked_gru")
        super().__init__([
            BasicGRUCell(hidden_size=hidden_size, dtype=dtype,
                         name=f"{name}.l{i}")
            for i in range(num_layers)
        ])


class LSTM:
    """Reference LSTM (text.py:886): multi-layer LSTM over a sequence.
    Returns (outputs, final_states) where final_states is the stacked
    cell's FLAT state list [h0, c0, h1, c1, ...] (see StackedRNNCell —
    layer i's final h/c are final_states[2*i] / final_states[2*i+1])."""

    def __init__(self, input_size=None, hidden_size=128, num_layers=1,
                 forget_bias=1.0, is_reverse=False, time_major=False,
                 dtype="float32", name=None):
        self.cell = StackedLSTMCell(input_size, hidden_size, num_layers,
                                    forget_bias, dtype, name)
        self.rnn = RNN(self.cell, is_reverse=is_reverse,
                       time_major=time_major)

    def __call__(self, inputs, initial_states=None, sequence_length=None):
        return self.rnn(inputs, initial_states, sequence_length)


class GRU:
    """Reference GRU (text.py:1470)."""

    def __init__(self, input_size=None, hidden_size=128, num_layers=1,
                 is_reverse=False, time_major=False, dtype="float32",
                 name=None):
        self.cell = StackedGRUCell(input_size, hidden_size, num_layers,
                                   dtype, name)
        self.rnn = RNN(self.cell, is_reverse=is_reverse,
                       time_major=time_major)

    def __call__(self, inputs, initial_states=None, sequence_length=None):
        return self.rnn(inputs, initial_states, sequence_length)


class BidirectionalLSTM:
    """Reference BidirectionalLSTM (text.py:1144): concat merge."""

    def __init__(self, input_size=None, hidden_size=128, num_layers=1,
                 forget_bias=1.0, time_major=False, dtype="float32",
                 name=None):
        name = name or unique_name.generate("bilstm")
        self.time_major = bool(time_major)
        self.fw = StackedLSTMCell(input_size, hidden_size, num_layers,
                                  forget_bias, dtype, f"{name}.fw")
        self.bw = StackedLSTMCell(input_size, hidden_size, num_layers,
                                  forget_bias, dtype, f"{name}.bw")

    def __call__(self, inputs, initial_states=None, sequence_length=None):
        return layers.birnn(self.fw, self.bw, inputs,
                            initial_states=initial_states,
                            sequence_length=sequence_length,
                            time_major=self.time_major)


class BidirectionalGRU:
    """Reference BidirectionalGRU (text.py:1581)."""

    def __init__(self, input_size=None, hidden_size=128, num_layers=1,
                 time_major=False, dtype="float32", name=None):
        name = name or unique_name.generate("bigru")
        self.time_major = bool(time_major)
        self.fw = StackedGRUCell(input_size, hidden_size, num_layers,
                                 dtype, f"{name}.fw")
        self.bw = StackedGRUCell(input_size, hidden_size, num_layers,
                                 dtype, f"{name}.bw")

    def __call__(self, inputs, initial_states=None, sequence_length=None):
        return layers.birnn(self.fw, self.bw, inputs,
                            initial_states=initial_states,
                            sequence_length=sequence_length,
                            time_major=self.time_major)


class RNN:
    """Reference RNN (text.py:476): run `cell` over the time axis of
    [B, T, D] (or [T, B, D] when time_major). Returns (outputs,
    final_states)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        self.cell = cell
        self.is_reverse = bool(is_reverse)
        self.time_major = bool(time_major)

    def __call__(self, inputs, initial_states=None, sequence_length=None):
        return layers.rnn(
            self.cell, inputs, initial_states=initial_states,
            sequence_length=sequence_length, time_major=self.time_major,
            is_reverse=self.is_reverse)


class BidirectionalRNN:
    """Reference BidirectionalRNN (text.py:1006): forward + reverse
    cells, outputs concatenated on the feature axis."""

    def __init__(self, cell_fw, cell_bw, merge_mode="concat"):
        if merge_mode != "concat":
            raise NotImplementedError(
                "merge_mode={!r}: the reference supports concat in its "
                "hapi examples; sum/ave/mul/zip have no users in the "
                "parity surface".format(merge_mode))
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw

    def __call__(self, inputs, initial_states=None, sequence_length=None):
        return layers.birnn(
            self.cell_fw, self.cell_bw, inputs,
            initial_states=initial_states, sequence_length=sequence_length)


class DynamicDecode:
    """Reference DynamicDecode (text.py:1762): drive a Decoder (e.g.
    layers.BeamSearchDecoder) to completion."""

    def __init__(self, decoder, max_step_num=None, output_time_major=False,
                 impute_finished=False, is_test=False, return_length=False):
        self.decoder = decoder
        self.max_step_num = max_step_num
        self.output_time_major = output_time_major
        self.return_length = return_length
        # dynamic_decode runs exactly max_step_num steps, touching
        # buffer positions [0, max_step_num)
        cell_max = getattr(getattr(decoder, "cell", None), "max_len", None)
        if (cell_max is not None and max_step_num is not None
                and int(max_step_num) > int(cell_max)):
            raise ValueError(
                f"DynamicDecode: max_step_num={max_step_num} exceeds the "
                f"TransformerCell's max_len={cell_max}; past the static "
                f"buffer every position mask is zero and outputs degrade "
                f"silently — raise max_len or lower max_step_num")

    def __call__(self, inits=None, **kwargs):
        return layers.dynamic_decode(
            self.decoder, inits=inits, max_step_num=self.max_step_num,
            output_time_major=self.output_time_major,
            return_length=self.return_length, **kwargs)


# ---------------------------------------------------------------------------
# convolutional encoder
# ---------------------------------------------------------------------------


class Conv1dPoolLayer:
    """Reference Conv1dPoolLayer (text.py:1980): 1-D conv over the time
    axis of [B, T, D] + max-pool over time. Emitted as a conv2d with a
    [filter_size x D] kernel on the [B, 1, T, D] view — one MXU matmul
    per window row instead of a per-step loop."""

    def __init__(self, num_channels, num_filters, filter_size,
                 pool_size=None, act="tanh", name=None):
        self.num_channels = num_channels  # feature dim D
        self.num_filters = num_filters
        self.filter_size = int(filter_size)
        self.pool_size = pool_size  # None -> global max pool over time
        self.act = act
        self.name = name or unique_name.generate("conv1d_pool")

    def __call__(self, x):
        b, t, d = x.shape
        x4 = layers.reshape(x, [b, 1, t, d])
        conv = layers.conv2d(
            x4, num_filters=self.num_filters,
            filter_size=[self.filter_size, d],
            padding=[self.filter_size // 2, 0], act=self.act,
            param_attr=ParamAttr(name=f"{self.name}.w_0"),
            bias_attr=ParamAttr(name=f"{self.name}.b_0"))
        # conv: [B, F, T', 1] -> pool over T'
        if self.pool_size is None:
            pooled = layers.reduce_max(conv, dim=[2, 3])  # [B, F]
        else:
            pooled = layers.pool2d(conv, pool_size=[self.pool_size, 1],
                                   pool_type="max",
                                   pool_stride=[self.pool_size, 1])
            pooled = layers.squeeze(pooled, axes=[3])  # [B, F, T'']
            pooled = layers.transpose(pooled, [0, 2, 1])
        return pooled


class CNNEncoder:
    """Reference CNNEncoder (text.py:2109): parallel Conv1dPoolLayers
    with different filter sizes, outputs concatenated."""

    def __init__(self, num_channels, num_filters, filter_sizes=(3, 4, 5),
                 pool_size=None, act="tanh", name=None):
        name = name or unique_name.generate("cnn_encoder")
        sizes = list(filter_sizes)
        filters = (num_filters if isinstance(num_filters, (list, tuple))
                   else [num_filters] * len(sizes))
        self.convs = [
            Conv1dPoolLayer(num_channels, f, s, pool_size=pool_size,
                            act=act, name=f"{name}.conv{i}")
            for i, (f, s) in enumerate(zip(filters, sizes))
        ]

    def __call__(self, x):
        outs = [conv(x) for conv in self.convs]
        return layers.concat(outs, axis=-1) if len(outs) > 1 else outs[0]


# ---------------------------------------------------------------------------
# transformer sub-blocks (reference text.py PrePostProcessLayer:2609,
# MultiHeadAttention:2687, FFN:2900) — the composable pieces; whole
# stacks should prefer TransformerEncoder/Decoder below (fused scan)
# ---------------------------------------------------------------------------


class PrePostProcessLayer:
    """process_cmd string: 'a' residual add, 'n' layer_norm,
    'd' dropout — applied in order (reference text.py:2609)."""

    def __init__(self, process_cmd, d_model=None, dropout_rate=0.0,
                 name=None):
        self.cmd = process_cmd
        self.dropout_rate = float(dropout_rate)
        self.name = name or unique_name.generate("prepost")

    def __call__(self, prev_out, out=None, is_test=False):
        x = out if out is not None else prev_out
        for c in self.cmd:
            if c == "a" and prev_out is not None and out is not None:
                x = layers.elementwise_add(prev_out, x)
            elif c == "n":
                x = layers.layer_norm(
                    x, begin_norm_axis=len(x.shape) - 1,
                    param_attr=ParamAttr(name=f"{self.name}.ln_s"),
                    bias_attr=ParamAttr(name=f"{self.name}.ln_b"))
            elif c == "d" and self.dropout_rate and not is_test:
                x = layers.dropout(
                    x, self.dropout_rate,
                    dropout_implementation="upscale_in_train")
        return x


class MultiHeadAttention:
    """q/k/v projections + the fused attention op + output projection
    (reference text.py:2687; here the score math is the Pallas flash
    kernel instead of decomposed matmuls). `d_key`/`d_value` are
    accepted for signature parity but UNUSED: the fused kernel reads
    head-interleaved [B, S, d_model] with head dim d_model // n_head —
    configs where d_key * n_head != d_model are not expressible."""

    def __init__(self, d_key=None, d_value=None, d_model=512, n_head=1,
                 dropout_rate=0.0, name=None):
        self.d_model = int(d_model)
        self.n_head = int(n_head)
        self.dropout_rate = float(dropout_rate)
        self.name = name or unique_name.generate("mha")

    def _fc(self, x, suffix):
        return layers.fc(
            x, self.d_model, num_flatten_dims=2,
            param_attr=ParamAttr(name=f"{self.name}.{suffix}.w"),
            bias_attr=ParamAttr(name=f"{self.name}.{suffix}.b"))

    def __call__(self, queries, keys=None, values=None, attn_bias=None,
                 causal=False, is_test=False):
        keys = queries if keys is None else keys
        values = keys if values is None else values
        q = self._fc(queries, "q")
        k = self._fc(keys, "k")
        v = self._fc(values, "v")
        ctx = layers.fused_multihead_attention(
            q, k, v, attn_bias, num_heads=self.n_head,
            dropout_prob=self.dropout_rate, is_test=is_test,
            causal=causal)
        return self._fc(ctx, "out")


class FFN:
    """Position-wise feed-forward (reference text.py:2900)."""

    def __init__(self, d_inner_hid, d_model, dropout_rate=0.0,
                 fc1_act="relu", name=None):
        self.d_inner = int(d_inner_hid)
        self.d_model = int(d_model)
        self.dropout_rate = float(dropout_rate)
        self.act = fc1_act
        self.name = name or unique_name.generate("ffn")

    def __call__(self, x, is_test=False):
        inter = layers.fc(
            x, self.d_inner, num_flatten_dims=2, act=self.act,
            param_attr=ParamAttr(name=f"{self.name}.fc1.w"),
            bias_attr=ParamAttr(name=f"{self.name}.fc1.b"))
        if self.dropout_rate and not is_test:
            inter = layers.dropout(
                inter, self.dropout_rate,
                dropout_implementation="upscale_in_train")
        return layers.fc(
            inter, self.d_model, num_flatten_dims=2,
            param_attr=ParamAttr(name=f"{self.name}.fc2.w"),
            bias_attr=ParamAttr(name=f"{self.name}.fc2.b"))


# ---------------------------------------------------------------------------
# transformer blocks (fused scan-over-layers stacks)
# ---------------------------------------------------------------------------


def _stack_param(helper, name, shape, init=None):
    return helper.create_parameter(
        ParamAttr(name=name,
                  initializer=init or NormalInitializer(0.0, 0.02)),
        shape=shape, dtype="float32")


class TransformerEncoder:
    """Reference TransformerEncoder (text.py:3061) on the fused
    scan-over-layers op (ops/encoder_stack.py): Pallas flash attention,
    post-layernorm residual blocks, one op for all n_layer layers."""

    def __init__(self, n_layer, n_head, d_key=None, d_value=None,
                 d_model=512, d_inner_hid=2048,
                 prepostprocess_dropout=0.1, attention_dropout=0.1,
                 relu_dropout=0.1, ffn_fc1_act="relu", name=None):
        self.n_layer = int(n_layer)
        self.n_head = int(n_head)
        self.d_model = int(d_model)
        self.d_inner = int(d_inner_hid)
        self.dropout = float(prepostprocess_dropout)
        self.attn_dropout = float(attention_dropout)
        self.act = ffn_fc1_act
        self.name = name or unique_name.generate("transformer_encoder")

    def __call__(self, enc_input, attn_bias=None, is_test=False):
        """enc_input: [B, S, d_model]; attn_bias: additive mask
        broadcastable to [B, n_head, S, S] (e.g. a [B,1,1,S] pad bias)."""
        from ..fluid.layers.nn import _rng_salt_counter

        L, h, f = self.n_layer, self.d_model, self.d_inner
        helper = LayerHelper("fused_encoder_stack")
        ones, zeros = ConstantInitializer(1.0), ConstantInitializer(0.0)
        n = self.name
        p = {
            "QKVW": _stack_param(helper, f"{n}.qkv_w", [L, h, 3 * h]),
            "QKVB": _stack_param(helper, f"{n}.qkv_b", [L, 3 * h], zeros),
            "OutW": _stack_param(helper, f"{n}.out_w", [L, h, h]),
            "OutB": _stack_param(helper, f"{n}.out_b", [L, h], zeros),
            "Ln1S": _stack_param(helper, f"{n}.ln1_s", [L, h], ones),
            "Ln1B": _stack_param(helper, f"{n}.ln1_b", [L, h], zeros),
            "FfnW1": _stack_param(helper, f"{n}.ffn_w1", [L, h, f]),
            "FfnB1": _stack_param(helper, f"{n}.ffn_b1", [L, f], zeros),
            "FfnW2": _stack_param(helper, f"{n}.ffn_w2", [L, f, h]),
            "FfnB2": _stack_param(helper, f"{n}.ffn_b2", [L, h], zeros),
            "Ln2S": _stack_param(helper, f"{n}.ln2_s", [L, h], ones),
            "Ln2B": _stack_param(helper, f"{n}.ln2_b", [L, h], zeros),
        }
        out = helper.create_variable_for_type_inference("float32")
        ins = {"Hidden": [enc_input], **{k: [v] for k, v in p.items()}}
        if attn_bias is not None:
            ins["AttnBias"] = [attn_bias]
        _rng_salt_counter[0] += 1
        helper.append_op(
            type="fused_encoder_stack", inputs=ins, outputs={"Out": [out]},
            attrs={"num_heads": self.n_head, "act": self.act,
                   "dropout_prob": self.dropout,
                   "attn_dropout_prob": self.attn_dropout,
                   "is_test": is_test, "use_flash_attention": True,
                   "rng_salt": _rng_salt_counter[0]})
        return out


class TransformerDecoder:
    """Reference TransformerDecoder (text.py:3314) on the fused decoder
    stack op (ops/decoder_stack.py): causal self-attention + rectangular
    cross-attention over the encoder output, scanned over layers."""

    def __init__(self, n_layer, n_head, d_key=None, d_value=None,
                 d_model=512, d_inner_hid=2048,
                 prepostprocess_dropout=0.1, attention_dropout=0.1,
                 relu_dropout=0.1, ffn_fc1_act="relu", name=None):
        self.n_layer = int(n_layer)
        self.n_head = int(n_head)
        self.d_model = int(d_model)
        self.d_inner = int(d_inner_hid)
        self.dropout = float(prepostprocess_dropout)
        self.attn_dropout = float(attention_dropout)
        self.act = ffn_fc1_act
        self.name = name or unique_name.generate("transformer_decoder")

    def __call__(self, dec_input, enc_output, cross_attn_bias=None,
                 is_test=False):
        """dec_input: [B, T, d_model]; enc_output: [B, S, d_model];
        cross_attn_bias: source pad bias [B, 1, 1, S]."""
        from ..fluid.layers.nn import _rng_salt_counter

        L, h, f = self.n_layer, self.d_model, self.d_inner
        helper = LayerHelper("fused_decoder_stack")
        ones, zeros = ConstantInitializer(1.0), ConstantInitializer(0.0)

        def p_(suffix, shape, init=None):
            return _stack_param(helper, f"{self.name}.{suffix}", shape, init)

        p = {
            "SelfQKVW": p_("self_qkv_w", [L, h, 3 * h]),
            "SelfQKVB": p_("self_qkv_b", [L, 3 * h], zeros),
            "SelfOutW": p_("self_out_w", [L, h, h]),
            "SelfOutB": p_("self_out_b", [L, h], zeros),
            "Ln1S": p_("ln1_s", [L, h], ones),
            "Ln1B": p_("ln1_b", [L, h], zeros),
            "CrossQW": p_("cross_q_w", [L, h, h]),
            "CrossQB": p_("cross_q_b", [L, h], zeros),
            "CrossKW": p_("cross_k_w", [L, h, h]),
            "CrossKB": p_("cross_k_b", [L, h], zeros),
            "CrossVW": p_("cross_v_w", [L, h, h]),
            "CrossVB": p_("cross_v_b", [L, h], zeros),
            "CrossOutW": p_("cross_out_w", [L, h, h]),
            "CrossOutB": p_("cross_out_b", [L, h], zeros),
            "Ln2S": p_("ln2_s", [L, h], ones),
            "Ln2B": p_("ln2_b", [L, h], zeros),
            "FfnW1": p_("ffn_w1", [L, h, f]),
            "FfnB1": p_("ffn_b1", [L, f], zeros),
            "FfnW2": p_("ffn_w2", [L, f, h]),
            "FfnB2": p_("ffn_b2", [L, h], zeros),
            "Ln3S": p_("ln3_s", [L, h], ones),
            "Ln3B": p_("ln3_b", [L, h], zeros),
        }
        out = helper.create_variable_for_type_inference("float32")
        ins = {"Hidden": [dec_input], "EncOut": [enc_output],
               **{k: [v] for k, v in p.items()}}
        if cross_attn_bias is not None:
            ins["SrcBias"] = [cross_attn_bias]
        _rng_salt_counter[0] += 1
        helper.append_op(
            type="fused_decoder_stack", inputs=ins, outputs={"Out": [out]},
            attrs={"num_heads": self.n_head, "act": self.act,
                   "dropout_prob": self.dropout,
                   "attn_dropout_prob": self.attn_dropout,
                   "is_test": is_test, "use_flash_attention": True,
                   "rng_salt": _rng_salt_counter[0]})
        return out


class TransformerCell(layers.RNNCell):
    """Reference TransformerCell (text.py:2252): step-wise decoding over
    a TransformerDecoder.

    TPU-native redesign: instead of per-layer k/v caches (dynamic
    shapes), the cell carries a STATIC [B, max_len, H] embedding buffer
    and re-runs the fused decoder stack over the whole prefix each step
    — the causal mask makes row `pos` exact, shapes stay compile-time
    constant, and the O(T^2 · L) decode cost is the standard static-
    shape trade for short generation lengths. State (all plain tensors,
    so BeamSearchDecoder's tile/gather machinery just works):
    [buffer, pos, enc_output, cross_bias?].

    CONTRACT: decode at most `max_len` steps (dynamic_decode's
    max_step_num must be < max_len); past the buffer the position mask
    would be all-zero and outputs degrade silently. DynamicDecode
    asserts this when it can see the cell.
    """

    def __init__(self, decoder, max_len=64, with_bias=True):
        self.decoder = decoder
        self.max_len = int(max_len)
        self.with_bias = bool(with_bias)

    def get_initial_states(self, enc_output, cross_attn_bias=None,
                           dtype="float32"):
        """enc_output [B, S, H] (+ the [B, 1, 1, S] source bias iff the
        cell was built with with_bias=True — a mismatch would silently
        drop the bias or destabilize the state structure)."""
        from ..fluid.layers import tensor as _tensor

        if (cross_attn_bias is not None) != self.with_bias:
            raise ValueError(
                f"TransformerCell(with_bias={self.with_bias}) but "
                f"cross_attn_bias is "
                f"{'set' if cross_attn_bias is not None else 'missing'} "
                f"— the bias rides the state list, so the two must agree")
        b = enc_output.shape[0]
        h = enc_output.shape[-1]
        buf = _tensor.fill_constant([b, self.max_len, h], dtype, 0.0)
        pos = _tensor.fill_constant([b], "int64", 0)
        states = [buf, pos, enc_output]
        if cross_attn_bias is not None:
            states.append(cross_attn_bias)
        return states

    def call(self, inputs, states):
        """inputs: current token embedding [B, H] (position encoding is
        applied in-cell over the whole buffer, identical to training)."""
        buf, pos, enc_out = states[0], states[1], states[2]
        bias = states[3] if self.with_bias and len(states) > 3 else None
        # one_hot on [B, 1] then squeeze: the [B] form would dispatch to
        # the legacy one_hot op at B==1 (shape[-1]==1) and lose the
        # batch dim (round-5 review finding)
        onehot = layers.squeeze(
            layers.one_hot(layers.unsqueeze(pos, [1]), self.max_len),
            axes=[1])  # [B, L]
        mask3 = layers.unsqueeze(onehot, [2])       # [B, L, 1]
        buf = layers.elementwise_add(
            layers.elementwise_mul(
                buf, layers.scale(mask3, scale=-1.0, bias=1.0)),
            layers.elementwise_mul(layers.unsqueeze(inputs, [1]), mask3))
        x = layers.add_position_encoding(buf, alpha=1.0, beta=1.0)
        dec_out = self.decoder(x, enc_out, bias, is_test=True)
        out_row = layers.reduce_sum(
            layers.elementwise_mul(dec_out, mask3), dim=1)  # [B, H]
        new_pos = layers.elementwise_add(
            pos, layers.fill_constant([1], "int64", 1))
        new_states = [buf, new_pos, enc_out]
        if bias is not None:
            new_states.append(bias)
        return out_row, new_states


class TransformerBeamSearchDecoder(layers.BeamSearchDecoder):
    """Reference TransformerBeamSearchDecoder (text.py:2421). The
    generic beam machinery already beam-tiles and parent-gathers every
    tensor in TransformerCell's state list (buffer, pos, enc_output,
    bias), so this subclass is the reference-named entry point."""


# ---------------------------------------------------------------------------
# LSTM seq2seq blocks
# ---------------------------------------------------------------------------


class Seq2SeqEncoder:
    """LSTM sequence encoder (the reference's hapi seq2seq example
    encoder, seq2seq machine translation over BasicLSTMCell): embedding
    + (optionally bidirectional) scanned LSTM."""

    def __init__(self, vocab_size, embed_dim, hidden_size,
                 bidirectional=False, name=None):
        self.name = name or unique_name.generate("seq2seq_enc")
        self.vocab_size = int(vocab_size)
        self.embed_dim = int(embed_dim)
        self.hidden_size = int(hidden_size)
        self.bidirectional = bool(bidirectional)

    def __call__(self, src_ids, src_length=None):
        emb = layers.embedding(
            src_ids, size=[self.vocab_size, self.embed_dim],
            param_attr=ParamAttr(name=f"{self.name}.embed",
                                 initializer=NormalInitializer(0.0, 0.1)))
        if self.bidirectional:
            fw = BasicLSTMCell(hidden_size=self.hidden_size,
                               name=f"{self.name}.lstm_fw")
            bw = BasicLSTMCell(hidden_size=self.hidden_size,
                               name=f"{self.name}.lstm_bw")
            out, (fin_fw, fin_bw) = layers.birnn(
                fw, bw, emb, sequence_length=src_length)
            return out, fin_fw
        cell = BasicLSTMCell(hidden_size=self.hidden_size,
                             name=f"{self.name}.lstm")
        return layers.rnn(cell, emb, sequence_length=src_length)


class Seq2SeqDecoder:
    """Teacher-forced attention decoder. TPU-native: the target LSTM
    scans once over the whole sequence, then Luong-style attention runs
    as ONE rectangular fused attention ([B,T,H] queries over [B,S,H]
    encoder keys) instead of per-step attention matmuls — the MXU sees
    two big matmuls per batch, and causality is free (decoder states
    only see the source)."""

    def __init__(self, vocab_size, embed_dim, hidden_size,
                 use_attention=True, name=None):
        self.name = name or unique_name.generate("seq2seq_dec")
        self.vocab_size = int(vocab_size)
        self.embed_dim = int(embed_dim)
        self.hidden_size = int(hidden_size)
        self.use_attention = bool(use_attention)

    def __call__(self, trg_ids, enc_output, enc_final_states,
                 src_mask=None):
        emb = layers.embedding(
            trg_ids, size=[self.vocab_size, self.embed_dim],
            param_attr=ParamAttr(name=f"{self.name}.embed",
                                 initializer=NormalInitializer(0.0, 0.1)))
        cell = BasicLSTMCell(hidden_size=self.hidden_size,
                             name=f"{self.name}.lstm")
        hid, _ = layers.rnn(cell, emb, initial_states=enc_final_states)
        if self.use_attention:
            bias = None
            if src_mask is not None:
                bias = layers.unsqueeze(layers.unsqueeze(layers.scale(
                    layers.cast(src_mask, "float32"), scale=1e4,
                    bias=-1e4), [1]), [1])
            ctx = layers.fused_multihead_attention(
                hid, enc_output, enc_output, bias, num_heads=1,
                dropout_prob=0.0, is_test=True, causal=False)
            hid = layers.fc(
                layers.concat([hid, ctx], axis=2), self.hidden_size,
                num_flatten_dims=2, act="tanh",
                param_attr=ParamAttr(name=f"{self.name}.attn_fc.w"),
                bias_attr=ParamAttr(name=f"{self.name}.attn_fc.b"))
        return layers.fc(
            hid, self.vocab_size, num_flatten_dims=2,
            param_attr=ParamAttr(name=f"{self.name}.proj.w"),
            bias_attr=ParamAttr(name=f"{self.name}.proj.b"))


# ---------------------------------------------------------------------------
# CRF tagging
# ---------------------------------------------------------------------------


class LinearChainCRF:
    """Reference LinearChainCRF layer (text.py:3506): emissions + labels
    -> per-sequence negative log-likelihood."""

    def __init__(self, param_attr=None, size=None, name=None):
        self.name = name or unique_name.generate("crf")
        self.param_attr = param_attr or ParamAttr(name=f"{self.name}.w")

    def __call__(self, input, label, length=None):
        return layers.linear_chain_crf(
            input, label, param_attr=self.param_attr, length=length)


class CRFDecoding:
    """Reference CRFDecoding (text.py:3655): Viterbi argmax path using
    the SAME transition parameter as LinearChainCRF (share param_attr —
    scope storage is keyed by name, so an inference-only program that
    never built the CRF loss still reads the trained transitions)."""

    def __init__(self, param_attr=None, size=None, name=None):
        self.name = name or unique_name.generate("crf")
        self.param_attr = param_attr or ParamAttr(name=f"{self.name}.w")

    def __call__(self, input, length=None):
        helper = LayerHelper("crf_decoding", param_attr=self.param_attr)
        d = input.shape[-1]
        trans = helper.create_parameter(
            helper.param_attr, shape=[d + 2, d], dtype=input.dtype)
        path = helper.create_variable_for_type_inference("int64")
        ins = {"Emission": [input], "Transition": [trans]}
        if length is not None:
            ins["Length"] = [length]
        helper.append_op(type="crf_decoding", inputs=ins,
                         outputs={"ViterbiPath": [path]}, attrs={})
        return path


class SequenceTagging:
    """Reference SequenceTagging (text.py:3832): embedding ->
    bidirectional GRU encoder -> emission fc -> CRF loss (training) /
    Viterbi decode (inference)."""

    def __init__(self, vocab_size, num_labels, word_emb_dim=128,
                 grnn_hidden_dim=128, crf_lr=1.0, name=None):
        self.name = name or unique_name.generate("seq_tagging")
        self.vocab_size = int(vocab_size)
        self.num_labels = int(num_labels)
        self.word_emb_dim = int(word_emb_dim)
        self.hidden = int(grnn_hidden_dim)
        self._crf_attr = ParamAttr(name=f"{self.name}.crf_w",
                                   learning_rate=crf_lr)

    def emissions(self, word_ids, length=None):
        emb = layers.embedding(
            word_ids, size=[self.vocab_size, self.word_emb_dim],
            param_attr=ParamAttr(name=f"{self.name}.embed",
                                 initializer=NormalInitializer(0.0, 0.1)))
        fw = BasicGRUCell(hidden_size=self.hidden, name=f"{self.name}.gru_fw")
        bw = BasicGRUCell(hidden_size=self.hidden, name=f"{self.name}.gru_bw")
        hid, _ = layers.birnn(fw, bw, emb, sequence_length=length)
        return layers.fc(
            hid, self.num_labels, num_flatten_dims=2,
            param_attr=ParamAttr(name=f"{self.name}.emit.w"),
            bias_attr=ParamAttr(name=f"{self.name}.emit.b"))

    def __call__(self, word_ids, target=None, length=None):
        emission = self.emissions(word_ids, length=length)
        if target is not None:
            crf = LinearChainCRF(param_attr=self._crf_attr)
            return crf(emission, target, length=length)
        return CRFDecoding(param_attr=self._crf_attr)(emission,
                                                      length=length)
