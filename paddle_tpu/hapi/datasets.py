"""Map-style Dataset classes over the built-in loaders (reference
python/paddle/incubate/hapi/datasets/: MNIST, Cifar, Imdb, UCIHousing,
Flowers, VOC2012...). Usable directly with paddle.io.DataLoader
(multiprocess workers) and hapi Model.fit."""
from __future__ import annotations

import numpy as np

from ..fluid.dataloader import Dataset

__all__ = ["MNIST", "Cifar10", "Imdb", "UCIHousing", "Flowers", "VOC2012",
           "Movielens", "WMT16", "Conll05st"]


class _ReaderDataset(Dataset):
    """Materialize a reader-creator's samples once (the synthetic/cached
    sets are small); index them map-style."""

    def __init__(self, reader):
        self._samples = list(reader())

    def __getitem__(self, i):
        return self._samples[i]

    def __len__(self):
        return len(self._samples)


def _check_mode(mode, allowed):
    if mode not in allowed:
        raise ValueError(f"mode must be one of {allowed}, got {mode!r}")


class _ImageDataset(_ReaderDataset):
    """Shared (image, label) dataset with an optional transform."""

    def __init__(self, reader, transform=None):
        super().__init__(reader)
        self._transform = transform

    def __getitem__(self, i):
        img, lbl = self._samples[i]
        if self._transform is not None:
            img = self._transform(img)
        return img, np.asarray([lbl], np.int64)


class MNIST(_ImageDataset):
    def __init__(self, mode="train", transform=None):
        from ..dataset import mnist

        _check_mode(mode, ("train", "test"))
        super().__init__(mnist.train() if mode == "train" else mnist.test(),
                         transform)


class Cifar10(_ImageDataset):
    def __init__(self, mode="train", transform=None):
        from ..dataset import cifar

        _check_mode(mode, ("train", "test"))
        super().__init__(
            cifar.train10() if mode == "train" else cifar.test10(), transform)


class Imdb(_ReaderDataset):
    def __init__(self, mode="train"):
        from ..dataset import imdb

        _check_mode(mode, ("train", "test"))
        wd = imdb.word_dict()
        super().__init__(imdb.train(wd) if mode == "train" else imdb.test(wd))
        self.word_idx = wd


class UCIHousing(_ReaderDataset):
    def __init__(self, mode="train"):
        from ..dataset import uci_housing

        _check_mode(mode, ("train", "test"))
        super().__init__(
            uci_housing.train() if mode == "train" else uci_housing.test())


class Flowers(_ImageDataset):
    def __init__(self, mode="train", transform=None):
        from ..dataset import flowers

        _check_mode(mode, ("train", "test", "valid"))
        r = {"train": flowers.train, "test": flowers.test,
             "valid": flowers.valid}[mode]
        super().__init__(r(), transform)


class VOC2012(_ReaderDataset):
    def __init__(self, mode="train"):
        from ..dataset import voc2012

        r = {"train": voc2012.train, "test": voc2012.test,
             "val": voc2012.val}[mode]
        super().__init__(r())


class Movielens(_ReaderDataset):
    def __init__(self, mode="train"):
        from ..dataset import movielens

        _check_mode(mode, ("train", "test"))
        super().__init__(
            movielens.train() if mode == "train" else movielens.test())


class WMT16(_ReaderDataset):
    def __init__(self, mode="train", src_dict_size=10000, trg_dict_size=10000):
        from ..dataset import wmt16

        r = {"train": wmt16.train, "test": wmt16.test,
             "val": wmt16.validation}[mode]
        super().__init__(r(src_dict_size, trg_dict_size))


class Conll05st(_ReaderDataset):
    def __init__(self, mode="test"):
        from ..dataset import conll05

        _check_mode(mode, ("train", "test"))
        super().__init__(
            conll05.test() if mode == "test" else conll05.train())
