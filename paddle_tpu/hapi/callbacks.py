"""hapi callbacks (reference python/paddle/incubate/hapi/callbacks.py:
Callback, ProgBarLogger, ModelCheckpoint; EarlyStopping is the one
post-1.8 addition users expect from a Keras-like API)."""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class Callback:
    def set_model(self, model):
        self.model = model

    def on_train_begin(self):
        pass

    def on_train_end(self):
        pass

    def on_epoch_begin(self, epoch):
        pass

    def on_epoch_end(self, epoch, logs: Optional[Dict] = None):
        """Return True to stop training."""
        return False

    def on_batch_begin(self, mode, step):
        pass

    def on_batch_end(self, mode, step, logs: Optional[Dict] = None):
        pass


class CallbackList:
    def __init__(self, callbacks: List[Callback]):
        self.callbacks = list(callbacks)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def on_train_begin(self):
        for c in self.callbacks:
            c.on_train_begin()

    def on_train_end(self):
        for c in self.callbacks:
            c.on_train_end()

    def on_epoch_begin(self, epoch):
        for c in self.callbacks:
            c.on_epoch_begin(epoch)

    def on_epoch_end(self, epoch, logs=None) -> bool:
        stop = False
        for c in self.callbacks:
            stop = bool(c.on_epoch_end(epoch, logs)) or stop
        return stop

    def on_batch_begin(self, mode, step):
        for c in self.callbacks:
            c.on_batch_begin(mode, step)

    def on_batch_end(self, mode, step, logs=None):
        for c in self.callbacks:
            c.on_batch_end(mode, step, logs)


class ProgBarLogger(Callback):
    """Epoch/step logging (reference callbacks.ProgBarLogger)."""

    def __init__(self, log_freq=10, verbose=2):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch):
        self._epoch = epoch
        self._steps = 0

    def on_batch_end(self, mode, step, logs=None):
        self._steps += 1
        if self.verbose > 1 and mode == "train" and step % self.log_freq == 0:
            msg = ", ".join(f"{k}: {v:.6f}" for k, v in (logs or {}).items())
            print(f"epoch {self._epoch} step {step}: {msg}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            msg = ", ".join(
                f"{k}: {v:.6f}" for k, v in (logs or {}).items() if v is not None
            )
            print(f"epoch {epoch}: {msg}")
        return False


class ModelCheckpoint(Callback):
    """Save every `save_freq` epochs (reference callbacks.ModelCheckpoint)
    or — save_freq_unit="step" — every `save_freq` train STEPS, so a
    preemption mid-epoch costs minutes of work, not the epoch.

    keep_last_n switches the save path to the model's CheckpointManager
    (fluid/checkpoint.py): step-numbered atomic checkpoint dirs under
    save_dir with only the newest N retained, loadable with
    Model.fit(resume=...). keep_last_n=None keeps the legacy behavior
    for epoch saves (Model.save to save_dir/epoch_<n>, unbounded).

    async_save: hand serialization + commit to the manager's background
    writer so the step loop only pays the snapshot cost (None = the
    manager's default, i.e. PADDLE_CKPT_ASYNC). on_train_end drains any
    queued/in-flight write, so a finished fit leaves its checkpoints on
    disk either way."""

    def __init__(self, save_freq=1, save_dir="checkpoints",
                 save_freq_unit="epoch", keep_last_n=None,
                 async_save=None):
        if save_freq_unit not in ("epoch", "step"):
            raise ValueError(
                f"save_freq_unit must be 'epoch' or 'step', got "
                f"{save_freq_unit!r}")
        if save_freq_unit == "step" and keep_last_n is None:
            keep_last_n = 3  # unbounded step snapshots would fill disk
        self.save_freq = int(save_freq)
        self.save_dir = save_dir
        self.save_freq_unit = save_freq_unit
        self.keep_last_n = keep_last_n
        self.async_save = async_save
        self._gstep = 0
        self._epoch = 0

    def _manager(self):
        return self.model._checkpoint_manager(
            self.save_dir, keep_last_n=self.keep_last_n or 3)

    def on_epoch_begin(self, epoch):
        self._epoch = epoch

    def on_batch_end(self, mode, step, logs=None):
        if mode != "train":
            return
        self._gstep += 1
        if (self.save_freq_unit == "step"
                and self._gstep % self.save_freq == 0):
            self._manager().save(
                self._gstep,
                extra_state={"epoch": self._epoch,
                             "global_step": self._gstep},
                async_=self.async_save)

    def on_epoch_end(self, epoch, logs=None):
        if self.save_freq_unit == "epoch" and (epoch + 1) % self.save_freq == 0:
            if self.keep_last_n is not None:
                self._manager().save(
                    self._gstep,
                    extra_state={"epoch": epoch + 1,
                                 "global_step": self._gstep},
                    async_=self.async_save)
            else:
                import os

                self.model.save(os.path.join(self.save_dir, f"epoch_{epoch}"))
        return False

    def on_train_end(self):
        if self.keep_last_n is not None and getattr(self, "model", None):
            # a finished fit leaves its checkpoints ON DISK: drain any
            # queued/in-flight async write (and surface its failure)
            self._manager().drain()


class MetricsLogger(Callback):
    """Emit hapi training metrics through the unified telemetry layer
    (paddle_tpu.telemetry) so Model.fit, bench.py and the executor's
    step breakdown share one registry / JSONL code path (ISSUE 4).

    Registry series (always cheap, scrapeable via
    telemetry.to_prometheus()):
      hapi_train_batches_total   counter
      hapi_train_loss            gauge (last batch loss)
      hapi_batch_ms              histogram (on_batch_begin..end wall)
      hapi_epochs_total          counter
    JSONL (only when PADDLE_METRICS_PATH is set): one kind="train_epoch"
    record per epoch with the epoch logs (loss, val_* ...).

    Model.fit appends one automatically when the telemetry sink is
    active and the callback list doesn't already carry one."""

    def __init__(self):
        self._t0 = None

    def on_batch_begin(self, mode, step):
        if mode == "train":
            import time

            self._t0 = time.perf_counter()

    def on_batch_end(self, mode, step, logs=None):
        if mode != "train":
            return
        import time

        from .. import telemetry

        reg = telemetry.get_registry()
        reg.counter("hapi_train_batches_total").inc()
        if self._t0 is not None:
            reg.histogram("hapi_batch_ms",
                          help="fit() train batch wall time").observe(
                (time.perf_counter() - self._t0) * 1e3)
            self._t0 = None
        loss = (logs or {}).get("loss")
        if loss is not None:
            reg.gauge("hapi_train_loss").set(float(loss))

    def on_epoch_end(self, epoch, logs=None):
        from .. import telemetry

        telemetry.get_registry().counter("hapi_epochs_total").inc()
        rec = {"kind": "train_epoch", "epoch": int(epoch)}
        for k, v in (logs or {}).items():
            if v is not None:
                try:
                    rec[k] = float(v)
                except (TypeError, ValueError):
                    pass
        telemetry.emit(rec)
        return False


class EarlyStopping(Callback):
    def __init__(self, monitor="val_loss", patience=3, min_delta=0.0,
                 mode="min"):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = min_delta
        self.sign = 1.0 if mode == "min" else -1.0
        self.best = np.inf
        self.wait = 0

    def on_epoch_end(self, epoch, logs=None):
        val = (logs or {}).get(self.monitor)
        if val is None:
            return False
        score = self.sign * float(val)
        if score < self.best - self.min_delta:
            self.best = score
            self.wait = 0
            return False
        self.wait += 1
        return self.wait > self.patience
