"""hapi callbacks (reference python/paddle/incubate/hapi/callbacks.py:
Callback, ProgBarLogger, ModelCheckpoint; EarlyStopping is the one
post-1.8 addition users expect from a Keras-like API)."""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class Callback:
    def set_model(self, model):
        self.model = model

    def on_train_begin(self):
        pass

    def on_train_end(self):
        pass

    def on_epoch_begin(self, epoch):
        pass

    def on_epoch_end(self, epoch, logs: Optional[Dict] = None):
        """Return True to stop training."""
        return False

    def on_batch_begin(self, mode, step):
        pass

    def on_batch_end(self, mode, step, logs: Optional[Dict] = None):
        pass


class CallbackList:
    def __init__(self, callbacks: List[Callback]):
        self.callbacks = list(callbacks)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def on_train_begin(self):
        for c in self.callbacks:
            c.on_train_begin()

    def on_train_end(self):
        for c in self.callbacks:
            c.on_train_end()

    def on_epoch_begin(self, epoch):
        for c in self.callbacks:
            c.on_epoch_begin(epoch)

    def on_epoch_end(self, epoch, logs=None) -> bool:
        stop = False
        for c in self.callbacks:
            stop = bool(c.on_epoch_end(epoch, logs)) or stop
        return stop

    def on_batch_begin(self, mode, step):
        for c in self.callbacks:
            c.on_batch_begin(mode, step)

    def on_batch_end(self, mode, step, logs=None):
        for c in self.callbacks:
            c.on_batch_end(mode, step, logs)


class ProgBarLogger(Callback):
    """Epoch/step logging (reference callbacks.ProgBarLogger)."""

    def __init__(self, log_freq=10, verbose=2):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch):
        self._epoch = epoch
        self._steps = 0

    def on_batch_end(self, mode, step, logs=None):
        self._steps += 1
        if self.verbose > 1 and mode == "train" and step % self.log_freq == 0:
            msg = ", ".join(f"{k}: {v:.6f}" for k, v in (logs or {}).items())
            print(f"epoch {self._epoch} step {step}: {msg}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            msg = ", ".join(
                f"{k}: {v:.6f}" for k, v in (logs or {}).items() if v is not None
            )
            print(f"epoch {epoch}: {msg}")
        return False


class ModelCheckpoint(Callback):
    """Save persistables every `save_freq` epochs (reference
    callbacks.ModelCheckpoint)."""

    def __init__(self, save_freq=1, save_dir="checkpoints"):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if (epoch + 1) % self.save_freq == 0:
            import os

            self.model.save(os.path.join(self.save_dir, f"epoch_{epoch}"))
        return False


class EarlyStopping(Callback):
    def __init__(self, monitor="val_loss", patience=3, min_delta=0.0,
                 mode="min"):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = min_delta
        self.sign = 1.0 if mode == "min" else -1.0
        self.best = np.inf
        self.wait = 0

    def on_epoch_end(self, epoch, logs=None):
        val = (logs or {}).get(self.monitor)
        if val is None:
            return False
        score = self.sign * float(val)
        if score < self.best - self.min_delta:
            self.best = score
            self.wait = 0
            return False
        self.wait += 1
        return self.wait > self.patience
