"""paddle.io 2.0-preview namespace: datasets + multiprocess DataLoader.

Parity: the reference exposes Dataset/BatchSampler/DataLoader as
`paddle.io` (python/paddle/io/__init__.py re-exporting
fluid/dataloader/ + fluid/reader.py:112).
"""
from ..fluid.dataloader import (  # noqa: F401
    BatchSampler,
    Dataset,
    IterableDataset,
    TensorDataset,
    default_collate_fn,
)
from ..fluid.reader import DataLoader  # noqa: F401

__all__ = [
    "Dataset",
    "IterableDataset",
    "TensorDataset",
    "BatchSampler",
    "DataLoader",
    "default_collate_fn",
]
