// Native async data feed: file -> channel -> batch pipeline.
//
// TPU-era C++ equivalent of the reference's DataFeed machinery
// (/root/reference/paddle/fluid/framework/data_feed.h:108 DataFeed,
//  :293 InMemoryDataFeed, :650 MultiSlotDataFeed and data_set.h Dataset):
// reader threads parse record files into a bounded channel; the trainer
// thread drains whole batches from the channel; an optional shuffle
// buffer (channel-level, like the reference's local_shuffle) and a full
// in-memory mode with global shuffle (data_set.h load_into_memory /
// global_shuffle) are supported. The Python binding is ctypes
// (paddle_tpu/native/__init__.py); records are dense float32 rows of a
// fixed width (the MultiSlot text format collapses to this once slots are
// dense — sparse slots ride the embedding path instead).
//
// Build: g++ -O2 -std=c++17 -shared -fPIC datafeed.cc -o libdatafeed.so -lpthread
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <fstream>
#include <mutex>
#include <random>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Channel {
  // bounded multi-producer single-consumer channel of rows
  std::deque<std::vector<float>> q;
  std::mutex mu;
  std::condition_variable not_full, not_empty;
  size_t capacity = 4096;
  bool closed = false;

  void put(std::vector<float>&& row) {
    std::unique_lock<std::mutex> lk(mu);
    not_full.wait(lk, [&] { return q.size() < capacity || closed; });
    if (closed) return;
    q.push_back(std::move(row));
    not_empty.notify_one();
  }
  // returns false at end-of-stream
  bool get(std::vector<float>* row) {
    std::unique_lock<std::mutex> lk(mu);
    not_empty.wait(lk, [&] { return !q.empty() || closed; });
    if (q.empty()) return false;
    *row = std::move(q.front());
    q.pop_front();
    not_full.notify_one();
    return true;
  }
  void close() {
    std::lock_guard<std::mutex> lk(mu);
    closed = true;
    not_full.notify_all();
    not_empty.notify_all();
  }
  void reset(size_t cap) {
    std::lock_guard<std::mutex> lk(mu);
    q.clear();
    closed = false;
    capacity = cap;
  }
};

struct DataFeed {
  int ncols = 0;
  int batch_size = 1;
  size_t channel_capacity = 4096;
  int shuffle_buffer = 0;  // channel-level shuffle window (0 = off)
  uint64_t seed = 0;
  std::vector<std::string> files;
  std::vector<std::thread> readers;
  Channel channel;
  std::atomic<int> active_readers{0};
  // in-memory mode
  bool in_memory = false;
  std::vector<std::vector<float>> memory;
  size_t cursor = 0;
  // shuffle window state (consumer side)
  std::vector<std::vector<float>> window;
  std::mt19937_64 rng;
  std::mutex start_mu;
  bool started = false;

  void parse_file(const std::string& path) {
    std::ifstream in(path);
    if (!in) {
      std::fprintf(stderr, "datafeed: cannot open %s\n", path.c_str());
      return;
    }
    std::string line;
    while (std::getline(in, line)) {
      if (line.empty()) continue;
      std::vector<float> row;
      row.reserve(ncols);
      const char* p = line.c_str();
      char* end = nullptr;
      for (int i = 0; i < ncols; ++i) {
        float v = std::strtof(p, &end);
        if (end == p) break;
        row.push_back(v);
        p = end;
      }
      if ((int)row.size() == ncols) channel.put(std::move(row));
    }
  }

  void start_readers(int nthreads) {
    std::lock_guard<std::mutex> lk(start_mu);
    if (started) return;
    started = true;
    rng.seed(seed);
    channel.reset(channel_capacity);
    if (in_memory) {
      // stream straight from the shuffled memory vector
      return;
    }
    if (nthreads < 1) nthreads = 1;
    if (nthreads > (int)files.size() && !files.empty())
      nthreads = (int)files.size();
    active_readers = nthreads;
    for (int t = 0; t < nthreads; ++t) {
      readers.emplace_back([this, t, nthreads] {
        for (size_t i = t; i < files.size(); i += nthreads) parse_file(files[i]);
        if (--active_readers == 0) channel.close();
      });
    }
  }

  // consumer-side: next row through the shuffle window
  bool next_row(std::vector<float>* row) {
    if (in_memory) {
      if (cursor >= memory.size()) return false;
      *row = memory[cursor++];
      return true;
    }
    if (shuffle_buffer <= 1) return channel.get(row);
    // keep the window topped up, emit a random element
    std::vector<float> r;
    while ((int)window.size() < shuffle_buffer && channel.get(&r))
      window.push_back(std::move(r));
    if (window.empty()) return false;
    size_t j = rng() % window.size();
    *row = std::move(window[j]);
    window[j] = std::move(window.back());
    window.pop_back();
    return true;
  }

  int next_batch(float* out, int max_rows) {
    int n = 0;
    std::vector<float> row;
    while (n < max_rows && next_row(&row)) {
      std::memcpy(out + (size_t)n * ncols, row.data(), sizeof(float) * ncols);
      ++n;
    }
    return n;
  }

  void load_into_memory(int nthreads) {
    in_memory = true;
    start_readers_for_load(nthreads);
    std::vector<float> row;
    while (channel.get(&row)) memory.push_back(std::move(row));
    for (auto& th : readers) th.join();
    readers.clear();
    cursor = 0;
  }

  void start_readers_for_load(int nthreads) {
    rng.seed(seed);
    channel.reset(channel_capacity);
    if (nthreads > (int)files.size() && !files.empty())
      nthreads = (int)files.size();
    if (nthreads < 1) nthreads = 1;
    active_readers = nthreads;
    for (int t = 0; t < nthreads; ++t) {
      readers.emplace_back([this, t, nthreads] {
        for (size_t i = t; i < files.size(); i += nthreads) parse_file(files[i]);
        if (--active_readers == 0) channel.close();
      });
    }
  }

  void shuffle_memory() {
    std::mt19937_64 g(seed ^ 0x9E3779B97F4A7C15ULL);
    for (size_t i = memory.size(); i > 1; --i) {
      size_t j = g() % i;
      std::swap(memory[i - 1], memory[j]);
    }
    cursor = 0;
  }

  ~DataFeed() {
    channel.close();
    for (auto& th : readers)
      if (th.joinable()) th.join();
  }
};

}  // namespace

extern "C" {

void* df_create(int ncols, int batch_size, int channel_capacity,
                int shuffle_buffer, uint64_t seed) {
  auto* f = new DataFeed();
  f->ncols = ncols;
  f->batch_size = batch_size;
  if (channel_capacity > 0) f->channel_capacity = (size_t)channel_capacity;
  f->shuffle_buffer = shuffle_buffer;
  f->seed = seed;
  return f;
}

void df_add_file(void* h, const char* path) {
  static_cast<DataFeed*>(h)->files.emplace_back(path);
}

void df_start(void* h, int nthreads) {
  static_cast<DataFeed*>(h)->start_readers(nthreads);
}

// fills out[max_rows * ncols]; returns rows produced (0 => end of epoch)
int df_next_batch(void* h, float* out, int max_rows) {
  return static_cast<DataFeed*>(h)->next_batch(out, max_rows);
}

void df_load_into_memory(void* h, int nthreads) {
  static_cast<DataFeed*>(h)->load_into_memory(nthreads);
}

void df_shuffle(void* h) { static_cast<DataFeed*>(h)->shuffle_memory(); }

long df_memory_size(void* h) {
  return (long)static_cast<DataFeed*>(h)->memory.size();
}

void df_rewind(void* h) { static_cast<DataFeed*>(h)->cursor = 0; }

void df_destroy(void* h) { delete static_cast<DataFeed*>(h); }

}  // extern "C"
