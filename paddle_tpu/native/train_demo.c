/* Pure-C training driver (reference fluid/train/demo/demo_trainer.cc):
 * loads a saved TRAIN program and runs SGD steps without any Python
 * script — the C API shim embeds the interpreter itself.
 *
 *   gcc train_demo.c -o train_demo -ldl
 *   ./train_demo <libpaddle_tpu_capi.so> <train_model_dir>
 *
 * Trains y = x*w + b on synthetic data and asserts the loss decreases.
 */
#include <dlfcn.h>
#include <stdio.h>
#include <stdlib.h>

typedef void* (*create_fn)(const char*, const char**);
typedef void (*destroy_fn)(void*);
typedef int (*set_f_fn)(void*, const char*, const float*, const long long*, int, const char**);
typedef int (*step_fn)(void*, double*, const char**);

int main(int argc, char** argv) {
  if (argc < 3) {
    fprintf(stderr, "usage: %s <libcapi.so> <train_model_dir>\n", argv[0]);
    return 2;
  }
  void* lib = dlopen(argv[1], RTLD_NOW | RTLD_GLOBAL);
  if (!lib) {
    fprintf(stderr, "dlopen: %s\n", dlerror());
    return 2;
  }
  create_fn create = (create_fn)dlsym(lib, "PD_TrainerCreate");
  destroy_fn destroy = (destroy_fn)dlsym(lib, "PD_TrainerDestroy");
  set_f_fn set_f = (set_f_fn)dlsym(lib, "PD_TrainerSetInputFloat");
  step_fn step = (step_fn)dlsym(lib, "PD_TrainerRunStep");
  if (!create || !destroy || !set_f || !step) {
    fprintf(stderr, "missing PD_Trainer symbols\n");
    return 2;
  }

  const char* err = NULL;
  void* tr = create(argv[2], &err);
  if (!tr) {
    fprintf(stderr, "create failed: %s\n", err ? err : "?");
    return 1;
  }

  /* synthetic linear data: y = 2*x0 - 3*x1 + 0.5 */
  float x[16 * 2], y[16 * 1];
  unsigned seed = 7;
  for (int i = 0; i < 16; ++i) {
    float a = (float)((seed = seed * 1103515245u + 12345u) >> 16 & 1023) / 512.0f - 1.0f;
    float b = (float)((seed = seed * 1103515245u + 12345u) >> 16 & 1023) / 512.0f - 1.0f;
    x[2 * i] = a;
    x[2 * i + 1] = b;
    y[i] = 2.0f * a - 3.0f * b + 0.5f;
  }
  long long xs[2] = {16, 2}, ys[2] = {16, 1};

  double first = 0, loss = 0;
  for (int it = 0; it < 60; ++it) {
    if (set_f(tr, "x", x, xs, 2, &err) || set_f(tr, "y", y, ys, 2, &err)) {
      fprintf(stderr, "set_input failed: %s\n", err ? err : "?");
      return 1;
    }
    if (step(tr, &loss, &err)) {
      fprintf(stderr, "run_step failed: %s\n", err ? err : "?");
      return 1;
    }
    if (it == 0) first = loss;
  }
  printf("C trainer: loss %.4f -> %.4f over 60 steps\n", first, loss);
  destroy(tr);
  if (!(loss < first * 0.2)) {
    fprintf(stderr, "loss did not decrease enough\n");
    return 1;
  }
  printf("TRAIN DEMO OK\n");
  return 0;
}
