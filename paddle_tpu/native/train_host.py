"""Python host object behind the C training API (capi.cc PD_Trainer*).

Reference story: fluid/train/demo drives training from C++ without a
Python script. Here the C side embeds CPython and calls this class: it
loads a fluid.io.save_train_model directory, accepts named feeds, runs
whole-block-compiled train steps, and reports the loss.
"""
from __future__ import annotations

import numpy as np


class CTrainer:
    def __init__(self, model_dir: str):
        import paddle_tpu.fluid as fluid

        self._fluid = fluid
        self._exe = fluid.Executor()
        self._scope = fluid.executor.Scope()
        with fluid.scope_guard(self._scope):
            (self._main, self._startup, self._feed_names,
             self._loss_name) = fluid.io.load_train_model(self._exe, model_dir)
        self._feed = {}

    def get_feed_names(self):
        return list(self._feed_names)

    def get_loss_name(self):
        return self._loss_name

    def set_input(self, name, flat_values, shape, dtype="float32"):
        # copy=True: the C caller may hand a memoryview aliasing its own
        # buffer, which it is free to reuse right after this call returns
        self._feed[name] = np.array(
            flat_values, dtype=dtype, copy=True).reshape(
                [int(s) for s in shape])

    def run_step(self) -> float:
        missing = [n for n in self._feed_names if n not in self._feed]
        if missing:
            raise ValueError(f"CTrainer: missing feeds {missing}")
        with self._fluid.scope_guard(self._scope):
            (loss,) = self._exe.run(self._main, feed=self._feed,
                                    fetch_list=[self._loss_name])
        return float(np.asarray(loss).reshape(()))

    def save(self, dirname):
        with self._fluid.scope_guard(self._scope):
            self._fluid.io.save_train_model(
                self._exe, dirname, self._feed_names, self._loss_name,
                main_program=self._main, startup_program=self._startup)
