"""Native (C++) runtime components, bound via ctypes.

The reference keeps its data pipeline in C++ (framework/data_feed.cc,
data_set.cc — reader threads, channels, global shuffle) because Python
readers can't keep accelerators fed. Same decision here: datafeed.cc is
compiled on first use with the system g++ into libdatafeed.so next to
this file (no pybind11 in the image; ctypes keeps the binding
dependency-free). Every native class has a pure-Python fallback so the
framework works without a toolchain.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "datafeed.cc")
_lock = threading.Lock()


def _hashed_so_path(src_path: str, stem: str) -> str:
    """Build artifact keyed by a source hash: a stale or foreign-arch
    binary can never be dlopen'd (the .so is not version-controlled)."""
    import hashlib

    with open(src_path, "rb") as f:
        h = hashlib.sha256(f.read()).hexdigest()[:12]
    d = os.path.join(_HERE, "build")
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, f"{stem}-{h}.so")


def _build_so(src_path: str, stem: str, extra_flags=()) -> str:
    """Compile to a temp path + atomic rename: a concurrent process can
    never dlopen a partially written binary."""
    so = _hashed_so_path(src_path, stem)
    if not os.path.exists(so):
        tmp = f"{so}.tmp.{os.getpid()}"
        # -I flags may precede the source; -l libraries must FOLLOW it
        # (with --as-needed defaults, libs listed first are dropped)
        incs = [f for f in extra_flags if not f.startswith("-l")]
        libs = [f for f in extra_flags if f.startswith("-l")]
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
             *incs, src_path, "-o", tmp, *libs],
            check=True, capture_output=True, text=True,
        )
        os.replace(tmp, so)
    return so


def _so_path() -> str:
    return _hashed_so_path(_SRC, "libdatafeed")
_lib = None
_build_err: str | None = None


def _load():
    global _lib, _build_err
    with _lock:
        if _lib is not None or _build_err is not None:
            return _lib
        try:
            so = _build_so(_SRC, "libdatafeed", ("-lpthread",))
            lib = ctypes.CDLL(so)
            lib.df_create.restype = ctypes.c_void_p
            lib.df_create.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_int,
                                      ctypes.c_int, ctypes.c_uint64]
            lib.df_add_file.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            lib.df_start.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.df_next_batch.restype = ctypes.c_int
            lib.df_next_batch.argtypes = [ctypes.c_void_p,
                                          ctypes.POINTER(ctypes.c_float),
                                          ctypes.c_int]
            lib.df_load_into_memory.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.df_shuffle.argtypes = [ctypes.c_void_p]
            lib.df_memory_size.restype = ctypes.c_long
            lib.df_memory_size.argtypes = [ctypes.c_void_p]
            lib.df_rewind.argtypes = [ctypes.c_void_p]
            lib.df_destroy.argtypes = [ctypes.c_void_p]
            _lib = lib
        except Exception as e:  # noqa: BLE001 — record and fall back
            _build_err = str(e)
        return _lib


def native_available() -> bool:
    return _load() is not None


class NativeDataFeed:
    """Threaded file->channel->batch reader over dense float32 rows.

    Rows are whitespace-separated floats, `ncols` per line (the dense
    MultiSlot layout). shuffle_buffer > 1 enables channel-level local
    shuffle; load_into_memory()+shuffle() is the global-shuffle mode."""

    def __init__(self, ncols: int, batch_size: int, channel_capacity: int = 4096,
                 shuffle_buffer: int = 0, seed: int = 0, num_threads: int = 4):
        self._lib = _load()
        if self._lib is None:
            raise RuntimeError(f"native datafeed unavailable: {_build_err}")
        self.ncols = ncols
        self.batch_size = batch_size
        self._h = self._lib.df_create(
            ncols, batch_size, channel_capacity, shuffle_buffer, seed
        )
        self._started = False
        self._loaded = False
        self.num_threads = max(1, int(num_threads))

    def set_filelist(self, files):
        for f in files:
            self._lib.df_add_file(self._h, os.fsencode(f))

    def load_into_memory(self):
        self._lib.df_load_into_memory(self._h, self.num_threads)
        self._loaded = True

    def shuffle(self):
        self._lib.df_shuffle(self._h)

    def memory_size(self) -> int:
        return int(self._lib.df_memory_size(self._h))

    def rewind(self):
        self._lib.df_rewind(self._h)

    def __iter__(self):
        if not self._loaded and not self._started:
            self._lib.df_start(self._h, self.num_threads)
            self._started = True
        buf = np.empty((self.batch_size, self.ncols), np.float32)
        while True:
            n = self._lib.df_next_batch(
                self._h, buf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                self.batch_size,
            )
            if n == 0:
                return
            yield buf[:n].copy()

    def __del__(self):
        lib = getattr(self, "_lib", None)
        h = getattr(self, "_h", None)
        if lib is not None and h:
            lib.df_destroy(h)


class PythonDataFeed:
    """Pure-Python fallback with the same surface (no reader threads)."""

    def __init__(self, ncols, batch_size, channel_capacity=4096,
                 shuffle_buffer=0, seed=0, num_threads=1):
        self.ncols = ncols
        self.batch_size = batch_size
        self.num_threads = num_threads  # accepted for surface parity
        self.shuffle_buffer = shuffle_buffer
        self.seed = seed
        self.files = []
        self._memory = None

    def set_filelist(self, files):
        self.files = list(files)

    def _rows(self):
        rng = np.random.RandomState(self.seed)
        window = []
        for path in self.files:
            with open(path) as f:
                for line in f:
                    parts = line.split()
                    if len(parts) < self.ncols:
                        continue
                    row = np.asarray(parts[: self.ncols], np.float32)
                    if self.shuffle_buffer > 1:
                        window.append(row)
                        if len(window) >= self.shuffle_buffer:
                            j = rng.randint(len(window))
                            window[j], window[-1] = window[-1], window[j]
                            yield window.pop()
                    else:
                        yield row
        while window:
            j = rng.randint(len(window))
            window[j], window[-1] = window[-1], window[j]
            yield window.pop()

    def load_into_memory(self):
        self._memory = list(self._rows())

    def shuffle(self):
        rng = np.random.RandomState(self.seed ^ 0x9E3779B9)
        rng.shuffle(self._memory)

    def memory_size(self):
        return len(self._memory or [])

    def rewind(self):
        pass

    def __iter__(self):
        rows = self._memory if self._memory is not None else self._rows()
        batch = []
        for row in rows:
            batch.append(row)
            if len(batch) == self.batch_size:
                yield np.stack(batch)
                batch = []
        if batch:
            yield np.stack(batch)


def make_datafeed(ncols, batch_size, **kw):
    """Native feed when the toolchain is available, Python fallback else."""
    if native_available():
        return NativeDataFeed(ncols, batch_size, **kw)
    return PythonDataFeed(ncols, batch_size, **kw)


# ---------------------------------------------------------------------------
# C inference API (capi.cc): built like the datafeed, loaded on demand
# ---------------------------------------------------------------------------

_CAPI_SRC = os.path.join(_HERE, "capi.cc")
_capi_lib = None
_capi_err: str | None = None


def load_capi():
    """Build (if needed) and dlopen the C inference API with ctypes
    signatures attached. In-process use shares the running interpreter;
    for standalone C/Go clients the shim links libpython itself and
    self-initializes the embedded interpreter on first use."""
    global _capi_lib, _capi_err
    with _lock:
        if _capi_lib is not None or _capi_err is not None:
            return _capi_lib
        try:
            import sysconfig

            inc = sysconfig.get_paths()["include"]
            # link libpython so STANDALONE (non-Python) consumers can
            # dlopen the shim; a static-Python build (no shared
            # libpython) falls back to the symbol-resolving in-process
            # form, which needs no linking
            libdir = sysconfig.get_config_var("LIBDIR") or ""
            pyver = sysconfig.get_config_var("LDVERSION") or ""
            libs = []
            if libdir:
                libs.append(f"-L{libdir}")
            if pyver:
                libs.append(f"-lpython{pyver}")
            try:
                so = _build_so(_CAPI_SRC, "libpaddle_tpu_capi",
                               (f"-I{inc}", *libs))
            except subprocess.CalledProcessError:
                so = _build_so(_CAPI_SRC, "libpaddle_tpu_capi_inproc",
                               (f"-I{inc}",))
            lib = ctypes.CDLL(so)
            lib.PD_PredictorCreate.restype = ctypes.c_void_p
            lib.PD_PredictorCreate.argtypes = [
                ctypes.c_char_p, ctypes.POINTER(ctypes.c_char_p)]
            lib.PD_PredictorDestroy.argtypes = [ctypes.c_void_p]
            lib.PD_GetInputNum.argtypes = [ctypes.c_void_p]
            lib.PD_GetOutputNum.argtypes = [ctypes.c_void_p]
            for f in (lib.PD_GetInputName, lib.PD_GetOutputName):
                f.argtypes = [ctypes.c_void_p, ctypes.c_int,
                              ctypes.c_char_p, ctypes.c_int]
            lib.PD_SetInputFloat.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_longlong), ctypes.c_int,
                ctypes.POINTER(ctypes.c_char_p)]
            lib.PD_PredictorRun.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p)]
            lib.PD_GetOutputFloat.restype = ctypes.c_longlong
            lib.PD_GetOutputFloat.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_float), ctypes.c_longlong,
                ctypes.POINTER(ctypes.c_longlong), ctypes.c_int,
                ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_char_p)]
            lib.PD_RunOnce.restype = ctypes.c_longlong
            lib.PD_RunOnce.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_int), ctypes.c_int,
                ctypes.c_char_p, ctypes.POINTER(ctypes.c_float),
                ctypes.c_longlong, ctypes.POINTER(ctypes.c_char_p)]
            _capi_lib = lib
        except Exception as e:  # noqa: BLE001 — record and report
            _capi_err = str(e)
        return _capi_lib


def capi_error() -> str | None:
    return _capi_err
