/* Minimal C client of the paddle_tpu inference C API (reference
 * inference/capi/ consumer pattern; the Go/R clients in go/paddle wrap
 * the same surface).
 *
 * Build:  gcc capi_example.c -o demo -ldl
 * Run:    PYTHONPATH=/path/to/repo ./demo libpaddle_tpu_capi.so model_dir
 *
 * The shim links libpython and self-initializes the embedded
 * interpreter on the first PD_PredictorCreate — the client needs no
 * Python headers or libraries at all.
 */
#include <dlfcn.h>
#include <stdio.h>
#include <stdlib.h>

typedef void* (*create_fn)(const char*, const char**);
typedef int (*run_fn)(void*, const char**);
typedef int (*set_fn)(void*, const char*, const float*, const long long*,
                      int, const char**);
typedef long long (*get_fn)(void*, const char*, float*, long long,
                            long long*, int, int*, const char**);
typedef int (*name_fn)(void*, int, char*, int);
typedef void (*destroy_fn)(void*);

int main(int argc, char** argv) {
  if (argc < 3) {
    fprintf(stderr, "usage: %s <libpaddle_tpu_capi.so> <model_dir>\n",
            argv[0]);
    return 2;
  }
  void* lib = dlopen(argv[1], RTLD_NOW | RTLD_GLOBAL);
  if (!lib) { fprintf(stderr, "dlopen: %s\n", dlerror()); return 1; }
  create_fn create = (create_fn)dlsym(lib, "PD_PredictorCreate");
  set_fn set_input = (set_fn)dlsym(lib, "PD_SetInputFloat");
  run_fn run = (run_fn)dlsym(lib, "PD_PredictorRun");
  get_fn get_out = (get_fn)dlsym(lib, "PD_GetOutputFloat");
  name_fn in_name = (name_fn)dlsym(lib, "PD_GetInputName");
  name_fn out_name = (name_fn)dlsym(lib, "PD_GetOutputName");
  destroy_fn destroy = (destroy_fn)dlsym(lib, "PD_PredictorDestroy");

  const char* err = NULL;
  void* pred = create(argv[2], &err);
  if (!pred) { fprintf(stderr, "create: %s\n", err); return 1; }

  char iname[256], oname[256];
  in_name(pred, 0, iname, sizeof iname);
  out_name(pred, 0, oname, sizeof oname);

  float input[4 * 8];
  for (int i = 0; i < 4 * 8; ++i) input[i] = 1.0f;
  long long shape[2] = {4, 8};
  if (set_input(pred, iname, input, shape, 2, &err) != 0 ||
      run(pred, &err) != 0) {
    fprintf(stderr, "run: %s\n", err);
    return 1;
  }
  long long oshape[4];
  int ndim = 0;
  /* size-query mode first (buf=NULL), then fetch */
  long long total = get_out(pred, oname, NULL, 0, oshape, 4, &ndim, &err);
  if (total <= 0) { fprintf(stderr, "size query: %s\n", err); return 1; }
  float* buf = (float*)malloc(sizeof(float) * (size_t)total);
  if (!buf) { fprintf(stderr, "oom\n"); return 1; }
  if (get_out(pred, oname, buf, total, oshape, 4, &ndim, &err) != total) {
    fprintf(stderr, "fetch: %s\n", err);
    return 1;
  }
  printf("output %s: %lld elems, first=%f\n", oname, total, buf[0]);
  free(buf);
  destroy(pred);
  return 0;
}
