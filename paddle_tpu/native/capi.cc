// C inference API (reference paddle/fluid/inference/capi/: PD_* surface).
//
// TPU-native twist: the reference's C API wraps its C++ predictor core;
// here the predictor core IS the Python inference module (whose heavy
// lifting is XLA), so this shim embeds CPython and drives
// paddle_tpu.inference through the C API. Intended consumers are the
// same as the reference's: C/Go/R clients that cannot link Python
// directly but can dlopen one .so.
//
// Thread-safety: every entry point takes the GIL (PyGILState_Ensure),
// so calls may come from any thread.
#include <Python.h>

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace {

struct Predictor {
  PyObject* obj;  // paddle_tpu.inference.Predictor
};

PyObject* import_attr(const char* mod, const char* attr) {
  PyObject* m = PyImport_ImportModule(mod);
  if (!m) return nullptr;
  PyObject* a = PyObject_GetAttrString(m, attr);
  Py_DECREF(m);
  return a;
}

void set_err(const char** err, const char* msg) {
  if (err) *err = strdup(msg);
}

void capture_py_err(const char** err) {
  if (!PyErr_Occurred()) {
    set_err(err, "unknown error");
    return;
  }
  PyObject *type, *value, *tb;
  PyErr_Fetch(&type, &value, &tb);
  PyObject* s = value ? PyObject_Str(value) : nullptr;
  set_err(err, s ? PyUnicode_AsUTF8(s) : "unknown error");
  Py_XDECREF(s);
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

}  // namespace

extern "C" {

// Create a predictor from a saved inference model directory.
// Returns nullptr on failure (err, if non-null, receives a malloc'd
// message the caller frees).
namespace {
void ensure_interpreter() {
  // standalone C/Go consumer: bring up the embedded interpreter once
  // (PYTHONPATH must reach paddle_tpu); a Python host process skips
  // this. call_once guards concurrent PD_PredictorCreate callers.
  static std::once_flag once;
  std::call_once(once, [] {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      PyEval_SaveThread();  // release the GIL for PyGILState_Ensure
    }
  });
}
}  // namespace

void* PD_PredictorCreate(const char* model_dir, const char** err) {
  if (err) *err = nullptr;
  ensure_interpreter();
  PyGILState_STATE g = PyGILState_Ensure();
  void* out = nullptr;
  PyObject* cfg_cls = import_attr("paddle_tpu.inference", "Config");
  PyObject* create = import_attr("paddle_tpu.inference", "create_predictor");
  if (cfg_cls && create) {
    PyObject* cfg = PyObject_CallFunction(cfg_cls, "s", model_dir);
    if (cfg) {
      PyObject* pred = PyObject_CallFunctionObjArgs(create, cfg, nullptr);
      if (pred) {
        out = new Predictor{pred};
      }
      Py_DECREF(cfg);
    }
  }
  if (!out) capture_py_err(err);
  Py_XDECREF(cfg_cls);
  Py_XDECREF(create);
  PyGILState_Release(g);
  return out;
}

void PD_PredictorDestroy(void* h) {
  if (!h) return;
  PyGILState_STATE g = PyGILState_Ensure();
  Py_DECREF(static_cast<Predictor*>(h)->obj);
  delete static_cast<Predictor*>(h);
  PyGILState_Release(g);
}

static int name_list_size(void* h, const char* method) {
  PyGILState_STATE g = PyGILState_Ensure();
  int n = -1;
  PyObject* names =
      PyObject_CallMethod(static_cast<Predictor*>(h)->obj, method, nullptr);
  if (names) {
    n = static_cast<int>(PyList_Size(names));
    Py_DECREF(names);
  } else {
    PyErr_Clear();
  }
  PyGILState_Release(g);
  return n;
}

static int name_at(void* h, const char* method, int i, char* buf, int buf_len) {
  PyGILState_STATE g = PyGILState_Ensure();
  int ok = -1;
  PyObject* names =
      PyObject_CallMethod(static_cast<Predictor*>(h)->obj, method, nullptr);
  if (names && i >= 0 && i < PyList_Size(names)) {
    const char* s = PyUnicode_AsUTF8(PyList_GetItem(names, i));
    if (s) {
      std::snprintf(buf, buf_len, "%s", s);
      ok = 0;
    }
  }
  if (!names) PyErr_Clear();
  Py_XDECREF(names);
  PyGILState_Release(g);
  return ok;
}

int PD_GetInputNum(void* h) { return name_list_size(h, "get_input_names"); }
int PD_GetOutputNum(void* h) { return name_list_size(h, "get_output_names"); }
int PD_GetInputName(void* h, int i, char* buf, int buf_len) {
  return name_at(h, "get_input_names", i, buf, buf_len);
}
int PD_GetOutputName(void* h, int i, char* buf, int buf_len) {
  return name_at(h, "get_output_names", i, buf, buf_len);
}

// Set a float32 input by name. shape is int64[ndim].
int PD_SetInputFloat(void* h, const char* name, const float* data,
                     const long long* shape, int ndim, const char** err) {
  if (err) *err = nullptr;
  PyGILState_STATE g = PyGILState_Ensure();
  int rc = -1;
  // build a numpy array via the buffer-less path: list-of-shape + frombuffer
  PyObject* np = PyImport_ImportModule("numpy");
  if (np) {
    long long total = 1;
    for (int i = 0; i < ndim; ++i) total *= shape[i];
    PyObject* mem = PyMemoryView_FromMemory(
        reinterpret_cast<char*>(const_cast<float*>(data)),
        total * sizeof(float), PyBUF_READ);
    PyObject* flat =
        mem ? PyObject_CallMethod(np, "frombuffer", "Os", mem, "float32")
            : nullptr;
    PyObject* shp = PyTuple_New(ndim);
    for (int i = 0; i < ndim; ++i)
      PyTuple_SetItem(shp, i, PyLong_FromLongLong(shape[i]));
    PyObject* view =
        flat ? PyObject_CallMethod(flat, "reshape", "O", shp) : nullptr;
    // force a real copy: the view aliases the caller's buffer, which the
    // caller may free or reuse right after this call returns
    PyObject* arr =
        view ? PyObject_CallMethod(np, "array", "O", view) : nullptr;
    Py_XDECREF(view);
    if (arr) {
      PyObject* handle = PyObject_CallMethod(
          static_cast<Predictor*>(h)->obj, "get_input_handle", "s", name);
      if (handle) {
        PyObject* r =
            PyObject_CallMethod(handle, "copy_from_cpu", "O", arr);
        if (r) {
          rc = 0;
          Py_DECREF(r);
        }
        Py_DECREF(handle);
      }
    }
    Py_XDECREF(arr);
    Py_XDECREF(shp);
    Py_XDECREF(flat);
    Py_XDECREF(mem);
    Py_DECREF(np);
  }
  if (rc != 0) capture_py_err(err);
  PyGILState_Release(g);
  return rc;
}

int PD_PredictorRun(void* h, const char** err) {
  if (err) *err = nullptr;
  PyGILState_STATE g = PyGILState_Ensure();
  int rc = -1;
  PyObject* r =
      PyObject_CallMethod(static_cast<Predictor*>(h)->obj, "run", nullptr);
  if (r) {
    rc = 0;
    Py_DECREF(r);
  } else {
    capture_py_err(err);
  }
  PyGILState_Release(g);
  return rc;
}

// Copy a float32 output into buf (capacity buf_len floats). Returns the
// number of elements, fills shape[0..*ndim) (capacity max_ndim).
long long PD_GetOutputFloat(void* h, const char* name, float* buf,
                            long long buf_len, long long* shape, int max_ndim,
                            int* ndim, const char** err) {
  if (err) *err = nullptr;
  PyGILState_STATE g = PyGILState_Ensure();
  long long n = -1;
  PyObject* handle = PyObject_CallMethod(static_cast<Predictor*>(h)->obj,
                                         "get_output_handle", "s", name);
  PyObject* arr =
      handle ? PyObject_CallMethod(handle, "copy_to_cpu", nullptr) : nullptr;
  PyObject* np = PyImport_ImportModule("numpy");
  if (arr && np) {
    PyObject* c = PyObject_CallMethod(np, "ascontiguousarray", "Os", arr,
                                      "float32");
    if (c) {
      PyObject* shp = PyObject_GetAttrString(c, "shape");
      int nd = static_cast<int>(PyTuple_Size(shp));
      long long total = 1;
      for (int i = 0; i < nd; ++i) {
        long long d = PyLong_AsLongLong(PyTuple_GetItem(shp, i));
        if (i < max_ndim) shape[i] = d;
        total *= d;
      }
      if (ndim) *ndim = nd;
      if (buf == nullptr) {
        // size-query mode: fill shape/ndim, report the element count
        n = total;
      } else if (total > buf_len) {
        set_err(err, "output buffer too small; call with buf=NULL to "
                     "query the required element count");
        PyErr_Clear();
      } else {
        PyObject* tob = PyObject_CallMethod(c, "tobytes", nullptr);
        if (tob) {
          std::memcpy(buf, PyBytes_AsString(tob),
                      total * sizeof(float));
          n = total;
          Py_DECREF(tob);
        }
      }
      Py_DECREF(shp);
      Py_DECREF(c);
    }
  }
  if (n < 0 && (err == nullptr || *err == nullptr)) capture_py_err(err);
  Py_XDECREF(np);
  Py_XDECREF(arr);
  Py_XDECREF(handle);
  PyGILState_Release(g);
  return n;
}

// ---------------------------------------------------------------------------
// training API (reference fluid/train/demo: drive training without a
// Python script) — PD_Trainer* over native/train_host.py CTrainer
// ---------------------------------------------------------------------------

void* PD_TrainerCreate(const char* model_dir, const char** err) {
  if (err) *err = nullptr;
  ensure_interpreter();
  PyGILState_STATE g = PyGILState_Ensure();
  void* out = nullptr;
  PyObject* cls = import_attr("paddle_tpu.native.train_host", "CTrainer");
  if (cls) {
    PyObject* tr = PyObject_CallFunction(cls, "s", model_dir);
    if (tr) out = new Predictor{tr};
    Py_DECREF(cls);
  }
  if (!out) capture_py_err(err);
  PyGILState_Release(g);
  return out;
}

void PD_TrainerDestroy(void* h) { PD_PredictorDestroy(h); }

namespace {
// shared zero-copy feed path for the trainer: memoryview -> np.frombuffer
// -> reshape -> copy (same pattern as PD_SetInputFloat above)
int trainer_set_input(void* h, const char* name, const void* data,
                      size_t elem_size, const char* np_dtype,
                      const long long* shape, int ndim, const char** err) {
  if (err) *err = nullptr;
  PyGILState_STATE g = PyGILState_Ensure();
  int rc = -1;
  PyObject* np = PyImport_ImportModule("numpy");
  if (np) {
    long long total = 1;
    for (int i = 0; i < ndim; ++i) total *= shape[i];
    PyObject* mem = PyMemoryView_FromMemory(
        reinterpret_cast<char*>(const_cast<void*>(data)),
        total * elem_size, PyBUF_READ);
    PyObject* flat =
        mem ? PyObject_CallMethod(np, "frombuffer", "Os", mem, np_dtype)
            : nullptr;
    PyObject* shp = PyList_New(ndim);
    for (int i = 0; i < ndim; ++i)
      PyList_SetItem(shp, i, PyLong_FromLongLong(shape[i]));
    if (flat) {
      PyObject* r = PyObject_CallMethod(
          static_cast<Predictor*>(h)->obj, "set_input", "sOOs", name, flat,
          shp, np_dtype);
      if (r) {
        rc = 0;
        Py_DECREF(r);
      }
    }
    Py_XDECREF(shp);
    Py_XDECREF(flat);
    Py_XDECREF(mem);
    Py_DECREF(np);
  }
  if (rc != 0) capture_py_err(err);
  PyGILState_Release(g);
  return rc;
}
}  // namespace

int PD_TrainerSetInputFloat(void* h, const char* name, const float* data,
                            const long long* shape, int ndim,
                            const char** err) {
  return trainer_set_input(h, name, data, sizeof(float), "float32", shape,
                           ndim, err);
}

int PD_TrainerSetInputInt(void* h, const char* name, const long long* data,
                          const long long* shape, int ndim,
                          const char** err) {
  return trainer_set_input(h, name, data, sizeof(long long), "int64", shape,
                           ndim, err);
}

// Runs one train step; returns 0 and writes the loss, or -1 on error.
int PD_TrainerRunStep(void* h, double* loss_out, const char** err) {
  if (err) *err = nullptr;
  PyGILState_STATE g = PyGILState_Ensure();
  int ok = -1;
  PyObject* r = PyObject_CallMethod(static_cast<Predictor*>(h)->obj,
                                    "run_step", nullptr);
  if (r) {
    if (loss_out) *loss_out = PyFloat_AsDouble(r);
    ok = PyErr_Occurred() ? -1 : 0;
    Py_DECREF(r);
  }
  if (ok != 0) capture_py_err(err);
  PyGILState_Release(g);
  return ok;
}

int PD_TrainerSave(void* h, const char* dirname, const char** err) {
  if (err) *err = nullptr;
  PyGILState_STATE g = PyGILState_Ensure();
  PyObject* r = PyObject_CallMethod(static_cast<Predictor*>(h)->obj, "save",
                                    "s", dirname);
  int ok = r ? 0 : -1;
  if (!r) capture_py_err(err);
  Py_XDECREF(r);
  PyGILState_Release(g);
  return ok;
}

// One-shot scripting entry (R/other .C-style FFIs that cannot hold
// opaque handles): load model, feed one float tensor, run, copy one
// float output. Returns the output element count, or -1 on error.
long long PD_RunOnce(const char* model_dir, const char* input_name,
                     const float* data, const int* shape, int ndim,
                     const char* output_name, float* out, long long out_cap,
                     const char** err) {
  if (err) *err = nullptr;
  if (ndim < 0 || ndim > 16) {
    set_err(err, "PD_RunOnce: ndim must be in [0, 16]");
    return -1;
  }
  void* h = PD_PredictorCreate(model_dir, err);
  if (!h) return -1;
  long long shape64[16];
  for (int i = 0; i < ndim; ++i) shape64[i] = shape[i];
  long long n = -1;
  if (PD_SetInputFloat(h, input_name, data, shape64, ndim, err) == 0 &&
      PD_PredictorRun(h, err) == 0) {
    long long out_shape[8];
    int out_ndim = 0;
    n = PD_GetOutputFloat(h, output_name, out, out_cap, out_shape, 8,
                          &out_ndim, err);
  }
  PD_PredictorDestroy(h);
  return n;
}

// R .C calling convention: EVERY argument is a pointer (character ->
// char**, integer -> int*, double -> double*) and the routine returns
// void. n_out receives the element count, or -1 on error (err message
// printed to stderr — .C has no good string-out channel).
void PD_RunOnceR(char** model_dir, char** input_name, float* data,
                 int* shape, int* ndim, char** output_name, float* out,
                 double* out_cap, double* n_out) {
  const char* err = nullptr;
  long long n = PD_RunOnce(model_dir[0], input_name[0], data, shape,
                           *ndim, output_name[0], out,
                           (long long)(*out_cap), &err);
  if (n < 0 && err) {
    fprintf(stderr, "PD_RunOnceR: %s\n", err);
    free((void*)err);
  }
  *n_out = (double)n;
}

}  // extern "C"
