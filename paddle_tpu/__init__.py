"""paddle_tpu — a TPU-native deep-learning framework with the capabilities
of PaddlePaddle Fluid 1.8 (reference: /root/reference).

Static-graph programs (fluid.Program) are JIT-compiled whole-block via
XLA; distributed training uses jax.sharding meshes + XLA collectives over
ICI/DCN; hot kernels use Pallas. See SURVEY.md for the design blueprint.
"""
__version__ = "0.1.0"

from . import dataset, fluid, hapi, inference, io, nn, ops, reader, telemetry, tensor  # noqa: F401
from .tensor import *  # noqa: F401,F403 — 2.0 puts tensor ops at the root
from .fluid import (  # noqa: F401
    CPUPlace,
    Executor,
    ParamAttr,
    Program,
    TPUPlace,
    Variable,
    default_main_program,
    default_startup_program,
    global_scope,
    program_guard,
    scope_guard,
)

CUDAPlace = fluid.CUDAPlace
XLAPlace = fluid.XLAPlace


def batch(reader_fn, batch_size, drop_last=False):
    """Group a sample reader into a batch reader (reference
    python/paddle/batch.py)."""

    def batch_reader():
        b = []
        for sample in reader_fn():
            b.append(sample)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader
