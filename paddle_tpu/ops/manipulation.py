"""Tensor manipulation ops: reshape/transpose/concat/split/slice/gather/...

Parity surface: reference root-level manipulation ops (~40k LoC C++/CUDA):
reshape_op.cc, transpose_op.cc, concat_op.cc, split_op.cc, slice_op.cc,
strided_slice_op.cc, stack_op.cc, squeeze_op.cc, unsqueeze_op.cc,
expand_op.cc, tile (expand v2), gather_op.cc, gather_nd_op.cc,
scatter_op.cc, pad_op.cc, flatten_op.cc, arg_min_max_op_base.h,
top_k_op.cc, cumsum_op.cc, flip_op.cc, roll_op.cc, tril_triu_op.cc,
index_select_op.cc, where_op.cc. All are pure jnp/lax calls — XLA folds
most of them into layout changes or fuses them away entirely.

The *2 variants (reshape2/transpose2/squeeze2/unsqueeze2/flatten2) also
emit an XShape output carrying the pre-op shape, matching the reference's
grad plumbing; here it is a zero-size tensor kept only for desc parity.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..fluid.dtypes import runtime_dtype
from .registry import register, set_grad_maker


def _xshape(x):
    return jnp.zeros((0,) + tuple(x.shape), x.dtype)


def _infer_reshape(shape_attr, in_shape):
    shape = list(int(s) for s in shape_attr)
    numel = int(np.prod(in_shape))
    for i, s in enumerate(shape):
        if s == 0:
            shape[i] = in_shape[i]
    if -1 in shape:
        i = shape.index(-1)
        rest = int(np.prod([s for s in shape if s != -1]))
        shape[i] = numel // max(rest, 1)
    return tuple(shape)


@register("reshape")
def reshape(ctx, ins, attrs):
    x = ins["X"][0]
    return {"Out": [x.reshape(_infer_reshape(attrs["shape"], x.shape))]}


@register("reshape2")
def reshape2(ctx, ins, attrs):
    x = ins["X"][0]
    out = x.reshape(_infer_reshape(attrs["shape"], x.shape))
    return {"Out": [out], "XShape": [_xshape(x)]}


@register("transpose")
def transpose(ctx, ins, attrs):
    return {"Out": [jnp.transpose(ins["X"][0], attrs["axis"])]}


@register("transpose2")
def transpose2(ctx, ins, attrs):
    x = ins["X"][0]
    return {"Out": [jnp.transpose(x, attrs["axis"])], "XShape": [_xshape(x)]}


@register("concat")
def concat(ctx, ins, attrs):
    return {"Out": [jnp.concatenate(ins["X"], axis=attrs.get("axis", 0))]}


@register("split")
def split(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 0)
    num = attrs.get("num", 0)
    sections = attrs.get("sections", [])
    if sections:
        idxs = np.cumsum(sections)[:-1].tolist()
        outs = jnp.split(x, idxs, axis=axis)
    else:
        outs = jnp.split(x, num, axis=axis)
    return {"Out": list(outs)}


@register("slice")
def slice_op(ctx, ins, attrs):
    x = ins["Input"][0]
    axes = attrs["axes"]
    starts = attrs["starts"]
    ends = attrs["ends"]
    decrease = attrs.get("decrease_axis", [])
    idx = [slice(None)] * x.ndim
    for ax, st, en in zip(axes, starts, ends):
        dim = x.shape[ax]
        st = int(np.clip(st if st >= 0 else st + dim, 0, dim))
        en = int(np.clip(en if en >= 0 else en + dim, 0, dim))
        idx[ax] = slice(st, en)
    out = x[tuple(idx)]
    if decrease:
        out = out.reshape(
            tuple(d for i, d in enumerate(out.shape) if i not in set(decrease))
        )
    return {"Out": [out]}


@register("strided_slice")
def strided_slice(ctx, ins, attrs):
    x = ins["Input"][0]
    idx = [slice(None)] * x.ndim
    for ax, st, en, sd in zip(
        attrs["axes"], attrs["starts"], attrs["ends"], attrs["strides"]
    ):
        idx[ax] = slice(st, en, sd)
    return {"Out": [x[tuple(idx)]]}


@register("stack")
def stack(ctx, ins, attrs):
    return {"Y": [jnp.stack(ins["X"], axis=attrs.get("axis", 0))]}


@register("unstack")
def unstack(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 0)
    num = x.shape[axis]
    outs = [jnp.squeeze(a, axis=axis) for a in jnp.split(x, num, axis=axis)]
    return {"Y": outs}


@register("unbind")
def unbind(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 0)
    outs = [jnp.squeeze(a, axis=axis) for a in jnp.split(x, x.shape[axis], axis=axis)]
    return {"Out": outs}


def _squeeze_axes(x, axes):
    if not axes:
        return tuple(i for i, d in enumerate(x.shape) if d == 1)
    return tuple(a % x.ndim for a in axes if x.shape[a % x.ndim] == 1)


@register("squeeze")
def squeeze(ctx, ins, attrs):
    x = ins["X"][0]
    return {"Out": [jnp.squeeze(x, axis=_squeeze_axes(x, attrs.get("axes", [])))]}


@register("squeeze2")
def squeeze2(ctx, ins, attrs):
    x = ins["X"][0]
    out = jnp.squeeze(x, axis=_squeeze_axes(x, attrs.get("axes", [])))
    return {"Out": [out], "XShape": [_xshape(x)]}


@register("unsqueeze")
def unsqueeze(ctx, ins, attrs):
    x = ins["X"][0]
    for a in sorted(attrs["axes"]):
        x = jnp.expand_dims(x, a)
    return {"Out": [x]}


@register("unsqueeze2")
def unsqueeze2(ctx, ins, attrs):
    x = ins["X"][0]
    out = x
    for a in sorted(attrs["axes"]):
        out = jnp.expand_dims(out, a)
    return {"Out": [out], "XShape": [_xshape(x)]}


@register("flatten")
def flatten(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 1)
    lead = int(np.prod(x.shape[:axis])) if axis > 0 else 1
    return {"Out": [x.reshape((lead, -1))]}


@register("flatten2")
def flatten2(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 1)
    lead = int(np.prod(x.shape[:axis])) if axis > 0 else 1
    return {"Out": [x.reshape((lead, -1))], "XShape": [_xshape(x)]}


@register("flatten_contiguous_range")
def flatten_contiguous_range(ctx, ins, attrs):
    x = ins["X"][0]
    start = attrs.get("start_axis", 1) % max(x.ndim, 1)
    stop = attrs.get("stop_axis", -1) % max(x.ndim, 1)
    mid = int(np.prod(x.shape[start : stop + 1]))
    new_shape = tuple(x.shape[:start]) + (mid,) + tuple(x.shape[stop + 1 :])
    return {"Out": [x.reshape(new_shape)], "XShape": [_xshape(x)]}


@register("expand")
def expand(ctx, ins, attrs):
    x = ins["X"][0]
    times = attrs["expand_times"]
    return {"Out": [jnp.tile(x, tuple(times))]}


@register("expand_v2")
def expand_v2(ctx, ins, attrs):
    x = ins["X"][0]
    shape = list(attrs["shape"])
    # -1 entries keep the input dim
    xshape = (1,) * (len(shape) - x.ndim) + tuple(x.shape)
    tgt = tuple(xs if s == -1 else s for s, xs in zip(shape, xshape))
    return {"Out": [jnp.broadcast_to(x.reshape(xshape), tgt)]}


@register("expand_as")
def expand_as(ctx, ins, attrs):
    x, tgt = ins["X"][0], ins["target_tensor"][0]
    return {"Out": [jnp.broadcast_to(x, tgt.shape)]}


@register("tile")
def tile(ctx, ins, attrs):
    return {"Out": [jnp.tile(ins["X"][0], tuple(attrs["repeat_times"]))]}


@register("gather")
def gather(ctx, ins, attrs):
    x, idx = ins["X"][0], ins["Index"][0]
    axis = attrs.get("axis", 0)
    return {"Out": [jnp.take(x, idx.reshape(-1), axis=axis)]}


@register("gather_nd")
def gather_nd(ctx, ins, attrs):
    x, idx = ins["X"][0], ins["Index"][0]
    nd = idx.shape[-1]
    flat_idx = tuple(idx[..., i] for i in range(nd))
    return {"Out": [x[flat_idx]]}


@register("scatter")
def scatter(ctx, ins, attrs):
    x, ids, upd = ins["X"][0], ins["Ids"][0], ins["Updates"][0]
    overwrite = attrs.get("overwrite", True)
    ids = ids.reshape(-1)
    if overwrite:
        out = x.at[ids].set(upd)
    else:
        out = x.at[ids].set(jnp.zeros_like(upd[0]))
        out = out.at[ids].add(upd)
    return {"Out": [out]}


@register("scatter_nd_add")
def scatter_nd_add(ctx, ins, attrs):
    x, idx, upd = ins["X"][0], ins["Index"][0], ins["Updates"][0]
    nd = idx.shape[-1]
    flat_idx = tuple(idx[..., i] for i in range(nd))
    return {"Out": [x.at[flat_idx].add(upd)]}


@register("pad")
def pad(ctx, ins, attrs):
    x = ins["X"][0]
    pads = attrs["paddings"]
    val = attrs.get("pad_value", 0.0)
    cfg = [(pads[2 * i], pads[2 * i + 1]) for i in range(x.ndim)]
    return {"Out": [jnp.pad(x, cfg, constant_values=val)]}


@register("pad2d")
def pad2d(ctx, ins, attrs):
    x = ins["X"][0]  # NCHW
    p = attrs["paddings"]  # [top, bottom, left, right]
    mode = attrs.get("mode", "constant")
    val = attrs.get("pad_value", 0.0)
    cfg = [(0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])]
    if attrs.get("data_format", "NCHW") == "NHWC":
        cfg = [(0, 0), (p[0], p[1]), (p[2], p[3]), (0, 0)]
    jmode = {"constant": "constant", "reflect": "reflect", "edge": "edge"}[mode]
    kw = {"constant_values": val} if mode == "constant" else {}
    return {"Out": [jnp.pad(x, cfg, mode=jmode, **kw)]}


@register("pad3d")
def pad3d(ctx, ins, attrs):
    x = ins["X"][0]  # NCDHW
    p = attrs["paddings"]  # [left, right, top, bottom, front, back]
    mode = attrs.get("mode", "constant")
    val = attrs.get("value", 0.0)
    cfg = [(0, 0), (0, 0), (p[4], p[5]), (p[2], p[3]), (p[0], p[1])]
    if attrs.get("data_format", "NCDHW") == "NDHWC":
        cfg = [(0, 0), (p[4], p[5]), (p[2], p[3]), (p[0], p[1]), (0, 0)]
    jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
    kw = {"constant_values": val} if mode == "constant" else {}
    return {"Out": [jnp.pad(x, cfg, mode=jmode, **kw)]}


@register("arg_max", stop_gradient=True, no_vjp_grad=True)
def arg_max(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    keepdims = attrs.get("keepdims", False)
    out = jnp.argmax(x, axis=axis).astype(runtime_dtype("int64"))
    if keepdims:
        out = jnp.expand_dims(out, axis)
    return {"Out": [out]}


@register("arg_min", stop_gradient=True, no_vjp_grad=True)
def arg_min(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    keepdims = attrs.get("keepdims", False)
    out = jnp.argmin(x, axis=axis).astype(runtime_dtype("int64"))
    if keepdims:
        out = jnp.expand_dims(out, axis)
    return {"Out": [out]}


@register("argsort", no_vjp_grad=True)
def argsort(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    desc = attrs.get("descending", False)
    idx = jnp.argsort(-x if desc else x, axis=axis).astype(runtime_dtype("int64"))
    out = jnp.take_along_axis(x, idx, axis=axis)
    return {"Out": [out], "Indices": [idx]}


def _scatter_back_grad(x, idx, dout, axis):
    """d(gather-by-index)/dx: scatter dout back through saved indices."""
    return jnp.put_along_axis(
        jnp.zeros_like(x), idx, dout.astype(x.dtype), axis=axis, inplace=False
    )


@register("argsort_grad", no_vjp_grad=True)
def argsort_grad(ctx, ins, attrs):
    axis = attrs.get("axis", -1)
    dx = _scatter_back_grad(
        ins["X"][0], ins["Indices"][0], ins["Out@GRAD"][0], axis
    )
    return {"X@GRAD": [dx]}


def _indices_grad_maker(grad_type):
    # Out is differentiable via saved Indices (reference top_k_op.cc /
    # argsort_op.cc grad kernels); Indices itself carries no gradient.
    def maker(op, out_grads, block):
        og = out_grads.get("Out")
        if og is None:
            return [], {}
        xname = op.input("X")[0]
        gname = xname + "@GRAD"
        desc = {
            "type": grad_type,
            "inputs": {
                "X": [xname],
                "Indices": [op.output("Indices")[0]],
                "Out@GRAD": [og[0]],
            },
            "outputs": {"X@GRAD": [gname]},
            "attrs": dict(op.attrs),
        }
        return [desc], {xname: gname}

    return maker


set_grad_maker("argsort", _indices_grad_maker("argsort_grad"))


@register("top_k", no_vjp_grad=True)
def top_k(ctx, ins, attrs):
    x = ins["X"][0]
    k = attrs["k"]
    vals, idx = jax.lax.top_k(x, k)
    return {"Out": [vals], "Indices": [idx.astype(runtime_dtype("int64"))]}


@register("top_k_grad", no_vjp_grad=True)
def top_k_grad(ctx, ins, attrs):
    dx = _scatter_back_grad(
        ins["X"][0], ins["Indices"][0], ins["Out@GRAD"][0], -1
    )
    return {"X@GRAD": [dx]}


set_grad_maker("top_k", _indices_grad_maker("top_k_grad"))


@register("top_k_v2_grad", no_vjp_grad=True)
def top_k_v2_grad(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1) % x.ndim
    dx = _scatter_back_grad(x, ins["Indices"][0], ins["Out@GRAD"][0], axis)
    return {"X@GRAD": [dx]}


@register("top_k_v2", no_vjp_grad=True)
def top_k_v2(ctx, ins, attrs):
    x = ins["X"][0]
    k = attrs["k"]
    axis = attrs.get("axis", -1) % x.ndim
    largest = attrs.get("largest", True)
    xm = jnp.moveaxis(x, axis, -1)
    vals, idx = jax.lax.top_k(xm if largest else -xm, k)
    if not largest:
        vals = -vals
    return {
        "Out": [jnp.moveaxis(vals, -1, axis)],
        "Indices": [jnp.moveaxis(idx.astype(runtime_dtype("int64")), -1, axis)],
    }


set_grad_maker("top_k_v2", _indices_grad_maker("top_k_v2_grad"))


@register("cumsum")
def cumsum(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    if attrs.get("flatten", False):
        x = x.reshape(-1)
        axis = 0
    reverse = attrs.get("reverse", False)
    if reverse:
        x = jnp.flip(x, axis)
    out = jnp.cumsum(x, axis=axis)
    if attrs.get("exclusive", False):
        out = out - x
    if reverse:
        out = jnp.flip(out, axis)
    return {"Out": [out]}


@register("flip")
def flip(ctx, ins, attrs):
    return {"Out": [jnp.flip(ins["X"][0], axis=tuple(attrs["axis"]))]}


@register("roll")
def roll(ctx, ins, attrs):
    x = ins["X"][0]
    shifts = attrs["shifts"]
    axis = attrs.get("axis", None)
    if axis is not None and len(axis) == 0:
        axis = None
    return {
        "Out": [
            jnp.roll(
                x,
                tuple(shifts) if len(shifts) > 1 else shifts[0],
                axis=tuple(axis) if axis is not None else None,
            )
        ]
    }


@register("tril_triu")
def tril_triu(ctx, ins, attrs):
    x = ins["X"][0]
    d = attrs.get("diagonal", 0)
    lower = attrs.get("lower", True)
    return {"Out": [jnp.tril(x, d) if lower else jnp.triu(x, d)]}


@register("diag_v2", no_vjp_grad=True)
def diag_v2(ctx, ins, attrs):
    x = ins["X"][0]
    offset = attrs.get("offset", 0)
    if x.ndim == 1:
        n = x.shape[0] + abs(offset)
        out = jnp.full((n, n), attrs.get("padding_value", 0.0), x.dtype)
        idx = jnp.arange(x.shape[0])
        r = idx if offset >= 0 else idx - offset
        c = idx + offset if offset >= 0 else idx
        out = out.at[r, c].set(x)
        return {"Out": [out]}
    return {"Out": [jnp.diagonal(x, offset=offset)]}


@register("index_select")
def index_select(ctx, ins, attrs):
    x, idx = ins["X"][0], ins["Index"][0]
    return {"Out": [jnp.take(x, idx, axis=attrs.get("dim", 0))]}


@register("where")
def where(ctx, ins, attrs):
    cond, x, y = ins["Condition"][0], ins["X"][0], ins["Y"][0]
    return {"Out": [jnp.where(cond, x, y)]}


@register("meshgrid")
def meshgrid(ctx, ins, attrs):
    outs = jnp.meshgrid(*ins["X"], indexing="ij")
    return {"Out": list(outs)}


@register("take_along_axis")
def take_along_axis(ctx, ins, attrs):
    x, idx = ins["Input"][0], ins["Index"][0]
    return {"Result": [jnp.take_along_axis(x, idx, axis=attrs.get("Axis", 0))]}


@register("shard_index", stop_gradient=True, no_vjp_grad=True)
def shard_index(ctx, ins, attrs):
    """Remap global ids to shard-local ids (reference shard_index_op.cc),
    used by model-parallel embedding/fc layers."""
    x = ins["X"][0]
    index_num = attrs["index_num"]
    nshards = attrs["nshards"]
    shard_id = attrs["shard_id"]
    ignore_value = attrs.get("ignore_value", -1)
    shard_size = (index_num + nshards - 1) // nshards
    in_shard = (x // shard_size) == shard_id
    out = jnp.where(in_shard, x % shard_size, ignore_value)
    return {"Out": [out]}
