"""Sequence & RNN ops on the padded+mask representation.

Parity surface: reference operators/sequence_ops/ (~6.1k LoC:
sequence_pool_op.cc, sequence_conv_op.cc, sequence_softmax_op.cc,
sequence_reverse_op.h, sequence_expand_as_op.cc, sequence_pad_op.cc,
sequence_mask_op.cc), lstm_op.cc + math/detail/lstm_kernel.h,
gru_op.cc + math/detail/gru_kernel.h, edit_distance_op.cc.

TPU-native representation (SURVEY.md §7 "hard parts" — LoD): the
reference stores ragged sequences as LoD tensors ([sum(len), D] plus
offsets) and every sequence_* kernel walks the offsets. XLA wants static
shapes, so here a batch of sequences is a dense [B, T, ...] tensor plus
an optional per-row `Length` [B] int32; padding lives at the tail of the
time axis and is masked out inside each op. Recurrences (lstm/gru) are
`lax.scan` over the time axis — one compiled step body, O(1)-in-T
compile time, and the scan carries are exactly the reference's per-step
state (SSA-ified, no per-step Scopes).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..fluid.dtypes import runtime_dtype
from .registry import register

NEG_INF = -1e30


def _length_mask(x, ins, time_axis=1):
    """[B, T] float mask from the optional Length input (None = all valid)."""
    if not ins.get("Length"):
        return None
    length = ins["Length"][0]
    t = x.shape[time_axis]
    return (jnp.arange(t)[None, :] < length[:, None]).astype(jnp.float32)


def _bcast(mask, x):
    """[B,T] mask broadcast to x's rank ([B,T,...])."""
    return mask.reshape(mask.shape + (1,) * (x.ndim - 2))


@register("sequence_mask", stop_gradient=True, no_vjp_grad=True)
def sequence_mask(ctx, ins, attrs):
    """X = lengths [B] -> [B, maxlen] (reference sequence_mask_op.cc)."""
    length = ins["X"][0]
    maxlen = int(attrs.get("maxlen", -1))
    if maxlen <= 0:
        raise ValueError("sequence_mask needs a static maxlen attr on TPU")

    dt = runtime_dtype(attrs.get("out_dtype", "int64"))
    out = (jnp.arange(maxlen)[None, :] < length[..., None]).astype(dt)
    return {"Y": [out]}


@register("sequence_pool")
def sequence_pool(ctx, ins, attrs):
    """X [B,T,...] (+Length) -> Out [B,...]; pooltype AVERAGE/SUM/SQRT/
    MAX/LAST/FIRST (reference sequence_pool_op.cc)."""
    x = ins["X"][0]
    ptype = attrs.get("pooltype", "AVERAGE").upper()
    mask = _length_mask(x, ins)
    t = x.shape[1]
    if mask is None:
        n = jnp.full((x.shape[0],) + (1,) * (x.ndim - 2), float(t), jnp.float32)
        last_idx = jnp.full((x.shape[0],), t - 1, jnp.int32)
        xm = x
    else:
        n = jnp.maximum(jnp.sum(mask, axis=1), 1.0).reshape(
            (x.shape[0],) + (1,) * (x.ndim - 2)
        )
        last_idx = jnp.maximum(jnp.sum(mask, axis=1).astype(jnp.int32) - 1, 0)
        xm = x * _bcast(mask, x).astype(x.dtype)
    nonempty = None
    if mask is not None:
        nonempty = (jnp.sum(mask, axis=1) > 0).reshape(
            (x.shape[0],) + (1,) * (x.ndim - 2)
        )
    if ptype == "SUM":
        out = jnp.sum(xm, axis=1)
    elif ptype == "AVERAGE":
        out = jnp.sum(xm, axis=1) / n.astype(x.dtype)
    elif ptype == "SQRT":
        out = jnp.sum(xm, axis=1) / jnp.sqrt(n).astype(x.dtype)
    elif ptype == "MAX":
        xmax = x if mask is None else jnp.where(
            _bcast(mask, x) > 0, x, jnp.asarray(NEG_INF, x.dtype)
        )
        idx = jnp.argmax(xmax, axis=1).astype(jnp.int32)
        out = jnp.max(xmax, axis=1)
        if nonempty is not None:
            # zero-length rows: 0, like the other pooltypes (not NEG_INF)
            out = jnp.where(nonempty, out, 0.0).astype(x.dtype)
        return {"Out": [out], "MaxIndex": [idx]}
    elif ptype == "LAST":
        out = jnp.take_along_axis(
            x, last_idx.reshape((-1, 1) + (1,) * (x.ndim - 2)), axis=1
        ).squeeze(1)
        if nonempty is not None:
            out = jnp.where(nonempty, out, 0.0).astype(x.dtype)
    elif ptype == "FIRST":
        out = x[:, 0]
        if nonempty is not None:
            out = jnp.where(nonempty, out, 0.0).astype(x.dtype)
    else:
        raise ValueError(f"unknown pooltype {ptype!r}")
    return {"Out": [out]}


@register("sequence_softmax")
def sequence_softmax(ctx, ins, attrs):
    """Masked softmax over the time axis (reference sequence_softmax_op)."""
    x = ins["X"][0]
    mask = _length_mask(x, ins)
    if mask is None:
        return {"Out": [jax.nn.softmax(x, axis=1)]}
    m = _bcast(mask, x)
    z = jnp.where(m > 0, x, NEG_INF)
    out = jax.nn.softmax(z, axis=1)
    return {"Out": [out * m.astype(out.dtype)]}


@register("sequence_reverse")
def sequence_reverse(ctx, ins, attrs):
    """Reverse the valid prefix of each row; padding stays at the tail
    (reference sequence_reverse_op.h)."""
    x = ins["X"][0]
    t = x.shape[1]
    if not ins.get("Length"):
        return {"Y": [jnp.flip(x, axis=1)]}
    length = ins["Length"][0]
    pos = jnp.arange(t)[None, :]
    rev = length[:, None] - 1 - pos  # index of the element that lands at pos
    idx = jnp.where(pos < length[:, None], rev, pos).astype(jnp.int32)
    out = jnp.take_along_axis(x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)), axis=1)
    return {"Y": [out]}


@register("sequence_expand_as")
def sequence_expand_as(ctx, ins, attrs):
    """X [B, ...] (one row per sequence) broadcast over Y's time axis ->
    [B, T, ...] (padded analog of reference sequence_expand_as_op.cc)."""
    x, y = ins["X"][0], ins["Y"][0]
    t = y.shape[1]
    out = jnp.broadcast_to(
        jnp.expand_dims(x, 1), (x.shape[0], t) + tuple(x.shape[1:])
    )
    return {"Out": [out]}


@register("sequence_expand")
def sequence_expand(ctx, ins, attrs):
    """Same dense semantics as sequence_expand_as: with padded batches the
    LoD ref-level distinction vanishes (every row expands to T steps)."""
    return {"Out": [sequence_expand_as(ctx, ins, attrs)["Out"][0]]}


@register("sequence_conv")
def sequence_conv(ctx, ins, attrs):
    """Context-window convolution over time (reference sequence_conv_op.cc):
    window of context_length rows starting at t+context_start is flattened
    to [ctx*D] and matmul'd with Filter [ctx*D, num_filters]."""
    x = ins["X"][0]  # [B, T, D]
    w = ins["Filter"][0]
    cl = int(attrs.get("contextLength", attrs.get("context_length", 3)))
    cs = int(attrs.get("contextStart", attrs.get("context_start", -(cl - 1) // 2)))
    b, t, d = x.shape
    mask = _length_mask(x, ins)
    if mask is not None:
        x = x * _bcast(mask, x).astype(x.dtype)
    pad_lo = max(-cs, 0)
    pad_hi = max(cs + cl - 1, 0)
    xp = jnp.pad(x, [(0, 0), (pad_lo, pad_hi), (0, 0)])
    cols = [xp[:, pad_lo + cs + j: pad_lo + cs + j + t, :] for j in range(cl)]
    ctx_mat = jnp.concatenate(cols, axis=-1)  # [B, T, cl*D]
    out = jnp.einsum("btc,cf->btf", ctx_mat, w)
    if mask is not None:
        out = out * _bcast(mask, out).astype(out.dtype)
    return {"Out": [out]}


@register("sequence_pad")
def sequence_pad(ctx, ins, attrs):
    """Dense-representation identity + length passthrough: inputs are
    already padded [B,T,...]; emits Length so downstream sequence ops can
    mask (the LoD->padded conversion of reference sequence_pad_op.cc is
    a no-op here)."""
    x = ins["X"][0]
    if ins.get("Length"):
        length = ins["Length"][0]
    else:
        length = jnp.full((x.shape[0],), x.shape[1], jnp.int32)
    return {"Out": [x], "Length": [length]}


@register("sequence_unpad")
def sequence_unpad(ctx, ins, attrs):
    """Inverse of sequence_pad: zero out the padding tail (a true unpad
    would be ragged; masking is the static-shape equivalent)."""
    x = ins["X"][0]
    mask = _length_mask(x, ins)
    if mask is None:
        return {"Out": [x]}
    return {"Out": [x * _bcast(mask, x).astype(x.dtype)]}


@register("edit_distance", stop_gradient=True, no_vjp_grad=True)
def edit_distance(ctx, ins, attrs):
    """Levenshtein distance between padded int rows (reference
    edit_distance_op.cc). Hyps [B,Th]+HypsLength, Refs [B,Tr]+RefsLength.
    DP over a lax.scan per hypothesis position."""
    hyp, ref = ins["Hyps"][0], ins["Refs"][0]
    b, th = hyp.shape
    tr = ref.shape[1]
    hlen = ins["HypsLength"][0] if ins.get("HypsLength") else jnp.full((b,), th, jnp.int32)
    rlen = ins["RefsLength"][0] if ins.get("RefsLength") else jnp.full((b,), tr, jnp.int32)

    # row[j] = edit distance between hyp[:i] and ref[:j]
    init = jnp.broadcast_to(jnp.arange(tr + 1, dtype=jnp.float32), (b, tr + 1))

    def body(i, row):
        hy = jax.lax.dynamic_index_in_dim(hyp, i, axis=1, keepdims=False)  # [B]
        sub_cost = (ref != hy[:, None]).astype(jnp.float32)  # [B, Tr]
        # new_row[0] = i+1; new_row[j] = min(row[j]+1, new_row[j-1]+1,
        #                                    row[j-1]+sub(j-1))
        del_cost = row[:, 1:] + 1.0
        sub = row[:, :-1] + sub_cost
        # prefix-min recurrence for insertions via associative scan:
        # new_row[j] = min over k<=j of (cand[k] + (j-k)) where cand is
        # min(del, sub) prefixed with i+1
        first = jnp.full((b, 1), i + 1.0, jnp.float32)
        cand = jnp.concatenate([first, jnp.minimum(del_cost, sub)], axis=1)
        j = jnp.arange(tr + 1, dtype=jnp.float32)[None, :]
        shifted = cand - j
        run_min = jax.lax.associative_scan(jnp.minimum, shifted, axis=1)
        new_row = run_min + j
        valid = (i < hlen).reshape(b, 1)
        return jnp.where(valid, new_row, row)

    row = jax.lax.fori_loop(0, th, body, init)
    dist = jnp.take_along_axis(row, rlen[:, None], axis=1)[:, 0]
    if attrs.get("normalized", False):
        dist = dist / jnp.maximum(rlen.astype(jnp.float32), 1.0)
    seq_num = jnp.asarray([b], runtime_dtype("int64"))
    return {"Out": [dist.reshape(b, 1)], "SequenceNum": [seq_num]}


# ---------------------------------------------------------------------------
# recurrent cells: lstm / gru (reference lstm_op.cc, gru_op.cc)
# ---------------------------------------------------------------------------


def _act_by_name(name):
    return {
        "sigmoid": jax.nn.sigmoid,
        "tanh": jnp.tanh,
        "relu": jax.nn.relu,
        "identity": lambda x: x,
    }[name]


@register("lstm")
def lstm(ctx, ins, attrs):
    """Full-sequence LSTM. Input [B,T,4H] is the pre-projected x (the fc
    lives outside, as in reference dynamic_lstm); Weight [H,4H] recurrent,
    Bias [1,4H]. Gate layout matches math/detail/lstm_kernel.h:
    [candidate c, input i, forget f, output o]; no peepholes.
    Outputs Hidden/Cell [B,T,H]."""
    x = ins["Input"][0]
    w = ins["Weight"][0]
    bias = ins["Bias"][0].reshape(-1) if ins.get("Bias") else None
    b, t, h4 = x.shape
    h = h4 // 4
    act_gate = _act_by_name(attrs.get("gate_activation", "sigmoid"))
    act_cand = _act_by_name(attrs.get("candidate_activation", "tanh"))
    act_cell = _act_by_name(attrs.get("cell_activation", "tanh"))
    is_reverse = bool(attrs.get("is_reverse", False))
    mask = _length_mask(x, ins)

    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((b, h), x.dtype)
    c0 = ins["C0"][0] if ins.get("C0") else jnp.zeros((b, h), x.dtype)

    xs = jnp.swapaxes(x, 0, 1)  # [T, B, 4H]
    ms = jnp.swapaxes(mask, 0, 1) if mask is not None else None
    if is_reverse:
        xs = jnp.flip(xs, 0)
        ms = jnp.flip(ms, 0) if ms is not None else None

    def step(carry, inp):
        hp, cp = carry
        if ms is None:
            xt, mt = inp, None
        else:
            xt, mt = inp
        g = xt + hp @ w
        if bias is not None:
            g = g + bias
        c_t, i_t, f_t, o_t = jnp.split(g, 4, axis=-1)
        cand = act_cand(c_t)
        i = act_gate(i_t)
        f = act_gate(f_t)
        c = cand * i + cp * f
        o = act_gate(o_t)
        hh = o * act_cell(c)
        if mt is not None:
            keep = mt[:, None].astype(hh.dtype)
            hh = hh * keep + hp * (1 - keep)
            c = c * keep + cp * (1 - keep)
        return (hh, c), (hh, c)

    inputs = xs if ms is None else (xs, ms)
    (_, _), (hs, cs) = jax.lax.scan(step, (h0, c0), inputs)
    if is_reverse:
        hs, cs = jnp.flip(hs, 0), jnp.flip(cs, 0)
    return {
        "Hidden": [jnp.swapaxes(hs, 0, 1)],
        "Cell": [jnp.swapaxes(cs, 0, 1)],
    }


@register("gru")
def gru(ctx, ins, attrs):
    """Full-sequence GRU. Input [B,T,3H] pre-projected; Weight [H,3H]
    ([:, :2H] = update+reset recurrent, [:, 2H:] = candidate); Bias [1,3H].
    Semantics of math/detail/gru_kernel.h (origin_mode=False):
      u = σ(x_u + h W_u); r = σ(x_r + h W_r)
      c̃ = tanh(x_c + (r ⊙ h) W_c);  h' = (1-u) ⊙ h + u ⊙ c̃
    origin_mode=True flips the blend: h' = u ⊙ h + (1-u) ⊙ c̃."""
    x = ins["Input"][0]
    w = ins["Weight"][0]
    bias = ins["Bias"][0].reshape(-1) if ins.get("Bias") else None
    b, t, h3 = x.shape
    h = h3 // 3
    act_gate = _act_by_name(attrs.get("gate_activation", "sigmoid"))
    act_cand = _act_by_name(attrs.get("activation", "tanh"))
    origin = bool(attrs.get("origin_mode", False))
    is_reverse = bool(attrs.get("is_reverse", False))
    mask = _length_mask(x, ins)

    w_ur = w[:, : 2 * h]
    w_c = w[:, 2 * h:]
    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((b, h), x.dtype)

    xs = jnp.swapaxes(x, 0, 1)
    ms = jnp.swapaxes(mask, 0, 1) if mask is not None else None
    if is_reverse:
        xs = jnp.flip(xs, 0)
        ms = jnp.flip(ms, 0) if ms is not None else None

    def step(hp, inp):
        if ms is None:
            xt, mt = inp, None
        else:
            xt, mt = inp
        g_ur = xt[:, : 2 * h] + hp @ w_ur
        g_c = xt[:, 2 * h:]
        if bias is not None:
            g_ur = g_ur + bias[: 2 * h]
            g_c = g_c + bias[2 * h:]
        u = act_gate(g_ur[:, :h])
        r = act_gate(g_ur[:, h:])
        cand = act_cand(g_c + (r * hp) @ w_c)
        hh = u * hp + (1 - u) * cand if origin else (1 - u) * hp + u * cand
        if mt is not None:
            keep = mt[:, None].astype(hh.dtype)
            hh = hh * keep + hp * (1 - keep)
        return hh, hh

    inputs = xs if ms is None else (xs, ms)
    _, hs = jax.lax.scan(step, h0, inputs)
    if is_reverse:
        hs = jnp.flip(hs, 0)
    return {"Hidden": [jnp.swapaxes(hs, 0, 1)]}


# ---------------------------------------------------------------------------
# structured prediction: linear-chain CRF + viterbi (reference
# linear_chain_crf_op.cc, crf_decoding_op.cc) and CTC (warpctc_op.cc)
# ---------------------------------------------------------------------------


def _crf_unpack(transition):
    """Paddle layout: Transition [D+2, D]; row 0 = start weights, row 1 =
    stop weights, rows 2.. = square transition matrix."""
    return transition[0], transition[1], transition[2:]


@register("linear_chain_crf", no_vjp_grad=False)
def linear_chain_crf(ctx, ins, attrs):
    """Negative log-likelihood of a linear-chain CRF on padded inputs.
    Emission [B,T,D] (+Length), Transition [D+2,D], Label [B,T] int.
    LogLikelihood [B,1] (the op returns -nll like the reference: its
    output is the log-likelihood maximized by *minimizing* the mean of
    the negated value; we return nll so book models can minimize mean)."""
    em = ins["Emission"][0]
    trans = ins["Transition"][0]
    label = ins["Label"][0]
    if label.ndim == 3 and label.shape[-1] == 1:
        label = label[..., 0]
    b, t, d = em.shape
    start_w, stop_w, tr = _crf_unpack(trans)
    mask = _length_mask(em, ins)
    if mask is None:
        mask = jnp.ones((b, t), jnp.float32)
    length = jnp.sum(mask, axis=1).astype(jnp.int32)

    emf = em.astype(jnp.float32)
    # --- log partition via forward algorithm (scan over time)
    alpha0 = start_w[None, :] + emf[:, 0, :]

    def fwd(alpha, inp):
        e_t, m_t = inp  # [B,D], [B]
        scores = alpha[:, :, None] + tr[None, :, :]  # [B, D_from, D_to]
        new = jax.scipy.special.logsumexp(scores, axis=1) + e_t
        keep = m_t[:, None]
        return jnp.where(keep > 0, new, alpha), None

    es = jnp.swapaxes(emf, 0, 1)[1:]
    msk = jnp.swapaxes(mask, 0, 1)[1:]
    alpha, _ = jax.lax.scan(fwd, alpha0, (es, msk))
    last_lbl_idx = jnp.maximum(length - 1, 0)
    logz = jax.scipy.special.logsumexp(alpha + stop_w[None, :], axis=1)

    # --- gold path score
    lbl = label.astype(jnp.int32)
    em_score = jnp.sum(
        jnp.take_along_axis(emf, lbl[..., None], axis=2)[..., 0] * mask, axis=1
    )
    frm = lbl[:, :-1]
    to = lbl[:, 1:]
    tr_score = jnp.sum(tr[frm, to] * mask[:, 1:], axis=1)
    start_score = start_w[lbl[:, 0]]
    last_lbl = jnp.take_along_axis(lbl, last_lbl_idx[:, None], axis=1)[:, 0]
    stop_score = stop_w[last_lbl]
    gold = em_score + tr_score + start_score + stop_score
    nll = (logz - gold)[:, None]
    return {"LogLikelihood": [nll]}


@register("crf_decoding", stop_gradient=True, no_vjp_grad=True)
def crf_decoding(ctx, ins, attrs):
    """Viterbi decode (reference crf_decoding_op.cc). Emission [B,T,D]
    (+Length), Transition [D+2,D] -> ViterbiPath [B,T] int64 (padding
    positions 0). If Label is given, outputs correctness mask instead
    semantics kept simple: always the path."""
    em = ins["Emission"][0].astype(jnp.float32)
    trans = ins["Transition"][0].astype(jnp.float32)
    b, t, d = em.shape
    start_w, stop_w, tr = _crf_unpack(trans)
    mask = _length_mask(em, ins)
    if mask is None:
        mask = jnp.ones((b, t), jnp.float32)
    length = jnp.sum(mask, axis=1).astype(jnp.int32)

    def fwd(carry, inp):
        score = carry
        e_t, m_t = inp
        cand = score[:, :, None] + tr[None, :, :]  # [B, from, to]
        best_prev = jnp.argmax(cand, axis=1).astype(jnp.int32)  # [B, to]
        new = jnp.max(cand, axis=1) + e_t
        keep = m_t[:, None]
        new = jnp.where(keep > 0, new, score)
        best_prev = jnp.where(
            keep.astype(jnp.int32) > 0, best_prev,
            jnp.broadcast_to(jnp.arange(d, dtype=jnp.int32)[None, :], (b, d)),
        )
        return new, best_prev

    es = jnp.swapaxes(em, 0, 1)[1:]
    msk = jnp.swapaxes(mask, 0, 1)[1:]
    score0 = start_w[None, :] + em[:, 0, :]
    final, back = jax.lax.scan(fwd, score0, (es, msk))
    final = final + stop_w[None, :]
    last = jnp.argmax(final, axis=1).astype(jnp.int32)  # [B]

    def bwd(lbl, bp):
        prev = jnp.take_along_axis(bp, lbl[:, None], axis=1)[:, 0]
        return prev, lbl

    # reverse scan emits the label at times 1..T-1 (in forward order) and
    # carries the time-0 label out
    first, path_rev = jax.lax.scan(bwd, last, back, reverse=True)
    path = jnp.concatenate([first[None, :], path_rev], axis=0)  # [T, B]
    path = jnp.swapaxes(path, 0, 1)
    path = (path * mask.astype(path.dtype)).astype(runtime_dtype("int64"))
    return {"ViterbiPath": [path]}


@register("warpctc")
def warpctc(ctx, ins, attrs):
    """CTC loss on padded inputs (reference warpctc_op.cc — here a pure
    lax.scan log-alpha recursion instead of the warp-ctc CUDA library).
    Logits [B,T,C] (+LogitsLength), Label [B,L] (+LabelLength);
    blank = attrs['blank']. Loss [B,1]."""
    logits = ins["Logits"][0].astype(jnp.float32)
    label = ins["Label"][0].astype(jnp.int32)
    b, t, c = logits.shape
    l = label.shape[1]
    blank = int(attrs.get("blank", 0))
    tlen = (
        ins["LogitsLength"][0].astype(jnp.int32)
        if ins.get("LogitsLength")
        else jnp.full((b,), t, jnp.int32)
    )
    llen = (
        ins["LabelLength"][0].astype(jnp.int32)
        if ins.get("LabelLength")
        else jnp.full((b,), l, jnp.int32)
    )
    logp = jax.nn.log_softmax(logits, axis=-1)
    # extended label sequence: blank, l1, blank, l2, ..., blank  (2L+1)
    s = 2 * l + 1
    ext = jnp.full((b, s), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(label)
    # can skip from s-2 to s when ext[s] != blank and ext[s] != ext[s-2]
    ext_prev2 = jnp.concatenate([jnp.full((b, 2), -1, jnp.int32), ext[:, :-2]], axis=1)
    can_skip = (ext != blank) & (ext != ext_prev2)

    def lp_at(t_idx):
        lp_t = jax.lax.dynamic_index_in_dim(logp, t_idx, axis=1, keepdims=False)
        return jnp.take_along_axis(lp_t, ext, axis=1)  # [B, S]

    neg = jnp.float32(NEG_INF)
    alpha0 = jnp.full((b, s), neg)
    alpha0 = alpha0.at[:, 0].set(logp[:, 0, blank])
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(llen > 0, jnp.take_along_axis(logp[:, 0, :], ext[:, 1:2], axis=1)[:, 0], neg)
    )

    def step(alpha, t_idx):
        a_shift1 = jnp.concatenate([jnp.full((b, 1), neg), alpha[:, :-1]], axis=1)
        a_shift2 = jnp.concatenate([jnp.full((b, 2), neg), alpha[:, :-2]], axis=1)
        a2 = jnp.where(can_skip, a_shift2, neg)
        merged = jnp.logaddexp(jnp.logaddexp(alpha, a_shift1), a2)
        new = merged + lp_at(t_idx)
        valid = (t_idx < tlen)[:, None]
        return jnp.where(valid, new, alpha), None

    alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, t))
    # final: sum of positions 2*llen (last blank) and 2*llen-1 (last label)
    idx_last = jnp.clip(2 * llen, 0, s - 1)[:, None]
    idx_prev = jnp.clip(2 * llen - 1, 0, s - 1)[:, None]
    a_last = jnp.take_along_axis(alpha, idx_last, axis=1)[:, 0]
    a_prev = jnp.take_along_axis(alpha, idx_prev, axis=1)[:, 0]
    # empty target: only the all-blank path exists (idx_prev would alias
    # idx_last and double-count it by log 2)
    ll = jnp.where(llen > 0, jnp.logaddexp(a_last, a_prev), a_last)
    loss = -ll
    if attrs.get("norm_by_times", False):
        loss = loss / jnp.maximum(tlen.astype(jnp.float32), 1.0)
    return {"Loss": [loss[:, None]]}


# ---------------------------------------------------------------------------
# beam search step (reference beam_search_op.cc): used inside a decode loop
# ---------------------------------------------------------------------------


@register("beam_search", stop_gradient=True, no_vjp_grad=True)
def beam_search(ctx, ins, attrs):
    """One beam-search step on a flattened beam batch.

    pre_ids [B*W, 1] int, pre_scores [B*W, 1] f32, scores [B*W, V]
    (log-probabilities of the next token per live hypothesis).
    attrs: beam_size W, end_id.
    Outputs: selected_ids [B*W, 1], selected_scores [B*W, 1],
    parent_idx [B*W] int32 (index into the flattened beam the selection
    came from — the caller uses it to gather carried decoder state).
    Finished hypotheses (pre_id == end_id) propagate with frozen score.
    """
    pre_ids = ins["pre_ids"][0].reshape(-1)
    pre_scores = ins["pre_scores"][0].reshape(-1).astype(jnp.float32)
    scores = ins["scores"][0].astype(jnp.float32)
    w = int(attrs["beam_size"])
    end_id = int(attrs["end_id"])
    bw, v = scores.shape
    b = bw // w

    finished = pre_ids == end_id
    # frozen: only the end_id continuation keeps the old score
    cont = pre_scores[:, None] + scores
    frozen = jnp.full((bw, v), NEG_INF, jnp.float32)
    frozen = frozen.at[:, end_id].set(pre_scores)
    total = jnp.where(finished[:, None], frozen, cont)  # [B*W, V]

    total_b = total.reshape(b, w * v)
    top_scores, top_idx = jax.lax.top_k(total_b, w)  # [B, W]
    parent_in_beam = (top_idx // v).astype(jnp.int32)  # [B, W]
    token = (top_idx % v).astype(pre_ids.dtype)
    parent_flat = (
        parent_in_beam + (jnp.arange(b, dtype=jnp.int32) * w)[:, None]
    ).reshape(-1)
    return {
        "selected_ids": [token.reshape(bw, 1)],
        "selected_scores": [top_scores.reshape(bw, 1)],
        "parent_idx": [parent_flat],
    }


@register("sequence_concat")
def sequence_concat(ctx, ins, attrs):
    """Ragged concat along time on padded rows (reference
    sequence_concat_op.cc): inputs X (list of [B, Ti, ...]) with optional
    per-input Length ([k*B] stacked or absent = full). Valid prefixes are
    packed back-to-back per row; output time = sum(Ti)."""
    xs = ins["X"]
    b = xs[0].shape[0]
    t_out = sum(x.shape[1] for x in xs)
    feat = xs[0].shape[2:]
    if ins.get("Length"):
        lens = jnp.split(ins["Length"][0].reshape(len(xs), b), len(xs))
        lens = [l.reshape(b) for l in lens]
    else:
        lens = [jnp.full((b,), x.shape[1], jnp.int32) for x in xs]
    out = jnp.zeros((b, t_out) + tuple(feat), xs[0].dtype)
    offset = jnp.zeros((b,), jnp.int32)
    for x, ln in zip(xs, lens):
        ln = ln.astype(jnp.int32)
        t = x.shape[1]
        steps = jnp.arange(t, dtype=jnp.int32)
        valid = (steps[None, :] < ln[:, None])  # [B, T]
        tgt = jnp.clip(offset[:, None] + steps[None, :], 0, t_out - 1)
        bidx = jnp.broadcast_to(jnp.arange(b, dtype=jnp.int32)[:, None], tgt.shape)
        idx = jnp.stack([bidx, tgt], axis=-1).reshape(-1, 2)
        upd = (x * valid.reshape(valid.shape + (1,) * len(feat)).astype(x.dtype)
               ).reshape((b * t,) + tuple(feat))
        out = out.at[idx[:, 0], idx[:, 1]].add(upd)
        offset = offset + ln
    return {"Out": [out], "Length": [offset]}


@register("sequence_enumerate", stop_gradient=True, no_vjp_grad=True)
def sequence_enumerate(ctx, ins, attrs):
    """Sliding windows of ids (reference sequence_enumerate_op.cc):
    X [B, T] int -> Out [B, T, win]; positions past the row's length (or
    window overruns) read pad_value."""
    x = ins["X"][0]
    win = int(attrs["win_size"])
    pad = int(attrs.get("pad_value", 0))
    b, t = x.shape[:2]
    if ins.get("Length"):
        ln = ins["Length"][0].astype(jnp.int32)
    else:
        ln = jnp.full((b,), t, jnp.int32)
    steps = jnp.arange(t, dtype=jnp.int32)
    cols = []
    for k in range(win):
        idx = jnp.clip(steps + k, 0, t - 1)
        v = x[:, idx]
        ok = ((steps + k)[None, :] < ln[:, None])
        cols.append(jnp.where(ok, v, pad))
    return {"Out": [jnp.stack(cols, axis=-1)]}


@register("sequence_slice")
def sequence_slice(ctx, ins, attrs):
    """Per-row subsequence (reference sequence_slice_op.cc): X [B, T, ...],
    Offset [B] or [B,1], Length [B] or [B,1] -> Out [B, T, ...] with row b
    holding X[b, off_b : off_b+len_b] left-aligned, rest zero."""
    x = ins["X"][0]
    off = ins["Offset"][0].reshape(-1).astype(jnp.int32)
    ln = ins["Length"][0].reshape(-1).astype(jnp.int32)
    b, t = x.shape[:2]
    steps = jnp.arange(t, dtype=jnp.int32)
    src = jnp.clip(off[:, None] + steps[None, :], 0, t - 1)  # [B, T]
    picked = jnp.take_along_axis(
        x, src.reshape((b, t) + (1,) * (x.ndim - 2)), axis=1
    ) if x.ndim > 2 else jnp.take_along_axis(x, src, axis=1)
    valid = (steps[None, :] < ln[:, None]).reshape(
        (b, t) + (1,) * (x.ndim - 2)).astype(x.dtype)
    return {"Out": [picked * valid], "OutLength": [ln]}


@register("sequence_scatter")
def sequence_scatter(ctx, ins, attrs):
    """Scatter per-row updates into X at per-row column ids (reference
    sequence_scatter_op.cc on the padded layout): X [B, D], Ids [B, S],
    Updates [B, S] (+ optional Length [B] masking trailing id slots)."""
    x = ins["X"][0]
    ids = ins["Ids"][0].astype(jnp.int32)
    upd = ins["Updates"][0]
    b, s = ids.shape[:2]
    if ins.get("Length"):
        ln = ins["Length"][0].reshape(-1).astype(jnp.int32)
        valid = (jnp.arange(s, dtype=jnp.int32)[None, :] < ln[:, None])
        upd = upd * valid.astype(upd.dtype)
    bidx = jnp.broadcast_to(jnp.arange(b, dtype=jnp.int32)[:, None], ids.shape)
    return {"Out": [x.at[bidx.reshape(-1), ids.reshape(-1)].add(upd.reshape(-1))]}


@register("sequence_reshape")
def sequence_reshape(ctx, ins, attrs):
    """Re-chunk the time axis to a new feature width (reference
    sequence_reshape_op.cc): [B, T, D] -> [B, T*D/new_dim, new_dim]."""
    x = ins["X"][0]
    new_dim = int(attrs["new_dim"])
    b, t, d = x.shape
    if (t * d) % new_dim:
        raise ValueError(
            f"sequence_reshape: T*D={t*d} not divisible by new_dim={new_dim}")
    return {"Out": [x.reshape(b, (t * d) // new_dim, new_dim)]}


@register("gather_tree", stop_gradient=True, no_vjp_grad=True)
def gather_tree(ctx, ins, attrs):
    """Beam-search backtrace (reference gather_tree_op.cc): Ids and
    Parents [T, B, W]; walk parents from the last step back, emitting the
    full id path per final beam."""
    ids, parents = ins["Ids"][0], ins["Parents"][0].astype(jnp.int32)
    t = ids.shape[0]
    # last step emits its own ids in final beam order; then walk back:
    # beam[b, w] = which beam slot the path through w occupied at time ti
    outs = [ids[t - 1]]
    beam = parents[t - 1]
    for ti in range(t - 2, -1, -1):
        outs.append(jnp.take_along_axis(ids[ti], beam, axis=-1))
        beam = jnp.take_along_axis(parents[ti], beam, axis=-1)
    return {"Out": [jnp.stack(outs[::-1], axis=0)]}
