"""Dense math ops: elementwise (with paddle axis-broadcast), matmul family,
activations, softmax.

Parity surface: reference operators/elementwise/* (~6.9k LoC),
matmul_op.cc, mul_op.cc, activation_op.cc (~30 activations),
softmax_op.cc, log_softmax_op.cc. On TPU these are single jnp/lax calls
that XLA fuses into surrounding matmuls; matmuls hit the MXU in bf16 when
AMP is on.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register


def _paddle_broadcast(x, y, axis):
    """Paddle elementwise broadcast: align y's dims to x starting at `axis`
    (reference: operators/elementwise/elementwise_op_function.h)."""
    xr, yr = x.ndim, y.ndim
    if xr == yr:
        return x, y
    if xr < yr:  # numpy-style broadcast from the left for x
        return x, y
    a = axis if axis is not None and axis >= 0 else xr - yr
    new_shape = (1,) * a + tuple(y.shape) + (1,) * (xr - a - yr)
    return x, y.reshape(new_shape)


def _ew(name, fn):
    @register(name)
    def _emit(ctx, ins, attrs, _fn=fn):
        x, y = ins["X"][0], ins["Y"][0]
        x, y = _paddle_broadcast(x, y, attrs.get("axis", -1))
        return {"Out": [_fn(x, y)]}

    return _emit


_ew("elementwise_add", jnp.add)
_ew("elementwise_sub", jnp.subtract)
_ew("elementwise_mul", jnp.multiply)
_ew("elementwise_div", jnp.divide)
_ew("elementwise_min", jnp.minimum)
_ew("elementwise_max", jnp.maximum)
_ew("elementwise_pow", jnp.power)
_ew("elementwise_mod", jnp.mod)
_ew("elementwise_floordiv", jnp.floor_divide)


@register("sum")
def sum_op(ctx, ins, attrs):
    """Add N tensors (grad accumulation op; reference sum_op.cc)."""
    xs = ins["X"]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {"Out": [out]}


@register("matmul")
def matmul(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    tx = attrs.get("transpose_X", False)
    ty = attrs.get("transpose_Y", False)
    alpha = attrs.get("alpha", 1.0)
    # transpose of a 1-D operand is the identity; jnp.matmul already gives
    # vec@mat -> (n,) and mat@vec -> (m,) like the reference
    if tx and x.ndim > 1:
        x = jnp.swapaxes(x, -1, -2)
    if ty and y.ndim > 1:
        y = jnp.swapaxes(y, -1, -2)
    out = jnp.matmul(x, y)
    if alpha != 1.0:
        out = out * jnp.asarray(alpha, out.dtype)
    return {"Out": [out]}


@register("matmul_v2")
def matmul_v2(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    if attrs.get("trans_x", False):
        x = jnp.swapaxes(x, -1, -2)
    if attrs.get("trans_y", False):
        y = jnp.swapaxes(y, -1, -2)
    return {"Out": [jnp.matmul(x, y)]}


@register("mul")
def mul(ctx, ins, attrs):
    """Flattening matmul (reference mul_op.cc): x flattened at
    x_num_col_dims, y at y_num_col_dims, then 2-D matmul."""
    x, y = ins["X"][0], ins["Y"][0]
    xn = attrs.get("x_num_col_dims", 1)
    yn = attrs.get("y_num_col_dims", 1)
    xs, ys = x.shape, y.shape
    x2 = x.reshape((int(np.prod(xs[:xn])), int(np.prod(xs[xn:]))))
    y2 = y.reshape((int(np.prod(ys[:yn])), int(np.prod(ys[yn:]))))
    out = x2 @ y2
    return {"Out": [out.reshape(tuple(xs[:xn]) + tuple(ys[yn:]))]}


@register("dot")
def dot(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    return {"Out": [jnp.sum(x * y, axis=-1, keepdims=x.ndim > 1)]}


# ---------------------------------------------------------------------------
# activations (reference activation_op.cc registers these as separate ops)
# ---------------------------------------------------------------------------


def _act(name, fn):
    @register(name)
    def _emit(ctx, ins, attrs, _fn=fn):
        return {"Out": [_fn(ins["X"][0], attrs)]}

    return _emit


_act("relu", lambda x, a: jax.nn.relu(x))
_act("sigmoid", lambda x, a: jax.nn.sigmoid(x))
_act("tanh", lambda x, a: jnp.tanh(x))
_act("exp", lambda x, a: jnp.exp(x))
_act("log", lambda x, a: jnp.log(x))
_act("log2", lambda x, a: jnp.log2(x))
_act("log10", lambda x, a: jnp.log10(x))
_act("log1p", lambda x, a: jnp.log1p(x))
_act("sqrt", lambda x, a: jnp.sqrt(x))
_act("rsqrt", lambda x, a: jax.lax.rsqrt(x))
_act("abs", lambda x, a: jnp.abs(x))
_act("ceil", lambda x, a: jnp.ceil(x))
_act("floor", lambda x, a: jnp.floor(x))
_act("round", lambda x, a: jnp.round(x))
_act("cos", lambda x, a: jnp.cos(x))
_act("sin", lambda x, a: jnp.sin(x))
_act("tan", lambda x, a: jnp.tan(x))
_act("acos", lambda x, a: jnp.arccos(x))
_act("asin", lambda x, a: jnp.arcsin(x))
_act("atan", lambda x, a: jnp.arctan(x))
_act("sinh", lambda x, a: jnp.sinh(x))
_act("cosh", lambda x, a: jnp.cosh(x))
_act("square", lambda x, a: jnp.square(x))
_act("reciprocal", lambda x, a: 1.0 / x)
_act("softplus", lambda x, a: jax.nn.softplus(x))
_act("softsign", lambda x, a: jax.nn.soft_sign(x))
_act("silu", lambda x, a: jax.nn.silu(x))
_act("swish", lambda x, a: x * jax.nn.sigmoid(a.get("beta", 1.0) * x))
_act("logsigmoid", lambda x, a: jax.nn.log_sigmoid(x))
_act("relu6", lambda x, a: jnp.clip(x, 0.0, a.get("threshold", 6.0)))
_act(
    "leaky_relu",
    lambda x, a: jax.nn.leaky_relu(x, negative_slope=a.get("alpha", 0.02)),
)
_act("elu", lambda x, a: jax.nn.elu(x, alpha=a.get("alpha", 1.0)))
_act(
    "hard_sigmoid",
    lambda x, a: jnp.clip(
        a.get("slope", 0.2) * x + a.get("offset", 0.5), 0.0, 1.0
    ),
)
_act(
    "hard_swish",
    lambda x, a: x
    * jnp.clip(x + a.get("offset", 3.0), 0.0, a.get("threshold", 6.0))
    / a.get("scale", 6.0),
)
_act(
    "thresholded_relu",
    lambda x, a: jnp.where(x > a.get("threshold", 1.0), x, 0.0),
)
_act(
    "hard_shrink",
    lambda x, a: jnp.where(jnp.abs(x) > a.get("threshold", 0.5), x, 0.0),
)
_act(
    "soft_shrink",
    lambda x, a: jnp.sign(x)
    * jnp.maximum(jnp.abs(x) - a.get("lambda", 0.5), 0.0),
)
_act("erf", lambda x, a: jax.lax.erf(x))
_act(
    "gelu",
    lambda x, a: jax.nn.gelu(x, approximate=bool(a.get("approximate", False))),
)
_act("mish", lambda x, a: x * jnp.tanh(jax.nn.softplus(x)))
_act("sign", lambda x, a: jnp.sign(x))


@register("pow")
def pow_op(ctx, ins, attrs):
    return {"Out": [jnp.power(ins["X"][0], attrs.get("factor", 1.0))]}


@register("clip")
def clip(ctx, ins, attrs):
    x = ins["X"][0]
    return {"Out": [jnp.clip(x, attrs.get("min"), attrs.get("max"))]}


@register("clip_by_norm")
def clip_by_norm(ctx, ins, attrs):
    x = ins["X"][0]
    max_norm = attrs["max_norm"]
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    scale = max_norm / jnp.maximum(norm, max_norm)
    return {"Out": [x * scale]}


@register("prelu")
def prelu(ctx, ins, attrs):
    x, alpha = ins["X"][0], ins["Alpha"][0]
    mode = attrs.get("mode", "all")
    if mode == "channel":
        alpha = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    return {"Out": [jnp.where(x > 0, x, alpha * x)]}


@register("softmax")
def softmax(ctx, ins, attrs):
    axis = attrs.get("axis", -1)
    return {"Out": [jax.nn.softmax(ins["X"][0], axis=axis)]}


@register("log_softmax")
def log_softmax(ctx, ins, attrs):
    axis = attrs.get("axis", -1)
    return {"Out": [jax.nn.log_softmax(ins["X"][0], axis=axis)]}


@register("maxout")
def maxout(ctx, ins, attrs):
    x = ins["X"][0]
    groups = attrs["groups"]
    n, c = x.shape[0], x.shape[1]
    rest = x.shape[2:]
    x = x.reshape((n, c // groups, groups) + rest)
    return {"Out": [jnp.max(x, axis=2)]}


@register("isfinite", stop_gradient=True, no_vjp_grad=True)
def isfinite(ctx, ins, attrs):
    # reference isfinite_op: reduces to a single bool over all inputs
    xs = ins["X"]
    ok = jnp.asarray(True)
    for x in xs:
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(x)))
    return {"Out": [ok.reshape((1,))]}


@register("isfinite_v2", stop_gradient=True, no_vjp_grad=True)
def isfinite_v2(ctx, ins, attrs):
    return {"Out": [jnp.isfinite(ins["X"][0])]}


@register("isinf", stop_gradient=True, no_vjp_grad=True)
def isinf_reduce(ctx, ins, attrs):
    # reference overflow_op: has_inf reduces to a single bool
    return {"Out": [jnp.any(jnp.isinf(ins["X"][0])).reshape(1)]}


@register("isnan", stop_gradient=True, no_vjp_grad=True)
def isnan_reduce(ctx, ins, attrs):
    return {"Out": [jnp.any(jnp.isnan(ins["X"][0])).reshape(1)]}


@register("isnan_v2", stop_gradient=True, no_vjp_grad=True)
def isnan_v2(ctx, ins, attrs):
    return {"Out": [jnp.isnan(ins["X"][0])]}


@register("isinf_v2", stop_gradient=True, no_vjp_grad=True)
def isinf_v2(ctx, ins, attrs):
    return {"Out": [jnp.isinf(ins["X"][0])]}


@register("squared_l2_norm")
def squared_l2_norm(ctx, ins, attrs):
    x = ins["X"][0]
    return {"Out": [jnp.sum(jnp.square(x)).reshape((1,))]}


@register("p_norm")
def p_norm(ctx, ins, attrs):
    x = ins["X"][0]
    p = attrs.get("porder", 2.0)
    axis = attrs.get("axis", -1)
    keepdim = attrs.get("keepdim", False)
    out = jnp.linalg.norm(x, ord=p, axis=axis, keepdims=keepdim)
    return {"Out": [out]}


@register("addmm")
def addmm(ctx, ins, attrs):
    inp, x, y = ins["Input"][0], ins["X"][0], ins["Y"][0]
    alpha = attrs.get("Alpha", 1.0)
    beta = attrs.get("Beta", 1.0)
    return {"Out": [beta * inp + alpha * (x @ y)]}


@register("kron")
def kron(ctx, ins, attrs):
    return {"Out": [jnp.kron(ins["X"][0], ins["Y"][0])]}


@register("trace")
def trace_op(ctx, ins, attrs):
    x = ins["Input"][0]
    out = jnp.trace(
        x,
        offset=attrs.get("offset", 0),
        axis1=attrs.get("axis1", 0),
        axis2=attrs.get("axis2", 1),
    )
    return {"Out": [out]}


@register("cholesky")
def cholesky(ctx, ins, attrs):
    x = ins["X"][0]
    u = attrs.get("upper", False)
    out = jnp.linalg.cholesky(x)
    if u:
        out = jnp.swapaxes(out, -1, -2)
    return {"Out": [out]}


@register("inverse")
def inverse(ctx, ins, attrs):
    return {"Output": [jnp.linalg.inv(ins["Input"][0])]}


@register("matrix_power")
def matrix_power(ctx, ins, attrs):
    return {"Out": [jnp.linalg.matrix_power(ins["X"][0], attrs["n"])]}


@register("logsumexp")
def logsumexp(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", None)
    if axis is not None and len(axis) == 0:
        axis = None
    keepdim = attrs.get("keepdim", False)
    out = jax.scipy.special.logsumexp(
        x, axis=tuple(axis) if axis is not None else None, keepdims=keepdim
    )
    if out.ndim == 0:
        out = out.reshape((1,))  # fluid reductions keep at least rank 1
    return {"Out": [out]}


@register("cos_sim")
def cos_sim(ctx, ins, attrs):
    """Row-wise cosine similarity (reference cos_sim_op.cc): X [N,D],
    Y [N,D] or [1,D] broadcast. Out [N,1] (+ saved norms)."""
    x, y = ins["X"][0], ins["Y"][0]
    xn = jnp.sqrt(jnp.sum(jnp.square(x), axis=1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(y), axis=1, keepdims=True))
    dot_ = jnp.sum(x * y, axis=1, keepdims=True)
    out = dot_ / jnp.maximum(xn * yn, 1e-12)
    return {"Out": [out], "XNorm": [xn], "YNorm": [yn]}
