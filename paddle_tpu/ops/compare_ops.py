"""Comparison & logical ops. Parity surface: reference
operators/controlflow/compare_op.cc and logical_op.cc."""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register


def _cmp(name, fn):
    @register(name, stop_gradient=True, no_vjp_grad=True)
    def _emit(ctx, ins, attrs, _fn=fn):
        return {"Out": [_fn(ins["X"][0], ins["Y"][0])]}

    return _emit


_cmp("equal", jnp.equal)
_cmp("not_equal", jnp.not_equal)
_cmp("less_than", jnp.less)
_cmp("less_equal", jnp.less_equal)
_cmp("greater_than", jnp.greater)
_cmp("greater_equal", jnp.greater_equal)


@register("logical_and", stop_gradient=True, no_vjp_grad=True)
def logical_and(ctx, ins, attrs):
    return {"Out": [jnp.logical_and(ins["X"][0], ins["Y"][0])]}


@register("logical_or", stop_gradient=True, no_vjp_grad=True)
def logical_or(ctx, ins, attrs):
    return {"Out": [jnp.logical_or(ins["X"][0], ins["Y"][0])]}


@register("logical_xor", stop_gradient=True, no_vjp_grad=True)
def logical_xor(ctx, ins, attrs):
    return {"Out": [jnp.logical_xor(ins["X"][0], ins["Y"][0])]}


@register("logical_not", stop_gradient=True, no_vjp_grad=True)
def logical_not(ctx, ins, attrs):
    return {"Out": [jnp.logical_not(ins["X"][0])]}


@register("allclose", stop_gradient=True, no_vjp_grad=True)
def allclose(ctx, ins, attrs):
    x, y = ins["Input"][0], ins["Other"][0]
    rtol = float(attrs.get("rtol", 1e-5))
    atol = float(attrs.get("atol", 1e-8))
    out = jnp.allclose(x, y, rtol=rtol, atol=atol, equal_nan=attrs.get("equal_nan", False))
    return {"Out": [jnp.asarray(out)]}


@register("maximum")
def maximum(ctx, ins, attrs):
    return {"Out": [jnp.maximum(ins["X"][0], ins["Y"][0])]}


@register("minimum")
def minimum(ctx, ins, attrs):
    return {"Out": [jnp.minimum(ins["X"][0], ins["Y"][0])]}
