"""Fake-quantization ops (reference contrib/slim/quantization +
operators/fake_quantize_op.cc).

Simulated int8: quantize-dequantize in float with a per-tensor scale, so
training/calibration see quantization error while the math stays on the
MXU. Gradients are straight-through (identity on X) — round() has zero
derivative, so each op registers an explicit grad maker instead of the
generic vjp path.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register, set_grad_maker


def _qdq(x, scale, bits):
    qmax = float(2 ** (bits - 1) - 1)
    s = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(x / s * qmax), -qmax, qmax)
    return q * s / qmax


def _ste_grad_maker(op, out_grads, block):
    """dX = dOut (straight-through estimator)."""
    og = out_grads.get("Out")
    if og is None:
        return [], {}
    xname = op.input("X")[0]
    gname = xname + "@GRAD"
    desc = {
        "type": "assign",
        "inputs": {"X": [og[0]]},
        "outputs": {"Out": [gname]},
        "attrs": {},
    }
    return [desc], {xname: gname}


@register("fake_quantize_dequantize_abs_max", no_vjp_grad=True)
def fake_qdq_abs_max(ctx, ins, attrs):
    """Per-tensor abs-max scale from the CURRENT value (weights)."""
    x = ins["X"][0]
    bits = int(attrs.get("bit_length", 8))
    scale = jnp.max(jnp.abs(x))
    return {"Out": [_qdq(x, scale, bits)], "OutScale": [scale.reshape(1)]}


set_grad_maker("fake_quantize_dequantize_abs_max", _ste_grad_maker)


@register("fake_quantize_dequantize_moving_average_abs_max", no_vjp_grad=True)
def fake_qdq_moving_avg(ctx, ins, attrs):
    """Activation quantization with the reference's debiased EMA
    (fake_quantize_op.cc moving-average pair): accum' = rate*accum +
    absmax, state' = rate*state + 1, scale = accum'/state' — so the
    step-1 scale is ~absmax regardless of initialization. is_test reads
    the stored pair without updating."""
    x = ins["X"][0]
    accum = ins["InAccum"][0].reshape(())
    state = ins["InState"][0].reshape(())
    bits = int(attrs.get("bit_length", 8))
    rate = float(attrs.get("moving_rate", 0.9))
    absmax = jnp.max(jnp.abs(x))
    if attrs.get("is_test", False):
        new_accum, new_state = accum, state
    else:
        new_accum = rate * accum + absmax
        new_state = rate * state + 1.0
    # never-updated state (0): fall back to the live absmax
    scale = jnp.where(new_state > 0, new_accum / jnp.maximum(new_state, 1e-12),
                      absmax)
    return {
        "Out": [_qdq(x, scale, bits)],
        "OutAccum": [new_accum.reshape(1)],
        "OutState": [new_state.reshape(1)],
        "OutScale": [scale.reshape(1)],
    }


set_grad_maker("fake_quantize_dequantize_moving_average_abs_max", _ste_grad_maker)


@register("fake_quant_dequant_fixed_scale", no_vjp_grad=True)
def fake_qdq_fixed(ctx, ins, attrs):
    """Quant-dequant with a calibration-time scale (the PTQ output form)."""
    x = ins["X"][0]
    bits = int(attrs.get("bit_length", 8))
    scale = jnp.asarray(float(attrs["scale"]), x.dtype)
    return {"Out": [_qdq(x, scale, bits)]}


set_grad_maker("fake_quant_dequant_fixed_scale", _ste_grad_maker)
