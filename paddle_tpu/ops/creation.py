"""Tensor creation / initialization ops.

Parity surface: reference ops fill_constant_op.cc, uniform_random_op.cc,
gaussian_random_op.cc, truncated_gaussian_random_op.cc, assign_op.cc,
cast_op.cc, scale_op.cc, shape_op.cc, range_op.cc
(/root/reference/paddle/fluid/operators/*.cc). Random ops draw from the
functional PRNG threaded by the Executor (ctx.rng()) instead of a global
generator — deterministic per compiled step, reproducible across replays.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..fluid.dtypes import convert_dtype, runtime_dtype
from .registry import register


def _attr_dtype(attrs, default="float32"):
    return runtime_dtype(attrs.get("dtype", default))


def _attr_shape(attrs):
    return tuple(int(d) for d in attrs.get("shape", ()))


@register("fill_constant", no_vjp_grad=True)
def fill_constant(ctx, ins, attrs):
    dt = _attr_dtype(attrs)
    shape = _attr_shape(attrs)
    val = attrs.get("value", 0.0)
    if attrs.get("str_value"):
        val = float(attrs["str_value"])
    return {"Out": [jnp.full(shape, val, dtype=dt)]}


@register("fill_constant_batch_size_like", no_vjp_grad=True)
def fill_constant_batch_size_like(ctx, ins, attrs):
    ref = ins["Input"][0]
    dt = _attr_dtype(attrs)
    shape = list(_attr_shape(attrs))
    in_idx = int(attrs.get("input_dim_idx", 0))
    out_idx = int(attrs.get("output_dim_idx", 0))
    shape[out_idx] = ref.shape[in_idx]
    return {"Out": [jnp.full(tuple(shape), attrs.get("value", 0.0), dtype=dt)]}


@register("uniform_random", no_vjp_grad=True)
def uniform_random(ctx, ins, attrs):
    dt = _attr_dtype(attrs)
    shape = _attr_shape(attrs)
    lo = float(attrs.get("min", -1.0))
    hi = float(attrs.get("max", 1.0))
    out = jax.random.uniform(ctx.rng(), shape, dtype=jnp.float32, minval=lo, maxval=hi)
    return {"Out": [out.astype(dt)]}


@register("gaussian_random", no_vjp_grad=True)
def gaussian_random(ctx, ins, attrs):
    dt = _attr_dtype(attrs)
    shape = _attr_shape(attrs)
    mean = float(attrs.get("mean", 0.0))
    std = float(attrs.get("std", 1.0))
    out = mean + std * jax.random.normal(ctx.rng(), shape, dtype=jnp.float32)
    return {"Out": [out.astype(dt)]}


@register("truncated_gaussian_random", no_vjp_grad=True)
def truncated_gaussian_random(ctx, ins, attrs):
    dt = _attr_dtype(attrs)
    shape = _attr_shape(attrs)
    mean = float(attrs.get("mean", 0.0))
    std = float(attrs.get("std", 1.0))
    out = jax.random.truncated_normal(ctx.rng(), -2.0, 2.0, shape, dtype=jnp.float32)
    return {"Out": [(mean + std * out).astype(dt)]}


@register("assign")
def assign(ctx, ins, attrs):
    return {"Out": [jnp.asarray(ins["X"][0])]}


@register("cast")
def cast(ctx, ins, attrs):
    dt = runtime_dtype(attrs.get("out_dtype", attrs.get("dtype", "float32")))
    return {"Out": [ins["X"][0].astype(dt)]}


@register("scale")
def scale(ctx, ins, attrs):
    x = ins["X"][0]
    s = attrs.get("scale", 1.0)
    b = attrs.get("bias", 0.0)
    if attrs.get("bias_after_scale", True):
        return {"Out": [x * s + jnp.asarray(b, x.dtype)]}
    return {"Out": [(x + jnp.asarray(b, x.dtype)) * s]}


@register("shape", stop_gradient=True, no_vjp_grad=True)
def shape_op(ctx, ins, attrs):
    x = ins["Input"][0]
    return {"Out": [jnp.asarray(np.array(x.shape, dtype=np.int32))]}


@register("range", no_vjp_grad=True)
def range_op(ctx, ins, attrs):
    # XLA needs static shapes: bounds are attrs, not tensors (the layers API
    # converts python scalars; tensor bounds would make the shape dynamic).
    start = attrs["start"]
    end = attrs["end"]
    step = attrs.get("step", 1)
    dt = _attr_dtype(attrs, "int64")
    return {"Out": [jnp.arange(start, end, step, dtype=dt)]}


@register("fill_zeros_like", no_vjp_grad=True)
def fill_zeros_like(ctx, ins, attrs):
    return {"Out": [jnp.zeros_like(ins["X"][0])]}


@register("fill_any_like", no_vjp_grad=True)
def fill_any_like(ctx, ins, attrs):
    dt = attrs.get("dtype")
    x = ins["X"][0]
    dtype = convert_dtype(dt) if dt is not None else x.dtype
    return {"Out": [jnp.full_like(x, attrs.get("value", 0.0), dtype=dtype)]}


@register("eye", no_vjp_grad=True)
def eye(ctx, ins, attrs):
    dt = _attr_dtype(attrs)
    n = int(attrs["num_rows"])
    m = int(attrs.get("num_columns", n))
    return {"Out": [jnp.eye(n, m, dtype=dt)]}


@register("assign_value", no_vjp_grad=True)
def assign_value(ctx, ins, attrs):
    dt = _attr_dtype(attrs)
    shape = _attr_shape(attrs)
    vals = attrs.get("values")
    if vals is None:
        vals = attrs.get("fp32_values") or attrs.get("int32_values") or attrs.get("int64_values")
    arr = np.asarray(vals, dtype=dt).reshape(shape)
    return {"Out": [jnp.asarray(arr)]}


@register("increment")
def increment(ctx, ins, attrs):
    x = ins["X"][0]
    return {"Out": [x + jnp.asarray(attrs.get("step", 1.0), x.dtype)]}


@register("linspace", no_vjp_grad=True)
def linspace(ctx, ins, attrs):
    dt = _attr_dtype(attrs)
    out = jnp.linspace(
        attrs["start"], attrs["stop"], int(attrs["num"]), dtype=dt
    )
    return {"Out": [out]}
