"""Collective ops: c_allreduce_* / c_broadcast / c_allgather / c_reducescatter.

Parity surface: /root/reference/paddle/fluid/operators/collective/
(c_allreduce_op.h:73-106 calls ncclAllReduce on the ring keyed by ring_id;
c_gen_nccl_id_op.cc + c_comm_init_op.cc bootstrap the rings).

TPU-native design: there are no NCCL rings — a collective is an HLO op over
a named mesh axis, and XLA schedules it on ICI/DCN. `ring_id` maps to a
mesh axis name through EmitContext.axis_env, which is populated when the
op is emitted inside a manual-SPMD region (shard_map — pipeline stages,
ring attention, and the paddle_tpu.distributed functional API). Emitted
outside any axis binding (the whole-program GSPMD path, where XLA inserts
collectives from shardings, or a world-size-1 run) each op degrades to its
single-participant semantics. Bootstrap ops (c_gen_nccl_id, c_comm_init)
are no-ops kept for program compatibility: the JAX distributed coordination
service replaces the NCCL-id gRPC exchange.

Numerics are delegated to paddle_tpu.distributed — one implementation per
collective.
"""
from __future__ import annotations

from .registry import register


def _axis(ctx, attrs):
    return ctx.axis_env.get(int(attrs.get("ring_id", 0)))


def _allreduce(op_name):
    def emit(ctx, ins, attrs):
        from .. import distributed as dist

        x = ins["X"][0]
        ax = _axis(ctx, attrs)
        if ax is None:
            return {"Out": [x]}
        return {"Out": [dist.all_reduce(x, op=op_name, group=ax)]}

    return emit


register("c_allreduce_sum")(_allreduce("sum"))
register("c_allreduce_max")(_allreduce("max"))
register("c_allreduce_min")(_allreduce("min"))
register("c_allreduce_prod")(_allreduce("prod"))


@register("c_broadcast")
def c_broadcast(ctx, ins, attrs):
    from .. import distributed as dist

    x = ins["X"][0]
    ax = _axis(ctx, attrs)
    if ax is None:
        return {"Out": [x]}
    return {"Out": [dist.broadcast(x, src=int(attrs.get("root", 0)), group=ax)]}


@register("c_allgather")
def c_allgather(ctx, ins, attrs):
    from .. import distributed as dist

    x = ins["X"][0]
    ax = _axis(ctx, attrs)
    if ax is None:
        return {"Out": [x]}
    return {"Out": [dist.all_gather(x, group=ax)]}


@register("c_reducescatter")
def c_reducescatter(ctx, ins, attrs):
    from .. import distributed as dist

    x = ins["X"][0]
    ax = _axis(ctx, attrs)
    if ax is None:
        return {"Out": [x]}
    return {"Out": [dist.reduce_scatter(x, group=ax)]}


@register("c_identity")
def c_identity(ctx, ins, attrs):
    return {"Out": [ins["X"][0]]}


def _noop(ctx, ins, attrs):
    out = ins.get("X")
    return {"Out": [out[0]]} if out else {}


# stream-sync and bootstrap ops: single-program XLA has no separate
# comm/calc streams and no NCCL-id exchange — kept as no-ops for parity
register("c_sync_calc_stream", no_vjp_grad=True)(_noop)
register("c_sync_comm_stream", no_vjp_grad=True)(_noop)
register("c_gen_nccl_id", no_vjp_grad=True, no_infer=True)(lambda ctx, ins, attrs: {})
register("c_comm_init", no_vjp_grad=True, no_infer=True)(lambda ctx, ins, attrs: {})
register("c_comm_init_all", no_vjp_grad=True, no_infer=True)(lambda ctx, ins, attrs: {})


@register("c_wait_compute", no_vjp_grad=True)
def c_wait_compute(ctx, ins, attrs):
    return _noop(ctx, ins, attrs)


@register("c_wait_comm", no_vjp_grad=True)
def c_wait_comm(ctx, ins, attrs):
    return _noop(ctx, ins, attrs)
