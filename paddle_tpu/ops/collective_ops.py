"""Collective ops: c_allreduce_* / c_broadcast / c_allgather / c_reducescatter.

Parity surface: /root/reference/paddle/fluid/operators/collective/
(c_allreduce_op.h:73-106 calls ncclAllReduce on the ring keyed by ring_id;
c_gen_nccl_id_op.cc + c_comm_init_op.cc bootstrap the rings).

TPU-native design: there are no NCCL rings — a collective is an HLO op over
a named mesh axis, and XLA schedules it on ICI/DCN. `ring_id` maps to a
mesh axis name through EmitContext.axis_env, which is populated when the
op is emitted inside a manual-SPMD region (shard_map — pipeline stages,
ring attention, and the paddle_tpu.distributed functional API). Emitted
outside any axis binding (the whole-program GSPMD path, where XLA inserts
collectives from shardings, or a world-size-1 run) each op degrades to its
single-participant semantics. Bootstrap ops (c_gen_nccl_id, c_comm_init)
are no-ops kept for program compatibility: the JAX distributed coordination
service replaces the NCCL-id gRPC exchange.

Numerics are delegated to paddle_tpu.distributed — one implementation per
collective.
"""
from __future__ import annotations

from .registry import register


def _axis(ctx, attrs):
    return ctx.axis_env.get(int(attrs.get("ring_id", 0)))


def _allreduce(op_name):
    def emit(ctx, ins, attrs):
        from .. import distributed as dist

        x = ins["X"][0]
        ax = _axis(ctx, attrs)
        if ax is None:
            return {"Out": [x]}
        return {"Out": [dist.all_reduce(x, op=op_name, group=ax)]}

    return emit


register("c_allreduce_sum")(_allreduce("sum"))
register("c_allreduce_max")(_allreduce("max"))
register("c_allreduce_min")(_allreduce("min"))
register("c_allreduce_prod")(_allreduce("prod"))


@register("c_broadcast")
def c_broadcast(ctx, ins, attrs):
    from .. import distributed as dist

    x = ins["X"][0]
    ax = _axis(ctx, attrs)
    if ax is None:
        return {"Out": [x]}
    return {"Out": [dist.broadcast(x, src=int(attrs.get("root", 0)), group=ax)]}


@register("c_allgather")
def c_allgather(ctx, ins, attrs):
    from .. import distributed as dist

    x = ins["X"][0]
    ax = _axis(ctx, attrs)
    if ax is None:
        return {"Out": [x]}
    return {"Out": [dist.all_gather(x, group=ax)]}


@register("c_reducescatter")
def c_reducescatter(ctx, ins, attrs):
    from .. import distributed as dist

    x = ins["X"][0]
    ax = _axis(ctx, attrs)
    if ax is None:
        return {"Out": [x]}
    return {"Out": [dist.reduce_scatter(x, group=ax)]}


@register("c_identity")
def c_identity(ctx, ins, attrs):
    return {"Out": [ins["X"][0]]}


def _noop(ctx, ins, attrs):
    out = ins.get("X")
    return {"Out": [out[0]]} if out else {}


# stream-sync and bootstrap ops: single-program XLA has no separate
# comm/calc streams and no NCCL-id exchange — kept as no-ops for parity
register("c_sync_calc_stream", no_vjp_grad=True)(_noop)
register("c_sync_comm_stream", no_vjp_grad=True)(_noop)
register("c_gen_nccl_id", no_vjp_grad=True, no_infer=True)(lambda ctx, ins, attrs: {})
register("c_comm_init", no_vjp_grad=True, no_infer=True)(lambda ctx, ins, attrs: {})
register("c_comm_init_all", no_vjp_grad=True, no_infer=True)(lambda ctx, ins, attrs: {})


@register("c_wait_compute", no_vjp_grad=True)
def c_wait_compute(ctx, ins, attrs):
    return _noop(ctx, ins, attrs)


@register("c_wait_comm", no_vjp_grad=True)
def c_wait_comm(ctx, ins, attrs):
    return _noop(ctx, ins, attrs)


@register("c_dcn_grad_sync", no_vjp_grad=True)
def c_dcn_grad_sync(ctx, ins, attrs):
    """Two-level multi-slice gradient sync (the TPU-era successor to the
    reference's hierarchical allreduce, platform/nccl_helper.h:185
    InitHierarchicalCtxs, and to DGC's sparse allreduce,
    details/sparse_all_reduce_op_handle.cc).

    Runs inside the executor's manual shard_map over ("dcn", inner axes):
    the local gradient is densely pmean'd over the fast inner (ICI) axes,
    then either densely pmean'd over "dcn" (use_dgc=False — hierarchical
    allreduce) or DGC-compressed across it: add the persistent
    error-feedback residual, take the top-k = (1 - sparsity) * numel
    entries by magnitude, all-gather only those k (value, index) pairs
    over the DCN axis — k floats+ints per slice instead of the full
    tensor — scatter-add into a dense buffer, and keep what was NOT sent
    as the next step's residual (error feedback makes the compression
    unbiased over time).

    Reference-parity knobs: `sparsity` (fraction dropped) and
    `rampup_begin_step` with the in-graph `Step` counter input — steps
    before the rampup boundary sync densely (DGC's warm-up), matching
    DGCMomentumOptimizer's rampup contract.

    Emitted outside a manual mesh region (world size 1), it degrades to
    identity. In/out slot `ErrorFeedback` names the same persistable var
    — shape [n_dcn, *param_shape], SHARDED over the dcn axis (each slice
    owns its own residual; declaring it replicated would silently
    collapse the per-slice residuals on any metadata-trusting reshard)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    g = ins["X"][0]
    manual = getattr(ctx, "manual_axes", None) or ()
    dcn_axis = attrs.get("dcn_axis", "dcn")
    inner = tuple(a for a in manual if a != dcn_axis)
    outs = {}
    if dcn_axis not in manual:
        outs["Out"] = [g]
        if "ErrorFeedback" in ins:
            outs["ErrorFeedback"] = [ins["ErrorFeedback"][0]]
        return outs
    if inner:
        g = lax.pmean(g, inner)
    if attrs.get("intra_only", False):
        # LocalSGD regime: gradients sync only WITHIN the slice (fast
        # ICI); parameters diverge per slice and are averaged over the
        # slow DCN axis every k steps by c_dcn_localsgd_sync
        outs["Out"] = [g]
        return outs
    # wire_dtype (fleet sets bfloat16 under AMP — the reference
    # fp16_allreduce meta-optimizer's analog): the ICI-level mean above
    # stays full precision; only the SLOW dcn hop is quantized, halving
    # DCN traffic. The result is cast back to the gradient dtype.
    wire = attrs.get("wire_dtype", "") or ""
    if not attrs.get("use_dgc", False):
        gw = g.astype(wire) if wire else g
        outs["Out"] = [lax.pmean(gw, dcn_axis).astype(g.dtype)]
        if "ErrorFeedback" in ins:
            outs["ErrorFeedback"] = [ins["ErrorFeedback"][0]]
        return outs
    n_dcn = lax.psum(jnp.ones((), jnp.float32), dcn_axis)
    e3 = ins["ErrorFeedback"][0]  # local view [1, *param_shape]
    e = e3[0]
    acc = (g + e).astype(jnp.float32)
    flat = acc.reshape(-1)
    sparsity = float(attrs.get("sparsity", 0.999))
    k = max(1, int(round(flat.size * (1.0 - sparsity))))
    _, topi = lax.top_k(jnp.abs(flat), k)
    vals = flat[topi]
    if wire:
        # quantize the transmitted values; the residual below keeps the
        # UNSENT remainder (incl. quantization error) as error feedback,
        # so the compression stays unbiased over time
        vals = vals.astype(wire)
    sent = jnp.zeros_like(flat).at[topi].set(vals.astype(flat.dtype))
    e_new = (flat - sent).reshape(acc.shape)
    all_vals = lax.all_gather(vals, dcn_axis)  # [n_dcn, k] on the wire
    all_idx = lax.all_gather(topi, dcn_axis)
    sparse_sync = jnp.zeros_like(flat).at[all_idx.reshape(-1)].add(
        all_vals.reshape(-1).astype(flat.dtype)
    ).reshape(acc.shape) / n_dcn
    rampup = int(attrs.get("rampup_begin_step", 0))
    if rampup > 0 and "Step" in ins:
        # DGC warm-up: dense sync (and zero residual) until the boundary
        ramping = ins["Step"][0].reshape(()) < rampup
        dense_sync = lax.pmean(acc, dcn_axis)
        out = jnp.where(ramping, dense_sync, sparse_sync)
        e_new = jnp.where(ramping, jnp.zeros_like(e_new), e_new)
    else:
        out = sparse_sync
    outs["Out"] = [out.astype(ins["X"][0].dtype)]
    outs["ErrorFeedback"] = [e_new[None].astype(e3.dtype)]
    return outs


@register("dcn_expand_param", no_vjp_grad=True)
def dcn_expand_param(ctx, ins, attrs):
    """Startup-time LocalSGD storage expansion: tile an initialized
    parameter to [n_dcn, *shape] so the training program can shard it
    over "dcn" (per-slice divergent weights — reference LocalSGD,
    transpiler/collective.py:270, keeps per-worker weights the same
    way). Idempotent: an already-expanded value passes through."""
    import jax.numpy as jnp

    x = ins["X"][0]
    n = int(attrs["n_dcn"])
    rank = int(attrs["param_rank"])
    if x.ndim == rank + 1 and x.shape[0] == n:
        return {"Out": [x]}
    return {"Out": [jnp.tile(x[None], (n,) + (1,) * x.ndim)]}


@register("c_dcn_localsgd_sync", no_vjp_grad=True)
def c_dcn_localsgd_sync(ctx, ins, attrs):
    """LocalSGD consensus step (reference transpiler/collective.py:270
    LocalSGD transpile + DistributedStrategy localsgd_configs): every
    `k_steps` optimizer steps, average the per-slice divergent
    parameters over the slow "dcn" axis; other steps pass through. The
    replicated in-graph Step counter makes every slice take the same
    branch, so the collective inside lax.cond is safe."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    p = ins["X"][0]
    manual = getattr(ctx, "manual_axes", None) or ()
    dcn_axis = attrs.get("dcn_axis", "dcn")
    if dcn_axis not in manual:
        return {"Out": [p]}
    k = max(1, int(attrs.get("k_steps", 1)))
    step = ins["Step"][0].reshape(()).astype(jnp.int32)
    do_sync = (step % k) == (k - 1)
    out = jax.lax.cond(
        do_sync,
        lambda x: lax.pmean(x, dcn_axis),
        lambda x: x,
        p,
    )
    return {"Out": [out]}
