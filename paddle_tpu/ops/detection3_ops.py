"""Detection ops, batch 3: RPN/FPN proposals, ROI extractors, YOLOv3.

Parity surface: reference operators/detection/ — generate_proposals_op.cc,
rpn_target_assign_op.cc, retinanet_target_assign (same file),
retinanet_detection_output_op.cc, collect_fpn_proposals_op.cc,
distribute_fpn_proposals_op.cc, prroi_pool_op.cc, psroi_pool_op.cc,
roi_perspective_transform_op.cc, deformable_conv_op.cc,
deformable_psroi_pooling_op.cc, yolov3_loss_op.cc.

Static-shape contract: proposal/assignment outputs are FIXED-size and
padded (scores -inf / weights 0), with valid-count side outputs, mirroring
detection2_ops. Random subsampling (RPN) draws from the op-context PRNG
via salted keys, so retracing under vjp sees the same sample.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .detection2_ops import _iou_matrix, _nms_single
from .registry import register


def _decode_deltas(anchors, deltas, variances=None):
    """anchors [A,4] xyxy + deltas [A,4] -> boxes [A,4] (RPN convention)."""
    aw = anchors[:, 2] - anchors[:, 0] + 1.0
    ah = anchors[:, 3] - anchors[:, 1] + 1.0
    acx = anchors[:, 0] + aw * 0.5
    acy = anchors[:, 1] + ah * 0.5
    if variances is not None:
        deltas = deltas * variances
    cx = deltas[:, 0] * aw + acx
    cy = deltas[:, 1] * ah + acy
    w = jnp.exp(jnp.clip(deltas[:, 2], -10, 10)) * aw
    h = jnp.exp(jnp.clip(deltas[:, 3], -10, 10)) * ah
    return jnp.stack(
        [cx - w / 2, cy - h / 2, cx + w / 2 - 1.0, cy + h / 2 - 1.0], axis=1)


@register("generate_proposals", stop_gradient=True, no_vjp_grad=True)
def generate_proposals(ctx, ins, attrs):
    """RPN proposal generation (reference generate_proposals_op.cc):
    Scores [N, A, H, W], BboxDeltas [N, 4A, H, W], Anchors [H, W, A, 4],
    ImInfo [N, 3]. Out: RpnRois [N, post_nms_topN, 4] (zero-padded),
    RpnRoiProbs [N, post_nms_topN, 1], RpnRoisNum [N]."""
    scores = ins["Scores"][0]
    deltas = ins["BboxDeltas"][0]
    anchors = ins["Anchors"][0].reshape(-1, 4)
    variances = ins["Variances"][0].reshape(-1, 4) if ins.get("Variances") else None
    im_info = ins["ImInfo"][0]
    pre_n = int(attrs.get("pre_nms_topN", 6000))
    post_n = int(attrs.get("post_nms_topN", 1000))
    nms_thresh = float(attrs.get("nms_thresh", 0.7))
    min_size = float(attrs.get("min_size", 0.0))
    n = scores.shape[0]
    a = scores.shape[1]

    def one(sc, dl, info):
        s = sc.transpose(1, 2, 0).reshape(-1)          # [H*W*A]
        d = dl.reshape(a, 4, *dl.shape[1:]).transpose(2, 3, 0, 1).reshape(-1, 4)
        boxes = _decode_deltas(anchors, d, variances)
        h_img = info[0] / info[2]
        w_img = info[1] / info[2]
        boxes = jnp.stack([
            jnp.clip(boxes[:, 0], 0, w_img - 1),
            jnp.clip(boxes[:, 1], 0, h_img - 1),
            jnp.clip(boxes[:, 2], 0, w_img - 1),
            jnp.clip(boxes[:, 3], 0, h_img - 1)], axis=1)
        ws = boxes[:, 2] - boxes[:, 0] + 1
        hs = boxes[:, 3] - boxes[:, 1] + 1
        ok = (ws >= min_size) & (hs >= min_size)
        s = jnp.where(ok, s, -jnp.inf)
        k = min(pre_n, s.shape[0])
        top_s, order = jax.lax.top_k(s, k)
        cand = boxes[order]
        iou = _iou_matrix(cand, cand)

        def body(i, keep):
            sup = jnp.any((iou[i] > nms_thresh) & keep & (jnp.arange(k) < i))
            return keep.at[i].set(jnp.isfinite(top_s[i]) & ~sup)

        keep = jax.lax.fori_loop(0, k, body, jnp.zeros((k,), bool))
        kept_s = jnp.where(keep, top_s, -jnp.inf)
        kk = min(post_n, k)
        fin_s, fin_i = jax.lax.top_k(kept_s, kk)
        rois = cand[fin_i] * jnp.isfinite(fin_s)[:, None]
        probs = jnp.where(jnp.isfinite(fin_s), fin_s, 0.0)[:, None]
        pad = post_n - kk
        if pad > 0:
            rois = jnp.concatenate([rois, jnp.zeros((pad, 4))], axis=0)
            probs = jnp.concatenate([probs, jnp.zeros((pad, 1))], axis=0)
        return rois, probs, jnp.isfinite(fin_s).sum().astype(jnp.int32)

    rois, probs, counts = jax.vmap(one)(scores, deltas, im_info)
    return {"RpnRois": [rois], "RpnRoiProbs": [probs], "RpnRoisNum": [counts]}


@register("rpn_target_assign", stop_gradient=True, no_vjp_grad=True)
def rpn_target_assign(ctx, ins, attrs):
    """RPN training targets (reference rpn_target_assign_op.cc), dense:
    Anchor [A, 4], GtBoxes [N, G, 4] (zero pads), ImInfo [N, 3].
    Outputs per anchor: Label [N, A] (1 fg / 0 bg / -1 ignore after
    subsampling), LocTarget [N, A, 4], LocWeight/ScoreWeight masks.
    Subsampling keeps rpn_batch_size_per_im anchors at fg_fraction."""
    anchor = ins["Anchor"][0]
    gt = ins["GtBoxes"][0]
    pos_thr = float(attrs.get("rpn_positive_overlap", 0.7))
    neg_thr = float(attrs.get("rpn_negative_overlap", 0.3))
    batch = int(attrs.get("rpn_batch_size_per_im", 256))
    fg_frac = float(attrs.get("rpn_fg_fraction", 0.5))
    a = anchor.shape[0]
    key = ctx.salted_rng(int(attrs.get("rng_salt", 17)))

    def one(gtb, k):
        valid_gt = (jnp.abs(gtb).sum(axis=1) > 0)
        iou = _iou_matrix(gtb, anchor)                  # [G, A]
        iou = jnp.where(valid_gt[:, None], iou, -1.0)
        best_per_anchor = jnp.max(iou, axis=0)
        best_gt = jnp.argmax(iou, axis=0)
        # anchors that are the argmax for some gt are positive too
        best_per_gt = jnp.max(iou, axis=1, keepdims=True)
        forced = jnp.any((iou == best_per_gt) & (best_per_gt > 0), axis=0)
        pos = (best_per_anchor >= pos_thr) | forced
        neg = (best_per_anchor < neg_thr) & ~pos
        # random subsample to the batch budget
        r = jax.random.uniform(k, (a,))
        n_fg = int(batch * fg_frac)
        fg_score = jnp.where(pos, r, -jnp.inf)
        _, fg_idx = jax.lax.top_k(fg_score, min(n_fg, a))
        fg_keep = jnp.zeros((a,), bool).at[fg_idx].set(True) & pos
        n_bg = batch - n_fg
        bg_score = jnp.where(neg, r, -jnp.inf)
        _, bg_idx = jax.lax.top_k(bg_score, min(n_bg, a))
        bg_keep = jnp.zeros((a,), bool).at[bg_idx].set(True) & neg
        label = jnp.where(fg_keep, 1, jnp.where(bg_keep, 0, -1))
        tgt = gtb[best_gt]
        aw = anchor[:, 2] - anchor[:, 0] + 1.0
        ah = anchor[:, 3] - anchor[:, 1] + 1.0
        acx = anchor[:, 0] + aw * 0.5
        acy = anchor[:, 1] + ah * 0.5
        tw = tgt[:, 2] - tgt[:, 0] + 1.0
        th = tgt[:, 3] - tgt[:, 1] + 1.0
        tcx = tgt[:, 0] + tw * 0.5
        tcy = tgt[:, 1] + th * 0.5
        loc = jnp.stack([
            (tcx - acx) / aw, (tcy - acy) / ah,
            jnp.log(jnp.maximum(tw / aw, 1e-10)),
            jnp.log(jnp.maximum(th / ah, 1e-10))], axis=1)
        return (label.astype(jnp.int32), loc,
                fg_keep.astype(jnp.float32)[:, None],
                (fg_keep | bg_keep).astype(jnp.float32)[:, None])

    n = gt.shape[0]
    keys = jax.random.split(key, n)
    label, loc, locw, scorew = jax.vmap(one)(gt, keys)
    return {"Label": [label], "LocTarget": [loc],
            "LocWeight": [locw], "ScoreWeight": [scorew]}


@register("retinanet_target_assign", stop_gradient=True, no_vjp_grad=True)
def retinanet_target_assign(ctx, ins, attrs):
    """RetinaNet targets (reference retinanet flavor of
    rpn_target_assign_op.cc): NO subsampling (focal loss uses all), pos
    iou >= positive_overlap, neg < negative_overlap, rest ignored; also
    emits per-anchor class labels and the foreground count."""
    anchor = ins["Anchor"][0]
    gt = ins["GtBoxes"][0]
    gt_labels = ins["GtLabels"][0].astype(jnp.int32)
    pos_thr = float(attrs.get("positive_overlap", 0.5))
    neg_thr = float(attrs.get("negative_overlap", 0.4))

    def one(gtb, gtl):
        valid_gt = gtl > 0
        iou = _iou_matrix(gtb, anchor)
        iou = jnp.where(valid_gt[:, None], iou, -1.0)
        best = jnp.max(iou, axis=0)
        best_gt = jnp.argmax(iou, axis=0)
        best_per_gt = jnp.max(iou, axis=1, keepdims=True)
        forced = jnp.any((iou == best_per_gt) & (best_per_gt > 0), axis=0)
        pos = (best >= pos_thr) | forced
        neg = (best < neg_thr) & ~pos
        cls = jnp.where(pos, gtl[best_gt], 0)
        label = jnp.where(pos, 1, jnp.where(neg, 0, -1))
        tgt = gtb[best_gt]
        aw = anchor[:, 2] - anchor[:, 0] + 1.0
        ah = anchor[:, 3] - anchor[:, 1] + 1.0
        loc = jnp.stack([
            (tgt[:, 0] + (tgt[:, 2] - tgt[:, 0]) / 2
             - anchor[:, 0] - aw / 2) / aw,
            (tgt[:, 1] + (tgt[:, 3] - tgt[:, 1]) / 2
             - anchor[:, 1] - ah / 2) / ah,
            jnp.log(jnp.maximum((tgt[:, 2] - tgt[:, 0] + 1.0) / aw, 1e-10)),
            jnp.log(jnp.maximum((tgt[:, 3] - tgt[:, 1] + 1.0) / ah, 1e-10)),
        ], axis=1)
        return (label.astype(jnp.int32), cls, loc,
                pos.astype(jnp.float32)[:, None],
                pos.sum().astype(jnp.int32))

    label, cls, loc, locw, fg = jax.vmap(one)(gt, gt_labels)
    return {"Label": [label], "ClsLabel": [cls], "LocTarget": [loc],
            "LocWeight": [locw], "ForegroundNumber": [fg]}


@register("collect_fpn_proposals", stop_gradient=True, no_vjp_grad=True)
def collect_fpn_proposals(ctx, ins, attrs):
    """Merge per-level proposals by score (reference
    collect_fpn_proposals_op.cc): MultiLevelRois (list of [N, Ri, 4]),
    MultiLevelScores (list of [N, Ri, 1]) -> FpnRois [N, post_nms_topN, 4]."""
    rois = jnp.concatenate(ins["MultiLevelRois"], axis=1)
    scores = jnp.concatenate(ins["MultiLevelScores"], axis=1)[..., 0]
    post_n = int(attrs.get("post_nms_topN", 1000))

    def one(r, s):
        k = min(post_n, s.shape[0])
        top_s, idx = jax.lax.top_k(s, k)
        out = r[idx]
        if k < post_n:
            out = jnp.concatenate([out, jnp.zeros((post_n - k, 4))], axis=0)
        return out, (top_s > 0).sum().astype(jnp.int32)

    out, counts = jax.vmap(one)(rois, scores)
    return {"FpnRois": [out], "RoisNum": [counts]}


@register("distribute_fpn_proposals", stop_gradient=True, no_vjp_grad=True)
def distribute_fpn_proposals(ctx, ins, attrs):
    """Route each ROI to its FPN level by scale (reference
    distribute_fpn_proposals_op.cc): level = floor(log2(sqrt(area) /
    refer_scale)) + refer_level, clamped. Dense: each level output is
    ROI-count sized with non-member rows zeroed (Mask i), plus
    RestoreIndex mapping."""
    rois = ins["FpnRois"][0]  # [R, 4]
    min_level = int(attrs["min_level"])
    max_level = int(attrs["max_level"])
    refer_level = int(attrs["refer_level"])
    refer_scale = float(attrs["refer_scale"])
    w = jnp.maximum(rois[:, 2] - rois[:, 0], 0.0)
    h = jnp.maximum(rois[:, 3] - rois[:, 1], 0.0)
    scale = jnp.sqrt(w * h)
    lvl = jnp.floor(jnp.log2(jnp.maximum(scale, 1e-6) / refer_scale + 1e-12)) \
        + refer_level
    lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
    outs, masks = [], []
    for level in range(min_level, max_level + 1):
        m = (lvl == level).astype(rois.dtype)
        outs.append(rois * m[:, None])
        masks.append(m)
    restore = jnp.argsort(
        jnp.argsort(lvl * rois.shape[0] + jnp.arange(rois.shape[0])))
    return {"MultiFpnRois": outs,
            "LevelMask": [jnp.stack(masks, axis=0)],
            "RestoreIndex": [restore[:, None].astype(jnp.int32)]}


def _bilinear_at(img, ys, xs):
    """img [C, H, W]; ys/xs [...]: bilinear samples [C, ...] (0 outside)."""
    c, h, w = img.shape
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)

    def tap(yi, xi):
        ok = (yi >= 0) & (yi <= h - 1) & (xi >= 0) & (xi <= w - 1)
        yc = jnp.clip(yi.astype(jnp.int32), 0, h - 1)
        xc = jnp.clip(xi.astype(jnp.int32), 0, w - 1)
        v = img[:, yc, xc]
        return v * ok.astype(img.dtype)

    wy = (ys - y0).astype(img.dtype)
    wx = (xs - x0).astype(img.dtype)
    return (tap(y0, x0) * (1 - wy) * (1 - wx)
            + tap(y0, x0 + 1) * (1 - wy) * wx
            + tap(y0 + 1, x0) * wy * (1 - wx)
            + tap(y0 + 1, x0 + 1) * wy * wx)


@register("prroi_pool")
def prroi_pool(ctx, ins, attrs):
    """Precise ROI pooling (reference prroi_pool_op.cc): the exact
    bilinear integral is approximated by a dense 4x4 sample grid per bin
    (converges to the integral; fully differentiable)."""
    x, rois = ins["X"][0], ins["ROIs"][0]
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    scale = float(attrs.get("spatial_scale", 1.0))
    samples = 4
    batch_ids = (ins["BatchId"][0].astype(jnp.int32).reshape(-1)
                 if ins.get("BatchId")
                 else jnp.zeros((rois.shape[0],), jnp.int32))

    def one(roi, bid):
        x1, y1, x2, y2 = roi * scale
        bh = jnp.maximum(y2 - y1, 1e-6) / ph
        bw = jnp.maximum(x2 - x1, 1e-6) / pw
        iy = (jnp.arange(ph * samples) + 0.5) / samples  # in bin units
        ix = (jnp.arange(pw * samples) + 0.5) / samples
        ys = y1 + iy * bh
        xs = x1 + ix * bw
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        vals = _bilinear_at(x[bid], gy, gx)  # [C, ph*s, pw*s]
        c = vals.shape[0]
        vals = vals.reshape(c, ph, samples, pw, samples)
        return vals.mean(axis=(2, 4))

    return {"Out": [jax.vmap(one)(rois, batch_ids)]}


@register("psroi_pool")
def psroi_pool(ctx, ins, attrs):
    """Position-sensitive ROI pooling (reference psroi_pool_op.cc):
    input channels C = output_channels * ph * pw; bin (i, j) of output
    channel k averages input channel k*ph*pw + i*pw + j over the bin."""
    x, rois = ins["X"][0], ins["ROIs"][0]
    oc = int(attrs["output_channels"])
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    scale = float(attrs.get("spatial_scale", 1.0))
    samples = 2
    batch_ids = (ins["BatchId"][0].astype(jnp.int32).reshape(-1)
                 if ins.get("BatchId")
                 else jnp.zeros((rois.shape[0],), jnp.int32))

    def one2(roi, bid):
        x1, y1, x2, y2 = roi * scale
        bh = jnp.maximum(y2 - y1, 0.1) / ph
        bw = jnp.maximum(x2 - x1, 0.1) / pw
        img = x[bid].reshape(oc, ph * pw, x.shape[2], x.shape[3])
        outs = []
        for i in range(ph):
            for j in range(pw):
                iy = y1 + (i + (jnp.arange(samples) + 0.5) / samples) * bh
                ix = x1 + (j + (jnp.arange(samples) + 0.5) / samples) * bw
                gy, gx = jnp.meshgrid(iy, ix, indexing="ij")
                v = _bilinear_at(img[:, i * pw + j], gy, gx)  # [oc, s, s]
                outs.append(v.mean(axis=(1, 2)))
        return jnp.stack(outs, axis=1).reshape(oc, ph, pw)

    return {"Out": [jax.vmap(one2)(rois, batch_ids)]}


@register("roi_perspective_transform")
def roi_perspective_transform(ctx, ins, attrs):
    """Warp quadrilateral ROIs to a fixed rectangle (reference
    roi_perspective_transform_op.cc): per-ROI homography solved from the
    4 corners, then bilinear sampling."""
    x, rois = ins["X"][0], ins["ROIs"][0]  # rois [R, 8]: 4 (x, y) corners
    th = int(attrs.get("transformed_height", 1))
    tw = int(attrs.get("transformed_width", 1))
    scale = float(attrs.get("spatial_scale", 1.0))
    batch_ids = (ins["BatchId"][0].astype(jnp.int32).reshape(-1)
                 if ins.get("BatchId")
                 else jnp.zeros((rois.shape[0],), jnp.int32))

    def one(quad, bid):
        src = quad.reshape(4, 2) * scale  # (x, y) corners: tl, tr, br, bl
        dst = jnp.asarray(
            [[0, 0], [tw - 1, 0], [tw - 1, th - 1], [0, th - 1]], jnp.float32)
        # solve homography dst -> src (8 unknowns)
        rows = []
        rhs = []
        for i in range(4):
            dx, dy = dst[i, 0], dst[i, 1]
            sx, sy = src[i, 0], src[i, 1]
            rows.append(jnp.stack([dx, dy, jnp.asarray(1.0), jnp.asarray(0.0),
                                   jnp.asarray(0.0), jnp.asarray(0.0),
                                   -dx * sx, -dy * sx]))
            rhs.append(sx)
            rows.append(jnp.stack([jnp.asarray(0.0), jnp.asarray(0.0),
                                   jnp.asarray(0.0), dx, dy, jnp.asarray(1.0),
                                   -dx * sy, -dy * sy]))
            rhs.append(sy)
        A = jnp.stack(rows)
        bvec = jnp.stack(rhs)
        hcoef = jnp.linalg.solve(A + 1e-8 * jnp.eye(8), bvec)
        hmat = jnp.concatenate([hcoef, jnp.ones((1,))]).reshape(3, 3)
        gy, gx = jnp.meshgrid(jnp.arange(th, dtype=jnp.float32),
                              jnp.arange(tw, dtype=jnp.float32),
                              indexing="ij")
        ones = jnp.ones_like(gx)
        pts = jnp.stack([gx, gy, ones], axis=0).reshape(3, -1)
        warped = hmat @ pts
        sx = warped[0] / jnp.maximum(warped[2], 1e-8)
        sy = warped[1] / jnp.maximum(warped[2], 1e-8)
        vals = _bilinear_at(x[bid], sy.reshape(th, tw), sx.reshape(th, tw))
        return vals

    return {"Out": [jax.vmap(one)(rois, batch_ids)]}


@register("deformable_conv")
def deformable_conv(ctx, ins, attrs):
    """Deformable convolution v1/v2 (reference deformable_conv_op.cc):
    Offset [N, 2*dg*kh*kw, Ho, Wo] shifts each kernel tap's sampling
    point; optional Mask (v2) modulates each tap. Implemented as bilinear
    gather into an im2col tensor + a dense matmul — the MXU-friendly
    lowering of the CUDA kernel's per-tap sampling."""
    x = ins["Input"][0]
    offset = ins["Offset"][0]
    w = ins["Filter"][0]  # [Co, C/g, kh, kw]
    mask = ins["Mask"][0] if ins.get("Mask") else None
    strides = [int(s) for s in attrs.get("strides", [1, 1])]
    paddings = [int(p) for p in attrs.get("paddings", [0, 0])]
    dilations = [int(d) for d in attrs.get("dilations", [1, 1])]
    groups = int(attrs.get("groups", 1) or 1)
    dg = int(attrs.get("deformable_groups", 1) or 1)
    n, c, h, wdt = x.shape
    co, _, kh, kw = w.shape
    if c % groups or c % dg or co % groups:
        raise ValueError(
            f"deformable_conv: input channels {c} must divide by both "
            f"groups={groups} and deformable_groups={dg}, and output "
            f"channels {co} by groups"
        )
    ho = (h + 2 * paddings[0] - dilations[0] * (kh - 1) - 1) // strides[0] + 1
    wo = (wdt + 2 * paddings[1] - dilations[1] * (kw - 1) - 1) // strides[1] + 1

    base_y = (jnp.arange(ho) * strides[0] - paddings[0])[:, None]
    base_x = (jnp.arange(wo) * strides[1] - paddings[1])[None, :]
    cdg = c // dg      # channels per deformable group (own offset set)
    cg = c // groups   # input channels per conv group
    cog = co // groups

    def one(img, off, m):
        cols = []
        for ki in range(kh):
            for kj in range(kw):
                vs = []
                for gd in range(dg):
                    t = 2 * (gd * kh * kw + ki * kw + kj)
                    oy = off[t]      # [Ho, Wo]
                    ox = off[t + 1]
                    ys = base_y + ki * dilations[0] + oy
                    xs = base_x + kj * dilations[1] + ox
                    v = _bilinear_at(
                        img[gd * cdg:(gd + 1) * cdg], ys, xs
                    )  # [C/dg, Ho, Wo]
                    if m is not None:
                        v = v * m[gd * kh * kw + ki * kw + kj][None]
                    vs.append(v)
                cols.append(vs[0] if dg == 1 else jnp.concatenate(vs, axis=0))
        col = jnp.stack(cols, axis=1)  # [C, K, Ho, Wo]
        outs = []
        for gi in range(groups):
            colg = col[gi * cg:(gi + 1) * cg]
            # reorder to (k-major, c-minor) to match the filter layout
            col2 = colg.reshape(cg, kh * kw, ho * wo).transpose(
                1, 0, 2).reshape(kh * kw * cg, ho * wo)
            wk = w[gi * cog:(gi + 1) * cog].transpose(0, 2, 3, 1).reshape(
                cog, kh * kw * cg)
            outs.append((wk @ col2).reshape(cog, ho, wo))
        return outs[0] if groups == 1 else jnp.concatenate(outs, axis=0)

    if mask is None:
        out = jax.vmap(lambda img, off: one(img, off, None))(x, offset)
    else:
        out = jax.vmap(one)(x, offset, mask)
    return {"Output": [out]}


@register("deformable_psroi_pooling")
def deformable_psroi_pooling(ctx, ins, attrs):
    """Deformable PS-ROI pooling (reference
    deformable_psroi_pooling_op.cc): psroi_pool with learned per-bin
    offsets (Trans [R, 2, ph, pw] scaled by trans_std)."""
    x, rois = ins["Input"][0], ins["ROIs"][0]
    trans = ins["Trans"][0] if ins.get("Trans") else None
    oc = int(attrs["output_channels"])
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    scale = float(attrs.get("spatial_scale", 1.0))
    trans_std = float(attrs.get("trans_std", 0.1))
    no_trans = bool(attrs.get("no_trans", False))
    samples = 2
    batch_ids = (ins["BatchId"][0].astype(jnp.int32).reshape(-1)
                 if ins.get("BatchId")
                 else jnp.zeros((rois.shape[0],), jnp.int32))

    def one(roi, bid, tr):
        x1, y1, x2, y2 = roi * scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bh, bw = rh / ph, rw / pw
        img = x[bid].reshape(oc, ph * pw, x.shape[2], x.shape[3])
        outs = []
        for i in range(ph):
            for j in range(pw):
                dy = 0.0 if (no_trans or tr is None) else tr[0, i, j] * trans_std * rh
                dx = 0.0 if (no_trans or tr is None) else tr[1, i, j] * trans_std * rw
                iy = y1 + (i + (jnp.arange(samples) + 0.5) / samples) * bh + dy
                ix = x1 + (j + (jnp.arange(samples) + 0.5) / samples) * bw + dx
                gy, gx = jnp.meshgrid(iy, ix, indexing="ij")
                v = _bilinear_at(img[:, i * pw + j], gy, gx)
                outs.append(v.mean(axis=(1, 2)))
        return jnp.stack(outs, axis=1).reshape(oc, ph, pw)

    if trans is None:
        out = jax.vmap(lambda r, b: one(r, b, None))(rois, batch_ids)
    else:
        out = jax.vmap(one)(rois, batch_ids, trans)
    return {"Output": [out]}


@register("yolov3_loss")
def yolov3_loss(ctx, ins, attrs):
    """YOLOv3 training loss (reference yolov3_loss_op.cc): per-cell
    objectness + class + box losses against anchor-matched ground truth,
    with an ignore mask for predictions whose best gt IoU exceeds
    ignore_thresh. X [N, A*(5+C), H, W]; GTBox [N, G, 4] (cx, cy, w, h,
    normalized), GTLabel [N, G]."""
    x = ins["X"][0]
    gt_box = ins["GTBox"][0]
    gt_label = ins["GTLabel"][0].astype(jnp.int32)
    anchors = [float(v) for v in attrs["anchors"]]
    anchor_mask = [int(v) for v in attrs.get("anchor_mask",
                                             list(range(len(anchors) // 2)))]
    class_num = int(attrs["class_num"])
    ignore_thresh = float(attrs.get("ignore_thresh", 0.7))
    downsample = int(attrs.get("downsample_ratio", 32))
    n, _, h, w = x.shape
    na = len(anchor_mask)
    input_size = downsample * h
    an_w = jnp.asarray([anchors[2 * m] for m in anchor_mask], jnp.float32)
    an_h = jnp.asarray([anchors[2 * m + 1] for m in anchor_mask], jnp.float32)

    pred = x.reshape(n, na, 5 + class_num, h, w)
    tx, ty = pred[:, :, 0], pred[:, :, 1]
    tw_p, th_p = pred[:, :, 2], pred[:, :, 3]
    tobj = pred[:, :, 4]
    tcls = pred[:, :, 5:]

    gx = (jax.nn.sigmoid(tx) + jnp.arange(w)[None, None, None, :]) / w
    gy = (jax.nn.sigmoid(ty) + jnp.arange(h)[None, None, :, None]) / h
    gw = jnp.exp(jnp.clip(tw_p, -10, 10)) * an_w[None, :, None, None] / input_size
    gh = jnp.exp(jnp.clip(th_p, -10, 10)) * an_h[None, :, None, None] / input_size

    def one(gxb, gyb, gwb, ghb, tob, tcb, txb, tyb, twb, thb, gtb, gtl):
        valid = gtl >= 0
        # pred boxes [A*H*W, 4] xyxy; gt boxes xyxy
        px1 = (gxb - gwb / 2).reshape(-1)
        py1 = (gyb - ghb / 2).reshape(-1)
        px2 = (gxb + gwb / 2).reshape(-1)
        py2 = (gyb + ghb / 2).reshape(-1)
        pbox = jnp.stack([px1, py1, px2, py2], axis=1)
        gx1 = gtb[:, 0] - gtb[:, 2] / 2
        gy1 = gtb[:, 1] - gtb[:, 3] / 2
        gx2 = gtb[:, 0] + gtb[:, 2] / 2
        gy2 = gtb[:, 1] + gtb[:, 3] / 2
        gbox = jnp.stack([gx1, gy1, gx2, gy2], axis=1)
        iou = _iou_matrix(pbox, gbox)  # [AHW, G]
        iou = jnp.where(valid[None, :], iou, 0.0)
        best = jnp.max(iou, axis=1).reshape(na, h, w)
        noobj_mask = (best < ignore_thresh).astype(jnp.float32)

        # gt assignment: responsible cell + best anchor by wh IoU
        gi = jnp.clip((gtb[:, 0] * w).astype(jnp.int32), 0, w - 1)
        gj = jnp.clip((gtb[:, 1] * h).astype(jnp.int32), 0, h - 1)
        gw_abs = gtb[:, 2] * input_size
        gh_abs = gtb[:, 3] * input_size
        inter = (jnp.minimum(gw_abs[:, None], an_w[None, :])
                 * jnp.minimum(gh_abs[:, None], an_h[None, :]))
        union = (gw_abs * gh_abs)[:, None] + (an_w * an_h)[None, :] - inter
        best_a = jnp.argmax(inter / jnp.maximum(union, 1e-10), axis=1)

        obj_tgt = jnp.zeros((na, h, w))
        loss = 0.0
        g = gtb.shape[0]
        scale_f = 2.0 - gtb[:, 2] * gtb[:, 3]  # small-box upweight
        for k in range(g):
            a_k, j_k, i_k = best_a[k], gj[k], gi[k]
            v = valid[k].astype(jnp.float32)
            sf = scale_f[k]
            tx_t = gtb[k, 0] * w - i_k
            ty_t = gtb[k, 1] * h - j_k
            tw_t = jnp.log(jnp.maximum(
                gw_abs[k] / an_w[a_k], 1e-10))
            th_t = jnp.log(jnp.maximum(gh_abs[k] / an_h[a_k], 1e-10))
            px = jax.nn.sigmoid(txb[a_k, j_k, i_k])
            py_ = jax.nn.sigmoid(tyb[a_k, j_k, i_k])
            loss += v * sf * ((px - tx_t) ** 2 + (py_ - ty_t) ** 2)
            loss += v * sf * ((twb[a_k, j_k, i_k] - tw_t) ** 2
                              + (thb[a_k, j_k, i_k] - th_t) ** 2)
            # class loss (BCE over classes)
            cls_logit = tcb[:, a_k, j_k, i_k]
            cls_tgt = jax.nn.one_hot(gtl[k], class_num)
            bce = jnp.maximum(cls_logit, 0) - cls_logit * cls_tgt + \
                jnp.log1p(jnp.exp(-jnp.abs(cls_logit)))
            loss += v * bce.sum()
            obj_tgt = obj_tgt.at[a_k, j_k, i_k].max(v)
        # objectness BCE: positives at assigned cells, negatives elsewhere
        obj_bce = jnp.maximum(tob, 0) - tob * obj_tgt + \
            jnp.log1p(jnp.exp(-jnp.abs(tob)))
        loss += (obj_bce * obj_tgt).sum()
        loss += (obj_bce * (1 - obj_tgt) * noobj_mask).sum()
        return loss

    loss = jax.vmap(one)(gx, gy, gw, gh, tobj,
                         tcls.transpose(0, 2, 1, 3, 4), tx, ty, tw_p, th_p,
                         gt_box, gt_label)
    return {"Loss": [loss]}
