"""Vision ops: 3D conv/pool, resizing, sampling grids, local response norm.

Parity surface: reference operators/ conv3d_transpose (conv_transpose_op.cc),
pool3d (pool_op.cc), interpolate family (interpolate_op.cc: bilinear/
nearest/trilinear), grid_sampler_op.cc, affine_grid_op.cc, lrn_op.cc,
unfold_op.cc, roi_pool_op.cc, pixel_shuffle_op.cc, temporal_shift_op.cc.

TPU-native notes: everything lowers to dense XLA HLO — conv_general_dilated
for conv/unfold, jax.image.resize for interpolation, gather-free bilinear
sampling written as weighted corner reads so the MXU/VPU fuse it. No
per-op CUDA kernels; grads come from the generic vjp path.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


@register("pool3d")
def pool3d(ctx, ins, attrs):
    """NCDHW pooling (reference pool_op.cc 3D path)."""
    x = ins["X"][0]
    ptype = attrs.get("pooling_type", "max")
    ksize = list(attrs.get("ksize", [1, 1, 1]))
    strides = list(attrs.get("strides", ksize))
    paddings = list(attrs.get("paddings", [0, 0, 0]))
    if attrs.get("global_pooling", False) or attrs.get("adaptive", False) and ksize == [1, 1, 1]:
        red = jnp.max if ptype == "max" else jnp.mean
        return {"Out": [red(x, axis=(2, 3, 4), keepdims=True)]}
    if attrs.get("adaptive", False):
        od, oh, ow = ksize
        d, h, w = x.shape[2:]
        red = jnp.max if ptype == "max" else jnp.mean
        if d % od or h % oh or w % ow:
            from .nn_ops import adaptive_pool_nd

            return {"Out": [adaptive_pool_nd(x, (od, oh, ow), red)]}
        xr = x.reshape(x.shape[0], x.shape[1], od, d // od, oh, h // oh, ow, w // ow)
        return {"Out": [red(xr, axis=(3, 5, 7))]}
    pad = [(p, p) for p in paddings]
    window = (1, 1) + tuple(ksize)
    stride = (1, 1) + tuple(strides)
    full_pad = [(0, 0), (0, 0)] + pad
    if ptype == "max":
        out = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, window, stride, full_pad)
    else:
        s = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, stride, full_pad)
        if attrs.get("exclusive", True) and any(p for p in paddings):
            ones = jnp.ones_like(x)
            cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, stride, full_pad)
            out = s / cnt
        else:
            out = s / float(ksize[0] * ksize[1] * ksize[2])
    return {"Out": [out]}


@register("conv3d_transpose")
def conv3d_transpose(ctx, ins, attrs):
    """NCDHW transposed conv (reference conv_transpose_op.cc 3D path);
    filter layout [Cin, Cout/groups, kD, kH, kW] as in the reference."""
    x, w = ins["Input"][0], ins["Filter"][0]
    strides = list(attrs.get("strides", [1, 1, 1]))
    paddings = list(attrs.get("paddings", [0, 0, 0]))
    dilations = list(attrs.get("dilations", [1, 1, 1]))
    groups = int(attrs.get("groups", 1) or 1)
    # jax transposed conv: conv_general_dilated with lhs_dilation=strides;
    # groups>1 runs one transposed conv per channel group ([Cin, Cout/g,
    # kD,kH,kW] filters slice along Cin into g groups of Cin/g)
    k = w.shape[2:]
    pad = [
        (dilations[i] * (k[i] - 1) - paddings[i],
         dilations[i] * (k[i] - 1) - paddings[i])
        for i in range(3)
    ]

    def _tconv(xg, wg):
        return jax.lax.conv_general_dilated(
            xg, jnp.flip(wg, axis=(2, 3, 4)).swapaxes(0, 1),
            window_strides=(1, 1, 1), padding=pad,
            lhs_dilation=tuple(strides), rhs_dilation=tuple(dilations),
            dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        )

    if groups == 1:
        out = _tconv(x, w)
    else:
        cin = x.shape[1]
        if cin % groups:
            raise ValueError(
                f"conv3d_transpose: Cin {cin} must divide by groups={groups}"
            )
        cig = cin // groups
        out = jnp.concatenate(
            [
                _tconv(x[:, gi * cig:(gi + 1) * cig],
                       w[gi * cig:(gi + 1) * cig])
                for gi in range(groups)
            ],
            axis=1,
        )
    if attrs.get("output_padding"):
        op_ = attrs["output_padding"]
        if any(op_):
            out = jnp.pad(out, [(0, 0), (0, 0), (0, op_[0]), (0, op_[1]),
                                (0, op_[2])])
    return {"Output": [out]}


def _resize(x, out_shape, method, align_corners, align_mode=1):
    """Resize trailing spatial dims with the reference's coordinate maps
    (interpolate_op.h:96): align_corners -> src = l*(in-1)/(out-1) with
    0.5-rounding for nearest; align_corners=False, align_mode=1 (the fluid
    default) -> src = l*in/out; align_mode=0 -> half-pixel, which is
    exactly jax.image.resize's map (fast path)."""
    spatial = len(out_shape)
    full = x.shape[: x.ndim - spatial] + tuple(out_shape)
    if method != "nearest" and not align_corners and align_mode == 0:
        return jax.image.resize(x, full, method=method)
    return _resize_explicit(x, out_shape, method, align_corners)


def _resize_explicit(x, out_shape, method, align_corners):
    spatial_axes = list(range(x.ndim - len(out_shape), x.ndim))
    out = x
    for ax, osz in zip(spatial_axes, out_shape):
        isz = out.shape[ax]
        if align_corners:
            if osz == 1 or isz == 1:
                idx = jnp.zeros((osz,), jnp.float32)
            else:
                idx = jnp.arange(osz, dtype=jnp.float32) * (isz - 1) / (osz - 1)
        else:
            idx = jnp.arange(osz, dtype=jnp.float32) * isz / osz
        if method == "nearest":
            # reference: int(ratio*l + 0.5) when align_corners else int(ratio*l)
            pick = idx + 0.5 if align_corners else idx
            out = jnp.take(out, jnp.clip(pick.astype(jnp.int32), 0, isz - 1),
                           axis=ax)
            continue
        lo = jnp.clip(jnp.floor(idx).astype(jnp.int32), 0, isz - 1)
        hi = jnp.clip(lo + 1, 0, isz - 1)
        frac = (idx - lo).astype(x.dtype)
        a = jnp.take(out, lo, axis=ax)
        b = jnp.take(out, hi, axis=ax)
        shape = [1] * a.ndim
        shape[ax] = osz
        f = frac.reshape(shape)
        out = a * (1 - f) + b * f
    return out


@register("bilinear_interp")
def bilinear_interp(ctx, ins, attrs):
    x = ins["X"][0]  # NCHW
    oh, ow = int(attrs["out_h"]), int(attrs["out_w"])
    return {"Out": [_resize(x, (oh, ow), "bilinear",
                            bool(attrs.get("align_corners", True)),
                            int(attrs.get("align_mode", 1)))]}


@register("nearest_interp")
def nearest_interp(ctx, ins, attrs):
    x = ins["X"][0]
    oh, ow = int(attrs["out_h"]), int(attrs["out_w"])
    return {"Out": [_resize(x, (oh, ow), "nearest",
                            bool(attrs.get("align_corners", True)))]}


@register("trilinear_interp")
def trilinear_interp(ctx, ins, attrs):
    x = ins["X"][0]  # NCDHW
    od, oh, ow = int(attrs["out_d"]), int(attrs["out_h"]), int(attrs["out_w"])
    return {"Out": [_resize(x, (od, oh, ow), "linear",
                            bool(attrs.get("align_corners", True)),
                            int(attrs.get("align_mode", 1)))]}


@register("linear_interp")
def linear_interp(ctx, ins, attrs):
    x = ins["X"][0]  # NCW
    ow = int(attrs["out_w"])
    return {"Out": [_resize(x, (ow,), "linear",
                            bool(attrs.get("align_corners", True)),
                            int(attrs.get("align_mode", 1)))]}


@register("affine_grid")
def affine_grid(ctx, ins, attrs):
    """Theta [N,2,3] -> sampling grid [N,H,W,2] (reference
    affine_grid_op.cc; align_corners semantics of the 2020 op = True)."""
    theta = ins["Theta"][0]
    h, w = [int(v) for v in attrs["output_shape"]][-2:]
    ys = jnp.linspace(-1.0, 1.0, h)
    xs = jnp.linspace(-1.0, 1.0, w)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # [H,W,3]
    grid = jnp.einsum("hwk,njk->nhwj", base.astype(theta.dtype), theta)
    return {"Output": [grid]}


@register("grid_sampler")
def grid_sampler(ctx, ins, attrs):
    """Bilinear sampling of X [N,C,H,W] at Grid [N,Ho,Wo,2] in [-1,1]
    (reference grid_sampler_op.cc; zero padding, align_corners=True)."""
    x, grid = ins["X"][0], ins["Grid"][0]
    n, c, h, w = x.shape
    gx = (grid[..., 0] + 1.0) * (w - 1) / 2.0  # [N,Ho,Wo]
    gy = (grid[..., 1] + 1.0) * (h - 1) / 2.0
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)

    def _gather(yi, xi):
        yi_c = jnp.clip(yi.astype(jnp.int32), 0, h - 1)
        xi_c = jnp.clip(xi.astype(jnp.int32), 0, w - 1)
        flat = x.reshape(n, c, h * w)
        idx = (yi_c * w + xi_c).reshape(n, -1)  # [N, Ho*Wo]
        got = jnp.take_along_axis(flat, idx[:, None, :], axis=2)
        got = got.reshape(n, c, *gx.shape[1:])
        inside = ((yi >= 0) & (yi <= h - 1) & (xi >= 0) & (xi <= w - 1))
        return got * inside[:, None].astype(x.dtype)

    wx = (gx - x0).astype(x.dtype)[:, None]
    wy = (gy - y0).astype(x.dtype)[:, None]
    out = (
        _gather(y0, x0) * (1 - wy) * (1 - wx)
        + _gather(y0, x0 + 1) * (1 - wy) * wx
        + _gather(y0 + 1, x0) * wy * (1 - wx)
        + _gather(y0 + 1, x0 + 1) * wy * wx
    )
    return {"Output": [out]}


@register("lrn")
def lrn(ctx, ins, attrs):
    """Local response normalization across channels (reference lrn_op.cc):
    out = x / (k + alpha * sum_window(x^2))^beta."""
    x = ins["X"][0]
    n = int(attrs.get("n", 5))
    k = float(attrs.get("k", 1.0))
    alpha = float(attrs.get("alpha", 1e-4))
    beta = float(attrs.get("beta", 0.75))
    sq = x * x
    half = n // 2
    padded = jnp.pad(sq, [(0, 0), (half, n - 1 - half), (0, 0), (0, 0)])
    window = jnp.stack(
        [padded[:, i : i + x.shape[1]] for i in range(n)], axis=0
    ).sum(axis=0)
    mid = (k + alpha * window) ** beta
    return {"Out": [x / mid], "MidOut": [mid]}


@register("unfold")
def unfold(ctx, ins, attrs):
    """im2col: X [N,C,H,W] -> [N, C*kh*kw, L] (reference unfold_op.cc)."""
    x = ins["X"][0]
    kh, kw = [int(v) for v in attrs["kernel_sizes"]]
    sh, sw = [int(v) for v in attrs.get("strides", [1, 1])]
    pads = [int(v) for v in attrs.get("paddings", [0, 0, 0, 0])]
    if len(pads) == 2:
        pads = [pads[0], pads[1], pads[0], pads[1]]
    dh, dw = [int(v) for v in attrs.get("dilations", [1, 1])]
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw),
        [(pads[0], pads[2]), (pads[1], pads[3])],
        rhs_dilation=(dh, dw),
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )  # [N, C*kh*kw, Ho, Wo]
    n, ckk = patches.shape[:2]
    return {"Y": [patches.reshape(n, ckk, -1)]}


@register("roi_pool")
def roi_pool(ctx, ins, attrs):
    """Max-pool each ROI to a fixed grid (reference roi_pool_op.cc).
    ROIs [R, 4] as (x1, y1, x2, y2) in input scale; RoisNum/batch ids via
    BatchId input (default all batch 0)."""
    x, rois = ins["X"][0], ins["ROIs"][0]
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    scale = float(attrs.get("spatial_scale", 1.0))
    batch_ids = (
        ins["BatchId"][0].astype(jnp.int32).reshape(-1)
        if ins.get("BatchId") else jnp.zeros((rois.shape[0],), jnp.int32)
    )
    n, c, h, w = x.shape

    def one_roi(roi, bid):
        x1, y1, x2, y2 = [jnp.round(roi[i] * scale) for i in range(4)]
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        bh, bw = rh / ph, rw / pw
        img = x[bid]  # [C,H,W]
        ys = jnp.arange(h, dtype=jnp.float32)
        xs = jnp.arange(w, dtype=jnp.float32)
        outs = []
        for i in range(ph):
            for j in range(pw):
                y_lo = jnp.floor(y1 + i * bh)
                y_hi = jnp.ceil(y1 + (i + 1) * bh)
                x_lo = jnp.floor(x1 + j * bw)
                x_hi = jnp.ceil(x1 + (j + 1) * bw)
                m = (
                    ((ys >= y_lo) & (ys < jnp.maximum(y_hi, y_lo + 1)))[:, None]
                    & ((xs >= x_lo) & (xs < jnp.maximum(x_hi, x_lo + 1)))[None, :]
                )
                cell = jnp.where(m[None], img, -jnp.inf).max(axis=(1, 2))
                outs.append(cell)
        return jnp.stack(outs, axis=1).reshape(c, ph, pw)

    out = jax.vmap(one_roi)(rois, batch_ids)
    return {"Out": [out]}


@register("pixel_shuffle")
def pixel_shuffle(ctx, ins, attrs):
    """[N, C*r^2, H, W] -> [N, C, H*r, W*r] (reference pixel_shuffle_op.cc)."""
    x = ins["X"][0]
    r = int(attrs.get("upscale_factor", 1))
    n, c, h, w = x.shape
    oc = c // (r * r)
    out = x.reshape(n, oc, r, r, h, w).transpose(0, 1, 4, 2, 5, 3)
    return {"Out": [out.reshape(n, oc, h * r, w * r)]}


@register("temporal_shift")
def temporal_shift(ctx, ins, attrs):
    """Shift 1/4 channels forward and 1/4 backward across the segment
    (time) dim (reference temporal_shift_op.cc): X [N*T, C, H, W]."""
    x = ins["X"][0]
    t = int(attrs["seg_num"])
    ratio = float(attrs.get("shift_ratio", 0.25))
    nt, c, h, w = x.shape
    n = nt // t
    x5 = x.reshape(n, t, c, h, w)
    c1 = int(c * ratio)
    c2 = int(c * 2 * ratio)
    fwd = jnp.concatenate(
        [x5[:, 1:, :c1], jnp.zeros_like(x5[:, :1, :c1])], axis=1)
    bwd = jnp.concatenate(
        [jnp.zeros_like(x5[:, :1, c1:c2]), x5[:, :-1, c1:c2]], axis=1)
    out = jnp.concatenate([fwd, bwd, x5[:, :, c2:]], axis=2)
    return {"Out": [out.reshape(nt, c, h, w)]}
