"""Detection/vision ops (reference paddle/fluid/operators/detection/,
17k LoC — this is the high-traffic subset: iou_similarity_op.cc,
box_coder_op.cc, prior_box_op.cc, yolo_box_op.cc, roi_align_op.cc).

All dense/static-shape: ragged per-image ROI lists (LoD in the
reference) ride as a flat ROI tensor plus a per-ROI batch index, the
same padded-representation answer used by the sequence ops.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register


def _iou(x, y, off=0.0):
    """x [N,4], y [M,4] (xmin,ymin,xmax,ymax) -> [N,M]. off=1 for pixel
    (non-normalized) boxes, where a 1x1 box has area 1 (reference
    box_normalized=False semantics)."""
    area_x = (x[:, 2] - x[:, 0] + off) * (x[:, 3] - x[:, 1] + off)
    area_y = (y[:, 2] - y[:, 0] + off) * (y[:, 3] - y[:, 1] + off)
    lt = jnp.maximum(x[:, None, :2], y[None, :, :2])
    rb = jnp.minimum(x[:, None, 2:], y[None, :, 2:])
    wh = jnp.maximum(rb - lt + off, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    union = area_x[:, None] + area_y[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register("iou_similarity")
def iou_similarity(ctx, ins, attrs):
    off = 0.0 if attrs.get("box_normalized", True) else 1.0
    return {"Out": [_iou(ins["X"][0], ins["Y"][0], off)]}


@register("box_coder")
def box_coder(ctx, ins, attrs):
    """Encode/decode boxes against priors (reference box_coder_op.cc).
    encode_center_size: target corner boxes -> (dx,dy,dw,dh) deltas;
    decode_center_size: deltas -> corner boxes. Decode axis semantics
    follow the reference: axis=0 broadcasts priors over target rows
    (tb [N,M,4], priors along dim 1); axis=1 broadcasts along dim 0."""
    prior = ins["PriorBox"][0]  # [M, 4] corner form
    tb = ins["TargetBox"][0]
    code_type = attrs.get("code_type", "encode_center_size")
    axis = int(attrs.get("axis", 0))
    box_normalized = bool(attrs.get("box_normalized", True))
    if ins.get("PriorBoxVar"):
        var = ins["PriorBoxVar"][0]
    else:
        v = attrs.get("variance") or [1.0, 1.0, 1.0, 1.0]
        var = jnp.asarray(v, prior.dtype)[None, :]

    off = 0.0 if box_normalized else 1.0
    pw = prior[:, 2] - prior[:, 0] + off
    ph = prior[:, 3] - prior[:, 1] + off
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph * 0.5

    if code_type == "encode_center_size":
        # tb [N,4] corner boxes vs each prior -> [N, M, 4]
        tw = tb[:, 2] - tb[:, 0] + off
        th = tb[:, 3] - tb[:, 1] + off
        tcx = tb[:, 0] + tw * 0.5
        tcy = tb[:, 1] + th * 0.5
        dx = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        dy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        dw = jnp.log(tw[:, None] / pw[None, :])
        dh = jnp.log(th[:, None] / ph[None, :])
        out = jnp.stack([dx, dy, dw, dh], axis=-1) / var[None, :, :]
        return {"OutputBox": [out]}
    if code_type == "decode_center_size":
        # tb [N, M, 4] deltas; priors broadcast along dim (1 - axis)...
        # axis=0: priors align with tb dim 1; axis=1: with tb dim 0
        if axis == 0:
            exp = lambda a: a[None, :]
            vexp = var[None, :, :]
        else:
            exp = lambda a: a[:, None]
            vexp = var[:, None, :]
        d = tb * vexp
        cx = d[..., 0] * exp(pw) + exp(pcx)
        cy = d[..., 1] * exp(ph) + exp(pcy)
        w = jnp.exp(d[..., 2]) * exp(pw)
        h = jnp.exp(d[..., 3]) * exp(ph)
        out = jnp.stack(
            [cx - w * 0.5, cy - h * 0.5, cx + w * 0.5 - off, cy + h * 0.5 - off],
            axis=-1,
        )
        return {"OutputBox": [out]}
    raise ValueError(f"unknown code_type {code_type!r}")


@register("prior_box", stop_gradient=True, no_vjp_grad=True)
def prior_box(ctx, ins, attrs):
    """SSD prior boxes over a feature map (reference prior_box_op.cc)."""
    feat = ins["Input"][0]   # [N, C, H, W]
    image = ins["Image"][0]  # [N, C, IH, IW]
    h, w = feat.shape[2], feat.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    min_sizes = [float(s) for s in attrs["min_sizes"]]
    max_sizes = [float(s) for s in attrs.get("max_sizes", [])]
    ars = [1.0]
    for ar in attrs.get("aspect_ratios", []):
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(float(ar))
            if attrs.get("flip", False):
                ars.append(1.0 / float(ar))
    variances = [float(v) for v in attrs.get("variances", [0.1, 0.1, 0.2, 0.2])]
    step_w = float(attrs.get("step_w", 0.0)) or iw / w
    step_h = float(attrs.get("step_h", 0.0)) or ih / h
    offset = float(attrs.get("offset", 0.5))

    boxes = []
    for si, ms in enumerate(min_sizes):
        # reference order per min_size: min, ratios != 1, then ITS max
        boxes.append((ms, ms))
        for ar in ars:
            if abs(ar - 1.0) < 1e-6:
                continue
            boxes.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        if si < len(max_sizes):
            m = float(np.sqrt(ms * max_sizes[si]))
            boxes.append((m, m))

    cx = (jnp.arange(w, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(h, dtype=jnp.float32) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)  # [H, W]
    out = []
    for bw, bh in boxes:
        out.append(jnp.stack([
            (cxg - bw / 2.0) / iw, (cyg - bh / 2.0) / ih,
            (cxg + bw / 2.0) / iw, (cyg + bh / 2.0) / ih,
        ], axis=-1))
    prior = jnp.stack(out, axis=2)  # [H, W, P, 4]
    if attrs.get("clip", False):
        prior = jnp.clip(prior, 0.0, 1.0)
    var = jnp.broadcast_to(
        jnp.asarray(variances, jnp.float32), prior.shape
    )
    return {"Boxes": [prior], "Variances": [var]}


@register("yolo_box", stop_gradient=True, no_vjp_grad=True)
def yolo_box(ctx, ins, attrs):
    """Decode YOLOv3 head output to boxes+scores (reference
    yolo_box_op.cc). X [N, P*(5+C), H, W], ImgSize [N, 2] (h, w)."""
    x = ins["X"][0]
    img = ins["ImgSize"][0].astype(jnp.float32)
    anchors = [int(a) for a in attrs["anchors"]]
    cls = int(attrs["class_num"])
    conf_thresh = float(attrs.get("conf_thresh", 0.005))
    ds = int(attrs.get("downsample_ratio", 32))
    n, _, h, w = x.shape
    p = len(anchors) // 2
    x = x.reshape(n, p, 5 + cls, h, w)

    gx = jnp.arange(w, dtype=jnp.float32)
    gy = jnp.arange(h, dtype=jnp.float32)
    sig = jax.nn.sigmoid
    bx = (sig(x[:, :, 0]) + gx[None, None, None, :]) / w   # [N,P,H,W]
    by = (sig(x[:, :, 1]) + gy[None, None, :, None]) / h
    aw = jnp.asarray(anchors[0::2], jnp.float32)[None, :, None, None]
    ah = jnp.asarray(anchors[1::2], jnp.float32)[None, :, None, None]
    bw = jnp.exp(x[:, :, 2]) * aw / (ds * w)
    bh = jnp.exp(x[:, :, 3]) * ah / (ds * h)
    conf = sig(x[:, :, 4])
    probs = sig(x[:, :, 5:]) * conf[:, :, None]

    img_h = img[:, 0][:, None, None, None]
    img_w = img[:, 1][:, None, None, None]
    x0 = (bx - bw / 2.0) * img_w
    y0 = (by - bh / 2.0) * img_h
    x1 = (bx + bw / 2.0) * img_w
    y1 = (by + bh / 2.0) * img_h
    if attrs.get("clip_bbox", True):
        x0 = jnp.clip(x0, 0.0, img_w - 1.0)
        y0 = jnp.clip(y0, 0.0, img_h - 1.0)
        x1 = jnp.clip(x1, 0.0, img_w - 1.0)
        y1 = jnp.clip(y1, 0.0, img_h - 1.0)
    boxes = jnp.stack([x0, y0, x1, y1], axis=-1)  # [N,P,H,W,4]
    # confidence gate (reference zeroes below-threshold entries)
    keep = (conf > conf_thresh)[..., None]
    boxes = jnp.where(keep, boxes, 0.0).reshape(n, p * h * w, 4)
    scores = jnp.where(keep, probs.transpose(0, 1, 3, 4, 2), 0.0).reshape(
        n, p * h * w, cls
    )
    return {"Boxes": [boxes], "Scores": [scores]}


@register("roi_align")
def roi_align(ctx, ins, attrs):
    """ROI Align (reference roi_align_op.cc): average of bilinear samples
    on a pooled grid. ROIs [R,4] in input-image coordinates with a per-ROI
    batch index (RoisNum [N] counts in the reference's LoD style, or a
    flat BatchIndex [R])."""
    x = ins["X"][0]          # [N, C, H, W]
    rois = ins["ROIs"][0]    # [R, 4]
    r = rois.shape[0]
    if ins.get("BatchIndex"):
        bidx = ins["BatchIndex"][0].astype(jnp.int32)
    elif ins.get("RoisNum"):
        counts = ins["RoisNum"][0].astype(jnp.int32)
        bidx = jnp.repeat(
            jnp.arange(counts.shape[0], dtype=jnp.int32), counts,
            total_repeat_length=r,
        )
    else:
        bidx = jnp.zeros((r,), jnp.int32)
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    scale = float(attrs.get("spatial_scale", 1.0))
    ratio = int(attrs.get("sampling_ratio", -1))
    if ratio <= 0:
        ratio = 2  # adaptive in the reference; fixed 2 covers common cfgs
    n, c, h, w = x.shape

    r0 = rois * scale  # [R,4] in feature coords
    rw = jnp.maximum(r0[:, 2] - r0[:, 0], 1.0)
    rh = jnp.maximum(r0[:, 3] - r0[:, 1], 1.0)
    bin_w = rw / pw
    bin_h = rh / ph

    # sample grid: [R, ph, ratio] y coords and [R, pw, ratio] x coords
    iy = jnp.arange(ph, dtype=jnp.float32)
    ix = jnp.arange(pw, dtype=jnp.float32)
    sy = jnp.arange(ratio, dtype=jnp.float32)
    ys = (r0[:, 1, None, None] + (iy[None, :, None] +
          (sy[None, None, :] + 0.5) / ratio) * bin_h[:, None, None])
    xs = (r0[:, 0, None, None] + (ix[None, :, None] +
          (sy[None, None, :] + 0.5) / ratio) * bin_w[:, None, None])

    def bilinear(img, yy, xx):
        """img [C,H,W]; yy,xx [...]: bilinear samples -> [C, ...]."""
        yy = jnp.clip(yy, 0.0, h - 1.0)
        xx = jnp.clip(xx, 0.0, w - 1.0)
        y0 = jnp.floor(yy).astype(jnp.int32)
        x0 = jnp.floor(xx).astype(jnp.int32)
        y1 = jnp.minimum(y0 + 1, h - 1)
        x1 = jnp.minimum(x0 + 1, w - 1)
        ly = yy - y0
        lx = xx - x0
        v00 = img[:, y0, x0]
        v01 = img[:, y0, x1]
        v10 = img[:, y1, x0]
        v11 = img[:, y1, x1]
        return (v00 * (1 - ly) * (1 - lx) + v01 * (1 - ly) * lx +
                v10 * ly * (1 - lx) + v11 * ly * lx)

    def one_roi(roi_ys, roi_xs, b):
        img = x[b]  # [C,H,W]
        yy = roi_ys[:, None, :, None]          # [ph,1,ratio,1]
        xx = roi_xs[None, :, None, :]          # [1,pw,1,ratio]
        yy = jnp.broadcast_to(yy, (ph, pw, ratio, ratio))
        xx = jnp.broadcast_to(xx, (ph, pw, ratio, ratio))
        vals = bilinear(img, yy, xx)           # [C,ph,pw,ratio,ratio]
        return jnp.mean(vals, axis=(-1, -2))   # [C,ph,pw]

    out = jax.vmap(one_roi)(ys, xs, bidx)      # [R,C,ph,pw]
    return {"Out": [out]}
