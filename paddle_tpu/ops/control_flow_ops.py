"""Control-flow ops: cond (lax.cond) and while (lax.while_loop).

Parity: /root/reference/paddle/fluid/operators/controlflow/
(conditional_block_op.cc, while_op.cc). The reference interprets
sub-blocks with per-step Scopes; here sub-blocks are SSA-ified —
captured outer vars become explicit operands, block-carried state becomes
lax loop carries — so the whole construct compiles into HLO
Conditional/While (SURVEY.md §7 "hard parts": per-step scopes -> SSA).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from . import registry
from .registry import register


def _scalar_pred(p):
    p = jnp.asarray(p)
    if p.ndim > 0:
        p = p.reshape(())
    return p.astype(jnp.bool_)


def _cond_infer(in_metas, attrs):
    blk = attrs["true_block"]
    metas = []
    for n in attrs["true_out_names"]:
        v = blk._find_var_recursive(n)
        metas.append((v.shape, v.dtype))
    return {"Out": metas}


@register("cond", infer_shape=_cond_infer)
def cond_op(ctx, ins, attrs):
    captured = list(attrs["captured_names"])
    cap_vals = list(ins.get("Input", []))
    t_blk, f_blk = attrs["true_block"], attrs["false_block"]
    t_outs, f_outs = attrs["true_out_names"], attrs["false_out_names"]

    def make_branch(blk, out_names):
        def f(cap):
            env = dict(zip(captured, cap))
            registry.emit_ops(ctx, blk.ops, env)
            return tuple(env[n] for n in out_names)

        return f

    outs = jax.lax.cond(
        _scalar_pred(ins["Cond"][0]),
        make_branch(t_blk, t_outs),
        make_branch(f_blk, f_outs),
        tuple(cap_vals),
    )
    return {"Out": list(outs)}


def _while_infer(in_metas, attrs):
    return {"Out": list(in_metas.get("LoopVars", []))}


@register("while_loop", infer_shape=_while_infer, no_vjp_grad=True)
def while_loop_op(ctx, ins, attrs):
    """inputs: LoopVars (carried state), Input (captured constants).
    attrs: cond_block/body_block, loop_var_names (names the blocks use for
    the carries), cond_out_name, body_out_names, captured_names."""
    captured = dict(zip(attrs["captured_names"], ins.get("Input", [])))
    loop_names = list(attrs["loop_var_names"])
    cond_blk, body_blk = attrs["cond_block"], attrs["body_block"]

    def cond_fn(carry):
        env = dict(captured)
        env.update(zip(loop_names, carry))
        registry.emit_ops(ctx, cond_blk.ops, env)
        return _scalar_pred(env[attrs["cond_out_name"]])

    def body_fn(carry):
        env = dict(captured)
        env.update(zip(loop_names, carry))
        registry.emit_ops(ctx, body_blk.ops, env)
        out = []
        for init, name in zip(carry, attrs["body_out_names"]):
            v = env[name]
            out.append(jnp.asarray(v, jnp.asarray(init).dtype))
        return tuple(out)

    final = jax.lax.while_loop(cond_fn, body_fn, tuple(ins["LoopVars"]))
    return {"Out": list(final)}


@register("select_input", infer_shape=lambda m, a: {"Out": [m["X"][0]]})
def select_input(ctx, ins, attrs):
    """Out = X[Mask] — reference controlflow/select_input_op."""
    mask = _scalar_pred(ins["Mask"][0]).astype(jnp.int32)
    xs = ins["X"]
    out = jax.lax.switch(jnp.clip(mask, 0, len(xs) - 1), [lambda x=x: x for x in xs])
    return {"Out": [out]}
