"""Control-flow ops: cond (lax.cond) and while (lax.while_loop).

Parity: /root/reference/paddle/fluid/operators/controlflow/
(conditional_block_op.cc, while_op.cc). The reference interprets
sub-blocks with per-step Scopes; here sub-blocks are SSA-ified —
captured outer vars become explicit operands, block-carried state becomes
lax loop carries — so the whole construct compiles into HLO
Conditional/While (SURVEY.md §7 "hard parts": per-step scopes -> SSA).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from . import registry
from .registry import register


def _scalar_pred(p):
    p = jnp.asarray(p)
    if p.ndim > 0:
        p = p.reshape(())
    return p.astype(jnp.bool_)


def _cond_infer(in_metas, attrs):
    blk = attrs["true_block"]
    metas = []
    for n in attrs["true_out_names"]:
        v = blk._find_var_recursive(n)
        metas.append((v.shape, v.dtype))
    return {"Out": metas}


@register("cond", infer_shape=_cond_infer)
def cond_op(ctx, ins, attrs):
    captured = list(attrs["captured_names"])
    cap_vals = list(ins.get("Input", []))
    t_blk, f_blk = attrs["true_block"], attrs["false_block"]
    t_outs, f_outs = attrs["true_out_names"], attrs["false_out_names"]

    def make_branch(blk, out_names):
        def f(cap):
            env = dict(zip(captured, cap))
            registry.emit_ops(ctx, blk.ops, env)
            return tuple(env[n] for n in out_names)

        return f

    outs = jax.lax.cond(
        _scalar_pred(ins["Cond"][0]),
        make_branch(t_blk, t_outs),
        make_branch(f_blk, f_outs),
        tuple(cap_vals),
    )
    return {"Out": list(outs)}


def _while_infer(in_metas, attrs):
    return {"Out": list(in_metas.get("LoopVars", []))}


@register("while_loop", infer_shape=_while_infer, no_vjp_grad=True)
def while_loop_op(ctx, ins, attrs):
    """inputs: LoopVars (carried state), Input (captured constants).
    attrs: cond_block/body_block, loop_var_names (names the blocks use for
    the carries), cond_out_name, body_out_names, captured_names."""
    captured = dict(zip(attrs["captured_names"], ins.get("Input", [])))
    loop_names = list(attrs["loop_var_names"])
    cond_blk, body_blk = attrs["cond_block"], attrs["body_block"]

    def cond_fn(carry):
        env = dict(captured)
        env.update(zip(loop_names, carry))
        registry.emit_ops(ctx, cond_blk.ops, env)
        return _scalar_pred(env[attrs["cond_out_name"]])

    def body_fn(carry):
        env = dict(captured)
        env.update(zip(loop_names, carry))
        registry.emit_ops(ctx, body_blk.ops, env)
        out = []
        for init, name in zip(carry, attrs["body_out_names"]):
            v = env[name]
            out.append(jnp.asarray(v, jnp.asarray(init).dtype))
        return tuple(out)

    final = jax.lax.while_loop(cond_fn, body_fn, tuple(ins["LoopVars"]))
    return {"Out": list(final)}


@register("select_input", infer_shape=lambda m, a: {"Out": [m["X"][0]]})
def select_input(ctx, ins, attrs):
    """Out = X[Mask] — reference controlflow/select_input_op."""
    mask = _scalar_pred(ins["Mask"][0]).astype(jnp.int32)
    xs = ins["X"]
    out = jax.lax.switch(jnp.clip(mask, 0, len(xs) - 1), [lambda x=x: x for x in xs])
    return {"Out": [out]}


def _recurrent_infer(in_metas, attrs):
    blk = attrs["step_block"]
    t = attrs["__seq_len__"]
    outs = []
    for n in attrs["step_output_names"]:
        v = blk._find_var_recursive(n)
        outs.append(((v.shape[0], t) + tuple(v.shape[1:]), v.dtype))
    states = []
    for n in attrs["memory_out_names"]:
        v = blk._find_var_recursive(n)
        states.append((v.shape, v.dtype))
    return {"Out": outs, "FinalStates": states}


@register("recurrent", infer_shape=_recurrent_infer)
def recurrent_op(ctx, ins, attrs):
    """Block-based RNN (reference recurrent_op.cc / StaticRNN): scan the
    step sub-block over the time axis. The reference runs the block in a
    per-step Scope; here the block is SSA-ified into a lax.scan body —
    memories are the carries, step inputs are the scanned xs — so the
    whole recurrence compiles to one HLO While and reverse-mode AD works
    through the generic vjp path (no per-step scopes to differentiate).

    inputs: StepInputs [B,T,...] (sliced per step), Memories (initial
    carry values), Captured (loop constants).
    attrs: step_block, step_input_names, memory_in_names,
    memory_out_names, step_output_names, captured_names, is_reverse."""
    step_blk = attrs["step_block"]
    step_in_names = list(attrs["step_input_names"])
    mem_in = list(attrs["memory_in_names"])
    mem_out = list(attrs["memory_out_names"])
    out_names = list(attrs["step_output_names"])
    captured = dict(zip(attrs["captured_names"], ins.get("Captured", [])))
    reverse = bool(attrs.get("is_reverse", False))

    xs = [jnp.swapaxes(x, 0, 1) for x in ins.get("StepInputs", [])]  # [T,B,..]
    if reverse:
        xs = [jnp.flip(x, 0) for x in xs]
    mems = tuple(ins.get("Memories", []))

    def body(carry, x_t):
        env = dict(captured)
        env.update(zip(mem_in, carry))
        env.update(zip(step_in_names, x_t))
        registry.emit_ops(ctx, step_blk.ops, env)
        new_carry = tuple(env[n] for n in mem_out)
        outs = tuple(env[n] for n in out_names)
        return new_carry, outs

    final, stacked = jax.lax.scan(body, mems, tuple(xs))
    outs = [jnp.swapaxes(o, 0, 1) for o in stacked]  # [B,T,...]
    if reverse:
        outs = [jnp.flip(o, 1) for o in outs]
    return {"Out": outs, "FinalStates": list(final)}


@register("py_func")
def py_func_op(ctx, ins, attrs):
    """Python-callback op (reference controlflow/py_func_op.cc): run a
    host Python callable inside the compiled program via
    jax.pure_callback. The callable is stored in the op attrs (the same
    way sub-Blocks are). backward_func, when given, defines the vjp —
    also as a host callback."""
    if jax.default_backend() == "axon":
        raise NotImplementedError(
            "py_func needs host callbacks, which the axon dev tunnel does "
            "not support; run on a real TPU host or the CPU backend"
        )
    import numpy as np

    xs = ins["X"]
    fwd = attrs["pyfunc_fwd"]
    bwd = attrs.get("pyfunc_bwd")
    skip_idx = set(attrs.get("pyfunc_skip_idx", []))
    out_specs = [
        jax.ShapeDtypeStruct(tuple(s), jnp.dtype(str(np.dtype(d))))
        for s, d in attrs["pyfunc_out_meta"]
    ]

    def host_fwd(*arrs):
        res = fwd(*arrs)
        res = res if isinstance(res, (list, tuple)) else [res]
        if len(res) != len(out_specs):
            raise ValueError(
                f"py_func forward returned {len(res)} arrays; the op "
                f"declares {len(out_specs)} outputs"
            )
        return tuple(np.asarray(r, dtype=spec.dtype) for r, spec in zip(res, out_specs))

    if bwd is None:
        outs = jax.pure_callback(host_fwd, tuple(out_specs), *xs)
        return {"Out": list(outs)}

    @jax.custom_vjp
    def call(*xs_):
        return jax.pure_callback(host_fwd, tuple(out_specs), *xs_)

    def call_fwd(*xs_):
        return call(*xs_), xs_

    def call_bwd(res_xs, gs):
        # cotangents are produced only for ACTIVE inputs: float dtype and
        # not listed in skip_vars_in_backward_input; everything else gets
        # None (ints cannot carry gradients)
        active = [
            i for i, x in enumerate(res_xs)
            if i not in skip_idx and jnp.issubdtype(x.dtype, jnp.floating)
        ]

        def host_bwd(*arrs):
            n = len(res_xs)
            # reference py_func contract: inputs listed in
            # skip_vars_in_backward_input are omitted from the bwd args
            fwd_args = [a for i, a in enumerate(arrs[:n]) if i not in skip_idx]
            grads = bwd(*fwd_args, *arrs[n:])
            grads = grads if isinstance(grads, (list, tuple)) else [grads]
            if len(grads) != len(active):
                raise ValueError(
                    f"py_func backward returned {len(grads)} gradients; "
                    f"expected {len(active)} (one per float non-skipped input)"
                )
            return tuple(
                np.asarray(g, dtype=np.dtype(str(res_xs[i].dtype)))
                for g, i in zip(grads, active)
            )

        in_specs = tuple(
            jax.ShapeDtypeStruct(res_xs[i].shape, res_xs[i].dtype)
            for i in active
        )
        dact = jax.pure_callback(host_bwd, in_specs, *res_xs, *gs)
        out = [None] * len(res_xs)
        for g, i in zip(dact, active):
            out[i] = g
        return tuple(out)

    call.defvjp(call_fwd, call_bwd)
    outs = call(*xs)
    return {"Out": list(outs)}


@register("print", no_vjp_grad=True,
          infer_shape=lambda m, a: {"Out": [m["In"][0]]})
def print_op(ctx, ins, attrs):
    """Runtime tensor printing (reference print_op.cc) via a host
    callback; honors first_n (stop after N prints) and summarize
    (np.array2string threshold). Out aliases In so the print stays
    ordered relative to consumers."""
    x = ins["In"][0]
    msg = str(attrs.get("message") or "")
    name = str(attrs.get("var_name", ""))
    first_n = int(attrs.get("first_n", -1))
    summarize = int(attrs.get("summarize", 20))
    state = {"n": 0}  # one closure per compiled program (trace-time)

    def _emit(val):
        import numpy as np

        if 0 <= first_n <= state["n"]:
            return
        state["n"] += 1
        body = np.array2string(
            np.asarray(val), threshold=summarize if summarize > 0 else 1000)
        print(f"{msg}{name} = {body}", flush=True)

    jax.debug.callback(_emit, x, ordered=False)
    return {"Out": [x]}


@register("assert", no_vjp_grad=True, stop_gradient=True,
          infer_shape=lambda m, a: {"Out": [((1,), "bool")]})
def assert_op(ctx, ins, attrs):
    """Runtime assertion (reference assert_op.cc): host callback raises
    when the condition is false, aborting the step."""
    cond = _scalar_pred(ins["Cond"][0])
    data = [jnp.asarray(d) for d in ins.get("Data", [])]

    def _check(c, *vals):
        import numpy as np

        if not bool(np.asarray(c)):
            raise AssertionError(
                "layers.Assert failed"
                + ("; data: " + ", ".join(repr(np.asarray(v)) for v in vals)
                   if vals else "")
            )

    jax.debug.callback(_check, cond, *data, ordered=False)
    return {"Out": [cond.reshape(1)]}
