"""Fused multi-head attention op.

Replaces the reference's BERT attention fusion machinery
(/root/reference/paddle/fluid/framework/ir/multihead_matmul_fuse_pass.cc and
 /root/reference/paddle/fluid/operators/math/bert_encoder_functor.cu):
there, a graph pass pattern-matches the decomposed attention subgraph and
swaps in a hand-written CUDA kernel. Here attention is a first-class op;
on TPU it lowers to a Pallas flash-attention kernel (online softmax, O(S)
memory), elsewhere to a jnp composition that XLA fuses.

Semantics: Q,K,V are [B, S, H] (head-interleaved, pre-split); BiasQK is an
additive mask broadcastable to [B, nh, S, S]. Output is [B, S, H].
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .registry import register


def _split_heads(x, num_heads):
    b, s, h = x.shape
    return x.reshape(b, s, num_heads, h // num_heads).transpose(0, 2, 1, 3)


def _merge_heads(x):
    b, nh, s, dh = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b, s, nh * dh)


def _reference_attention(q, k, v, bias, dropout_prob, deterministic, rng_key):
    """jnp composition: [B,nh,S,dh] in, [B,nh,S,dh] out."""
    dh = q.shape[-1]
    scores = jnp.einsum(
        "bnqd,bnkd->bnqk", q, k, preferred_element_type=jnp.float32
    ) * (1.0 / math.sqrt(dh))
    if bias is not None:
        scores = scores + bias.astype(scores.dtype)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    if not deterministic and dropout_prob > 0.0:
        keep = jax.random.bernoulli(rng_key, 1.0 - dropout_prob, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_prob), 0.0)
    return jnp.einsum("bnqk,bnkd->bnqd", probs, v)


# test hook: force the pallas path (interpret mode) on CPU
FORCE_PALLAS = False


def _use_pallas(q):
    from .pallas.flash_attention import flash_shapes_ok

    return flash_shapes_ok(q.shape[2], q.shape[-1])


@register("fused_multihead_attention")
def fused_multihead_attention(ctx, ins, attrs):
    from ..parallel.ring_attention import (
        key_bias_from_attn_bias,
        ring_attention_global,
        use_ring,
    )

    q3, k3, v3 = ins["Q"][0], ins["K"][0], ins["V"][0]
    bias = ins.get("BiasQK", [None])[0]
    nh = int(attrs["num_heads"])
    dropout_prob = float(attrs.get("dropout_prob", 0.0))
    is_test = bool(attrs.get("is_test", False))
    causal = bool(attrs.get("causal", False))

    if use_ring(ctx, attrs):
        # sequence-parallel ring attention over the "sp" mesh axis; probs
        # dropout is applied inside the ring (numerator-only masking)
        b, s, h = q3.shape
        key_bias = key_bias_from_attn_bias(bias, b)
        dkey = None
        if not is_test and dropout_prob > 0.0:
            dkey = ctx.salted_rng(int(attrs.get("rng_salt", 0)))
        out = ring_attention_global(
            _split_heads(q3, nh), _split_heads(k3, nh), _split_heads(v3, nh),
            ctx.mesh, axis="sp", bias=key_bias, causal=causal,
            dropout_prob=0.0 if is_test else dropout_prob, dropout_key=dkey,
        )
        return {"Out": [_merge_heads(out)]}

    # BSH fast path: no head transposes, rectangular (cross-attention)
    # q/kv lengths included — per-key ([B,1,1,S]) or absent bias only.
    # BiasQK gets a ZERO cotangent on every kernel path of this op (the
    # BHSD call below also defaults bias_requires_grad=False): the op's
    # bias contract is an additive mask derived from data, not a
    # trainable parameter.
    from .pallas.flash_attention import bsh_dispatch_ok

    sq, skv, h = q3.shape[1], k3.shape[1], q3.shape[2]
    if bsh_dispatch_ok(sq, skv, h, nh, bias=bias, batch=q3.shape[0],
                       causal=causal):
        from .pallas.flash_attention import flash_attention_bsh

        dkey = None
        if not is_test and dropout_prob > 0.0:
            dkey = ctx.salted_rng(int(attrs.get("rng_salt", 0)))
        out = flash_attention_bsh(
            q3, k3, v3, bias, num_heads=nh, causal=causal,
            dropout_prob=0.0 if is_test else dropout_prob,
            dropout_key=dkey, mesh=ctx.mesh,
        )
        return {"Out": [out]}

    q = _split_heads(q3, nh)
    k = _split_heads(k3, nh)
    v = _split_heads(v3, nh)

    # full [.., S, S] biases on square q/kv lengths ride the BHSD kernel;
    # everything else falls through to the jnp composition
    if _use_pallas(q) and q.shape[2] == k.shape[2]:
        from .pallas.flash_attention import flash_attention

        dkey = None
        if not is_test and dropout_prob > 0.0:
            dkey = ctx.salted_rng(int(attrs.get("rng_salt", 0)))
        out = flash_attention(
            q, k, v, bias, causal=causal,
            dropout_prob=0.0 if is_test else dropout_prob,
            dropout_key=dkey, mesh=ctx.mesh,
        )
    else:
        if causal:
            import numpy as _np

            s = q.shape[2]
            cmask = jnp.where(
                _np.tril(_np.ones((s, s), bool)), 0.0, -1e30
            )[None, None, :, :]
            bias = cmask if bias is None else bias + cmask
        rng = None
        if not is_test and dropout_prob > 0.0:
            rng = ctx.salted_rng(int(attrs.get("rng_salt", 0)))
        # zero-cotangent BiasQK contract: the kernel paths above never
        # produce a dbias, so the fallback must not either — a shape or
        # backend change would otherwise flip gradient semantics
        if bias is not None:
            bias = jax.lax.stop_gradient(bias)
        out = _reference_attention(q, k, v, bias, dropout_prob, is_test, rng)
    return {"Out": [_merge_heads(out)]}
