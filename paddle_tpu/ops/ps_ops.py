"""distributed_lookup_table: device <-> host-PS embedding bridge.

Parity: reference operators/distributed_ops/distributed_lookup_table
(trainer side) + the pserver optimizer block it pairs with. The forward
gathers rows from the host table via jax.pure_callback; the backward is
an io_callback that PUSHES the rows' gradients to the server, which
applies its own optimizer (ps.ShardedHostTable.push_gradients) — so the
device-side program never materializes or differentiates the table.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register


@functools.lru_cache(maxsize=64)
def _lookup_fn(table_name: str, dim: int, out_dtype: str):
    from ..distributed import ps

    dt = jnp.dtype(out_dtype)

    @jax.custom_vjp
    def lookup(ids, anchor):
        # `anchor` is the (1,) float Parameter that carries the vjp to
        # this op: gradients only propagate along DIFFERENTIABLE inputs,
        # and ids are integers — without a float input on the custom_vjp
        # itself, jax.vjp would treat the lookup as a constant and the
        # backward push would never run
        flat = ids.reshape(-1)
        rows = jax.pure_callback(
            lambda i: ps.get_table(table_name).gather(i).astype(out_dtype),
            jax.ShapeDtypeStruct((flat.shape[0], dim), dt),
            flat,
        )
        return rows.reshape(ids.shape + (dim,)) + (anchor[0] * 0).astype(dt)

    def fwd(ids, anchor):
        return lookup(ids, anchor), (ids, anchor)

    def bwd(res, g):
        ids, anchor = res
        flat = ids.reshape(-1)
        gflat = g.reshape(flat.shape[0], dim)

        def push(i, gr):
            ps.get_table(table_name).push_gradients(i, gr)
            return np.int32(0)

        from jax.experimental import io_callback

        # pin the push to one device: SPMD partitioning forbids replicated
        # side-effecting custom-calls, and the server update must apply
        # exactly once per step regardless of mesh size. Unordered: within
        # a step the push is data-dependent on the gather (through the
        # loss), and cross-step reordering is the documented async-PS
        # (Downpour) semantics — ordered=True would also thread a token
        # whose replicated tuple sharding the SPMD partitioner rejects
        token = io_callback(
            push, jax.ShapeDtypeStruct((), jnp.int32), flat, gflat,
            sharding=jax.sharding.SingleDeviceSharding(jax.devices()[0]),
        )
        # anchor's gradient is identically zero; the token dependency
        # keeps the push effect anchored in the cotangent
        danchor = jnp.zeros_like(anchor) + token.astype(anchor.dtype) * 0
        return (None, danchor)

    lookup.defvjp(fwd, bwd)
    return lookup


@register("distributed_lookup_table", no_vjp_grad=False)
def distributed_lookup_table(ctx, ins, attrs):
    """Inputs: Ids [B,...] int; W — a (1,) zero anchor Parameter (the
    trainer-side stub: autodiff's needs-grad walk seeds from Parameters,
    and the host table is NOT a program Parameter by design, so the
    anchor is what makes backward reach this op; its own gradient is
    identically zero)."""
    from ..distributed import ps

    if jax.default_backend() == "axon":
        # the axon dev tunnel proxies PJRT without host send/recv, so
        # pure_callback/io_callback cannot run; real TPU hosts support
        # them (this is a tunnel limitation, not a TPU one)
        raise NotImplementedError(
            "distributed_lookup_table needs host callbacks, which the "
            "axon dev tunnel does not support; run on a real TPU host or "
            "the CPU backend"
        )
    ids = ins["Ids"][0]
    name = attrs["table_names"][0] if "table_names" in attrs else attrs["table_name"]
    table = ps.get_table(name)
    fn = _lookup_fn(name, table.dim, str(np.dtype(table.dtype)))
    anchor = (
        ins["W"][0] if ins.get("W") else jnp.zeros((1,), jnp.float32)
    )
    return {"Outputs": [fn(ids, anchor)]}
