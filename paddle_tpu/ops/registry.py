"""Op registry: op type -> JAX emitter (+ optional overrides).

TPU-native replacement for the reference's operator registry
(/root/reference/paddle/fluid/framework/op_registry.h:223,
 operator.cc:908 RunImpl kernel dispatch). Instead of per-(place,dtype)
kernels, each op registers ONE `emit` function mapping JAX values -> JAX
values. The Executor traces a whole block of emitters into a single jitted
function, so XLA sees the full graph and fuses across op boundaries — there
is no per-op dispatch at runtime.

Three services are derived from the same emitter:
  * execution  — emitters called under jax.jit trace
  * shape/dtype inference — jax.eval_shape over the emitter (framework.py)
  * autodiff   — a synthesized `<op>_grad` op whose emitter is jax.vjp of
                 the forward emitter (see grad_emit below); ops with
                 randomness or data-dependent residuals register explicit
                 grad ops instead (e.g. dropout_grad uses the saved Mask).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

Ins = Dict[str, List[Any]]  # slot -> list of jax values
Attrs = Dict[str, Any]


class EmitContext:
    """Per-trace context handed to emitters (rng threading, mesh info)."""

    def __init__(self, rng_key=None, mesh=None, axis_env=None):
        self._key = rng_key
        self._base_key = rng_key  # frozen per-step key for salted_rng
        self.mesh = mesh
        # mapping of logical ring_id -> mesh axis name, for collective ops
        self.axis_env = axis_env or {}

    def rng(self):
        """Split and return a fresh PRNG key (functional rng threading)."""
        import jax

        if self._key is None:
            self._key = jax.random.PRNGKey(0)
        self._key, sub = jax.random.split(self._key)
        return sub

    def salted_rng(self, salt: int):
        """Deterministic per-op key: fold a graph-build-time salt into the
        per-step base key. Unlike rng(), the result does not depend on trace
        order, so an op with internal randomness (fused attention dropout)
        gets the SAME mask when its forward emitter is re-traced under
        jax.vjp by the generic grad path — no saved mask needed."""
        import jax

        base = self._base_key
        if base is None:
            base = jax.random.PRNGKey(0)
        return jax.random.fold_in(base, salt)

    @property
    def rng_state(self):
        return self._key


@dataclasses.dataclass
class OpSpec:
    type: str
    emit: Callable[[EmitContext, Ins, Attrs], Dict[str, List[Any]]]
    # explicit shape inference override (rarely needed; control flow etc.)
    infer_shape: Optional[Callable] = None
    no_infer: bool = False
    # custom grad-op builder: fn(op, out_grads: {slot: [names]|None})
    #   -> (list_of_op_descs, {fwd_in_slot: [grad_names]})
    grad_maker: Optional[Callable] = None
    # ops that must NOT take the generic vjp grad path (randomness /
    # non-differentiable): they either register grad_maker or are leaves
    no_vjp_grad: bool = False
    # stateless ops whose outputs are never differentiable (compare etc.)
    stop_gradient: bool = False


_REGISTRY: Dict[str, OpSpec] = {}


def register(
    type: str,
    *,
    infer_shape=None,
    no_infer=False,
    grad_maker=None,
    no_vjp_grad=False,
    stop_gradient=False,
):
    """Decorator: register `emit` for op `type`."""

    def deco(emit_fn):
        _REGISTRY[type] = OpSpec(
            type=type,
            emit=emit_fn,
            infer_shape=infer_shape,
            no_infer=no_infer,
            grad_maker=grad_maker,
            no_vjp_grad=no_vjp_grad,
            stop_gradient=stop_gradient,
        )
        return emit_fn

    return deco


def set_grad_maker(type: str, grad_maker):
    _REGISTRY[type].grad_maker = grad_maker


def get(type: str) -> Optional[OpSpec]:
    spec = _REGISTRY.get(type)
    if spec is not None:
        return spec
    # lazily synthesize generic vjp-based grad ops: "<base>_grad"
    if type.endswith("_grad"):
        base = _REGISTRY.get(type[: -len("_grad")])
        if base is not None and not base.no_vjp_grad:
            spec = OpSpec(type=type, emit=_make_generic_grad_emit(base))
            _REGISTRY[type] = spec
            return spec
    return None


def registered_ops() -> List[str]:
    return sorted(_REGISTRY)


# ---------------------------------------------------------------------------
# generic vjp grad
# ---------------------------------------------------------------------------

GRAD = "@GRAD"


def _make_generic_grad_emit(base: OpSpec):
    """Build the emitter for `<base>_grad`.

    Grad-op convention (established by backward.append_backward):
      inputs : forward inputs under their original slots, plus available
               output grads under "<out_slot>@GRAD"
      outputs: input grads under "<in_slot>@GRAD"
      attrs  : forward attrs + "__fwd_in_slots__" (list of fwd input slots)

    The emitter re-traces the forward emitter under jax.vjp; XLA CSE folds
    the duplicated pure forward subgraph with the primal one, so this costs
    no extra FLOPs at runtime while staying exactly consistent with the
    forward lowering.
    """
    import jax
    import jax.numpy as jnp

    def grad_emit(ctx: EmitContext, ins: Ins, attrs: Attrs):
        fwd_attrs = {k: v for k, v in attrs.items() if not k.startswith("__")}
        in_slots = list(attrs["__fwd_in_slots__"])
        fwd_ins = {s: list(ins[s]) for s in in_slots if s in ins}

        def fn(fi):
            return base.emit(ctx, fi, fwd_attrs)

        outs, vjp_fn = jax.vjp(fn, fwd_ins)
        cot = {}
        for slot, vals in outs.items():
            gs = ins.get(slot + GRAD)
            cs = []
            for i, v in enumerate(vals):
                g = gs[i] if gs is not None and i < len(gs) and gs[i] is not None else None
                if not jnp.issubdtype(v.dtype, jnp.floating) and not jnp.issubdtype(
                    v.dtype, jnp.complexfloating
                ):
                    cs.append(np.zeros(v.shape, jax.dtypes.float0))
                elif g is None:
                    cs.append(jnp.zeros(v.shape, v.dtype))
                else:
                    cs.append(jnp.asarray(g, v.dtype))
            cot[slot] = cs
        (d_ins,) = vjp_fn(cot)
        result = {}
        for slot in fwd_ins:
            gvals = d_ins.get(slot)
            if gvals is None:
                continue
            cleaned = []
            for g, v in zip(gvals, fwd_ins[slot]):
                if g is None or (hasattr(g, "dtype") and g.dtype == jax.dtypes.float0):
                    cleaned.append(jnp.zeros(jnp.shape(v), jnp.result_type(v)) if v is not None else None)
                else:
                    cleaned.append(g)
            result[slot + GRAD] = cleaned
        return result

    return grad_emit


# ---------------------------------------------------------------------------
# block emission: shared by the Executor's whole-block trace and by
# control-flow op emitters (cond/while) that recursively evaluate sub-blocks
# ---------------------------------------------------------------------------


def emit_ops(ctx: EmitContext, ops, env: Dict[str, Any]) -> Dict[str, Any]:
    """Trace a list of framework Operators into JAX values. `env` maps var
    name -> value and is mutated in place (op outputs land there)."""
    for op in ops:
        spec = get(op.type)
        if spec is None:
            raise KeyError(f"op {op.type!r} has no registered emitter")
        ins = {}
        for slot, names in op.inputs.items():
            vals = []
            for n in names:
                if n not in env:
                    raise RuntimeError(
                        f"op {op.type}: input var {n!r} not produced, fed, "
                        f"captured, nor in scope"
                    )
                vals.append(env[n])
            if vals:
                ins[slot] = vals
        outs = spec.emit(ctx, ins, op.attrs)
        for slot, names in op.outputs.items():
            vals = outs.get(slot)
            if vals is None:
                continue
            for n, v in zip(names, vals):
                env[n] = v
    return env


# ---------------------------------------------------------------------------
# abstract evaluation (shape/dtype inference service for framework.py)
# ---------------------------------------------------------------------------


def abstract_eval(op_type: str, in_metas, attrs, dyn_probe: int):
    """Run the emitter under jax.eval_shape.

    in_metas: {slot: [(shape|None, np.dtype)]}; -1 dims replaced by
    dyn_probe. Returns {slot: [(shape, dtype)]}.
    """
    import jax

    spec = get(op_type)
    structs = {}
    for slot, metas in in_metas.items():
        structs[slot] = [
            jax.ShapeDtypeStruct(
                tuple(dyn_probe if d == -1 else d for d in (shape or ())), dtype
            )
            for shape, dtype in metas
        ]

    def fn(ins):
        ctx = EmitContext(rng_key=jax.random.PRNGKey(0))
        return spec.emit(ctx, ins, dict(attrs))

    out = jax.eval_shape(fn, structs)
    return {
        slot: [(tuple(int(d) for d in v.shape), np.dtype(v.dtype)) for v in vals]
        for slot, vals in out.items()
    }
