"""Op registry: op type -> JAX emitter (+ optional overrides).

TPU-native replacement for the reference's operator registry
(/root/reference/paddle/fluid/framework/op_registry.h:223,
 operator.cc:908 RunImpl kernel dispatch). Instead of per-(place,dtype)
kernels, each op registers ONE `emit` function mapping JAX values -> JAX
values. The Executor traces a whole block of emitters into a single jitted
function, so XLA sees the full graph and fuses across op boundaries — there
is no per-op dispatch at runtime.

Three services are derived from the same emitter:
  * execution  — emitters called under jax.jit trace
  * shape/dtype inference — jax.eval_shape over the emitter (framework.py)
  * autodiff   — a synthesized `<op>_grad` op whose emitter is jax.vjp of
                 the forward emitter (see grad_emit below); ops with
                 randomness or data-dependent residuals register explicit
                 grad ops instead (e.g. dropout_grad uses the saved Mask).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

Ins = Dict[str, List[Any]]  # slot -> list of jax values
Attrs = Dict[str, Any]


class EmitContext:
    """Per-trace context handed to emitters (rng threading, mesh info)."""

    def __init__(self, rng_key=None, mesh=None, axis_env=None,
                 manual_axes=None, op_scopes=False):
        self._key = rng_key
        self._base_key = rng_key  # frozen per-step key for salted_rng
        self.mesh = mesh
        # FLAGS_op_profile: emit_ops wraps each op's lowering in
        # jax.named_scope("op<idx>:<type>") so device profiles attribute
        # back to Program IR ops (telemetry/cost.py). Trace-time only.
        self.op_scopes = bool(op_scopes)
        # mapping of logical ring_id -> mesh axis name, for collective ops
        self.axis_env = axis_env or {}
        # mesh axes the surrounding shard_map runs MANUALLY over (the
        # executor's multi-slice dcn mode); emitters needing collectives
        # use lax.p* with these names directly
        self.manual_axes = tuple(manual_axes) if manual_axes else ()
        # (type, fwd input names) -> LIFO of (outs, vjp_fn, fwd_ins):
        # captured at forward emission, consumed by the generic grad op —
        # the primal forward is computed ONCE (emitting the backward by
        # re-tracing would duplicate it; XLA cannot CSE two while loops
        # whose bodies differ, so a scanned encoder would run twice)
        self.vjp_cache: Dict[tuple, list] = {}

    def rng(self):
        """Split and return a fresh PRNG key (functional rng threading)."""
        import jax

        if self._key is None:
            self._key = jax.random.PRNGKey(0)
        self._key, sub = jax.random.split(self._key)
        return sub

    def salted_rng(self, salt: int):
        """Deterministic per-op key: fold a graph-build-time salt into the
        per-step base key. Unlike rng(), the result does not depend on trace
        order, so an op with internal randomness (fused attention dropout)
        gets the SAME mask when its forward emitter is re-traced under
        jax.vjp by the generic grad path — no saved mask needed."""
        import jax

        base = self._base_key
        if base is None:
            base = jax.random.PRNGKey(0)
        return jax.random.fold_in(base, salt)

    @property
    def rng_state(self):
        return self._key


@dataclasses.dataclass
class OpSpec:
    type: str
    emit: Callable[[EmitContext, Ins, Attrs], Dict[str, List[Any]]]
    # explicit shape inference override (rarely needed; control flow etc.)
    infer_shape: Optional[Callable] = None
    no_infer: bool = False
    # custom grad-op builder: fn(op, out_grads: {slot: [names]|None})
    #   -> (list_of_op_descs, {fwd_in_slot: [grad_names]})
    grad_maker: Optional[Callable] = None
    # ops that must NOT take the generic vjp grad path (randomness /
    # non-differentiable): they either register grad_maker or are leaves
    no_vjp_grad: bool = False
    # stateless ops whose outputs are never differentiable (compare etc.)
    stop_gradient: bool = False
    # True for lazily synthesized "<base>_grad" specs (generic vjp)
    generic_vjp: bool = False


_REGISTRY: Dict[str, OpSpec] = {}


def register(
    type: str,
    *,
    infer_shape=None,
    no_infer=False,
    grad_maker=None,
    no_vjp_grad=False,
    stop_gradient=False,
):
    """Decorator: register `emit` for op `type`."""

    def deco(emit_fn):
        _REGISTRY[type] = OpSpec(
            type=type,
            emit=emit_fn,
            infer_shape=infer_shape,
            no_infer=no_infer,
            grad_maker=grad_maker,
            no_vjp_grad=no_vjp_grad,
            stop_gradient=stop_gradient,
        )
        return emit_fn

    return deco


def set_grad_maker(type: str, grad_maker):
    _REGISTRY[type].grad_maker = grad_maker


def get(type: str) -> Optional[OpSpec]:
    spec = _REGISTRY.get(type)
    if spec is not None:
        return spec
    # lazily synthesize generic vjp-based grad ops: "<base>_grad"
    if type.endswith("_grad"):
        base = _REGISTRY.get(type[: -len("_grad")])
        if base is not None and not base.no_vjp_grad:
            spec = OpSpec(
                type=type, emit=_make_generic_grad_emit(base), generic_vjp=True
            )
            _REGISTRY[type] = spec
            return spec
    return None


def registered_ops() -> List[str]:
    return sorted(_REGISTRY)


# ---------------------------------------------------------------------------
# generic vjp grad
# ---------------------------------------------------------------------------

GRAD = "@GRAD"


def _apply_vjp(ins: Ins, outs, vjp_fn, fwd_ins):
    """Build cotangents from the grad op's "<slot>@GRAD" inputs, run the
    vjp, and clean the input gradients (zeros for float0/None)."""
    import jax
    import jax.numpy as jnp

    cot = {}
    for slot, vals in outs.items():
        gs = ins.get(slot + GRAD)
        cs = []
        for i, v in enumerate(vals):
            g = gs[i] if gs is not None and i < len(gs) and gs[i] is not None else None
            if not jnp.issubdtype(v.dtype, jnp.floating) and not jnp.issubdtype(
                v.dtype, jnp.complexfloating
            ):
                cs.append(np.zeros(v.shape, jax.dtypes.float0))
            elif g is None:
                cs.append(jnp.zeros(v.shape, v.dtype))
            else:
                cs.append(jnp.asarray(g, v.dtype))
        cot[slot] = cs
    (d_ins,) = vjp_fn(cot)
    result = {}
    for slot in fwd_ins:
        gvals = d_ins.get(slot)
        if gvals is None:
            continue
        cleaned = []
        for g, v in zip(gvals, fwd_ins[slot]):
            if g is None or (hasattr(g, "dtype") and g.dtype == jax.dtypes.float0):
                cleaned.append(jnp.zeros(jnp.shape(v), jnp.result_type(v)) if v is not None else None)
            else:
                cleaned.append(g)
        result[slot + GRAD] = cleaned
    return result


def _make_generic_grad_emit(base: OpSpec):
    """Build the FALLBACK emitter for `<base>_grad` (used when the primal
    vjp was not captured — e.g. gradients() called on a block whose
    forward was emitted in a different trace).

    Grad-op convention (established by backward.append_backward):
      inputs : forward inputs under their original slots, plus available
               output grads under "<out_slot>@GRAD"
      outputs: input grads under "<in_slot>@GRAD"
      attrs  : forward attrs + "__fwd_in_slots__" (list of fwd input slots)

    The fast path lives in emit_ops: when the forward op of this grad op
    was emitted in the same trace, its captured (outs, vjp_fn) pair is
    reused and the forward is NOT re-traced.
    """
    import jax

    def grad_emit(ctx: EmitContext, ins: Ins, attrs: Attrs):
        fwd_attrs = {k: v for k, v in attrs.items() if not k.startswith("__")}
        in_slots = list(attrs["__fwd_in_slots__"])
        fwd_ins = {s: list(ins[s]) for s in in_slots if s in ins}

        def fn(fi):
            return base.emit(ctx, fi, fwd_attrs)

        outs, vjp_fn = jax.vjp(fn, fwd_ins)
        return _apply_vjp(ins, outs, vjp_fn, fwd_ins)

    return grad_emit


# ---------------------------------------------------------------------------
# block emission: shared by the Executor's whole-block trace and by
# control-flow op emitters (cond/while) that recursively evaluate sub-blocks
# ---------------------------------------------------------------------------


def _attrs_sig(attrs):
    """Stable signature of forward attrs. The grad desc carries a shallow
    COPY of the forward attrs (backward.py: dict(op.attrs)), so contained
    objects (Blocks, callables) are identical and repr() is consistent
    between the pair."""
    return tuple(sorted(
        (k, repr(v)) for k, v in attrs.items() if not k.startswith("__")
    ))


def _fwd_key_from_fwd(op):
    # attrs are part of the key: two same-type ops over the same inputs
    # but different attrs (e.g. scale by 2 vs 3) must not share a vjp
    return (op.type, tuple(sorted(
        (s, tuple(ns)) for s, ns in op.inputs.items() if ns
    )), _attrs_sig(op.attrs))


def _fwd_key_from_grad(op):
    slots = op.attrs.get("__fwd_in_slots__", ())
    return (op.type[: -len("_grad")], tuple(sorted(
        (s, tuple(op.inputs.get(s, ()))) for s in slots if op.inputs.get(s)
    )), _attrs_sig(op.attrs))


def emit_ops(ctx: EmitContext, ops, env: Dict[str, Any],
             on_op=None) -> Dict[str, Any]:
    """Trace a list of framework Operators into JAX values. `env` maps var
    name -> value and is mutated in place (op outputs land there).

    on_op: optional per-op probe called as on_op(op_idx, op, outs) AFTER
    the op's outputs land in env — the numerics doctor's instrumented
    eager replay hangs its finiteness checks here (telemetry/numerics.
    bisect_first_nonfinite). None (the default) costs nothing.

    Primal reuse: forward ops whose generic grad op appears later in the
    list are emitted under jax.vjp ONCE; the grad op consumes the stored
    vjp instead of re-tracing the forward (a re-traced scanned encoder
    would otherwise run twice — XLA cannot CSE differing while loops).

    Op-scope tagging (ctx.op_scopes, FLAGS_op_profile): every op's
    emission is wrapped in jax.named_scope("op<idx>:<type>") so each HLO
    instruction's op_name metadata carries the Program IR position of
    the op that lowered it — the join key telemetry/cost.py aggregates
    xplane device events by. Grad-op backward compute (the cached vjp_fn
    call) is tagged at the GRAD op's index; sub-block emitters recursing
    through emit_ops nest their scopes under the parent op's."""
    import contextlib

    import jax

    def _scope(idx, op):
        if not ctx.op_scopes:
            return contextlib.nullcontext()
        return jax.named_scope(f"op{idx}:{op.type}")

    wanted: Dict[tuple, int] = {}
    for op in ops:
        if op.type.endswith("_grad"):
            spec = get(op.type)
            if spec is not None and spec.generic_vjp:
                k = _fwd_key_from_grad(op)
                wanted[k] = wanted.get(k, 0) + 1

    for op_idx, op in enumerate(ops):
        spec = get(op.type)
        if spec is None:
            raise KeyError(f"op {op.type!r} has no registered emitter")
        ins = {}
        for slot, names in op.inputs.items():
            vals = []
            for n in names:
                if n not in env:
                    raise RuntimeError(
                        f"op {op.type}: input var {n!r} not produced, fed, "
                        f"captured, nor in scope"
                    )
                vals.append(env[n])
            if vals:
                ins[slot] = vals

        with _scope(op_idx, op):
            outs = None
            if spec.generic_vjp:
                cached = ctx.vjp_cache.get(_fwd_key_from_grad(op))
                if cached:
                    f_outs, vjp_fn, fwd_ins = cached.pop()
                    outs = _apply_vjp(ins, f_outs, vjp_fn, fwd_ins)
            elif (
                not spec.no_vjp_grad
                and not spec.stop_gradient
                and spec.grad_maker is None
                and wanted.get(_fwd_key_from_fwd(op), 0) > 0
            ):
                key = _fwd_key_from_fwd(op)
                attrs = op.attrs

                def fn(fi, _spec=spec, _attrs=attrs):
                    return _spec.emit(ctx, fi, _attrs)

                outs, vjp_fn = jax.vjp(fn, ins)
                ctx.vjp_cache.setdefault(key, []).append((outs, vjp_fn, ins))
                wanted[key] -= 1
            if outs is None:
                outs = spec.emit(ctx, ins, op.attrs)
        for slot, names in op.outputs.items():
            vals = outs.get(slot)
            if vals is None:
                continue
            for n, v in zip(names, vals):
                env[n] = v
        if on_op is not None:
            on_op(op_idx, op, outs)
    return env


# ---------------------------------------------------------------------------
# abstract evaluation (shape/dtype inference service for framework.py)
# ---------------------------------------------------------------------------


def abstract_eval(op_type: str, in_metas, attrs, dyn_probe: int):
    """Run the emitter under jax.eval_shape.

    in_metas: {slot: [(shape|None, np.dtype)]}; -1 dims replaced by
    dyn_probe. Returns {slot: [(shape, dtype)]}.
    """
    import jax

    spec = get(op_type)
    structs = {}
    for slot, metas in in_metas.items():
        structs[slot] = [
            jax.ShapeDtypeStruct(
                tuple(dyn_probe if d == -1 else d for d in (shape or ())), dtype
            )
            for shape, dtype in metas
        ]

    def fn(ins):
        ctx = EmitContext(rng_key=jax.random.PRNGKey(0))
        return spec.emit(ctx, ins, dict(attrs))

    out = jax.eval_shape(fn, structs)
    return {
        slot: [(tuple(int(d) for d in v.shape), np.dtype(v.dtype)) for v in vals]
        for slot, vals in out.items()
    }
