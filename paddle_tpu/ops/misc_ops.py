"""Breadth ops: activations, selection, uniqueness, hashing, metrics.

Parity surface: reference operators/ selu_op.cc, activation_op.cc
(brelu/soft_relu/stanh), multiplex_op.cc, unique_with_counts_op.cc (+
unique_op.cc), sampling_id_op.cc, hash_op.cc, mean_iou_op.cc,
data_norm_op.cc, row_conv_op.cc, im2sequence_op.cc, shuffle_channel_op.cc,
space_to_depth_op.cc, bilinear_tensor_product_op.cc, spectral_norm_op.cc.

Static-shape notes: `unique`/`unique_with_counts` return SAME-SIZE outputs
(the unique prefix followed by padding) plus a scalar count — XLA cannot
produce data-dependent shapes; callers slice with the count host-side.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..fluid.dtypes import runtime_dtype
from .registry import register


@register("selu")
def selu(ctx, ins, attrs):
    x = ins["X"][0]
    scale = float(attrs.get("scale", 1.0507009873554805))
    alpha = float(attrs.get("alpha", 1.6732632423543772))
    return {"Out": [scale * jnp.where(x > 0, x, alpha * (jnp.exp(x) - 1.0))]}


@register("brelu")
def brelu(ctx, ins, attrs):
    x = ins["X"][0]
    t_min = float(attrs.get("t_min", 0.0))
    t_max = float(attrs.get("t_max", 24.0))
    return {"Out": [jnp.clip(x, t_min, t_max)]}


@register("soft_relu")
def soft_relu(ctx, ins, attrs):
    x = ins["X"][0]
    t = float(attrs.get("threshold", 40.0))
    return {"Out": [jnp.log1p(jnp.exp(jnp.clip(x, -t, t)))]}


@register("stanh")
def stanh(ctx, ins, attrs):
    x = ins["X"][0]
    a = float(attrs.get("scale_a", 0.67))
    b = float(attrs.get("scale_b", 1.7159))
    return {"Out": [b * jnp.tanh(a * x)]}


@register("multiplex")
def multiplex(ctx, ins, attrs):
    """Ids [B,1] selects which of the N stacked X tensors supplies row b
    (reference multiplex_op.cc)."""
    ids = ins["Ids"][0].astype(jnp.int32).reshape(-1)
    xs = jnp.stack(ins["X"], axis=0)  # [N, B, ...]
    rows = jnp.arange(xs.shape[1])
    return {"Out": [xs[ids, rows]]}


@register("unique_with_counts", stop_gradient=True, no_vjp_grad=True)
def unique_with_counts(ctx, ins, attrs):
    """1-D unique with static output sizes: Out is [N] (unique prefix;
    jnp.unique(size=..., fill_value=None) pads the tail by REPEATING THE
    SMALLEST unique value), Index [N] maps x -> position in Out, Count
    [N] (0 beyond the unique prefix — use it or UniqueCount to find the
    real prefix length), UniqueCount [] scalar."""
    x = ins["X"][0].reshape(-1)
    uniq, idx, counts = jnp.unique(
        x, return_inverse=True, return_counts=True, size=x.shape[0],
        fill_value=None,
    )
    n_unique = (counts > 0).sum()
    return {
        "Out": [uniq],
        "Index": [idx.astype(jnp.int32).reshape(-1)],
        "Count": [counts.astype(jnp.int32)],
        "UniqueCount": [n_unique.astype(jnp.int32)],
    }


@register("unique", stop_gradient=True, no_vjp_grad=True)
def unique(ctx, ins, attrs):
    r = unique_with_counts(ctx, ins, attrs)
    return {"Out": r["Out"], "Index": r["Index"], "UniqueCount": r["UniqueCount"]}


@register("sampling_id", stop_gradient=True, no_vjp_grad=True)
def sampling_id(ctx, ins, attrs):
    """Sample one class id per row from probabilities X [B, C]
    (reference sampling_id_op.cc)."""
    x = ins["X"][0]
    key = ctx.salted_rng(int(attrs.get("rng_salt", 0))) if attrs.get(
        "rng_salt") is not None else ctx.rng()
    ids = jax.random.categorical(key, jnp.log(jnp.maximum(x, 1e-30)), axis=-1)
    return {"Out": [ids.astype(runtime_dtype("int64"))]}


@register("hash", stop_gradient=True, no_vjp_grad=True)
def hash_op(ctx, ins, attrs):
    """Deterministic integer hashing of int ids into [0, mod_by) with
    num_hash independent hash functions (reference hash_op.cc uses xxhash;
    here a Knuth multiplicative mix — different values, same contract:
    deterministic, well-spread)."""
    x = ins["X"][0].astype(jnp.uint32)
    num_hash = int(attrs.get("num_hash", 1))
    mod_by = int(attrs.get("mod_by", 1))
    outs = []
    for i in range(num_hash):
        h = (x + jnp.uint32(i * 0x9E3779B9)) * jnp.uint32(2654435761)
        h = h ^ (h >> 16)
        h = h * jnp.uint32(0x85EBCA6B)
        h = h ^ (h >> 13)
        outs.append((h % jnp.uint32(mod_by)).astype(runtime_dtype("int64")))
    # reference emits [rows, num_hash, 1] for [rows, 1] input
    return {"Out": [jnp.stack(outs, axis=1).reshape(x.shape[0], num_hash, -1)]}


@register("mean_iou", stop_gradient=True, no_vjp_grad=True)
def mean_iou(ctx, ins, attrs):
    """Mean intersection-over-union over classes (reference mean_iou_op.cc).
    Predictions/Labels int [*]; num_classes static."""
    pred = ins["Predictions"][0].reshape(-1).astype(jnp.int32)
    label = ins["Labels"][0].reshape(-1).astype(jnp.int32)
    nc = int(attrs["num_classes"])
    p1 = jax.nn.one_hot(pred, nc, dtype=jnp.float32)
    l1 = jax.nn.one_hot(label, nc, dtype=jnp.float32)
    inter = (p1 * l1).sum(0)
    union = p1.sum(0) + l1.sum(0) - inter
    present = union > 0
    iou = jnp.where(present, inter / jnp.maximum(union, 1.0), 0.0)
    miou = iou.sum() / jnp.maximum(present.sum(), 1)
    return {
        "OutMeanIou": [miou.astype(jnp.float32)],
        "OutWrong": [(l1.sum(0) - inter).astype(jnp.int32)],
        "OutCorrect": [inter.astype(jnp.int32)],
    }


@register("data_norm")
def data_norm(ctx, ins, attrs):
    """Normalization from accumulated batch statistics (reference
    data_norm_op.cc, CTR models): scale/shift derived from running
    size/sum/squared-sum accumulators rather than per-batch stats."""
    x = ins["X"][0]
    bsize = ins["BatchSize"][0]
    bsum = ins["BatchSum"][0]
    bsq = ins["BatchSquareSum"][0]
    eps = float(attrs.get("epsilon", 1e-4))
    means = bsum / bsize
    # reference data_norm_op.cc:302: scale = sqrt(size / square_sum) —
    # no mean^2 subtraction (the accumulators are mean-removed upstream)
    scales = jnp.sqrt(bsize / jnp.maximum(bsq, eps))
    out = (x - means) * scales
    return {"Y": [out], "Means": [means], "Scales": [scales]}


@register("row_conv")
def row_conv(ctx, ins, attrs):
    """Lookahead row convolution (reference row_conv_op.cc): X [B,T,D],
    Filter [future_context+1, D]; out[t] = sum_k f[k] * x[t+k]."""
    x, f = ins["X"][0], ins["Filter"][0]
    ctx_len = f.shape[0]
    padded = jnp.pad(x, [(0, 0), (0, ctx_len - 1), (0, 0)])
    out = sum(
        padded[:, k : k + x.shape[1]] * f[k][None, None, :]
        for k in range(ctx_len)
    )
    return {"Out": [out]}


@register("im2sequence", stop_gradient=False)
def im2sequence(ctx, ins, attrs):
    """Slide a window over [N,C,H,W] and lay patches out as a sequence
    [N, L, C*kh*kw] (reference im2sequence_op.cc; dense analog of its
    LoD output)."""
    x = ins["X"][0]
    kh, kw = [int(v) for v in attrs["kernels"]]
    sh, sw = [int(v) for v in attrs.get("strides", [1, 1])]
    pads = [int(v) for v in attrs.get("paddings", [0, 0, 0, 0])]
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), [(pads[0], pads[2]), (pads[1], pads[3])],
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )  # [N, C*kh*kw, Ho, Wo]
    n, ckk, ho, wo = patches.shape
    return {"Out": [patches.reshape(n, ckk, ho * wo).transpose(0, 2, 1)]}


@register("shuffle_channel")
def shuffle_channel(ctx, ins, attrs):
    x = ins["X"][0]
    g = int(attrs.get("group", 1))
    n, c, h, w = x.shape
    out = x.reshape(n, g, c // g, h, w).swapaxes(1, 2).reshape(n, c, h, w)
    return {"Out": [out]}


@register("space_to_depth")
def space_to_depth(ctx, ins, attrs):
    x = ins["X"][0]
    bs = int(attrs.get("blocksize", 1))
    n, c, h, w = x.shape
    out = x.reshape(n, c, h // bs, bs, w // bs, bs)
    out = out.transpose(0, 3, 5, 1, 2, 4)
    return {"Out": [out.reshape(n, c * bs * bs, h // bs, w // bs)]}


@register("bilinear_tensor_product")
def bilinear_tensor_product(ctx, ins, attrs):
    """out[b,k] = x[b] @ W[k] @ y[b] + bias[k] (reference
    bilinear_tensor_product_op.cc)."""
    x, y, w = ins["X"][0], ins["Y"][0], ins["Weight"][0]
    out = jnp.einsum("bi,kij,bj->bk", x, w, y)
    if ins.get("Bias"):
        out = out + ins["Bias"][0]
    return {"Out": [out]}


@register("spectral_norm")
def spectral_norm(ctx, ins, attrs):
    """Weight / sigma_max via power iteration with carried U/V vectors
    (reference spectral_norm_op.cc)."""
    w, u, v = ins["Weight"][0], ins["U"][0], ins["V"][0]
    dim = int(attrs.get("dim", 0))
    iters = int(attrs.get("power_iters", 1))
    eps = float(attrs.get("eps", 1e-12))
    perm = [dim] + [i for i in range(w.ndim) if i != dim]
    mat = jnp.transpose(w, perm).reshape(w.shape[dim], -1)
    u = u.reshape(-1)
    v = v.reshape(-1)
    for _ in range(iters):
        v = mat.T @ u
        v = v / (jnp.linalg.norm(v) + eps)
        u = mat @ v
        u = u / (jnp.linalg.norm(u) + eps)
    sigma = u @ mat @ v
    return {"Out": [w / sigma]}


@register("histogram", stop_gradient=True, no_vjp_grad=True)
def histogram(ctx, ins, attrs):
    """Fixed-bin histogram (reference histogram_op.cc): min==max==0 uses
    the data's own range."""
    x = ins["X"][0].reshape(-1).astype(jnp.float32)
    bins = int(attrs.get("bins", 100))
    lo = float(attrs.get("min", 0))
    hi = float(attrs.get("max", 0))
    if lo == 0.0 and hi == 0.0:
        lo_v, hi_v = jnp.min(x), jnp.max(x)
    else:
        lo_v, hi_v = jnp.float32(lo), jnp.float32(hi)
    span = jnp.maximum(hi_v - lo_v, 1e-30)
    idx = jnp.clip(((x - lo_v) / span * bins).astype(jnp.int32), 0, bins - 1)
    inside = (x >= lo_v) & (x <= hi_v)
    out = jnp.zeros((bins,), jnp.int32).at[idx].add(inside.astype(jnp.int32))
    return {"Out": [out]}


@register("nonzero_static", stop_gradient=True, no_vjp_grad=True)
def nonzero_static(ctx, ins, attrs):
    """Static-shape nonzero: [numel, ndim] indices with the valid rows
    first (original order) and -1 padding, plus a scalar count."""
    x = ins["X"][0]
    flat = (x != 0).reshape(-1)
    numel = flat.shape[0]
    order = jnp.argsort(~flat, stable=True)  # nonzero positions first
    count = flat.sum().astype(jnp.int32)
    pos = jnp.where(jnp.arange(numel) < count, order, -1)
    idx = []
    rem = pos
    for dim in reversed(x.shape):
        idx.append(jnp.where(pos >= 0, rem % dim, -1))
        rem = rem // dim
    out = jnp.stack(idx[::-1], axis=1).astype(jnp.int32)
    return {"Out": [out], "Count": [count]}


@register("randperm", stop_gradient=True, no_vjp_grad=True)
def randperm(ctx, ins, attrs):
    """Random permutation of [0, n) (reference randperm_op.cc)."""
    from ..fluid.dtypes import convert_dtype

    n = int(attrs["n"])
    key = ctx.salted_rng(int(attrs.get("rng_salt", 0)))
    perm = jax.random.permutation(key, n)
    return {"Out": [perm.astype(runtime_dtype(attrs.get("dtype", "int64")))]}


@register("tanh_shrink")
def tanh_shrink(ctx, ins, attrs):
    x = ins["X"][0]
    return {"Out": [x - jnp.tanh(x)]}


@register("diag_embed")
def diag_embed(ctx, ins, attrs):
    """[..., N] -> [..., N, N] with the input on the main diagonal
    (reference diag_embed_op.cc, main-diagonal case)."""
    x = ins["X"][0]
    n = x.shape[-1]
    return {"Out": [x[..., None] * jnp.eye(n, dtype=x.dtype)]}


@register("precision_recall", stop_gradient=True, no_vjp_grad=True)
def precision_recall(ctx, ins, attrs):
    """Streaming multi-class precision/recall/F1 (reference
    operators/metrics/precision_recall_op.cc): Indices [N,1] predicted
    class, Labels [N,1], optional Weights [N,1]; StatesInfo [C,4] carries
    (TP, FP, TN, FN) per class across batches. Outputs BatchMetrics and
    AccumMetrics as [6]: macro-P, macro-R, macro-F1, micro-P, micro-R,
    micro-F1."""
    idx = ins["Indices"][0].reshape(-1).astype(jnp.int32)
    lbl = ins["Labels"][0].reshape(-1).astype(jnp.int32)
    w = (ins["Weights"][0].reshape(-1).astype(jnp.float32)
         if ins.get("Weights") else jnp.ones(idx.shape, jnp.float32))
    states = ins["StatesInfo"][0].astype(jnp.float32)  # [C, 4]
    c = states.shape[0]
    pred1 = jax.nn.one_hot(idx, c, dtype=jnp.float32) * w[:, None]
    lab1 = jax.nn.one_hot(lbl, c, dtype=jnp.float32) * w[:, None]
    tp = (pred1 * (idx == lbl)[:, None].astype(jnp.float32)).sum(0)
    fp = pred1.sum(0) - tp
    fn = lab1.sum(0) - tp
    tn = w.sum() - tp - fp - fn

    def metrics(tp_, fp_, fn_):
        prec = jnp.where(tp_ + fp_ > 0, tp_ / jnp.maximum(tp_ + fp_, 1e-10), 0.0)
        rec = jnp.where(tp_ + fn_ > 0, tp_ / jnp.maximum(tp_ + fn_, 1e-10), 0.0)
        f1 = jnp.where(prec + rec > 0,
                       2 * prec * rec / jnp.maximum(prec + rec, 1e-10), 0.0)
        return prec, rec, f1

    def six(tp_, fp_, fn_):
        p, r, f = metrics(tp_, fp_, fn_)
        mp, mr, mf = p.mean(), r.mean(), f.mean()
        up, ur, uf = metrics(tp_.sum(), fp_.sum(), fn_.sum())
        return jnp.stack([mp, mr, mf, up, ur, uf])

    batch = six(tp, fp, fn)
    new_states = states + jnp.stack([tp, fp, tn, fn], axis=1)
    accum = six(new_states[:, 0], new_states[:, 1], new_states[:, 3])
    return {"BatchMetrics": [batch], "AccumMetrics": [accum],
            "AccumStatesInfo": [new_states]}


def _tree_conv_coeffs(edges, n, max_depth):
    """Host-side tree2col coefficients (reference operators/math/
    tree2col.cc behavior, contract pinned by test_tree_conv_op.py's
    naive oracle): C[b, u, v, k] = eta_k of node v in node u's patch
    (nodes within `max_depth` hops, coefficients from depth and sibling
    position). Integer tree structure only — no gradients flow here."""
    import numpy as np

    edges = np.asarray(edges)
    b = edges.shape[0]
    out = np.zeros((b, n, n, 3), np.float32)
    for bi in range(b):
        children = [[] for _ in range(n + 2)]
        for p, c in edges[bi].tolist():
            if p >= 1:
                children[int(p)].append(int(c))

        for u in range(1, n + 1):
            # (node, idx-among-siblings, n-siblings, depth); a per-root
            # visited set (reference construct_patch) counts each node
            # once even with duplicate edges or multi-parent EdgeSets
            stack = [(u, 1, 1, 0)]
            visited = set()
            entries = []
            while stack:
                node, idx, l, depth = stack.pop()
                if node in visited:
                    continue
                visited.add(node)
                entries.append((node, idx, l, depth))
                if depth + 1 < max_depth:
                    ch = children[node]
                    for i, c in enumerate(ch, 1):
                        stack.append((c, i, len(ch), depth + 1))
            for node, idx, l, depth in entries:
                eta_t = float(max_depth - depth) / float(max_depth)
                eta_l = (1.0 - eta_t) * (
                    0.5 if l == 1 else float(idx - 1) / float(l - 1))
                eta_r = (1.0 - eta_t) * (1.0 - eta_l)
                out[bi, u - 1, node - 1, 0] += eta_l
                out[bi, u - 1, node - 1, 1] += eta_r
                out[bi, u - 1, node - 1, 2] += eta_t
    return out


@register("tree_conv")
def tree_conv(ctx, ins, attrs):
    """Tree-based convolution (TBCNN; reference tree_conv_op.cc): the
    data-dependent patch structure is built HOST-side from the integer
    EdgeSet (stop-gradient), and the learnable math is one einsum —
    fully differentiable wrt NodesVector and Filter on device."""
    nodes = ins["NodesVector"][0]          # [B, N, FS]
    edges = ins["EdgeSet"][0]              # [B, E, 2] int
    w = ins["Filter"][0]                   # [FS, 3, OUT, NF]
    max_depth = int(attrs.get("max_depth", 2))
    bsz, n, _fs = nodes.shape

    if jax.default_backend() == "axon":
        raise NotImplementedError(
            "tree_conv builds patches via host callbacks, which the axon "
            "dev tunnel does not support; run on a real TPU host or the "
            "CPU backend")
    import functools as _ft

    coeffs = jax.pure_callback(
        _ft.partial(_tree_conv_coeffs, n=n, max_depth=max_depth),
        jax.ShapeDtypeStruct((bsz, n, n, 3), jnp.float32),
        edges,
    )
    coeffs = jax.lax.stop_gradient(coeffs)
    out = jnp.einsum("buvk,bvi,ikof->buof", coeffs,
                     nodes.astype(jnp.float32), w.astype(jnp.float32))
    return {"Out": [out.astype(nodes.dtype)]}


@register("tensor_stats", stop_gradient=True, no_vjp_grad=True)
def tensor_stats(ctx, ins, attrs):
    """Numerics observability reduction (telemetry/numerics.py,
    FLAGS_tensor_stats): one pass over X producing the (4,) float32
    vector [nan_count, inf_count, max_abs_finite, l2_finite]. Emitted
    next to the op that produced X, so XLA fuses it into the step and
    the host only pays the sampled device->host read of the stat var.
    max/l2 run over the FINITE elements (a single Inf must not flatten
    the rest of the series to Inf)."""
    x = ins["X"][0]
    xf = x.astype(jnp.float32)
    finite = jnp.isfinite(xf)
    nan_ct = jnp.sum(jnp.isnan(xf)).astype(jnp.float32)
    inf_ct = jnp.sum(jnp.isinf(xf)).astype(jnp.float32)
    safe = jnp.where(finite, xf, 0.0)
    max_abs = jnp.max(jnp.abs(safe)) if xf.size else jnp.float32(0.0)
    l2 = jnp.sqrt(jnp.sum(jnp.square(safe)))
    return {"Out": [jnp.stack([nan_ct, inf_ct,
                               jnp.asarray(max_abs, jnp.float32),
                               l2])]}
