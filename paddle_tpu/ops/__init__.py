"""Op library: JAX emitters registered by op type.

Importing this package registers all built-in ops (the analog of the
reference's static REGISTER_OPERATOR initializers).
"""
from . import registry  # noqa: F401
from . import (  # noqa: F401
    attention,
    collective_ops,
    compare_ops,
    control_flow_ops,
    creation,
    detection2_ops,
    detection3_ops,
    detection_ops,
    encoder_stack,
    manipulation,
    math_ops,
    misc_ops,
    moe_ops,
    nn_ops,
    optimizer_ops,
    ps_ops,
    quant_ops,
    recompute,
    reduce_ops,
    sequence_ops,
    vision_ops,
)
from .registry import EmitContext, OpSpec, get, register, registered_ops  # noqa: F401
