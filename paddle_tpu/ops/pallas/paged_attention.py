"""Paged decode-step attention — one query token per sequence attending
over a paged KV cache.

The serving engine (inference/engine.py) keeps each request's KV history
in fixed-size pages of a preallocated HBM pool (inference/kv_cache.py).
At every decode step each active slot owns one query vector and a page
table naming which physical pages hold its history; this op computes

    o[b] = softmax(q[b] . K[b]^T * sm_scale) V[b]

where K[b]/V[b] are the first ``lengths[b]`` logical positions gathered
through ``page_table[b]``.  Two implementations with identical math:

* ``jnp``   — gather pages into a dense [B, T, H, D] view and run a
  stable fp32 softmax.  Reference semantics; used on CPU and for GQA.
* ``pallas`` — a TPU kernel over grid (batch, pages) that streams one
  KV page per step through VMEM using ``PrefetchScalarGridSpec``: the
  page table and lengths are scalar-prefetched so each k/v BlockSpec
  index map can chase ``table[b, p]`` and DMA the right physical page
  while the previous one computes.  Online softmax state (m, l, acc)
  lives in VMEM scratch and persists across the page dimension, so the
  output block is written once on the last page.

Pages past a sequence's length are fully masked (they contribute
exp(-inf) = 0), so garbage table entries beyond the live range are
harmless as long as they index real pages — the pool reserves physical
page 0 as a trash page for exactly this.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import _interpret

_NEG_INF = float("-inf")


def _ref_paged_attention(q, k_pages, v_pages, page_table, lengths,
                         sm_scale):
    """Dense-gather reference: exact math of the kernel, any backend."""
    b, h, d = q.shape
    _, page, kh, _ = k_pages.shape
    maxp = page_table.shape[1]
    t = maxp * page
    # [B, maxp, page, KH, D] -> [B, T, KH, D]
    k = k_pages[page_table].reshape(b, t, kh, d)
    v = v_pages[page_table].reshape(b, t, kh, d)
    if kh != h:  # grouped-query: repeat shared KV heads
        rep = h // kh
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = jnp.einsum("bhd,bthd->bht", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    pos = jnp.arange(t, dtype=jnp.int32)[None, None, :]
    s = jnp.where(pos < lengths[:, None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bht,bthd->bhd", p / l,
                   v.astype(jnp.float32))
    return o.astype(q.dtype)


def _paged_kernel(table_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                  m_ref, l_ref, acc_ref, *, page, sm_scale, maxp):
    b = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    length = len_ref[b]
    pos = p * page + jax.lax.broadcasted_iota(jnp.int32, (1, page), 1)
    q = q_ref[0].astype(jnp.float32)          # [H, D]
    k = k_ref[0].astype(jnp.float32)          # [page, H, D]
    v = v_ref[0].astype(jnp.float32)
    h = q.shape[0]

    def head(i, _):
        qh = jax.lax.dynamic_slice_in_dim(q, i, 1, axis=0)   # [1, D]
        kh = jax.lax.dynamic_slice_in_dim(k, i, 1, axis=1)[:, 0, :]
        vh = jax.lax.dynamic_slice_in_dim(v, i, 1, axis=1)[:, 0, :]
        s = jax.lax.dot_general(
            qh, kh, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale   # [1, page]
        s = jnp.where(pos < length, s, _NEG_INF)
        m_prev = m_ref[i, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s))
        # all-masked page: keep state unchanged (exp(-inf - -inf) trap)
        alpha = jnp.where(jnp.isfinite(m_new),
                          jnp.exp(m_prev - m_new), 1.0)
        pw = jnp.where(jnp.isfinite(m_new), jnp.exp(s - m_new), 0.0)
        m_ref[i, 0] = m_new
        l_ref[i, 0] = l_ref[i, 0] * alpha + jnp.sum(pw)
        pv = jax.lax.dot_general(
            pw, vh, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # [1, D]
        acc_ref[i, :] = acc_ref[i, :] * alpha + pv[0]
        return 0

    jax.lax.fori_loop(0, h, head, 0)

    @pl.when(p == maxp - 1)
    def _emit():
        l = jnp.maximum(l_ref[...], 1e-30)                   # [H, 1]
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _pallas_paged_attention(q, k_pages, v_pages, page_table, lengths,
                            sm_scale):
    b, h, d = q.shape
    n_pages, page, kh, _ = k_pages.shape
    assert kh == h, "pallas path is MHA-only; GQA uses the jnp path"
    maxp = page_table.shape[1]
    kernel = functools.partial(_paged_kernel, page=page,
                               sm_scale=sm_scale, maxp=maxp)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, maxp),
        in_specs=[
            pl.BlockSpec((1, h, d), lambda bi, pi, tbl, lens: (bi, 0, 0)),
            pl.BlockSpec((1, page, h, d),
                         lambda bi, pi, tbl, lens: (tbl[bi, pi], 0, 0, 0)),
            pl.BlockSpec((1, page, h, d),
                         lambda bi, pi, tbl, lens: (tbl[bi, pi], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, h, d),
                               lambda bi, pi, tbl, lens: (bi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, d), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        interpret=_interpret(),
    )(page_table, lengths, q, k_pages, v_pages)


def paged_attention(q, k_pages, v_pages, page_table, lengths,
                    sm_scale=None, impl=None):
    """Decode-step attention over a paged KV pool.

    Args:
      q:          [B, H, D] one query token per slot.
      k_pages:    [P, page, KH, D] physical key pages (whole pool).
      v_pages:    [P, page, KH, D] physical value pages.
      page_table: [B, maxp] int32 physical page id per logical page.
      lengths:    [B] int32 live KV length per slot (0 => undefined
                  output for that slot; callers mask dead slots).
      sm_scale:   softmax scale; default 1/sqrt(D).
      impl:       'jnp' | 'pallas' | None (env PADDLE_PAGED_ATTN_IMPL,
                  default: pallas when MHA, jnp otherwise).
    """
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    if impl is None:
        impl = os.environ.get("PADDLE_PAGED_ATTN_IMPL", "auto")
    if impl == "auto":
        # kernel on real TPU backends for MHA; dense-gather reference
        # otherwise (GQA, and CPU tests — interpret mode is for parity
        # checks, not the serving hot loop)
        impl = ("pallas" if not _interpret()
                and q.shape[1] == k_pages.shape[2] else "jnp")
    if impl == "pallas":
        return _pallas_paged_attention(q, k_pages, v_pages,
                                       page_table, lengths, sm_scale)
    if impl == "jnp":
        return _ref_paged_attention(q, k_pages, v_pages,
                                    page_table, lengths, sm_scale)
    raise ValueError(f"unknown paged-attention impl {impl!r}")
