"""Flash attention (online-softmax) Pallas TPU kernel with custom VJP.

TPU-native replacement for the reference's fused BERT attention CUDA kernel
(/root/reference/paddle/fluid/operators/math/bert_encoder_functor.cu —
softmax over scores in shared memory) — here the whole attention is one
kernel: scores never materialize in HBM (O(S) memory instead of O(S^2)),
and the backward pass recomputes probabilities blockwise from the saved
log-sum-exp, the standard flash-attention-2 scheme.

Capabilities (round 2 — all TPU-lowering-legal layouts):
  - additive bias: per-key [B,1,1,S] (BERT padding mask, cheap correct
    dbias) or full [B,nh,S,S] / [B,1,S,S] / [1,1,S,S]
  - causal masking with block-level skipping (lower-triangular work only)
  - attention-probs dropout folded into the kernel: on TPU the mask is
    regenerated from the hardware PRNG (pltpu.prng_*) per (bh, q-block,
    k-block) in both forward and backward — zero HBM traffic for masks.
    Masking only the numerator accumulator and never the normalizer is
    exactly post-softmax dropout (same scheme as parallel/ring_attention).
    In interpret mode (CPU tests) the TPU PRNG is unavailable, so the
    mask is precomputed host-side and passed as an input — the dropout
    MATH (fwd + custom VJP) is identical and fully testable on CPU.
  - SPMD: `mesh=` wraps the kernel in shard_map over (dp, tp) — batch on
    dp, heads on tp (megatron split); dropout seeds are decorrelated per
    shard and per-key dbias is psum'd over tp.

Layout rules honored (Mosaic requires the last two block dims divisible
by (8, 128) or equal to the array dims): lse/delta ride as
[BH, NQ, 1, BQ]; the per-key bias as [B, 1, S].

Block sizes are 128 to match the MXU; S must be a multiple of 128.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

MIN_BLOCK = 128


def _pick_block(s):
    """Largest block that tiles s, capped at 512: at BERT-scale sequence
    lengths the whole score tile fits VMEM and bigger dots keep the MXU
    busy (128-blocks are latency-bound: profiled 4x slower at S=512)."""
    for cand in (512, 256, 128):
        if s % cand == 0:
            return cand
    raise ValueError(f"seq {s} not a multiple of {MIN_BLOCK}")
NEG_INF = -1e30

# mixing constants for the per-(bh, qi, ki) dropout seed (fwd and bwd must
# regenerate the exact same mask for a block pair); wrapped to signed i32
_SEED_BH = 0x9E3779B9 - (1 << 32)
_SEED_QI = 0x85EBCA6B - (1 << 32)
_SEED_KI = 0xC2B2AE35 - (1 << 32)


def _interpret() -> bool:
    # 'axon' is a tunneled real TPU backend; anything else (cpu tests) runs
    # the kernel in interpreter mode for exact-semantics checking
    return jax.default_backend() not in ("tpu", "axon")


def _block_seed(seed_ref, b, qi, ki):
    base = seed_ref[0]
    return (
        base
        + b * jnp.int32(_SEED_BH)
        + qi * jnp.int32(_SEED_QI)
        + ki * jnp.int32(_SEED_KI)
    )


def _dropout_keep(seed_ref, b, qi, ki, keep_prob, bq, bk):
    """[bq, bk] keep mask from the TPU hardware PRNG.

    Compare in int32 throughout: Mosaic's u32 compare/shift lowerings are
    signed, so mask the sign bit off the bitcast bits and compare 23-bit
    values — well-defined signed arithmetic with ~8e6 resolution."""
    pltpu.prng_seed(_block_seed(seed_ref, b, qi, ki))
    bits = pltpu.bitcast(
        pltpu.prng_random_bits((bq, bk)), jnp.int32
    )
    thresh = jnp.int32(int(keep_prob * float(1 << 23)))
    return (bits & jnp.int32(0x7FFFFF)) < thresh


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _make_fwd_kernel(*, sm_scale, num_heads, causal, dropout_prob, bias_mode,
                     use_prng, has_mask, bq, bk):
    """bias_mode: None | 'key' ([B,1,S] input) | 'full' ([G,S,S] input)."""

    def kernel(*refs):
        it = iter(refs)
        q_ref = next(it)          # [1, BQ, D]
        k_ref = next(it)          # [1, S, D]
        v_ref = next(it)          # [1, S, D]
        bias_ref = next(it) if bias_mode else None
        mask_ref = next(it) if has_mask else None     # [1, BQ, S] uint8
        seed_ref = next(it) if use_prng else None     # [1] int32 (SMEM)
        o_ref = next(it)          # [1, BQ, D]
        lse_ref = next(it)        # [1, 1, 1, BQ]

        b = pl.program_id(0)
        qi = pl.program_id(1)
        # keep the input dtype (bf16 under AMP) for the MXU dots — f32
        # inputs would force multi-pass f32 matmuls; accumulate in f32
        q = q_ref[0]
        seq_len = k_ref.shape[1]
        d = q.shape[-1]
        keep_prob = 1.0 - dropout_prob

        def body(i, carry):
            m, l, acc = carry
            k = k_ref[0, pl.ds(i * bk, bk), :]
            v = v_ref[0, pl.ds(i * bk, bk), :]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            ) * sm_scale  # [BQ, BK]
            if bias_mode == "key":
                s = s + bias_ref[0, 0, pl.ds(i * bk, bk)][None, :]
            elif bias_mode == "full":
                s = s + bias_ref[0, :, pl.ds(i * bk, bk)].astype(jnp.float32)
            if causal:
                qpos = qi * bq + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, bk), 0
                )
                kpos = i * bk + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, bk), 1
                )
                s = jnp.where(qpos >= kpos, s, NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m - m_new)
            l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
            # numerator-only dropout: l accumulates undropped p, acc the
            # masked p/(keep_prob) — exactly post-softmax dropout
            p_num = p
            if dropout_prob > 0.0:
                if use_prng:
                    keep = _dropout_keep(seed_ref, b, qi, i, keep_prob, bq, bk)
                else:
                    keep = mask_ref[0, :, pl.ds(i * bk, bk)] != 0
                p_num = jnp.where(keep, p / keep_prob, 0.0)
            acc = acc * alpha + jax.lax.dot_general(
                p_num.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return m_new, l, acc

        m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
        l0 = jnp.zeros((bq, 1), jnp.float32)
        acc0 = jnp.zeros((bq, d), jnp.float32)
        hi = (qi + 1) if causal else (seq_len // bk)
        m, l, acc = jax.lax.fori_loop(0, hi, body, (m0, l0, acc0))
        l_safe = jnp.maximum(l, 1e-30)
        o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0, 0] = (m + jnp.log(l_safe))[:, 0]

    return kernel


def _flash_fwd(q, k, v, bias, mask, seed, *, sm_scale, num_heads, causal,
               dropout_prob, bias_mode, bias_dims):
    bh, s, d = q.shape
    bq = bk = _pick_block(s)
    nq = s // bq
    use_prng = dropout_prob > 0.0 and mask is None
    has_mask = mask is not None and dropout_prob > 0.0
    grid = (bh, nq)
    in_specs = [
        pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0), memory_space=pltpu.VMEM),
    ]
    args = [q, k, v]
    if bias_mode:
        dv_, md_ = _bias_row_map(bias_dims, num_heads)
        if bias_mode == "key":
            in_specs.append(
                pl.BlockSpec(
                    (1, 1, s),
                    lambda b, i, dv=dv_, md=md_: ((b // dv) % md, 0, 0),
                    memory_space=pltpu.VMEM,
                )
            )
        else:
            in_specs.append(
                pl.BlockSpec(
                    (1, bq, s),
                    lambda b, i, dv=dv_, md=md_: ((b // dv) % md, i, 0),
                    memory_space=pltpu.VMEM,
                )
            )
        args.append(bias)
    if has_mask:
        in_specs.append(
            pl.BlockSpec((1, bq, s), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM)
        )
        args.append(mask)
    if use_prng:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(seed)
    kernel = _make_fwd_kernel(
        sm_scale=sm_scale, num_heads=num_heads, causal=causal,
        dropout_prob=dropout_prob, bias_mode=bias_mode, use_prng=use_prng,
        has_mask=has_mask, bq=bq, bk=bk,
    )
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, 1, bq), lambda b, i: (b, i, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            jax.ShapeDtypeStruct((bh, nq, 1, bq), jnp.float32),
        ],
        interpret=_interpret(),
    )(*args)
    return o, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _make_bwd_dq_kernel(*, sm_scale, num_heads, causal, dropout_prob,
                        bias_mode, use_prng, has_mask, bq, bk):
    def kernel(*refs):
        it = iter(refs)
        q_ref = next(it)          # [1, BQ, D]
        k_ref = next(it)          # [1, S, D]
        v_ref = next(it)          # [1, S, D]
        bias_ref = next(it) if bias_mode else None
        mask_ref = next(it) if has_mask else None
        seed_ref = next(it) if use_prng else None
        do_ref = next(it)         # [1, BQ, D]
        lse_ref = next(it)        # [1, 1, 1, BQ]
        delta_ref = next(it)      # [1, 1, 1, BQ]
        dq_ref = next(it)         # [1, BQ, D]

        b = pl.program_id(0)
        qi = pl.program_id(1)
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, 0, 0][:, None]
        delta = delta_ref[0, 0, 0][:, None]
        seq_len = k_ref.shape[1]
        d = q.shape[-1]
        keep_prob = 1.0 - dropout_prob

        def body(i, dq):
            k = k_ref[0, pl.ds(i * bk, bk), :]
            v = v_ref[0, pl.ds(i * bk, bk), :]
            s = (
                jax.lax.dot_general(
                    q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
                )
                * sm_scale
            )
            if bias_mode == "key":
                s = s + bias_ref[0, 0, pl.ds(i * bk, bk)][None, :]
            elif bias_mode == "full":
                s = s + bias_ref[0, :, pl.ds(i * bk, bk)].astype(jnp.float32)
            if causal:
                qpos = qi * bq + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, bk), 0
                )
                kpos = i * bk + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, bk), 1
                )
                s = jnp.where(qpos >= kpos, s, NEG_INF)
            p = jnp.exp(s - lse)  # normalized probs P [BQ, BK]
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )
            if dropout_prob > 0.0:
                if use_prng:
                    keep = _dropout_keep(seed_ref, b, qi, i, keep_prob, bq, bk)
                else:
                    keep = mask_ref[0, :, pl.ds(i * bk, bk)] != 0
                c = jnp.where(keep, 1.0 / keep_prob, 0.0)
                ds = p * (c * dp - delta) * sm_scale
            else:
                ds = p * (dp - delta) * sm_scale
            return dq + jax.lax.dot_general(
                ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        hi = (qi + 1) if causal else (seq_len // bk)
        dq = jax.lax.fori_loop(
            0, hi, body, jnp.zeros((bq, d), jnp.float32)
        )
        dq_ref[0] = dq.astype(dq_ref.dtype)

    return kernel


def _make_bwd_dkv_kernel(*, sm_scale, num_heads, causal, dropout_prob,
                         bias_mode, use_prng, has_mask, want_dbias, bq, bk):
    """Grid (BH, NK); loops over q blocks. Also accumulates dbias:
    per-key mode -> row-sums into [1,1,1,BK]; full mode -> writes the
    [S, BK] column of ds (pre-scale) when want_dbias."""

    def kernel(*refs):
        it = iter(refs)
        q_ref = next(it)          # [1, S, D]
        k_ref = next(it)          # [1, BK, D]
        v_ref = next(it)          # [1, BK, D]
        bias_ref = next(it) if bias_mode else None
        mask_ref = next(it) if has_mask else None    # [1, S, BK]
        seed_ref = next(it) if use_prng else None
        do_ref = next(it)         # [1, S, D]
        lse_ref = next(it)        # [1, NQ, 1, BQ]
        delta_ref = next(it)      # [1, NQ, 1, BQ]
        dk_ref = next(it)         # [1, BK, D]
        dv_ref = next(it)         # [1, BK, D]
        dbias_key_ref = None
        dbias_full_ref = None
        if want_dbias and bias_mode == "key":
            dbias_key_ref = next(it)   # [1, 1, 1, BK]
        elif want_dbias and bias_mode == "full":
            dbias_full_ref = next(it)  # [1, S, BK]

        b = pl.program_id(0)
        ki = pl.program_id(1)
        k = k_ref[0]  # [BK, D]
        v = v_ref[0]
        seq_len = q_ref.shape[1]
        d = k.shape[-1]
        keep_prob = 1.0 - dropout_prob
        if bias_mode == "key":
            b_block = bias_ref[0, 0, pl.ds(ki * bk, bk)]
        if dbias_full_ref is not None:
            dbias_full_ref[0] = jnp.zeros_like(dbias_full_ref[0])

        def body(i, carry):
            dk, dv, dbsum = carry
            q = q_ref[0, pl.ds(i * bq, bq), :]
            do = do_ref[0, pl.ds(i * bq, bq), :]
            lse = lse_ref[0, i, 0][:, None]
            delta = delta_ref[0, i, 0][:, None]
            s = (
                jax.lax.dot_general(
                    q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
                )
                * sm_scale
            )
            if bias_mode == "key":
                s = s + b_block[None, :]
            elif bias_mode == "full":
                s = s + bias_ref[0, pl.ds(i * bq, bq), :].astype(jnp.float32)
            if causal:
                qpos = i * bq + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, bk), 0
                )
                kpos = ki * bk + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, bk), 1
                )
                s = jnp.where(qpos >= kpos, s, NEG_INF)
            p = jnp.exp(s - lse)  # [BQ, BK]
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )
            if dropout_prob > 0.0:
                if use_prng:
                    keep = _dropout_keep(seed_ref, b, i, ki, keep_prob, bq, bk)
                else:
                    keep = mask_ref[0, pl.ds(i * bq, bq), :] != 0
                c = jnp.where(keep, 1.0 / keep_prob, 0.0)
                p_num = p * c
            else:
                p_num = p
            dv = dv + jax.lax.dot_general(
                p_num.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            ds_nos = p * ((dp * (c if dropout_prob > 0.0 else 1.0)) - delta)
            ds = ds_nos * sm_scale  # [BQ, BK]
            dk = dk + jax.lax.dot_general(
                ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            if dbias_full_ref is not None:
                dbias_full_ref[0, pl.ds(i * bq, bq), :] = ds_nos.astype(
                    dbias_full_ref.dtype
                )
            if dbias_key_ref is not None:
                dbsum = dbsum + jnp.sum(ds_nos, axis=0)
            return dk, dv, dbsum

        dk0 = jnp.zeros((bk, d), jnp.float32)
        dv0 = jnp.zeros((bk, d), jnp.float32)
        db0 = jnp.zeros((bk,), jnp.float32)
        lo = ki if causal else 0
        dk, dv, dbsum = jax.lax.fori_loop(lo, seq_len // bq, body, (dk0, dv0, db0))
        dk_ref[0] = dk.astype(dk_ref.dtype)
        dv_ref[0] = dv.astype(dv_ref.dtype)
        if dbias_key_ref is not None:
            dbias_key_ref[0, 0, 0] = dbsum

    return kernel


def _flash_bwd(res, g, *, sm_scale, num_heads, causal, dropout_prob,
               bias_mode, bias_dims, want_dbias, g_lse=None):
    q, k, v, bias, mask, seed, o, lse = res
    bh, s, d = q.shape
    bq = bk = _pick_block(s)
    nq, nk = s // bq, s // bk
    use_prng = dropout_prob > 0.0 and mask is None
    has_mask = mask is not None and dropout_prob > 0.0
    delta = jnp.sum(o.astype(jnp.float32) * g.astype(jnp.float32), axis=-1)  # [BH,S]
    if g_lse is not None:
        # lse cotangent: d lse_i/d s_ij = P_ij, so ds gains +P*g_lse —
        # algebraically identical to subtracting g_lse from delta
        delta = delta - g_lse.astype(jnp.float32)
    delta = delta.reshape(bh, nq, 1, bq)

    qspec = pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0), memory_space=pltpu.VMEM)
    fullspec = pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0), memory_space=pltpu.VMEM)
    rowspec = pl.BlockSpec((1, 1, 1, bq), lambda b, i: (b, i, 0, 0), memory_space=pltpu.VMEM)
    fullrow = pl.BlockSpec((1, nq, 1, bq), lambda b, i: (b, 0, 0, 0), memory_space=pltpu.VMEM)

    dv_, md_ = _bias_row_map(bias_dims, num_heads) if bias_mode else (1, 1)

    def bias_specs(block_rows, rows_idx):
        if bias_mode == "key":
            return pl.BlockSpec(
                (1, 1, s),
                lambda b, i, dv=dv_, md=md_: ((b // dv) % md, 0, 0),
                memory_space=pltpu.VMEM,
            )
        return pl.BlockSpec(
            (1, block_rows, s) if rows_idx else (1, s, bk),
            (lambda b, i, dv=dv_, md=md_: ((b // dv) % md, i, 0))
            if rows_idx
            else (lambda b, i, dv=dv_, md=md_: ((b // dv) % md, 0, i)),
            memory_space=pltpu.VMEM,
        )

    # ---- dq: grid over q blocks
    args = [q, k, v]
    in_specs = [qspec, fullspec, fullspec]
    if bias_mode:
        in_specs.append(bias_specs(bq, True))
        args.append(bias)
    if has_mask:
        in_specs.append(
            pl.BlockSpec((1, bq, s), lambda b, i: (b, i, 0), memory_space=pltpu.VMEM)
        )
        args.append(mask)
    if use_prng:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(seed)
    in_specs += [qspec, rowspec, rowspec]
    args += [g, lse, delta]
    dq = pl.pallas_call(
        _make_bwd_dq_kernel(
            sm_scale=sm_scale, num_heads=num_heads, causal=causal,
            dropout_prob=dropout_prob, bias_mode=bias_mode, use_prng=use_prng,
            has_mask=has_mask, bq=bq, bk=bk,
        ),
        grid=(bh, nq),
        in_specs=in_specs,
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        interpret=_interpret(),
    )(*args)

    # ---- dk/dv (+dbias): grid over k blocks
    kspec = pl.BlockSpec((1, bk, d), lambda b, i: (b, i, 0), memory_space=pltpu.VMEM)
    fullq = pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0), memory_space=pltpu.VMEM)
    args2 = [q, k, v]
    in_specs2 = [fullq, kspec, kspec]
    if bias_mode:
        in_specs2.append(bias_specs(s, False))
        args2.append(bias)
    if has_mask:
        in_specs2.append(
            pl.BlockSpec((1, s, bk), lambda b, i: (b, 0, i), memory_space=pltpu.VMEM)
        )
        args2.append(mask)
    if use_prng:
        in_specs2.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args2.append(seed)
    in_specs2 += [fullq, fullrow, fullrow]
    args2 += [g, lse, delta]

    out_specs2 = [kspec, kspec]
    out_shapes2 = [
        jax.ShapeDtypeStruct((bh, s, d), k.dtype),
        jax.ShapeDtypeStruct((bh, s, d), v.dtype),
    ]
    if want_dbias and bias_mode == "key":
        out_specs2.append(
            pl.BlockSpec((1, 1, 1, bk), lambda b, i: (b, i, 0, 0), memory_space=pltpu.VMEM)
        )
        out_shapes2.append(jax.ShapeDtypeStruct((bh, nk, 1, bk), jnp.float32))
    elif want_dbias and bias_mode == "full":
        out_specs2.append(
            pl.BlockSpec((1, s, bk), lambda b, i: (b, 0, i), memory_space=pltpu.VMEM)
        )
        out_shapes2.append(jax.ShapeDtypeStruct((bh, s, s), jnp.float32))

    outs = pl.pallas_call(
        _make_bwd_dkv_kernel(
            sm_scale=sm_scale, num_heads=num_heads, causal=causal,
            dropout_prob=dropout_prob, bias_mode=bias_mode, use_prng=use_prng,
            has_mask=has_mask, want_dbias=want_dbias and bias_mode is not None,
            bq=bq, bk=bk,
        ),
        grid=(bh, nk),
        in_specs=in_specs2,
        out_specs=out_specs2,
        out_shape=out_shapes2,
        interpret=_interpret(),
    )(*args2)
    dk, dv = outs[0], outs[1]

    # reduce the raw dbias to bias3's shape ([G,1,S] key / [G,S,S] full);
    # JAX autodiff maps it back to the user's 4-D bias through the
    # reshape/astype that produced bias3
    dbias = None
    if want_dbias and bias_mode is not None:
        bb, bn = bias_dims
        batch = bh // num_heads
        if bias_mode == "key":
            # [BH, NK, 1, BK] -> [BH, S]; queries were summed in-kernel
            db = outs[2].reshape(batch, num_heads, s)
        else:
            db = outs[2].reshape(batch, num_heads, s, s)
        # sum grid cells that shared one bias row (broadcast transpose)
        if bn == 1 and num_heads > 1:
            db = db.sum(axis=1, keepdims=True)
        if bb == 1 and batch > 1:
            db = db.sum(axis=0, keepdims=True)
        if bias_mode == "key":
            dbias = db.reshape(bb, 1, s)
        else:
            dbias = db.reshape(bb * bn, s, s)
    return dq, dk, dv, dbias


# ---------------------------------------------------------------------------
# core with custom VJP (created per call; closes over static config)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=256)
def _make_flash_core(*, sm_scale, num_heads, causal, dropout_prob, bias_mode,
                     bias_dims, want_dbias):
    """Cached per static config: eager callers reuse the same custom_vjp
    (and therefore JAX's trace/lowering caches) across calls."""
    statics = dict(
        sm_scale=sm_scale, num_heads=num_heads, causal=causal,
        dropout_prob=dropout_prob, bias_mode=bias_mode, bias_dims=bias_dims,
    )

    @jax.custom_vjp
    def core(q, k, v, bias, mask, seed):
        o, _ = _flash_fwd(q, k, v, bias, mask, seed, **statics)
        return o

    def core_fwd(q, k, v, bias, mask, seed):
        o, lse = _flash_fwd(q, k, v, bias, mask, seed, **statics)
        return o, (q, k, v, bias, mask, seed, o, lse)

    def core_bwd(res, g):
        dq, dk, dv, dbias = _flash_bwd(
            res, g, want_dbias=want_dbias, **statics
        )
        if res[3] is not None and dbias is None:
            # bias_requires_grad=False: zero cotangent (padding masks)
            dbias = jnp.zeros_like(res[3])
        elif dbias is not None:
            dbias = dbias.astype(res[3].dtype)
        return (dq, dk, dv, dbias, None, None)

    core.defvjp(core_fwd, core_bwd)
    return core


# ---------------------------------------------------------------------------
# public entry: [B, nh, S, D] with bias/causal/dropout/SPMD
# ---------------------------------------------------------------------------


def _classify_bias(bias, b, nh, s):
    """Returns (bias_3d, bias_mode, (bb, bn)). bias_3d is a plain traced
    reshape of the user bias, so dbias (returned in bias_3d's shape) flows
    back to the user shape through ordinary autodiff.

    Grid cell bh = b_idx * nh + h_idx maps to bias row
    (bh // div) % mod with div = nh if bn == 1 else 1 and mod = bb * bn —
    covering all four broadcast patterns ([B|1, nh|1, ...])."""
    if bias is None:
        return None, None, None
    if bias.ndim != 4:
        raise ValueError(f"flash_attention bias must be 4-D, got {bias.shape}")
    bb, bn, bq, bk = bias.shape
    if bb not in (1, b) or bn not in (1, nh):
        raise ValueError(
            f"bias dims {bias.shape} not broadcastable to batch={b}, heads={nh}"
        )
    if bk != s:
        raise ValueError(f"bias key dim {bk} != seq {s}")
    if bn == 1 and bq == 1:
        # per-key padding mask [B|1, 1, 1, S] -> [G, 1, S]
        b3 = bias.reshape(bb, 1, s).astype(jnp.float32)
        return b3, "key", (bb, 1)
    if bq != s:
        raise ValueError(f"bias query dim {bq} != seq {s}")
    b3 = bias.reshape(bb * bn, s, s)
    return b3, "full", (bb, bn)


def _bias_row_map(bias_dims, num_heads):
    """(div, mod) such that bias row = (bh // div) % mod."""
    bb, bn = bias_dims
    return (num_heads if bn == 1 else 1), bb * bn


def _flash_local(q, k, v, bias, mask, seed, *, sm_scale, causal, dropout_prob,
                 bias_requires_grad):
    """[B, nh, S, D] local (per-shard) flash attention."""
    b, nh, s, d = q.shape
    bias3, bias_mode, bias_dims = _classify_bias(bias, b, nh, s)
    mask3 = mask.reshape(b * nh, s, s) if mask is not None else None
    qf = q.reshape(b * nh, s, d)
    kf = k.reshape(b * nh, s, d)
    vf = v.reshape(b * nh, s, d)
    core = _make_flash_core(
        sm_scale=float(sm_scale), num_heads=nh, causal=causal,
        dropout_prob=dropout_prob, bias_mode=bias_mode, bias_dims=bias_dims,
        want_dbias=bias_requires_grad and bias_mode is not None,
    )
    o = core(qf, kf, vf, bias3, mask3, seed)
    return o.reshape(b, nh, s, d)


def flash_attention(q, k, v, bias=None, sm_scale=None, causal=False,
                    dropout_prob=0.0, dropout_key=None, dropout_seed=None,
                    bias_requires_grad=False, mesh=None, batch_axis="dp",
                    head_axis="tp"):
    """Flash attention with optional bias, causal mask, dropout and SPMD.

    q, k, v: [B, nh, S, D]. bias: additive, [B,1,1,S] (per-key padding
    mask) or [B|1, nh|1, S, S]. Returns [B, nh, S, D].

    dropout: `dropout_prob` with either `dropout_key` (a jax PRNG key) or
    `dropout_seed` (int32 scalar). On TPU the mask comes from the in-kernel
    hardware PRNG; in interpret mode (CPU) it is precomputed host-side.

    bias_requires_grad=False returns zero cotangent for the bias (the
    padding-mask case); set True to compute the real dbias.

    mesh: wrap in shard_map over (batch_axis, head_axis) if present —
    batch sharded on dp, heads on tp (megatron attention).
    """
    b, nh, s, d = q.shape
    if s % MIN_BLOCK != 0:
        raise ValueError(f"flash_attention needs seq % {MIN_BLOCK} == 0, got {s}")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)

    seed = None
    mask = None
    if dropout_prob > 0.0:
        if dropout_seed is not None:
            seed = jnp.asarray(dropout_seed, jnp.int32).reshape(1)
        elif dropout_key is not None:
            seed = jax.random.randint(
                dropout_key, (1,), 0, jnp.iinfo(jnp.int32).max, dtype=jnp.int32
            )
        else:
            raise ValueError("dropout needs dropout_key or dropout_seed")
        if _interpret():
            # CPU tests: TPU hardware PRNG is unavailable in interpret
            # mode; draw the mask host-side (same numerator-only math)
            mkey = dropout_key if dropout_key is not None else jax.random.PRNGKey(
                seed[0]
            )
            mask = jax.random.bernoulli(
                jax.random.fold_in(mkey, 7), 1.0 - dropout_prob, (b, nh, s, s)
            ).astype(jnp.uint8)

    kwargs = dict(
        sm_scale=sm_scale, causal=causal, dropout_prob=dropout_prob,
        bias_requires_grad=bias_requires_grad,
    )

    axes = [
        ax for ax in (batch_axis, head_axis)
        if mesh is not None and ax in mesh.axis_names and mesh.shape[ax] > 1
    ]
    if not axes:
        return _flash_local(q, k, v, bias, mask, seed, **kwargs)

    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    ba = batch_axis if batch_axis in axes else None
    ha = head_axis if head_axis in axes else None
    qspec = P(ba, ha, None, None)

    def spec_for(x):
        if x is None:
            return None
        return P(
            ba if x.shape[0] != 1 else None,
            ha if x.shape[1] != 1 else None,
            None,
            None,
        )

    bias_spec = spec_for(bias)
    mask_spec = P(ba, ha, None, None) if mask is not None else None

    def body(ql, kl, vl, bl, ml, sl):
        local_seed = sl
        if sl is not None:
            import jax.lax as lax

            salt = jnp.int32(0)
            if ba:
                salt = salt + lax.axis_index(ba) * jnp.int32(0x632BE59B)
            if ha:
                salt = salt + lax.axis_index(ha) * jnp.int32(0x1B873593)
            local_seed = sl + salt
        out = _flash_local(ql, kl, vl, bl, ml, local_seed, **kwargs)
        return out

    in_specs = (qspec, qspec, qspec, bias_spec, mask_spec, P() if seed is not None else None)
    return shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=qspec, check_vma=False,
    )(q, k, v, bias, mask, seed)


@functools.lru_cache(maxsize=64)
def _make_flash_core_lse(*, sm_scale, num_heads, causal, dropout_prob,
                         bias_mode, bias_dims, want_dbias=False):
    """Like _make_flash_core but returns (o, lse [BH, S]) with a VJP that
    accepts cotangents for BOTH outputs (g_lse folds into delta). Built
    for ring attention, which merges per-block partials by lse."""
    statics = dict(
        sm_scale=sm_scale, num_heads=num_heads, causal=causal,
        dropout_prob=dropout_prob, bias_mode=bias_mode, bias_dims=bias_dims,
    )

    @jax.custom_vjp
    def core(q, k, v, bias, mask, seed):
        o, lse4 = _flash_fwd(q, k, v, bias, mask, seed, **statics)
        return o, lse4.reshape(q.shape[0], q.shape[1])

    def core_fwd(q, k, v, bias, mask, seed):
        o, lse4 = _flash_fwd(q, k, v, bias, mask, seed, **statics)
        return (o, lse4.reshape(q.shape[0], q.shape[1])), (
            q, k, v, bias, mask, seed, o, lse4,
        )

    def core_bwd(res, gs):
        g_o, g_lse = gs
        dq, dk, dv, dbias = _flash_bwd(
            res, g_o, want_dbias=want_dbias and bias_mode is not None,
            g_lse=g_lse, **statics
        )
        if res[3] is not None and dbias is None:
            dbias = jnp.zeros_like(res[3])
        elif dbias is not None:
            dbias = dbias.astype(res[3].dtype)
        return (dq, dk, dv, dbias, None, None)

    core.defvjp(core_fwd, core_bwd)
    return core


def flash_block_with_lse(q, k, v, key_bias=None, sm_scale=None,
                         bias_requires_grad=True):
    """One attention block for ring attention: q/k/v [B, nh, S, D] local
    shards, key_bias [B, S] additive per-key bias (rotating with K).
    Returns (out [B, nh, S, D], lse [B, nh, S]) for log-sum-exp merging
    across ring steps. No dropout/causal here — the ring caller falls
    back to the jnp path for those. Bias gradients are computed by
    default, matching the jnp ring block math."""
    b, nh, s, d = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    bias3 = None
    bias_mode = None
    bias_dims = None
    if key_bias is not None:
        bias3 = key_bias.reshape(b, 1, s).astype(jnp.float32)
        bias_mode, bias_dims = "key", (b, 1)
    core = _make_flash_core_lse(
        sm_scale=float(sm_scale), num_heads=nh, causal=False,
        dropout_prob=0.0, bias_mode=bias_mode, bias_dims=bias_dims,
        want_dbias=bias_requires_grad,
    )
    o, lse = core(
        q.reshape(b * nh, s, d), k.reshape(b * nh, s, d),
        v.reshape(b * nh, s, d), bias3, None, None,
    )
    return o.reshape(b, nh, s, d), lse.reshape(b, nh, s)


def flash_shapes_ok(s, d) -> bool:
    """THE shape/backend/flag gate for every flash dispatch site (the
    attention op, the encoder stack, and the ring path all call this)."""
    from ...fluid.flags import flag
    from ..attention import FORCE_PALLAS

    if not flag("FLAGS_use_flash_attention"):
        return False
    shapes_ok = d in (64, 128, 256) and s % MIN_BLOCK == 0
    if FORCE_PALLAS:
        return shapes_ok
    return shapes_ok and not _interpret()


flash_block_ok = flash_shapes_ok  # ring-path alias
