"""Flash attention (online-softmax) Pallas TPU kernel with custom VJP.

TPU-native replacement for the reference's fused BERT attention CUDA kernel
(/root/reference/paddle/fluid/operators/math/bert_encoder_functor.cu —
softmax over scores in shared memory) — here the whole attention is one
kernel: scores never materialize in HBM (O(S) memory instead of O(S^2)),
and the backward pass recomputes probabilities blockwise from the saved
log-sum-exp, the standard flash-attention-2 scheme.

Round-3 kernel layout (profiled on v5e):
  - head-group batching: each grid cell owns G (bh) rows and loops over
    them in-kernel, so DMA blocks are G x bigger and the lse/delta
    tensors tile cleanly;
  - lse and delta ride as [BH, S] f32 with (G, bq) blocks — the round-2
    [BH, NQ, 1, BQ] layout forced T(1,128) sub-tile writes that cost
    ~0.37 ms/layer (70% of the bare kernel!) in the fwd alone, and the
    same penalty again on the bwd reads;
  - the per-key additive bias (BERT padding mask) is pre-broadcast to
    [BH, S] outside the kernel — JAX autodiff turns the broadcast into
    the head/batch sum for dbias, so the kernels lose all bias
    row-mapping arithmetic ([B,nh,S,S]-style full bias keeps the row-map
    path at G=1; it is the rare configuration).

Capabilities:
  - additive bias: per-key [B,1,1,S] (BERT padding mask, cheap correct
    dbias) or full [B,nh,S,S] / [B,1,S,S] / [1,1,S,S]
  - causal masking with block-level skipping (lower-triangular work
    only), including a runtime (q_offset, k_offset) pair so ring
    attention can causal-mask blocks whose global positions are shifted
    relative to the local shard
  - attention-probs dropout folded into the kernel: on TPU the mask is
    regenerated from the hardware PRNG (pltpu.prng_*) per (bh, q-block,
    k-block) in both forward and backward — zero HBM traffic for masks.
    Masking only the numerator accumulator and never the normalizer is
    exactly post-softmax dropout (same scheme as parallel/ring_attention).
    In interpret mode (CPU tests) the TPU PRNG is unavailable, so the
    mask is precomputed host-side and passed as an input — the dropout
    MATH (fwd + custom VJP) is identical and fully testable on CPU.
  - SPMD: `mesh=` wraps the kernel in shard_map over (dp, tp) — batch on
    dp, heads on tp (megatron split); dropout seeds are decorrelated per
    shard and per-key dbias is psum'd over tp.

Block sizes cap at 512 to match VMEM; S must be a multiple of 128.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from ... import compat as _compat
from ...tuning import feasible as _feas
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

MIN_BLOCK = 128
NEG_INF = -1e30
# Scoped-VMEM headroom for the group-size estimate. Calibrated on v5e:
# the dq kernel at (G=3, s=4096, bq=512) — estimate 9.9MB — actually
# allocates 16.98M scoped and OOMs the 16M limit, while (G=2, s=4096,
# estimate 7.7MB) fits; 9.5MB rejects the former and keeps the latter.
_VMEM_BUDGET = 9 * 1024 * 1024 + 512 * 1024


def _pick_block(s):
    """Largest block that tiles s, capped at 512: the whole score tile
    fits VMEM and bigger dots keep the MXU busy (128-blocks are
    latency-bound: profiled 4x slower at S=512). PADDLE_FLASH_BLOCK
    overrides for tuning sweeps (must divide s)."""
    import os

    forced = int(os.environ.get("PADDLE_FLASH_BLOCK", "0"))
    if forced >= MIN_BLOCK and s % forced == 0:
        return forced
    for cand in (512, 256, 128):
        if s % cand == 0:
            return cand
    raise _feas.NoFeasibleConfig(
        "flash", {"s": s},
        [({"block": c}, f"{s} % {c} != 0") for c in (512, 256, 128)],
        detail=f"seq must be a multiple of {MIN_BLOCK}")


def _scan_groups(bh, env_var, fits):
    """Shared group-size scan: honor a (divisibility-checked) env
    override, else take the largest divisor of bh whose footprint
    estimate fits."""
    import os

    forced = int(os.environ.get(env_var, "0"))
    if forced > 0 and bh % forced == 0:
        return forced
    for g in (8, 6, 4, 3, 2, 1):
        if bh % g == 0 and fits(g):
            return g
    return 1


def _pick_group(bh, s, bq, d, full_bias):
    """Head-group size G: how many bh rows one grid cell owns. Bounded by
    a VMEM estimate (k/v resident per cell, double-buffered) and by
    divisibility of bh. full-bias mode pins G=1 (its row-map indexing is
    per-bh)."""
    if full_bias:
        return 1

    def fits(g):
        kv = 2 * g * s * d * 2 * 2       # k+v, bf16, double-buffered
        qo = 2 * g * bq * d * 2 * 2      # q+o blocks
        sc = 3 * bq * min(s, 512) * 4    # per-head f32 score temporaries
        return kv + qo + sc <= _VMEM_BUDGET

    return _scan_groups(bh, "PADDLE_FLASH_GROUP", fits)


# lse, delta, the pre-broadcast key bias and its gradient all ride as
# [BH, 1, S] with (G, 1, block) blocks: the trailing (1, block) dims
# satisfy Mosaic's tiling rule for ANY head-group size G (a plain
# (G, block) block would need G % 8 == 0), and the rows are written/read
# lane-major, which pairs with the MXU transpose trick below.


# mixing constants for the per-(bh, qi, ki) dropout seed (fwd and bwd must
# regenerate the exact same mask for a block pair); wrapped to signed i32
_SEED_BH = 0x9E3779B9 - (1 << 32)
_SEED_QI = 0x85EBCA6B - (1 << 32)
_SEED_KI = 0xC2B2AE35 - (1 << 32)


def _interpret() -> bool:
    # 'axon' is a tunneled real TPU backend; anything else (cpu tests) runs
    # the kernel in interpreter mode for exact-semantics checking
    return jax.default_backend() not in ("tpu", "axon")


def _dropout_quantized_thresh(keep_prob):
    """THE single source of the 8-bit dropout quantization: keep a byte
    iff byte < t, with t in [1, 256]. t == 256 keeps everything exactly
    (bytes are <= 255), so near-1.0 keep probabilities round to a true
    no-op instead of silently dropping 1/256. The numerator rescale must
    divide by t/256 — derive BOTH from this function or the mask and the
    rescale go out of sync (a systematic training bias)."""
    return max(1, min(256, round(keep_prob * 256)))


def _dropout_quantized_keep(keep_prob):
    """Effective keep probability of the quantized in-kernel mask."""
    return _dropout_quantized_thresh(keep_prob) / 256.0


def _dropout_keep(seed_ref, bh, qi, ki, keep_prob, bq, bk):
    """[bq, bk] keep mask from the TPU hardware PRNG.

    One generated u32 word feeds up to FOUR mask bytes (column blocks of
    bk // pack, pack = min(4, bk // 128) to keep 128-lane alignment):
    the PRNG was ~12% of the forward kernel at one word per element.
    Compare in int32 throughout — Mosaic's u32 lowerings are signed;
    bytes are masked to [0, 255] so the arithmetic stays well-defined."""
    pltpu.prng_seed(
        seed_ref[0]
        + bh * jnp.int32(_SEED_BH)
        + qi * jnp.int32(_SEED_QI)
        + ki * jnp.int32(_SEED_KI)
    )
    thresh = jnp.int32(_dropout_quantized_thresh(keep_prob))
    pack = min(4, bk // 128)
    if pack > 1:
        words = pltpu.bitcast(
            pltpu.prng_random_bits((bq, bk // pack)), jnp.int32
        )
        parts = [
            ((words >> jnp.int32(8 * c)) & jnp.int32(0xFF)) < thresh
            for c in range(pack)
        ]
        return jnp.concatenate(parts, axis=1)
    bits = pltpu.bitcast(pltpu.prng_random_bits((bq, bk)), jnp.int32)
    return (bits & jnp.int32(0xFF)) < thresh


def _identity(n):
    """[n, n] f32 identity for MXU-side layout transposes (built once per
    grid cell, outside the head loop)."""
    r = jax.lax.broadcasted_iota(jnp.int32, (n, n), 0)
    c = jax.lax.broadcasted_iota(jnp.int32, (n, n), 1)
    return (r == c).astype(jnp.float32)


def _to_lanes(x_sparse, ident):
    """(n, 1) sublane-major -> (1, n) lane-major via an MXU matmul.

    The VPU relayout Mosaic emits for a plain reshape walks 1-lane-wide
    vregs and costs ~0.7us per call (profiled: it was 40% of the whole
    fwd kernel); the [1,n]x[n,n] identity matmul is noise on the MXU."""
    return jax.lax.dot_general(
        x_sparse, ident, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _to_sublanes(x_lane, ident):
    """(1, n) lane-major -> (n, 1) sublane-major via an MXU matmul."""
    return jax.lax.dot_general(
        ident, x_lane, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _causal_mask(s, qglob, kglob, bq, bk):
    qpos = qglob + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    kpos = kglob + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return jnp.where(qpos >= kpos, s, NEG_INF)


def _hi_blocks(causal, qi, bq, bk, nk, q_off, k_off):
    """Number of k blocks a causal q block must visit. q_off/k_off are
    global offsets (ring attention); both 0 locally."""
    if not causal:
        return nk
    # last visible kpos = q_off + (qi+1)*bq - 1 - k_off
    last = q_off + (qi + 1) * bq - k_off
    return jnp.clip((last + bk - 1) // bk, 0, nk)


def _lo_blocks(causal, ki, bq, bk, nq, q_off, k_off):
    """First q block that sees causal k block ki (dkv loop lower bound)."""
    if not causal:
        return 0
    first = k_off + ki * bk - q_off  # lowest qpos that can see this block
    return jnp.clip(first // bq, 0, nq)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _make_fwd_kernel(*, sm_scale, causal, dropout_prob, bias_mode, use_prng,
                     has_mask, has_offsets, G, bq, bk, num_heads, bias_dims):
    """bias_mode: None | 'key' ([BH,S] pre-broadcast) | 'full' ([R,S,S])."""

    def kernel(*refs):
        it = iter(refs)
        q_ref = next(it)          # [G, BQ, D]
        k_ref = next(it)          # [G, S, D]
        v_ref = next(it)          # [G, S, D]
        bias_ref = next(it) if bias_mode else None
        mask_ref = next(it) if has_mask else None     # [G, BQ, S] uint8
        seed_ref = next(it) if use_prng else None     # [1] int32 (SMEM)
        off_ref = next(it) if has_offsets else None   # [2] int32 (SMEM)
        o_ref = next(it)          # [G, BQ, D]
        lse_ref = next(it)        # [G, 1, BQ]

        gi = pl.program_id(0)
        qi = pl.program_id(1)
        seq_len = k_ref.shape[1]
        nk = seq_len // bk
        d = q_ref.shape[-1]
        keep_prob = 1.0 - dropout_prob
        # PRNG path draws quantized 8-bit uniforms; the rescale must
        # match its EFFECTIVE keep probability (mask path keeps exact)
        keep_div = (
            _dropout_quantized_keep(keep_prob) if use_prng else keep_prob
        )
        q_off = off_ref[0] if has_offsets else 0
        k_off = off_ref[1] if has_offsets else 0
        ident = _identity(bq)

        def head(g, _):
            bh = gi * G + g
            # keep the input dtype (bf16 under AMP) for the MXU dots — f32
            # inputs would force multi-pass f32 matmuls; accumulate in f32
            q = q_ref[g]

            def body(i, carry):
                m, l, acc = carry
                k = k_ref[g, pl.ds(i * bk, bk), :]
                v = v_ref[g, pl.ds(i * bk, bk), :]
                s = jax.lax.dot_general(
                    q, k, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                ) * sm_scale  # [BQ, BK]
                if bias_mode == "key":
                    s = s + bias_ref[g, 0, pl.ds(i * bk, bk)][None, :]
                elif bias_mode == "full":
                    s = s + bias_ref[0, :, pl.ds(i * bk, bk)].astype(jnp.float32)
                if causal:
                    s = _causal_mask(
                        s, q_off + qi * bq, k_off + i * bk, bq, bk
                    )
                m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
                p = jnp.exp(s - m_new)
                alpha = jnp.exp(m - m_new)
                l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
                # numerator-only dropout: l accumulates undropped p, acc
                # the masked p/keep_prob — exactly post-softmax dropout
                p_num = p
                if dropout_prob > 0.0:
                    if use_prng:
                        keep = _dropout_keep(
                            seed_ref, bh, qi, i, keep_prob, bq, bk
                        )
                    else:
                        keep = mask_ref[g, :, pl.ds(i * bk, bk)] != 0
                    p_num = jnp.where(keep, p / keep_div, 0.0)
                acc = acc * alpha + jax.lax.dot_general(
                    p_num.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                return m_new, l, acc

            m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
            l0 = jnp.zeros((bq, 1), jnp.float32)
            acc0 = jnp.zeros((bq, d), jnp.float32)
            hi = _hi_blocks(causal, qi, bq, bk, nk, q_off, k_off)
            m, l, acc = jax.lax.fori_loop(0, hi, body, (m0, l0, acc0))
            l_safe = jnp.maximum(l, 1e-30)
            o_ref[g] = (acc / l_safe).astype(o_ref.dtype)
            lse_ref[g, 0] = _to_lanes(m + jnp.log(l_safe), ident)[0]
            return 0

        jax.lax.fori_loop(0, G, head, 0)

    return kernel


def _fwd_specs(bh, s, d, G, bq, bias_mode, bias_dims, num_heads, has_mask,
               use_prng, has_offsets):
    in_specs = [
        pl.BlockSpec((G, bq, d), lambda g, i: (g, i, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((G, s, d), lambda g, i: (g, 0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((G, s, d), lambda g, i: (g, 0, 0), memory_space=pltpu.VMEM),
    ]
    if bias_mode == "key":
        in_specs.append(
            pl.BlockSpec((G, 1, s), lambda g, i: (g, 0, 0),
                         memory_space=pltpu.VMEM)
        )
    elif bias_mode == "full":
        dv_, md_ = _bias_row_map(bias_dims, num_heads)
        in_specs.append(
            pl.BlockSpec(
                (1, bq, s),
                lambda g, i, dv=dv_, md=md_: ((g // dv) % md, i, 0),
                memory_space=pltpu.VMEM,
            )
        )
    if has_mask:
        in_specs.append(
            pl.BlockSpec((G, bq, s), lambda g, i: (g, i, 0),
                         memory_space=pltpu.VMEM)
        )
    if use_prng:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
    if has_offsets:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
    return in_specs


def _flash_fwd(q, k, v, bias, mask, seed, offsets, *, sm_scale, num_heads,
               causal, dropout_prob, bias_mode, bias_dims):
    bh, s, d = q.shape
    bq = bk = _pick_block(s)
    nq = s // bq
    G = _pick_group(bh, s, bq, d, bias_mode == "full")
    use_prng = dropout_prob > 0.0 and mask is None
    has_mask = mask is not None and dropout_prob > 0.0
    has_offsets = offsets is not None
    in_specs = _fwd_specs(bh, s, d, G, bq, bias_mode, bias_dims, num_heads,
                          has_mask, use_prng, has_offsets)
    args = [q, k, v]
    if bias_mode:
        args.append(bias)
    if has_mask:
        args.append(mask)
    if use_prng:
        args.append(seed)
    if has_offsets:
        args.append(offsets)
    kernel = _make_fwd_kernel(
        sm_scale=sm_scale, causal=causal, dropout_prob=dropout_prob,
        bias_mode=bias_mode, use_prng=use_prng, has_mask=has_mask,
        has_offsets=has_offsets, G=G, bq=bq, bk=bk, num_heads=num_heads,
        bias_dims=bias_dims,
    )
    lse_spec = pl.BlockSpec(
        (G, 1, bq), lambda g, i: (g, 0, i), memory_space=pltpu.VMEM
    )
    lse_shape = jax.ShapeDtypeStruct((bh, 1, s), jnp.float32)
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh // G, nq),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((G, bq, d), lambda g, i: (g, i, 0), memory_space=pltpu.VMEM),
            lse_spec,
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            lse_shape,
        ],
        interpret=_interpret(),
    )(*args)
    return o, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _make_bwd_dq_kernel(*, sm_scale, causal, dropout_prob, bias_mode,
                        use_prng, has_mask, has_offsets, G, bq, bk,
                        num_heads, bias_dims):
    def kernel(*refs):
        it = iter(refs)
        q_ref = next(it)          # [G, BQ, D]
        k_ref = next(it)          # [G, S, D]
        v_ref = next(it)          # [G, S, D]
        bias_ref = next(it) if bias_mode else None
        mask_ref = next(it) if has_mask else None
        seed_ref = next(it) if use_prng else None
        off_ref = next(it) if has_offsets else None
        do_ref = next(it)         # [G, BQ, D]
        lse_ref = next(it)        # [G, 1, BQ]
        delta_ref = next(it)      # [G, 1, BQ]
        dq_ref = next(it)         # [G, BQ, D]

        gi = pl.program_id(0)
        qi = pl.program_id(1)
        seq_len = k_ref.shape[1]
        nk = seq_len // bk
        d = q_ref.shape[-1]
        keep_prob = 1.0 - dropout_prob
        # PRNG path draws quantized 8-bit uniforms; the rescale must
        # match its EFFECTIVE keep probability (mask path keeps exact)
        keep_div = (
            _dropout_quantized_keep(keep_prob) if use_prng else keep_prob
        )
        q_off = off_ref[0] if has_offsets else 0
        k_off = off_ref[1] if has_offsets else 0
        ident = _identity(bq)

        def head(g, _):
            bh = gi * G + g
            q = q_ref[g]
            do = do_ref[g]
            lse = _to_sublanes(lse_ref[g], ident)
            delta = _to_sublanes(delta_ref[g], ident)

            def body(i, dq):
                k = k_ref[g, pl.ds(i * bk, bk), :]
                v = v_ref[g, pl.ds(i * bk, bk), :]
                s = jax.lax.dot_general(
                    q, k, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                ) * sm_scale
                if bias_mode:  # split path serves full-bias only
                    s = s + bias_ref[0, :, pl.ds(i * bk, bk)].astype(jnp.float32)
                if causal:
                    s = _causal_mask(
                        s, q_off + qi * bq, k_off + i * bk, bq, bk
                    )
                p = jnp.exp(s - lse)  # normalized probs P [BQ, BK]
                dp = jax.lax.dot_general(
                    do, v, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                if dropout_prob > 0.0:
                    if use_prng:
                        keep = _dropout_keep(
                            seed_ref, bh, qi, i, keep_prob, bq, bk
                        )
                    else:
                        keep = mask_ref[g, :, pl.ds(i * bk, bk)] != 0
                    c = jnp.where(keep, 1.0 / keep_div, 0.0)
                    ds = p * (c * dp - delta) * sm_scale
                else:
                    ds = p * (dp - delta) * sm_scale
                return dq + jax.lax.dot_general(
                    ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )

            hi = _hi_blocks(causal, qi, bq, bk, nk, q_off, k_off)
            dq = jax.lax.fori_loop(0, hi, body, jnp.zeros((bq, d), jnp.float32))
            dq_ref[g] = dq.astype(dq_ref.dtype)
            return 0

        jax.lax.fori_loop(0, G, head, 0)

    return kernel


def _make_bwd_dkv_kernel(*, sm_scale, causal, dropout_prob, bias_mode,
                         use_prng, has_mask, has_offsets, want_dbias, G,
                         bq, bk, num_heads, bias_dims):
    """Split-path dk/dv kernel — serves ONLY the full-bias configuration
    (every other bias mode takes the fused backward). Grid (BH//G, NK);
    loops over q blocks; writes the [S, BK] column of ds (pre-scale) as
    dbias when want_dbias."""

    def kernel(*refs):
        it = iter(refs)
        q_ref = next(it)          # [G, S, D]
        k_ref = next(it)          # [G, BK, D]
        v_ref = next(it)          # [G, BK, D]
        bias_ref = next(it) if bias_mode else None
        mask_ref = next(it) if has_mask else None    # [G, S, BK]
        seed_ref = next(it) if use_prng else None
        off_ref = next(it) if has_offsets else None
        do_ref = next(it)         # [G, S, D]
        lse_ref = next(it)        # [G, 1, S]
        delta_ref = next(it)      # [G, 1, S]
        dk_ref = next(it)         # [G, BK, D]
        dv_ref = next(it)         # [G, BK, D]
        dbias_full_ref = next(it) if want_dbias else None  # [1, S, BK]

        gi = pl.program_id(0)
        ki = pl.program_id(1)
        seq_len = q_ref.shape[1]
        nq = seq_len // bq
        d = k_ref.shape[-1]
        keep_prob = 1.0 - dropout_prob
        # PRNG path draws quantized 8-bit uniforms; the rescale must
        # match its EFFECTIVE keep probability (mask path keeps exact)
        keep_div = (
            _dropout_quantized_keep(keep_prob) if use_prng else keep_prob
        )
        q_off = off_ref[0] if has_offsets else 0
        k_off = off_ref[1] if has_offsets else 0
        ident = _identity(bq)
        if dbias_full_ref is not None:
            dbias_full_ref[0] = jnp.zeros_like(dbias_full_ref[0])

        def head(g, _):
            bh = gi * G + g
            k = k_ref[g]  # [BK, D]
            v = v_ref[g]

            def body(i, carry):
                dk, dv, dbsum = carry
                q = q_ref[g, pl.ds(i * bq, bq), :]
                do = do_ref[g, pl.ds(i * bq, bq), :]
                lse = _to_sublanes(
                    lse_ref[g, :, pl.ds(i * bq, bq)], ident
                )
                delta = _to_sublanes(
                    delta_ref[g, :, pl.ds(i * bq, bq)], ident
                )
                s = jax.lax.dot_general(
                    q, k, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                ) * sm_scale
                if bias_mode:  # split path serves full-bias only
                    s = s + bias_ref[0, pl.ds(i * bq, bq), :].astype(jnp.float32)
                if causal:
                    s = _causal_mask(
                        s, q_off + i * bq, k_off + ki * bk, bq, bk
                    )
                p = jnp.exp(s - lse)  # [BQ, BK]
                dp = jax.lax.dot_general(
                    do, v, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                if dropout_prob > 0.0:
                    if use_prng:
                        keep = _dropout_keep(
                            seed_ref, bh, i, ki, keep_prob, bq, bk
                        )
                    else:
                        keep = mask_ref[g, pl.ds(i * bq, bq), :] != 0
                    c = jnp.where(keep, 1.0 / keep_div, 0.0)
                    p_num = p * c
                else:
                    c = 1.0
                    p_num = p
                dv = dv + jax.lax.dot_general(
                    p_num.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                ds_nos = p * (dp * c - delta)
                ds = ds_nos * sm_scale  # [BQ, BK]
                dk = dk + jax.lax.dot_general(
                    ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                if dbias_full_ref is not None:
                    dbias_full_ref[0, pl.ds(i * bq, bq), :] = ds_nos.astype(
                        dbias_full_ref.dtype
                    )
                return dk, dv, dbsum

            dk0 = jnp.zeros((bk, d), jnp.float32)
            dv0 = jnp.zeros((bk, d), jnp.float32)
            db0 = jnp.zeros((bk,), jnp.float32)
            lo = _lo_blocks(causal, ki, bq, bk, nq, q_off, k_off)
            dk, dv, _ = jax.lax.fori_loop(lo, nq, body, (dk0, dv0, db0))
            dk_ref[g] = dk.astype(dk_ref.dtype)
            dv_ref[g] = dv.astype(dv_ref.dtype)
            return 0

        jax.lax.fori_loop(0, G, head, 0)

    return kernel


def _make_bwd_fused_kernel(*, sm_scale, causal, dropout_prob, bias_mode,
                           use_prng, has_mask, has_offsets, want_dbias, G,
                           bq, bk, num_heads, bias_dims):
    """Single-pass backward: grid (BH//G, NK) with NK innermost. Computes
    dk/dv for this k block AND accumulates dq across the NK sweep into an
    f32 output block whose index map is constant in ki (Pallas keeps the
    revisited block resident in VMEM; it is zeroed at ki==0 and written
    back once the sweep ends). Versus the two-kernel scheme this shares
    the score/probability recompute (7 matmul passes instead of 9) and
    reads q/do/k/v once instead of twice. key-bias and no-bias only —
    full-bias keeps the split path (its row-map runs at G=1)."""

    def kernel(*refs):
        it = iter(refs)
        q_ref = next(it)          # [G, S, D]
        k_ref = next(it)          # [G, BK, D]
        v_ref = next(it)          # [G, BK, D]
        bias_ref = next(it) if bias_mode else None
        mask_ref = next(it) if has_mask else None    # [G, S, BK]
        seed_ref = next(it) if use_prng else None
        off_ref = next(it) if has_offsets else None
        do_ref = next(it)         # [G, S, D]
        lse_ref = next(it)        # [G, 1, S]
        delta_ref = next(it)      # [G, 1, S]
        dq_ref = next(it)         # [G, S, D] f32, revisited across ki
        dk_ref = next(it)         # [G, BK, D]
        dv_ref = next(it)         # [G, BK, D]
        dbias_key_ref = next(it) if (want_dbias and bias_mode == "key") else None

        gi = pl.program_id(0)
        ki = pl.program_id(1)
        nk = pl.num_programs(1)
        seq_len = q_ref.shape[1]
        nq = seq_len // bq
        d = k_ref.shape[-1]
        keep_prob = 1.0 - dropout_prob
        # PRNG path draws quantized 8-bit uniforms; the rescale must
        # match its EFFECTIVE keep probability (mask path keeps exact)
        keep_div = (
            _dropout_quantized_keep(keep_prob) if use_prng else keep_prob
        )
        q_off = off_ref[0] if has_offsets else 0
        k_off = off_ref[1] if has_offsets else 0
        ident = _identity(bq)

        @pl.when(ki == 0)
        def _init():
            dq_ref[...] = jnp.zeros_like(dq_ref)

        def head(g, _):
            bh = gi * G + g
            k = k_ref[g]  # [BK, D]
            v = v_ref[g]
            if bias_mode == "key":
                b_block = bias_ref[g, 0, pl.ds(ki * bk, bk)]

            def body(i, carry):
                dk, dv, dbsum = carry
                q = q_ref[g, pl.ds(i * bq, bq), :]
                do = do_ref[g, pl.ds(i * bq, bq), :]
                lse = _to_sublanes(
                    lse_ref[g, :, pl.ds(i * bq, bq)], ident
                )
                delta = _to_sublanes(
                    delta_ref[g, :, pl.ds(i * bq, bq)], ident
                )
                s = jax.lax.dot_general(
                    q, k, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                ) * sm_scale
                if bias_mode == "key":
                    s = s + b_block[None, :]
                if causal:
                    s = _causal_mask(
                        s, q_off + i * bq, k_off + ki * bk, bq, bk
                    )
                p = jnp.exp(s - lse)  # [BQ, BK]
                dp = jax.lax.dot_general(
                    do, v, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                if dropout_prob > 0.0:
                    if use_prng:
                        keep = _dropout_keep(
                            seed_ref, bh, i, ki, keep_prob, bq, bk
                        )
                    else:
                        keep = mask_ref[g, pl.ds(i * bq, bq), :] != 0
                    c = jnp.where(keep, 1.0 / keep_div, 0.0)
                    p_num = p * c
                else:
                    c = 1.0
                    p_num = p
                dv = dv + jax.lax.dot_general(
                    p_num.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                ds_nos = p * (dp * c - delta)
                ds = (ds_nos * sm_scale).astype(q.dtype)  # [BQ, BK]
                dk = dk + jax.lax.dot_general(
                    ds, q, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                dq_ref[g, pl.ds(i * bq, bq), :] += jax.lax.dot_general(
                    ds, k, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                if dbias_key_ref is not None:
                    dbsum = dbsum + jnp.sum(ds_nos, axis=0)
                return dk, dv, dbsum

            dk0 = jnp.zeros((bk, d), jnp.float32)
            dv0 = jnp.zeros((bk, d), jnp.float32)
            db0 = jnp.zeros((bk,), jnp.float32)
            lo = _lo_blocks(causal, ki, bq, bk, nq, q_off, k_off)
            dk, dv, dbsum = jax.lax.fori_loop(lo, nq, body, (dk0, dv0, db0))
            dk_ref[g] = dk.astype(dk_ref.dtype)
            dv_ref[g] = dv.astype(dv_ref.dtype)
            if dbias_key_ref is not None:
                dbias_key_ref[g, 0] = dbsum
            return 0

        jax.lax.fori_loop(0, G, head, 0)

    return kernel


def _bwd_fused(q, k, v, bias, mask, seed, offsets, g, lse, delta, *,
               sm_scale, num_heads, causal, dropout_prob, bias_mode,
               bias_dims, want_dbias, G, bq, bk):
    """Launch the single-pass backward. Returns (dq, dk, dv, dbias)."""
    bh, s, d = q.shape
    nk = s // bk
    use_prng = dropout_prob > 0.0 and mask is None
    has_mask = mask is not None and dropout_prob > 0.0
    has_offsets = offsets is not None

    kspec = pl.BlockSpec((G, bk, d), lambda g_, i: (g_, i, 0), memory_space=pltpu.VMEM)
    fullspec = pl.BlockSpec((G, s, d), lambda g_, i: (g_, 0, 0), memory_space=pltpu.VMEM)
    fullrow = pl.BlockSpec((G, 1, s), lambda g_, i: (g_, 0, 0), memory_space=pltpu.VMEM)

    args = [q, k, v]
    in_specs = [fullspec, kspec, kspec]
    if bias_mode == "key":
        in_specs.append(
            pl.BlockSpec((G, 1, s), lambda g_, i: (g_, 0, 0),
                         memory_space=pltpu.VMEM)
        )
        args.append(bias)
    if has_mask:
        in_specs.append(
            pl.BlockSpec((G, s, bk), lambda g_, i: (g_, 0, i), memory_space=pltpu.VMEM)
        )
        args.append(mask)
    if use_prng:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(seed)
    if has_offsets:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(offsets)
    in_specs += [fullspec, fullrow, fullrow]
    args += [g, lse, delta]

    out_specs = [
        pl.BlockSpec((G, s, d), lambda g_, i: (g_, 0, 0), memory_space=pltpu.VMEM),
        kspec,
        kspec,
    ]
    out_shapes = [
        jax.ShapeDtypeStruct((bh, s, d), jnp.float32),  # dq accumulator
        jax.ShapeDtypeStruct((bh, s, d), k.dtype),
        jax.ShapeDtypeStruct((bh, s, d), v.dtype),
    ]
    if want_dbias and bias_mode == "key":
        out_specs.append(
            pl.BlockSpec((G, 1, bk), lambda g_, i: (g_, 0, i),
                         memory_space=pltpu.VMEM)
        )
        out_shapes.append(jax.ShapeDtypeStruct((bh, 1, s), jnp.float32))

    outs = pl.pallas_call(
        _make_bwd_fused_kernel(
            sm_scale=sm_scale, causal=causal, dropout_prob=dropout_prob,
            bias_mode=bias_mode, use_prng=use_prng, has_mask=has_mask,
            has_offsets=has_offsets,
            want_dbias=want_dbias and bias_mode == "key",
            G=G, bq=bq, bk=bk, num_heads=num_heads, bias_dims=bias_dims,
        ),
        grid=(bh // G, nk),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        interpret=_interpret(),
    )(*args)
    dq = outs[0].astype(q.dtype)
    dk, dv = outs[1], outs[2]
    dbias = outs[3] if (want_dbias and bias_mode == "key") else None
    return dq, dk, dv, dbias


def _pick_group_bwd(bh, s, bq, d, full_bias):
    """Group size for the fused backward. Footprint model calibrated on
    v5e against Mosaic's scoped-vmem report (G=8/s=512 allocates 16.97M):
    full-length tensors (q, do double-buffered bf16; dq f32 revisited)
    cost ~16 B/elem, the four block tensors (k, v, dk, dv) ~16 B/elem of
    their bk-sized blocks, plus ~7MB of fixed score temporaries and the
    identity; keep the total under 14M of the 16M scoped limit."""
    if full_bias:
        return 1

    def fits(g):
        fulls = 16 * g * s * d
        blocks = 16 * g * min(s, bq) * d
        return fulls + blocks + 7 * 1024 * 1024 <= 14 * 1024 * 1024

    return _scan_groups(bh, "PADDLE_FLASH_GROUP_BWD", fits)


def _flash_bwd(res, g, *, sm_scale, num_heads, causal, dropout_prob,
               bias_mode, bias_dims, want_dbias, g_lse=None):
    q, k, v, bias, mask, seed, offsets, o, lse = res
    bh, s, d = q.shape
    bq = bk = _pick_block(s)
    nq, nk = s // bq, s // bk
    use_prng = dropout_prob > 0.0 and mask is None
    has_mask = mask is not None and dropout_prob > 0.0
    has_offsets = offsets is not None
    delta = jnp.sum(o.astype(jnp.float32) * g.astype(jnp.float32), axis=-1)  # [BH,S]
    if g_lse is not None:
        # lse cotangent: d lse_i/d s_ij = P_ij, so ds gains +P*g_lse —
        # algebraically identical to subtracting g_lse from delta
        delta = delta - g_lse.astype(jnp.float32)
    delta = delta.reshape(bh, 1, s)

    if bias_mode != "full":
        Gb = _pick_group_bwd(bh, s, bq, d, False)
        return _bwd_fused(
            q, k, v, bias, mask, seed, offsets, g, lse, delta,
            sm_scale=sm_scale, num_heads=num_heads, causal=causal,
            dropout_prob=dropout_prob, bias_mode=bias_mode,
            bias_dims=bias_dims, want_dbias=want_dbias, G=Gb, bq=bq, bk=bk,
        )

    # ---- full-bias split path (the rare [B|1, nh|1, S, S] bias): its
    # per-bh row-map indexing pins G=1
    G = 1
    qspec = pl.BlockSpec((G, bq, d), lambda g_, i: (g_, i, 0), memory_space=pltpu.VMEM)
    fullspec = pl.BlockSpec((G, s, d), lambda g_, i: (g_, 0, 0), memory_space=pltpu.VMEM)
    rowspec = pl.BlockSpec(
        (G, 1, bq), lambda g_, i: (g_, 0, i), memory_space=pltpu.VMEM
    )
    fullrow = pl.BlockSpec(
        (G, 1, s), lambda g_, i: (g_, 0, 0), memory_space=pltpu.VMEM
    )

    dv_, md_ = _bias_row_map(bias_dims, num_heads)

    def bias_spec(rows_idx):
        return pl.BlockSpec(
            (1, bq, s) if rows_idx else (1, s, bk),
            (lambda g_, i, dv=dv_, md=md_: ((g_ // dv) % md, i, 0))
            if rows_idx
            else (lambda g_, i, dv=dv_, md=md_: ((g_ // dv) % md, 0, i)),
            memory_space=pltpu.VMEM,
        )

    statics = dict(
        sm_scale=sm_scale, causal=causal, dropout_prob=dropout_prob,
        bias_mode=bias_mode, use_prng=use_prng, has_mask=has_mask,
        has_offsets=has_offsets, G=G, bq=bq, bk=bk, num_heads=num_heads,
        bias_dims=bias_dims,
    )

    # ---- dq: grid over q blocks
    args = [q, k, v]
    in_specs = [qspec, fullspec, fullspec]
    if bias_mode:
        in_specs.append(bias_spec(True))
        args.append(bias)
    if has_mask:
        in_specs.append(
            pl.BlockSpec((G, bq, s), lambda g_, i: (g_, i, 0), memory_space=pltpu.VMEM)
        )
        args.append(mask)
    if use_prng:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(seed)
    if has_offsets:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(offsets)
    in_specs += [qspec, rowspec, rowspec]
    args += [g, lse, delta]
    dq = pl.pallas_call(
        _make_bwd_dq_kernel(**statics),
        grid=(bh // G, nq),
        in_specs=in_specs,
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        interpret=_interpret(),
    )(*args)

    # ---- dk/dv (+dbias): grid over k blocks
    kspec = pl.BlockSpec((G, bk, d), lambda g_, i: (g_, i, 0), memory_space=pltpu.VMEM)
    args2 = [q, k, v]
    in_specs2 = [fullspec, kspec, kspec]
    if bias_mode:
        in_specs2.append(bias_spec(False))
        args2.append(bias)
    if has_mask:
        in_specs2.append(
            pl.BlockSpec((G, s, bk), lambda g_, i: (g_, 0, i), memory_space=pltpu.VMEM)
        )
        args2.append(mask)
    if use_prng:
        in_specs2.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args2.append(seed)
    if has_offsets:
        in_specs2.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args2.append(offsets)
    in_specs2 += [fullspec, fullrow, fullrow]
    args2 += [g, lse, delta]

    out_specs2 = [kspec, kspec]
    out_shapes2 = [
        jax.ShapeDtypeStruct((bh, s, d), k.dtype),
        jax.ShapeDtypeStruct((bh, s, d), v.dtype),
    ]
    if want_dbias:
        out_specs2.append(
            pl.BlockSpec((1, s, bk), lambda g_, i: (g_, 0, i), memory_space=pltpu.VMEM)
        )
        out_shapes2.append(jax.ShapeDtypeStruct((bh, s, s), jnp.float32))

    outs = pl.pallas_call(
        _make_bwd_dkv_kernel(want_dbias=want_dbias, **statics),
        grid=(bh // G, nk),
        in_specs=in_specs2,
        out_specs=out_specs2,
        out_shape=out_shapes2,
        interpret=_interpret(),
    )(*args2)
    dk, dv = outs[0], outs[1]

    # reduce dbias grid cells that shared one broadcast row
    dbias = None
    if want_dbias:
        bb, bn = bias_dims
        batch = bh // num_heads
        db = outs[2].reshape(batch, num_heads, s, s)
        if bn == 1 and num_heads > 1:
            db = db.sum(axis=1, keepdims=True)
        if bb == 1 and batch > 1:
            db = db.sum(axis=0, keepdims=True)
        dbias = db.reshape(bb * bn, s, s)
    return dq, dk, dv, dbias


# ---------------------------------------------------------------------------
# core with custom VJP (created per call; closes over static config)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=256)
def _make_flash_core(*, sm_scale, num_heads, causal, dropout_prob, bias_mode,
                     bias_dims, want_dbias):
    """Cached per static config: eager callers reuse the same custom_vjp
    (and therefore JAX's trace/lowering caches) across calls."""
    statics = dict(
        sm_scale=sm_scale, num_heads=num_heads, causal=causal,
        dropout_prob=dropout_prob, bias_mode=bias_mode, bias_dims=bias_dims,
    )

    @jax.custom_vjp
    def core(q, k, v, bias, mask, seed, offsets):
        o, _ = _flash_fwd(q, k, v, bias, mask, seed, offsets, **statics)
        return o

    def core_fwd(q, k, v, bias, mask, seed, offsets):
        o, lse = _flash_fwd(q, k, v, bias, mask, seed, offsets, **statics)
        # checkpoint_name so a surrounding jax.checkpoint with a
        # save_only_these_names policy can keep (o, lse) and let the
        # recompute pass DCE the forward kernel while q/k/v come back from
        # the (cheap) projection matmuls — the long-context remat story
        o = checkpoint_name(o, "flash_o")
        lse = checkpoint_name(lse, "flash_lse")
        return o, (q, k, v, bias, mask, seed, offsets, o, lse)

    def core_bwd(res, g):
        dq, dk, dv, dbias = _flash_bwd(
            res, g, want_dbias=want_dbias, **statics
        )
        if res[3] is not None and dbias is None:
            # bias_requires_grad=False: zero cotangent (padding masks)
            dbias = jnp.zeros_like(res[3])
        elif dbias is not None:
            dbias = dbias.astype(res[3].dtype)
        return (dq, dk, dv, dbias, None, None, None)

    core.defvjp(core_fwd, core_bwd)
    return core


# ---------------------------------------------------------------------------
# public entry: [B, nh, S, D] with bias/causal/dropout/SPMD
# ---------------------------------------------------------------------------


def _classify_bias(bias, b, nh, s):
    """Returns (bias_kernel, bias_mode, (bb, bn)).

    'key' mode pre-broadcasts the user's [B|1,1,1,S] padding mask to
    [B*nh, S] f32 with plain traced ops — dbias (returned in that shape)
    flows back to the user shape through ordinary autodiff (the
    broadcast transposes to a sum over heads/batch). 'full' mode keeps
    [R, S, S] rows with in-kernel row mapping (R = bb*bn)."""
    if bias is None:
        return None, None, None
    if bias.ndim != 4:
        raise ValueError(f"flash_attention bias must be 4-D, got {bias.shape}")
    bb, bn, bq, bk = bias.shape
    if bb not in (1, b) or bn not in (1, nh):
        raise ValueError(
            f"bias dims {bias.shape} not broadcastable to batch={b}, heads={nh}"
        )
    if bk != s:
        raise ValueError(f"bias key dim {bk} != seq {s}")
    if bn == 1 and bq == 1:
        bkey = jnp.broadcast_to(
            bias.astype(jnp.float32).reshape(bb, 1, s), (b, nh, s)
        ).reshape(b * nh, 1, s)
        return bkey, "key", (bb, 1)
    if bq != s:
        raise ValueError(f"bias query dim {bq} != seq {s}")
    b3 = bias.reshape(bb * bn, s, s)
    return b3, "full", (bb, bn)


def _bias_row_map(bias_dims, num_heads):
    """(div, mod) such that full-bias row = (bh // div) % mod."""
    bb, bn = bias_dims
    return (num_heads if bn == 1 else 1), bb * bn


def _flash_local(q, k, v, bias, mask, seed, *, sm_scale, causal, dropout_prob,
                 bias_requires_grad):
    """[B, nh, S, D] local (per-shard) flash attention."""
    b, nh, s, d = q.shape
    biask, bias_mode, bias_dims = _classify_bias(bias, b, nh, s)
    mask3 = mask.reshape(b * nh, s, s) if mask is not None else None
    qf = q.reshape(b * nh, s, d)
    kf = k.reshape(b * nh, s, d)
    vf = v.reshape(b * nh, s, d)
    core = _make_flash_core(
        sm_scale=float(sm_scale), num_heads=nh, causal=causal,
        dropout_prob=dropout_prob, bias_mode=bias_mode, bias_dims=bias_dims,
        want_dbias=bias_requires_grad and bias_mode is not None,
    )
    o = core(qf, kf, vf, biask, mask3, seed, None)
    return o.reshape(b, nh, s, d)


def flash_attention(q, k, v, bias=None, sm_scale=None, causal=False,
                    dropout_prob=0.0, dropout_key=None, dropout_seed=None,
                    bias_requires_grad=False, mesh=None, batch_axis="dp",
                    head_axis="tp"):
    """Flash attention with optional bias, causal mask, dropout and SPMD.

    q, k, v: [B, nh, S, D]. bias: additive, [B,1,1,S] (per-key padding
    mask) or [B|1, nh|1, S, S]. Returns [B, nh, S, D].

    dropout: `dropout_prob` with either `dropout_key` (a jax PRNG key) or
    `dropout_seed` (int32 scalar). On TPU the mask comes from the in-kernel
    hardware PRNG; in interpret mode (CPU) it is precomputed host-side.

    bias_requires_grad=False returns zero cotangent for the bias (the
    padding-mask case); set True to compute the real dbias.

    mesh: wrap in shard_map over (batch_axis, head_axis) if present —
    batch sharded on dp, heads on tp (megatron attention).
    """
    b, nh, s, d = q.shape
    if s % MIN_BLOCK != 0:
        raise ValueError(f"flash_attention needs seq % {MIN_BLOCK} == 0, got {s}")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)

    seed = None
    mask = None
    if dropout_prob > 0.0:
        if dropout_seed is not None:
            seed = jnp.asarray(dropout_seed, jnp.int32).reshape(1)
        elif dropout_key is not None:
            seed = jax.random.randint(
                dropout_key, (1,), 0, jnp.iinfo(jnp.int32).max, dtype=jnp.int32
            )
        else:
            raise ValueError("dropout needs dropout_key or dropout_seed")
        if _interpret():
            # CPU tests: TPU hardware PRNG is unavailable in interpret
            # mode; draw the mask host-side (same numerator-only math)
            mkey = dropout_key if dropout_key is not None else jax.random.PRNGKey(
                seed[0]
            )
            mask = jax.random.bernoulli(
                jax.random.fold_in(mkey, 7), 1.0 - dropout_prob, (b, nh, s, s)
            ).astype(jnp.uint8)

    kwargs = dict(
        sm_scale=sm_scale, causal=causal, dropout_prob=dropout_prob,
        bias_requires_grad=bias_requires_grad,
    )

    axes = [
        ax for ax in (batch_axis, head_axis)
        if mesh is not None and ax in mesh.axis_names and mesh.shape[ax] > 1
    ]
    if not axes:
        return _flash_local(q, k, v, bias, mask, seed, **kwargs)

    from ...compat import shard_map
    from jax.sharding import PartitionSpec as P

    ba = batch_axis if batch_axis in axes else None
    ha = head_axis if head_axis in axes else None
    qspec = P(ba, ha, None, None)

    def spec_for(x):
        if x is None:
            return None
        return P(
            ba if x.shape[0] != 1 else None,
            ha if x.shape[1] != 1 else None,
            None,
            None,
        )

    bias_spec = spec_for(bias)
    mask_spec = P(ba, ha, None, None) if mask is not None else None

    def body(ql, kl, vl, bl, ml, sl):
        local_seed = sl
        if sl is not None:
            import jax.lax as lax

            salt = jnp.int32(0)
            if ba:
                salt = salt + lax.axis_index(ba) * jnp.int32(0x632BE59B)
            if ha:
                salt = salt + lax.axis_index(ha) * jnp.int32(0x1B873593)
            local_seed = sl + salt
        out = _flash_local(ql, kl, vl, bl, ml, local_seed, **kwargs)
        return out

    in_specs = (qspec, qspec, qspec, bias_spec, mask_spec, P() if seed is not None else None)
    return shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=qspec, check=False,
    )(q, k, v, bias, mask, seed)


@functools.lru_cache(maxsize=64)
def _make_flash_core_lse(*, sm_scale, num_heads, causal, dropout_prob,
                         bias_mode, bias_dims, want_dbias=False):
    """Like _make_flash_core but returns (o, lse [BH, S]) with a VJP that
    accepts cotangents for BOTH outputs (g_lse folds into delta). Built
    for ring attention, which merges per-block partials by lse."""
    statics = dict(
        sm_scale=sm_scale, num_heads=num_heads, causal=causal,
        dropout_prob=dropout_prob, bias_mode=bias_mode, bias_dims=bias_dims,
    )

    @jax.custom_vjp
    def core(q, k, v, bias, mask, seed, offsets):
        o, lse = _flash_fwd(q, k, v, bias, mask, seed, offsets, **statics)
        return o, lse.reshape(q.shape[0], q.shape[1])

    def core_fwd(q, k, v, bias, mask, seed, offsets):
        o, lse = _flash_fwd(q, k, v, bias, mask, seed, offsets, **statics)
        o = checkpoint_name(o, "flash_o")
        lse = checkpoint_name(lse, "flash_lse")
        return (o, lse.reshape(q.shape[0], q.shape[1])), (
            q, k, v, bias, mask, seed, offsets, o, lse,
        )

    def core_bwd(res, gs):
        g_o, g_lse = gs
        dq, dk, dv, dbias = _flash_bwd(
            res, g_o, want_dbias=want_dbias and bias_mode is not None,
            g_lse=g_lse, **statics
        )
        if res[3] is not None and dbias is None:
            dbias = jnp.zeros_like(res[3])
        elif dbias is not None:
            dbias = dbias.astype(res[3].dtype)
        return (dq, dk, dv, dbias, None, None, None)

    core.defvjp(core_fwd, core_bwd)
    return core


def flash_block_with_lse(q, k, v, key_bias=None, sm_scale=None,
                         bias_requires_grad=True, causal=False,
                         q_offset=None, k_offset=None,
                         dropout_prob=0.0, dropout_seed=None,
                         dropout_mask=None):
    """One attention block for ring attention: q/k/v [B, nh, S, D] local
    shards, key_bias [B, S] additive per-key bias (rotating with K).
    Returns (out [B, nh, S, D], lse [B, nh, S]) for log-sum-exp merging
    across ring steps.

    causal + (q_offset, k_offset): global positions of this shard's q
    rows / the visiting k block, as int32 scalars (traced values are
    fine — they ride in SMEM), so the ring's shifted blocks mask
    correctly. dropout: `dropout_seed` int32 scalar (the ring caller
    folds its step index in); in interpret mode pass `dropout_mask`
    [B, nh, S, S] uint8 instead. Bias gradients are computed by default,
    matching the jnp ring block math."""
    b, nh, s, d = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    biask = None
    bias_mode = None
    bias_dims = None
    if key_bias is not None:
        biask = jnp.broadcast_to(
            key_bias.astype(jnp.float32).reshape(b, 1, s), (b, nh, s)
        ).reshape(b * nh, 1, s)
        bias_mode, bias_dims = "key", (b, 1)
    offsets = None
    if causal and (q_offset is not None or k_offset is not None):
        offsets = jnp.stack([
            jnp.asarray(q_offset if q_offset is not None else 0, jnp.int32),
            jnp.asarray(k_offset if k_offset is not None else 0, jnp.int32),
        ])
    seed = None
    mask3 = None
    if dropout_prob > 0.0:
        if dropout_mask is not None:
            mask3 = dropout_mask.reshape(b * nh, s, s)
        elif dropout_seed is not None:
            seed = jnp.asarray(dropout_seed, jnp.int32).reshape(1)
        else:
            raise ValueError("dropout needs dropout_seed or dropout_mask")
    core = _make_flash_core_lse(
        sm_scale=float(sm_scale), num_heads=nh, causal=causal,
        dropout_prob=dropout_prob, bias_mode=bias_mode, bias_dims=bias_dims,
        want_dbias=bias_requires_grad,
    )
    o, lse = core(
        q.reshape(b * nh, s, d), k.reshape(b * nh, s, d),
        v.reshape(b * nh, s, d), biask, mask3, seed, offsets,
    )
    return o.reshape(b, nh, s, d), lse.reshape(b, nh, s)


# ---------------------------------------------------------------------------
# BSH layout (transpose-free) kernels
# ---------------------------------------------------------------------------
#
# The [B, nh, S, D] layout above needs head-split/merge transposes around
# every kernel call; profiled on v5e (BERT-base s512/b48) those copies +
# their backward/recompute doubles cost ~30-45 ms/step — an order of
# magnitude more than the kernels themselves. These kernels read q/k/v
# exactly as the qkv projection produces them — [B, S, H] with H = nh*D
# — and slice each head's D lanes in-kernel with STATIC offsets (a
# static 64-lane slice lowers to plain vreg selects; measured FASTER
# than the pre-transposed layout even before counting the removed
# copies). Rectangular attention (S_q != S_kv, the NMT cross-attention
# shape) falls out for free because q and k/v carry separate lengths.
#
# Capabilities: per-key additive bias [B, 1, S_kv] (no dbias — padding
# masks), causal with (q_offset, k_offset), in-kernel PRNG dropout (same
# quantized-byte scheme and seed mixing as the BHSD kernels, bh = b*nh+h,
# so masks are reproducible across fwd/bwd). Full [.., S, S] bias and
# dbias stay on the BHSD path.


def _prescale_ok(sm_scale) -> bool:
    """Fold sm_scale into q BEFORE the qk dot when it is a power of two
    (d = 64/256 -> 1/8, 1/16): a bf16 exponent shift is EXACT, and it
    deletes one [BQ, BK] f32 multiply per (head, k-block) from the
    VPU-bound softmax pipeline. Non-pow2 scales (d=128) keep the
    per-block multiply — prescaling would perturb every logit by the
    bf16 rounding of the scale."""
    import math

    return math.frexp(float(sm_scale))[0] == 0.5


def _make_fwd_bsh_kernel(*, sm_scale, causal, dropout_prob, has_bias,
                         use_prng, has_mask, has_offsets, nh, d, bq, bk,
                         prescale=False):
    def kernel(*refs):
        it = iter(refs)
        q_ref = next(it)          # [1, BQ, H]
        k_ref = next(it)          # [1, Skv, H]
        v_ref = next(it)          # [1, Skv, H]
        bias_ref = next(it) if has_bias else None   # [1, 1, Skv]
        mask_ref = next(it) if has_mask else None   # [1, nh, BQ, Skv]
        seed_ref = next(it) if use_prng else None
        off_ref = next(it) if has_offsets else None
        o_ref = next(it)          # [1, BQ, H]
        lse_ref = next(it)        # [1, nh, BQ]

        b = pl.program_id(0)
        qi = pl.program_id(1)
        skv = k_ref.shape[1]
        nk = skv // bk
        keep_prob = 1.0 - dropout_prob
        keep_div = (
            _dropout_quantized_keep(keep_prob) if use_prng else keep_prob
        )
        q_off = off_ref[0] if has_offsets else 0
        k_off = off_ref[1] if has_offsets else 0
        ident = _identity(bq)
        hi = _hi_blocks(causal, qi, bq, bk, nk, q_off, k_off)

        for h in range(nh):
            q = q_ref[0, :, h * d:(h + 1) * d]   # [BQ, D] static lanes
            if prescale:
                q = q * jnp.asarray(sm_scale, q.dtype)
            bh = b * nh + h

            def body(i, carry, h=h, q=q, bh=bh):
                m, l, acc = carry
                k = k_ref[0, pl.ds(i * bk, bk), h * d:(h + 1) * d]
                v = v_ref[0, pl.ds(i * bk, bk), h * d:(h + 1) * d]
                s = jax.lax.dot_general(
                    q, k, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                if not prescale:
                    s = s * sm_scale
                if has_bias:
                    s = s + bias_ref[0, 0, pl.ds(i * bk, bk)][None, :]
                if causal:
                    s = _causal_mask(
                        s, q_off + qi * bq, k_off + i * bk, bq, bk
                    )
                m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
                p = jnp.exp(s - m_new)
                alpha = jnp.exp(m - m_new)
                l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
                p_num = p
                if dropout_prob > 0.0:
                    if use_prng:
                        keep = _dropout_keep(
                            seed_ref, bh, qi, i, keep_prob, bq, bk
                        )
                    else:
                        keep = mask_ref[0, h, :, pl.ds(i * bk, bk)] != 0
                    p_num = jnp.where(keep, p / keep_div, 0.0)
                acc = acc * alpha + jax.lax.dot_general(
                    p_num.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                return m_new, l, acc

            m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
            l0 = jnp.zeros((bq, 1), jnp.float32)
            acc0 = jnp.zeros((bq, d), jnp.float32)
            m, l, acc = jax.lax.fori_loop(0, hi, body, (m0, l0, acc0))
            l_safe = jnp.maximum(l, 1e-30)
            o_ref[0, :, h * d:(h + 1) * d] = (acc / l_safe).astype(o_ref.dtype)
            lse_ref[0, h:h + 1, :] = _to_lanes(m + jnp.log(l_safe), ident)

    return kernel


def default_bsh_block(s, skv, h, bwd=False, sync_bwd=False):
    """THE hand-picked BSH tile chooser (the autotune cache-miss
    fallback — tuning/search.py replaces it per shape when a measured
    winner exists; see _resolve_bsh_blocks).

    BSH kernels tolerate bigger tiles than the streamed BHSD path
    (whole-sequence VMEM residency is already the design): at S>=4096 a
    1024 tile measured 0.4266 vs 0.4240 MFU (BERT-base s4096/b8, v5e) —
    fewer block iterations amortize the per-block softmax epilogue.
    Footprint gates (v5e-calibrated): the fwd holds k/v resident —
    skv-sized, ~8 B/elem double-buffered — plus ~40MB of 1024-tile
    temporaries; the bwd's q/do/dq residency measured 124MB at
    (s8192, bq1024) vs the 112MB limit, so it escalates only at
    s==4096 (fits; the full s4096/b8 bench runs it).

    sync_bwd: in-kernel PRNG dropout seeds per (bh, q-block, k-block)
    and draws [bq, bk] masks, so the keep pattern DEPENDS on the block
    partition — when the fwd applied PRNG dropout, the bwd must
    regenerate the identical mask, which means identical tiles. Callers
    set sync_bwd on the fwd pick whenever use_prng, forcing the fwd
    down to whatever the bwd can afford. Without dropout (or with a
    materialized mask), mixed fwd/bwd tiles are fine — lse and delta
    ride as full [B, nh, S] arrays."""
    import os

    forced = int(os.environ.get("PADDLE_FLASH_BLOCK", "0"))
    if forced >= MIN_BLOCK and s % forced == 0:
        return forced
    if s >= 4096 and s % 1024 == 0:
        if bwd or sync_bwd:
            if s == 4096 and skv == 4096:
                return 1024
        elif 8 * skv * h + 40 * 2**20 <= _BSH_VMEM_LIMIT:
            return 1024
    return _pick_block(s)


_pick_block_bsh = default_bsh_block  # historical name (round-5 sweeps)


def _resolve_bsh_blocks(sq, skv, h, dtype, *, bwd=False, sync_bwd=False):
    """(bq, bk, vmem_limit_bytes) for one BSH kernel launch.

    Precedence: PADDLE_FLASH_BLOCK env override (hand sweeps) >
    FLAGS_kernel_autotune cache entry > default_bsh_block heuristic.
    One cache entry serves fwd AND bwd (in-kernel PRNG dropout must
    regenerate identical per-block masks, which requires identical
    tiles), so a cached config is validated against BOTH footprint
    models before it is trusted; an invalid or missing entry falls back
    to the hand-picked chooser — no behavior cliff."""
    import os

    key = {"sq": sq, "skv": skv, "h": h, "dtype": str(dtype)}
    if not int(os.environ.get("PADDLE_FLASH_BLOCK", "0")):
        from ... import tuning

        cfg = tuning.maybe_lookup("flash_bsh", key)
        if cfg:
            try:
                bq = int(cfg.get("bq", 0))
                bk = int(cfg.get("bk", 0))
                limit = (int(cfg["vmem_limit_mb"]) * 2**20
                         if cfg.get("vmem_limit_mb") else _BSH_VMEM_LIMIT)
            except (TypeError, ValueError):
                bq = bk = 0
                limit = _BSH_VMEM_LIMIT
            ok, _why = _feas.flash_bsh_ok(sq, skv, h, bq, bk, limit=limit)
            if ok:
                return bq, bk, limit
            # bad entry (edited by hand / stale shape): hand-picked path
            tuning.note_choice("flash_bsh", key, None, "default")
    return (
        default_bsh_block(sq, skv, h, bwd=bwd, sync_bwd=sync_bwd),
        default_bsh_block(skv, skv, h, bwd=bwd, sync_bwd=sync_bwd),
        _BSH_VMEM_LIMIT,
    )


def _flash_fwd_bsh(q, k, v, bias, mask, seed, offsets, *, sm_scale, nh,
                   causal, dropout_prob):
    b, sq, hdim = q.shape
    skv = k.shape[1]
    d = hdim // nh
    use_prng = dropout_prob > 0.0 and mask is None
    bq, bk, vmem_limit = _resolve_bsh_blocks(
        sq, skv, hdim, q.dtype, sync_bwd=use_prng)
    has_mask = mask is not None and dropout_prob > 0.0
    has_offsets = offsets is not None
    has_bias = bias is not None

    in_specs = [
        pl.BlockSpec((1, bq, hdim), lambda b_, i: (b_, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, skv, hdim), lambda b_, i: (b_, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, skv, hdim), lambda b_, i: (b_, 0, 0),
                     memory_space=pltpu.VMEM),
    ]
    args = [q, k, v]
    if has_bias:
        in_specs.append(
            pl.BlockSpec((1, 1, skv), lambda b_, i: (b_, 0, 0),
                         memory_space=pltpu.VMEM))
        args.append(bias)
    if has_mask:
        in_specs.append(
            pl.BlockSpec((1, nh, bq, skv), lambda b_, i: (b_, 0, i, 0),
                         memory_space=pltpu.VMEM))
        args.append(mask)
    if use_prng:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(seed)
    if has_offsets:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(offsets)

    kernel = _make_fwd_bsh_kernel(
        sm_scale=sm_scale, causal=causal, dropout_prob=dropout_prob,
        has_bias=has_bias, use_prng=use_prng, has_mask=has_mask,
        has_offsets=has_offsets, nh=nh, d=d, bq=bq, bk=bk,
        prescale=_prescale_ok(sm_scale),
    )
    o, lse = pl.pallas_call(
        kernel,
        grid=(b, sq // bq),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, hdim), lambda b_, i: (b_, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, nh, bq), lambda b_, i: (b_, 0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, sq, hdim), q.dtype),
            jax.ShapeDtypeStruct((b, nh, sq), jnp.float32),
        ],
        compiler_params=_compat.tpu_compiler_params(
            vmem_limit_bytes=vmem_limit),
        interpret=_interpret(),
    )(*args)
    return o, lse


def _make_bwd_bsh_kernel(*, sm_scale, causal, dropout_prob, has_bias,
                         use_prng, has_mask, has_offsets, nh, d, bq, bk,
                         prescale=False):
    """Single-pass BSH backward: grid (B, NKv) with NKv innermost per
    batch row. Computes dk/dv for this k block and accumulates dq into a
    revisited f32 output block (index constant in ki -> stays resident;
    zeroed at ki == 0)."""

    def kernel(*refs):
        it = iter(refs)
        q_ref = next(it)          # [1, Sq, H]
        k_ref = next(it)          # [1, BK, H]
        v_ref = next(it)          # [1, BK, H]
        bias_ref = next(it) if has_bias else None   # [1, 1, Skv]
        mask_ref = next(it) if has_mask else None   # [1, nh, Sq, BK]
        seed_ref = next(it) if use_prng else None
        off_ref = next(it) if has_offsets else None
        do_ref = next(it)         # [1, Sq, H]
        lse_ref = next(it)        # [1, nh, Sq]
        delta_ref = next(it)      # [1, nh, Sq]
        dq_ref = next(it)         # [1, Sq, H] f32, revisited across ki
        dk_ref = next(it)         # [1, BK, H]
        dv_ref = next(it)         # [1, BK, H]

        b = pl.program_id(0)
        ki = pl.program_id(1)
        sq = q_ref.shape[1]
        nq = sq // bq
        keep_prob = 1.0 - dropout_prob
        keep_div = (
            _dropout_quantized_keep(keep_prob) if use_prng else keep_prob
        )
        q_off = off_ref[0] if has_offsets else 0
        k_off = off_ref[1] if has_offsets else 0
        ident = _identity(bq)

        @pl.when(ki == 0)
        def _init():
            dq_ref[...] = jnp.zeros_like(dq_ref)

        lo = _lo_blocks(causal, ki, bq, bk, nq, q_off, k_off)
        for h in range(nh):
            k = k_ref[0, :, h * d:(h + 1) * d]   # [BK, D]
            v = v_ref[0, :, h * d:(h + 1) * d]
            bh = b * nh + h
            if has_bias:
                b_block = bias_ref[0, 0, pl.ds(ki * bk, bk)]

            def body(i, carry, h=h, k=k, v=v, bh=bh):
                dk, dv = carry
                q = q_ref[0, pl.ds(i * bq, bq), h * d:(h + 1) * d]
                if prescale:
                    # exact pow2 shift; dk = ds_nos^T @ q_pre is then
                    # ALREADY chain-rule scaled, and dq accumulates
                    # unscaled ds_nos @ k with ONE final scale pass —
                    # both per-block [BQ,BK] sm_scale multiplies gone
                    q = q * jnp.asarray(sm_scale, q.dtype)
                do = do_ref[0, pl.ds(i * bq, bq), h * d:(h + 1) * d]
                lse = _to_sublanes(
                    lse_ref[0, h:h + 1, pl.ds(i * bq, bq)], ident
                )
                delta = _to_sublanes(
                    delta_ref[0, h:h + 1, pl.ds(i * bq, bq)], ident
                )
                s = jax.lax.dot_general(
                    q, k, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                if not prescale:
                    s = s * sm_scale
                if has_bias:
                    s = s + b_block[None, :]
                if causal:
                    s = _causal_mask(
                        s, q_off + i * bq, k_off + ki * bk, bq, bk
                    )
                p = jnp.exp(s - lse)
                dp = jax.lax.dot_general(
                    do, v, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                if dropout_prob > 0.0:
                    if use_prng:
                        keep = _dropout_keep(
                            seed_ref, bh, i, ki, keep_prob, bq, bk
                        )
                    else:
                        keep = mask_ref[0, h, pl.ds(i * bq, bq), :] != 0
                    c = jnp.where(keep, 1.0 / keep_div, 0.0)
                    p_num = p * c
                else:
                    c = 1.0
                    p_num = p
                dv = dv + jax.lax.dot_general(
                    p_num.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                if prescale:
                    ds = (p * (dp * c - delta)).astype(q.dtype)
                else:
                    ds = (p * (dp * c - delta) * sm_scale).astype(q.dtype)
                dk = dk + jax.lax.dot_general(
                    ds, q, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                dq_ref[0, pl.ds(i * bq, bq), h * d:(h + 1) * d] += (
                    jax.lax.dot_general(
                        ds, k, (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )
                )
                return dk, dv

            dk0 = jnp.zeros((bk, d), jnp.float32)
            dv0 = jnp.zeros((bk, d), jnp.float32)
            dk, dv = jax.lax.fori_loop(lo, nq, body, (dk0, dv0))
            dk_ref[0, :, h * d:(h + 1) * d] = dk.astype(dk_ref.dtype)
            dv_ref[0, :, h * d:(h + 1) * d] = dv.astype(dv_ref.dtype)

        if prescale:
            # dq accumulated UNSCALED ds @ k across every ki: apply the
            # chain-rule sm_scale once, on the resident f32 buffer,
            # after the last k block of this batch row
            @pl.when(ki == pl.num_programs(1) - 1)
            def _scale_dq():
                dq_ref[...] = dq_ref[...] * sm_scale

    return kernel


def _flash_bwd_bsh(res, g, *, sm_scale, nh, causal, dropout_prob):
    q, k, v, bias, mask, seed, offsets, o, lse = res
    b, sq, hdim = q.shape
    skv = k.shape[1]
    d = hdim // nh
    use_prng = dropout_prob > 0.0 and mask is None
    bq, bk, vmem_limit = _resolve_bsh_blocks(
        sq, skv, hdim, q.dtype, bwd=True, sync_bwd=use_prng)
    has_mask = mask is not None and dropout_prob > 0.0
    has_offsets = offsets is not None
    has_bias = bias is not None

    # delta[b, h, s] = sum_d o*g per head, from the BSH layout
    delta = (
        (o.astype(jnp.float32) * g.astype(jnp.float32))
        .reshape(b, sq, nh, d).sum(axis=-1).transpose(0, 2, 1)
    )

    fullq = pl.BlockSpec((1, sq, hdim), lambda b_, i: (b_, 0, 0),
                        memory_space=pltpu.VMEM)
    kspec = pl.BlockSpec((1, bk, hdim), lambda b_, i: (b_, i, 0),
                         memory_space=pltpu.VMEM)
    statspec = pl.BlockSpec((1, nh, sq), lambda b_, i: (b_, 0, 0),
                            memory_space=pltpu.VMEM)

    args = [q, k, v]
    in_specs = [fullq, kspec, kspec]
    if has_bias:
        in_specs.append(
            pl.BlockSpec((1, 1, skv), lambda b_, i: (b_, 0, 0),
                         memory_space=pltpu.VMEM))
        args.append(bias)
    if has_mask:
        in_specs.append(
            pl.BlockSpec((1, nh, sq, bk), lambda b_, i: (b_, 0, 0, i),
                         memory_space=pltpu.VMEM))
        args.append(mask)
    if use_prng:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(seed)
    if has_offsets:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(offsets)
    in_specs += [fullq, statspec, statspec]
    args += [g, lse, delta]

    dq, dk, dv = pl.pallas_call(
        _make_bwd_bsh_kernel(
            sm_scale=sm_scale, causal=causal, dropout_prob=dropout_prob,
            has_bias=has_bias, use_prng=use_prng, has_mask=has_mask,
            has_offsets=has_offsets, nh=nh, d=d, bq=bq, bk=bk,
            prescale=_prescale_ok(sm_scale),
        ),
        grid=(b, skv // bk),
        in_specs=in_specs,
        out_specs=[fullq, kspec, kspec],
        out_shape=[
            jax.ShapeDtypeStruct((b, sq, hdim), jnp.float32),
            jax.ShapeDtypeStruct((b, skv, hdim), k.dtype),
            jax.ShapeDtypeStruct((b, skv, hdim), v.dtype),
        ],
        compiler_params=_compat.tpu_compiler_params(
            vmem_limit_bytes=vmem_limit),
        interpret=_interpret(),
    )(*args)
    return dq.astype(q.dtype), dk, dv


# the BSH kernels keep whole sequences resident (k/v in fwd, q/do/dq in
# bwd): ~40MB at s=4096/H=768, ~102MB at s=8192 (Mosaic's scoped-vmem
# report). v5e has 128MB of VMEM; the default ~16MB scoped limit is far
# below what the hardware allows, so raise it for these calls. Past the
# estimate below, dispatch falls back to the BHSD kernels (streamed
# blocks, head-transposed layout) — and beyond single-chip HBM, shard
# the sequence (ring attention over "sp") instead. The byte value lives
# in tuning/feasible.py so the autotuner's feasibility gate and the
# kernel can never disagree about the budget.
_BSH_VMEM_LIMIT = _feas.BSH_VMEM_LIMIT


def bsh_shapes_ok(sq, skv, h) -> bool:
    """Will the BSH kernels' whole-sequence VMEM residency fit? The 13
    B/elem slope + fixed blocks/temps term is calibrated against
    Mosaic's scoped-vmem report (s8192/h768 allocates 102M)."""
    est = 13 * max(sq, skv) * h + 24 * 1024 * 1024
    return est <= _BSH_VMEM_LIMIT


def bsh_dispatch_ok(sq, skv, h, num_heads, bias=None, batch=None,
                    causal=False) -> bool:
    """THE fitness test for every BSH dispatch site (the attention op and
    both fused stacks): flag/backend/shape gates on both lengths, VMEM
    residency, per-key-only bias actually holdable as [B, 1, S_kv], and
    no rectangular-causal (the kernel's zero-offset causal mask is
    top-left aligned — silently wrong when sq != skv)."""
    d = h // num_heads
    if not (flash_shapes_ok(sq, d) and flash_shapes_ok(skv, d)
            and bsh_shapes_ok(sq, skv, h)):
        return False
    if causal and sq != skv:
        return False
    if bias is None:
        return True
    if bias.ndim == 4:
        bb, bn, bq_, bk_ = bias.shape
    elif bias.ndim == 3:
        bb, bn, bk_ = bias.shape
        bq_ = 1
    else:
        return False
    return (bn == 1 and bq_ == 1 and bk_ == skv
            and (batch is None or bb == batch))


def _bsh_mask_materialize(sq, skv, h, dtype) -> bool:
    """The tuned dropout-mask axis (ISSUE 13): an autotune cache entry
    with {'mask': 'materialize'} precomputes the [B, nh, Sq, Skv] keep
    mask with the traced PRNG (one HBM-resident tensor read by both
    passes; the search harness's HBM gate rejects it where it cannot
    fit) instead of regenerating it from the in-kernel hardware PRNG.
    Identical dropout MATH either way — only the mask's source moves."""
    from ... import tuning

    cfg = tuning.maybe_lookup(
        "flash_bsh", {"sq": sq, "skv": skv, "h": h, "dtype": str(dtype)})
    return bool(cfg) and cfg.get("mask") == "materialize"


@functools.lru_cache(maxsize=256)
def _make_flash_core_bsh(*, sm_scale, nh, causal, dropout_prob):
    statics = dict(sm_scale=sm_scale, nh=nh, causal=causal,
                   dropout_prob=dropout_prob)

    @jax.custom_vjp
    def core(q, k, v, bias, mask, seed, offsets):
        o, _ = _flash_fwd_bsh(q, k, v, bias, mask, seed, offsets, **statics)
        return o

    def core_fwd(q, k, v, bias, mask, seed, offsets):
        o, lse = _flash_fwd_bsh(q, k, v, bias, mask, seed, offsets, **statics)
        o = checkpoint_name(o, "flash_o")
        lse = checkpoint_name(lse, "flash_lse")
        return o, (q, k, v, bias, mask, seed, offsets, o, lse)

    def core_bwd(res, g):
        dq, dk, dv = _flash_bwd_bsh(res, g, **statics)
        dbias = jnp.zeros_like(res[3]) if res[3] is not None else None
        return (dq, dk, dv, dbias, None, None, None)

    core.defvjp(core_fwd, core_bwd)
    return core


def flash_attention_bsh(q, k, v, bias=None, num_heads=None, sm_scale=None,
                        causal=False, dropout_prob=0.0, dropout_key=None,
                        dropout_seed=None, mesh=None, batch_axis="dp",
                        head_axis="tp"):
    """Transpose-free flash attention on projection-layout tensors.

    q: [B, S_q, H], k/v: [B, S_kv, H] with H = num_heads * D — exactly
    what the qkv/kv projections produce, no head split/merge transposes.
    S_q and S_kv may differ (cross-attention). bias: [B, 1, 1, S_kv] or
    [B, 1, S_kv] per-key additive (padding mask; zero cotangent — use the
    BHSD `flash_attention` for full biases or dbias). Returns [B, S_q, H].

    mesh: shard batch on `batch_axis` and HEADS on `head_axis` (the H
    lane dim splits per head groups; num_heads % tp == 0).
    """
    b, sq, hdim = q.shape
    if num_heads is None:
        raise ValueError("flash_attention_bsh needs num_heads")
    if causal and sq != k.shape[1]:
        raise ValueError(
            "flash_attention_bsh: causal with sq != skv would be top-left "
            "aligned (use the BHSD kernel with offsets, or equal lengths)")
    d = hdim // num_heads
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    if bias is not None:
        bias = bias.reshape(b, 1, k.shape[1]).astype(jnp.float32)

    seed = None
    mask = None
    if dropout_prob > 0.0:
        if dropout_seed is not None:
            seed = jnp.asarray(dropout_seed, jnp.int32).reshape(1)
        elif dropout_key is not None:
            seed = jax.random.randint(
                dropout_key, (1,), 0, jnp.iinfo(jnp.int32).max,
                dtype=jnp.int32)
        else:
            raise ValueError("dropout needs dropout_key or dropout_seed")
        # mask source is a tuned axis: interpret mode (no hardware PRNG)
        # and a cache entry saying {'mask': 'materialize'} both
        # precompute the keep mask outside the kernel; the default
        # regenerates it from the in-kernel PRNG with zero HBM traffic
        if _interpret() or _bsh_mask_materialize(sq, k.shape[1], hdim,
                                                 q.dtype):
            mkey = dropout_key if dropout_key is not None else (
                jax.random.PRNGKey(seed[0]))
            mask = jax.random.bernoulli(
                jax.random.fold_in(mkey, 7), 1.0 - dropout_prob,
                (b, num_heads, sq, k.shape[1]),
            ).astype(jnp.uint8)

    def local(ql, kl, vl, bl, ml, sl, nh_local):
        core = _make_flash_core_bsh(
            sm_scale=float(sm_scale), nh=nh_local, causal=causal,
            dropout_prob=dropout_prob)
        return core(ql, kl, vl, bl, ml, sl, None)

    axes = [
        ax for ax in (batch_axis, head_axis)
        if mesh is not None and ax in mesh.axis_names and mesh.shape[ax] > 1
    ]
    if not axes:
        return local(q, k, v, bias, mask, seed, num_heads)

    from ...compat import shard_map
    from jax.sharding import PartitionSpec as P

    ba = batch_axis if batch_axis in axes else None
    ha = head_axis if head_axis in axes else None
    nh_local = num_heads // (mesh.shape[ha] if ha else 1)
    qspec = P(ba, None, ha)
    bias_spec = P(ba, None, None) if bias is not None else None
    mask_spec = P(ba, ha, None, None) if mask is not None else None

    def body(ql, kl, vl, bl, ml, sl):
        local_seed = sl
        if sl is not None:
            import jax.lax as lax

            salt = jnp.int32(0)
            if ba:
                salt = salt + lax.axis_index(ba) * jnp.int32(0x632BE59B)
            if ha:
                salt = salt + lax.axis_index(ha) * jnp.int32(0x1B873593)
            local_seed = sl + salt
        return local(ql, kl, vl, bl, ml, local_seed, nh_local)

    in_specs = (qspec, qspec, qspec, bias_spec, mask_spec,
                P() if seed is not None else None)
    return shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=qspec,
        check=False,
    )(q, k, v, bias, mask, seed)


def flash_shapes_ok(s, d) -> bool:
    """THE shape/backend/flag gate for every flash dispatch site (the
    attention op, the encoder stack, and the ring path all call this)."""
    from ...fluid.flags import flag
    from ..attention import FORCE_PALLAS

    if not flag("FLAGS_use_flash_attention"):
        return False
    shapes_ok = d in (64, 128, 256) and s % MIN_BLOCK == 0
    if FORCE_PALLAS:
        return shapes_ok
    return shapes_ok and not _interpret()


flash_block_ok = flash_shapes_ok  # ring-path alias
