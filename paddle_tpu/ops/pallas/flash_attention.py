"""Flash attention (online-softmax) Pallas TPU kernel with custom VJP.

TPU-native replacement for the reference's fused BERT attention CUDA kernel
(/root/reference/paddle/fluid/operators/math/bert_encoder_functor.cu —
softmax over scores in shared memory) — here the whole attention is one
kernel: scores never materialize in HBM (O(S) memory instead of O(S^2)),
and the backward pass recomputes probabilities blockwise from the saved
log-sum-exp, the standard flash-attention-2 scheme.

Layout: q, k, v are [BH, S, D] (batch*heads flattened); optional additive
per-key bias is [B, S] (the BERT padding mask); heads of one batch share it.
Block sizes are 128 to match the MXU; D must be one of (64, 128, 256).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BLOCK_Q = 128
BLOCK_K = 128
NEG_INF = -1e30


def _interpret() -> bool:
    # 'axon' is a tunneled real TPU backend; anything else (cpu tests) runs
    # the kernel in interpreter mode for exact-semantics checking
    return jax.default_backend() not in ("tpu", "axon")


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref, *, sm_scale, num_heads):
    # q_ref [1, BQ, D]; k_ref/v_ref [1, S, D]; bias_ref [1, S] or None
    q = q_ref[0].astype(jnp.float32) * sm_scale
    seq_len = k_ref.shape[1]
    d = q.shape[-1]

    def body(i, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(i * BLOCK_K, BLOCK_K), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(i * BLOCK_K, BLOCK_K), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [BQ, BK]
        if bias_ref is not None:
            s = s + bias_ref[0, pl.ds(i * BLOCK_K, BLOCK_K)][None, :]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.dot(p, v, preferred_element_type=jnp.float32)
        return m_new, l, acc

    m0 = jnp.full((BLOCK_Q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((BLOCK_Q, 1), jnp.float32)
    acc0 = jnp.zeros((BLOCK_Q, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, seq_len // BLOCK_K, body, (m0, l0, acc0))
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0] = (m + jnp.log(l))[:, 0]


def _flash_fwd(q, k, v, bias, sm_scale, num_heads):
    bh, s, d = q.shape
    grid = (bh, s // BLOCK_Q)
    in_specs = [
        pl.BlockSpec((1, BLOCK_Q, d), lambda b, i: (b, i, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0), memory_space=pltpu.VMEM),
    ]
    args = [q, k, v]
    if bias is not None:
        in_specs.append(
            pl.BlockSpec(
                (1, s), lambda b, i: (b // num_heads, 0), memory_space=pltpu.VMEM
            )
        )
        args.append(bias)
        kernel = functools.partial(_fwd_kernel, sm_scale=sm_scale, num_heads=num_heads)
    else:
        kernel = functools.partial(
            lambda qr, kr, vr, o, lse, **kw: _fwd_kernel(qr, kr, vr, None, o, lse, **kw),
            sm_scale=sm_scale,
            num_heads=num_heads,
        )
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, BLOCK_Q, d), lambda b, i: (b, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, BLOCK_Q), lambda b, i: (b, i), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            jax.ShapeDtypeStruct((bh, s), jnp.float32),
        ],
        interpret=_interpret(),
    )(*args)
    return o, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref, delta_ref, dq_ref, *, sm_scale, num_heads
):
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0][:, None]
    delta = delta_ref[0][:, None]
    seq_len = k_ref.shape[1]
    d = q.shape[-1]

    def body(i, dq):
        k = k_ref[0, pl.ds(i * BLOCK_K, BLOCK_K), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(i * BLOCK_K, BLOCK_K), :].astype(jnp.float32)
        s = (
            jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )
            * sm_scale
        )
        if bias_ref is not None:
            s = s + bias_ref[0, pl.ds(i * BLOCK_K, BLOCK_K)][None, :]
        p = jnp.exp(s - lse)  # [BQ, BK]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * sm_scale
        return dq + jnp.dot(ds, k, preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(
        0, seq_len // BLOCK_K, body, jnp.zeros((BLOCK_Q, d), jnp.float32)
    )
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, *, sm_scale, num_heads
):
    k = k_ref[0].astype(jnp.float32)  # [BK, D]
    v = v_ref[0].astype(jnp.float32)
    seq_len = q_ref.shape[1]
    d = k.shape[-1]
    if bias_ref is not None:
        b_block = bias_ref[0, pl.ds(pl.program_id(1) * BLOCK_K, BLOCK_K)]
    else:
        b_block = None

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * BLOCK_Q, BLOCK_Q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(i * BLOCK_Q, BLOCK_Q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(i * BLOCK_Q, BLOCK_Q)][:, None]
        delta = delta_ref[0, pl.ds(i * BLOCK_Q, BLOCK_Q)][:, None]
        s = (
            jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )
            * sm_scale
        )
        if b_block is not None:
            s = s + b_block[None, :]
        p = jnp.exp(s - lse)  # [BQ, BK]
        dv = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * sm_scale  # [BQ, BK]
        dk = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return dk, dv

    dk0 = jnp.zeros((BLOCK_K, d), jnp.float32)
    dv0 = jnp.zeros((BLOCK_K, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(0, seq_len // BLOCK_Q, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd(res, g, sm_scale, num_heads):
    q, k, v, bias, o, lse = res
    bh, s, d = q.shape
    delta = jnp.sum(o.astype(jnp.float32) * g.astype(jnp.float32), axis=-1)  # [BH,S]

    qspec = pl.BlockSpec((1, BLOCK_Q, d), lambda b, i: (b, i, 0), memory_space=pltpu.VMEM)
    fullspec = pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0), memory_space=pltpu.VMEM)
    rowspec = pl.BlockSpec((1, BLOCK_Q), lambda b, i: (b, i), memory_space=pltpu.VMEM)
    fullrow = pl.BlockSpec((1, s), lambda b, i: (b, 0), memory_space=pltpu.VMEM)
    bias_spec = pl.BlockSpec((1, s), lambda b, i: (b // num_heads, 0), memory_space=pltpu.VMEM)

    # dq: grid over q blocks
    args = [q, k, v] + ([bias] if bias is not None else []) + [g, lse, delta]
    in_specs = [qspec, fullspec, fullspec] + ([bias_spec] if bias is not None else []) + [
        qspec,
        rowspec,
        rowspec,
    ]
    if bias is not None:
        dq_kernel = functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, num_heads=num_heads)
    else:
        dq_kernel = functools.partial(
            lambda qr, kr, vr, dor, lser, dr, dqr, **kw: _bwd_dq_kernel(
                qr, kr, vr, None, dor, lser, dr, dqr, **kw
            ),
            sm_scale=sm_scale,
            num_heads=num_heads,
        )
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, s // BLOCK_Q),
        in_specs=in_specs,
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        interpret=_interpret(),
    )(*args)

    # dk/dv: grid over k blocks
    kspec = pl.BlockSpec((1, BLOCK_K, d), lambda b, i: (b, i, 0), memory_space=pltpu.VMEM)
    fullq = pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0), memory_space=pltpu.VMEM)
    args2 = [q, k, v] + ([bias] if bias is not None else []) + [g, lse, delta]
    in_specs2 = [fullq, kspec, kspec] + ([bias_spec] if bias is not None else []) + [
        fullq,
        fullrow,
        fullrow,
    ]
    if bias is not None:
        dkv_kernel = functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, num_heads=num_heads)
    else:
        dkv_kernel = functools.partial(
            lambda qr, kr, vr, dor, lser, dr, dkr, dvr, **kw: _bwd_dkv_kernel(
                qr, kr, vr, None, dor, lser, dr, dkr, dvr, **kw
            ),
            sm_scale=sm_scale,
            num_heads=num_heads,
        )
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh, s // BLOCK_K),
        in_specs=in_specs2,
        out_specs=[kspec, kspec],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), k.dtype),
            jax.ShapeDtypeStruct((bh, s, d), v.dtype),
        ],
        interpret=_interpret(),
    )(*args2)

    dbias = None if bias is None else jnp.zeros_like(bias)
    return dq, dk, dv, dbias


# ---------------------------------------------------------------------------
# public entry: [B, nh, S, D] ± per-key bias [B, S]
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flash_core(q, k, v, bias, sm_scale, num_heads):
    o, _ = _flash_fwd(q, k, v, bias, sm_scale, num_heads)
    return o


def _flash_core_fwd(q, k, v, bias, sm_scale, num_heads):
    o, lse = _flash_fwd(q, k, v, bias, sm_scale, num_heads)
    return o, (q, k, v, bias, o, lse)


def _flash_core_bwd(sm_scale, num_heads, res, g):
    q, k, v, bias, o, lse = res
    dq, dk, dv, dbias = _flash_bwd((q, k, v, bias, o, lse), g, sm_scale, num_heads)
    return dq, dk, dv, dbias


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_attention(q, k, v, bias=None, sm_scale=None):
    """q,k,v: [B, nh, S, D]; bias: additive, broadcastable to [B,nh,S,S]
    but only the per-key form [B,1,1,S] is kernelized (BERT padding mask).
    Returns [B, nh, S, D]."""
    b, nh, s, d = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    key_bias = None
    if bias is not None:
        if bias.ndim == 4 and bias.shape[1] == 1 and bias.shape[2] == 1:
            key_bias = bias.reshape(b, bias.shape[-1]).astype(jnp.float32)
        else:
            raise ValueError(
                f"flash_attention kernel supports per-key bias [B,1,1,S]; got {bias.shape}"
            )
    qf = q.reshape(b * nh, s, d)
    kf = k.reshape(b * nh, s, d)
    vf = v.reshape(b * nh, s, d)
    o = _flash_core(qf, kf, vf, key_bias, sm_scale, nh)
    return o.reshape(b, nh, s, d)
