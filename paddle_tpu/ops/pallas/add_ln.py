"""Fused residual-add + LayerNorm Pallas TPU kernel with custom VJP.

TPU-native counterpart of the reference's fused residual/LayerNorm ops
(/root/reference/paddle/fluid/operators/fused/fused_layernorm_residual_dropout_bias.h
and layer_norm_op.cu — one CUDA kernel per row with welford stats).
Motivation measured on v5e (round-4 profile, BERT-base s512/b48): XLA's
convert+reduce LayerNorm fusions cost ~28 ms/step inside the encoder
scans — ~30x the bandwidth roofline for 4 row-stat passes over
[B,S,768] bf16 — while every matmul around them runs near peak. One
pass per row block with f32 stats in VMEM removes almost all of it.

Semantics (matching ops/encoder_stack._ln_f32 exactly):
    out = ((x + y) - mean) * rsqrt(var + eps) * scale + shift
computed in f32 regardless of input dtype, cast back to the input dtype.
y is the residual branch; pass y=None for plain LayerNorm. The backward
saves only the per-row (mean, rstd) f32 stats — x and y are values the
surrounding program already holds (or recomputes under remat policies),
and dx == dy (the add distributes the cotangent), so the bwd kernel
writes one tensor read twice by the caller.

Stats ride as [1, R] lane-major rows written through the same MXU
identity-transpose trick as the flash kernel's lse (a (R, 1)
sublane-major store costs a vreg-walking relayout).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...tuning import feasible as _feas
from .flash_attention import _identity, _interpret, _to_lanes, _to_sublanes

# single source shared with the autotuner's feasibility gate
_LN_VMEM_BUDGET = _feas.LN_VMEM_BUDGET

_ROW_CANDIDATES = (1024, 512, 256, 128, 64, 32, 16, 8)


def default_ln_rows(r, h):
    """THE hand-picked row-block chooser (the autotune cache-miss
    fallback): largest row block that tiles r under the VMEM budget
    (x, y, out blocks double-buffered bf16 + ~4 f32 temporaries per
    row block). None when nothing tiles."""
    for cand in _ROW_CANDIDATES:
        if r % cand == 0 and _feas.ln_vmem_bytes(cand, h) <= _LN_VMEM_BUDGET:
            return cand
    return None


_pick_rows = default_ln_rows  # historical name


def _resolve_ln_rows(r, h, dtype):
    """Row block for one kernel launch: FLAGS_kernel_autotune cache
    entry (validated against divisibility + the VMEM budget) or the
    hand-picked default. fwd and bwd resolve through the same entry, so
    the saved [1, R] stats always re-block consistently."""
    from ... import tuning

    key = {"r": r, "h": h, "dtype": str(dtype)}
    cfg = tuning.maybe_lookup("add_ln", key)
    if cfg:
        try:
            rows = int(cfg.get("block_rows", 0))
        except (TypeError, ValueError):
            rows = 0
        ok, _why = _feas.ln_rows_ok(r, h, rows)
        if ok:
            return rows
        tuning.note_choice("add_ln", key, None, "default")
    return default_ln_rows(r, h)


def ln_shapes_ok(r, h) -> bool:
    return h % 128 == 0 and default_ln_rows(r, h) is not None


def _fwd_kernel(*refs, eps, has_y, br):
    it = iter(refs)
    x_ref = next(it)
    y_ref = next(it) if has_y else None
    scale_ref = next(it)
    shift_ref = next(it)
    out_ref = next(it)
    mean_ref = next(it)
    rstd_ref = next(it)
    s = x_ref[...].astype(jnp.float32)
    if has_y:
        s = s + y_ref[...].astype(jnp.float32)
    mu = jnp.mean(s, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(s - mu), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (s - mu) * rstd
    out_ref[...] = (
        xhat * scale_ref[...].astype(jnp.float32)
        + shift_ref[...].astype(jnp.float32)
    ).astype(out_ref.dtype)
    ident = _identity(br)
    mean_ref[...] = _to_lanes(mu, ident)
    rstd_ref[...] = _to_lanes(rstd, ident)


def _bwd_kernel(*refs, has_y, br):
    it = iter(refs)
    x_ref = next(it)
    y_ref = next(it) if has_y else None
    scale_ref = next(it)
    mean_ref = next(it)
    rstd_ref = next(it)
    g_ref = next(it)
    dx_ref = next(it)
    dsc_ref = next(it)
    dsh_ref = next(it)
    ident = _identity(br)
    s = x_ref[...].astype(jnp.float32)
    if has_y:
        s = s + y_ref[...].astype(jnp.float32)
    mu = _to_sublanes(mean_ref[...], ident)
    rstd = _to_sublanes(rstd_ref[...], ident)
    xhat = (s - mu) * rstd
    g = g_ref[...].astype(jnp.float32)
    # per-block partials land in [NB, 1, H] (the 3-D shape keeps the
    # trailing block dims (1, H) legal for any NB); summed by the caller
    dsc_ref[...] = jnp.sum(g * xhat, axis=0, keepdims=True)[None]
    dsh_ref[...] = jnp.sum(g, axis=0, keepdims=True)[None]
    gs = g * scale_ref[...].astype(jnp.float32)
    m1 = jnp.mean(gs, axis=-1, keepdims=True)
    m2 = jnp.mean(gs * xhat, axis=-1, keepdims=True)
    dx_ref[...] = (rstd * (gs - m1 - xhat * m2)).astype(dx_ref.dtype)


def _ln_fwd(x, y, scale, shift, *, eps):
    r, h = x.shape
    br = _resolve_ln_rows(r, h, x.dtype)
    has_y = y is not None
    row_spec = pl.BlockSpec((br, h), lambda i: (i, 0), memory_space=pltpu.VMEM)
    vec_spec = pl.BlockSpec((1, h), lambda i: (0, 0), memory_space=pltpu.VMEM)
    stat_spec = pl.BlockSpec((1, br), lambda i: (0, i), memory_space=pltpu.VMEM)
    args = [x] + ([y] if has_y else []) + [scale.reshape(1, h), shift.reshape(1, h)]
    in_specs = [row_spec] * (2 if has_y else 1) + [vec_spec, vec_spec]
    out, mean, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps, has_y=has_y, br=br),
        grid=(r // br,),
        in_specs=in_specs,
        out_specs=[row_spec, stat_spec, stat_spec],
        out_shape=[
            jax.ShapeDtypeStruct((r, h), x.dtype),
            jax.ShapeDtypeStruct((1, r), jnp.float32),
            jax.ShapeDtypeStruct((1, r), jnp.float32),
        ],
        interpret=_interpret(),
    )(*args)
    return out, mean, rstd


def _ln_bwd(x, y, scale, mean, rstd, g, *, eps):
    r, h = x.shape
    br = _resolve_ln_rows(r, h, x.dtype)
    has_y = y is not None
    row_spec = pl.BlockSpec((br, h), lambda i: (i, 0), memory_space=pltpu.VMEM)
    vec_spec = pl.BlockSpec((1, h), lambda i: (0, 0), memory_space=pltpu.VMEM)
    stat_spec = pl.BlockSpec((1, br), lambda i: (0, i), memory_space=pltpu.VMEM)
    part_spec = pl.BlockSpec((1, 1, h), lambda i: (i, 0, 0),
                             memory_space=pltpu.VMEM)
    nb = r // br
    args = [x] + ([y] if has_y else []) + [scale.reshape(1, h), mean, rstd, g]
    in_specs = (
        [row_spec] * (2 if has_y else 1)
        + [vec_spec, stat_spec, stat_spec, row_spec]
    )
    dx, dsc, dsh = pl.pallas_call(
        functools.partial(_bwd_kernel, has_y=has_y, br=br),
        grid=(nb,),
        in_specs=in_specs,
        out_specs=[row_spec, part_spec, part_spec],
        out_shape=[
            jax.ShapeDtypeStruct((r, h), x.dtype),
            jax.ShapeDtypeStruct((nb, 1, h), jnp.float32),
            jax.ShapeDtypeStruct((nb, 1, h), jnp.float32),
        ],
        interpret=_interpret(),
    )(*args)
    return dx, dsc.sum(axis=(0, 1)), dsh.sum(axis=(0, 1))


@functools.lru_cache(maxsize=32)
def _make_core(eps, has_y):
    @jax.custom_vjp
    def core(x, y, scale, shift):
        out, _, _ = _ln_fwd(x, y, scale, shift, eps=eps)
        return out

    def core_fwd(x, y, scale, shift):
        out, mean, rstd = _ln_fwd(x, y, scale, shift, eps=eps)
        return out, (x, y, scale, mean, rstd)

    def core_bwd(res, g):
        x, y, scale, mean, rstd = res
        dx, dsc, dsh = _ln_bwd(x, y, scale, mean, rstd, g, eps=eps)
        return (
            dx,
            dx if has_y else None,
            dsc.astype(scale.dtype),
            dsh.astype(scale.dtype),
        )

    core.defvjp(core_fwd, core_bwd)
    return core


def fused_ln_dispatch_ok(shape) -> bool:
    """Backend/flag/shape gate for every fused-LN dispatch site (mirrors
    flash_shapes_ok)."""
    from ...fluid.flags import flag
    from ..attention import FORCE_PALLAS

    if not flag("FLAGS_use_fused_ln"):
        return False
    h = shape[-1]
    r = 1
    for d in shape[:-1]:
        r *= d
    ok = ln_shapes_ok(r, h)
    if FORCE_PALLAS:
        return ok
    return ok and not _interpret()


def fused_add_ln(x, y, scale, shift, eps=1e-5):
    """LayerNorm(x + y) over the last axis with f32 stats; y may be None.

    x/y: [..., H]; scale/shift: [H]. Dispatch gate: `ln_shapes_ok` on the
    flattened row count and H — callers fall back to the jnp composition
    otherwise (identical math).
    """
    shape = x.shape
    h = shape[-1]
    r = 1
    for d in shape[:-1]:
        r *= d
    if not ln_shapes_ok(r, h):
        raise _feas.NoFeasibleConfig(
            "add_ln", {"r": r, "h": h},
            [({"block_rows": c}, _feas.ln_rows_ok(r, h, c)[1])
             for c in _ROW_CANDIDATES],
            detail=("hidden dim must be a multiple of 128"
                    if h % 128 else "gate with fused_ln_dispatch_ok"))
    core = _make_core(float(eps), y is not None)
    out = core(
        x.reshape(r, h),
        None if y is None else y.reshape(r, h),
        scale.reshape(h),
        shift.reshape(h),
    )
    return out.reshape(shape)
