"""Pallas TPU kernels — the hot-op layer.

Where the reference ships hand-written CUDA (e.g.
/root/reference/paddle/fluid/operators/math/bert_encoder_functor.cu), this
package ships Pallas kernels tuned for the MXU/VMEM; everything else rides
XLA fusion.

Kernels: flash_attention (fused MHA), add_ln (residual+LayerNorm),
conv_bn (conv + batch-norm statistics + normalize + relu — the ResNet
conv-path bandwidth lever, bench_artifacts/resnet50_ceiling.md).
"""
