"""Fused NHWC conv -> batch_norm -> (optional) ReLU Pallas TPU kernels.

The ResNet-50 ceiling analysis (bench_artifacts/resnet50_ceiling.md) pins
the conv path at 0.30-0.31 MFU: every conv is separated by a batch-norm
whose statistics force a full HBM read-modify-write of the activation, so
~100 conv+BN tuple fusions each run within ~2x of their bandwidth bound.
XLA declines the producer-consumer fusion across the reduction boundary
(the arXiv:2301.13062 fusion gap); these kernels take it by hand:

  forward  pass 1: conv output tiles computed on the MXU with per-channel
           (sum, sum-of-squares) accumulated across the grid in the SAME
           kernel — the separate stats pass over the activation is gone.
  forward  pass 2: normalize + scale + shift (+ relu) in one elementwise
           sweep (the stats finalize [C]-sized math sits between the two
           pallas calls and is noise).
  backward pass 1: relu-mask + dgamma/dbeta partials in one read of
           (conv_out, grad) — the relu mask is recomputed from saved
           per-channel stats, no mask tensor is ever materialized.
  backward pass 2: the BN input cotangent dz in one elementwise sweep.
  backward conv:   dX / dW stay on XLA's native conv schedules — the
           round-5 experiments (FLAGS_conv_dw_im2col) measured them as
           the best available; only the normalization chain around them
           is replaced.

Coverage (conv_bn_shapes_ok): NHWC, groups=1, dilation=1; kh=kw=1 with
any stride (the 1x1 conv is lowered to one row-blocked matmul, strided
cases pre-subsample x — exact for 1x1), or any kernel size with stride 1
(per-image grid, halo rows come in with the padded block). Everything
else falls back to `conv_bn_reference` — the jnp composition with
IDENTICAL math (one-pass f32 moments, the batch_norm emitter convention),
so the fused_conv_bn op is always semantically one op regardless of
which engine runs it.

Stats outputs (batch mean/var) are state, not data: their cotangents are
structurally zero in real programs (MeanOut/VarianceOut feed non-trainable
moving-average params, SavedMean/SavedVariance are stop_gradient — the
same contract as the unfused batch_norm op), and the custom VJP ignores
them. Do not differentiate through the returned batch stats.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...tuning import feasible as _feas
from .flash_attention import _interpret

# per-grid-step VMEM budget: in/out blocks double-buffered + the f32
# accumulator; leaves headroom of the ~16MB/core for Mosaic's own use.
# The byte value lives in tuning/feasible.py so the autotuner's
# feasibility gate and the kernel can never disagree about it.
_CONV_BN_VMEM_BUDGET = _feas.CONV_BN_VMEM_BUDGET

_ROW_CANDIDATES = (2048, 1024, 512, 256, 128, 64, 32, 16, 8, 4, 2, 1)


def default_conv_bn_rows(r, width, bytes_per_row_unit):
    """THE hand-picked row-block chooser (the autotune cache-miss
    fallback): largest row block dividing r whose working set fits the
    budget."""
    for cand in _ROW_CANDIDATES:
        if r % cand == 0 and cand * width * bytes_per_row_unit <= _CONV_BN_VMEM_BUDGET:
            return cand
    return None


_pick_rows = default_conv_bn_rows  # historical name


def _resolve_rows(r, width, bytes_per_row_unit, kind, dtype):
    """Row block for one row-blocked pass (kind 'mm' = the 1x1 matmul
    pass, 'apply' = the normalize/backward elementwise sweeps):
    FLAGS_kernel_autotune cache entry validated against divisibility +
    the VMEM budget, else the hand-picked default."""
    from ... import tuning

    key = {"kind": kind, "r": r, "w": width, "dtype": str(dtype)}
    cfg = tuning.maybe_lookup("conv_bn", key)
    if cfg:
        try:
            rows = int(cfg.get("block_rows", 0))
        except (TypeError, ValueError):
            rows = 0
        ok, _why = _feas.conv_bn_rows_ok(r, width, rows, bytes_per_row_unit)
        if ok:
            return rows
        tuning.note_choice("conv_bn", key, None, "default")
    return default_conv_bn_rows(r, width, bytes_per_row_unit)


def _resolve_pads(pad, h, w, kh, kw, strides):
    """Normalize a lax-style padding spec to explicit ((lo,hi),(lo,hi))."""
    if pad == "VALID":
        return ((0, 0), (0, 0))
    if pad == "SAME":
        out = []
        for size, k, s in ((h, kh, strides[0]), (w, kw, strides[1])):
            total = max((-(-size // s) - 1) * s + k - size, 0)
            out.append((total // 2, total - total // 2))
        return tuple(out)
    return tuple((int(lo), int(hi)) for lo, hi in pad)


def conv_bn_shapes_ok(x_shape, w_shape, strides, pads, dilations=(1, 1),
                      groups=1) -> bool:
    """Structural + VMEM gate for the Pallas path (pads already explicit)."""
    n, h, w, c = x_shape
    o, cg, kh, kw = w_shape
    if groups != 1 or tuple(dilations) != (1, 1) or cg != c:
        return False
    if (kh, kw) == (1, 1):
        if any(p != (0, 0) for p in pads):
            return False
        ho = -(-h // strides[0])
        wo = -(-w // strides[1])
        r = n * ho * wo
        # x + y blocks double-buffered bf16-worst + f32 accumulator
        return _pick_rows(r, c + o, 2 * 2 + 4) is not None
    if tuple(strides) != (1, 1):
        return False
    hp = h + pads[0][0] + pads[0][1]
    wp = w + pads[1][0] + pads[1][1]
    ho, wo = hp - kh + 1, wp - kw + 1
    if ho <= 0 or wo <= 0:
        return False
    per_img = (
        2 * 2 * hp * wp * c          # x block, double-buffered, <=2B elts
        + 2 * 2 * ho * wo * o        # y block
        + 4 * ho * wo * o            # f32 accumulator
        + 2 * kh * kw * c * o        # weights (resident)
    )
    return per_img <= _CONV_BN_VMEM_BUDGET


def conv_bn_dispatch_ok(x_shape, w_shape, strides, pads, dilations=(1, 1),
                        groups=1) -> bool:
    """Backend + shape gate for dispatch sites (mirrors
    fused_ln_dispatch_ok): CPU/interpret runs take the jnp reference path
    unless FORCE_PALLAS pins the kernel (tests)."""
    from ..attention import FORCE_PALLAS

    ok = conv_bn_shapes_ok(x_shape, w_shape, strides, pads, dilations, groups)
    if FORCE_PALLAS:
        return ok
    return ok and not _interpret()


def conv_bn_s2d_ok(x_shape, w_shape, strides, pads) -> bool:
    """Structural + VMEM gate for the space-to-depth lowering of a kxk
    STRIDE-2 conv (pads already explicit): the 2x2 input phases stack
    into 4C channels, the filter splits into ceil(k/2)^2 taps, and the
    conv becomes stride-1 — servable by the per-image Pallas kernel
    that conv_bn_shapes_ok otherwise rejects for k>1 strided cases.

    Exactness conditions: each padded extent must be even OR the kernel
    odd along that dim (otherwise the evening pad row would enter the
    last window and change the output size)."""
    n, h, w, c = x_shape
    o, cg, kh, kw = w_shape
    if tuple(strides) != (2, 2) or cg != c or (kh, kw) == (1, 1):
        return False
    hp = h + pads[0][0] + pads[0][1]
    wp = w + pads[1][0] + pads[1][1]
    for ext, k in ((hp, kh), (wp, kw)):
        if ext % 2 and k % 2 == 0:
            return False
    ho = (hp - kh) // 2 + 1
    wo = (wp - kw) // 2 + 1
    if ho <= 0 or wo <= 0:
        return False
    # the normalize/backward sweeps must tile too
    if default_conv_bn_rows(n * ho * wo, o, 3 * 4) is None:
        return False
    return (_feas.conv_bn_s2d_per_image_bytes(hp, wp, c, o, kh, kw)
            <= _CONV_BN_VMEM_BUDGET)


def _s2d_wanted(x_shape, w_shape, strides, pads, dtype) -> bool:
    """The tuned space-to-depth axis (ISSUE 13): a kxk stride-2 conv is
    routed through the s2d lowering only when the autotune cache holds
    {'space_to_depth': 1} for this conv signature AND the structural
    gate passes — with the flag off or the cache empty, these convs
    take exactly the path they take today (the jnp reference)."""
    if not conv_bn_s2d_ok(x_shape, w_shape, strides, pads):
        return False
    from ..attention import FORCE_PALLAS

    if _interpret() and not FORCE_PALLAS:
        return False
    from ... import tuning

    n, h, w_sp, c = x_shape
    o, _cg, kh, kw = w_shape
    cfg = tuning.maybe_lookup("conv_bn_s2d", {
        "n": n, "h": h, "w": w_sp, "c": c, "o": o, "kh": kh, "kw": kw,
        "sh": int(strides[0]), "sw": int(strides[1]),
        "dtype": str(dtype)})
    return bool(cfg) and bool(cfg.get("space_to_depth"))


# ---------------------------------------------------------------------------
# reference composition (fallback path + test oracle) — the exact math of
# the unfused conv2d + batch_norm(+relu) emitters (ops/nn_ops.py)
# ---------------------------------------------------------------------------


def conv_bn_reference(x, w, scale, bias, *, strides, pads, eps=1e-5,
                      with_relu=False):
    """Returns (y, batch_mean, batch_var); f32 one-pass moments."""
    z = jax.lax.conv_general_dilated(
        x, w, window_strides=tuple(strides), padding=tuple(pads),
        dimension_numbers=("NHWC", "OIHW", "NHWC"),
    )
    zf = z.astype(jnp.float32)
    m = jnp.mean(zf, axis=(0, 1, 2))
    v = jnp.maximum(jnp.mean(zf * zf, axis=(0, 1, 2)) - m * m, 0.0)
    inv = jax.lax.rsqrt(v + eps)
    y = (zf - m) * inv * scale.astype(jnp.float32) + bias.astype(jnp.float32)
    if with_relu:
        y = jnp.maximum(y, 0.0)
    return y.astype(x.dtype), m, v


# ---------------------------------------------------------------------------
# forward kernels
# ---------------------------------------------------------------------------


def _accumulate_stats(y, s_ref, ss_ref):
    """Per-channel (sum, sumsq) accumulated across the sequential grid.
    Stats are taken on the STORED (dtype-rounded) conv output so the fused
    moments match what the unfused batch_norm computes from the conv op's
    written activation."""
    yf = y.astype(jnp.float32)
    ps = jnp.sum(yf, axis=0, keepdims=True)
    pss = jnp.sum(yf * yf, axis=0, keepdims=True)
    first = pl.program_id(0) == 0

    @pl.when(first)
    def _():
        s_ref[...] = ps
        ss_ref[...] = pss

    @pl.when(jnp.logical_not(first))
    def _():
        s_ref[...] += ps
        ss_ref[...] += pss


def _mm_stats_kernel(x_ref, w_ref, y_ref, s_ref, ss_ref):
    """1x1 conv as matmul + fused stats: x [br, C] @ w [C, O]."""
    acc = jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    y = acc.astype(y_ref.dtype)
    y_ref[...] = y
    _accumulate_stats(y, s_ref, ss_ref)


def _conv_stats_kernel(x_ref, w_ref, y_ref, s_ref, ss_ref, *, kh, kw, ho, wo):
    """kxk stride-1 conv per image as kh*kw shifted matmuls + fused stats.

    x_ref [1, Hp, Wp, C] carries the halo (input pre-padded); w_ref is
    [kh*kw*C, O] with rows ordered (ki, kj, c)."""
    x = x_ref[0]
    c = x.shape[-1]
    o = w_ref.shape[-1]
    acc = jnp.zeros((ho * wo, o), jnp.float32)
    for ki in range(kh):
        for kj in range(kw):
            xs = x[ki:ki + ho, kj:kj + wo, :].reshape(ho * wo, c)
            wk = w_ref[(ki * kw + kj) * c:(ki * kw + kj + 1) * c, :]
            acc = acc + jax.lax.dot_general(
                xs, wk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
    y = acc.astype(y_ref.dtype)
    y_ref[0] = y.reshape(ho, wo, o)
    _accumulate_stats(y, s_ref, ss_ref)


def _apply_kernel(y_ref, stat_ref, out_ref, *, with_relu):
    """normalize+affine(+relu): stat rows = (mean, rstd, scale, shift)."""
    y = y_ref[...].astype(jnp.float32)
    out = (y - stat_ref[0:1, :]) * stat_ref[1:2, :] * stat_ref[2:3, :] \
        + stat_ref[3:4, :]
    if with_relu:
        out = jnp.maximum(out, 0.0)
    out_ref[...] = out.astype(out_ref.dtype)


# ---------------------------------------------------------------------------
# backward kernels (relu-mask + BN chain; conv grads stay on XLA)
# ---------------------------------------------------------------------------


def _masked_grad(y, g, stat_ref, with_relu):
    xhat = (y - stat_ref[0:1, :]) * stat_ref[1:2, :]
    if with_relu:
        keep = xhat * stat_ref[2:3, :] + stat_ref[3:4, :] > 0.0
        g = jnp.where(keep, g, 0.0)
    return xhat, g


def _bwd_reduce_kernel(y_ref, g_ref, stat_ref, dg_ref, db_ref, *, with_relu):
    """Per-block (dgamma, dbeta) partials in [NB, 1, O] (summed by the
    caller — the add_ln partials convention)."""
    y = y_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    xhat, g = _masked_grad(y, g, stat_ref, with_relu)
    dg_ref[...] = jnp.sum(g * xhat, axis=0, keepdims=True)[None]
    db_ref[...] = jnp.sum(g, axis=0, keepdims=True)[None]


def _bwd_dz_kernel(y_ref, g_ref, stat_ref, tot_ref, dz_ref, *, with_relu,
                   rcount):
    """BN input cotangent: dz = gamma*rstd*(g - dbeta/R - xhat*dgamma/R).
    tot rows = (dgamma_total, dbeta_total)."""
    y = y_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    xhat, g = _masked_grad(y, g, stat_ref, with_relu)
    dz = stat_ref[1:2, :] * stat_ref[2:3, :] * (
        g - tot_ref[1:2, :] * rcount - xhat * tot_ref[0:1, :] * rcount
    )
    dz_ref[...] = dz.astype(dz_ref.dtype)


# ---------------------------------------------------------------------------
# host-side orchestration
# ---------------------------------------------------------------------------


def _row_specs(br, width):
    return pl.BlockSpec((br, width), lambda i: (i, 0), memory_space=pltpu.VMEM)


def _const_spec(rows, width):
    return pl.BlockSpec((rows, width), lambda i: (0, 0),
                        memory_space=pltpu.VMEM)


def _conv_fwd(x, w2d, out_dtype, kh, kw, pads):
    """k>1 stride-1 path: per-image grid, padded input carries the halo."""
    n, h, w_sp, c = x.shape
    o = w2d.shape[-1]
    xp = jnp.pad(x, ((0, 0), pads[0], pads[1], (0, 0)))
    hp, wp = xp.shape[1], xp.shape[2]
    ho, wo = hp - kh + 1, wp - kw + 1
    y, s, ss = pl.pallas_call(
        functools.partial(_conv_stats_kernel, kh=kh, kw=kw, ho=ho, wo=wo),
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, hp, wp, c), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            _const_spec(kh * kw * c, o),
        ],
        out_specs=[
            pl.BlockSpec((1, ho, wo, o), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            _const_spec(1, o),
            _const_spec(1, o),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, ho, wo, o), out_dtype),
            jax.ShapeDtypeStruct((1, o), jnp.float32),
            jax.ShapeDtypeStruct((1, o), jnp.float32),
        ],
        interpret=_interpret(),
    )(xp, w2d)
    return y.reshape(n * ho * wo, o), (n, ho, wo, o), s, ss


def _mm_fwd(x, w2d, out_dtype, strides):
    """1x1 path: (strided-subsampled) x flattened to rows x one matmul."""
    if strides != (1, 1):
        x = jax.lax.slice(x, (0, 0, 0, 0), x.shape,
                          (1, strides[0], strides[1], 1))
    n, ho, wo, c = x.shape
    o = w2d.shape[-1]
    r = n * ho * wo
    br = _resolve_rows(r, c + o, 2 * 2 + 4, "mm", x.dtype)
    y, s, ss = pl.pallas_call(
        _mm_stats_kernel,
        grid=(r // br,),
        in_specs=[_row_specs(br, c), _const_spec(c, o)],
        out_specs=[_row_specs(br, o), _const_spec(1, o), _const_spec(1, o)],
        out_shape=[
            jax.ShapeDtypeStruct((r, o), out_dtype),
            jax.ShapeDtypeStruct((1, o), jnp.float32),
            jax.ShapeDtypeStruct((1, o), jnp.float32),
        ],
        interpret=_interpret(),
    )(x.reshape(r, c), w2d)
    return y, (n, ho, wo, o), s, ss


def _space_to_depth_x(x, pads):
    """Exact stride-2 -> stride-1 input rearrangement: pad, even the
    extents (the extra zero row/col is provably outside every valid
    window under conv_bn_s2d_ok's parity condition), then stack the 2x2
    phase grid into channels: [N, Hp/2, Wp/2, 4C] with phase (a, b) at
    channels [(a*2+b)*C, (a*2+b+1)*C)."""
    xp = jnp.pad(x, ((0, 0), pads[0], pads[1], (0, 0)))
    hp, wp = xp.shape[1], xp.shape[2]
    if hp % 2 or wp % 2:
        xp = jnp.pad(xp, ((0, 0), (0, hp % 2), (0, wp % 2), (0, 0)))
    n, hp, wp, c = xp.shape
    x4 = xp.reshape(n, hp // 2, 2, wp // 2, 2, c)
    return x4.transpose(0, 1, 3, 2, 4, 5).reshape(n, hp // 2, wp // 2, 4 * c)


def _s2d_weights(w):
    """OIHW [O, C, kh, kw] -> [O, 4C, ceil(kh/2), ceil(kw/2)]: tap
    (du, dv) of phase (a, b) is original tap (2du+a, 2dv+b); taps past
    the original kernel extent stay zero (the sparse rearrangement that
    makes stride-2 kxk EXACTLY a stride-1 conv over the phase image)."""
    o, c, kh, kw = w.shape
    k2h, k2w = (kh + 1) // 2, (kw + 1) // 2
    w4 = jnp.zeros((o, 4 * c, k2h, k2w), w.dtype)
    for a in (0, 1):
        for b in (0, 1):
            lo = (a * 2 + b) * c
            for du in range(k2h):
                ki = 2 * du + a
                if ki >= kh:
                    continue
                for dv in range(k2w):
                    kj = 2 * dv + b
                    if kj >= kw:
                        continue
                    w4 = w4.at[:, lo:lo + c, du, dv].set(w[:, :, ki, kj])
    return w4


def _elementwise_rows(r, o, dtype=jnp.float32):
    # y + out + grad all <=4B, double-buffered
    br = _resolve_rows(r, o, 3 * 4, "apply", dtype)
    if br is None:
        raise _feas.NoFeasibleConfig(
            "conv_bn", {"kind": "apply", "r": r, "w": o},
            [({"block_rows": c},
              _feas.conv_bn_rows_ok(r, o, c, 3 * 4)[1])
             for c in _ROW_CANDIDATES])
    return br


def _pallas_fwd(x, w, scale, bias, *, strides, pads, eps, with_relu,
                s2d=False):
    o, c, kh, kw = w.shape
    if s2d:
        # kxk stride-2 via space-to-depth: stride-1 per-image kernel
        # over the phase image with the sparsely rearranged filter
        x4 = _space_to_depth_x(x, pads)
        w4 = _s2d_weights(w)
        k2h, k2w = w4.shape[2], w4.shape[3]
        w2d = jnp.transpose(w4, (2, 3, 1, 0)).reshape(k2h * k2w * 4 * c, o)
        z2d, oshape, s, ss = _conv_fwd(x4, w2d, x.dtype, k2h, k2w,
                                       ((0, 0), (0, 0)))
    elif (kh, kw) == (1, 1):
        w2d = jnp.transpose(w, (2, 3, 1, 0)).reshape(kh * kw * c, o)
        z2d, oshape, s, ss = _mm_fwd(x, w2d, x.dtype, strides)
    else:
        w2d = jnp.transpose(w, (2, 3, 1, 0)).reshape(kh * kw * c, o)
        z2d, oshape, s, ss = _conv_fwd(x, w2d, x.dtype, kh, kw, pads)
    r = z2d.shape[0]
    m = s[0] / r
    v = jnp.maximum(ss[0] / r - m * m, 0.0)
    inv = jax.lax.rsqrt(v + eps)
    stat = jnp.stack(
        [m, inv, scale.astype(jnp.float32), bias.astype(jnp.float32)]
    )
    br = _elementwise_rows(r, o, x.dtype)
    y2d = pl.pallas_call(
        functools.partial(_apply_kernel, with_relu=with_relu),
        grid=(r // br,),
        in_specs=[_row_specs(br, o), _const_spec(4, o)],
        out_specs=_row_specs(br, o),
        out_shape=jax.ShapeDtypeStruct((r, o), x.dtype),
        interpret=_interpret(),
    )(z2d, stat)
    return y2d.reshape(oshape), z2d, stat, m, v


def _pallas_bwd(x, w, z2d, stat, g, *, strides, pads, with_relu):
    r, o = z2d.shape
    br = _elementwise_rows(r, o, x.dtype)
    nb = r // br
    g2d = g.reshape(r, o)
    part_spec = pl.BlockSpec((1, 1, o), lambda i: (i, 0, 0),
                             memory_space=pltpu.VMEM)
    dg, db = pl.pallas_call(
        functools.partial(_bwd_reduce_kernel, with_relu=with_relu),
        grid=(nb,),
        in_specs=[_row_specs(br, o), _row_specs(br, o), _const_spec(4, o)],
        out_specs=[part_spec, part_spec],
        out_shape=[
            jax.ShapeDtypeStruct((nb, 1, o), jnp.float32),
            jax.ShapeDtypeStruct((nb, 1, o), jnp.float32),
        ],
        interpret=_interpret(),
    )(z2d, g2d, stat)
    dgamma = dg.sum(axis=(0, 1))
    dbeta = db.sum(axis=(0, 1))
    tot = jnp.stack([dgamma, dbeta])
    dz2d = pl.pallas_call(
        functools.partial(_bwd_dz_kernel, with_relu=with_relu,
                          rcount=1.0 / r),
        grid=(nb,),
        in_specs=[_row_specs(br, o), _row_specs(br, o), _const_spec(4, o),
                  _const_spec(2, o)],
        out_specs=_row_specs(br, o),
        out_shape=jax.ShapeDtypeStruct((r, o), x.dtype),
        interpret=_interpret(),
    )(z2d, g2d, stat, tot)
    # dX / dW on XLA's native conv schedules (the measured best — see the
    # round-5 im2col experiment); the primal conv is dead code under jit
    n, h, w_sp, c = x.shape
    kh, kw = w.shape[2], w.shape[3]
    ho = (h + pads[0][0] + pads[0][1] - kh) // strides[0] + 1
    wo = (w_sp + pads[1][0] + pads[1][1] - kw) // strides[1] + 1
    _, vjp_fn = jax.vjp(
        lambda x_, w_: jax.lax.conv_general_dilated(
            x_, w_, window_strides=tuple(strides), padding=tuple(pads),
            dimension_numbers=("NHWC", "OIHW", "NHWC"),
        ),
        x, w,
    )
    dx, dw = vjp_fn(dz2d.reshape(n, ho, wo, -1))
    return dx, dw, dgamma, dbeta


@functools.lru_cache(maxsize=64)
def _make_core(kh, kw, strides, pads, eps, with_relu, s2d=False):
    @jax.custom_vjp
    def core(x, w, scale, bias):
        y, _, _, m, v = _pallas_fwd(
            x, w, scale, bias, strides=strides, pads=pads, eps=eps,
            with_relu=with_relu, s2d=s2d,
        )
        return y, m, v

    def core_fwd(x, w, scale, bias):
        y, z2d, stat, m, v = _pallas_fwd(
            x, w, scale, bias, strides=strides, pads=pads, eps=eps,
            with_relu=with_relu, s2d=s2d,
        )
        return (y, m, v), (x, w, scale, z2d, stat)

    def core_bwd(res, cots):
        x, w, scale, z2d, stat = res
        g, _dm, _dv = cots  # batch-stat cotangents are state: zero by contract
        dx, dw, dgamma, dbeta = _pallas_bwd(
            x, w, z2d, stat, g, strides=strides, pads=pads,
            with_relu=with_relu,
        )
        return dx, dw, dgamma.astype(scale.dtype), dbeta.astype(scale.dtype)

    core.defvjp(core_fwd, core_bwd)
    return core


def fused_conv_bn(x, w, scale, bias, *, strides=(1, 1), pads="SAME",
                  eps=1e-5, with_relu=False):
    """Fused training-mode conv+BN(+ReLU) over NHWC x / OIHW w.

    Returns (y, batch_mean, batch_var) — batch moments in f32 for the
    caller's running-average update. Dispatches to the Pallas kernels
    when `conv_bn_dispatch_ok` passes, else to the jnp reference
    composition (identical math)."""
    strides = tuple(int(s) for s in strides)
    kh, kw = int(w.shape[2]), int(w.shape[3])
    pads = _resolve_pads(pads, x.shape[1], x.shape[2], kh, kw, strides)
    if conv_bn_dispatch_ok(x.shape, w.shape, strides, pads):
        core = _make_core(kh, kw, strides, pads, float(eps), bool(with_relu))
        return core(x, w, scale, bias)
    if _s2d_wanted(x.shape, w.shape, strides, pads, x.dtype):
        # tuned kxk stride-2 space-to-depth lowering (autotune cache
        # opt-in; exact — see _s2d_weights)
        core = _make_core(kh, kw, strides, pads, float(eps),
                          bool(with_relu), s2d=True)
        return core(x, w, scale, bias)
    return conv_bn_reference(
        x, w, scale, bias, strides=strides, pads=pads, eps=eps,
        with_relu=with_relu,
    )
