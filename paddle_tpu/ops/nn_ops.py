"""Neural-net ops: conv/pool/norm/dropout/embedding/losses.

Parity surface: reference conv_op.cc + conv_cudnn_op.cu.cc, pool_op.cc,
batch_norm_op.cc, layer_norm_op.cc, group_norm_op.cc, instance_norm_op.cc,
dropout_op.cc, lookup_table_v2_op.cc, one_hot_v2_op.cc, cross_entropy_op.cc,
softmax_with_cross_entropy_op.cc, sigmoid_cross_entropy_with_logits_op.cc,
squared_error / huber / log_loss ops, metrics/accuracy_op.cc.

TPU notes: convs lower to lax.conv_general_dilated (XLA tiles them onto the
MXU); embedding grad becomes a fused scatter-add via the generic vjp path —
the TPU-native replacement for the reference's SelectedRows sparse grad
(framework/selected_rows.h:32). dropout registers an explicit grad op that
reuses the saved Mask so backward sees the same randomness as forward.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..fluid.dtypes import convert_dtype
from .registry import register, set_grad_maker


# ---------------------------------------------------------------------------
# convolution
# ---------------------------------------------------------------------------


def _conv_padding(paddings, algo, ndim_spatial):
    if algo == "SAME":
        return "SAME"
    if algo == "VALID":
        return "VALID"
    p = list(paddings)
    if len(p) == ndim_spatial:
        return [(pi, pi) for pi in p]
    if len(p) == 2 * ndim_spatial:
        return [(p[2 * i], p[2 * i + 1]) for i in range(ndim_spatial)]
    raise ValueError(f"bad paddings {paddings}")


def _conv2d_impl(x, w, attrs):
    strides = tuple(attrs.get("strides", [1, 1]))
    dil = tuple(attrs.get("dilations", [1, 1]))
    groups = int(attrs.get("groups", 1))
    algo = attrs.get("padding_algorithm", "EXPLICIT")
    pad = _conv_padding(attrs.get("paddings", [0, 0]), algo, 2)
    df = attrs.get("data_format", "NCHW")
    if df in ("NCHW", "AnyLayout"):
        dn = ("NCHW", "OIHW", "NCHW")
    else:
        # weights are ALWAYS stored OIHW in paddle programs — tell lax so
        # directly instead of transposing (shape-sniffing for HWIO
        # misfired whenever k == C_in/groups)
        dn = ("NHWC", "OIHW", "NHWC")
    return jax.lax.conv_general_dilated(
        x, w, window_strides=strides, padding=pad,
        rhs_dilation=dil, dimension_numbers=dn, feature_group_count=groups,
    )


def _conv2d_key(attrs):
    return (
        tuple(attrs.get("strides", [1, 1])),
        tuple(attrs.get("dilations", [1, 1])),
        int(attrs.get("groups", 1)),
        attrs.get("padding_algorithm", "EXPLICIT"),
        tuple(attrs.get("paddings", [0, 0])),
        attrs.get("data_format", "NCHW"),
    )


import functools as _ft  # noqa: E402 — local to the conv vjp cache


@_ft.lru_cache(maxsize=64)
def _conv2d_im2col_dw_fn(key):
    """conv2d with an im2col-matmul dW formulation (custom vjp).

    The reference answers dW-conv slowness with cudnn's exhaustive algo
    search (conv_cudnn_op.cu.cc); XLA has one dW lowering and no search
    knob. This path reformulates ONLY the weight gradient: extract the
    kernel-window patches of x (conv_general_dilated_patches) and
    contract them against dy in a single [C*kh*kw, NHoWo]x[NHoWo, O]
    einsum — the MXU sees one big matmul instead of XLA's dW-conv
    schedule. dX keeps the standard transposed-conv lowering (it was
    never the bottleneck). NHWC, groups=1. Costs kh*kw x activation
    traffic for the patches, so it wins only where the dW conv is far
    off roofline — gate via FLAGS_conv_dw_im2col and measure.
    """
    strides, dil, groups, algo, paddings, df = key
    attrs = {"strides": list(strides), "dilations": list(dil),
             "groups": groups, "padding_algorithm": algo,
             "paddings": list(paddings), "data_format": df}

    @jax.custom_vjp
    def conv(x, w):
        return _conv2d_impl(x, w, attrs)

    def fwd(x, w):
        return conv(x, w), (x, w)

    def bwd(res, dy):
        x, w = res
        # dX: XLA's transposed-conv lowering via the standard vjp
        _, vjp_x = jax.vjp(lambda x_: _conv2d_impl(x_, w, attrs), x)
        (dx,) = vjp_x(dy)
        # dW: im2col patches -> one matmul
        o, cg, kh, kw = w.shape
        pad = _conv_padding(paddings, algo, 2)
        patches = jax.lax.conv_general_dilated_patches(
            x, filter_shape=(kh, kw), window_strides=strides,
            padding=pad if isinstance(pad, str) else tuple(pad),
            rhs_dilation=dil,
            dimension_numbers=("NHWC", "OIHW", "NHWC"),
        )  # [N, Ho, Wo, C*kh*kw], feature index = c*kh*kw + ki*kw + kj
        dw_flat = jnp.einsum(
            "nhwp,nhwo->op", patches, dy,
            preferred_element_type=jnp.float32,
        )
        dw = dw_flat.reshape(o, cg, kh, kw).astype(w.dtype)
        return dx, dw

    conv.defvjp(fwd, bwd)
    return conv


def _use_im2col_dw(attrs, w_shape):
    from ..fluid import flags as _flags

    if not _flags.get_flags(
            ["FLAGS_conv_dw_im2col"])["FLAGS_conv_dw_im2col"]:
        return False
    df = attrs.get("data_format", "NCHW")
    groups = int(attrs.get("groups", 1))
    kh, kw = int(w_shape[2]), int(w_shape[3])
    # NHWC only (the patches layout above), grouped convs excluded, and
    # 1x1 kernels gain nothing (dW already IS one matmul there)
    return df == "NHWC" and groups == 1 and (kh, kw) != (1, 1)


@register("conv2d")
def conv2d(ctx, ins, attrs):
    x, w = ins["Input"][0], ins["Filter"][0]
    if _use_im2col_dw(attrs, w.shape):
        fn = _conv2d_im2col_dw_fn(_conv2d_key(attrs))
        return {"Output": [fn(x, w)]}
    return {"Output": [_conv2d_impl(x, w, attrs)]}


@register("depthwise_conv2d")
def depthwise_conv2d(ctx, ins, attrs):
    x, w = ins["Input"][0], ins["Filter"][0]
    a = dict(attrs)
    a["groups"] = x.shape[1] if a.get("data_format", "NCHW") == "NCHW" else x.shape[-1]
    return {"Output": [_conv2d_impl(x, w, a)]}


@register("conv2d_transpose")
def conv2d_transpose(ctx, ins, attrs):
    x, w = ins["Input"][0], ins["Filter"][0]
    strides = tuple(attrs.get("strides", [1, 1]))
    dil = tuple(attrs.get("dilations", [1, 1]))
    groups = int(attrs.get("groups", 1))
    pad = _conv_padding(attrs.get("paddings", [0, 0]),
                        attrs.get("padding_algorithm", "EXPLICIT"), 2)
    # emulate gradient-of-conv semantics: lhs dilation
    if isinstance(pad, str):
        padding = pad
    else:
        kh = (w.shape[2] - 1) * dil[0] + 1
        kw = (w.shape[3] - 1) * dil[1] + 1
        padding = [
            (kh - 1 - pad[0][0], kh - 1 - pad[0][1]),
            (kw - 1 - pad[1][0], kw - 1 - pad[1][1]),
        ]
    w = jnp.flip(w, axis=(2, 3))  # (Cin, Cout/g, kh, kw)
    w = jnp.swapaxes(w, 0, 1) if groups == 1 else w.reshape(
        (groups, w.shape[0] // groups) + w.shape[1:]
    ).swapaxes(1, 2).reshape((w.shape[1] * groups, w.shape[0] // groups) + w.shape[2:])
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding=padding,
        lhs_dilation=strides, rhs_dilation=dil,
        dimension_numbers=("NCHW", "OIHW", "NCHW"), feature_group_count=groups,
    )
    if attrs.get("output_padding"):
        op_ = attrs["output_padding"]
        if any(op_):
            out = jnp.pad(out, [(0, 0), (0, 0), (0, op_[0]), (0, op_[1])])
    return {"Output": [out]}


@register("conv3d")
def conv3d(ctx, ins, attrs):
    x, w = ins["Input"][0], ins["Filter"][0]
    strides = tuple(attrs.get("strides", [1, 1, 1]))
    dil = tuple(attrs.get("dilations", [1, 1, 1]))
    groups = int(attrs.get("groups", 1))
    pad = _conv_padding(attrs.get("paddings", [0, 0, 0]),
                        attrs.get("padding_algorithm", "EXPLICIT"), 3)
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=strides, padding=pad, rhs_dilation=dil,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"), feature_group_count=groups,
    )
    return {"Output": [out]}


# ---------------------------------------------------------------------------
# pooling
# ---------------------------------------------------------------------------


def adaptive_pool_nd(x, out_sizes, red):
    """Adaptive pooling for NON-divisible output sizes (reference
    pool_op.h AdaptStartIndex/AdaptEndIndex): spatial bin i of dimension
    `in_size -> out` spans [floor(i*in/out), ceil((i+1)*in/out)). The
    bin extents are static Python ints, so each bin is a static slice
    reduced and stacked — fixed shapes, XLA-fusable, no gathers."""
    spatial = x.shape[2:]
    assert len(spatial) == len(out_sizes)

    def pool_axis(arr, axis, in_size, out):
        bins = [
            (int(np.floor(i * in_size / out)),
             int(np.ceil((i + 1) * in_size / out)))
            for i in range(out)
        ]
        parts = [
            red(jax.lax.slice_in_dim(arr, s, e, axis=axis), axis=axis,
                keepdims=True)
            for s, e in bins
        ]
        return jnp.concatenate(parts, axis=axis)

    out = x
    for d, (in_size, o) in enumerate(zip(spatial, out_sizes)):
        out = pool_axis(out, 2 + d, in_size, o)
    return out


@register("pool2d")
def pool2d(ctx, ins, attrs):
    x = ins["X"][0]
    ptype = attrs.get("pooling_type", "max")
    ksize = list(attrs.get("ksize", [1, 1]))
    strides = list(attrs.get("strides", ksize))
    paddings = list(attrs.get("paddings", [0, 0]))
    gp = attrs.get("global_pooling", False)
    adaptive = attrs.get("adaptive", False)
    exclusive = attrs.get("exclusive", True)
    algo = attrs.get("padding_algorithm", "EXPLICIT")
    df = attrs.get("data_format", "NCHW")
    hax, wax = (2, 3) if df == "NCHW" else (1, 2)
    H, W = x.shape[hax], x.shape[wax]

    if gp or (adaptive and ksize == [1, 1]):
        red = jnp.max if ptype == "max" else jnp.mean
        return {"Out": [red(x, axis=(hax, wax), keepdims=True)]}
    if adaptive:
        oh, ow = ksize
        red = jnp.max if ptype == "max" else jnp.mean
        if H % oh == 0 and W % ow == 0:
            if df == "NCHW":
                xr = x.reshape(x.shape[0], x.shape[1], oh, H // oh, ow, W // ow)
                return {"Out": [red(xr, axis=(3, 5))]}
            xr = x.reshape(x.shape[0], oh, H // oh, ow, W // ow, x.shape[3])
            return {"Out": [red(xr, axis=(2, 4))]}
        if df != "NCHW":
            raise NotImplementedError(
                "adaptive pool with non-divisible bins supports NCHW only")
        return {"Out": [adaptive_pool_nd(x, (oh, ow), red)]}

    if algo == "SAME":
        pad = "SAME"
    elif algo == "VALID":
        pad = [(0, 0), (0, 0)]
    else:
        if len(paddings) == 2:
            pad = [(paddings[0], paddings[0]), (paddings[1], paddings[1])]
        else:
            pad = [(paddings[0], paddings[1]), (paddings[2], paddings[3])]
    if attrs.get("ceil_mode", False) and pad != "SAME":
        # extend right/bottom padding so the window count rounds up
        def extra(dim, k, s, p):
            import math

            out = math.ceil((dim + p[0] + p[1] - k) / s) + 1
            need = (out - 1) * s + k - dim - p[0]
            return max(need - p[1], 0)

        pad = [
            (pad[0][0], pad[0][1] + extra(H, ksize[0], strides[0], pad[0])),
            (pad[1][0], pad[1][1] + extra(W, ksize[1], strides[1], pad[1])),
        ]
    if df == "NCHW":
        window = (1, 1, ksize[0], ksize[1])
        strid = (1, 1, strides[0], strides[1])
        full_pad = "SAME" if pad == "SAME" else [(0, 0), (0, 0)] + pad
    else:
        window = (1, ksize[0], ksize[1], 1)
        strid = (1, strides[0], strides[1], 1)
        full_pad = "SAME" if pad == "SAME" else [(0, 0)] + pad + [(0, 0)]
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        out = jax.lax.reduce_window(x, init, jax.lax.max, window, strid, full_pad)
    else:
        s = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strid, full_pad)
        if exclusive:
            ones = jnp.ones_like(x)
            cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strid, full_pad)
            out = s / cnt
        else:
            out = s / (ksize[0] * ksize[1])
    return {"Out": [out]}


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------


@register("batch_norm")
def batch_norm(ctx, ins, attrs):
    x = ins["X"][0]
    scale, bias = ins["Scale"][0], ins["Bias"][0]
    mean, var = ins["Mean"][0], ins["Variance"][0]
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    is_test = attrs.get("is_test", False)
    use_global = attrs.get("use_global_stats", False) or is_test
    layout = attrs.get("data_layout", "NCHW")
    ch_axis = 1 if layout == "NCHW" else x.ndim - 1
    axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    bshape = tuple(x.shape[ch_axis] if i == ch_axis else 1 for i in range(x.ndim))

    # statistics ALWAYS in f32 (the layer_norm convention): the op sits
    # on AMP's low-precision list, so bf16 in/out halves the activation
    # bandwidth of the conv stack while the mean/variance math stays
    # exact. (Blacklisting BN instead made AMP materialize f32 copies of
    # every bf16 activation — profiled as the dominant ResNet-50 cost.)
    xf = x.astype(jnp.float32)
    if use_global:
        m, v = mean, var
        mean_out, var_out = mean, var
        saved_mean = jnp.zeros_like(mean)
        saved_var = jnp.zeros_like(var)
    else:
        # one-pass moments: E[x] and E[x^2] reduce in a single fusion
        # over one read of the activation (jnp.var's subtract-then-square
        # form costs a second full read); f32 accumulation keeps the
        # cancellation benign at BN's normalized ranges (cuDNN does the
        # same)
        m = jnp.mean(xf, axis=axes)
        v = jnp.maximum(jnp.mean(xf * xf, axis=axes) - m * m, 0.0)
        mean_out = momentum * mean + (1 - momentum) * m
        var_out = momentum * var + (1 - momentum) * v
        saved_mean = m
        saved_var = 1.0 / jnp.sqrt(v + eps)
    inv = 1.0 / jnp.sqrt(v + eps)
    y = (
        (xf - m.reshape(bshape)) * inv.reshape(bshape)
        * scale.astype(jnp.float32).reshape(bshape)
        + bias.astype(jnp.float32).reshape(bshape)
    ).astype(x.dtype)
    return {
        "Y": [y],
        "MeanOut": [mean_out],
        "VarianceOut": [var_out],
        "SavedMean": [saved_mean],
        "SavedVariance": [saved_var],
    }


@register("fused_conv_bn")
def fused_conv_bn(ctx, ins, attrs):
    """conv2d -> batch_norm [-> relu] as ONE op (fluid/fusion_pass.py).

    Training mode routes through the Pallas mega-kernels
    (ops/pallas/conv_bn.py) — conv tiles + batch statistics in one pass,
    normalize+relu in a second, with a custom VJP fusing the relu/BN
    backward chain — falling back to the identical-math jnp composition
    for shapes the kernel doesn't cover. Inference (is_test /
    use_global_stats) folds the BN into the conv weights instead: one
    conv + one bias add, no normalization pass at all.

    Output contract matches batch_norm's (Y + the four stat outputs) so
    the fusion pass can rewire the BN's consumers verbatim.
    """
    from .pallas import conv_bn as _cb

    x, w = ins["Input"][0], ins["Filter"][0]
    scale, bias = ins["Scale"][0], ins["Bias"][0]
    mean, var = ins["Mean"][0], ins["Variance"][0]
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    with_relu = bool(attrs.get("with_relu", False))
    strides = tuple(attrs.get("strides", [1, 1]))
    algo = attrs.get("padding_algorithm", "EXPLICIT")
    pads = _conv_padding(attrs.get("paddings", [0, 0]), algo, 2)
    is_test = attrs.get("is_test", False)
    use_global = attrs.get("use_global_stats", False) or is_test
    nhwc = attrs.get("data_format", "NCHW") == "NHWC"

    if use_global:
        # weight folding: y = conv(x, w*(s*inv)) + (b - m*s*inv)
        sf = scale.astype(jnp.float32)
        inv = 1.0 / jnp.sqrt(var.astype(jnp.float32) + eps)
        gain = sf * inv
        wf = (w.astype(jnp.float32) * gain.reshape(-1, 1, 1, 1)).astype(w.dtype)
        shift = bias.astype(jnp.float32) - mean.astype(jnp.float32) * gain
        z = _conv2d_impl(x, wf, attrs)
        bshape = (1, 1, 1, -1) if nhwc else (1, -1, 1, 1)
        y = z.astype(jnp.float32) + shift.reshape(bshape)
        if with_relu:
            y = jnp.maximum(y, 0.0)
        return {
            "Y": [y.astype(x.dtype)],
            "MeanOut": [mean],
            "VarianceOut": [var],
            "SavedMean": [jnp.zeros_like(mean)],
            "SavedVariance": [jnp.zeros_like(var)],
        }

    if nhwc:
        y, m, v = _cb.fused_conv_bn(
            x, w, scale, bias, strides=strides, pads=pads, eps=eps,
            with_relu=with_relu,
        )
    else:
        # NCHW never reaches the Pallas path; compose via channel-last
        xt = jnp.transpose(x, (0, 2, 3, 1))
        pads_r = _cb._resolve_pads(pads, xt.shape[1], xt.shape[2],
                                   int(w.shape[2]), int(w.shape[3]), strides)
        y, m, v = _cb.conv_bn_reference(
            xt, w, scale, bias, strides=strides, pads=pads_r, eps=eps,
            with_relu=with_relu,
        )
        y = jnp.transpose(y, (0, 3, 1, 2))
    mean_out = momentum * mean + (1 - momentum) * m.astype(mean.dtype)
    var_out = momentum * var + (1 - momentum) * v.astype(var.dtype)
    return {
        "Y": [y],
        "MeanOut": [mean_out],
        "VarianceOut": [var_out],
        "SavedMean": [m.astype(mean.dtype)],
        "SavedVariance": [(1.0 / jnp.sqrt(v + eps)).astype(var.dtype)],
    }


@register("layer_norm")
def layer_norm(ctx, ins, attrs):
    # statistics ALWAYS in f32 (the fused-stack ln() convention): the op
    # can then sit on AMP's low-precision list — bf16 in/out keeps the
    # residual stream at half bandwidth while the mean/variance math
    # stays exact
    x = ins["X"][0]
    eps = attrs.get("epsilon", 1e-5)
    axis = attrs.get("begin_norm_axis", 1)
    lead = tuple(x.shape[:axis])
    if axis == x.ndim - 1 and ins.get("Scale") and ins.get("Bias"):
        # last-axis affine LN rides the fused Pallas kernel (one row
        # pass with f32 stats in VMEM, custom VJP) where the gate
        # passes — the wiring FLAGS_use_fused_ln always documented.
        # Mean/Variance keep the op contract via plain reductions that
        # XLA dead-code-eliminates when (as in real programs) unused.
        from .pallas.add_ln import fused_add_ln, fused_ln_dispatch_ok

        if fused_ln_dispatch_ok(x.shape):
            y = fused_add_ln(x, None, ins["Scale"][0], ins["Bias"][0],
                             eps=eps)
            xf = x.astype(jnp.float32)
            m = jnp.mean(xf, axis=-1, keepdims=True)
            v = jnp.var(xf, axis=-1, keepdims=True)
            return {
                "Y": [y],
                "Mean": [m.reshape(lead)],
                "Variance": [v.reshape(lead)],
            }
    xf = x.astype(jnp.float32)
    m = jnp.mean(xf, axis=tuple(range(axis, x.ndim)), keepdims=True)
    v = jnp.var(xf, axis=tuple(range(axis, x.ndim)), keepdims=True)
    y = (xf - m) * jax.lax.rsqrt(v + eps)
    tail_shape = (1,) * axis + tuple(x.shape[axis:])
    if ins.get("Scale"):
        y = y * ins["Scale"][0].astype(jnp.float32).reshape(tail_shape)
    if ins.get("Bias"):
        y = y + ins["Bias"][0].astype(jnp.float32).reshape(tail_shape)
    return {
        "Y": [y.astype(x.dtype)],
        "Mean": [m.reshape(lead)],
        "Variance": [v.reshape(lead)],
    }


@register("group_norm")
def group_norm(ctx, ins, attrs):
    x = ins["X"][0]  # NCHW
    groups = attrs["groups"]
    eps = attrs.get("epsilon", 1e-5)
    n, c = x.shape[0], x.shape[1]
    rest = x.shape[2:]
    xg = x.reshape((n, groups, c // groups) + rest)
    axes = tuple(range(2, xg.ndim))
    m = jnp.mean(xg, axis=axes, keepdims=True)
    v = jnp.var(xg, axis=axes, keepdims=True)
    y = ((xg - m) / jnp.sqrt(v + eps)).reshape(x.shape)
    bshape = (1, c) + (1,) * len(rest)
    if ins.get("Scale"):
        y = y * ins["Scale"][0].reshape(bshape)
    if ins.get("Bias"):
        y = y + ins["Bias"][0].reshape(bshape)
    return {
        "Y": [y],
        "Mean": [m.reshape(n, groups)],
        "Variance": [v.reshape(n, groups)],
    }


@register("instance_norm")
def instance_norm(ctx, ins, attrs):
    x = ins["X"][0]  # NCHW
    eps = attrs.get("epsilon", 1e-5)
    axes = tuple(range(2, x.ndim))
    m = jnp.mean(x, axis=axes, keepdims=True)
    v = jnp.var(x, axis=axes, keepdims=True)
    y = (x - m) / jnp.sqrt(v + eps)
    c = x.shape[1]
    bshape = (1, c) + (1,) * (x.ndim - 2)
    if ins.get("Scale"):
        y = y * ins["Scale"][0].reshape(bshape)
    if ins.get("Bias"):
        y = y + ins["Bias"][0].reshape(bshape)
    n = x.shape[0]
    return {
        "Y": [y],
        "SavedMean": [m.reshape(n * c)],
        "SavedVariance": [(1.0 / jnp.sqrt(v + eps)).reshape(n * c)],
    }


@register("norm")
def norm(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    eps = attrs.get("epsilon", 1e-10)
    nrm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    return {"Out": [x / nrm], "Norm": [nrm]}


# ---------------------------------------------------------------------------
# dropout (explicit grad op reusing the saved mask)
# ---------------------------------------------------------------------------


@register("dropout", no_vjp_grad=True)
def dropout(ctx, ins, attrs):
    x = ins["X"][0]
    p = float(attrs.get("dropout_prob", 0.5))
    is_test = attrs.get("is_test", False)
    impl = attrs.get("dropout_implementation", "downgrade_in_infer")
    if is_test:
        out = x * (1.0 - p) if impl == "downgrade_in_infer" else x
        return {"Out": [out], "Mask": [jnp.ones_like(x, dtype=jnp.uint8)]}
    keep = jax.random.bernoulli(ctx.rng(), 1.0 - p, x.shape)
    mask = keep.astype(jnp.uint8)
    if impl == "upscale_in_train":
        out = jnp.where(keep, x / max(1.0 - p, 1e-12), 0.0).astype(x.dtype)
    else:
        out = jnp.where(keep, x, 0.0).astype(x.dtype)
    return {"Out": [out], "Mask": [mask]}


@register("dropout_grad", no_vjp_grad=True)
def dropout_grad(ctx, ins, attrs):
    dout = ins["Out@GRAD"][0]
    mask = ins["Mask"][0]
    p = float(attrs.get("dropout_prob", 0.5))
    impl = attrs.get("dropout_implementation", "downgrade_in_infer")
    if attrs.get("is_test", False):
        # forward was out = x*(1-p) (downgrade) or out = x (upscale)
        dx = dout * (1.0 - p) if impl == "downgrade_in_infer" else dout
        return {"X@GRAD": [dx]}
    dx = dout * mask.astype(dout.dtype)
    if impl == "upscale_in_train":
        dx = dx / max(1.0 - p, 1e-12)
    return {"X@GRAD": [dx]}


def _dropout_grad_maker(op, out_grads, block):
    og = out_grads.get("Out")
    if og is None:
        return [], {}
    xname = op.input("X")[0]
    gname = xname + "@GRAD"
    desc = {
        "type": "dropout_grad",
        "inputs": {"Mask": [op.output("Mask")[0]], "Out@GRAD": [og[0]]},
        "outputs": {"X@GRAD": [gname]},
        "attrs": {k: v for k, v in op.attrs.items()},
    }
    return [desc], {xname: gname}


set_grad_maker("dropout", _dropout_grad_maker)


# ---------------------------------------------------------------------------
# embedding / one-hot
# ---------------------------------------------------------------------------


def _lookup(w, ids, padding_idx):
    out = jnp.take(w, ids, axis=0)
    if padding_idx is not None and padding_idx >= 0:
        padmask = (ids == padding_idx)[..., None]
        out = jnp.where(padmask, 0.0, out)
    return out


@register("lookup_table")
def lookup_table(ctx, ins, attrs):
    # v1 ids carry a trailing [,1] dim (LoD heritage); result keeps it dense
    w, ids = ins["W"][0], ins["Ids"][0]
    ids2 = ids.reshape(ids.shape[:-1])
    out = _lookup(w, ids2, attrs.get("padding_idx", -1))
    return {"Out": [out]}


@register("lookup_table_v2")
def lookup_table_v2(ctx, ins, attrs):
    w, ids = ins["W"][0], ins["Ids"][0]
    return {"Out": [_lookup(w, ids, attrs.get("padding_idx", -1))]}


@register("one_hot_v2", stop_gradient=True, no_vjp_grad=True)
def one_hot_v2(ctx, ins, attrs):
    x = ins["X"][0]
    depth = attrs["depth"]
    return {"Out": [jax.nn.one_hot(x, depth, dtype=jnp.float32)]}


@register("one_hot", stop_gradient=True, no_vjp_grad=True)
def one_hot(ctx, ins, attrs):
    x = ins["X"][0]
    x = x.reshape(x.shape[:-1])  # trailing 1 dim
    depth = attrs["depth"]
    return {"Out": [jax.nn.one_hot(x, depth, dtype=jnp.float32)]}


@register("embedding_with_scaled_gradient")
def embedding_with_scaled_gradient(ctx, ins, attrs):
    w, ids = ins["W"][0], ins["Ids"][0]
    return {"Out": [_lookup(w, ids, attrs.get("padding_idx", -1))]}


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------


@register("softmax_with_cross_entropy")
def softmax_with_cross_entropy(ctx, ins, attrs):
    logits, label = ins["Logits"][0], ins["Label"][0]
    axis = attrs.get("axis", -1) % logits.ndim
    soft_label = attrs.get("soft_label", False)
    ignore_index = attrs.get("ignore_index", -100)
    lse = jax.scipy.special.logsumexp(logits, axis=axis, keepdims=True)
    logp = logits - lse
    softmax = jnp.exp(logp)
    if soft_label:
        loss = -jnp.sum(label * logp, axis=axis, keepdims=True)
    else:
        # hard labels: label has shape of logits with the class axis = 1
        lbl = label
        if lbl.ndim == logits.ndim and lbl.shape[axis] == 1:
            idx = lbl.astype(jnp.int32)
        else:
            idx = jnp.expand_dims(lbl.astype(jnp.int32), axis)
        n_cls = logp.shape[axis]
        safe_idx = jnp.clip(idx, 0, n_cls - 1)
        picked = jnp.take_along_axis(logp, safe_idx, axis=axis)
        # kIgnoreIndex (-100) is itself a valid ignore value — mask always
        loss = jnp.where(idx == ignore_index, 0.0, -picked)
    return {"Softmax": [softmax], "Loss": [loss]}


@register("cross_entropy")
def cross_entropy(ctx, ins, attrs):
    x, label = ins["X"][0], ins["Label"][0]
    soft_label = attrs.get("soft_label", False)
    ignore_index = attrs.get("ignore_index", -100)
    eps = 1e-12
    if soft_label:
        loss = -jnp.sum(label * jnp.log(jnp.maximum(x, eps)), axis=-1, keepdims=True)
    else:
        lbl = label
        if lbl.ndim == x.ndim and lbl.shape[-1] == 1:
            lbl = jnp.squeeze(lbl, axis=-1)
        p = jnp.take_along_axis(x, lbl[..., None].astype(jnp.int32), axis=-1)
        loss = -jnp.log(jnp.maximum(p, eps))
        loss = jnp.where(lbl[..., None] == ignore_index, 0.0, loss)
    return {"Y": [loss]}


@register("cross_entropy2")
def cross_entropy2(ctx, ins, attrs):
    out = cross_entropy(ctx, ins, attrs)
    x = ins["X"][0]
    from .manipulation import _xshape

    return {
        "Y": out["Y"],
        "XShape": [_xshape(x)],
        "MatchX": [jnp.exp(-out["Y"][0])],
    }


@register("sigmoid_cross_entropy_with_logits")
def sigmoid_cross_entropy_with_logits(ctx, ins, attrs):
    x, label = ins["X"][0], ins["Label"][0]
    ignore_index = attrs.get("ignore_index", -100)
    loss = jnp.maximum(x, 0.0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    mask = label != ignore_index
    loss = jnp.where(mask, loss, 0.0)
    if attrs.get("normalize", False):
        loss = loss / jnp.maximum(jnp.sum(mask.astype(loss.dtype)), 1.0)
    return {"Out": [loss]}


@register("bce_loss")
def bce_loss(ctx, ins, attrs):
    x, label = ins["X"][0], ins["Label"][0]
    eps = 1e-12
    loss = -(label * jnp.log(jnp.maximum(x, eps)) + (1 - label) * jnp.log(jnp.maximum(1 - x, eps)))
    return {"Out": [loss]}


@register("square_error_cost")
def square_error_cost(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    return {"Out": [jnp.square(x - y)]}


@register("smooth_l1_loss")
def smooth_l1_loss(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    sigma = attrs.get("sigma", 1.0)
    s2 = sigma * sigma
    diff = x - y
    if ins.get("InsideWeight"):
        diff = diff * ins["InsideWeight"][0]
    ad = jnp.abs(diff)
    loss = jnp.where(ad < 1.0 / s2, 0.5 * s2 * diff * diff, ad - 0.5 / s2)
    if ins.get("OutsideWeight"):
        loss = loss * ins["OutsideWeight"][0]
    out = jnp.sum(loss.reshape(loss.shape[0], -1), axis=1, keepdims=True)
    return {"Out": [out], "Diff": [diff]}


@register("huber_loss")
def huber_loss(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    delta = attrs.get("delta", 1.0)
    r = y - x
    ar = jnp.abs(r)
    loss = jnp.where(ar <= delta, 0.5 * r * r, delta * (ar - 0.5 * delta))
    return {"Out": [loss], "Residual": [r]}


@register("log_loss")
def log_loss(ctx, ins, attrs):
    p, label = ins["Predicted"][0], ins["Labels"][0]
    eps = attrs.get("epsilon", 1e-4)
    loss = -label * jnp.log(p + eps) - (1 - label) * jnp.log(1 - p + eps)
    return {"Loss": [loss]}


@register("kldiv_loss")
def kldiv_loss(ctx, ins, attrs):
    x, tgt = ins["X"][0], ins["Target"][0]
    red = attrs.get("reduction", "mean")
    loss = jnp.where(tgt > 0, tgt * (jnp.log(tgt) - x), 0.0)
    if red == "mean":
        loss = jnp.mean(loss).reshape((1,))
    elif red == "sum":
        loss = jnp.sum(loss).reshape((1,))
    elif red == "batchmean":
        loss = (jnp.sum(loss) / x.shape[0]).reshape((1,))
    return {"Loss": [loss]}


@register("label_smooth")
def label_smooth(ctx, ins, attrs):
    x = ins["X"][0]
    eps = attrs.get("epsilon", 0.0)
    if ins.get("PriorDist"):
        prior = ins["PriorDist"][0]
        out = (1 - eps) * x + eps * prior
    else:
        out = (1 - eps) * x + eps / x.shape[-1]
    return {"Out": [out]}


@register("mse_loss")
def mse_loss(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    return {"Out": [jnp.mean(jnp.square(x - y)).reshape((1,))]}


@register("margin_rank_loss")
def margin_rank_loss(ctx, ins, attrs):
    x1, x2, label = ins["X1"][0], ins["X2"][0], ins["Label"][0]
    margin = attrs.get("margin", 0.0)
    act = jnp.maximum(0.0, -label * (x1 - x2) + margin)
    return {"Out": [act], "Activated": [(act > 0).astype(x1.dtype)]}


# ---------------------------------------------------------------------------
# metrics (reference operators/metrics/)
# ---------------------------------------------------------------------------


@register("accuracy", stop_gradient=True, no_vjp_grad=True)
def accuracy(ctx, ins, attrs):
    idx = ins["Indices"][0]
    label = ins["Label"][0]
    correct = jnp.any(idx == label.reshape(-1, 1), axis=1)
    total = jnp.asarray(idx.shape[0], jnp.int32)
    num_correct = jnp.sum(correct).astype(jnp.int32)
    acc = num_correct.astype(jnp.float32) / jnp.maximum(total, 1)
    return {
        "Accuracy": [acc.reshape((1,))],
        "Correct": [num_correct.reshape((1,))],
        "Total": [total.reshape((1,))],
    }


@register("auc", stop_gradient=True, no_vjp_grad=True)
def auc(ctx, ins, attrs):
    """Streaming ROC-AUC (reference operators/metrics/auc_op.cc): bucket
    positive-class scores into num_thresholds bins, accumulate pos/neg
    counts into the stat buffers, integrate by trapezoid."""
    pred = ins["Predict"][0]
    label = ins["Label"][0].reshape(-1)
    stat_pos = ins["StatPos"][0].reshape(-1)
    stat_neg = ins["StatNeg"][0].reshape(-1)
    num_t = int(attrs.get("num_thresholds", 4095))
    score = pred[:, -1] if pred.ndim == 2 else pred.reshape(-1)
    idx = jnp.clip((score * num_t).astype(jnp.int32), 0, num_t)
    is_pos = (label > 0).astype(stat_pos.dtype)
    stat_pos = stat_pos.at[idx].add(is_pos)
    stat_neg = stat_neg.at[idx].add(1 - is_pos)
    # integrate high->low threshold
    pos_rev = jnp.cumsum(stat_pos[::-1])
    neg_rev = jnp.cumsum(stat_neg[::-1])
    tot_pos = pos_rev[-1]
    tot_neg = neg_rev[-1]
    if str(attrs.get("curve", "ROC")) == "PR":
        # precision/recall points from the same buckets: TP = cum pos
        # from the high-threshold end, FP = cum neg; start at the
        # conventional (recall 0, precision 1) anchor
        tp = pos_rev.astype(jnp.float32)
        fp = neg_rev.astype(jnp.float32)
        # vacuous precision (no predictions above threshold) counts as 1
        prec = jnp.where(tp + fp > 0, tp / jnp.maximum(tp + fp, 1.0), 1.0)
        rec = tp / jnp.maximum(tot_pos.astype(jnp.float32), 1.0)
        p_pts = jnp.concatenate([jnp.ones(1, jnp.float32), prec])
        r_pts = jnp.concatenate([jnp.zeros(1, jnp.float32), rec])
        area = jnp.sum(
            (r_pts[1:] - r_pts[:-1]) * (p_pts[1:] + p_pts[:-1]) / 2.0
        )
        out = jnp.where(tot_pos > 0, area, 0.0)
        return {
            "AUC": [out.reshape(1)],
            "StatPosOut": [stat_pos.reshape(ins["StatPos"][0].shape)],
            "StatNegOut": [stat_neg.reshape(ins["StatNeg"][0].shape)],
        }
    x = jnp.concatenate([jnp.zeros(1, neg_rev.dtype), neg_rev])
    y = jnp.concatenate([jnp.zeros(1, pos_rev.dtype), pos_rev])
    area = jnp.sum(
        (x[1:] - x[:-1]).astype(jnp.float32) * (y[1:] + y[:-1]).astype(jnp.float32)
    ) / 2.0
    denom = jnp.maximum(tot_pos * tot_neg, 1).astype(jnp.float32)
    out = jnp.where(tot_pos * tot_neg > 0, area / denom, 0.0)
    return {
        "AUC": [out.reshape(1)],
        "StatPosOut": [stat_pos.reshape(ins["StatPos"][0].shape)],
        "StatNegOut": [stat_neg.reshape(ins["StatNeg"][0].shape)],
    }
