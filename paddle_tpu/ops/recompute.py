"""Recompute (activation checkpointing) as a fused segment op.

Parity surface: the reference's RecomputeOptimizer
(/root/reference/python/paddle/fluid/optimizer.py:4478) and the
checkpoint-aware backward builder
(/root/reference/python/paddle/fluid/backward.py:629), which re-append
forward op descs into the backward region so activations between
checkpoints are recomputed instead of stored.

TPU-native design: re-appending forward ops would be a no-op here —
the whole block is one XLA program and XLA's CSE would fold the
duplicated pure subgraph straight back into the primal one. Instead,
each checkpoint segment is collapsed into ONE `recompute_segment` op
whose emitter replays the segment's sub-ops under `jax.checkpoint`
(remat). The generic vjp-based grad op then differentiates through the
checkpointed function, so XLA receives real remat regions guarded by
optimization barriers: only the segment inputs (the checkpoints) are
kept live across forward→backward, and the segment body is recomputed
in the backward pass.
"""
from __future__ import annotations

from .registry import EmitContext, emit_ops, register


def _infer_recompute(in_metas, attrs):
    # outputs keep the metadata recorded at fusion time; segment sub-op
    # tracing under eval_shape would re-run the whole body per insert.
    return {"Out": [tuple(m) for m in attrs["recompute_out_metas"]]}


@register("recompute_segment", infer_shape=_infer_recompute)
def recompute_segment(ctx: EmitContext, ins, attrs):
    import jax

    sub_ops = attrs["recompute_sub_ops"]
    in_names = attrs["recompute_in_names"]
    out_names = attrs["recompute_out_names"]
    salt = int(attrs.get("recompute_seg_salt", 0))

    def body(*in_vals):
        # Deterministic per-segment rng: both the primal emit and the grad
        # op's re-trace (jax.vjp over this emitter) fold the same salt into
        # the frozen per-step base key, so ops with internal randomness
        # (dropout) draw identical masks in both traces.
        sub_ctx = EmitContext(
            rng_key=ctx.salted_rng(salt), mesh=ctx.mesh, axis_env=ctx.axis_env
        )
        env = dict(zip(in_names, in_vals))
        emit_ops(sub_ctx, sub_ops, env)
        return tuple(env[n] for n in out_names)

    outs = jax.checkpoint(body)(*ins["X"])
    return {"Out": list(outs)}
