"""Mixture-of-Experts FFN op with expert parallelism.

New TPU-era capability (the 2020 reference predates MoE): a fused
`moe_ffn` op — top-k router + capacity-bounded dispatch + per-expert FFN —
expressed entirely as dense einsums over a one-hot dispatch tensor
(Switch-Transformer / GShard formulation). That formulation is the
TPU-idiomatic one: every FLOP-carrying contraction is a large static-shape
einsum the MXU can tile, and when the expert dimension of W1/W2 is sharded
over an "ep" mesh axis (fleet.apply_expert_parallel) while tokens are
sharded over "dp", XLA's SPMD partitioner inserts the all-to-all pair
around the expert computation automatically — no hand-written dispatch
collective, mirroring how the rest of this framework gets its collectives
from GSPMD rather than a transpiler pass.

Exposed through the same surfaces as every other capability:
  fluid.layers.moe_ffn(...)            (layer DSL)
  DistributedStrategy.expert_parallel  (fleet strategy -> "ep" axis)

Semantics:
  X      [B, S, H]   tokens
  GateW  [H, E]      router weights
  W1     [E, H, F]   expert up-projection
  B1     [E, F]
  W2     [E, F, H]   expert down-projection
  B2     [E, H]
  ->
  Out     [B, S, H]  combined expert outputs (tokens over capacity get 0
                     from the expert path; callers keep the residual)
  AuxLoss []         Switch load-balancing loss, E * sum_e f_e * P_e
                     (1.0 when perfectly balanced)

Routing runs in float32 regardless of compute dtype (softmax/cumsum are
balance-critical); the expert einsums run in the input dtype so AMP
applies to the FLOP-heavy path only.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .registry import register


def moe_capacity(num_tokens: int, num_experts: int, top_k: int, capacity_factor: float) -> int:
    """Static per-expert capacity: ceil(top_k * T / E * factor)."""
    return max(1, int(math.ceil(top_k * num_tokens / num_experts * capacity_factor)))


def _activation(name: str):
    return {
        "gelu": jax.nn.gelu,
        "relu": jax.nn.relu,
        "silu": jax.nn.silu,
        "swish": jax.nn.silu,
        "tanh": jnp.tanh,
    }[name]


@register("moe_ffn")
def moe_ffn(ctx, ins, attrs):
    x = ins["X"][0]
    gate_w = ins["GateW"][0]
    w1, b1 = ins["W1"][0], ins["B1"][0]
    w2, b2 = ins["W2"][0], ins["B2"][0]

    top_k = int(attrs.get("top_k", 2))
    capacity_factor = float(attrs.get("capacity_factor", 1.25))
    act = _activation(str(attrs.get("activation", "gelu")))

    b, s, h = x.shape
    e = w1.shape[0]
    t = b * s
    cap = moe_capacity(t, e, top_k, capacity_factor)

    x2 = x.reshape(t, h)

    # ---- router (float32) ------------------------------------------------
    logits = jnp.einsum(
        "th,he->te", x2.astype(jnp.float32), gate_w.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    probs = jax.nn.softmax(logits, axis=-1)  # [T, E]

    # top-k selection, slot by slot; later slots see earlier picks masked
    remaining = probs
    slot_idx, slot_gate = [], []
    for _ in range(top_k):
        idx = jnp.argmax(remaining, axis=-1)  # [T]
        oh = jax.nn.one_hot(idx, e, dtype=jnp.float32)
        slot_idx.append(oh)
        slot_gate.append(jnp.sum(remaining * oh, axis=-1))  # [T]
        remaining = remaining * (1.0 - oh)
    # top-1 (Switch) keeps the RAW router prob as the gate — normalizing
    # would make it identically 1.0 and sever the task-loss gradient into
    # GateW; top-k>1 normalizes selected gates to sum to 1 (GShard combine),
    # which preserves the gradient through the relative weighting
    if top_k > 1:
        denom = sum(slot_gate)
        slot_gate = [g / jnp.maximum(denom, 1e-9) for g in slot_gate]

    # ---- capacity-bounded dispatch/combine tensors -----------------------
    # slot 0 claims positions first; slot 1 queues behind it (GShard order)
    counts = jnp.zeros((e,), jnp.float32)
    dispatch = jnp.zeros((t, e, cap), jnp.float32)
    combine = jnp.zeros((t, e, cap), jnp.float32)
    for oh, gate in zip(slot_idx, slot_gate):
        pos = jnp.cumsum(oh, axis=0) - oh + counts[None, :]  # [T, E]
        keep = oh * (pos < cap)  # [T, E]
        pos_oh = jax.nn.one_hot(jnp.sum(pos * oh, axis=-1).astype(jnp.int32),
                                cap, dtype=jnp.float32)  # [T, C]
        d = keep[:, :, None] * pos_oh[:, None, :]  # [T, E, C]
        dispatch = dispatch + d
        combine = combine + d * gate[:, None, None]
        counts = counts + jnp.sum(oh, axis=0)

    # ---- expert computation (input dtype: the AMP-able FLOPs) ------------
    disp = dispatch.astype(x.dtype)
    expert_in = jnp.einsum("tec,th->ech", disp, x2)  # [E, C, H]
    h1 = jnp.einsum("ech,ehf->ecf", expert_in, w1) + b1[:, None, :]
    h1 = act(h1)
    eout = jnp.einsum("ecf,efh->ech", h1, w2) + b2[:, None, :]
    out2 = jnp.einsum("tec,ech->th", combine.astype(x.dtype), eout)

    # ---- Switch load-balancing auxiliary loss ----------------------------
    # f_e: fraction of tokens whose FIRST choice is e; P_e: mean router prob
    frac = jnp.mean(slot_idx[0], axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(frac * mean_prob)

    return {"Out": [out2.reshape(b, s, h)], "AuxLoss": [aux.astype(jnp.float32)]}
