"""Optimizer update ops. Parity surface: reference operators/optimizers/
(sgd_op.cc, momentum_op.cc, adam_op.cc, adamax, adagrad, rmsprop_op.cc,
lamb_op.cc, lars_momentum_op.cc, ftrl_op.cc, ~5.5k LoC).

Like the reference, optimizer updates are ops in the program: the Executor
jits forward+backward+update as ONE XLA computation, so param updates fuse
with the last gradient ops and params stay device-resident (donated buffers)
— no host round-trip per step.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register


def _lr(ins):
    return ins["LearningRate"][0].reshape(())


@register("sgd", no_vjp_grad=True)
def sgd(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    return {"ParamOut": [p - _lr(ins) * g.astype(p.dtype)]}


@register("momentum", no_vjp_grad=True)
def momentum(ctx, ins, attrs):
    p, g, v = ins["Param"][0], ins["Grad"][0], ins["Velocity"][0]
    mu = attrs.get("mu", 0.9)
    lr = _lr(ins)
    rd = attrs.get("regularization_method", "")
    if rd == "l2_decay":
        g = g + attrs.get("regularization_coeff", 0.0) * p
    v_out = mu * v + g
    if attrs.get("use_nesterov", False):
        p_out = p - lr * (g + mu * v_out)
    else:
        p_out = p - lr * v_out
    return {"ParamOut": [p_out], "VelocityOut": [v_out]}


@register("adam", no_vjp_grad=True)
def adam(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    m1, m2 = ins["Moment1"][0], ins["Moment2"][0]
    b1p, b2p = ins["Beta1Pow"][0], ins["Beta2Pow"][0]
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    lr = _lr(ins)
    g = g.astype(m1.dtype)
    m1o = b1 * m1 + (1 - b1) * g
    m2o = b2 * m2 + (1 - b2) * g * g
    lr_t = lr * jnp.sqrt(1 - b2p.reshape(())) / (1 - b1p.reshape(()))
    p_out = p - lr_t * (m1o / (jnp.sqrt(m2o) + eps)).astype(p.dtype)
    return {
        "ParamOut": [p_out.astype(p.dtype)],
        "Moment1Out": [m1o],
        "Moment2Out": [m2o],
        "Beta1PowOut": [b1p * b1],
        "Beta2PowOut": [b2p * b2],
    }


@register("adamw", no_vjp_grad=True)
def adamw(ctx, ins, attrs):
    coeff = attrs.get("coeff", 0.01)
    lr = _lr(ins)
    p = ins["Param"][0]
    out = adam(ctx, ins, attrs)
    # decoupled weight decay (AdamW): decay applied on top of adam step
    if attrs.get("with_decay", True):
        out["ParamOut"] = [out["ParamOut"][0] - lr * coeff * p]
    return out


@register("adamax", no_vjp_grad=True)
def adamax(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    m, inf = ins["Moment"][0], ins["InfNorm"][0]
    b1p = ins["Beta1Pow"][0]
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    lr = _lr(ins)
    mo = b1 * m + (1 - b1) * g
    info = jnp.maximum(b2 * inf, jnp.abs(g))
    p_out = p - (lr / (1 - b1p.reshape(()))) * (mo / (info + eps))
    return {"ParamOut": [p_out], "MomentOut": [mo], "InfNormOut": [info]}


@register("adagrad", no_vjp_grad=True)
def adagrad(ctx, ins, attrs):
    p, g, m = ins["Param"][0], ins["Grad"][0], ins["Moment"][0]
    eps = attrs.get("epsilon", 1e-6)
    mo = m + g * g
    p_out = p - _lr(ins) * g / (jnp.sqrt(mo) + eps)
    return {"ParamOut": [p_out], "MomentOut": [mo]}


@register("decayed_adagrad", no_vjp_grad=True)
def decayed_adagrad(ctx, ins, attrs):
    p, g, m = ins["Param"][0], ins["Grad"][0], ins["Moment"][0]
    decay = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    mo = decay * m + (1 - decay) * g * g
    p_out = p - _lr(ins) * g / (jnp.sqrt(mo) + eps)
    return {"ParamOut": [p_out], "MomentOut": [mo]}


@register("rmsprop", no_vjp_grad=True)
def rmsprop(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    ms, mom = ins["MeanSquare"][0], ins["Moment"][0]
    rho = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    mu = attrs.get("momentum", 0.0)
    lr = _lr(ins)
    centered = attrs.get("centered", False)
    ms_out = rho * ms + (1 - rho) * g * g
    if centered:
        mg = ins["MeanGrad"][0]
        mg_out = rho * mg + (1 - rho) * g
        denom = ms_out - mg_out * mg_out + eps
    else:
        mg_out = None
        denom = ms_out + eps
    mom_out = mu * mom + lr * g / jnp.sqrt(denom)
    p_out = p - mom_out
    out = {"ParamOut": [p_out], "MomentOut": [mom_out], "MeanSquareOut": [ms_out]}
    if centered:
        out["MeanGradOut"] = [mg_out]
    return out


@register("lamb", no_vjp_grad=True)
def lamb(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    m1, m2 = ins["Moment1"][0], ins["Moment2"][0]
    b1p, b2p = ins["Beta1Pow"][0], ins["Beta2Pow"][0]
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-6)
    wd = attrs.get("weight_decay", 0.01)
    lr = _lr(ins)
    g = g.astype(m1.dtype)
    m1o = b1 * m1 + (1 - b1) * g
    m2o = b2 * m2 + (1 - b2) * g * g
    mhat = m1o / (1 - b1p.reshape(()))
    vhat = m2o / (1 - b2p.reshape(()))
    r = mhat / (jnp.sqrt(vhat) + eps) + wd * p
    p_norm = jnp.linalg.norm(p)
    r_norm = jnp.linalg.norm(r)
    trust = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
    p_out = p - lr * trust * r
    return {
        "ParamOut": [p_out],
        "Moment1Out": [m1o],
        "Moment2Out": [m2o],
        "Beta1PowOut": [b1p * b1],
        "Beta2PowOut": [b2p * b2],
    }


@register("lars_momentum", no_vjp_grad=True)
def lars_momentum(ctx, ins, attrs):
    p, g, v = ins["Param"][0], ins["Grad"][0], ins["Velocity"][0]
    mu = attrs.get("mu", 0.9)
    coeff = attrs.get("lars_coeff", 0.001)
    wd = attrs.get("lars_weight_decay", 0.0005)
    eps = attrs.get("epsilon", 0.0)
    lr = _lr(ins)
    p_norm = jnp.linalg.norm(p)
    g_norm = jnp.linalg.norm(g)
    local_lr = jnp.where(
        (p_norm > 0) & (g_norm > 0),
        lr * coeff * p_norm / (g_norm + wd * p_norm + eps),
        lr,
    )
    v_out = mu * v + local_lr * (g + wd * p)
    return {"ParamOut": [p - v_out], "VelocityOut": [v_out]}


@register("ftrl", no_vjp_grad=True)
def ftrl(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    sq, lin = ins["SquaredAccumulator"][0], ins["LinearAccumulator"][0]
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    power = attrs.get("lr_power", -0.5)
    lr = _lr(ins)
    new_sq = sq + g * g
    if power == -0.5:
        sigma = (jnp.sqrt(new_sq) - jnp.sqrt(sq)) / lr
    else:
        sigma = (new_sq ** (-power) - sq ** (-power)) / lr
    lin_out = lin + g - sigma * p
    if power == -0.5:
        denom = jnp.sqrt(new_sq) / lr + 2 * l2
    else:
        denom = new_sq ** (-power) / lr + 2 * l2
    pre = jnp.clip(lin_out, -l1, l1) - lin_out
    p_out = pre / denom
    return {
        "ParamOut": [p_out],
        "SquaredAccumOut": [new_sq],
        "LinearAccumOut": [lin_out],
    }


@register("dpsgd", no_vjp_grad=True)
def dpsgd(ctx, ins, attrs):
    """Differentially-private SGD (reference dpsgd_op.cc): clip + noise."""
    import jax

    p, g = ins["Param"][0], ins["Grad"][0]
    clip = attrs.get("clip", 10.0)
    sigma = attrs.get("sigma", 1.0)
    batch = attrs.get("batch_size", 16.0)
    lr = _lr(ins)
    gnorm = jnp.linalg.norm(g)
    scale = jnp.minimum(1.0, clip / jnp.maximum(gnorm, 1e-12))
    noise = sigma * clip * jax.random.normal(ctx.rng(), g.shape, dtype=g.dtype)
    p_out = p - lr * (g * scale + noise) / batch
    return {"ParamOut": [p_out]}
