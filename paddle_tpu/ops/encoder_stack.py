"""Fused transformer encoder stack: lax.scan over stacked layer params.

TPU-native compile-time optimization the reference cannot express: its
ProgramDesc unrolls every encoder layer into separate ops
(python builders emit 12x the op list; the C++ executor interprets each),
whereas scanning over a leading layer axis of stacked parameters makes
XLA compile ONE layer body — compile time O(1) in depth, identical
steady-state FLOPs. Used by the flagship bench path; the unrolled
per-layer builder (models/bert.py encoder_layer) stays for parity and
per-layer tensor-parallel rules.

Parallel modes (attrs set by fleet; see fleet/__init__.py):
  sequence_parallel — ring attention over the "sp" mesh axis
  pipeline          — GPipe over the "pp" mesh axis: stacked layer params
                      are sharded on the layer dim (each stage owns L/pp
                      consecutive layers); the batch is split into
                      num_microbatches and activations flow stage-to-stage
                      with lax.ppermute inside a lax.scan over
                      M + pp - 1 ticks. The TPU-native replacement for the
                      reference's SectionWorker thread pipeline
                      (/root/reference/paddle/fluid/framework/section_worker.cc:82,
                       pipeline_trainer.cc:24) — same microbatch schedule,
                      but expressed as one differentiable XLA program.

Slots (all stacked on dim 0 = layer):
  Hidden [B,S,H], AttnBias [B,1,1,S],
  QKVW [L,H,3H], QKVB [L,3H], OutW [L,H,H], OutB [L,H],
  Ln1S/Ln1B [L,H], FfnW1 [L,H,F], FfnB1 [L,F], FfnW2 [L,F,H], FfnB2 [L,H],
  Ln2S/Ln2B [L,H]
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from .registry import register

_PARAM_KEYS = (
    "QKVW", "QKVB", "OutW", "OutB", "Ln1S", "Ln1B",
    "FfnW1", "FfnB1", "FfnW2", "FfnB2", "Ln2S", "Ln2B",
)


def _policy_names(spec):
    """Parse a remat_policy attr: comma-separated checkpoint_name tags,
    with the shorthand 'flash' -> the kernel's saved residuals (o, lse).
    Tags available in the layer body: flash_o, flash_lse, attn_out,
    ln1_out, ffn_inter."""
    names = []
    for tok in str(spec).split(","):
        tok = tok.strip()
        if not tok:
            continue
        if tok == "flash":
            names += ["flash_o", "flash_lse"]
        else:
            names.append(tok)
    return tuple(dict.fromkeys(names))


def _act(name):
    return {
        "gelu": jax.nn.gelu,
        "relu": jax.nn.relu,
        "tanh": jnp.tanh,
        "silu": jax.nn.silu,
    }[name]


def _ln_f32(x, scale, shift, eps):
    """LayerNorm with f32 statistics regardless of compute dtype (bf16
    under AMP) — shared by the encoder and decoder stacks."""
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps) * scale.astype(jnp.float32) \
        + shift.astype(jnp.float32)
    return y.astype(x.dtype)


def _add_ln(x, y, scale, shift, eps):
    """LayerNorm(x + y) — the residual+LN pair of both stacks. Dispatches
    the fused Pallas kernel (ops/pallas/add_ln.py; XLA's convert+reduce
    LN fusions measured ~30x the bandwidth roofline inside the encoder
    scan) with the identical-math jnp fallback."""
    from .pallas.add_ln import fused_add_ln, fused_ln_dispatch_ok

    if fused_ln_dispatch_ok(x.shape):
        return fused_add_ln(x, y, scale, shift, eps=eps)
    return _ln_f32(x + y, scale, shift, eps)


def _cheap_dropout(x, prob, key):
    """uint8 random bits: 4x less generator traffic than bernoulli's
    32-bit uniforms (profiled ~10ms/step on BERT-base with f32 masks).
    The threshold is quantized to 1/256, so rescale by the EFFECTIVE
    keep probability to stay unbiased."""
    thresh = max(1, min(255, round((1.0 - prob) * 256)))
    keep_eff = thresh / 256.0
    bits = jax.random.bits(key, x.shape, dtype=jnp.uint8)
    return jnp.where(bits < jnp.uint8(thresh), x / keep_eff, 0.0)


def _use_gpipe(ctx, attrs):
    return (
        bool(attrs.get("pipeline", False))
        and ctx.mesh is not None
        and "pp" in ctx.mesh.axis_names
        and ctx.mesh.shape["pp"] > 1
    )


@register("fused_encoder_stack")
def fused_encoder_stack(ctx, ins, attrs):
    hidden = ins["Hidden"][0]
    bias = ins.get("AttnBias", [None])[0]
    nh = int(attrs["num_heads"])
    act = _act(attrs.get("act", "gelu"))
    dropout_prob = float(attrs.get("dropout_prob", 0.0))
    attn_dropout_prob = float(attrs.get("attn_dropout_prob", 0.0))
    is_test = bool(attrs.get("is_test", False))
    eps = float(attrs.get("epsilon", 1e-5))
    use_flash = bool(attrs.get("use_flash_attention", True))
    from ..parallel import ring_attention as ring_mod

    ring = ring_mod.use_ring(ctx, attrs)
    mesh = ctx.mesh
    base_key = ctx.salted_rng(int(attrs.get("rng_salt", 0)))
    remat_policy = _policy_names(attrs.get("remat_policy", ""))
    if remat_policy:
        # the policy checkpoint wraps the whole layer; inner blanket
        # checkpoints would force recompute of values the policy elects
        # to save, so they are mutually exclusive
        attrs = dict(attrs)
        attrs["remat_ffn"] = attrs["remat_qkv"] = attrs["remat_layer"] = False

    stacked = {k: ins[k][0] for k in _PARAM_KEYS}

    def add_ln(x, y, scale, shift):
        return _add_ln(x, y, scale, shift, eps)

    def dropout(x, prob, key):
        if is_test or prob <= 0.0:
            return x
        return _cheap_dropout(x, prob, key)

    def make_layer(bias_arr, mb_salt=None, manual=False):
        """Layer body closed over a (possibly microbatch-sliced) attention
        bias; batch size is read from the carried hidden state. mb_salt
        (pipeline path) decorrelates dropout masks across microbatches.
        manual=True means we are already inside a shard_map (GPipe) and
        the flash kernel must not wrap itself in another one."""

        def layer(carry, p):
            hid, idx = carry
            b, s, h = hid.shape
            dh = h // nh
            key = jax.random.fold_in(base_key, idx)
            if mb_salt is not None:
                key = jax.random.fold_in(key, mb_salt)
            k1, k2, k3 = jax.random.split(key, 3)

            # BSH fast path: the flash kernel reads q/k/v exactly as the
            # projection produces them ([B,S,H], heads sliced in-kernel
            # as static 64-lane views) — no head split/merge transposes,
            # which profiled at ~30-45 ms/step on BERT-base s512/b48.
            # Extreme lengths (whole-sequence VMEM residency won't fit)
            # and full [.., S, S] biases fall back to the streamed BHSD
            # kernel path below.
            from .pallas.flash_attention import bsh_dispatch_ok

            use_bsh = (
                (not ring) and use_flash
                and bsh_dispatch_ok(s, s, h, nh, bias=bias_arr, batch=b)
            )

            def project_qkv_flat(hid_, w, bias_):
                qkv = jnp.einsum("bsh,hk->bsk", hid_, w) + bias_
                return jnp.split(qkv, 3, axis=-1)

            def project_qkv(hid_, w, bias_):
                q_, k_, v_ = project_qkv_flat(hid_, w, bias_)

                def split_heads(x):
                    return x.reshape(b, s, nh, dh).transpose(0, 2, 1, 3)

                return (split_heads(q_), split_heads(k_), split_heads(v_))

            if attrs.get("remat_qkv", False):
                # recompute the q/k/v projections in the backward instead
                # of stashing three [B,S,H] tensors per layer: one extra
                # qkv matmul per layer buys ~3x H*S*B bytes off the
                # residual stash (whose transposed-layout copies stall
                # the forward scan)
                project_qkv = jax.checkpoint(project_qkv)
                project_qkv_flat = jax.checkpoint(project_qkv_flat)

            if use_bsh:
                from .pallas.flash_attention import flash_attention_bsh

                q, k, v = project_qkv_flat(hid, p["QKVW"], p["QKVB"])
                ctx_l = flash_attention_bsh(
                    q, k, v, bias_arr, num_heads=nh,
                    dropout_prob=0.0 if is_test else attn_dropout_prob,
                    dropout_key=None if is_test else k1,
                    mesh=None if manual else mesh,
                )  # [B, S, H] — already merged
            elif ring:
                # sequence-parallel ring attention over "sp"; probs dropout
                # runs inside the ring. Outside a manual region the ring
                # wraps itself in shard_map (one ring schedule per layer
                # iteration); under GPipe (manual=True) we are ALREADY
                # inside the pipeline's shard_map, where every mesh axis
                # is bound — call the per-shard ring body directly (the
                # pp x sp composition: microbatches flow over "pp" while
                # each stage's attention rotates k/v over "sp")
                q, k, v = project_qkv(hid, p["QKVW"], p["QKVB"])
                key_bias = ring_mod.key_bias_from_attn_bias(bias_arr, b)
                if manual:
                    ctx_l = ring_mod.ring_attention(
                        q, k, v, "sp", bias=key_bias,
                        dropout_prob=0.0 if is_test else attn_dropout_prob,
                        dropout_key=None if is_test else k1,
                    )
                else:
                    ctx_l = ring_mod.ring_attention_global(
                        q, k, v, mesh, axis="sp", bias=key_bias,
                        batch_axis="dp",
                        dropout_prob=0.0 if is_test else attn_dropout_prob,
                        dropout_key=None if is_test else k1,
                    )
                ctx_l = ctx_l.transpose(0, 2, 1, 3).reshape(b, s, h)
            elif use_flash and _flash_ok(s, dh):
                # streamed BHSD kernel: serves the shapes BSH can't hold
                # resident (very long S) and full [.., S, S] biases
                from .pallas.flash_attention import flash_attention

                q, k, v = project_qkv(hid, p["QKVW"], p["QKVB"])
                ctx_l = flash_attention(
                    q, k, v, bias_arr,
                    dropout_prob=0.0 if is_test else attn_dropout_prob,
                    dropout_key=None if is_test else k1,
                    mesh=None if manual else mesh,
                )
                ctx_l = ctx_l.transpose(0, 2, 1, 3).reshape(b, s, h)
            else:
                q, k, v = project_qkv(hid, p["QKVW"], p["QKVB"])
                scores = jnp.einsum(
                    "bnqd,bnkd->bnqk", q, k,
                    preferred_element_type=jnp.float32,
                ) / math.sqrt(dh)
                if bias_arr is not None:
                    scores = scores + bias_arr.astype(scores.dtype)
                probs = jax.nn.softmax(scores, axis=-1).astype(hid.dtype)
                probs = dropout(probs, attn_dropout_prob, k1)
                ctx_l = jnp.einsum("bnqk,bnkd->bnqd", probs, v)
                # tag the fallback path's context too so remat_policy
                # behaves the same when the kernel doesn't dispatch (the
                # kernel path tags o/lse inside its custom-vjp forward)
                ctx_l = checkpoint_name(ctx_l, "flash_o")
                ctx_l = ctx_l.transpose(0, 2, 1, 3).reshape(b, s, h)

            attn_out = jnp.einsum("bsh,hk->bsk", ctx_l, p["OutW"]) + p["OutB"]
            attn_out = checkpoint_name(
                dropout(attn_out, dropout_prob, k2), "attn_out"
            )
            hid = checkpoint_name(
                add_ln(hid, attn_out, p["Ln1S"], p["Ln1B"]), "ln1_out"
            )

            def ffn(h_, w1, b1, w2, b2, key3):
                inter = checkpoint_name(
                    act(jnp.einsum("bsh,hf->bsf", h_, w1) + b1), "ffn_inter"
                )
                out_ = jnp.einsum("bsf,fh->bsh", inter, w2) + b2
                return dropout(out_, dropout_prob, key3)

            if attrs.get("remat_ffn", False):
                # recompute `inter` ([B,S,F], the largest activation) in
                # the backward instead of saving it: ~1/3 extra fwd FLOPs
                # for this block buys ~F/H x memory off the residuals,
                # unlocking larger batches
                ffn = jax.checkpoint(ffn)
            ffn_out = ffn(hid, p["FfnW1"], p["FfnB1"], p["FfnW2"], p["FfnB2"], k3)
            hid = add_ln(hid, ffn_out, p["Ln2S"], p["Ln2B"])
            return (hid, idx + 1), None

        return layer

    if remat_policy and not _use_gpipe(ctx, attrs):
        # policy remat: save ONLY the tagged values (e.g. the flash
        # kernel's o/lse residuals) per layer; everything untagged — the
        # qkv/out/ffn projections, norms, dropouts — is recomputed in the
        # backward from the scan-carried hidden. With 'flash' saved the
        # recompute DCEs the forward attention kernel, unlike remat_layer
        # which re-runs it: the long-context (s>=4096) memory/FLOPs
        # sweet spot, and it also kills the q/k/v residual-stash layout
        # copies that stalled the forward scan at s512.
        _layer = make_layer(bias)
        pol = jax.checkpoint_policies.save_only_these_names(*remat_policy)
        layer_ck = jax.checkpoint(lambda c, p: _layer(c, p), policy=pol)
        (out, _), _ = jax.lax.scan(layer_ck, (hidden, jnp.int32(0)), stacked)
        return {"Out": [out]}

    if attrs.get("remat_layer", False) and not _use_gpipe(ctx, attrs):
        # full-layer remat: save only the carried hidden per layer
        _layer = make_layer(bias)
        layer_ck = jax.checkpoint(lambda c, p: _layer(c, p))
        (out, _), _ = jax.lax.scan(layer_ck, (hidden, jnp.int32(0)), stacked)
        return {"Out": [out]}

    if _use_gpipe(ctx, attrs):
        M = int(attrs.get("num_microbatches", 0)) or mesh.shape["pp"]
        ml = make_layer
        if remat_policy:
            # the policy wraps each stage-local layer body inside the
            # GPipe shard_map, so pipeline + remat_policy saves only the
            # tagged values per layer (same contract as the scan path)
            pol = jax.checkpoint_policies.save_only_these_names(
                *remat_policy)

            def ml(bias_arr, mb_salt=None, manual=False):
                inner = make_layer(bias_arr, mb_salt, manual)
                return jax.checkpoint(
                    lambda c, p: inner(c, p), policy=pol)
        out = _gpipe_stack(hidden, stacked, bias, mesh, M, ml,
                           ring=ring)
        return {"Out": [out]}

    layer = make_layer(bias)
    (out, _), _ = jax.lax.scan(layer, (hidden, jnp.int32(0)), stacked)
    return {"Out": [out]}


def _gpipe_stack(hidden, stacked, bias, mesh, M, make_layer, ring=False):
    """GPipe schedule over the "pp" axis. Stage s owns layers
    [s*L/pp, (s+1)*L/pp); microbatch m enters stage 0 at tick m and leaves
    stage pp-1 at tick m+pp-1. Activations rotate via ppermute; the
    attention bias is replicated over pp, so each stage just indexes the
    microbatch it is currently processing (m = t - s) — no transfer.
    ring=True additionally shards the SEQUENCE dim over "sp" (hidden and
    the per-key bias); the layer body then runs ring attention inside
    this shard_map (pp x sp composition for long-context pipelines)."""
    from jax import lax

    from ..compat import shard_map
    from jax.sharding import PartitionSpec as P

    npp = mesh.shape["pp"]
    dp = "dp" if "dp" in mesh.axis_names else None
    dp_size = mesh.shape[dp] if dp else 1
    sp = (
        "sp" if ring and "sp" in mesh.axis_names and mesh.shape["sp"] > 1
        else None
    )
    L = stacked["QKVW"].shape[0]
    if L % npp != 0:
        raise ValueError(f"num layers {L} must divide by pp={npp}")
    B = hidden.shape[0]
    if B % (dp_size * M) != 0:
        raise ValueError(
            f"per-dp-shard batch {B}//{dp_size} must divide by "
            f"num_microbatches={M}"
        )

    keys = list(_PARAM_KEYS)
    hid_spec = P(dp, sp, None)
    bias_spec = P(dp, None, None, sp)
    p_specs = tuple(P("pp") for _ in keys)
    perm = [(i, i + 1) for i in range(npp - 1)]

    def body(hid_l, bias_l, *p_locals):
        s_idx = lax.axis_index("pp")
        l_loc = L // npp
        b_loc = hid_l.shape[0]
        mb = b_loc // M
        mbs = hid_l.reshape(M, mb, *hid_l.shape[1:])
        bias_mbs = (
            bias_l.reshape(M, mb, *bias_l.shape[1:]) if bias_l is not None else None
        )
        p_local = dict(zip(keys, p_locals))

        def stage(x, bias_x, mb_salt):
            layer = make_layer(bias_x, mb_salt, manual=True)
            start = s_idx * l_loc
            (out, _), _ = lax.scan(layer, (x, start), p_local)
            return out

        def tick(carry, t):
            recv_x = carry
            # the microbatch this stage works on at tick t (bubble ticks
            # clamp to a valid index; their output is discarded)
            m_cur = jnp.clip(t - s_idx, 0, M - 1)
            x0 = lax.dynamic_index_in_dim(mbs, jnp.clip(t, 0, M - 1), 0, keepdims=False)
            x_in = jnp.where(s_idx == 0, x0, recv_x)
            b_in = (
                lax.dynamic_index_in_dim(bias_mbs, m_cur, 0, keepdims=False)
                if bias_mbs is not None
                else None
            )
            out = stage(x_in, b_in, m_cur)
            send_x = lax.ppermute(out, "pp", perm)
            emit = jnp.logical_and(s_idx == npp - 1, t >= npp - 1)
            y = jnp.where(emit, out, jnp.zeros_like(out))
            return send_x, y

        _, ys = lax.scan(tick, jnp.zeros_like(mbs[0]), jnp.arange(M + npp - 1))
        # microbatch m finishes at tick m + npp - 1 (on the last stage)
        out_l = ys[npp - 1:].reshape(b_loc, *hid_l.shape[1:])
        # only the last stage holds nonzero output; psum broadcasts it
        return lax.psum(out_l, "pp")

    if bias is None:
        def body_nobias(hid_l, *p_locals):
            return body(hid_l, None, *p_locals)

        return shard_map(
            body_nobias, mesh=mesh, in_specs=(hid_spec,) + p_specs,
            out_specs=hid_spec, check=False,
        )(hidden, *[stacked[k] for k in keys])

    return shard_map(
        body, mesh=mesh, in_specs=(hid_spec, bias_spec) + p_specs,
        out_specs=hid_spec, check=False,
    )(hidden, bias, *[stacked[k] for k in keys])


def _flash_ok(s, dh):
    from .pallas.flash_attention import flash_shapes_ok

    return flash_shapes_ok(s, dh)


_DEC_PARAM_KEYS = (
    "SelfQKVW", "SelfQKVB", "SelfOutW", "SelfOutB", "Ln1S", "Ln1B",
    "CrossQW", "CrossQB", "CrossKW", "CrossKB", "CrossVW", "CrossVB",
    "CrossOutW", "CrossOutB", "Ln2S", "Ln2B",
    "FfnW1", "FfnB1", "FfnW2", "FfnB2", "Ln3S", "Ln3B",
)


@register("fused_decoder_stack")
def fused_decoder_stack(ctx, ins, attrs):
    """Scan-fused transformer DECODER stack (causal self-attention +
    cross-attention over a loop-invariant encoder memory + FFN, post-LN):
    the NMT counterpart of fused_encoder_stack. The reference builds all
    6 decoder layers as separate op lists (dist_transformer.py); one
    scanned body compiles once, and both attentions run the BSH
    (transpose-free) Pallas flash kernel — causal masking in-kernel for
    self-attention, and RECTANGULAR (St != Ss) cross-attention with the
    source padding mask as a per-key bias. Under sequence parallelism
    ("sp" mesh axis): self-attention runs the causal ring over trg
    shards; cross-attention keeps the jnp composition on global arrays
    so GSPMD all-gathers the src-sharded k/v (Megatron-SP strategy).

    Slots (stacked on dim 0 = layer): _DEC_PARAM_KEYS above; inputs
    Hidden [B,St,H], EncOut [B,Ss,H], SrcBias [B,1,1,Ss]."""
    hidden = ins["Hidden"][0]
    enc_out = ins["EncOut"][0]
    src_bias = ins.get("SrcBias", [None])[0]
    nh = int(attrs["num_heads"])
    act = _act(attrs.get("act", "relu"))
    dropout_prob = float(attrs.get("dropout_prob", 0.0))
    attn_dropout_prob = float(attrs.get("attn_dropout_prob", 0.0))
    is_test = bool(attrs.get("is_test", False))
    eps = float(attrs.get("epsilon", 1e-5))
    use_flash = bool(attrs.get("use_flash_attention", True))
    from ..parallel import ring_attention as ring_mod

    # sequence parallelism: causal self-attention runs the ring over
    # "sp" (trg tokens sharded; k/v blocks rotate via ppermute); the
    # rectangular cross-attention keeps the jnp composition on GLOBAL
    # arrays — under GSPMD the trg dim stays sp-sharded and XLA
    # all-gathers the (src-sharded) k/v projections, the Megatron-SP
    # strategy for attending over a full memory from a sharded query
    ring = ring_mod.use_ring(ctx, attrs)
    mesh = ctx.mesh
    base_key = ctx.salted_rng(int(attrs.get("rng_salt", 0)))
    stacked = {k: ins[k][0] for k in _DEC_PARAM_KEYS}

    def add_ln(x, y, scale, shift):
        return _add_ln(x, y, scale, shift, eps)

    def dropout(x, prob, key):
        if is_test or prob <= 0.0:
            return x
        return _cheap_dropout(x, prob, key)

    b, st, h = hidden.shape
    ss = enc_out.shape[1]
    dh = h // nh

    def split_heads(x, s):
        return x.reshape(b, s, nh, dh).transpose(0, 2, 1, 3)

    def merge_heads(x, s):
        return x.transpose(0, 2, 1, 3).reshape(b, s, h)

    def jnp_attn(q, k, v, bias4, causal, key):
        scores = jnp.einsum(
            "bnqd,bnkd->bnqk", q, k, preferred_element_type=jnp.float32,
        ) / math.sqrt(dh)
        if bias4 is not None:
            scores = scores + bias4.astype(scores.dtype)
        if causal:
            qlen, klen = scores.shape[-2], scores.shape[-1]
            cm = jnp.arange(qlen)[:, None] >= jnp.arange(klen)[None, :]
            scores = jnp.where(cm, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
        probs = dropout(probs, attn_dropout_prob, key)
        return jnp.einsum("bnqk,bnkd->bnqd", probs, v)

    from .pallas.flash_attention import bsh_dispatch_ok

    def attend_flat(q3, k3, v3, bias4, causal, key):
        """q3 [B,Sq,H], k3/v3 [B,Skv,H] -> [B,Sq,H]. BSH kernel when the
        shapes allow — rectangular (cross-attention) included, no head
        transposes; jnp composition otherwise."""
        sq, skv = q3.shape[1], k3.shape[1]
        if ring and causal:
            # trg-sharded causal self-attention over the ring
            ctx4 = ring_mod.ring_attention_global(
                split_heads(q3, sq), split_heads(k3, skv),
                split_heads(v3, skv), mesh, axis="sp", causal=True,
                batch_axis="dp",
                dropout_prob=0.0 if is_test else attn_dropout_prob,
                dropout_key=None if is_test else key,
            )
            return merge_heads(ctx4, sq)
        if ring:
            # cross-attention under sp: jnp path — GSPMD gathers k/v
            ctx4 = jnp_attn(split_heads(q3, sq), split_heads(k3, skv),
                            split_heads(v3, skv), bias4, False, key)
            return merge_heads(ctx4, sq)
        if use_flash and bsh_dispatch_ok(sq, skv, h, nh, bias=bias4,
                                         batch=b, causal=causal):
            from .pallas.flash_attention import flash_attention_bsh

            return flash_attention_bsh(
                q3, k3, v3, bias4, num_heads=nh, causal=causal,
                dropout_prob=0.0 if is_test else attn_dropout_prob,
                dropout_key=None if is_test else key,
                mesh=mesh,
            )
        ctx4 = jnp_attn(split_heads(q3, sq), split_heads(k3, skv),
                        split_heads(v3, skv), bias4, causal, key)
        return merge_heads(ctx4, sq)

    def layer(carry, p):
        hid, idx = carry
        key = jax.random.fold_in(base_key, idx)
        k1, k2, k3, k4, k5 = jax.random.split(key, 5)

        # --- causal self-attention
        qkv = jnp.einsum("bsh,hk->bsk", hid, p["SelfQKVW"]) + p["SelfQKVB"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        ctx_s = attend_flat(q, k, v, None, True, k1)
        self_out = jnp.einsum(
            "bsh,hk->bsk", ctx_s, p["SelfOutW"]
        ) + p["SelfOutB"]
        hid = add_ln(hid, dropout(self_out, dropout_prob, k2),
                     p["Ln1S"], p["Ln1B"])

        # --- cross-attention over the encoder memory (rectangular: trg
        # queries over src keys — in-kernel via the BSH layout)
        qc = jnp.einsum("bsh,hk->bsk", hid, p["CrossQW"]) + p["CrossQB"]
        kc = jnp.einsum("bsh,hk->bsk", enc_out, p["CrossKW"]) + p["CrossKB"]
        vc = jnp.einsum("bsh,hk->bsk", enc_out, p["CrossVW"]) + p["CrossVB"]
        ctx_c = attend_flat(qc, kc, vc, src_bias, False, k3)
        cross_out = jnp.einsum(
            "bsh,hk->bsk", ctx_c, p["CrossOutW"]
        ) + p["CrossOutB"]
        hid = add_ln(hid, dropout(cross_out, dropout_prob, k4),
                     p["Ln2S"], p["Ln2B"])

        # --- FFN
        def ffn(h_, w1, b1, w2, b2, key5):
            inter = act(jnp.einsum("bsh,hf->bsf", h_, w1) + b1)
            out_ = jnp.einsum("bsf,fh->bsh", inter, w2) + b2
            return dropout(out_, dropout_prob, key5)

        if attrs.get("remat_ffn", False):
            ffn = jax.checkpoint(ffn)
        ffn_out = ffn(hid, p["FfnW1"], p["FfnB1"], p["FfnW2"], p["FfnB2"], k5)
        hid = add_ln(hid, ffn_out, p["Ln3S"], p["Ln3B"])
        return (hid, idx + 1), None

    (out, _), _ = jax.lax.scan(layer, (hidden, jnp.int32(0)), stacked)
    return {"Out": [out]}
