"""Fused transformer encoder stack: lax.scan over stacked layer params.

TPU-native compile-time optimization the reference cannot express: its
ProgramDesc unrolls every encoder layer into separate ops
(python builders emit 12x the op list; the C++ executor interprets each),
whereas scanning over a leading layer axis of stacked parameters makes
XLA compile ONE layer body — compile time O(1) in depth, identical
steady-state FLOPs. Used by the flagship bench path; the unrolled
per-layer builder (models/bert.py encoder_layer) stays for parity and
per-layer tensor-parallel rules.

Slots (all stacked on dim 0 = layer):
  Hidden [B,S,H], AttnBias [B,1,1,S],
  QKVW [L,H,3H], QKVB [L,3H], OutW [L,H,H], OutB [L,H],
  Ln1S/Ln1B [L,H], FfnW1 [L,H,F], FfnB1 [L,F], FfnW2 [L,F,H], FfnB2 [L,H],
  Ln2S/Ln2B [L,H]
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .registry import register


def _act(name):
    return {
        "gelu": jax.nn.gelu,
        "relu": jax.nn.relu,
        "tanh": jnp.tanh,
        "silu": jax.nn.silu,
    }[name]


@register("fused_encoder_stack")
def fused_encoder_stack(ctx, ins, attrs):
    hidden = ins["Hidden"][0]
    bias = ins.get("AttnBias", [None])[0]
    nh = int(attrs["num_heads"])
    act = _act(attrs.get("act", "gelu"))
    dropout_prob = float(attrs.get("dropout_prob", 0.0))
    attn_dropout_prob = float(attrs.get("attn_dropout_prob", 0.0))
    is_test = bool(attrs.get("is_test", False))
    eps = float(attrs.get("epsilon", 1e-5))
    use_flash = bool(attrs.get("use_flash_attention", True))
    from ..parallel import ring_attention as ring_mod

    ring = ring_mod.use_ring(ctx, attrs)
    mesh = ctx.mesh
    base_key = ctx.salted_rng(int(attrs.get("rng_salt", 0)))

    stacked = {
        k: ins[k][0]
        for k in (
            "QKVW", "QKVB", "OutW", "OutB", "Ln1S", "Ln1B",
            "FfnW1", "FfnB1", "FfnW2", "FfnB2", "Ln2S", "Ln2B",
        )
    }
    b, s, h = hidden.shape
    dh = h // nh

    def ln(x, scale, shift):
        mu = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
        return (x - mu) * jax.lax.rsqrt(var + eps) * scale + shift

    def dropout(x, prob, key):
        if is_test or prob <= 0.0:
            return x
        keep = jax.random.bernoulli(key, 1.0 - prob, x.shape)
        return jnp.where(keep, x / (1.0 - prob), 0.0)

    def layer(carry, xs):
        hid, idx = carry
        p = xs
        key = jax.random.fold_in(base_key, idx)
        k1, k2, k3 = jax.random.split(key, 3)

        qkv = jnp.einsum("bsh,hk->bsk", hid, p["QKVW"]) + p["QKVB"]
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def split_heads(x):
            return x.reshape(b, s, nh, dh).transpose(0, 2, 1, 3)

        q, k, v = split_heads(q), split_heads(k), split_heads(v)
        if ring:
            # sequence-parallel ring attention over "sp"; probs dropout runs
            # inside the ring. shard_map inside the scan body is fine — XLA
            # sees one ring schedule per layer iteration
            key_bias = ring_mod.key_bias_from_attn_bias(bias, b)
            ctx_l = ring_mod.ring_attention_global(
                q, k, v, mesh, axis="sp", bias=key_bias, batch_axis="dp",
                dropout_prob=0.0 if is_test else attn_dropout_prob,
                dropout_key=None if is_test else k1,
            )
        elif use_flash and (is_test or attn_dropout_prob == 0.0) and _flash_ok(s, dh):
            from .pallas.flash_attention import flash_attention

            ctx_l = flash_attention(q, k, v, bias)
        else:
            scores = jnp.einsum("bnqd,bnkd->bnqk", q, k) / math.sqrt(dh)
            if bias is not None:
                scores = scores + bias.astype(scores.dtype)
            probs = jax.nn.softmax(scores, axis=-1)
            probs = dropout(probs, attn_dropout_prob, k1)
            ctx_l = jnp.einsum("bnqk,bnkd->bnqd", probs, v)
        ctx_l = ctx_l.transpose(0, 2, 1, 3).reshape(b, s, h)

        attn_out = jnp.einsum("bsh,hk->bsk", ctx_l, p["OutW"]) + p["OutB"]
        attn_out = dropout(attn_out, dropout_prob, k2)
        hid = ln(hid + attn_out, p["Ln1S"], p["Ln1B"])

        inter = act(jnp.einsum("bsh,hf->bsf", hid, p["FfnW1"]) + p["FfnB1"])
        ffn_out = jnp.einsum("bsf,fh->bsh", inter, p["FfnW2"]) + p["FfnB2"]
        ffn_out = dropout(ffn_out, dropout_prob, k3)
        hid = ln(hid + ffn_out, p["Ln2S"], p["Ln2B"])
        return (hid, idx + 1), None

    (out, _), _ = jax.lax.scan(layer, (hidden, jnp.int32(0)), stacked)
    return {"Out": [out]}


def _flash_ok(s, dh):
    if jax.default_backend() not in ("tpu", "axon"):
        from . import attention

        if not attention.FORCE_PALLAS:
            return False
    return dh in (64, 128, 256) and s % 128 == 0
