"""Reduction ops. Parity surface: reference operators/reduce_ops/ (~2.2k LoC):
reduce_sum/mean/max/min/prod/all/any with attrs dim / keep_dim / reduce_all."""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register


def _axes(x, attrs):
    if attrs.get("reduce_all", False):
        return None
    dim = attrs.get("dim", [0])
    if isinstance(dim, int):
        dim = [dim]
    if len(dim) == 0:
        return None
    return tuple(d % x.ndim for d in dim)


def _reduce(name, fn, stop_grad=False):
    @register(name, stop_gradient=stop_grad)
    def _emit(ctx, ins, attrs, _fn=fn):
        x = ins["X"][0]
        out = _fn(x, axis=_axes(x, attrs), keepdims=attrs.get("keep_dim", False))
        if out.ndim == 0:
            out = out.reshape((1,))  # fluid reductions keep at least rank 1
        return {"Out": [out]}

    return _emit


_reduce("reduce_sum", jnp.sum)
_reduce("reduce_mean", jnp.mean)
_reduce("reduce_max", jnp.max)
_reduce("reduce_min", jnp.min)
_reduce("reduce_prod", jnp.prod)
_reduce("reduce_all", jnp.all, stop_grad=True)
_reduce("reduce_any", jnp.any, stop_grad=True)


@register("mean")
def mean(ctx, ins, attrs):
    """Whole-tensor mean to a [1] tensor (reference mean_op.cc)."""
    return {"Out": [jnp.mean(ins["X"][0]).reshape((1,))]}


@register("frobenius_norm")
def frobenius_norm(ctx, ins, attrs):
    x = ins["X"][0]
    out = jnp.sqrt(
        jnp.sum(jnp.square(x), axis=_axes(x, attrs), keepdims=attrs.get("keep_dim", False))
    )
    if out.ndim == 0:
        out = out.reshape((1,))
    return {"Out": [out]}
