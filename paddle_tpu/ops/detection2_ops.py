"""Detection ops, batch 2: anchors, NMS family, assignment, SSD.

Parity surface: reference operators/detection/ — anchor_generator_op.cc,
density_prior_box_op.cc, box_clip_op.cc, box_decoder_and_assign_op.cc,
multiclass_nms_op.cc, matrix_nms (2.x), locality_aware_nms_op.cc,
target_assign_op.cc, polygon_box_transform_op.cc, and
operators/ctc_align_op.cc (ctc_greedy_decoder backend).

Static-shape contract (XLA): the reference emits LoD outputs whose row
count depends on the data; here every NMS/assign op returns FIXED-size
tensors padded with -1 rows (label slot) or zero weights, plus explicit
valid-count outputs. Suppression loops run over a static keep_top_k.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


def _iou_matrix(a, b):
    """[Na, 4] x [Nb, 4] -> [Na, Nb] IoU (xyxy boxes)."""
    area_a = jnp.maximum(a[:, 2] - a[:, 0], 0) * jnp.maximum(a[:, 3] - a[:, 1], 0)
    area_b = jnp.maximum(b[:, 2] - b[:, 0], 0) * jnp.maximum(b[:, 3] - b[:, 1], 0)
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / jnp.maximum(area_a[:, None] + area_b[None, :] - inter, 1e-10)


@register("anchor_generator", stop_gradient=True, no_vjp_grad=True)
def anchor_generator(ctx, ins, attrs):
    """Dense anchors over the feature map (reference anchor_generator_op.cc):
    Input [N, C, H, W] -> Anchors [H, W, A, 4] (xyxy, input-image scale),
    Variances [H, W, A, 4]."""
    x = ins["Input"][0]
    sizes = [float(s) for s in attrs["anchor_sizes"]]
    ratios = [float(r) for r in attrs["aspect_ratios"]]
    variances = [float(v) for v in attrs.get("variances", [0.1, 0.1, 0.2, 0.2])]
    stride = [float(s) for s in attrs["stride"]]
    offset = float(attrs.get("offset", 0.5))
    h, w = x.shape[2], x.shape[3]

    anchors = []
    for r in ratios:
        for s in sizes:
            aw = s * (r ** 0.5)
            ah = s / (r ** 0.5)
            anchors.append((-aw / 2, -ah / 2, aw / 2, ah / 2))
    base = jnp.asarray(anchors, jnp.float32)  # [A, 4]
    cx = (jnp.arange(w, dtype=jnp.float32) + offset) * stride[0]
    cy = (jnp.arange(h, dtype=jnp.float32) + offset) * stride[1]
    gx, gy = jnp.meshgrid(cx, cy)  # [H, W]
    centers = jnp.stack([gx, gy, gx, gy], axis=-1)  # [H, W, 4]
    out = centers[:, :, None, :] + base[None, None, :, :]
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32), out.shape)
    return {"Anchors": [out], "Variances": [var]}


@register("density_prior_box", stop_gradient=True, no_vjp_grad=True)
def density_prior_box(ctx, ins, attrs):
    """Dense + fixed-size priors (reference density_prior_box_op.cc):
    fixed_sizes x densities grids per cell."""
    x, img = ins["Input"][0], ins["Image"][0]
    fixed_sizes = [float(s) for s in attrs["fixed_sizes"]]
    fixed_ratios = [float(r) for r in attrs["fixed_ratios"]]
    densities = [int(d) for d in attrs["densities"]]
    variances = [float(v) for v in attrs.get("variances", [0.1, 0.1, 0.2, 0.2])]
    offset = float(attrs.get("offset", 0.5))
    clip = bool(attrs.get("clip", False))
    h, w = x.shape[2], x.shape[3]
    ih, iw = img.shape[2], img.shape[3]
    step_w = float(attrs.get("step_w", 0.0)) or iw / w
    step_h = float(attrs.get("step_h", 0.0)) or ih / h

    boxes = []
    for size, density in zip(fixed_sizes, densities):
        for ratio in fixed_ratios:
            bw = size * (ratio ** 0.5)
            bh = size / (ratio ** 0.5)
            shift = size / density
            for di in range(density):
                for dj in range(density):
                    dx = (dj + 0.5) * shift - size / 2.0
                    dy = (di + 0.5) * shift - size / 2.0
                    boxes.append((dx, dy, bw, bh))
    base = jnp.asarray(boxes, jnp.float32)  # [P, 4] (dx, dy, w, h)
    cx = (jnp.arange(w, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(h, dtype=jnp.float32) + offset) * step_h
    gx, gy = jnp.meshgrid(cx, cy)
    px = gx[:, :, None] + base[None, None, :, 0]
    py = gy[:, :, None] + base[None, None, :, 1]
    bw = base[None, None, :, 2]
    bh = base[None, None, :, 3]
    out = jnp.stack(
        [(px - bw / 2) / iw, (py - bh / 2) / ih,
         (px + bw / 2) / iw, (py + bh / 2) / ih], axis=-1)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32), out.shape)
    return {"Boxes": [out], "Variances": [var]}


@register("box_clip")
def box_clip(ctx, ins, attrs):
    """Clip boxes to image bounds (reference box_clip_op.cc): Input
    [N, B, 4], ImInfo [N, 3] (h, w, scale)."""
    boxes = ins["Input"][0]
    im_info = ins["ImInfo"][0]
    h = (im_info[:, 0] / im_info[:, 2])[:, None] - 1.0
    w = (im_info[:, 1] / im_info[:, 2])[:, None] - 1.0
    x1 = jnp.clip(boxes[..., 0], 0.0, w)
    y1 = jnp.clip(boxes[..., 1], 0.0, h)
    x2 = jnp.clip(boxes[..., 2], 0.0, w)
    y2 = jnp.clip(boxes[..., 3], 0.0, h)
    return {"Output": [jnp.stack([x1, y1, x2, y2], axis=-1)]}


@register("box_decoder_and_assign", stop_gradient=True, no_vjp_grad=True)
def box_decoder_and_assign(ctx, ins, attrs):
    """Decode per-class deltas and keep the best-scoring class's box
    (reference box_decoder_and_assign_op.cc). PriorBox [B, 4],
    TargetBox [B, C*4], BoxScore [B, C]."""
    prior = ins["PriorBox"][0]
    deltas = ins["TargetBox"][0]
    scores = ins["BoxScore"][0]
    var = [float(v) for v in attrs.get("box_var", [0.1, 0.1, 0.2, 0.2])]
    # reference box_clip attr bounds the w/h delta exponent (e.g.
    # log(1000/16) = 4.135), preventing exp() blowups on wild regressions
    bclip = float(attrs.get("box_clip", 10.0))
    b = prior.shape[0]
    c = scores.shape[1]
    d = deltas.reshape(b, c, 4)
    pw = prior[:, 2] - prior[:, 0] + 1.0
    ph = prior[:, 3] - prior[:, 1] + 1.0
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph * 0.5
    cx = var[0] * d[..., 0] * pw[:, None] + pcx[:, None]
    cy = var[1] * d[..., 1] * ph[:, None] + pcy[:, None]
    wd = jnp.exp(jnp.minimum(var[2] * d[..., 2], bclip)) * pw[:, None]
    hd = jnp.exp(jnp.minimum(var[3] * d[..., 3], bclip)) * ph[:, None]
    # reference +1 size convention: far corners get -1 (x2 = cx + w/2 - 1)
    decoded = jnp.stack(
        [cx - wd / 2, cy - hd / 2, cx + wd / 2 - 1.0, cy + hd / 2 - 1.0],
        axis=-1)
    best = jnp.argmax(scores, axis=1)
    assigned = jnp.take_along_axis(
        decoded, best[:, None, None].repeat(4, -1), axis=1).reshape(b, 4)
    return {"DecodeBox": [decoded.reshape(b, c * 4)],
            "OutputAssignBox": [assigned]}


def _nms_single(boxes, scores, score_threshold, iou_threshold, top_k):
    """Greedy NMS over one class: returns keep mask [B] (static size)."""
    valid = scores > score_threshold
    order_scores = jnp.where(valid, scores, -jnp.inf)
    n = boxes.shape[0]
    k = min(top_k, n) if top_k > 0 else n
    top_scores, order = jax.lax.top_k(order_scores, k)
    cand = boxes[order]
    iou = _iou_matrix(cand, cand)

    def body(i, keep):
        # keep candidate i unless it overlaps an earlier kept candidate
        sup = jnp.any(
            (iou[i] > iou_threshold) & keep & (jnp.arange(k) < i))
        ok = jnp.isfinite(top_scores[i]) & ~sup
        return keep.at[i].set(ok)

    keep = jnp.zeros((k,), bool)
    keep = jax.lax.fori_loop(0, k, body, keep)
    return order, top_scores, keep


@register("multiclass_nms", stop_gradient=True, no_vjp_grad=True)
def multiclass_nms(ctx, ins, attrs):
    """Per-class greedy NMS (reference multiclass_nms_op.cc).

    BBoxes [N, B, 4], Scores [N, C, B]. Out: FIXED [N, keep_top_k, 6]
    (label, score, x1, y1, x2, y2; label = -1 pads), NmsRoisNum [N]."""
    bboxes = ins["BBoxes"][0]
    scores = ins["Scores"][0]
    st = float(attrs.get("score_threshold", 0.0))
    it = float(attrs.get("nms_threshold", 0.3))
    nms_top_k = int(attrs.get("nms_top_k", 400))
    keep_top_k = int(attrs.get("keep_top_k", 100))
    background = int(attrs.get("background_label", 0))
    n, c = scores.shape[0], scores.shape[1]

    def per_image(boxes, sc):
        all_scores, all_labels, all_boxes, all_idx = [], [], [], []
        for cls in range(c):
            if cls == background:
                continue
            order, s, keep = _nms_single(boxes, sc[cls], st, it, nms_top_k)
            s = jnp.where(keep, s, -jnp.inf)
            all_scores.append(s)
            all_labels.append(jnp.full(s.shape, cls, jnp.float32))
            all_boxes.append(boxes[order])
            all_idx.append(order.astype(jnp.int32))  # original box rows
        cat_s = jnp.concatenate(all_scores)
        cat_l = jnp.concatenate(all_labels)
        cat_b = jnp.concatenate(all_boxes, axis=0)
        cat_i = jnp.concatenate(all_idx)
        k = min(keep_top_k, cat_s.shape[0])
        top_s, idx = jax.lax.top_k(cat_s, k)
        valid = jnp.isfinite(top_s)
        row = jnp.concatenate(
            [jnp.where(valid, cat_l[idx], -1.0)[:, None],
             jnp.where(valid, top_s, 0.0)[:, None],
             cat_b[idx] * valid[:, None]], axis=1)
        sel = jnp.where(valid, cat_i[idx], -1)
        pad = keep_top_k - k
        if pad > 0:
            row = jnp.concatenate(
                [row, jnp.tile(jnp.asarray([[-1, 0, 0, 0, 0, 0]], row.dtype),
                               (pad, 1))], axis=0)
            sel = jnp.concatenate([sel, jnp.full((pad,), -1, jnp.int32)])
        return row, sel[:, None], valid.sum().astype(jnp.int32)

    outs, sel_idx, counts = jax.vmap(per_image)(bboxes, scores)
    return {"Out": [outs], "Index": [sel_idx], "NmsRoisNum": [counts]}


@register("matrix_nms", stop_gradient=True, no_vjp_grad=True)
def matrix_nms(ctx, ins, attrs):
    """Parallel soft-NMS via the decay matrix (reference matrix_nms_op.cc,
    SOLOv2): no sequential suppression loop — TPU-friendly by design."""
    bboxes = ins["BBoxes"][0]
    scores = ins["Scores"][0]
    st = float(attrs.get("score_threshold", 0.0))
    post_threshold = float(attrs.get("post_threshold", 0.0))
    nms_top_k = int(attrs.get("nms_top_k", 400))
    keep_top_k = int(attrs.get("keep_top_k", 100))
    use_gaussian = bool(attrs.get("use_gaussian", False))
    sigma = float(attrs.get("gaussian_sigma", 2.0))
    background = int(attrs.get("background_label", 0))
    n, c = scores.shape[0], scores.shape[1]

    def per_class(boxes, s):
        k = min(nms_top_k, s.shape[0])
        top_s, order = jax.lax.top_k(jnp.where(s > st, s, -jnp.inf), k)
        cand = boxes[order]
        iou = _iou_matrix(cand, cand)
        upper = jnp.triu(iou, k=1)  # [i, j]: suppressor i (higher score), j
        # compensate_i: the suppressor's own worst overlap with anything
        # scored higher — divides ITS row (matrix_nms_op.cc decay formula)
        max_iou = jnp.max(upper, axis=0)
        comp_row = jnp.clip(max_iou, 0.0, 1.0 - 1e-6)[:, None]
        if use_gaussian:
            decay = jnp.min(
                jnp.exp(-(upper ** 2 - comp_row ** 2) / sigma), axis=0)
        else:
            comp = jnp.where(upper > 0,
                             (1.0 - upper) / (1.0 - comp_row), 1.0)
            decay = jnp.min(comp, axis=0)
        new_s = jnp.where(jnp.isfinite(top_s), top_s * decay, -jnp.inf)
        new_s = jnp.where(new_s > post_threshold, new_s, -jnp.inf)
        return cand, new_s

    def per_image(boxes, sc):
        all_s, all_l, all_b = [], [], []
        for cls in range(c):
            if cls == background:
                continue
            cand, s = per_class(boxes, sc[cls])
            all_s.append(s)
            all_l.append(jnp.full(s.shape, cls, jnp.float32))
            all_b.append(cand)
        cat_s = jnp.concatenate(all_s)
        cat_l = jnp.concatenate(all_l)
        cat_b = jnp.concatenate(all_b, axis=0)
        k = min(keep_top_k, cat_s.shape[0])
        top_s, idx = jax.lax.top_k(cat_s, k)
        valid = jnp.isfinite(top_s)
        row = jnp.concatenate(
            [jnp.where(valid, cat_l[idx], -1.0)[:, None],
             jnp.where(valid, top_s, 0.0)[:, None],
             cat_b[idx] * valid[:, None]], axis=1)
        pad = keep_top_k - k
        if pad > 0:
            row = jnp.concatenate(
                [row, jnp.tile(jnp.asarray([[-1, 0, 0, 0, 0, 0]], row.dtype),
                               (pad, 1))], axis=0)
        return row, valid.sum().astype(jnp.int32)

    outs, counts = jax.vmap(per_image)(bboxes, scores)
    return {"Out": [outs], "RoisNum": [counts]}


@register("locality_aware_nms", stop_gradient=True, no_vjp_grad=True)
def locality_aware_nms(ctx, ins, attrs):
    """Locality-aware NMS (reference locality_aware_nms_op.cc, EAST OCR):
    score-weighted merge of consecutive overlapping boxes, then standard
    NMS. Single-class (C=1) as in the reference."""
    bboxes = ins["BBoxes"][0]  # [N, B, 4]
    scores = ins["Scores"][0]  # [N, 1, B]
    it = float(attrs.get("nms_threshold", 0.3))
    st = float(attrs.get("score_threshold", 0.0))
    nms_top_k = int(attrs.get("nms_top_k", 400))
    keep_top_k = int(attrs.get("keep_top_k", 100))

    def per_image(boxes, sc):
        s = sc[0]
        nb = boxes.shape[0]
        # locality merge: weight-average each box with its NEXT neighbor
        # when IoU > threshold (one merge pass, the dense analog of the
        # reference's sequential scan over geometrically-sorted rows)
        nxt = jnp.roll(boxes, -1, axis=0)
        nxt_s = jnp.roll(s, -1)
        iou = jax.vmap(
            lambda a, bx: _iou_matrix(a[None], bx[None])[0, 0])(boxes, nxt)
        do_merge = (iou > it) & (jnp.arange(nb) < nb - 1)
        wsum = s + nxt_s
        merged = (boxes * s[:, None] + nxt * nxt_s[:, None]) / jnp.maximum(
            wsum[:, None], 1e-10)
        boxes2 = jnp.where(do_merge[:, None], merged, boxes)
        s2 = jnp.where(do_merge, wsum, s)
        # NMS over nms_top_k candidates, THEN keep the keep_top_k best
        order, top_s, keep = _nms_single(boxes2, s2, st, it,
                                         min(nms_top_k, nb))
        kept_s = jnp.where(keep & jnp.isfinite(top_s), top_s, -jnp.inf)
        kk = min(keep_top_k, kept_s.shape[0])
        fin_s, fin_i = jax.lax.top_k(kept_s, kk)
        valid = jnp.isfinite(fin_s)
        row = jnp.concatenate(
            [jnp.where(valid, 0.0, -1.0)[:, None],
             jnp.where(valid, fin_s, 0.0)[:, None],
             boxes2[order][fin_i] * valid[:, None]], axis=1)
        if kk < keep_top_k:
            row = jnp.concatenate(
                [row, jnp.tile(jnp.asarray([[-1, 0, 0, 0, 0, 0]], row.dtype),
                               (keep_top_k - kk, 1))], axis=0)
        return row

    return {"Out": [jax.vmap(per_image)(bboxes, scores)]}


@register("target_assign", stop_gradient=True, no_vjp_grad=True)
def target_assign(ctx, ins, attrs):
    """Assign per-prior targets by match indices (reference
    target_assign_op.cc): X [N, M, K] (rows to gather), MatchIndices
    [N, P] (-1 = unmatched -> mismatch_value, weight 0)."""
    x = ins["X"][0]
    match = ins["MatchIndices"][0].astype(jnp.int32)
    mismatch = attrs.get("mismatch_value", 0)
    idx = jnp.clip(match, 0, x.shape[1] - 1)
    out = jnp.take_along_axis(
        x, idx[:, :, None].repeat(x.shape[2], -1), axis=1)
    matched = (match >= 0)
    out = jnp.where(matched[:, :, None], out,
                    jnp.asarray(mismatch, x.dtype))
    weight = matched.astype(jnp.float32)[:, :, None]
    return {"Out": [out], "OutWeight": [weight]}


@register("bipartite_match", stop_gradient=True, no_vjp_grad=True)
def bipartite_match(ctx, ins, attrs):
    """Greedy bipartite matching (reference bipartite_match_op.cc):
    DistMat [N, M, P] (rows = ground truth, cols = priors). Outputs
    ColToRowMatchIndices [N, P] (-1 unmatched) and matched distances.
    match_type='per_prediction' additionally matches cols whose best row
    distance exceeds overlap_threshold."""
    dist = ins["DistMat"][0]
    match_type = attrs.get("match_type", "bipartite")
    thr = float(attrs.get("dist_threshold", 0.5))
    n, m, p = dist.shape

    def one(d):
        col_match = jnp.full((p,), -1, jnp.int32)
        col_dist = jnp.zeros((p,), jnp.float32)
        row_used = jnp.zeros((m,), bool)
        col_used = jnp.zeros((p,), bool)

        def body(_, carry):
            col_match, col_dist, row_used, col_used = carry
            masked = jnp.where(row_used[:, None] | col_used[None, :],
                               -jnp.inf, d)
            flat = jnp.argmax(masked)
            r, c0 = flat // p, flat % p
            best = masked[r, c0]
            ok = jnp.isfinite(best)
            col_match = jnp.where(ok, col_match.at[c0].set(r.astype(jnp.int32)),
                                  col_match)
            col_dist = jnp.where(ok, col_dist.at[c0].set(best), col_dist)
            row_used = jnp.where(ok, row_used.at[r].set(True), row_used)
            col_used = jnp.where(ok, col_used.at[c0].set(True), col_used)
            return col_match, col_dist, row_used, col_used

        col_match, col_dist, row_used, col_used = jax.lax.fori_loop(
            0, min(m, p), body, (col_match, col_dist, row_used, col_used))
        if match_type == "per_prediction":
            best_row = jnp.argmax(d, axis=0).astype(jnp.int32)
            best_d = jnp.max(d, axis=0)
            extra = (col_match < 0) & (best_d >= thr)
            col_match = jnp.where(extra, best_row, col_match)
            col_dist = jnp.where(extra, best_d, col_dist)
        return col_match, col_dist

    cm, cd = jax.vmap(one)(dist)
    return {"ColToRowMatchIndices": [cm], "ColToRowMatchDist": [cd]}


@register("polygon_box_transform", stop_gradient=True, no_vjp_grad=True)
def polygon_box_transform(ctx, ins, attrs):
    """EAST head geometry: offsets -> absolute corner coords (reference
    polygon_box_transform_op.cc): input [N, 8|K, H, W]; out[c] = 4*j -
    in[c] for even c (x) and 4*i - in[c] for odd c (y) where in != 0."""
    x = ins["Input"][0]
    n, k, h, w = x.shape
    jj = jnp.arange(w, dtype=x.dtype)[None, None, None, :]
    ii = jnp.arange(h, dtype=x.dtype)[None, None, :, None]
    is_x = (jnp.arange(k) % 2 == 0)[None, :, None, None]
    base = jnp.where(is_x, 4.0 * jj, 4.0 * ii)
    return {"Output": [jnp.where(x != 0, base - x, x)]}


@register("ctc_align", stop_gradient=True, no_vjp_grad=True)
def ctc_align(ctx, ins, attrs):
    """CTC greedy collapse (reference ctc_align_op.cc): remove repeats
    then blanks. Input [B, T] ids; output [B, T] left-aligned with
    `padding_value` tail + OutLength [B]."""
    x = ins["Input"][0].astype(jnp.int32)
    blank = int(attrs.get("blank", 0))
    pad = int(attrs.get("padding_value", 0))
    b, t = x.shape
    prev = jnp.concatenate([jnp.full((b, 1), -1, jnp.int32), x[:, :-1]], axis=1)
    keep = (x != prev) & (x != blank)
    if ins.get("InputLength"):
        ln = ins["InputLength"][0].reshape(-1).astype(jnp.int32)
        keep = keep & (jnp.arange(t)[None, :] < ln[:, None])
    # left-align kept ids: a stable argsort on ~keep moves kept positions
    # to the front in their original order (no dynamic boolean indexing)
    order = jnp.argsort(~keep, axis=1, stable=True)
    gathered = jnp.take_along_axis(x, order, axis=1)
    lengths = keep.sum(axis=1).astype(jnp.int32)
    out = jnp.where(jnp.arange(t)[None, :] < lengths[:, None], gathered, pad)
    return {"Output": [out], "OutputLength": [lengths]}


@register("ssd_loss")
def ssd_loss(ctx, ins, attrs):
    """Fused SSD multibox loss (reference python layers/detection.py
    ssd_loss composition over bipartite_match/target_assign/box_coder +
    mine_hard_examples): one XLA program, differentiable w.r.t. Location
    and Confidence (matching decisions are piecewise-constant).

    Location [N,P,4], Confidence [N,P,C], GtBox [N,G,4],
    GtLabel [N,G] (-1 pads), PriorBox [P,4] -> Loss [N,1]."""
    loc = ins["Location"][0]
    conf = ins["Confidence"][0]
    gt_box = ins["GtBox"][0]
    gt_label = ins["GtLabel"][0].astype(jnp.int32)
    prior = ins["PriorBox"][0]
    if ins.get("PriorBoxVar"):
        var = ins["PriorBoxVar"][0]
    else:
        var = jnp.asarray(
            attrs.get("box_var") or [0.1, 0.1, 0.2, 0.2], jnp.float32)[None, :]
    bg = int(attrs.get("background_label", 0))
    thr = float(attrs.get("overlap_threshold", 0.5))
    ratio = float(attrs.get("neg_pos_ratio", 3.0))
    lw = float(attrs.get("loc_loss_weight", 1.0))
    cw = float(attrs.get("conf_loss_weight", 1.0))
    normalize = bool(attrs.get("normalize", True))
    n, p = loc.shape[0], loc.shape[1]

    def one(loc_i, conf_i, gtb, gtl):
        valid_gt = gtl >= 0
        iou = _iou_matrix(gtb, prior)  # [G, P]
        iou = jnp.where(valid_gt[:, None], iou, -1.0)
        best_gt = jnp.argmax(iou, axis=0)            # [P]
        best_iou = jnp.max(iou, axis=0)
        matched = best_iou >= thr                    # per_prediction match
        tgt_box = gtb[best_gt]                       # [P, 4]
        tgt_lbl = jnp.where(matched, gtl[best_gt], bg)
        # encode matched gt against priors (box_coder encode_center_size)
        pw = prior[:, 2] - prior[:, 0]
        ph = prior[:, 3] - prior[:, 1]
        pcx = prior[:, 0] + pw * 0.5
        pcy = prior[:, 1] + ph * 0.5
        tw = tgt_box[:, 2] - tgt_box[:, 0]
        th = tgt_box[:, 3] - tgt_box[:, 1]
        tcx = tgt_box[:, 0] + tw * 0.5
        tcy = tgt_box[:, 1] + th * 0.5
        v = jnp.broadcast_to(var, (p, 4))
        enc = jnp.stack([
            (tcx - pcx) / jnp.maximum(pw, 1e-10) / v[:, 0],
            (tcy - pcy) / jnp.maximum(ph, 1e-10) / v[:, 1],
            jnp.log(jnp.maximum(tw / jnp.maximum(pw, 1e-10), 1e-10)) / v[:, 2],
            jnp.log(jnp.maximum(th / jnp.maximum(ph, 1e-10), 1e-10)) / v[:, 3],
        ], axis=1)
        d = loc_i - enc
        ad = jnp.abs(d)
        smooth = jnp.where(ad < 1.0, 0.5 * d * d, ad - 0.5).sum(axis=1)
        posf = matched.astype(jnp.float32)
        loc_l = (smooth * posf).sum()
        # softmax CE per prior
        logp = jax.nn.log_softmax(conf_i, axis=-1)
        ce = -jnp.take_along_axis(logp, tgt_lbl[:, None], axis=1)[:, 0]
        n_pos = posf.sum()
        neg_ce = jnp.where(matched, -jnp.inf, ce)
        k = neg_ce.shape[0]
        top_neg, _ = jax.lax.top_k(neg_ce, k)
        keep = jnp.arange(k) < jnp.minimum(ratio * n_pos, k)
        neg_l = jnp.where(keep & jnp.isfinite(top_neg), top_neg, 0.0).sum()
        pos_l = (ce * posf).sum()
        total = lw * loc_l + cw * (pos_l + neg_l)
        if normalize:
            total = total / jnp.maximum(n_pos, 1.0)
        return total

    loss = jax.vmap(one)(loc, conf, gt_box, gt_label)
    return {"Loss": [loss[:, None]]}
