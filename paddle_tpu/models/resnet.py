"""ResNet family (reference pattern: tests/unittests/seresnext_net.py and
the fluid image_classification models; BASELINE.md tracks ResNet-50
images/sec/chip).

TPU notes: the public API takes NCHW images (the layers default), but
the network COMPUTES in NHWC — one transpose at the stem puts channels
in the lane dimension, which is the layout the TPU vector unit and
XLA's conv emitters want (channel-minor); with NCHW internals XLA
inserts per-layer layout copies instead. batch_norm runs bf16 in/out
under AMP with f32 statistics inside the emitter (blacklisting it made
AMP materialize f32 copies of every activation — profiled at ~2x the
conv time on v5e). The whole train step compiles to one XLA program
like every other model here.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from ..fluid import ParamAttr
from ..fluid import layers


@dataclass
class ResNetConfig:
    depth: int = 50
    num_classes: int = 1000
    # bottleneck block counts per stage (depth 50 default)
    blocks: List[int] = field(default_factory=lambda: [3, 4, 6, 3])
    base_filters: int = 64
    # internal compute layout; "NHWC" = channel-minor (TPU-native)
    layout: str = "NHWC"
    # fold 2x2 input blocks into channels and train a 4x4/s1 stem on 12
    # channels instead of 7x7/s2 on 3 (the MLPerf TPU ResNet trick): a
    # 3-in-channel conv runs the 128-lane MXU at 3/128 occupancy, the
    # folded form at 12/128 with a quarter the positions. Same
    # receptive-field family (4x4 folded = 8x8 unfolded ⊇ 7x7); NHWC only
    stem_space_to_depth: bool = False

    # block rosters match hapi/vision.py _RESNET_CFGS (the de-drift
    # contract: one depth table for the bench zoo and the hapi models)
    @staticmethod
    def resnet50(num_classes: int = 1000) -> "ResNetConfig":
        return ResNetConfig(50, num_classes, [3, 4, 6, 3])

    @staticmethod
    def resnet18(num_classes: int = 1000) -> "ResNetConfig":
        return ResNetConfig(18, num_classes, [2, 2, 2, 2])

    @staticmethod
    def resnet34(num_classes: int = 1000) -> "ResNetConfig":
        return ResNetConfig(34, num_classes, [3, 4, 6, 3])

    @staticmethod
    def resnet101(num_classes: int = 1000) -> "ResNetConfig":
        return ResNetConfig(101, num_classes, [3, 4, 23, 3])

    @staticmethod
    def resnet152(num_classes: int = 1000) -> "ResNetConfig":
        return ResNetConfig(152, num_classes, [3, 8, 36, 3])

    @staticmethod
    def tiny(num_classes: int = 10) -> "ResNetConfig":
        """For tests: 2 stages, 1 block each, 8 base filters."""
        return ResNetConfig(8, num_classes, [1, 1], base_filters=8)


def _conv_bn(x, filters, ksize, stride=1, act=None, name="", layout="NCHW",
             padding=None):
    conv = layers.conv2d(
        x, filters, ksize, stride=stride,
        padding=(ksize - 1) // 2 if padding is None else padding,
        param_attr=ParamAttr(name=f"{name}.w"), bias_attr=False,
        data_format=layout,
    )
    return layers.batch_norm(conv, act=act, param_attr=ParamAttr(name=f"{name}.bn_s"),
                             bias_attr=ParamAttr(name=f"{name}.bn_b"),
                             data_layout=layout)


def _channels(x, layout):
    return x.shape[1] if layout == "NCHW" else x.shape[-1]


def _bottleneck(x, filters, stride, name, layout):
    """1x1 -> 3x3 -> 1x1 (x4) with projection shortcut when needed."""
    out = _conv_bn(x, filters, 1, act="relu", name=f"{name}.c1", layout=layout)
    out = _conv_bn(out, filters, 3, stride=stride, act="relu",
                   name=f"{name}.c2", layout=layout)
    out = _conv_bn(out, filters * 4, 1, name=f"{name}.c3", layout=layout)
    if stride != 1 or _channels(x, layout) != filters * 4:
        short = _conv_bn(x, filters * 4, 1, stride=stride,
                         name=f"{name}.proj", layout=layout)
    else:
        short = x
    return layers.relu(layers.elementwise_add(out, short))


def _basic_block(x, filters, stride, name, layout):
    """3x3 -> 3x3 (resnet18/34)."""
    out = _conv_bn(x, filters, 3, stride=stride, act="relu",
                   name=f"{name}.c1", layout=layout)
    out = _conv_bn(out, filters, 3, name=f"{name}.c2", layout=layout)
    if stride != 1 or _channels(x, layout) != filters:
        short = _conv_bn(x, filters, 1, stride=stride,
                         name=f"{name}.proj", layout=layout)
    else:
        short = x
    return layers.relu(layers.elementwise_add(out, short))


def resnet(cfg: ResNetConfig, images):
    """images [N, 3, H, W] -> logits [N, num_classes]. Internal compute
    follows cfg.layout (NHWC default: one stem transpose, channel-minor
    everywhere after)."""
    bottleneck = cfg.depth >= 50
    layout = cfg.layout
    x = images
    if layout == "NHWC":
        x = layers.transpose(x, [0, 2, 3, 1])
    s2d = (
        getattr(cfg, "stem_space_to_depth", False)
        and layout == "NHWC"
        and x.shape[1] % 2 == 0 and x.shape[2] % 2 == 0
    )
    if s2d:
        b, h, w, c = x.shape
        x = layers.reshape(x, [b, h // 2, 2, w // 2, 2, c])
        x = layers.transpose(x, [0, 1, 3, 2, 4, 5])
        x = layers.reshape(x, [b, h // 2, w // 2, 4 * c])
        # 4x4/s1 on the folded grid ≡ 8x8/s2 on the original; pad (2,1)
        # keeps the output aligned with the canonical 7x7/s2 pad-3 stem
        x = _conv_bn(x, cfg.base_filters, 4, stride=1, act="relu",
                     name="stem", layout=layout, padding=[2, 1, 2, 1])
    else:
        x = _conv_bn(x, cfg.base_filters, 7, stride=2, act="relu",
                     name="stem", layout=layout)
    x = layers.pool2d(x, 3, pool_type="max", pool_stride=2, pool_padding=1,
                      data_format=layout)
    filters = cfg.base_filters
    for stage, n_blocks in enumerate(cfg.blocks):
        for b in range(n_blocks):
            stride = 2 if (stage > 0 and b == 0) else 1
            block = _bottleneck if bottleneck else _basic_block
            x = block(x, filters, stride, f"s{stage}.b{b}", layout)
        filters *= 2
    x = layers.pool2d(x, 1, pool_type="avg", global_pooling=True,
                      data_format=layout)
    return layers.fc(x, cfg.num_classes, param_attr=ParamAttr(name="head.w"))


def build_resnet_train_program(cfg, batch, image_size, main_program,
                               startup_program):
    """Classification train program; returns (main, startup, feeds, loss)."""
    from ..fluid import framework

    with framework.program_guard(main_program, startup_program):
        img = layers.data("image", [batch, 3, image_size, image_size],
                          append_batch_size=False)
        label = layers.data("label", [batch, 1], dtype="int64",
                            append_batch_size=False)
        logits = resnet(cfg, img)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    return main_program, startup_program, ["image", "label"], loss


def resnet_step_flops(cfg: ResNetConfig, batch: int, image_size: int) -> float:
    """fwd+bwd FLOPs (3x fwd conv/fc MACs x2) — standard accounting."""
    flops = 0.0
    h = image_size
    # stem
    h = h // 2
    flops += 2 * (7 * 7 * 3) * cfg.base_filters * h * h
    h = h // 2  # maxpool
    cin = cfg.base_filters
    filters = cfg.base_filters
    bottleneck = cfg.depth >= 50
    for stage, n_blocks in enumerate(cfg.blocks):
        for b in range(n_blocks):
            stride = 2 if (stage > 0 and b == 0) else 1
            h_out = h // stride
            if bottleneck:
                flops += 2 * cin * filters * h * h                     # 1x1
                flops += 2 * 9 * filters * filters * h_out * h_out     # 3x3
                flops += 2 * filters * filters * 4 * h_out * h_out     # 1x1
                if stride != 1 or cin != filters * 4:
                    flops += 2 * cin * filters * 4 * h_out * h_out
                cin = filters * 4
            else:
                flops += 2 * 9 * cin * filters * h_out * h_out
                flops += 2 * 9 * filters * filters * h_out * h_out
                if stride != 1 or cin != filters:
                    flops += 2 * cin * filters * h_out * h_out
                cin = filters
            h = h_out
        filters *= 2
    flops += 2 * cin * cfg.num_classes
    return 3.0 * flops * batch  # fwd(1x) + bwd(2x)
