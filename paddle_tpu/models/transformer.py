"""Transformer-base NMT (encoder-decoder) — the BASELINE.md config
mirroring /root/reference/python/paddle/fluid/tests/unittests/
dist_transformer.py (Transformer-base: 6+6 layers, d_model 512, 8 heads,
d_ff 2048, shared target embedding/projection, label smoothing).

TPU-first notes:
- self-attention (encoder and causal decoder) runs the fused op — the
  Pallas flash kernel, with in-kernel causal masking on the decoder side;
- cross-attention (trg queries over src keys) has different q/kv lengths,
  outside the flash kernel's square tiling, so it composes jnp-style ops
  that XLA fuses;
- sinusoid position encodings are build-time constants
  (layers.add_position_encoding);
- static [B, S] shapes; padding handled with additive -1e4 biases.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Optional

from ..fluid import layers
from ..fluid.framework import Program, program_guard
from ..fluid.initializer import ConstantInitializer, NormalInitializer
from ..fluid.param_attr import ParamAttr


@dataclasses.dataclass
class TransformerConfig:
    src_vocab_size: int = 30000
    trg_vocab_size: int = 30000
    d_model: int = 512
    num_heads: int = 8
    d_inner: int = 2048
    n_encoder_layers: int = 6
    n_decoder_layers: int = 6
    dropout: float = 0.1
    label_smooth_eps: float = 0.1
    # scan over layers (fused_encoder_stack / fused_decoder_stack):
    # O(1)-in-depth compile, flash kernels for self- AND cross-attention
    fuse_stack: bool = False

    @staticmethod
    def base() -> "TransformerConfig":
        return TransformerConfig()

    @staticmethod
    def tiny() -> "TransformerConfig":
        return TransformerConfig(
            src_vocab_size=64, trg_vocab_size=64, d_model=32, num_heads=4,
            d_inner=64, n_encoder_layers=2, n_decoder_layers=2)


def _fc3(x, size, pname, act=None):
    return layers.fc(
        x, size, num_flatten_dims=2,
        param_attr=ParamAttr(name=f"{pname}.w_0",
                             initializer=NormalInitializer(0.0, 0.02)),
        bias_attr=ParamAttr(name=f"{pname}.b_0",
                            initializer=ConstantInitializer(0.0)),
        act=act)


def _ln(x, name):
    return layers.layer_norm(
        x, begin_norm_axis=2,
        param_attr=ParamAttr(name=f"{name}_scale"),
        bias_attr=ParamAttr(name=f"{name}_bias"))


def _cross_attention(cfg, q3, kv, kv_bias, name, is_test):
    """Cross-attention (trg queries over src keys) through the fused
    attention op: square q/kv lengths run the Pallas flash kernel with
    the source padding bias as a per-key mask; rectangular lengths take
    the op's jnp composition (XLA-fused)."""
    h = q3.shape[-1]
    q3 = _fc3(q3, h, f"{name}_query_fc")  # learned W_Q (dist_transformer
    k3 = _fc3(kv, h, f"{name}_key_fc")    # __compute_qkv projects q too)
    v3 = _fc3(kv, h, f"{name}_value_fc")
    return layers.fused_multihead_attention(
        q3, k3, v3, kv_bias, num_heads=cfg.num_heads,
        dropout_prob=cfg.dropout, is_test=is_test, causal=False)


def _self_attn_block(cfg, hidden, bias, name, is_test, causal):
    h = hidden.shape[-1]
    q = _fc3(hidden, h, f"{name}_q_fc")
    k = _fc3(hidden, h, f"{name}_k_fc")
    v = _fc3(hidden, h, f"{name}_v_fc")
    ctx = layers.fused_multihead_attention(
        q, k, v, bias, num_heads=cfg.num_heads, dropout_prob=cfg.dropout,
        is_test=is_test, causal=causal)
    out = _fc3(ctx, h, f"{name}_out_fc")
    if not is_test and cfg.dropout > 0:
        out = layers.dropout(out, cfg.dropout,
                             dropout_implementation="upscale_in_train")
    return _ln(layers.elementwise_add(hidden, out), f"{name}_post_ln")


def _ffn_block(cfg, hidden, name, is_test):
    h = hidden.shape[-1]
    inter = _fc3(hidden, cfg.d_inner, f"{name}_ffn_fc0", act="relu")
    out = _fc3(inter, h, f"{name}_ffn_fc1")
    if not is_test and cfg.dropout > 0:
        out = layers.dropout(out, cfg.dropout,
                             dropout_implementation="upscale_in_train")
    return _ln(layers.elementwise_add(hidden, out), f"{name}_ffn_ln")


def _embed(cfg, ids, vocab, emb_name, is_test):
    emb = layers.embedding(
        ids, size=[vocab, cfg.d_model],
        param_attr=ParamAttr(name=emb_name,
                             initializer=NormalInitializer(0.0, 0.02)))
    emb = layers.scale(emb, scale=cfg.d_model ** 0.5)
    emb = layers.add_position_encoding(emb, alpha=1.0, beta=1.0)
    if not is_test and cfg.dropout > 0:
        emb = layers.dropout(emb, cfg.dropout,
                             dropout_implementation="upscale_in_train")
    return emb


def _pad_bias(mask):
    """[B, S] 1/0 mask -> additive [B, 1, 1, S] bias."""
    bias = layers.scale(layers.cast(mask, "float32"), scale=1e4, bias=-1e4)
    return layers.unsqueeze(layers.unsqueeze(bias, [1]), [1])


def _stack_param(helper, name, shape, init=None):
    return helper.create_parameter(
        ParamAttr(name=name, initializer=init or NormalInitializer(0.0, 0.02)),
        shape=shape, dtype="float32")


def _fused_encoder_stack(cfg, hidden, bias, is_test):
    from ..fluid.layer_helper import LayerHelper
    from ..fluid.layers.nn import _rng_salt_counter

    L, h, f = cfg.n_encoder_layers, cfg.d_model, cfg.d_inner
    helper = LayerHelper("fused_encoder_stack")
    ones, zeros = ConstantInitializer(1.0), ConstantInitializer(0.0)
    p = {
        "QKVW": _stack_param(helper, "enc_stack.qkv_w", [L, h, 3 * h]),
        "QKVB": _stack_param(helper, "enc_stack.qkv_b", [L, 3 * h], zeros),
        "OutW": _stack_param(helper, "enc_stack.out_w", [L, h, h]),
        "OutB": _stack_param(helper, "enc_stack.out_b", [L, h], zeros),
        "Ln1S": _stack_param(helper, "enc_stack.ln1_s", [L, h], ones),
        "Ln1B": _stack_param(helper, "enc_stack.ln1_b", [L, h], zeros),
        "FfnW1": _stack_param(helper, "enc_stack.ffn_w1", [L, h, f]),
        "FfnB1": _stack_param(helper, "enc_stack.ffn_b1", [L, f], zeros),
        "FfnW2": _stack_param(helper, "enc_stack.ffn_w2", [L, f, h]),
        "FfnB2": _stack_param(helper, "enc_stack.ffn_b2", [L, h], zeros),
        "Ln2S": _stack_param(helper, "enc_stack.ln2_s", [L, h], ones),
        "Ln2B": _stack_param(helper, "enc_stack.ln2_b", [L, h], zeros),
    }
    out = helper.create_variable_for_type_inference("float32")
    _rng_salt_counter[0] += 1
    helper.append_op(
        type="fused_encoder_stack",
        inputs={"Hidden": [hidden], "AttnBias": [bias],
                **{k: [v] for k, v in p.items()}},
        outputs={"Out": [out]},
        attrs={"num_heads": cfg.num_heads, "act": "relu",
               "dropout_prob": cfg.dropout,
               "attn_dropout_prob": cfg.dropout, "is_test": is_test,
               "use_flash_attention": getattr(cfg, "use_flash", True),
               "rng_salt": _rng_salt_counter[0]},
    )
    return out


def _fused_decoder_stack(cfg, hidden, enc_out, src_bias, is_test):
    from ..fluid.layer_helper import LayerHelper
    from ..fluid.layers.nn import _rng_salt_counter

    L, h, f = cfg.n_decoder_layers, cfg.d_model, cfg.d_inner
    helper = LayerHelper("fused_decoder_stack")
    ones, zeros = ConstantInitializer(1.0), ConstantInitializer(0.0)

    def p_(name, shape, init=None):
        return _stack_param(helper, f"dec_stack.{name}", shape, init)

    p = {
        "SelfQKVW": p_("self_qkv_w", [L, h, 3 * h]),
        "SelfQKVB": p_("self_qkv_b", [L, 3 * h], zeros),
        "SelfOutW": p_("self_out_w", [L, h, h]),
        "SelfOutB": p_("self_out_b", [L, h], zeros),
        "Ln1S": p_("ln1_s", [L, h], ones),
        "Ln1B": p_("ln1_b", [L, h], zeros),
        "CrossQW": p_("cross_q_w", [L, h, h]),
        "CrossQB": p_("cross_q_b", [L, h], zeros),
        "CrossKW": p_("cross_k_w", [L, h, h]),
        "CrossKB": p_("cross_k_b", [L, h], zeros),
        "CrossVW": p_("cross_v_w", [L, h, h]),
        "CrossVB": p_("cross_v_b", [L, h], zeros),
        "CrossOutW": p_("cross_out_w", [L, h, h]),
        "CrossOutB": p_("cross_out_b", [L, h], zeros),
        "Ln2S": p_("ln2_s", [L, h], ones),
        "Ln2B": p_("ln2_b", [L, h], zeros),
        "FfnW1": p_("ffn_w1", [L, h, f]),
        "FfnB1": p_("ffn_b1", [L, f], zeros),
        "FfnW2": p_("ffn_w2", [L, f, h]),
        "FfnB2": p_("ffn_b2", [L, h], zeros),
        "Ln3S": p_("ln3_s", [L, h], ones),
        "Ln3B": p_("ln3_b", [L, h], zeros),
    }
    out = helper.create_variable_for_type_inference("float32")
    _rng_salt_counter[0] += 1
    helper.append_op(
        type="fused_decoder_stack",
        inputs={"Hidden": [hidden], "EncOut": [enc_out],
                "SrcBias": [src_bias], **{k: [v] for k, v in p.items()}},
        outputs={"Out": [out]},
        attrs={"num_heads": cfg.num_heads, "act": "relu",
               "dropout_prob": cfg.dropout,
               "attn_dropout_prob": cfg.dropout, "is_test": is_test,
               "use_flash_attention": getattr(cfg, "use_flash", True),
               "rng_salt": _rng_salt_counter[0]},
    )
    return out


def transformer_encoder(cfg, src_ids, src_mask, is_test=False):
    hidden = _embed(cfg, src_ids, cfg.src_vocab_size, "src_embedding", is_test)
    bias = _pad_bias(src_mask)
    if getattr(cfg, "fuse_stack", False):
        return _fused_encoder_stack(cfg, hidden, bias, is_test), bias
    for i in range(cfg.n_encoder_layers):
        hidden = _self_attn_block(cfg, hidden, bias, f"enc_{i}", is_test,
                                  causal=False)
        hidden = _ffn_block(cfg, hidden, f"enc_{i}", is_test)
    return hidden, bias


def transformer_decoder(cfg, trg_ids, enc_out, src_bias, is_test=False):
    hidden = _embed(cfg, trg_ids, cfg.trg_vocab_size, "trg_embedding", is_test)
    if getattr(cfg, "fuse_stack", False):
        return _fused_decoder_stack(cfg, hidden, enc_out, src_bias, is_test)
    for i in range(cfg.n_decoder_layers):
        hidden = _self_attn_block(cfg, hidden, None, f"dec_{i}", is_test,
                                  causal=True)
        cross = _cross_attention(cfg, hidden, enc_out, src_bias,
                                 f"dec_{i}_cross", is_test)
        cross_out = _fc3(cross, cfg.d_model, f"dec_{i}_cross_out_fc")
        if not is_test and cfg.dropout > 0:
            # residual-path dropout, like every other sublayer
            cross_out = layers.dropout(
                cross_out, cfg.dropout,
                dropout_implementation="upscale_in_train")
        hidden = _ln(layers.elementwise_add(hidden, cross_out),
                     f"dec_{i}_cross_ln")
        hidden = _ffn_block(cfg, hidden, f"dec_{i}", is_test)
    return hidden


def build_transformer_nmt_program(
    cfg: TransformerConfig, batch: int, src_len: int, trg_len: int,
    is_test: bool = False,
    main_program: Optional[Program] = None,
    startup_program: Optional[Program] = None,
):
    """Feeds: src_ids/trg_ids [B, S] int32, src_mask [B, S_src] float32,
    labels [B, S_trg, 1] int32, label_weights [B, S_trg, 1] float32.
    Returns (main, startup, feed_names, loss)."""
    main = main_program or Program()
    startup = startup_program or Program()
    with program_guard(main, startup):
        src_ids = layers.data("src_ids", [batch, src_len], dtype="int32",
                              append_batch_size=False)
        trg_ids = layers.data("trg_ids", [batch, trg_len], dtype="int32",
                              append_batch_size=False)
        src_mask = layers.data("src_mask", [batch, src_len], dtype="float32",
                               append_batch_size=False)
        labels = layers.data("labels", [batch, trg_len, 1], dtype="int32",
                             append_batch_size=False)
        label_weights = layers.data(
            "label_weights", [batch, trg_len, 1], dtype="float32",
            append_batch_size=False)

        enc_out, src_bias = transformer_encoder(cfg, src_ids, src_mask, is_test)
        dec_out = transformer_decoder(cfg, trg_ids, enc_out, src_bias, is_test)
        # shared target embedding as the output projection (weight tying);
        # logits STAY flat [B*St, V] end-to-end — reshaping to [B, St, V]
        # forces a ~1GB layout copy of the largest tensor in the model
        trg_emb = main.global_block().var("trg_embedding")
        flat = layers.reshape(dec_out, [batch * trg_len, cfg.d_model])
        logits = layers.matmul(flat, trg_emb, transpose_y=True)
        labels_flat = layers.reshape(labels, [batch * trg_len, 1])
        weights_flat = layers.reshape(label_weights, [batch * trg_len, 1])

        # analytic label smoothing: with y_sm = (1-eps)*onehot + eps/K,
        # CE(y_sm) = (1-eps)*CE_hard + eps*(logsumexp - mean(logits)).
        # Same value as label_smooth + soft-label CE, WITHOUT the [B*St,
        # 30000] one-hot materialization (multi-GB of HBM traffic/step).
        eps_ls = float(cfg.label_smooth_eps)
        ce_hard = layers.softmax_with_cross_entropy(logits, labels_flat)
        if eps_ls > 0.0:
            mx = layers.reduce_max(logits, dim=-1, keep_dim=True)
            lse = layers.elementwise_add(
                layers.log(layers.reduce_sum(
                    layers.exp(layers.elementwise_sub(logits, mx)),
                    dim=-1, keep_dim=True)),
                mx)
            uniform_ce = layers.elementwise_sub(
                lse, layers.reduce_mean(logits, dim=-1, keep_dim=True))
            ce = layers.elementwise_add(
                layers.scale(ce_hard, scale=1.0 - eps_ls),
                layers.scale(uniform_ce, scale=eps_ls))
        else:
            ce = ce_hard
        ce = layers.elementwise_mul(ce, weights_flat)
        denom = layers.elementwise_add(
            layers.reduce_sum(label_weights),
            layers.fill_constant([1], "float32", 1e-6))
        loss = layers.elementwise_div(layers.reduce_sum(ce), denom)
    feeds = ["src_ids", "trg_ids", "src_mask", "labels", "label_weights"]
    return main, startup, feeds, loss


def transformer_step_flops(cfg: TransformerConfig, batch, src_len, trg_len):
    """fwd+bwd matmul FLOPs per step (6N per active-token parameter) +
    attention score/context terms. Cross-attention K/V projections run
    over SRC tokens; q/out projections run over TRG tokens."""
    h, f = cfg.d_model, cfg.d_inner
    ld = cfg.n_decoder_layers
    # per src token: encoder qkv+out+ffn, plus decoder cross K/V proj
    enc_tok = (6 * cfg.n_encoder_layers * (4 * h * h + 2 * h * f)
               + 12 * cfg.n_encoder_layers * src_len * h
               + 6 * ld * (2 * h * h))
    # per trg token: decoder self qkv+out, cross q+out, ffn, vocab proj,
    # self-attn over trg_len + cross-attn over src_len
    dec_tok = (6 * ld * (4 * h * h + 2 * h * h + 2 * h * f)
               + 6 * cfg.trg_vocab_size * h
               + 12 * ld * (trg_len + src_len) * h)
    return batch * (src_len * enc_tok + trg_len * dec_tok)


def random_nmt_batch(cfg: TransformerConfig, batch, src_len, trg_len, seed=0):
    import numpy as np

    rng = np.random.RandomState(seed)
    return {
        "src_ids": rng.randint(0, cfg.src_vocab_size,
                               (batch, src_len)).astype(np.int32),
        "trg_ids": rng.randint(0, cfg.trg_vocab_size,
                               (batch, trg_len)).astype(np.int32),
        "src_mask": np.ones((batch, src_len), np.float32),
        "labels": rng.randint(0, cfg.trg_vocab_size,
                              (batch, trg_len, 1)).astype(np.int32),
        "label_weights": np.ones((batch, trg_len, 1), np.float32),
    }
