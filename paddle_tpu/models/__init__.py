"""Model zoo built on the paddle_tpu static-graph API.

Mirrors the reference's model coverage (tests/book/ classic models,
dist_transformer.py, BERT/ERNIE encoder layers backed by
multihead_matmul_fuse_pass.cc / bert_encoder_functor.cu) — here the models
are first-class builders emitting Programs that the XLA executor compiles
whole, so the "fusion passes" of the reference are unnecessary: XLA +
Pallas attention give the fused kernels directly.
"""
from . import bert  # noqa: F401
from .bert import BertConfig, build_bert_pretrain_program  # noqa: F401
from . import resnet  # noqa: F401
from . import transformer  # noqa: F401
from .transformer import TransformerConfig, build_transformer_nmt_program  # noqa: F401
