"""BERT/ERNIE-base encoder + pretraining heads (flagship model).

Capability parity: the reference supports BERT-class encoders through its
layer DSL and fuses them with `multihead_matmul_fuse_pass.cc` /
`embedding_eltwise_layernorm_fuse_pass.cc` / `bert_encoder_functor.cu`
(see /root/reference/paddle/fluid/framework/ir/multihead_matmul_fuse_pass.cc).
Here the whole encoder is one XLA program, so those fusions are automatic;
the attention core additionally lowers to a Pallas flash-attention kernel
on TPU (ops/pallas/flash_attention.py) via the `fused_multihead_attention`
op when `config.use_flash_attention` is set.

TPU-first design notes:
- static shapes everywhere (batch, seq_len fixed at build time) so XLA
  tiles matmuls onto the MXU;
- masked-LM gather is a flat `gather` (XLA dynamic-gather) instead of the
  reference's LoD machinery;
- bf16 compute comes from the AMP rewriter (contrib/mixed_precision),
  not hand-inserted casts.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Optional

from ..fluid import layers
from ..fluid.framework import Program, program_guard
from ..fluid.initializer import ConstantInitializer, TruncatedNormalInitializer
from ..fluid.param_attr import ParamAttr


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    initializer_range: float = 0.02
    use_flash_attention: bool = True
    # recompute the FFN inter activation in backward (memory for FLOPs):
    # unlocks larger global batches on HBM-bound configs
    remat_ffn: bool = False
    remat_qkv: bool = False  # recompute q/k/v projections in backward
    remat_layer: bool = False  # save only per-layer hidden (more FLOPs)
    # checkpoint-policy remat (fuse_stack only): comma-separated
    # checkpoint_name tags to SAVE per layer; everything else is
    # recomputed. "flash" = the attention kernel's (o, lse) residuals —
    # the backward then skips the forward kernel re-run that full-layer
    # remat pays, while dropping the q/k/v stash. Long-context default.
    remat_policy: str = ""
    # scan over stacked layer params (fused_encoder_stack op): O(1)-in-depth
    # compile time; param names become encoder_stack.* instead of per-layer
    fuse_stack: bool = False
    # Mixture-of-Experts FFN (ops/moe_ops.py): >0 replaces every dense FFN
    # with a moe_ffn of that many experts; shard over "ep" via
    # DistributedStrategy.expert_parallel. Incompatible with fuse_stack
    # (per-layer routers can't be scanned over stacked params yet).
    moe_num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01

    @staticmethod
    def base() -> "BertConfig":
        return BertConfig()

    @staticmethod
    def tiny() -> "BertConfig":
        """For tests / dryruns."""
        return BertConfig(
            vocab_size=128,
            hidden_size=32,
            num_hidden_layers=2,
            num_attention_heads=4,
            intermediate_size=64,
            max_position_embeddings=64,
        )


def _winit(cfg):
    return ParamAttr(initializer=TruncatedNormalInitializer(scale=cfg.initializer_range))


def encoder_layer(cfg: BertConfig, hidden, attn_bias, name: str, is_test: bool):
    """One post-LN transformer block: MHA + FFN, residuals, layer_norm.

    hidden: [B, S, H]; attn_bias: [B, 1, 1, S] additive (-1e4 * (1-mask)).
    """
    b, s, h = hidden.shape
    nh = cfg.num_attention_heads
    dh = h // nh

    def _fc3(x, size, pname, act=None):
        return layers.fc(
            x,
            size,
            num_flatten_dims=2,
            param_attr=ParamAttr(
                name=f"{pname}.w_0",
                initializer=TruncatedNormalInitializer(scale=cfg.initializer_range),
            ),
            bias_attr=ParamAttr(name=f"{pname}.b_0", initializer=ConstantInitializer(0.0)),
            act=act,
        )

    q = _fc3(hidden, h, f"{name}_query_fc")
    k = _fc3(hidden, h, f"{name}_key_fc")
    v = _fc3(hidden, h, f"{name}_value_fc")

    if cfg.use_flash_attention:
        ctx_layer = layers.fused_multihead_attention(
            q, k, v, attn_bias, num_heads=nh,
            dropout_prob=cfg.attention_probs_dropout_prob, is_test=is_test,
        )
    else:
        # reshape to [B, nh, S, dh]
        def _split_heads(x):
            x = layers.reshape(x, [b, s, nh, dh])
            return layers.transpose(x, [0, 2, 1, 3])

        q, k, v = _split_heads(q), _split_heads(k), _split_heads(v)
        scores = layers.matmul(q, k, transpose_y=True, alpha=1.0 / math.sqrt(dh))
        scores = layers.elementwise_add(scores, attn_bias)
        probs = layers.softmax(scores, axis=-1)
        if not is_test and cfg.attention_probs_dropout_prob > 0:
            probs = layers.dropout(
                probs,
                cfg.attention_probs_dropout_prob,
                dropout_implementation="upscale_in_train",
            )
        ctx_layer = layers.matmul(probs, v)
        ctx_layer = layers.transpose(ctx_layer, [0, 2, 1, 3])
        ctx_layer = layers.reshape(ctx_layer, [b, s, h])

    attn_out = _fc3(ctx_layer, h, f"{name}_output_fc")
    if not is_test and cfg.hidden_dropout_prob > 0:
        attn_out = layers.dropout(
            attn_out, cfg.hidden_dropout_prob, dropout_implementation="upscale_in_train"
        )
    attn_out = layers.layer_norm(
        layers.elementwise_add(hidden, attn_out),
        begin_norm_axis=2,
        param_attr=ParamAttr(name=f"{name}_post_att_ln_scale"),
        bias_attr=ParamAttr(name=f"{name}_post_att_ln_bias"),
    )

    if cfg.moe_num_experts > 0:
        ffn_out, _aux = layers.moe_ffn(
            attn_out,
            num_experts=cfg.moe_num_experts,
            expert_hidden=cfg.intermediate_size,
            top_k=cfg.moe_top_k,
            capacity_factor=cfg.moe_capacity_factor,
            act=cfg.hidden_act,
            param_attr=ParamAttr(initializer=_winit(cfg).initializer),
            name=f"{name}_moe",
        )
    else:
        inter = _fc3(attn_out, cfg.intermediate_size, f"{name}_ffn_fc_0", act=cfg.hidden_act)
        ffn_out = _fc3(inter, h, f"{name}_ffn_fc_1")
    if not is_test and cfg.hidden_dropout_prob > 0:
        ffn_out = layers.dropout(
            ffn_out, cfg.hidden_dropout_prob, dropout_implementation="upscale_in_train"
        )
    return layers.layer_norm(
        layers.elementwise_add(attn_out, ffn_out),
        begin_norm_axis=2,
        param_attr=ParamAttr(name=f"{name}_post_ffn_ln_scale"),
        bias_attr=ParamAttr(name=f"{name}_post_ffn_ln_bias"),
    )


def bert_encoder(
    cfg: BertConfig,
    input_ids,
    token_type_ids,
    position_ids,
    input_mask,
    is_test: bool = False,
):
    """Embeddings + transformer stack. Returns (sequence_output [B,S,H])."""
    emb = layers.embedding(
        input_ids,
        size=[cfg.vocab_size, cfg.hidden_size],
        param_attr=ParamAttr(name="word_embedding", initializer=_winit(cfg).initializer),
    )
    pos_emb = layers.embedding(
        position_ids,
        size=[cfg.max_position_embeddings, cfg.hidden_size],
        param_attr=ParamAttr(name="pos_embedding", initializer=_winit(cfg).initializer),
    )
    type_emb = layers.embedding(
        token_type_ids,
        size=[cfg.type_vocab_size, cfg.hidden_size],
        param_attr=ParamAttr(name="sent_embedding", initializer=_winit(cfg).initializer),
    )
    emb = layers.elementwise_add(layers.elementwise_add(emb, pos_emb), type_emb)
    emb = layers.layer_norm(
        emb,
        begin_norm_axis=2,
        param_attr=ParamAttr(name="pre_encoder_ln_scale"),
        bias_attr=ParamAttr(name="pre_encoder_ln_bias"),
    )
    if not is_test and cfg.hidden_dropout_prob > 0:
        emb = layers.dropout(
            emb, cfg.hidden_dropout_prob, dropout_implementation="upscale_in_train"
        )

    # additive attention bias [B, 1, 1, S]: 0 where attend, -1e4 where pad
    mask_f = layers.cast(input_mask, "float32")
    attn_bias = layers.scale(mask_f, scale=1e4, bias=-1e4)  # 1e4*(mask-1)
    attn_bias = layers.unsqueeze(layers.unsqueeze(attn_bias, [1]), [1])  # [B,1,1,S]

    if cfg.fuse_stack:
        if cfg.moe_num_experts > 0:
            raise ValueError(
                "fuse_stack + moe_num_experts: the scanned stack cannot hold "
                "per-layer MoE routers yet; set fuse_stack=False for MoE"
            )
        return _encoder_stack(cfg, emb, attn_bias, is_test)
    hidden = emb
    for i in range(cfg.num_hidden_layers):
        hidden = encoder_layer(cfg, hidden, attn_bias, f"encoder_layer_{i}", is_test)
    return hidden


def _encoder_stack(cfg: BertConfig, hidden, attn_bias, is_test: bool):
    """Scan-based stack (ops/encoder_stack.py): stacked [L, ...] params."""
    from ..fluid.layer_helper import LayerHelper
    from ..fluid.layers.nn import _rng_salt_counter

    L, h, f = cfg.num_hidden_layers, cfg.hidden_size, cfg.intermediate_size
    helper = LayerHelper("fused_encoder_stack")

    def param(name, shape, init=None):
        return helper.create_parameter(
            ParamAttr(
                name=f"encoder_stack.{name}",
                initializer=init or TruncatedNormalInitializer(scale=cfg.initializer_range),
            ),
            shape=shape,
            dtype="float32",
        )

    ones = ConstantInitializer(1.0)
    zeros = ConstantInitializer(0.0)
    p = {
        "QKVW": param("qkv_w", [L, h, 3 * h]),
        "QKVB": param("qkv_b", [L, 3 * h], zeros),
        "OutW": param("out_w", [L, h, h]),
        "OutB": param("out_b", [L, h], zeros),
        "Ln1S": param("ln1_scale", [L, h], ones),
        "Ln1B": param("ln1_bias", [L, h], zeros),
        "FfnW1": param("ffn_w1", [L, h, f]),
        "FfnB1": param("ffn_b1", [L, f], zeros),
        "FfnW2": param("ffn_w2", [L, f, h]),
        "FfnB2": param("ffn_b2", [L, h], zeros),
        "Ln2S": param("ln2_scale", [L, h], ones),
        "Ln2B": param("ln2_bias", [L, h], zeros),
    }
    out = helper.create_variable_for_type_inference("float32")
    _rng_salt_counter[0] += 1
    helper.append_op(
        type="fused_encoder_stack",
        inputs={"Hidden": [hidden], "AttnBias": [attn_bias], **{k: [v] for k, v in p.items()}},
        outputs={"Out": [out]},
        attrs={
            "num_heads": cfg.num_attention_heads,
            "act": cfg.hidden_act,
            "dropout_prob": cfg.hidden_dropout_prob,
            "attn_dropout_prob": cfg.attention_probs_dropout_prob,
            "is_test": is_test,
            "use_flash_attention": cfg.use_flash_attention,
            "remat_ffn": cfg.remat_ffn,
            "remat_qkv": getattr(cfg, "remat_qkv", False),
            "remat_layer": getattr(cfg, "remat_layer", False),
            "remat_policy": getattr(cfg, "remat_policy", ""),
            "rng_salt": _rng_salt_counter[0],
        },
    )
    return out


def bert_pooler(cfg: BertConfig, sequence_output):
    """tanh FC over the [CLS] (first) token."""
    b, s, h = sequence_output.shape
    first = layers.slice(sequence_output, axes=[1], starts=[0], ends=[1])
    first = layers.reshape(first, [b, h])
    return layers.fc(
        first,
        h,
        param_attr=ParamAttr(name="pooled_fc.w_0", initializer=_winit(cfg).initializer),
        bias_attr=ParamAttr(name="pooled_fc.b_0"),
        act="tanh",
    )


def build_bert_pretrain_program(
    cfg: BertConfig,
    batch_size: int,
    seq_len: int,
    max_preds: int,
    is_test: bool = False,
    main_program: Optional[Program] = None,
    startup_program: Optional[Program] = None,
):
    """Full MLM + NSP pretraining graph (static shapes, TPU-friendly).

    Returns (main_program, startup_program, feed_names, loss_var).
    Feeds: input_ids/token_type_ids/position_ids [B,S] int64 (oops int32),
    input_mask [B,S] float32, mask_positions [B*max_preds] int32 (flat
    indices into [B*S]), mask_labels [B*max_preds] int32,
    mask_weights [B*max_preds] float32, nsp_labels [B] int32.
    """
    main = main_program or Program()
    startup = startup_program or Program()
    with program_guard(main, startup):
        def data(name, shape, dtype):
            return layers.data(name, shape=shape, dtype=dtype, append_batch_size=False)

        input_ids = data("input_ids", [batch_size, seq_len], "int32")
        token_type_ids = data("token_type_ids", [batch_size, seq_len], "int32")
        position_ids = data("position_ids", [batch_size, seq_len], "int32")
        input_mask = data("input_mask", [batch_size, seq_len], "float32")
        mask_positions = data("mask_positions", [batch_size * max_preds], "int32")
        mask_labels = data("mask_labels", [batch_size * max_preds, 1], "int32")
        mask_weights = data("mask_weights", [batch_size * max_preds, 1], "float32")
        nsp_labels = data("nsp_labels", [batch_size, 1], "int32")

        seq_out = bert_encoder(
            cfg, input_ids, token_type_ids, position_ids, input_mask, is_test=is_test
        )
        pooled = bert_pooler(cfg, seq_out)

        # ---- masked LM head (tied to word embedding, transform + bias) ----
        flat = layers.reshape(seq_out, [batch_size * seq_len, cfg.hidden_size])
        picked = layers.gather(flat, mask_positions)  # [B*max_preds, H]
        trans = layers.fc(
            picked,
            cfg.hidden_size,
            param_attr=ParamAttr(
                name="mask_lm_trans_fc.w_0", initializer=_winit(cfg).initializer
            ),
            bias_attr=ParamAttr(name="mask_lm_trans_fc.b_0"),
            act=cfg.hidden_act,
        )
        trans = layers.layer_norm(
            trans,
            begin_norm_axis=1,
            param_attr=ParamAttr(name="mask_lm_trans_ln_scale"),
            bias_attr=ParamAttr(name="mask_lm_trans_ln_bias"),
        )
        word_emb = main.global_block().var("word_embedding")
        logits = layers.matmul(trans, word_emb, transpose_y=True)  # [B*mp, V]
        out_bias = layers.create_parameter(
            shape=[cfg.vocab_size],
            dtype="float32",
            name="mask_lm_out_fc.b_0",
            default_initializer=ConstantInitializer(0.0),
        )
        logits = layers.elementwise_add(logits, out_bias)
        mlm_loss = layers.softmax_with_cross_entropy(logits, mask_labels)
        mlm_loss = layers.elementwise_mul(mlm_loss, mask_weights)
        denom = layers.reduce_sum(mask_weights)
        denom = layers.elementwise_add(
            denom, layers.fill_constant(shape=[1], dtype="float32", value=1e-5)
        )
        mlm_loss = layers.elementwise_div(layers.reduce_sum(mlm_loss), denom)

        # ---- next-sentence head ----
        nsp_logits = layers.fc(
            pooled,
            2,
            param_attr=ParamAttr(
                name="next_sent_fc.w_0", initializer=_winit(cfg).initializer
            ),
            bias_attr=ParamAttr(name="next_sent_fc.b_0"),
        )
        nsp_loss = layers.reduce_mean(
            layers.softmax_with_cross_entropy(nsp_logits, nsp_labels)
        )
        loss = layers.elementwise_add(mlm_loss, nsp_loss)

        # ---- MoE load-balancing auxiliary losses (if any moe_ffn ops) ----
        aux_names = [
            n
            for op in main.global_block().ops
            if op.type == "moe_ffn"
            for n in op.outputs.get("AuxLoss", [])
        ]
        if aux_names:
            aux_vars = [main.global_block().var(n) for n in aux_names]
            aux_total = aux_vars[0]
            for a in aux_vars[1:]:
                aux_total = layers.elementwise_add(aux_total, a)
            loss = layers.elementwise_add(
                loss, layers.scale(aux_total, scale=cfg.moe_aux_weight)
            )

    feed_names = [
        "input_ids",
        "token_type_ids",
        "position_ids",
        "input_mask",
        "mask_positions",
        "mask_labels",
        "mask_weights",
        "nsp_labels",
    ]
    return main, startup, feed_names, loss


def tensor_parallel_rules():
    """Megatron-style PartitionSpec rules for the encoder parameters:
    QKV and FFN-in are column-parallel (shard output dim on "tp"), the
    attention-output and FFN-out projections are row-parallel, and the
    word embedding is vocab-sharded. XLA SPMD inserts the all-reduces the
    reference would have needed explicit ops for — and tensor parallelism
    itself is a capability the 2020 reference lacks (SURVEY.md §2.5)."""
    col_w = (None, "tp")
    row_w = ("tp", None)
    return [
        (r"_(query|key|value)_fc\.w_0$", col_w),
        (r"_(query|key|value)_fc\.b_0$", ("tp",)),
        (r"_output_fc\.w_0$", row_w),
        (r"_ffn_fc_0\.w_0$", col_w),
        (r"_ffn_fc_0\.b_0$", ("tp",)),
        (r"_ffn_fc_1\.w_0$", row_w),
        (r"^word_embedding$", row_w),  # vocab-sharded
    ]


def random_pretrain_batch(cfg: BertConfig, batch_size: int, seq_len: int, max_preds: int, seed: int = 0):
    """Synthetic data batch for benchmarking / tests."""
    import numpy as np

    rng = np.random.RandomState(seed)
    b, s, mp = batch_size, seq_len, max_preds
    pos = np.tile(np.arange(s, dtype=np.int32), (b, 1))
    mask_pos = (
        np.tile(rng.permutation(s)[:mp], (b, 1))
        + (np.arange(b) * s)[:, None]
    ).astype(np.int32)
    return {
        "input_ids": rng.randint(0, cfg.vocab_size, (b, s)).astype(np.int32),
        "token_type_ids": (rng.rand(b, s) > 0.5).astype(np.int32),
        "position_ids": pos,
        "input_mask": np.ones((b, s), np.float32),
        "mask_positions": mask_pos.reshape(-1),
        "mask_labels": rng.randint(0, cfg.vocab_size, (b * mp, 1)).astype(np.int32),
        "mask_weights": np.ones((b * mp, 1), np.float32),
        "nsp_labels": rng.randint(0, 2, (b, 1)).astype(np.int32),
    }
