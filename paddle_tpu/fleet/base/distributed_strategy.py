"""DistributedStrategy: structured distributed-training config.

Parity: /root/reference/python/paddle/fleet/base/distributed_strategy.py
wrapping framework/distributed_strategy.proto:95-130. The reference's
fields (amp, recompute, gradient_merge, localsgd, dgc, pipeline,
nccl_comm_num, hierarchical_allreduce...) are kept where meaningful;
NCCL-topology knobs become mesh-axis knobs (XLA owns the rings). New
TPU-era fields: mesh_axes, tensor_parallel, sequence_parallel.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple


class DistributedStrategy:
    def __init__(self):
        # --- parity fields (reference distributed_strategy.proto) ---
        self.amp: bool = False
        self.amp_configs: Dict = {}
        self.recompute: bool = False
        self.recompute_configs: Dict = {"checkpoints": []}
        self.gradient_merge: bool = False
        self.gradient_merge_configs: Dict = {"k_steps": 1, "avg": True}
        self.pipeline: bool = False
        self.pipeline_configs: Dict = {"accumulate_steps": 1}
        # localsgd needs per-worker divergent weights, which the GSPMD
        # executor (replicated params) cannot express yet: setting it makes
        # minimize raise. dgc targets SLOW interconnects: over single-slice
        # TPU ICI it stays rejected, but with hybrid_dcn >= 2 (multi-slice
        # mesh with an outer DCN axis) it compresses the cross-slice
        # gradient exchange (reference details/sparse_all_reduce_op_handle.cc
        # -> top-k + error feedback over the "dcn" axis here). elastic is a
        # dead flag in the reference too. None of these is silently
        # ignored — fleet.minimize rejects unsupported combinations.
        self.localsgd: bool = False
        self.localsgd_configs: Dict = {"k_steps": 1}
        self.dgc: bool = False
        self.dgc_configs: Dict = {"rampup_begin_step": 0, "sparsity": 0.999}
        # multi-slice: number of slices on the outer (DCN) mesh axis; the
        # inner axis stays "dp" over ICI. >= 2 activates the manual
        # two-level gradient sync (dense over dp, dense-or-DGC over dcn)
        self.hybrid_dcn: int = 0
        # lamb/lars swap the inner optimizer (reference meta-optimizer chain)
        self.lars: bool = False
        self.lars_configs: Dict = {}
        self.lamb: bool = False
        self.lamb_configs: Dict = {}
        # ZeRO-2 analog: shard optimizer moments over "dp" (memory / dp)
        self.sharding: bool = False
        self.sharding_configs: Dict = {}
        self.elastic: bool = False
        self.auto: bool = False
        # legacy NCCL knobs accepted but inert (XLA owns collectives)
        self.nccl_comm_num: int = 1
        self.hierarchical_allreduce_inter_nranks: int = 1
        self.sync_nccl_allreduce: bool = True
        self.fuse_grad_size_in_MB: int = 32
        # --- TPU-era extensions ---
        # ordered mesh axes, e.g. {"dp": -1} or {"dp": 2, "tp": 4}
        self.mesh_axes: Dict[str, int] = {}
        self.mesh = None  # pre-built jax.sharding.Mesh (wins over mesh_axes)
        self.tensor_parallel: bool = False
        # [(param-name regex, PartitionSpec tuple)]
        self.tensor_parallel_rules: List[Tuple[str, tuple]] = []
        self.sequence_parallel: bool = False
        # shard moe_ffn expert weights over the "ep" mesh axis (GSPMD
        # inserts the dispatch/combine all-to-alls); see ops/moe_ops.py
        self.expert_parallel: bool = False

    def __repr__(self):
        on = [
            k for k, v in vars(self).items()
            if isinstance(v, bool) and v
        ]
        return f"DistributedStrategy(enabled={on}, mesh_axes={self.mesh_axes})"
