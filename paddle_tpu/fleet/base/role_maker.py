"""Role makers: rank/endpoint discovery.

Parity: /root/reference/python/paddle/fleet/base/role_maker.py. On TPU the
coordination service replaces Gloo/MPI; these classes keep the env-var
protocol (PaddleCloud convention) so launch scripts port unchanged.
"""
from __future__ import annotations

import os


class RoleMakerBase:
    def worker_index(self) -> int:
        from ..  import worker_index

        return worker_index()

    def worker_num(self) -> int:
        from .. import worker_num

        return worker_num()

    def is_worker(self) -> bool:
        return True

    def is_server(self) -> bool:
        return False

    def is_first_worker(self) -> bool:
        return self.worker_index() == 0


class PaddleCloudRoleMaker(RoleMakerBase):
    """Reads the PaddleCloud env protocol: PADDLE_TRAINER_ID /
    PADDLE_TRAINER_ENDPOINTS for workers, and the server role via
    TRAINING_ROLE=PSERVER + PADDLE_PORT/PADDLE_PSERVERS (the reference's
    parameter-server convention; here a server process runs the host
    embedding service, distributed/ps.py)."""

    def __init__(self, is_collective: bool = True):
        self.is_collective = is_collective

    def worker_index(self) -> int:
        return int(os.environ.get("PADDLE_TRAINER_ID", 0))

    def worker_num(self) -> int:
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return len(eps.split(",")) if eps else 1

    def is_worker(self) -> bool:
        return os.environ.get("TRAINING_ROLE", "TRAINER").upper() == "TRAINER"

    def is_server(self) -> bool:
        return os.environ.get("TRAINING_ROLE", "").upper() == "PSERVER"

    def server_index(self) -> int:
        return int(os.environ.get("PADDLE_PSERVER_ID", 0))

    def server_num(self) -> int:
        return len(self.get_pserver_endpoints())

    def get_pserver_endpoints(self):
        eps = os.environ.get("PADDLE_PSERVERS", "")
        return [e.strip() for e in eps.split(",") if e.strip()]


class UserDefinedRoleMaker(RoleMakerBase):
    def __init__(self, current_id: int = 0, worker_num: int = 1, role=None,
                 worker_endpoints=None, server_endpoints=None):
        self._id = current_id
        self._num = worker_num
        self._role = role
        self._server_eps = list(server_endpoints or [])

    def worker_index(self) -> int:
        return self._id

    def worker_num(self) -> int:
        return self._num

    def is_server(self) -> bool:
        return str(self._role).upper() in ("SERVER", "PSERVER", "ROLE.SERVER")

    def is_worker(self) -> bool:
        return not self.is_server()

    def get_pserver_endpoints(self):
        return list(self._server_eps)
