"""Role makers: rank/endpoint discovery.

Parity: /root/reference/python/paddle/fleet/base/role_maker.py. On TPU the
coordination service replaces Gloo/MPI; these classes keep the env-var
protocol (PaddleCloud convention) so launch scripts port unchanged.
"""
from __future__ import annotations

import os


class RoleMakerBase:
    def worker_index(self) -> int:
        from ..  import worker_index

        return worker_index()

    def worker_num(self) -> int:
        from .. import worker_num

        return worker_num()

    def is_worker(self) -> bool:
        return True

    def is_server(self) -> bool:
        return False

    def is_first_worker(self) -> bool:
        return self.worker_index() == 0


class PaddleCloudRoleMaker(RoleMakerBase):
    """Reads PADDLE_TRAINER_ID / PADDLE_TRAINER_ENDPOINTS env."""

    def __init__(self, is_collective: bool = True):
        self.is_collective = is_collective

    def worker_index(self) -> int:
        return int(os.environ.get("PADDLE_TRAINER_ID", 0))

    def worker_num(self) -> int:
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return len(eps.split(",")) if eps else 1


class UserDefinedRoleMaker(RoleMakerBase):
    def __init__(self, current_id: int = 0, worker_num: int = 1, role=None, worker_endpoints=None):
        self._id = current_id
        self._num = worker_num

    def worker_index(self) -> int:
        return self._id

    def worker_num(self) -> int:
        return self._num
