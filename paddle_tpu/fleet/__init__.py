"""Fleet 2.0-style distributed API.

Parity surface: /root/reference/python/paddle/fleet/base/fleet_base.py
(init:25, distributed_optimizer:213, minimize:234) and
DistributedStrategy (distributed_strategy.py wrapping
framework/distributed_strategy.proto:95-130).

TPU-native behavior: instead of a meta-optimizer chain that rewrites the
program with NCCL ops, `distributed_optimizer(...).minimize(loss)` builds
the backward + update ops normally and then attaches a device Mesh plus
PartitionSpec annotations (dp/tp/sp axes) to the program; the Executor
jits the step over the mesh and XLA SPMD inserts the collectives. Tensor
parallel and sequence parallel are therefore *new* capabilities the
reference lacks, exposed through the same strategy surface.
"""
from __future__ import annotations

from typing import Optional

from .base.distributed_strategy import DistributedStrategy  # noqa: F401
from .base.role_maker import PaddleCloudRoleMaker, UserDefinedRoleMaker  # noqa: F401
from . import metrics  # noqa: F401  (reference paddle.fleet.metrics)

from .. import parallel as _parallel
from ..parallel import create_mesh, set_var_sharding
from ..parallel.env import get_rank, get_world_size, init_parallel_env

_fleet_state = {"initialized": False, "role_maker": None, "strategy": None}


def init(role_maker=None, is_collective: bool = True, strategy: Optional[DistributedStrategy] = None):
    init_parallel_env()
    _fleet_state.update(
        initialized=True, role_maker=role_maker, strategy=strategy or DistributedStrategy()
    )


def is_first_worker() -> bool:
    return get_rank() == 0


# -- PS-role lifecycle (reference fleet_base.py:235-249) -------------------


def init_worker() -> None:
    """Trainer-side PS bootstrap (reference fleet_base.init_worker).
    RemoteTable clients connect lazily on create_table, so this only
    bootstraps the coordination env; kept for API parity — launched
    trainer scripts can call it unconditionally."""
    init_parallel_env()


def init_server(model_dir: Optional[str] = None,
                snapshot_dir: Optional[str] = None,
                snapshot_secs: Optional[float] = None, **kwargs) -> None:
    """Server-side init (reference fleet_base.init_server): record the
    checkpoint directory whose `<table>.pkl` state_dicts preload each
    table on first creation (saved via `ps.get_table(n).state_dict()`).

    snapshot_secs > 0 makes run_server() checkpoint every table
    atomically on that interval (ps_server.PSServer.snapshot), into
    snapshot_dir — defaulting to model_dir, so a crashed-and-restarted
    server resumes from its own latest snapshot through the same preload
    path (bounded-staleness recovery; env fallbacks:
    PADDLE_PS_SNAPSHOT_DIR / PADDLE_PS_SNAPSHOT_SECS).

    Cross-job adoption: each snapshot dir carries a `manifest.json`
    (snapshot epoch, trainer-group generation, table geometries) written
    atomically AFTER the table pickles. Point a NEW job's model_dir — or
    its launcher's stable PADDLE_PS_SNAPSHOT_DIR — at a previous job's
    snapshot dir and the tables are adopted automatically, the way this
    manual init_server(model_dir) contract always worked; inspect what
    will be adopted with fleet.ps_snapshot_manifest(dir)."""
    _fleet_state["ps_model_dir"] = model_dir
    _fleet_state["ps_snapshot_dir"] = snapshot_dir or model_dir
    _fleet_state["ps_snapshot_secs"] = snapshot_secs


def membership() -> Optional[dict]:
    """The job control plane's membership table (ISSUE 8): epoch, world
    size, and each member's lease state, straight from the launcher's
    coordinator (PADDLE_COORDINATOR_ENDPOINT). None when no control
    plane is armed — single-process runs and lease-less launches."""
    from ..distributed import coordinator

    return coordinator.query_membership()


def ps_snapshot_manifest(dirname: str) -> Optional[dict]:
    """Parsed manifest.json of a PS snapshot directory (snapshot epoch,
    generation, tables), or None for absent/pre-manifest dirs."""
    from ..distributed.ps_server import read_snapshot_manifest

    return read_snapshot_manifest(dirname)


def ps_stats(table_name: Optional[str] = None) -> dict:
    """PS data-plane telemetry through the idempotent `stats` verb
    (ISSUE 4): per-verb latency summaries, retry / replay-dedup
    counters and bytes in/out from each pserver process, plus per-table
    traffic counters. Replicated tables (PADDLE_PS_REPLICATION > 1) add
    a "replication" section — factor plus each partition's replica
    roles, epochs, last-applied seqs and lag (ISSUE 7), the same view
    debugz /statusz serves as ps_replication. Every table also carries
    a "memory" section (ISSUE 11): per-partition resident bytes
    (rows x row width + optimizer accumulators + the replication log
    ring) — the capacity-planning signal /statusz serves as ps_memory.

    table_name names one registered table; None reports every table
    this process created. Hosted tables (RemoteTable) fan the verb out
    to their pservers; in-process tables report their local counters.
    Returns {table_name: stats_dict}."""
    from ..distributed import ps

    names = [table_name] if table_name else sorted(ps._tables)
    out = {}
    for n in names:
        t = ps.get_table(n)
        # GeoSGDClient wraps either table kind: unwrap to whatever can
        # actually report (RemoteTable.stats or the local counters)
        target = t if hasattr(t, "stats") else getattr(t, "server", t)
        if hasattr(target, "stats"):
            out[n] = target.stats()
        else:  # in-process ShardedHostTable
            mem = target.memory_stats()
            out[n] = {"push_calls": target.push_calls,
                      "pushed_bytes": target.pushed_bytes,
                      "servers": [],
                      "memory": {"partitions": {n: mem},
                                 "resident_bytes": mem["resident_bytes"]}}
    return out


def run_server() -> None:
    """Run the pserver event loop on PADDLE_PORT (blocks until a client
    sends shutdown — the listen_and_serv analog, distributed/
    ps_server.py). The process role contract matches the reference:
    TRAINING_ROLE=PSERVER processes call init_server() + run_server(),
    trainers call init_worker() and train. PADDLE_PORT is required:
    trainers resolve a FIXED port from PADDLE_PSERVERS_IP_PORT_LIST, so
    binding an ephemeral one would wedge the job undiscoverably."""
    import os as _os

    from ..distributed import ps_server

    port = int(_os.environ.get("PADDLE_PORT", 0))
    if port <= 0:
        raise RuntimeError(
            "fleet.run_server: PADDLE_PORT is not set; the pserver must "
            "bind the port trainers were told about "
            "(PADDLE_PSERVERS_IP_PORT_LIST). For an OS-assigned port use "
            "`python -m paddle_tpu.distributed.ps_server --port 0`, "
            "which prints the bound port")

    def ready(addr):
        print(f"[fleet.run_server] listening on {addr[0]}:{addr[1]}",
              flush=True)

    ps_server.serve(
        port=port,
        preload_dir=_fleet_state.get("ps_model_dir"),
        snapshot_dir=_fleet_state.get("ps_snapshot_dir"),
        snapshot_secs=_fleet_state.get("ps_snapshot_secs"),
        ready_cb=ready,
    )


def stop_worker() -> None:
    """Trainer-side teardown (reference fleet_base.stop_worker): flush
    pending Geo deltas, close RemoteTable connections, and drop the
    tables from the process-local registry so a restarted training
    phase can create_table again."""
    from ..distributed import ps

    for name, t in list(ps._tables.items()):
        if hasattr(t, "flush"):
            t.flush()
        closer = getattr(t, "close", None) or getattr(
            getattr(t, "server", None), "close", None)
        if closer:
            closer()
        ps.drop_table(name)


def worker_index() -> int:
    return get_rank()


def worker_num() -> int:
    return get_world_size()


def worker_endpoints():
    """Launcher-provided endpoints (reference role_maker.get_trainer_endpoints);
    empty on a single host with no launcher env."""
    from ..parallel.env import get_endpoints

    return get_endpoints()


def barrier_worker():
    """Cross-process barrier: a tiny psum over all devices forces every
    process to reach this point (replaces the reference's Gloo barrier,
    framework/fleet/gloo_wrapper.h). Single-process: trivially returns."""
    if get_world_size() <= 1:
        return
    import jax
    import jax.numpy as jnp

    jax.block_until_ready(
        jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")(
            jnp.ones((jax.local_device_count(),))
        )
    )


class DistributedOptimizer:
    """Wraps an inner Optimizer; minimize() = inner minimize + mesh/sharding
    attach (the GSPMD replacement for the reference's meta-optimizer chain,
    fleet/meta_optimizers/*.py)."""

    def __init__(self, optimizer, strategy: Optional[DistributedStrategy] = None):
        self.inner_opt = optimizer
        self.user_defined_strategy = strategy or _fleet_state.get("strategy") or DistributedStrategy()

    def minimize(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        import jax

        strategy = self.user_defined_strategy
        inner = self.inner_opt
        program = loss.block.program

        _reject_unsupported(strategy)

        dcn = int(strategy.hybrid_dcn or 0)
        mesh = strategy.mesh
        if mesh is None:
            if dcn >= 2:
                axes = dict(strategy.mesh_axes) if strategy.mesh_axes else {}
                if "dcn" not in axes:
                    axes = {"dcn": dcn, **(axes or {"dp": -1})}
            else:
                axes = dict(strategy.mesh_axes) if strategy.mesh_axes else {"dp": -1}
            mesh = create_mesh(axes)
        if dcn >= 2:
            # a mesh without the outer axis would make c_dcn_grad_sync
            # degrade to identity — silent parameter divergence; fail loud
            if "dcn" not in mesh.axis_names or mesh.shape["dcn"] != dcn:
                raise ValueError(
                    f"strategy.hybrid_dcn={dcn} but the resolved mesh "
                    f"{dict(mesh.shape)} has no matching 'dcn' axis; give "
                    f"the mesh a 'dcn' axis of exactly that size (or drop "
                    f"strategy.mesh/mesh_axes and let fleet build it)"
                )

        # optimizer swaps (reference fleet/meta_optimizers/{lamb,lars}_
        # optimizer.py replace the inner optimizer the same way)
        if strategy.lamb:
            from ..fluid.optimizer import LambOptimizer

            cfg = strategy.lamb_configs or {}
            inner = LambOptimizer(
                learning_rate=getattr(inner, "_learning_rate", 0.001),
                lamb_weight_decay=cfg.get("lamb_weight_decay", 0.01),
                beta1=cfg.get("beta1", 0.9),
                beta2=cfg.get("beta2", 0.999),
                epsilon=cfg.get("epsilon", 1e-6),
            )
        elif strategy.lars:
            from ..fluid.optimizer import LarsMomentumOptimizer

            cfg = strategy.lars_configs or {}
            inner = LarsMomentumOptimizer(
                learning_rate=getattr(inner, "_learning_rate", 0.001),
                momentum=cfg.get("momentum", getattr(inner, "_momentum", 0.9)),
                lars_coeff=cfg.get("lars_coeff", 0.001),
                lars_weight_decay=cfg.get("lars_weight_decay", 0.0005),
                epsilon=cfg.get("epsilon", 0),
            )

        sp_active = (
            strategy.sequence_parallel
            and "sp" in mesh.axis_names
            and mesh.shape["sp"] > 1
        )
        # sequence parallel marks forward attention ops BEFORE backward, so
        # the synthesized grad ops capture the attr and the backward ring
        # is sequence-parallel too
        if sp_active:
            apply_sequence_parallel(program, mesh)

        pp_active = (
            strategy.pipeline
            and "pp" in mesh.axis_names
            and mesh.shape["pp"] > 1
        )

        # program rewrites that precede backward (AMP, recompute)
        if strategy.amp:
            from ..contrib.mixed_precision import decorate

            amp_cfg = dict(strategy.amp_configs or {})
            # consumed by the dcn sync ops, not the decorator
            amp_cfg.pop("bf16_grad_sync", None)
            inner = decorate(inner, **amp_cfg)
        if strategy.recompute and strategy.recompute_configs.get("checkpoints"):
            from ..fluid.optimizer import RecomputeOptimizer

            inner = RecomputeOptimizer(inner)
            inner._set_checkpoints(strategy.recompute_configs["checkpoints"])
        if strategy.gradient_merge:
            from ..fluid.optimizer import GradientMergeOptimizer

            inner = GradientMergeOptimizer(
                inner, k_steps=strategy.gradient_merge_configs.get("k_steps", 1),
                avg=strategy.gradient_merge_configs.get("avg", True),
            )
        if pp_active:
            # outermost: its minimize marks encoder stacks for the GPipe
            # schedule before any wrapped pass appends backward ops.
            # accumulate_steps <= 1 (the DistributedStrategy default) would
            # mean M=1 — every stage idle (pp-1)/pp of the time — so fall
            # back to one microbatch per stage
            from ..fluid.optimizer import PipelineOptimizer

            acc = int(strategy.pipeline_configs.get("accumulate_steps", 1))
            if acc <= 1:
                acc = mesh.shape["pp"]
            inner = PipelineOptimizer(inner, num_microbatches=acc)

        if dcn >= 2:
            # multi-slice: the executor runs the step MANUALLY sharded
            # over (dcn, dp) so per-shard gradients are visible. Either
            # a c_dcn_grad_sync op per parameter does the two-level
            # reduction (dense over ICI, dense-or-DGC over DCN), or
            # LocalSGD keeps per-slice weights with k-step consensus
            if strategy.localsgd:
                inner = _DCNLocalSGDOptimizer(inner, strategy)
            else:
                inner = _DCNGradSyncOptimizer(inner, strategy)

        result = inner.minimize(
            loss, startup_program=startup_program,
            parameter_list=parameter_list, no_grad_set=no_grad_set,
        )

        if dcn >= 2:
            manual = tuple(a for a in ("dcn", "dp") if a in mesh.axis_names)
            program._manual_axes = manual
            for v in program.list_vars():
                if getattr(v, "is_data", False) and v.shape:
                    _parallel.set_var_sharding(
                        v, (tuple(manual),) + (None,) * (len(v.shape) - 1)
                    )
            program._mesh = mesh
            if startup_program is not None:
                startup_program._mesh = mesh
            return result

        if strategy.sharding and "dp" in mesh.axis_names and mesh.shape["dp"] > 1:
            _shard_optimizer_states(inner, mesh)
        if "dp" in mesh.axis_names:
            _parallel.shard_program_data_parallel(program, mesh, axis="dp")
        if sp_active:
            _parallel.shard_program_sequence_parallel(program, mesh, axis="sp")
        if "tp" in mesh.axis_names and mesh.shape["tp"] > 1:
            apply_tensor_parallel_rules(program, strategy.tensor_parallel_rules)
        if (
            strategy.expert_parallel
            and "ep" in mesh.axis_names
            and mesh.shape["ep"] > 1
        ):
            apply_expert_parallel(program, mesh)
        if pp_active:
            _shard_pipeline_params(program)
        program._mesh = mesh
        if startup_program is not None:
            startup_program._mesh = mesh
        return result

    def __getattr__(self, item):
        return getattr(self.inner_opt, item)


def distributed_optimizer(optimizer, strategy: Optional[DistributedStrategy] = None):
    return DistributedOptimizer(optimizer, strategy)


def _backward_params_grads(inner, loss, startup_program, parameter_list,
                           no_grad_set):
    """backward() across inner-optimizer flavors: the AMP decorator
    returns (scaled_loss, params_grads) (reference decorator.py
    backward:112), plain/recompute optimizers return params_grads."""
    res = inner.backward(loss, startup_program, parameter_list,
                         no_grad_set)
    if (isinstance(res, tuple) and len(res) == 2
            and isinstance(res[1], list)):
        return res[1]
    return res


class _DCNGradSyncOptimizer:
    """Insert a c_dcn_grad_sync op between backward and the optimizer
    update for every parameter gradient (the multi-slice hybrid_dcn
    mode). The inner optimizer must expose backward/apply_optimize:
    plain, recompute, and AMP optimizers do — amp composes by wrapping
    (AMP backward emits bf16 grads, the sync ops ride them, AMP
    apply_optimize casts f32 for the update); gradient_merge is
    rejected by _reject_unsupported."""

    def __init__(self, inner, strategy):
        self.inner_opt = inner
        self._strategy = strategy

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from ..fluid import unique_name
        from ..fluid.optimizer import _create_persistable_var

        strategy = self._strategy
        n_dcn = int(strategy.hybrid_dcn)
        params_grads = _backward_params_grads(
            self.inner_opt, loss, startup_program, parameter_list,
            no_grad_set)
        block = loss.block.program.global_block()
        use_dgc = bool(strategy.dgc)
        cfgs = strategy.dgc_configs or {}
        sparsity = float(cfgs.get("sparsity", 0.999))
        rampup = int(cfgs.get("rampup_begin_step", 0))
        # AMP composes: parameter grads reach here as f32 masters (the
        # cast vjp accumulates f32), so low-precision lives on the WIRE —
        # the slow dcn hop runs bf16 (reference fp16_allreduce analog)
        # unless amp_configs["bf16_grad_sync"] turns it off
        wire = (
            "bfloat16"
            if strategy.amp
            and (strategy.amp_configs or {}).get("bf16_grad_sync", True)
            else ""
        )
        step_var = None
        if use_dgc and rampup > 0:
            # in-graph step counter driving the DGC dense warm-up; the
            # increment is appended AFTER the sync ops below, so step i
            # reads counter value i and `Step < rampup` gives exactly
            # rampup dense steps (DGCMomentumOptimizer parity)
            step_var = _create_persistable_var(
                unique_name.generate("dcn_dgc_step"), [1], "float32", 0.0
            )
        synced = []
        for p, g in params_grads:
            if g is None:
                synced.append((p, g))
                continue
            inputs = {"X": [g]}
            outputs = {}
            if use_dgc:
                # [n_dcn, *shape], SHARDED over "dcn": each slice owns its
                # error-feedback residual (replicating it would collapse
                # the per-slice state on any metadata-trusting reshard)
                ef = _create_persistable_var(
                    p.name + "@DGCErrorFeedback",
                    (n_dcn,) + tuple(p.shape), "float32", 0.0,
                )
                set_var_sharding(
                    ef, ("dcn",) + (None,) * len(tuple(p.shape))
                )
                inputs["ErrorFeedback"] = [ef]
                outputs["ErrorFeedback"] = [ef]
                if step_var is not None:
                    inputs["Step"] = [step_var]
            out_name = unique_name.generate(g.name + "@DCNSync")
            block.append_op(
                type="c_dcn_grad_sync",
                inputs=inputs,
                outputs={"Out": [out_name], **outputs},
                attrs={"use_dgc": use_dgc, "sparsity": sparsity,
                       "rampup_begin_step": rampup, "dcn_axis": "dcn",
                       "wire_dtype": wire},
            )
            synced.append((p, block.var(out_name)))
        if step_var is not None:
            block.append_op(
                type="scale",
                inputs={"X": [step_var]},
                outputs={"Out": [step_var]},
                attrs={"scale": 1.0, "bias": 1.0},
            )
        opt_ops = self.inner_opt.apply_optimize(
            loss, startup_program, synced
        )
        return opt_ops, params_grads

    def __getattr__(self, item):
        return getattr(self.inner_opt, item)


class _DCNLocalSGDOptimizer:
    """LocalSGD across the slow DCN axis (reference
    transpiler/collective.py:270 LocalSGD transpile +
    DistributedStrategy.localsgd_configs): gradients pmean only INSIDE
    the slice (fast ICI, intra_only c_dcn_grad_sync); the inner
    optimizer then updates PER-SLICE divergent parameters — stored
    [n_dcn, *shape] sharded over "dcn", squeezed to the local view by
    the executor — and every k_steps a c_dcn_localsgd_sync op averages
    the parameters over "dcn". Optimizer accumulators (momentum/Adam
    moments) follow their per-slice gradients, so they get the same
    divergent storage."""

    def __init__(self, inner, strategy):
        self.inner_opt = inner
        self._strategy = strategy

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from ..fluid import framework, unique_name
        from ..fluid.optimizer import _create_persistable_var

        strategy = self._strategy
        n_dcn = int(strategy.hybrid_dcn)
        k_steps = max(
            1, int((strategy.localsgd_configs or {}).get("k_steps", 1)))
        params_grads = _backward_params_grads(
            self.inner_opt, loss, startup_program, parameter_list,
            no_grad_set)
        program = loss.block.program
        block = program.global_block()
        synced = []
        for p, g in params_grads:
            if g is None:
                synced.append((p, g))
                continue
            out_name = unique_name.generate(g.name + "@DPSync")
            block.append_op(
                type="c_dcn_grad_sync",
                inputs={"X": [g]},
                outputs={"Out": [out_name]},
                attrs={"intra_only": True, "dcn_axis": "dcn"},
            )
            synced.append((p, block.var(out_name)))
        opt_ops = self.inner_opt.apply_optimize(loss, startup_program, synced)

        # replicated in-graph step counter, incremented AFTER the sync
        # ops: step i reads value i, so `i % k == k-1` fires the first
        # consensus after exactly k local updates
        # int32: a float32 counter saturates at 2^24 (x+1 == x), which
        # would freeze step%k on very long runs
        step_var = _create_persistable_var(
            unique_name.generate("localsgd_step"), [1], "int32", 0.0)
        divergent = set(getattr(program, "_dcn_divergent_names", ()))
        for p, g in params_grads:
            if g is None:
                continue
            block.append_op(
                type="c_dcn_localsgd_sync",
                inputs={"X": [p], "Step": [step_var]},
                outputs={"Out": [p]},
                attrs={"k_steps": k_steps, "dcn_axis": "dcn"},
            )
            divergent.add(p.name)
            _parallel.set_var_sharding(
                p, ("dcn",) + (None,) * len(tuple(p.shape)))
        block.append_op(
            type="increment", inputs={"X": [step_var]},
            outputs={"Out": [step_var]}, attrs={"step": 1},
        )
        # accumulators diverge with their slice's gradients
        for slot in getattr(self.inner_opt, "_accumulators", {}).values():
            for acc_var in slot.values():
                divergent.add(acc_var.name)
                _parallel.set_var_sharding(
                    acc_var, ("dcn",) + (None,) * len(tuple(acc_var.shape)))
        program._dcn_divergent_names = divergent

        # startup: expand every divergent var's storage to [n_dcn, *shape]
        sp = startup_program or framework.default_startup_program()
        sblock = sp.global_block()
        for name in sorted(divergent):
            if sblock.has_var(name):
                sv = sblock.var(name)
                sblock.append_op(
                    type="dcn_expand_param",
                    inputs={"X": [sv]},
                    outputs={"Out": [sv]},
                    attrs={"n_dcn": n_dcn,
                           "param_rank": len(tuple(sv.shape))},
                )
        return opt_ops, params_grads

    def __getattr__(self, item):
        return getattr(self.inner_opt, item)


def _reject_unsupported(strategy):
    """No silently ignored strategy field: every accepted-but-unimplemented
    flag raises with the reason (VERDICT round-1 weak #4)."""
    if strategy.dgc and int(strategy.hybrid_dcn or 0) < 2:
        raise NotImplementedError(
            "strategy.dgc: deep gradient compression exists to survive slow "
            "interconnects (reference details/sparse_all_reduce_op_handle.cc); "
            "over single-slice TPU ICI the XLA all-reduce runs near roofline "
            "so compression only costs accuracy — set strategy.hybrid_dcn to "
            "the slice count to apply DGC across the slow DCN axis, where it "
            "belongs"
        )
    if int(strategy.hybrid_dcn or 0) >= 2:
        for flag, name in (
            (strategy.tensor_parallel, "tensor_parallel"),
            (strategy.pipeline, "pipeline"),
            (strategy.sequence_parallel, "sequence_parallel"),
            (strategy.expert_parallel, "expert_parallel"),
            (strategy.gradient_merge, "gradient_merge"),
        ):
            if flag:
                raise NotImplementedError(
                    f"strategy.hybrid_dcn composes with data parallelism "
                    f"and amp for now; unset strategy.{name}"
                )
        if strategy.sharding:
            raise NotImplementedError(
                "strategy.sharding + hybrid_dcn: ZeRO state sharding "
                "relies on GSPMD resharding the accumulator at the "
                "update, but the multi-slice step runs MANUALLY sharded "
                "(executor shard_map over (dcn, dp)) where a dp-sharded "
                "accumulator's local view cannot meet the replicated "
                "parameter — gathering it in-step would forfeit the "
                "memory saving sharding exists for. Use sharding on "
                "single-slice meshes"
            )
    if strategy.localsgd:
        if int(strategy.hybrid_dcn or 0) < 2:
            raise NotImplementedError(
                "strategy.localsgd: single-slice GSPMD keeps parameters "
                "replicated, and over fast ICI the dense all-reduce is "
                "near roofline — LocalSGD's infrequent-sync regime is the "
                "slow DCN axis: set strategy.hybrid_dcn to the slice "
                "count (per-slice divergent weights, k-step consensus). "
                "The eager multi-process path has "
                "fluid.dygraph.parallel.LocalSGD."
            )
        if strategy.dgc:
            raise NotImplementedError(
                "strategy.localsgd + strategy.dgc: pick ONE dcn-axis sync "
                "model — k-step parameter averaging (localsgd) or "
                "per-step compressed gradients (dgc)"
            )
    if strategy.elastic:
        raise NotImplementedError(
            "strategy.elastic: a dead flag in the reference too "
            "(distributed_strategy.proto:106, no trainer-side impl); the "
            "recovery story is checkpoint/resume via fluid.io"
        )
    if strategy.auto:
        raise NotImplementedError(
            "strategy.auto: automatic strategy search is not implemented; "
            "set mesh_axes / tensor_parallel / pipeline explicitly"
        )


def _unwrap_optimizer(opt):
    while True:
        for attr in ("inner_opt", "_optimizer"):
            nxt = getattr(opt, attr, None)
            if nxt is not None:
                opt = nxt
                break
        else:
            return opt


def _shard_optimizer_states(inner, mesh):
    """ZeRO-style optimizer-state sharding (strategy.sharding): moment
    accumulators are elementwise state, so sharding their leading dim over
    "dp" divides optimizer memory by dp; XLA inserts the (cheap, ICI)
    gathers where the update needs them. The parameters themselves stay
    replicated — this is the reference's sharding strategy restricted to
    optimizer state (ZeRO-2 analog), which GSPMD expresses natively."""
    opt = _unwrap_optimizer(inner)
    accs = getattr(opt, "_accumulators", None)
    if not accs:
        return
    dp = mesh.shape["dp"]
    for by_param in accs.values():
        for v in by_param.values():
            if v.shape and len(v.shape) >= 1 and v.shape[0] % dp == 0 and v.shape[0] >= dp:
                set_var_sharding(v, ("dp",) + (None,) * (len(v.shape) - 1))


def apply_sequence_parallel(program, mesh):
    """Mark every attention-bearing op to use the ring-attention path over
    the "sp" axis (parallel/ring_attention.py). Must run before
    append_backward: grad ops snapshot forward attrs at creation."""
    for block in program.blocks:
        for op in block.ops:
            if op.type in ("fused_multihead_attention", "fused_encoder_stack",
                           "fused_decoder_stack"):
                # decoder stack under sp: causal self-attention rides the
                # ring over trg shards, cross-attention k/v is gathered
                # by GSPMD (ops/encoder_stack.py fused_decoder_stack)
                op._set_attr("sequence_parallel", True)


def _shard_pipeline_params(program):
    """Shard stacked encoder-layer parameters (dim 0 = layer) over "pp", so
    each stage's weights live only on its own shard — the placement analog
    of the reference's per-section scopes (pipeline_trainer.cc:212)."""
    for block in program.blocks:
        for op in block.ops:
            if op.type != "fused_encoder_stack" or not op.attr("pipeline"):
                continue
            for slot, names in op.inputs.items():
                if slot in ("Hidden", "AttnBias"):
                    continue
                for n in names:
                    v = block._find_var_recursive(n)
                    if v is not None and v.persistable and v.shape:
                        set_var_sharding(
                            v, ("pp",) + (None,) * (len(v.shape) - 1)
                        )


def apply_expert_parallel(program, mesh, axis: str = "ep"):
    """Shard every moe_ffn op's expert-indexed parameters (W1/B1/W2/B2,
    dim 0 = expert) over `axis`. Tokens stay dp-sharded and the router
    (GateW) replicated; XLA's SPMD partitioner then places each expert's
    FFN on its own ep shard and inserts the dispatch/combine all-to-alls
    around the expert einsums (ops/moe_ops.py) — expert parallelism as a
    sharding annotation, consistent with how dp/tp/sp are expressed."""
    ep = mesh.shape[axis]
    for block in program.blocks:
        for op in block.ops:
            if op.type != "moe_ffn":
                continue
            for slot in ("W1", "B1", "W2", "B2"):
                for n in op.inputs.get(slot, []):
                    v = block._find_var_recursive(n)
                    if v is None or not v.shape:
                        continue
                    if v.shape[0] % ep != 0:
                        raise ValueError(
                            f"moe_ffn param {n}: num_experts {v.shape[0]} "
                            f"not divisible by ep axis size {ep}"
                        )
                    set_var_sharding(v, (axis,) + (None,) * (len(v.shape) - 1))


def apply_tensor_parallel_rules(program, rules):
    """rules: list of (name_regex, spec_tuple). Sets PartitionSpec on every
    parameter whose name matches — megatron-style column/row sharding is a
    pair of rules."""
    import re

    if not rules:
        return
    for p in program.all_parameters():
        for pattern, spec in rules:
            if re.search(pattern, p.name):
                set_var_sharding(p, spec)
                break
