"""Allreduced scalar metric helpers on the fleet namespace.

Parity surface: reference python/paddle/fleet/metrics/metric.py — each
helper resolves a host value (numpy array, program Variable, or scope
var name), allreduces it across trainer PROCESSES, and returns the
global metric. The reference rides the role maker's MPI allreduce; here
the transport is the JAX coordination service (parallel.env
init_parallel_env) — `process_allgather` over gloo on CPU fleets, ICI/
DCN on TPU pods — and a single-process run is the identity, so the same
training script works launched or not.

Accumulator convention (identical to the reference examples): the model
keeps float32 running stats in persistable vars (correct/total counts,
AUC bucket stats from layers.auc); after train/infer the driver calls
these helpers on the fetched numpy values.
"""
from __future__ import annotations

import math

import numpy as np

__all__ = ["sum", "max", "min", "auc", "mae", "rmse", "mse", "acc"]

_py_sum, _py_max, _py_min = sum, max, min


def _resolve(value, scope):
    """numpy array | fluid Variable | scope var name -> numpy array."""
    from ...fluid import executor, framework

    if isinstance(value, framework.Variable):
        value = value.name
    if isinstance(value, str):
        scope = scope if scope is not None else executor.global_scope()
        found = scope.find_var(value)
        if found is None:
            raise KeyError(f"fleet.metrics: no var {value!r} in scope")
        value = found
    return np.asarray(value, np.float64)


def _all_reduce(arr: np.ndarray, mode: str = "sum") -> np.ndarray:
    """Cross-process host allreduce (reference _role_maker._all_reduce).
    Single process: identity. Multi process: allgather over the JAX
    coordination service, reduce in numpy (float64 — metric counters
    must not lose integer precision the way an f32 psum would)."""
    import jax

    if jax.process_count() == 1:
        return arr.copy()
    from jax.experimental import multihost_utils

    stacked = np.asarray(
        multihost_utils.process_allgather(arr.astype(np.float64)))
    if mode == "sum":
        return stacked.sum(axis=0)
    if mode == "max":
        return stacked.max(axis=0)
    if mode == "min":
        return stacked.min(axis=0)
    raise ValueError(f"unknown allreduce mode {mode!r}")


def sum(input, scope=None):  # noqa: A001 — reference name
    """Distributed elementwise sum (reference metric.py:23)."""
    return _all_reduce(_resolve(input, scope), "sum")


def max(input, scope=None):  # noqa: A001
    """Distributed elementwise max (reference metric.py:62)."""
    return _all_reduce(_resolve(input, scope), "max")


def min(input, scope=None):  # noqa: A001
    """Distributed elementwise min (reference metric.py:101)."""
    return _all_reduce(_resolve(input, scope), "min")


def auc(stat_pos, stat_neg, scope=None):
    """Distributed AUC from per-trainer threshold-bucket stats
    (reference metric.py:140): allreduce-sum the positive/negative
    bucket counters produced by layers.auc, then integrate the ROC
    trapezoids over the global buckets, high threshold to low."""
    pos = _all_reduce(_resolve(stat_pos, scope).reshape(-1), "sum")
    neg = _all_reduce(_resolve(stat_neg, scope).reshape(-1), "sum")
    # integrate from the top bucket down (descending threshold)
    pos_cum = np.cumsum(pos[::-1])
    neg_cum = np.cumsum(neg[::-1])
    tot_pos, tot_neg = pos_cum[-1], neg_cum[-1]
    if tot_pos * tot_neg == 0 or (tot_pos + tot_neg) == 0:
        return 0.5
    new_neg = neg_cum
    old_neg = np.concatenate([[0.0], neg_cum[:-1]])
    new_pos = pos_cum
    old_pos = np.concatenate([[0.0], pos_cum[:-1]])
    area = np.sum((new_neg - old_neg) * (old_pos + new_pos) / 2.0)
    return float(area / (tot_pos * tot_neg))


def mae(abserr, total_ins_num, scope=None):
    """Distributed mean absolute error (reference metric.py:223)."""
    g = _all_reduce(_resolve(abserr, scope).reshape(-1), "sum")
    return float(g[0] / total_ins_num)


def rmse(sqrerr, total_ins_num, scope=None):
    """Distributed root mean squared error (reference metric.py:261)."""
    g = _all_reduce(_resolve(sqrerr, scope).reshape(-1), "sum")
    return float(math.sqrt(g[0] / total_ins_num))


def mse(sqrerr, total_ins_num, scope=None):
    """Distributed mean squared error (reference metric.py:299)."""
    g = _all_reduce(_resolve(sqrerr, scope).reshape(-1), "sum")
    return float(g[0] / total_ins_num)


def acc(correct, total, scope=None):
    """Distributed accuracy: sum(correct)/sum(total) over trainers
    (reference metric.py:337)."""
    c = _all_reduce(_resolve(correct, scope).reshape(-1), "sum")
    t = _all_reduce(_resolve(total, scope).reshape(-1), "sum")
    return float(c[0] / t[0])
