"""DistributeTranspiler: rewrite a program's embeddings onto the PS.

Parity surface: reference
python/paddle/fluid/transpiler/distribute_transpiler.py:545 (transpile)
and geo_sgd_transpiler.py. The reference slices EVERY parameter onto
pservers and rewrites gradients into send/recv pairs; on TPU, GSPMD
data parallelism subsumes dense-parameter distribution entirely, so the
transpile targets exactly what XLA cannot subsume — lookup tables
bigger than (or destined for) host memory. Each selected
`lookup_table(_v2)` op becomes a `distributed_lookup_table` op backed
by a host table (distributed/ps.py): in-process for one trainer,
hosted in pserver processes (distributed/ps_server.py) when endpoints
are given — the same `pservers=`/`trainers=`/`sync_mode=` contract as
the reference's transpile call.

TPU-era contract difference (deliberate): transpile runs BEFORE
minimize. The reference transpiles the fully-built program because it
must rewrite the backward's send/recv; here the PS push IS the lookup
op's vjp, so the rewrite must happen before append_backward creates
dense W gradients. Transpiling a program that already has gradient or
optimizer ops on a selected table raises with this explanation.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional

from . import framework
from .initializer import ConstantInitializer


@dataclasses.dataclass
class DistributeTranspilerConfig:
    """Reference transpiler config surface (distribute_transpiler.py
    DistributeTranspilerConfig + geo fields). Unused reference knobs
    (slice_var_up/min_block_size — block slicing is the server's
    num_shards here) are accepted for parity."""

    slice_var_up: bool = True
    min_block_size: int = 8192
    # "pserver" (sync/async per transpile arg) or "geo"
    mode: str = "pserver"
    geo_sgd_need_push_nums: int = 100
    # only lookup tables with at least this many rows move to the PS
    # (0 = every lookup table; the reference moves everything)
    min_rows_for_ps: int = 0
    # server-side optimizer for pushed gradients (host PS supports the
    # reference pserver optimizer block equivalents sgd/adagrad)
    server_optimizer: str = "sgd"
    server_learning_rate: float = 0.1


class DistributeTranspiler:
    def __init__(self, config: Optional[DistributeTranspilerConfig] = None):
        self.config = config or DistributeTranspilerConfig()
        self.tables: List[str] = []

    def transpile(self, trainer_id, program=None, pservers="",
                  trainers=1, sync_mode=True, startup_program=None,
                  current_endpoint=""):
        """Rewrite `program`'s lookup tables onto the parameter server
        (reference transpile:545 signature). pservers: comma-separated
        endpoints ("" = in-process table). Returns the table names."""
        from ..distributed import ps

        program = program or framework.default_main_program()
        startup = startup_program or framework.default_startup_program()
        cfg = self.config
        endpoints = [e.strip() for e in pservers.split(",") if e.strip()]
        mode = "geo" if cfg.mode == "geo" else (
            "sync" if sync_mode else "async")

        # scan EVERY block: lookup ops inside While/cond sub-blocks (the
        # NMT decoder pattern) must move too — a silently-skipped giant
        # table would defeat the feature's purpose. Targets group by
        # parameter: tied embeddings (one W, several lookup ops) get ONE
        # table and every op rewritten.
        by_param = {}
        for blk in program.blocks:
            for op in blk.ops:
                if op.type not in ("lookup_table", "lookup_table_v2"):
                    continue
                w_name = op.input("W")[0]
                w = blk._find_var_recursive(w_name)
                if w is None or not isinstance(w, framework.Parameter):
                    continue
                if int(w.shape[0]) < cfg.min_rows_for_ps:
                    continue
                if op.type == "lookup_table":
                    raise NotImplementedError(
                        f"DistributeTranspiler: {w_name!r} is consumed "
                        f"by a v1 lookup_table op whose ids carry a "
                        f"trailing [,1] dim the op strips internally; "
                        f"distributed_lookup_table returns "
                        f"ids.shape+(dim,), which would change the "
                        f"output rank. Use layers.embedding "
                        f"(lookup_table_v2) or squeeze the ids")
                if int(op.attr("padding_idx", -1)) >= 0:
                    raise NotImplementedError(
                        f"DistributeTranspiler: {w_name!r} uses "
                        f"padding_idx; the host table has no padding-row "
                        f"masking (pad rows would train as normal rows). "
                        f"Remap pad ids out of the lookup instead")
                by_param.setdefault(w.name, (w, []))[1].append((blk, op))

        for w, _ops in by_param.values():
            for blk in program.blocks:
                self._check_untouched(blk, w)

        for w, ops in by_param.values():
            kw = {}
            if cfg.mode == "geo":
                kw["geo_sync_steps"] = cfg.geo_sgd_need_push_nums
            kw.update(self._init_kwargs(startup, w))
            ps.create_table(
                w.name, shape=tuple(w.shape), mode=mode,
                num_trainers=int(trainers) if str(trainers).isdigit()
                else None,
                endpoints=endpoints or None,
                optimizer=cfg.server_optimizer,
                learning_rate=cfg.server_learning_rate,
                **kw,
            )
            anchor = self._make_anchor(program, startup, w)
            for blk, op in ops:
                self._rewrite_lookup(blk, op, w, anchor)
            self._drop_param(program, startup, w)
            self.tables.append(w.name)
        from .flags import flag

        if flag("FLAGS_program_verify"):
            # cross-program lint of the transpile result: every
            # distributed_lookup_table op must name a registered table
            # whose embedding dim matches the program's output var —
            # catches a stale table left by a previous transpile at
            # transpile time instead of as wrongly-sized rows mid-step
            from .analysis import assert_pair_valid

            assert_pair_valid(
                program, where="DistributeTranspiler.transpile "
                               "(FLAGS_program_verify)")
        return list(self.tables)

    # -- surgery ---------------------------------------------------------

    @staticmethod
    def _check_untouched(block, w):
        grad_name = w.name + "@GRAD"
        for op in block.ops:
            names = [n for ns in list(op.inputs.values())
                     + list(op.outputs.values()) for n in ns]
            if grad_name in names or (
                op.type.endswith("_grad") and w.name in names
            ) or (
                "Param" in op.inputs and op.input("Param")[0] == w.name
            ):
                raise RuntimeError(
                    f"DistributeTranspiler: table {w.name!r} already has "
                    f"gradient/optimizer ops ({op.type}); on this stack "
                    f"the PS push is the lookup op's vjp, so transpile "
                    f"must run BEFORE minimize/append_backward "
                    f"(reference order differs because it rewrites "
                    f"send/recv into an already-built backward)")

    @staticmethod
    def _init_kwargs(startup, w):
        """Carry W's initializer into the host table where the form maps
        (gaussian std/seed — the embedding norm everywhere in this
        repo); other initializers cannot be reproduced server-side, so
        their loss is SURFACED as a warning rather than silent
        (review finding: pretrained/uniform inits were dropped)."""
        import warnings

        for o in startup.global_block().ops:
            if w.name not in [n for ns in o.outputs.values() for n in ns]:
                continue
            if o.type == "gaussian_random":
                return {
                    "initializer_std": float(o.attr("std", 1.0)),
                    "seed": int(o.attr("seed", 0)),
                }
            warnings.warn(
                f"DistributeTranspiler: table {w.name!r} was initialized "
                f"by {o.type!r}, which the host table cannot reproduce — "
                f"it will use its default normal(0, 1/sqrt(dim)) init. "
                f"Load pretrained rows via "
                f"ps.get_table({w.name!r}).load_state_dict(...) if the "
                f"init matters", RuntimeWarning, stacklevel=4)
            return {}
        return {}

    @staticmethod
    def _make_anchor(program, startup, w):
        """(1,) zero Parameter routing autodiff into the lookup op
        (same pattern as layers.distributed_embedding)."""
        from . import unique_name

        anchor_name = unique_name.generate(f"{w.name}_anchor")
        program.global_block().create_parameter(
            name=anchor_name, shape=[1], dtype="float32", trainable=True)
        sblock = startup.global_block()
        sv = sblock.create_var(name=anchor_name, shape=(1,),
                               dtype="float32", persistable=True)
        ConstantInitializer(0.0)(sv, sblock)
        return anchor_name

    @staticmethod
    def _rewrite_lookup(block, op, w, anchor_name):
        """lookup_table_v2(W, Ids) -> distributed_lookup_table(Ids,
        anchor); W leaves the device program entirely (its storage now
        lives in the host/pserver table)."""
        out = op.output("Out")[0]
        op.type = "distributed_lookup_table"
        op.inputs = {"Ids": [op.input("Ids")[0]], "W": [anchor_name]}
        op.outputs = {"Outputs": [out]}
        op.attrs = {"table_names": [w.name]}

    @staticmethod
    def _drop_param(program, startup, w):
        sblock = startup.global_block()
        sops = [o for o in sblock.ops
                if w.name in [n for ns in o.outputs.values() for n in ns]]
        for o in sops:
            sblock.ops.remove(o)
        for blk in list(program.blocks) + [sblock]:
            blk.vars.pop(w.name, None)
        # in-place op mutation bypasses append_op's version bump; the
        # executor's compile cache must see a new program version
        program._bump_version()
        startup._bump_version()


def get_transpiler(config=None):
    return DistributeTranspiler(config)
