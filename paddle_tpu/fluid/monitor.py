"""Executor step-time breakdown on the telemetry layer (ISSUE 4).

Answers "where did this step go" for the whole-block-XLA execution
model, where op boundaries vanish inside one compiled program
(arXiv:2301.13062) and the only honest per-phase account is at the
executor's seams:

  data_wait_ms   host time spent materializing the feed (plus, in
                 dataset loops, the time blocked on the input iterator
                 — timed_iter / add_data_wait)
  compile_ms     trace + XLA compile when the step misses the cache;
                 cache_hit / retraces count the misses that matter
                 (a RETRACE is a new compile for a program the cache
                 already held under a different signature — the silent
                 shape-instability tax)
  device_ms      the compiled step call. Honest only under the
                 FLAGS_benchmark fence (block_until_ready inside the
                 timed window); without the fence it measures dispatch,
                 which is what the async hot path actually pays
  fetch_ms       device->host conversion of the fetch list
  ckpt_save_ms   CheckpointManager.save durations (attached to the next
                 committed step record)
  idle_ms        raw gap between the previous Executor.run return and
                 this one's entry — the goodput ledger's idle signal
                 (ISSUE 15). Iterator wait recorded by timed_iter in
                 that gap also lands in data_wait_ms; the ledger
                 classifies by residual so nothing double-counts
  peak_hbm_bytes device allocator high-water (jax memory_stats), the
                 MAX across all local devices — per-device values land
                 in the device_peak_hbm_bytes{device=...} gauges and
                 debugz /memz; 0 where the backend reports none (CPU)

Cost contract: with PADDLE_METRICS_PATH unset nothing here touches the
filesystem or fences the device; the always-on residue is a handful of
counter increments and one deque append per step (the step-rate sample
the straggler heartbeat rides on), unmeasurable next to any real step.

Every number also lands in the process metrics registry
(telemetry.get_registry()) for the Prometheus exposition.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Optional, Tuple

from ..telemetry import get_registry, goodput, sink

_reg = get_registry()

# always-on counters, resolved per use (get-or-create) so a registry
# reset() in tests never leaves orphaned metric objects behind


def _counter(name, help=""):
    return _reg.counter(name, help=help)

_lock = threading.Lock()
_tls = threading.local()

# step-rate sample for the heartbeat/straggler channel: recent commit
# timestamps (monotonic) -> avg step seconds over the window
_recent = collections.deque(maxlen=16)
_step_count = 0
_pending_data_wait_ms = 0.0
_pending_ckpt_save_ms = 0.0
_hb_registered = False

# recent step records for the debugz /steps endpoint (same dicts the
# JSONL sink writes); populated only while a consumer exists (sink on
# or debugz armed) — the flag-off hot path builds no dicts
_recent_steps = collections.deque(maxlen=128)
_keep_recent = False
_aux_armed = False

# idle accounting (ISSUE 15): perf_counter at the end of the previous
# Executor.run — the gap to the next begin_step is the step record's
# idle_ms, the goodput ledger's idle signal
_last_run_end: Optional[float] = None
# rolling (data_wait_ms, wall_ms) per recent step: the data-starved
# fraction heartbeat stamps carry for input-skew attribution
_dw_window = collections.deque(maxlen=16)
_last_commit_wall: Optional[float] = None


def enabled() -> bool:
    """True when per-step records are being written (PADDLE_METRICS_PATH
    set or telemetry.sink.enable() called)."""
    return sink.enabled()


def _arm_aux() -> None:
    """One-shot arming of the env-gated telemetry consumers that ride
    the step loop: the debugz introspection server (PADDLE_DEBUGZ_PORT —
    arming it also turns on the /steps ring buffer) and the metrics push
    exporter (PADDLE_METRICS_PUSH_URL). Cost after the first call: one
    bool read."""
    global _aux_armed, _keep_recent
    if _aux_armed:
        return
    _aux_armed = True
    try:
        from ..telemetry import debugz, export

        if debugz.maybe_serve() is not None:
            _keep_recent = True
        export.maybe_start()
        export.maybe_start_traces()
    except Exception:  # noqa: BLE001 — introspection never fails a step
        pass
    try:
        from ..telemetry import tracing

        if tracing.enabled():
            # tracing rides the step loop too: keep the /steps ring (the
            # flight recorder dumps it next to the span ring) and arm
            # the SIGTERM/crash/exit dump hooks
            _keep_recent = True
            tracing.maybe_install_hooks()
    except Exception:  # noqa: BLE001
        pass


def recent_steps() -> list:
    """Most-recent step records, oldest first (debugz /steps)."""
    with _lock:
        return list(_recent_steps)


class StepRecord:
    __slots__ = ("data_wait_ms", "compile_ms", "device_ms", "fetch_ms",
                 "ckpt_save_ms", "idle_ms", "cache_hit", "fenced")

    def __init__(self):
        self.data_wait_ms = 0.0
        self.compile_ms = 0.0
        self.device_ms = 0.0
        self.fetch_ms = 0.0
        self.ckpt_save_ms = 0.0
        self.idle_ms = 0.0
        self.cache_hit = True
        self.fenced = False


def begin_step() -> Optional[StepRecord]:
    """Open a step record when a consumer exists (JSONL sink on, the
    debugz server armed — its /steps page reads the same records — or
    the goodput ledger classifying wall-clock); None otherwise. The
    record is thread-local so _ensure_compiled (called deeper in the
    stack) can contribute compile numbers."""
    _arm_aux()
    if not (sink.enabled() or _keep_recent or goodput.enabled()):
        return None
    rec = StepRecord()
    if _last_run_end is not None:
        # raw gap between consecutive Executor.run calls. Iterator wait
        # (timed_iter) happens inside this gap and ALSO lands in
        # data_wait_ms — the goodput ledger classifies by residual, so
        # a dataset loop's idle is the gap net of its data wait
        rec.idle_ms = max(
            0.0, (time.perf_counter() - _last_run_end) * 1e3)
    _tls.rec = rec
    return rec


def current_record() -> Optional[StepRecord]:
    return getattr(_tls, "rec", None)


def abandon_step() -> None:
    """Drop the open record (step raised; nothing committed)."""
    global _last_run_end
    _tls.rec = None
    _last_run_end = time.perf_counter()


def record_compile(ms: float, retrace: bool) -> None:
    """Called by Executor._ensure_compiled on a cache MISS."""
    _counter("executor_cache_misses_total",
             "compile-cache misses (first compiles)").inc()
    if retrace:
        _counter("executor_retraces_total",
                 "recompiles of an already-compiled program under a new "
                 "feed signature / flag set (shape instability)").inc()
    _reg.histogram("executor_compile_ms",
                   help="trace+XLA compile durations").observe(ms)
    rec = current_record()
    if rec is not None:
        rec.compile_ms += ms
        rec.cache_hit = False


def record_cache_hit() -> None:
    _counter("executor_cache_hits_total", "compile-cache hits").inc()


def add_data_wait(ms: float) -> None:
    """Input-pipeline wait attributed to the NEXT step (dataset loops
    block on the iterator BEFORE calling run)."""
    global _pending_data_wait_ms
    with _lock:
        _pending_data_wait_ms += ms


def observe_checkpoint_save(ms: float) -> None:
    global _pending_ckpt_save_ms
    _reg.histogram("checkpoint_save_ms",
                   help="CheckpointManager.save durations").observe(ms)
    with _lock:
        _pending_ckpt_save_ms += ms


def timed_iter(iterable):
    """Wrap a batch iterator so time blocked on next() lands in the
    following step's data_wait_ms. Pass-through when telemetry is off."""
    if not sink.enabled():
        yield from iterable
        return
    it = iter(iterable)
    while True:
        t0 = time.perf_counter()
        try:
            v = next(it)
        except StopIteration:
            return
        add_data_wait((time.perf_counter() - t0) * 1e3)
        yield v


def device_memory_stats() -> list:
    """Per-LOCAL-device allocator stats: one dict per device with the
    high-water mark, current usage and the allocator limit where the
    backend reports them (TPU; CPU reports nothing and yields zeros).
    The multi-chip truth behind peak_hbm_bytes — a mesh spanning >1
    local chip has one high-water PER DEVICE, and "does it fit" is a
    per-device question (debugz /memz serves this list live)."""
    out = []
    try:
        import jax

        for i, d in enumerate(jax.local_devices()):
            try:
                stats = d.memory_stats() or {}
            except Exception:  # noqa: BLE001 — backend may not report
                stats = {}
            out.append({
                "device": i,
                "kind": getattr(d, "device_kind", "?"),
                "peak_bytes": int(stats.get("peak_bytes_in_use")
                                  or stats.get("bytes_in_use") or 0),
                "bytes_in_use": int(stats.get("bytes_in_use") or 0),
                "bytes_limit": int(stats.get("bytes_limit") or 0),
            })
    except Exception:  # noqa: BLE001 — diagnostics never fail the step
        pass
    return out


def peak_hbm_bytes() -> int:
    """Device allocator high-water mark — the MAX across all local
    devices (jax memory_stats). The old scalar name and schema are kept
    for compatibility; before ISSUE 11 this read local_devices()[0]
    only, which under-reported the moment a mesh spanned >1 chip
    (device 0 is not necessarily the fullest). 0 when the backend
    reports nothing (CPU). Per-device values: device_memory_stats()
    and the device_peak_hbm_bytes{device=...} gauges."""
    stats = device_memory_stats()
    return max((d["peak_bytes"] for d in stats), default=0)


def mark_step() -> int:
    """Always-on per-step bookkeeping: step counter + the step-rate
    sample the heartbeat stamps carry. Returns the step index just
    completed (0-based monotone per process)."""
    global _step_count, _hb_registered
    _counter("executor_steps_total", "Executor.run completions").inc()
    with _lock:
        step = _step_count
        _step_count += 1
        _recent.append(time.monotonic())
    if not _hb_registered:
        _hb_registered = True
        try:  # publish (step, avg step time) through the heartbeat file
            from ..distributed import heartbeat

            heartbeat.set_step_provider(step_rate_sample)
            heartbeat.set_aux_provider(
                lambda: {"data_frac": data_wait_fraction()})
        except Exception:  # noqa: BLE001 — liveness channel is optional
            pass
    return step


def global_step() -> int:
    return _step_count


def step_rate_sample() -> Tuple[int, Optional[float]]:
    """(steps completed, recent avg step seconds or None) — the payload
    heartbeat stamps carry for launcher-side straggler detection."""
    with _lock:
        n = _step_count
        if len(_recent) >= 2:
            span = _recent[-1] - _recent[0]
            avg = span / (len(_recent) - 1) if span > 0 else None
        else:
            avg = None
    return n, avg


def data_wait_fraction() -> Optional[float]:
    """Recent input-pipeline share of step wall time (0..1), or None
    when no telemetry consumer is armed / no window yet. Rides the
    heartbeat stamps (input-skew attribution: a straggler whose
    data_frac is high is data-starved, not compute-slow)."""
    if not (sink.enabled() or goodput.enabled() or _keep_recent):
        return None
    with _lock:
        dw = sum(d for d, _ in _dw_window)
        wall = sum(w for _, w in _dw_window)
    if wall <= 0:
        return None
    return round(min(1.0, dw / wall), 4)


def commit_step(rec: Optional[StepRecord]) -> None:
    """Close the step: always-on bookkeeping, plus the JSONL record and
    gauges when telemetry output is on."""
    global _pending_data_wait_ms, _pending_ckpt_save_ms
    global _last_run_end, _last_commit_wall
    step = mark_step()
    _last_run_end = time.perf_counter()
    if rec is None:
        return
    _tls.rec = None
    with _lock:
        rec.data_wait_ms += _pending_data_wait_ms
        rec.ckpt_save_ms += _pending_ckpt_save_ms
        _pending_data_wait_ms = 0.0
        _pending_ckpt_save_ms = 0.0
    devs = device_memory_stats()
    peak = max((d["peak_bytes"] for d in devs), default=0)
    # the legacy scalar keeps its name (schema compatibility) but is now
    # the MAX across local devices; per-device gauges carry the split
    _reg.gauge("peak_hbm_bytes",
               help="device allocator high-water (bytes, max over local "
                    "devices)").set(peak)
    for d in devs:
        _reg.gauge("device_peak_hbm_bytes",
                   help="per-device allocator high-water (bytes)",
                   device=str(d["device"])).set(d["peak_bytes"])
    _reg.histogram("executor_device_ms",
                   help="compiled step call (fenced iff FLAGS_benchmark)"
                   ).observe(rec.device_ms)
    _reg.histogram("executor_data_wait_ms",
                   help="feed materialization + input-iterator wait"
                   ).observe(rec.data_wait_ms)
    payload = {
        "kind": "step",
        "step": step,
        "data_wait_ms": round(rec.data_wait_ms, 3),
        "compile_ms": round(rec.compile_ms, 3),
        "device_ms": round(rec.device_ms, 3),
        "fetch_ms": round(rec.fetch_ms, 3),
        "ckpt_save_ms": round(rec.ckpt_save_ms, 3),
        "idle_ms": round(rec.idle_ms, 3),
        "cache_hit": rec.cache_hit,
        "fenced": rec.fenced,
        "retraces": _counter("executor_retraces_total").value,
        "peak_hbm_bytes": peak,
    }
    # input-skew window (ISSUE 15): data-wait fraction of recent step
    # wall — heartbeat stamps carry it so a data-starved straggler is
    # named as such, not as a compute straggler
    now_wall = time.time()
    with _lock:
        if _last_commit_wall is not None:
            _dw_window.append((rec.data_wait_ms,
                               max(0.0, (now_wall - _last_commit_wall)
                                   * 1e3)))
        _last_commit_wall = now_wall
    try:
        # join the step's causal trace (PADDLE_TRACING): the record and
        # the span ring now cite each other; key absent when tracing is
        # off, so the documented schema is unchanged by default
        from ..telemetry import tracing

        tid = tracing.last_step_trace_id()
        if tid is not None:
            payload["trace_id"] = tid
    except Exception:  # noqa: BLE001
        pass
    if _keep_recent:
        with _lock:
            _recent_steps.append(dict(payload, ts=round(time.time(), 6)))
    sink.emit(payload)
    try:
        # goodput ledger (ISSUE 15): classify the wall window ending at
        # this commit. Unarmed cost: one cached bool read
        goodput.on_step_commit(payload, now=now_wall)
    except Exception:  # noqa: BLE001 — accounting never fails a step
        pass


def reset_for_tests() -> None:
    """Zero the per-process step state (unit tests only; the registry
    is reset separately via telemetry.get_registry().reset())."""
    global _step_count, _pending_data_wait_ms, _pending_ckpt_save_ms
    global _aux_armed, _keep_recent, _last_run_end, _last_commit_wall
    with _lock:
        _step_count = 0
        _recent.clear()
        _recent_steps.clear()
        _dw_window.clear()
        _pending_data_wait_ms = 0.0
        _pending_ckpt_save_ms = 0.0
    _aux_armed = False
    _keep_recent = False
    _last_run_end = None
    _last_commit_wall = None
    _tls.rec = None
