"""LayerHelper: shared plumbing for fluid.layers.* functions.

Parity surface: python/paddle/fluid/layer_helper.py — creates parameters
(with startup-program init ops), temp variables, appends ops & activations.
"""
from __future__ import annotations

import copy

from . import framework, unique_name
from .dtypes import convert_dtype, is_floating
from .initializer import ConstantInitializer, XavierInitializer
from .param_attr import ParamAttr


class LayerHelper:
    def __init__(self, layer_type: str, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = kwargs.get("name")
        if name is None:
            name = unique_name.generate(layer_type)
        self.name = name

    @property
    def main_program(self) -> framework.Program:
        return framework.default_main_program()

    @property
    def startup_program(self) -> framework.Program:
        return framework.default_startup_program()

    # ------------------------------------------------------------------
    def append_op(self, *args, **kwargs):
        return self.main_program.current_block().append_op(*args, **kwargs)

    def multiple_input(self, input_param_name="input"):
        inputs = self.kwargs.get(input_param_name, [])
        if isinstance(inputs, framework.Variable):
            inputs = [inputs]
        return list(inputs)

    def input(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        if len(inputs) != 1:
            raise ValueError(f"{self.layer_type} layer needs exactly one input")
        return inputs[0]

    @property
    def param_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("param_attr"))

    @property
    def bias_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("bias_attr"))

    def input_dtype(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        dtype = None
        for i in inputs:
            if dtype is None:
                dtype = i.dtype
            elif dtype != i.dtype:
                raise ValueError("mixed input dtypes")
        return dtype

    # ------------------------------------------------------------------
    def create_parameter(
        self,
        attr,
        shape,
        dtype=None,
        is_bias=False,
        default_initializer=None,
    ):
        if attr is False:
            return None
        attr = ParamAttr._to_attr(attr)
        if attr.name is None:
            attr.name = unique_name.generate(".".join([self.name, "w" if not is_bias else "b"]))
        if default_initializer is None:
            if is_bias:
                initializer = attr.initializer or ConstantInitializer(0.0)
            else:
                initializer = attr.initializer or XavierInitializer()
        else:
            initializer = attr.initializer or default_initializer
        dtype = convert_dtype(dtype or "float32")

        startup_block = self.startup_program.global_block()
        sp = startup_block.create_parameter(
            attr.name, shape, dtype, **{k: v for k, v in attr._to_kwargs().items() if k != "name"}
        )
        initializer(sp, startup_block)
        main_block = self.main_program.global_block()
        mp = main_block.create_parameter(
            attr.name, shape, dtype, **{k: v for k, v in attr._to_kwargs().items() if k != "name"}
        )
        return mp

    def create_variable_for_type_inference(self, dtype=None, stop_gradient=False):
        return self.main_program.current_block().create_var(
            name=unique_name.generate(".".join([self.name, "tmp"])),
            dtype=convert_dtype(dtype or "float32"),
            shape=None,
            persistable=False,
            stop_gradient=stop_gradient,
        )

    def create_global_variable(self, persistable=False, *args, **kwargs):
        return self.main_program.global_block().create_var(
            name=unique_name.generate(".".join([self.name, "tmp"])),
            persistable=persistable,
            *args,
            **kwargs,
        )

    def create_or_get_global_variable(self, name, *args, **kwargs):
        block = self.main_program.global_block()
        if name in block.vars:
            return block.vars[name]
        return block.create_var(name=name, persistable=True, *args, **kwargs)

    def set_variable_initializer(self, var, initializer):
        startup_block = self.startup_program.global_block()
        sv = startup_block.create_var(
            name=var.name,
            shape=var.shape,
            dtype=var.dtype,
            persistable=True,
        )
        initializer(sv, startup_block)
        return var

    # ------------------------------------------------------------------
    def append_bias_op(self, input_var, dim_start=1, dim_end=None):
        bias_attr = self.bias_attr
        if bias_attr is False or bias_attr is None:
            return input_var
        size = list(input_var.shape[dim_start:dim_end])
        b = self.create_parameter(bias_attr, shape=size, dtype=input_var.dtype, is_bias=True)
        tmp = self.create_variable_for_type_inference(dtype=input_var.dtype)
        self.append_op(
            type="elementwise_add",
            inputs={"X": [input_var], "Y": [b]},
            outputs={"Out": [tmp]},
            attrs={"axis": dim_start},
        )
        return tmp

    def append_activation(self, input_var):
        act = self.kwargs.get("act")
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {"type": act}
        act = copy.deepcopy(act)
        act_type = act.pop("type")
        tmp = self.create_variable_for_type_inference(dtype=input_var.dtype)
        self.append_op(
            type=act_type,
            inputs={"X": [input_var]},
            outputs={"Out": [tmp]},
            attrs=act,
        )
        return tmp


def emit_op(op_type, ins, attrs=None, out_slots=("Out",), out_dtype=None):
    """Emit one op in the CURRENT mode: static (appends to the default
    program via LayerHelper) or dygraph (runs the registered emitter
    eagerly through the tracer). The shared backend for
    paddle_tpu.nn.functional and the thin 2.0 tensor wrappers."""
    from . import framework

    attrs = attrs or {}
    if framework.in_dygraph_mode():
        from .dygraph.base import _trace_op

        outs = _trace_op(op_type, ins, attrs, list(out_slots))
        return outs[0] if len(outs) == 1 else outs
    helper = LayerHelper(op_type)
    # creation-style ops (randperm etc.) have no inputs: out_dtype rules
    ref = next((v for vs in ins.values() for v in vs), None)
    dtype = out_dtype or (ref.dtype if ref is not None else "float32")
    outs = {
        s: [helper.create_variable_for_type_inference(dtype)]
        for s in out_slots
    }
    helper.append_op(type=op_type, inputs=ins, outputs=outs, attrs=attrs)
    flat = [outs[s][0] for s in out_slots]
    return flat[0] if len(flat) == 1 else flat
