"""Gradient clipping. Parity: python/paddle/fluid/clip.py
(GradientClipByValue, GradientClipByNorm, GradientClipByGlobalNorm)."""
from __future__ import annotations

import numpy as np

from . import framework


class GradientClipBase:
    def _process(self, params_grads):
        raise NotImplementedError

    def __call__(self, params_grads):
        return self._process(params_grads)


class GradientClipByValue(GradientClipBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def _process(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            block = g.block
            c = block.create_var(name=g.name + "@CLIP", dtype=g.dtype, shape=g.shape)
            block.append_op(
                type="clip",
                inputs={"X": [g]},
                outputs={"Out": [c]},
                attrs={"min": self.min, "max": self.max},
            )
            out.append((p, c))
        return out


class GradientClipByNorm(GradientClipBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _process(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            block = g.block
            # clip_by_norm derives the norm internally (ops/math_ops.py)
            c = block.create_var(name=g.name + "@CLIP", dtype=g.dtype, shape=g.shape)
            block.append_op(
                type="clip_by_norm",
                inputs={"X": [g]},
                outputs={"Out": [c]},
                attrs={"max_norm": self.clip_norm},
            )
            out.append((p, c))
        return out


class GradientClipByGlobalNorm(GradientClipBase):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _process(self, params_grads):
        block = None
        sq_norms = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                continue
            block = g.block
            n = block.create_var(name=g.name + "@SQN", dtype=g.dtype, shape=(1,))
            block.append_op(
                type="squared_l2_norm", inputs={"X": [g]}, outputs={"Out": [n]}
            )
            sq_norms.append(n)
        if not sq_norms:
            return params_grads
        total = block.create_var(name=f"@GLOBAL_NORM@{self.group_name}", shape=(1,))
        block.append_op(
            type="sum", inputs={"X": sq_norms}, outputs={"Out": [total]}
        )
        gnorm = block.create_var(name=f"@GLOBAL_NORM_SQRT@{self.group_name}", shape=(1,))
        block.append_op(type="sqrt", inputs={"X": [total]}, outputs={"Out": [gnorm]})
        from .flags import flag as _flag

        if _flag("FLAGS_tensor_stats"):
            # numerics observability (ISSUE 12): the global norm is
            # already computed here — persist it instead of discarding
            # it (grad_global_norm gauge + clip-trigger accounting at
            # the sample cadence). Flag-off: bit-identical build.
            from ..telemetry import numerics as _numerics

            _numerics.install_global_norm_stat(
                gnorm, self.clip_norm, self.group_name)
        # scale = clip_norm / max(global_norm, clip_norm)
        denom = block.create_var(name=f"@GN_DENOM@{self.group_name}", shape=(1,))
        block.append_op(
            type="clip",
            inputs={"X": [gnorm]},
            outputs={"Out": [denom]},
            attrs={"min": self.clip_norm, "max": float(np.finfo(np.float32).max)},
        )
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            scaled_num = g.block.create_var(name=g.name + "@GCLIP_NUM", dtype=g.dtype, shape=g.shape)
            g.block.append_op(
                type="scale",
                inputs={"X": [g]},
                outputs={"Out": [scaled_num]},
                attrs={"scale": self.clip_norm},
            )
            c = g.block.create_var(name=g.name + "@GCLIP", dtype=g.dtype, shape=g.shape)
            g.block.append_op(
                type="elementwise_div",
                inputs={"X": [scaled_num], "Y": [denom]},
                outputs={"Out": [c]},
            )
            out.append((p, c))
        return out


# paddle 1.x aliases
ErrorClipByValue = GradientClipByValue


def set_gradient_clip(clip, param_list=None, program=None):
    program = program or framework.default_main_program()
    program._grad_clip = clip
