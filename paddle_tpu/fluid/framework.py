"""Static-graph IR: Program / Block / Variable / Operator / Parameter.

Parity surface: python/paddle/fluid/framework.py in the reference
(Program:3857, Block:2395, Operator:1821, Variable:834, Parameter:4970).

TPU-native design notes (vs the reference):
- The reference mirrors a C++ protobuf ProgramDesc and interprets it op-by-op.
  Here the Program IS the source of truth in Python; the Executor lowers a
  whole block to a single jitted JAX function (StableHLO via XLA), so there is
  no per-op kernel dispatch at runtime.
- Output shape/dtype inference is done by abstractly evaluating each op's JAX
  emitter (jax.eval_shape) instead of hand-written InferShape functions; a
  dual-probe substitution propagates -1 (batch) dims through the trace.
"""
from __future__ import annotations

import contextlib
import itertools
import copy
import sys
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from . import unique_name
from .dtypes import convert_dtype, dtype_name, is_floating
from .flags import flag

GRAD_VAR_SUFFIX = "@GRAD"
_dummy_batch_probes = (3, 5)

# op attr holding the build-time Python call stack (reference OpDesc attr
# "op_callstack", operator.cc exception enrichment). Double-underscored so
# the registry's attr signatures (registry._attrs_sig) and the generic
# grad path ignore it — pure diagnostics, never semantics.
OP_CALLSTACK_ATTR = "__op_callstack__"


def _capture_callstack(skip: int = 2, limit: int = 32):
    """Cheap (file, line, fn) stack walk for op attribution — no source
    lines are read (unlike traceback.extract_stack), so this costs a few
    microseconds per op. FLAGS_op_callstack=0 disables capture for
    build-speed-critical jobs."""
    if not flag("FLAGS_op_callstack"):
        return None
    try:
        f = sys._getframe(skip)
    except ValueError:
        return None
    out = []
    while f is not None and len(out) < limit:
        code = f.f_code
        out.append((code.co_filename, f.f_lineno, code.co_name))
        f = f.f_back
    return tuple(out)


class Variable:
    """A named tensor slot in a Block.

    Reference: framework.py:834. LoD (ragged-sequence metadata) is represented
    as `lod_level` for API parity, but the TPU build lowers ragged sequences
    to dense padded tensors (see ops/sequence.py), so no runtime LoD exists.
    """

    def __init__(
        self,
        block: "Block",
        name: str,
        shape: Optional[Sequence[int]] = None,
        dtype: Any = "float32",
        lod_level: int = 0,
        persistable: bool = False,
        stop_gradient: bool = False,
        is_data: bool = False,
        trainable: bool = True,
        **kwargs,
    ):
        self.block = block
        self.name = name
        self.shape = tuple(int(s) for s in shape) if shape is not None else None
        self.dtype = convert_dtype(dtype)
        self.lod_level = lod_level
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_data = is_data
        self.trainable = trainable
        # op that produces this var (last writer), for pruning/backward
        self.op: Optional["Operator"] = None

    # -- paddle-compatible sugar -------------------------------------------
    @property
    def ndim(self):
        return len(self.shape) if self.shape is not None else None

    def astype(self, dtype):
        from . import layers

        return layers.cast(self, dtype)

    @property
    def grad_name(self) -> str:
        return self.name + GRAD_VAR_SUFFIX

    def __repr__(self):
        return (
            f"Variable(name={self.name}, shape={self.shape}, "
            f"dtype={dtype_name(self.dtype)}, persistable={self.persistable}, "
            f"stop_gradient={self.stop_gradient})"
        )

    __str__ = __repr__

    # arithmetic sugar (static graph) — defined via layers to emit ops
    def _binary(self, other, fn_name, reverse=False):
        from . import layers

        fn = getattr(layers, fn_name)
        if not isinstance(other, Variable):
            value = float(other)
            dtype = self.dtype
            if not is_floating(dtype) and not value.is_integer():
                # int/bool var against a fractional scalar: a same-dtype
                # constant would silently truncate (x * 0.5 -> x * 0, the
                # bug proglint's fill-truncation check flags). Promote the
                # scalar; the op's jnp promotion yields the float result.
                dtype = "float32"
            other = layers.fill_constant(
                shape=[1], dtype=dtype, value=value
            )
        return fn(other, self) if reverse else fn(self, other)

    def __add__(self, other):
        return self._binary(other, "elementwise_add")

    __radd__ = __add__

    def __sub__(self, other):
        return self._binary(other, "elementwise_sub")

    def __rsub__(self, other):
        return self._binary(other, "elementwise_sub", reverse=True)

    def __mul__(self, other):
        return self._binary(other, "elementwise_mul")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binary(other, "elementwise_div")

    def __rtruediv__(self, other):
        return self._binary(other, "elementwise_div", reverse=True)

    def __matmul__(self, other):
        from . import layers

        return layers.matmul(self, other)

    def __neg__(self):
        from . import layers

        return layers.scale(self, scale=-1.0)

    # comparison sugar (reference layers/math_op_patch.py monkey-patch):
    # emits compare ops, which is what lets AST-converted `if x > 0:`
    # build a cond predicate during a to_static trace
    def __gt__(self, other):
        return self._binary(other, "greater_than")

    def __ge__(self, other):
        return self._binary(other, "greater_equal")

    def __lt__(self, other):
        return self._binary(other, "less_than")

    def __le__(self, other):
        return self._binary(other, "less_equal")


class Parameter(Variable):
    """Trainable persistable variable. Reference: framework.py:4970."""

    def __init__(self, block, name, shape, dtype, **kwargs):
        kwargs.setdefault("persistable", True)
        kwargs.setdefault("stop_gradient", False)
        super().__init__(block, name, shape=shape, dtype=dtype, **kwargs)
        self.trainable = kwargs.get("trainable", True)
        self.regularizer = kwargs.get("regularizer", None)
        self.need_clip = kwargs.get("need_clip", True)
        self.is_distributed = kwargs.get("is_distributed", False)
        self.optimize_attr = kwargs.get("optimize_attr", {"learning_rate": 1.0})


class Operator:
    """One op in a block: type + named input/output var lists + attrs.

    Reference: framework.py:1821 (wrapping C++ OpDesc,
    paddle/fluid/framework/op_desc.h). Inputs/outputs map slot name ->
    list of variable names (strings).
    """

    def __init__(
        self,
        block: "Block",
        type: str,
        inputs: Optional[Dict[str, List[str]]] = None,
        outputs: Optional[Dict[str, List[str]]] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ):
        self.block = block
        self.type = type
        self.inputs = {k: list(v) for k, v in (inputs or {}).items()}
        self.outputs = {k: list(v) for k, v in (outputs or {}).items()}
        self.attrs = dict(attrs or {})

    def input_names(self) -> List[str]:
        return [n for vs in self.inputs.values() for n in vs]

    def output_names(self) -> List[str]:
        return [n for vs in self.outputs.values() for n in vs]

    def input(self, slot: str) -> List[str]:
        return self.inputs.get(slot, [])

    def output(self, slot: str) -> List[str]:
        return self.outputs.get(slot, [])

    def attr(self, name: str, default=None):
        return self.attrs.get(name, default)

    def _set_attr(self, name: str, val):
        self.attrs[name] = val
        self.block.program._bump_version()

    def __repr__(self):
        ins = {k: v for k, v in self.inputs.items()}
        outs = {k: v for k, v in self.outputs.items()}
        return f"Op(type={self.type}, inputs={ins}, outputs={outs})"


class Block:
    """Ordered op list + var map. Reference: framework.py:2395."""

    def __init__(self, program: "Program", idx: int, parent_idx: int = -1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: Dict[str, Variable] = {}
        self.ops: List[Operator] = []

    @property
    def parent_block(self) -> Optional["Block"]:
        if self.parent_idx < 0:
            return None
        return self.program.blocks[self.parent_idx]

    # -- variables ----------------------------------------------------------
    def create_var(self, name=None, **kwargs) -> Variable:
        if name is None:
            name = unique_name.generate("_generated_var")
        if name in self.vars:
            return self.vars[name]
        v = Variable(self, name, **kwargs)
        self.vars[name] = v
        self.program._bump_version()
        return v

    def create_parameter(self, name, shape, dtype, **kwargs) -> Parameter:
        # parameters live in the global (root) block, like the reference
        global_block = self.program.global_block()
        p = Parameter(global_block, name, shape, dtype, **kwargs)
        global_block.vars[name] = p
        self.program._bump_version()
        return p

    def var(self, name: str) -> Variable:
        v = self._find_var_recursive(name)
        if v is None:
            raise ValueError(f"Variable {name!r} not found in block {self.idx}")
        return v

    def has_var(self, name: str) -> bool:
        return self._find_var_recursive(name) is not None

    def _find_var_recursive(self, name: str) -> Optional[Variable]:
        blk: Optional[Block] = self
        while blk is not None:
            if name in blk.vars:
                return blk.vars[name]
            blk = blk.parent_block
        return None

    def all_parameters(self) -> List[Parameter]:
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    # -- ops ----------------------------------------------------------------
    def append_op(
        self,
        type: str,
        inputs: Optional[Dict[str, Any]] = None,
        outputs: Optional[Dict[str, Any]] = None,
        attrs: Optional[Dict[str, Any]] = None,
        infer: bool = True,
    ) -> Operator:
        op = Operator(
            self,
            type,
            inputs=_normalize_io(inputs),
            outputs=_normalize_io(outputs),
            attrs=attrs,
        )
        dev = _current_op_device()
        if dev is not None and "op_device" not in op.attrs:
            op.attrs["op_device"] = dev
        if OP_CALLSTACK_ATTR not in op.attrs:
            cs = _capture_callstack()
            if cs is not None:
                op.attrs[OP_CALLSTACK_ATTR] = cs
        self.ops.append(op)
        self._post_insert(op, infer)
        return op

    def _insert_op(self, index: int, **kwargs) -> Operator:
        infer = kwargs.pop("infer", True)
        op = Operator(
            self,
            kwargs["type"],
            inputs=_normalize_io(kwargs.get("inputs")),
            outputs=_normalize_io(kwargs.get("outputs")),
            attrs=kwargs.get("attrs"),
        )
        if OP_CALLSTACK_ATTR not in op.attrs:
            cs = _capture_callstack()
            if cs is not None:
                op.attrs[OP_CALLSTACK_ATTR] = cs
        self.ops.insert(index, op)
        self._post_insert(op, infer)
        return op

    def _remove_op(self, index: int):
        del self.ops[index]
        self.program._bump_version()

    def _post_insert(self, op: Operator, infer: bool):
        # ensure output vars exist; infer their shapes/dtypes from the emitter
        for slot, names in op.outputs.items():
            for n in names:
                if self._find_var_recursive(n) is None:
                    self.create_var(name=n)
        if infer:
            try:
                infer_op_outputs(self, op)
            except Exception as e:  # noqa: BLE001 — surface op context
                raise RuntimeError(
                    f"shape inference failed for op {op.type}: {e}"
                ) from e
        for n in op.output_names():
            self._find_var_recursive(n).op = op
        self.program._bump_version()

    def __repr__(self):
        lines = [f"Block(idx={self.idx}, parent={self.parent_idx}) {{"]
        for v in self.vars.values():
            lines.append(f"  {v}")
        for op in self.ops:
            lines.append(f"  {op}")
        lines.append("}")
        return "\n".join(lines)


_program_serial_counter = itertools.count()


class Program:
    """A list of blocks; block 0 is global. Reference: framework.py:3857."""

    def __init__(self):
        self.blocks: List[Block] = [Block(self, 0)]
        self.current_block_idx = 0
        self.random_seed = 0
        self._version = 0
        # monotonic identity for the executor compile cache: id() can be
        # REUSED by CPython after a Program is GC'd, aliasing a stale
        # cache entry when feed/fetch signatures happen to match
        self._serial = next(_program_serial_counter)
        # set by AMP / fleet passes; consumed by the Executor
        self._amp_enabled = False
        self._mesh = None  # paddle_tpu.parallel mesh attached by fleet

    def _bump_version(self):
        self._version += 1

    def global_block(self) -> Block:
        return self.blocks[0]

    def current_block(self) -> Block:
        return self.blocks[self.current_block_idx]

    def block(self, idx: int) -> Block:
        return self.blocks[idx]

    @property
    def num_blocks(self) -> int:
        return len(self.blocks)

    def _create_block(self, parent_idx: Optional[int] = None) -> Block:
        if parent_idx is None:
            parent_idx = self.current_block_idx
        b = Block(self, len(self.blocks), parent_idx)
        self.blocks.append(b)
        self.current_block_idx = b.idx
        self._bump_version()
        return b

    def _rollback(self):
        self.current_block_idx = self.current_block().parent_idx
        if self.current_block_idx < 0:
            self.current_block_idx = 0

    def all_parameters(self) -> List[Parameter]:
        return self.global_block().all_parameters()

    def list_vars(self):
        for b in self.blocks:
            yield from b.vars.values()

    def clone(self, for_test: bool = False) -> "Program":
        """Deep-copy the program. for_test=True marks test mode: ops like
        dropout/batch_norm read attr is_test (rewritten here)."""
        p = Program.__new__(Program)
        p.blocks = []
        p.current_block_idx = 0
        p.random_seed = self.random_seed
        p._version = 0
        p._serial = next(_program_serial_counter)  # own compile-cache identity
        p._amp_enabled = self._amp_enabled
        p._mesh = self._mesh
        for b in self.blocks:
            nb = Block(p, b.idx, b.parent_idx)
            p.blocks.append(nb)
        for b, nb in zip(self.blocks, p.blocks):
            for name, v in b.vars.items():
                cls = Parameter if isinstance(v, Parameter) else Variable
                nv = cls.__new__(cls)
                nv.__dict__.update({k: w for k, w in v.__dict__.items() if k not in ("block", "op")})
                nv.block = nb
                nv.op = None
                nb.vars[name] = nv
            for op in b.ops:
                attrs = {
                    k: (v if not isinstance(v, Block) else p.blocks[v.idx])
                    for k, v in op.attrs.items()
                }
                # fused recompute segments carry live sub-Operator lists:
                # copy them (no aliasing with the source program) and apply
                # the is_test rewrite inside the segment too (dropout etc.)
                if "recompute_sub_ops" in attrs:
                    subs = []
                    for sop in attrs["recompute_sub_ops"]:
                        nsop = Operator(
                            nb,
                            sop.type,
                            inputs=copy.deepcopy(sop.inputs),
                            outputs=copy.deepcopy(sop.outputs),
                            attrs=dict(sop.attrs),
                        )
                        if for_test and "is_test" in nsop.attrs:
                            nsop.attrs["is_test"] = True
                        subs.append(nsop)
                    attrs["recompute_sub_ops"] = subs
                nop = Operator(
                    nb,
                    op.type,
                    inputs=copy.deepcopy(op.inputs),
                    outputs=copy.deepcopy(op.outputs),
                    attrs=attrs,
                )
                if for_test and "is_test" in nop.attrs:
                    nop.attrs["is_test"] = True
                nb.ops.append(nop)
                for n in nop.output_names():
                    fv = nb._find_var_recursive(n)
                    if fv is not None:
                        fv.op = nop
        p._bump_version()
        return p

    def __repr__(self):
        return "\n".join(repr(b) for b in self.blocks)


# ---------------------------------------------------------------------------
# shape/dtype inference by abstract evaluation of the op emitter
# ---------------------------------------------------------------------------


def compute_op_output_metas(block: Block, op: Operator):
    """Pure output-meta inference: {slot: [(shape, dtype)]} from the
    registered emitter (jax.eval_shape dual-probe for -1 dims) or the
    explicit infer_shape override. Returns None for no_infer ops. Never
    mutates the program — the static verifier (fluid/analysis) re-runs
    this to cross-check recorded metadata after graph rewrites."""
    from ..ops import registry

    spec = registry.get(op.type)
    if spec is None:
        raise KeyError(f"op {op.type!r} is not registered")
    in_metas = {
        slot: [_var_meta(block, n) for n in names]
        for slot, names in op.inputs.items()
    }
    if spec.infer_shape is not None:
        # explicit override (control flow, data-dependent shapes)
        return spec.infer_shape(in_metas, op.attrs)
    if spec.no_infer:
        return None

    has_dynamic = any(
        (m[0] is not None and -1 in m[0]) for ms in in_metas.values() for m in ms
    )
    probes = _dummy_batch_probes if has_dynamic else (_dummy_batch_probes[0],)
    results = [registry.abstract_eval(op.type, in_metas, op.attrs, probe) for probe in probes]
    out0 = results[0]
    metas = {}
    for slot in out0:
        metas[slot] = []
        for i, (shape0, dt) in enumerate(out0[slot]):
            if len(results) > 1:
                shape1 = results[1][slot][i][0]
                shape = tuple(
                    -1 if a != b else a for a, b in zip(shape0, shape1)
                )
            else:
                shape = shape0
            metas[slot].append((shape, dt))
    return metas


def infer_op_outputs(block: Block, op: Operator):
    """Set shapes/dtypes of op's output vars by abstractly tracing the
    registered JAX emitter (twice, with different probe values standing in
    for -1 dims, to detect batch-dim propagation)."""
    metas = compute_op_output_metas(block, op)
    if metas is not None:
        _apply_metas(block, op, metas)


def _apply_metas(block, op, metas):
    for slot, names in op.outputs.items():
        ms = metas.get(slot)
        if ms is None:
            continue
        for n, (shape, dt) in zip(names, ms):
            v = block._find_var_recursive(n)
            v.shape = tuple(shape) if shape is not None else None
            if dt is not None:
                v.dtype = convert_dtype(dt)


def _var_meta(block, name):
    v = block.var(name)
    return (v.shape, v.dtype)


def _normalize_io(io: Optional[Dict[str, Any]]) -> Dict[str, List[str]]:
    out: Dict[str, List[str]] = {}
    for slot, val in (io or {}).items():
        if val is None:
            continue
        if isinstance(val, (Variable, str)):
            val = [val]
        out[slot] = [v.name if isinstance(v, Variable) else str(v) for v in val]
    return out


# ---------------------------------------------------------------------------
# default programs & guards (reference: framework.py program_guard etc.)
# ---------------------------------------------------------------------------

_main_program_ = Program()
_startup_program_ = Program()


def default_main_program() -> Program:
    return _main_program_


def default_startup_program() -> Program:
    return _startup_program_


def switch_main_program(program: Program) -> Program:
    global _main_program_
    old = _main_program_
    _main_program_ = program
    return old


def switch_startup_program(program: Program) -> Program:
    global _startup_program_
    old = _startup_program_
    _startup_program_ = program
    return old


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Optional[Program] = None):
    old_main = switch_main_program(main_program)
    old_startup = None
    if startup_program is not None:
        old_startup = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(old_main)
        if old_startup is not None:
            switch_startup_program(old_startup)


# ---------------------------------------------------------------------------
# device_guard: pipeline-stage annotation (reference fluid.device_guard;
# ops get attr "op_device" like the reference's OpDesc attribute consumed by
# PipelineOptimizer, optimizer.py:3627)
# ---------------------------------------------------------------------------

_op_device_stack: List[str] = []


@contextlib.contextmanager
def device_guard(device: Optional[str] = None):
    """Annotate ops appended in this scope with a device/stage tag, e.g.
    "gpu:0". On TPU the tag names a pipeline stage, not a physical device —
    placement is the mesh's job."""
    _op_device_stack.append(device)
    try:
        yield
    finally:
        _op_device_stack.pop()


def _current_op_device() -> Optional[str]:
    return _op_device_stack[-1] if _op_device_stack else None


# dygraph mode switch (filled in by paddle_tpu.fluid.dygraph)
_dygraph_tracer_ = None


def in_dygraph_mode() -> bool:
    return _dygraph_tracer_ is not None


def _dygraph_tracer():
    return _dygraph_tracer_


def grad_var_name(name: str) -> str:
    return name + GRAD_VAR_SUFFIX
