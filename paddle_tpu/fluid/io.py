"""Checkpoint / model IO.

Parity surface: /root/reference/python/paddle/fluid/io.py —
save_params:373, save_persistables:598, load_persistables:966,
save_inference_model:1164, load_inference_model:1374, save:1669, load:1733.

TPU-native design: the reference runs save/load **ops** through the
executor (operators/save_op.cc) so checkpointing is graph execution; here
persistable scope arrays are saved with Orbax (sharded-array aware — a
TP/DP-sharded train state checkpoints and restores across different mesh
shapes, the jax-native story the reference's per-pserver block checkpoints
approximate). The "persistables by name" contract is preserved.
"""
from __future__ import annotations

import json
import os
import pickle
from typing import Dict, List, Optional, Sequence

import numpy as np

from . import framework
from .executor import global_scope


def _fsync_enabled() -> bool:
    """PADDLE_CKPT_FSYNC gates the durability fsyncs (file contents AND
    their parent directory) across every save path. Default ON: tmp +
    os.replace alone is atomic against a process kill but NOT against
    power loss — the rename can hit stable storage before the contents
    it points at. Tests that hammer checkpoints may opt out with
    PADDLE_CKPT_FSYNC=0."""
    return os.environ.get("PADDLE_CKPT_FSYNC", "1").lower() not in (
        "0", "false", "off", "no")


def _fsync_dir(path: str) -> None:
    """fsync a DIRECTORY so a just-created/renamed entry in it is
    durable (no-op on platforms without dir fsync, and when the
    PADDLE_CKPT_FSYNC opt-out is set)."""
    if not _fsync_enabled():
        return
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass


def _atomic_write_bytes(path: str, blob: bytes,
                        crash_phase: Optional[str] = None) -> None:
    """Write-to-temp + os.replace: a crash mid-save can never leave a
    torn file at `path` for preload/load_train_model to reject — the
    reader sees either the complete old file or the complete new one
    (same contract as ps_server.PSServer.snapshot). The file is fsynced
    before the rename and the parent directory after it (power-loss
    durability; PADDLE_CKPT_FSYNC=0 opts out). `crash_phase` names a
    deterministic kill site between the tmp write and the rename
    (faults `crash:<phase>:<nth>` rules — the "during manifest rename"
    drill)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            if _fsync_enabled():
                os.fsync(f.fileno())
        if crash_phase is not None:
            from ..distributed import faults

            faults.crash_point(crash_phase)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    _fsync_dir(os.path.dirname(path) or ".")


def _persistable_names(program) -> List[str]:
    return [v.name for v in program.list_vars() if v.persistable]


def _param_names(program) -> List[str]:
    return [p.name for p in program.all_parameters()]


def _save_arrays(dirname: str, names: List[str], scope,
                 filename: Optional[str] = None, encrypt_key=None):
    os.makedirs(dirname, exist_ok=True)
    arrays = {}
    for n in names:
        v = scope.find_var(n)
        if v is None:
            raise RuntimeError(f"variable {n!r} not found in scope; nothing to save")
        arrays[n] = np.asarray(v)

    def _write(path, dump):
        import io as _io

        buf = _io.BytesIO()
        dump(buf)
        blob = buf.getvalue()
        if encrypt_key is not None:
            from . import crypto

            blob = crypto.encrypt_bytes(blob, encrypt_key)
        _atomic_write_bytes(path, blob)

    if filename is not None:
        _write(os.path.join(dirname, filename),
               lambda b: np.savez(b, **arrays))
    else:
        for n, a in arrays.items():
            _write(os.path.join(dirname, n.replace("/", "__slash__") + ".npy"),
                   lambda b, _a=a: np.save(b, _a))


def _load_arrays(dirname: str, names: List[str], scope,
                 filename: Optional[str] = None, decrypt_key=None):
    import io as _io

    import jax.numpy as jnp

    def _read(path):
        with open(path, "rb") as f:
            blob = f.read()
        if decrypt_key is not None:
            from . import crypto

            blob = crypto.decrypt_bytes(blob, decrypt_key)
        return _io.BytesIO(blob)

    if filename is not None:
        with np.load(_read(os.path.join(dirname, filename))) as z:
            found = {n: z[n] for n in names if n in z.files}
            missing = [n for n in names if n not in z.files]
    else:
        found, missing = {}, []
        for n in names:
            p = os.path.join(dirname, n.replace("/", "__slash__") + ".npy")
            if os.path.exists(p):
                found[n] = np.load(_read(p))
            else:
                missing.append(n)
    if missing:
        raise RuntimeError(f"checkpoint at {dirname!r} is missing variables: {missing}")
    for n, a in found.items():
        scope.set_var(n, jnp.asarray(a))


def _ps_table_names(program) -> List[str]:
    names = []
    for block in program.blocks:
        for op in block.ops:
            if op.type == "distributed_lookup_table":
                # mirror the emitter's attr fallback (ops/ps_ops.py:95)
                got = op.attr("table_names", []) or (
                    [op.attr("table_name")] if op.attr("table_name")
                    else [])
                names.extend(got)
    return sorted(set(names))


def _save_ps_tables(dirname: str, program) -> None:
    """Checkpoint host/pserver tables alongside the scope persistables
    (the reference pulls parameter blocks back from pservers at save —
    io.py:1019 + checkpoint_notify_op; here the table's state_dict is
    pickled to `<dirname>/<table>.pkl`, the SAME format
    fleet.init_server(model_dir)/ps_server preload restores from)."""
    import warnings

    from ..distributed import ps

    for name in _ps_table_names(program):
        try:
            t = ps.get_table(name)
        except KeyError:
            # surface NOW, not at the far-away restore: loading this
            # "successful" checkpoint would fail on the missing .pkl
            warnings.warn(
                f"save: program references PS table {name!r} but no such "
                f"table is registered in this process — the checkpoint "
                f"will NOT contain it and load_persistables will reject "
                f"it. create_table before saving (or drop the lookup op)",
                RuntimeWarning, stacklevel=3)
            continue
        _atomic_write_bytes(os.path.join(dirname, f"{name}.pkl"),
                            pickle.dumps(t.state_dict()))


def _load_ps_tables(dirname: str, program) -> None:
    for name in _ps_table_names(program):
        path = os.path.join(dirname, f"{name}.pkl")
        if not os.path.exists(path):
            raise RuntimeError(
                f"checkpoint at {dirname!r} is missing PS table "
                f"{name!r} ({name}.pkl); the program's "
                f"distributed_lookup_table ops cannot resume without it")
        from ..distributed import ps

        with open(path, "rb") as f:
            ps.get_table(name).load_state_dict(pickle.load(f))


def save_params(executor, dirname, main_program=None, filename=None):
    """reference io.py:373 — trainable parameters only."""
    program = main_program or framework.default_main_program()
    _save_arrays(dirname, _param_names(program), global_scope(), filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    """reference io.py:598 — params + optimizer moments + LR etc.;
    host/pserver embedding tables ride along as <table>.pkl."""
    program = main_program or framework.default_main_program()
    _save_arrays(dirname, _persistable_names(program), global_scope(), filename)
    _save_ps_tables(dirname, program)


def load_params(executor, dirname, main_program=None, filename=None):
    program = main_program or framework.default_main_program()
    _load_arrays(dirname, _param_names(program), global_scope(), filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    program = main_program or framework.default_main_program()
    _load_arrays(dirname, _persistable_names(program), global_scope(), filename)
    _load_ps_tables(dirname, program)


# ---------------------------------------------------------------------------
# inference model export: prune program to feed->fetch subgraph + params
# ---------------------------------------------------------------------------


def _prune_for_inference(program, feed_names: List[str], fetch_vars,
                         state_vars: Sequence[str] = ()) -> "framework.Program":
    """Backward slice from fetch vars, like the reference's prune
    (io.py:1164 save_inference_model -> Program._prune_with_input).

    state_vars: extra slice roots for state-carrying vars (decode-step
    KV caches: read at an earlier op, written back at a later one).
    Nothing downstream of the fetches needs the write-back op, so a
    pure fetch-rooted slice would drop it and the frozen program would
    stop carrying state across steps — seeding `needed` keeps the
    writer chain live."""
    pruned = program.clone(for_test=True)
    block = pruned.global_block()
    fetch_names = {v.name if isinstance(v, framework.Variable) else str(v) for v in fetch_vars}
    needed = set(fetch_names) | {str(n) for n in state_vars}
    keep: List[int] = []
    for i in range(len(block.ops) - 1, -1, -1):
        op = block.ops[i]
        if any(n in needed for n in op.output_names()):
            keep.append(i)
            needed.update(op.input_names())
    keep_set = set(keep)
    block.ops = [op for i, op in enumerate(block.ops) if i in keep_set]
    return pruned


def _serialize_program(program) -> bytes:
    """Pickle the op list + var metas (the ProgramDesc analog; the C++
    protobuf serializer arrives with the native runtime layer)."""
    blocks = []
    for b in program.blocks:
        blocks.append(
            {
                "idx": b.idx,
                "parent_idx": b.parent_idx,
                "vars": {
                    name: {
                        "shape": v.shape,
                        "dtype": str(np.dtype(v.dtype)) if v.dtype is not None else None,
                        "persistable": v.persistable,
                        "stop_gradient": v.stop_gradient,
                        "is_data": v.is_data,
                        "is_parameter": isinstance(v, framework.Parameter),
                        "trainable": getattr(v, "trainable", False),
                    }
                    for name, v in b.vars.items()
                },
                "ops": [
                    {
                        "type": op.type,
                        "inputs": op.inputs,
                        "outputs": op.outputs,
                        "attrs": {
                            k: (("__block__", v.idx) if isinstance(v, framework.Block) else v)
                            for k, v in op.attrs.items()
                        },
                    }
                    for op in b.ops
                ],
            }
        )
    return pickle.dumps({"version": 1, "blocks": blocks})


def _deserialize_program(data: bytes) -> "framework.Program":
    payload = pickle.loads(data)
    program = framework.Program()
    program.blocks = []
    for bd in payload["blocks"]:
        blk = framework.Block(program, bd["idx"], bd["parent_idx"])
        program.blocks.append(blk)
    for bd, blk in zip(payload["blocks"], program.blocks):
        for name, meta in bd["vars"].items():
            cls = framework.Parameter if meta["is_parameter"] else framework.Variable
            v = cls.__new__(cls)
            v.block = blk
            v.name = name
            v.shape = tuple(meta["shape"]) if meta["shape"] is not None else None
            v.dtype = np.dtype(meta["dtype"]) if meta["dtype"] else np.dtype("float32")
            v.lod_level = 0
            v.persistable = meta["persistable"]
            v.stop_gradient = meta["stop_gradient"]
            v.is_data = meta["is_data"]
            v.trainable = meta.get("trainable", False)
            v.op = None
            if meta["is_parameter"]:
                v.regularizer = None
                v.need_clip = True
                v.is_distributed = False
                v.optimize_attr = {"learning_rate": 1.0}
            blk.vars[name] = v
        for od in bd["ops"]:
            attrs = {
                k: (program.blocks[v[1]] if isinstance(v, tuple) and len(v) == 2 and v[0] == "__block__" else v)
                for k, v in od["attrs"].items()
            }
            op = framework.Operator(blk, od["type"], inputs=od["inputs"], outputs=od["outputs"], attrs=attrs)
            blk.ops.append(op)
            for n in op.output_names():
                fv = blk._find_var_recursive(n)
                if fv is not None:
                    fv.op = op
    program._bump_version()
    return program


def save_inference_model(
    dirname,
    feeded_var_names: List[str],
    target_vars,
    executor,
    main_program=None,
    model_filename=None,
    params_filename=None,
    encrypt_key=None,
):
    """reference io.py:1164 — prune to the inference subgraph + save params.
    encrypt_key: AES-encrypt the serialized program (reference
    framework/io/crypto cipher applied at save time)."""
    program = main_program or framework.default_main_program()
    pruned = _prune_for_inference(program, feeded_var_names, target_vars)
    os.makedirs(dirname, exist_ok=True)
    model_filename = model_filename or "__model__"
    blob = _serialize_program(pruned)
    if encrypt_key is not None:
        from . import crypto

        blob = crypto.encrypt_bytes(blob, encrypt_key)
    _atomic_write_bytes(os.path.join(dirname, model_filename), blob)
    fetch_names = [
        v.name if isinstance(v, framework.Variable) else str(v) for v in target_vars
    ]
    _atomic_write_bytes(
        os.path.join(dirname, "__meta__.json"),
        json.dumps({"feed_names": list(feeded_var_names),
                    "fetch_names": fetch_names}).encode())
    # save every persistable reachable in the pruned graph — Parameters
    # AND buffers (BatchNorm running stats, traced constants); a
    # Parameters-only filter would silently drop buffers and make the
    # model unloadable
    used = {n for op in pruned.global_block().ops for n in op.input_names()}
    pnames = [
        v.name for v in pruned.list_vars() if v.persistable and v.name in used
    ]
    _save_arrays(dirname, pnames, global_scope(), params_filename,
                 encrypt_key=encrypt_key)
    return fetch_names


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None, decrypt_key=None):
    """reference io.py:1374 — returns (program, feed_names, fetch_vars)."""
    model_filename = model_filename or "__model__"
    with open(os.path.join(dirname, model_filename), "rb") as f:
        blob = f.read()
    if decrypt_key is not None:
        from . import crypto

        blob = crypto.decrypt_bytes(blob, decrypt_key)
    program = _deserialize_program(blob)
    with open(os.path.join(dirname, "__meta__.json")) as f:
        meta = json.load(f)
    used = {n for op in program.global_block().ops for n in op.input_names()}
    pnames = [
        v.name for v in program.list_vars() if v.persistable and v.name in used
    ]
    _load_arrays(dirname, pnames, global_scope(), params_filename,
                 decrypt_key=decrypt_key)
    fetch_vars = [program.global_block().var(n) for n in meta["fetch_names"]]
    return program, meta["feed_names"], fetch_vars


# ---------------------------------------------------------------------------
# new-style whole-state save/load (reference io.py:1669/1733) — Orbax-backed
# sharded checkpointing for distributed train state
# ---------------------------------------------------------------------------


def save(program, model_path: str):
    """Orbax sharded checkpoint of all persistables (+ program text);
    host/pserver tables ride along as `<model_path>.ps/<table>.pkl` —
    the table's W left the device program (transpiler), so the scope
    walk alone would silently lose the embedding state."""
    import orbax.checkpoint as ocp

    scope = global_scope()
    state = {}
    for n in _persistable_names(program):
        v = scope.find_var(n)
        if v is not None:
            state[n.replace("/", "__slash__")] = v
    path = os.path.abspath(model_path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path + ".ckpt", state, force=True)
    ckptr.wait_until_finished()
    _atomic_write_bytes(path + ".pdmodel", _serialize_program(program))
    if _ps_table_names(program):
        os.makedirs(path + ".ps", exist_ok=True)
        _save_ps_tables(path + ".ps", program)


def load(program, model_path: str, executor=None):
    import jax
    import orbax.checkpoint as ocp

    scope = global_scope()
    path = os.path.abspath(model_path)
    ckptr = ocp.StandardCheckpointer()
    restored = ckptr.restore(path + ".ckpt")
    for n, a in restored.items():
        scope.set_var(n.replace("__slash__", "/"), jax.numpy.asarray(a))
    if _ps_table_names(program):
        _load_ps_tables(path + ".ps", program)


# ---------------------------------------------------------------------------
# train-model export/import: the C++ training-driver story (reference
# fluid/train/demo — train a saved program WITHOUT Python on the driver
# side; here the C API embeds CPython and drives this loader)
# ---------------------------------------------------------------------------


def save_train_model(executor, dirname, feed_names, loss, main_program=None,
                     startup_program=None):
    """Serialize the FULL training program (forward+backward+optimizer),
    its startup program, the feed/loss names, and current persistables."""
    main_program = main_program or framework.default_main_program()
    startup_program = startup_program or framework.default_startup_program()
    os.makedirs(dirname, exist_ok=True)
    _atomic_write_bytes(os.path.join(dirname, "__train_model__"),
                        pickle.dumps({
                            "version": 1,
                            "main": _serialize_program(main_program),
                            "startup": _serialize_program(startup_program),
                            "feed_names": list(feed_names),
                            "loss_name": loss if isinstance(loss, str)
                            else loss.name,
                        }))
    save_persistables(executor, dirname, main_program=main_program)


def load_train_model(executor, dirname):
    """Returns (main_program, startup_program, feed_names, loss_name);
    runs the startup program and restores saved persistables."""
    with open(os.path.join(dirname, "__train_model__"), "rb") as f:
        meta = pickle.load(f)
    main = _deserialize_program(meta["main"])
    startup = _deserialize_program(meta["startup"])
    executor.run(startup)
    load_persistables(executor, dirname, main_program=main)
    return main, startup, meta["feed_names"], meta["loss_name"]
