"""Dtype utilities: paddle-style dtype strings <-> numpy/jax dtypes.

Mirrors the VarType.Type enum surface of the reference
(/root/reference/paddle/fluid/framework/framework.proto:104) without the
protobuf dependency on the hot path: dtypes are canonicalized to numpy dtypes,
which is what JAX/XLA consume natively.
"""
from __future__ import annotations

import numpy as np

# Canonical names follow the reference's VarType.Type spelling (lowered).
_STR2NP = {
    "bool": np.dtype("bool"),
    "int8": np.dtype("int8"),
    "uint8": np.dtype("uint8"),
    "int16": np.dtype("int16"),
    "int32": np.dtype("int32"),
    "int64": np.dtype("int64"),
    "float16": np.dtype("float16"),
    "bfloat16": None,  # filled lazily to avoid importing jax at module load
    "float32": np.dtype("float32"),
    "float64": np.dtype("float64"),
    "complex64": np.dtype("complex64"),
    "complex128": np.dtype("complex128"),
}


def _bf16():
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16)


def convert_dtype(dtype) -> np.dtype:
    """Canonicalize any dtype spec (str, np.dtype, jnp dtype) to np.dtype."""
    if dtype is None:
        return np.dtype("float32")
    if isinstance(dtype, str):
        key = dtype.lower()
        if key == "bfloat16":
            return _bf16()
        if key in _STR2NP:
            return _STR2NP[key]
        return np.dtype(dtype)
    try:
        d = np.dtype(dtype)
        return d
    except TypeError:
        # jax weak types / ml_dtypes scalars
        return np.dtype(getattr(dtype, "dtype", dtype))


def dtype_name(dtype) -> str:
    d = convert_dtype(dtype)
    return d.name


def is_floating(dtype) -> bool:
    d = convert_dtype(dtype)
    return np.issubdtype(d, np.floating) or d.name == "bfloat16"


def is_integer(dtype) -> bool:
    return np.issubdtype(convert_dtype(dtype), np.integer)


def runtime_dtype(dtype) -> np.dtype:
    """Device-side dtype for array CREATION under this framework's
    standard x64-off jax config: a 64-bit request would be truncated by
    jax anyway, with a UserWarning on every call — narrow it to the
    32-bit runtime equivalent explicitly instead. Variable METADATA keeps
    the declared 64-bit dtype (reference parity); only device arrays
    narrow. With jax_enable_x64 on, 64-bit passes through untouched."""
    d = convert_dtype(dtype)
    if d.kind in "iuf" and d.itemsize == 8:
        from jax import config as _jcfg

        if not bool(getattr(_jcfg, "jax_enable_x64", False)):
            return np.dtype(d.kind + "4")
    return d
