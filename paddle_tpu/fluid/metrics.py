"""Training metrics with host-side accumulation.

Parity surface: /root/reference/python/paddle/fluid/metrics.py
(MetricBase, Accuracy, Precision, Recall, Auc, CompositeMetric,
ChunkEvaluator, EditDistance) — host-side numpy accumulators fed from
fetched values; the heavy per-batch math (topk/compare) stays in-graph
via layers.accuracy / layers.auc.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np


class MetricBase:
    def __init__(self, name: Optional[str] = None):
        self._name = name or self.__class__.__name__

    def reset(self):
        for k, v in self.__dict__.items():
            if k.startswith("_"):
                continue
            if isinstance(v, (int, float)):
                setattr(self, k, 0 if isinstance(v, int) else 0.0)
            elif isinstance(v, np.ndarray):
                setattr(self, k, np.zeros_like(v))

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError

    def get_config(self):
        return {
            k: v for k, v in self.__dict__.items() if not k.startswith("_")
        }


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics: List[MetricBase] = []

    def add_metric(self, metric: MetricBase):
        self._metrics.append(metric)

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        self.value += float(np.asarray(value).reshape(-1)[0]) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("Accuracy: no samples accumulated")
        return self.value / self.weight


class Precision(MetricBase):
    """Binary precision on {0,1} predictions (reference metrics.py Precision)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0.0
        self.fp = 0.0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels == 0)))

    def eval(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0.0
        self.fn = 0.0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds == 0) & (labels == 1)))

    def eval(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0


class Auc(MetricBase):
    """Streaming ROC-AUC over (prob, label) batches, histogram-bucketed
    (reference metrics.Auc; operators/metrics/auc_op.cc)."""

    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        if curve not in ("ROC", "PR"):
            raise ValueError(f"Auc curve {curve!r}: use 'ROC' or 'PR'")
        self.curve = curve
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, np.int64)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        score = preds[:, -1] if preds.ndim == 2 else preds.reshape(-1)
        labels = np.asarray(labels).reshape(-1)
        idx = np.clip((score * self.num_thresholds).astype(np.int64), 0,
                      self.num_thresholds)
        np.add.at(self._stat_pos, idx[labels > 0], 1)
        np.add.at(self._stat_neg, idx[labels <= 0], 1)

    def eval(self):
        pos = np.cumsum(self._stat_pos[::-1])
        neg = np.cumsum(self._stat_neg[::-1])
        tot_pos, tot_neg = pos[-1], neg[-1]
        if self.curve == "PR":
            if tot_pos == 0:
                return 0.0
            tp = pos.astype(np.float64)
            fp = neg.astype(np.float64)
            # no predictions above threshold -> precision is vacuous (1):
            # emitting 0 there would poison the trapezoid at recall 0
            prec = np.where(tp + fp > 0, tp / np.maximum(tp + fp, 1.0), 1.0)
            rec = tp / tot_pos
            p_pts = np.concatenate([[1.0], prec])
            r_pts = np.concatenate([[0.0], rec])
            return float(np.sum(
                (r_pts[1:] - r_pts[:-1]) * (p_pts[1:] + p_pts[:-1]) / 2.0))
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        x = np.concatenate([[0], neg])
        y = np.concatenate([[0], pos])
        area = np.sum((x[1:] - x[:-1]) * (y[1:] + y[:-1])) / 2.0
        return float(area / (tot_pos * tot_neg))


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        distances = np.asarray(distances).reshape(-1)
        self.total_distance += float(np.sum(distances))
        self.seq_num += int(seq_num)
        self.instance_error += int(np.sum(distances > 0))

    def eval(self):
        if self.seq_num == 0:
            raise ValueError("EditDistance: no data")
        return (
            self.total_distance / self.seq_num,
            self.instance_error / self.seq_num,
        )
