"""DataLoader / GeneratorLoader / PyReader: host data pipeline.

Parity surface: /root/reference/python/paddle/fluid/reader.py
(DataLoader:112, from_generator:372, GeneratorLoader:953, PyReader:1213)
and the C++ reader ops (operators/reader/buffered_reader.cc — async
double buffering).

TPU-native design: the reference pushes LoDTensors into a C++ blocking
queue consumed by read ops inside the program. Here feeding is explicit
(Executor.run(feed=...)), so the loader's job is pipelining: a background
thread drains the user generator into a bounded queue (double buffering)
while the previous step runs on device; batches come out as feed dicts.
The file-backed path is the native C++ feed (paddle_tpu/native)."""
from __future__ import annotations

import queue
import threading
from typing import Callable, List, Optional, Sequence

import numpy as np

from . import framework

_END = object()


class GeneratorLoader:
    """Reference reader.py:953. iterable mode only (the non-iterable
    start()/reset() protocol existed for in-program read ops, which the
    whole-block XLA executor does not need)."""

    def __init__(self, feed_list=None, capacity=64, iterable=True,
                 return_list=False, drop_last=True):
        self._feed_list = list(feed_list or [])
        self._names = [
            v.name if isinstance(v, framework.Variable) else str(v)
            for v in self._feed_list
        ]
        self._capacity = int(capacity)
        self._iterable = iterable
        self._return_list = return_list
        self._drop_last = drop_last
        self._batch_reader: Optional[Callable] = None

    # -- generator flavors (reference from_generator API) ----------------
    def set_sample_generator(self, reader, batch_size, drop_last=True, places=None):
        def batch_reader():
            batch = []
            for sample in reader():
                if not isinstance(sample, (tuple, list)):
                    sample = (sample,)
                batch.append(sample)
                if len(batch) == batch_size:
                    yield _stack_samples(batch)
                    batch = []
            if batch and not drop_last:
                yield _stack_samples(batch)

        self._batch_reader = batch_reader
        return self

    def set_sample_list_generator(self, reader, places=None):
        def batch_reader():
            for sample_list in reader():
                yield _stack_samples(sample_list)

        self._batch_reader = batch_reader
        return self

    def set_batch_generator(self, reader, places=None):
        self._batch_reader = reader
        return self

    # -- iteration with background prefetch ------------------------------
    def __iter__(self):
        if self._batch_reader is None:
            raise RuntimeError(
                "DataLoader: call set_sample_generator / "
                "set_sample_list_generator / set_batch_generator first"
            )
        q: queue.Queue = queue.Queue(maxsize=self._capacity)
        err: List[BaseException] = []
        stop = threading.Event()

        def _put(item) -> bool:
            # timed put + stop flag: when the consumer abandons iteration
            # (break / early stop) the worker exits instead of blocking on
            # a full queue forever (one leaked thread per abandoned epoch)
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            try:
                for batch in self._batch_reader():
                    if not _put(batch):
                        return
            except BaseException as e:  # noqa: BLE001 — re-raised on consumer
                err.append(e)
            finally:
                _put(_END)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is _END:
                    if err:
                        raise err[0]
                    return
                arrays = [np.asarray(a) for a in item]
                if self._return_list or not self._names:
                    yield arrays
                else:
                    yield dict(zip(self._names, arrays))
        finally:
            stop.set()


def _stack_samples(samples):
    ncol = len(samples[0])
    return [np.stack([np.asarray(s[i]) for s in samples]) for i in range(ncol)]


class DataLoader:
    """Reference reader.py:112."""

    @staticmethod
    def from_generator(feed_list=None, capacity=64, use_double_buffer=True,
                       iterable=True, return_list=False, use_multiprocess=False,
                       drop_last=True):
        return GeneratorLoader(
            feed_list=feed_list, capacity=capacity, iterable=iterable,
            return_list=return_list, drop_last=drop_last,
        )

    @staticmethod
    def from_dataset(dataset, places=None, drop_last=True):
        """Iterate a Dataset (fluid/dataset.py) as feed dicts."""
        return dataset._as_loader(drop_last=drop_last)


class PyReader:
    """Legacy wrapper (reference reader.py:1213): decorate_* map onto the
    GeneratorLoader flavors."""

    def __init__(self, feed_list=None, capacity=64, iterable=True,
                 return_list=False):
        self._loader = GeneratorLoader(feed_list, capacity, iterable, return_list)

    def decorate_sample_generator(self, sample_generator, batch_size,
                                  drop_last=True, places=None):
        self._loader.set_sample_generator(sample_generator, batch_size, drop_last)

    def decorate_sample_list_generator(self, reader, places=None):
        self._loader.set_sample_list_generator(reader)

    def decorate_batch_generator(self, reader, places=None):
        self._loader.set_batch_generator(reader)

    def __iter__(self):
        return iter(self._loader)
