"""DataLoader / GeneratorLoader / PyReader: host data pipeline.

Parity surface: /root/reference/python/paddle/fluid/reader.py
(DataLoader:112, from_generator:372, GeneratorLoader:953, PyReader:1213)
and the C++ reader ops (operators/reader/buffered_reader.cc — async
double buffering).

TPU-native design: the reference pushes LoDTensors into a C++ blocking
queue consumed by read ops inside the program. Here feeding is explicit
(Executor.run(feed=...)), so the loader's job is pipelining: a background
thread drains the user generator into a bounded queue (double buffering)
while the previous step runs on device; batches come out as feed dicts.
The file-backed path is the native C++ feed (paddle_tpu/native)."""
from __future__ import annotations

import queue
import threading
import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from . import framework

# ---------------------------------------------------------------------------
# data-pipeline instrumentation (ISSUE 15): break the opaque data_wait
# scalar into stages. Armed only while a telemetry consumer exists
# (PADDLE_METRICS_PATH sink or the PADDLE_GOODPUT ledger) — flag-off,
# every loader path below costs one cached bool read per epoch and the
# produced batches are bit-identical either way.
#
#   data_fetch_ms    pulling one item/batch from the user's reader or
#                    indexing the dataset (the producer side)
#   data_decode_ms   collate_fn over the fetched samples (DataLoader)
#   data_batch_ms    stacking samples into batch arrays (_stack_samples)
#   data_h2d_ms      host array materialization of the yielded batch
#                    (np.asarray before the feed; the device transfer
#                    itself is charged to the executor's data_wait)
#   data_queue_depth prefetch queue depth sampled at each consumer get
#                    (0 = the consumer is starved, the producer is the
#                    bottleneck; capacity = producer ahead, healthy)
# ---------------------------------------------------------------------------

def _pipeline_armed() -> bool:
    from ..telemetry import goodput, sink

    return sink.enabled() or goodput.enabled()


def _stage_obs() -> Optional[dict]:
    """The per-stage histograms, or None when no consumer is armed.
    Resolved from the registry per call (get-or-create dict lookups) so
    a registry reset() never strands observations on orphaned metrics;
    callers hold the returned dict for the whole epoch."""
    if not _pipeline_armed():
        return None
    from ..telemetry import get_registry

    reg = get_registry()
    return dict(
        fetch=reg.histogram(
            "data_fetch_ms",
            help="input pipeline: user reader / dataset fetch"),
        decode=reg.histogram(
            "data_decode_ms",
            help="input pipeline: collate_fn (decode) time"),
        batch=reg.histogram(
            "data_batch_ms",
            help="input pipeline: sample stacking into batches"),
        h2d=reg.histogram(
            "data_h2d_ms",
            help="input pipeline: host batch-array materialization"),
    )


def _queue_gauge(loader: str):
    """Prefetch queue-depth gauge for one loader flavor, or None."""
    if not _pipeline_armed():
        return None
    from ..telemetry import get_registry

    return get_registry().gauge(
        "data_queue_depth",
        help="prefetch queue depth at consumer get (0 = starved)",
        loader=loader)


def _timed_source(it, hist):
    """Wrap an iterator so each next() lands in `hist` (fetch stage)."""
    it = iter(it)
    while True:
        t0 = time.perf_counter()
        try:
            item = next(it)
        except StopIteration:
            return
        hist.observe((time.perf_counter() - t0) * 1e3)
        yield item


def _generator_producer(q, reader):
    """Child body for GeneratorLoader.use_multiprocess (module-level so
    spawn can pickle it by reference)."""
    try:
        for batch in reader():
            q.put([np.asarray(a) for a in batch])
        q.put(None)
    except Exception as e:  # noqa: BLE001 — shipped to parent
        q.put(("__error__", repr(e)))
    except KeyboardInterrupt:
        pass

_END = object()


class GeneratorLoader:
    """Reference reader.py:953. iterable mode only (the non-iterable
    start()/reset() protocol existed for in-program read ops, which the
    whole-block XLA executor does not need)."""

    def __init__(self, feed_list=None, capacity=64, iterable=True,
                 return_list=False, drop_last=True, use_multiprocess=False):
        self._feed_list = list(feed_list or [])
        self._names = [
            v.name if isinstance(v, framework.Variable) else str(v)
            for v in self._feed_list
        ]
        self._capacity = int(capacity)
        self._iterable = iterable
        self._return_list = return_list
        self._drop_last = drop_last
        # run the generator in a fork()ed child instead of a thread — the
        # reference DygraphGeneratorLoader's use_multiprocess (reader.py:660):
        # heavy Python preprocessing stops sharing the GIL with the trainer
        self._use_multiprocess = use_multiprocess
        self._batch_reader: Optional[Callable] = None

    # -- generator flavors (reference from_generator API) ----------------
    def set_sample_generator(self, reader, batch_size, drop_last=True, places=None):
        def batch_reader():
            batch = []
            for sample in reader():
                if not isinstance(sample, (tuple, list)):
                    sample = (sample,)
                batch.append(sample)
                if len(batch) == batch_size:
                    yield _stack_samples(batch)
                    batch = []
            if batch and not drop_last:
                yield _stack_samples(batch)

        self._batch_reader = batch_reader
        return self

    def set_sample_list_generator(self, reader, places=None):
        def batch_reader():
            for sample_list in reader():
                yield _stack_samples(sample_list)

        self._batch_reader = batch_reader
        return self

    def set_batch_generator(self, reader, places=None):
        self._batch_reader = reader
        return self

    # -- iteration with background prefetch ------------------------------
    def __iter__(self):
        if self._batch_reader is None:
            raise RuntimeError(
                "DataLoader: call set_sample_generator / "
                "set_sample_list_generator / set_batch_generator first"
            )
        if self._use_multiprocess:
            yield from self._iter_multiprocess()
            return
        obs = _stage_obs()
        depth = _queue_gauge("generator")
        q: queue.Queue = queue.Queue(maxsize=self._capacity)
        err: List[BaseException] = []
        stop = threading.Event()

        def _put(item) -> bool:
            # timed put + stop flag: when the consumer abandons iteration
            # (break / early stop) the worker exits instead of blocking on
            # a full queue forever (one leaked thread per abandoned epoch)
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            try:
                source = self._batch_reader()
                if obs is not None:
                    # fetch stage: each batch pulled from the user's
                    # reader, timed in the producer thread
                    source = _timed_source(source, obs["fetch"])
                for batch in source:
                    if not _put(batch):
                        return
            except BaseException as e:  # noqa: BLE001 — re-raised on consumer
                err.append(e)
            finally:
                _put(_END)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        try:
            while True:
                if depth is not None:
                    depth.set(q.qsize())
                item = q.get()
                if item is _END:
                    if err:
                        raise err[0]
                    return
                t0 = time.perf_counter() if obs is not None else 0.0
                arrays = [np.asarray(a) for a in item]
                if obs is not None:
                    obs["h2d"].observe((time.perf_counter() - t0) * 1e3)
                if self._return_list or not self._names:
                    yield arrays
                else:
                    yield dict(zip(self._names, arrays))
        finally:
            stop.set()

    def _iter_multiprocess(self):
        """One off-process producer streaming batches over an mp queue.
        Spawn when the reader pickles (fork under the multithreaded JAX
        runtime risks child deadlock); fork otherwise."""
        import multiprocessing as mp

        from .dataloader import _child_env, _spawn_safe

        if _spawn_safe(self._batch_reader, None, None):
            method = "spawn"
        else:
            import warnings

            warnings.warn(
                "GeneratorLoader: the batch reader is not picklable; "
                "falling back to fork() for the producer process, which "
                "can deadlock under the multithreaded JAX runtime — use a "
                "module-level reader function to enable spawn",
                RuntimeWarning, stacklevel=3,
            )
            method = "fork"
        ctx = mp.get_context(method)
        q = ctx.Queue(maxsize=self._capacity)

        p = ctx.Process(target=_generator_producer,
                        args=(q, self._batch_reader), daemon=True)
        with _child_env():
            p.start()
        try:
            while True:
                try:
                    item = q.get(timeout=1.0)
                except queue.Empty:
                    if not p.is_alive():
                        raise RuntimeError(
                            "DataLoader: generator worker process died"
                        ) from None
                    continue
                if item is None:
                    return
                if isinstance(item, tuple) and len(item) == 2 and item[0] == "__error__":
                    raise RuntimeError(f"DataLoader worker failed: {item[1]}")
                arrays = [np.asarray(a) for a in item]
                if self._return_list or not self._names:
                    yield arrays
                else:
                    yield dict(zip(self._names, arrays))
        finally:
            if p.is_alive():
                p.terminate()
            p.join(timeout=5)
            q.cancel_join_thread()
            q.close()


def _buffered_gen(gen, capacity=2, depth_gauge=None):
    """Background-thread prefetch (double buffering) with abandon-safe
    shutdown: a stop flag checked by the timed put releases the worker
    when the consumer breaks early. `depth_gauge` (ISSUE 15) samples
    the queue depth at every consumer get."""
    q: queue.Queue = queue.Queue(maxsize=capacity)
    err: List[BaseException] = []
    stop = threading.Event()

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for item in gen:
                if not _put(item):
                    return
        except BaseException as e:  # noqa: BLE001 — re-raised on consumer
            err.append(e)
        finally:
            _put(_END)

    threading.Thread(target=worker, daemon=True).start()
    try:
        while True:
            if depth_gauge is not None:
                depth_gauge.set(q.qsize())
            item = q.get()
            if item is _END:
                if err:
                    raise err[0]
                return
            yield item
    finally:
        stop.set()


def _stack_samples(samples):
    obs = _stage_obs()
    t0 = time.perf_counter() if obs is not None else 0.0
    ncol = len(samples[0])
    out = [np.stack([np.asarray(s[i]) for s in samples])
           for i in range(ncol)]
    if obs is not None:
        obs["batch"].observe((time.perf_counter() - t0) * 1e3)
    return out


class DataLoader:
    """Reference reader.py:112: map-style Dataset + BatchSampler +
    multiprocess workers (fluid/dataloader/), plus the from_generator /
    from_dataset constructors.

    num_workers=0 loads inline; num_workers=N forks N worker processes
    that collate index-batches in parallel — submission order is restored,
    so N>0 yields the identical batch sequence (dataloader/__init__.py).
    """

    def __init__(self, dataset, feed_list=None, places=None, return_list=False,
                 batch_sampler=None, batch_size=1, shuffle=False,
                 drop_last=False, collate_fn=None, num_workers=0,
                 use_buffer_reader=True, use_shared_memory=False, timeout=0,
                 worker_init_fn=None, multiprocessing_context=None):
        from .dataloader import BatchSampler, IterableDataset, default_collate_fn

        self._dataset = dataset
        self._names = [
            v.name if isinstance(v, framework.Variable) else str(v)
            for v in (feed_list or [])
        ]
        self._return_list = return_list or not self._names
        self._iterable_ds = isinstance(dataset, IterableDataset)
        if self._iterable_ds:
            if num_workers > 0:
                raise ValueError(
                    "IterableDataset cannot be index-sharded across workers; "
                    "use num_workers=0 (or GeneratorLoader for off-process "
                    "streaming)"
                )
            if batch_sampler is not None:
                raise ValueError("IterableDataset does not take a batch_sampler")
            self._batch_size, self._drop_last = int(batch_size), drop_last
            self._batch_sampler = None
        else:
            self._batch_sampler = batch_sampler or BatchSampler(
                dataset=dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last,
            )
        self._collate = collate_fn or default_collate_fn
        self._num_workers = int(num_workers)
        self._use_buffer = use_buffer_reader
        self._timeout = timeout
        self._worker_init_fn = worker_init_fn
        self._mp_context = multiprocessing_context

    def __len__(self):
        if self._batch_sampler is None:
            raise TypeError("len() of an IterableDataset loader")
        return len(self._batch_sampler)

    def _raw_batches(self):
        obs = _stage_obs()

        def _collate_timed(items):
            if obs is None:
                return self._collate(items)
            t0 = time.perf_counter()
            out = self._collate(items)
            obs["decode"].observe((time.perf_counter() - t0) * 1e3)
            return out

        if self._iterable_ds:
            buf = []
            source = (self._dataset if obs is None
                      else _timed_source(self._dataset, obs["fetch"]))
            for sample in source:
                buf.append(sample)
                if len(buf) == self._batch_size:
                    yield _collate_timed(buf)
                    buf = []
            if buf and not self._drop_last:
                yield _collate_timed(buf)
            return
        batches = list(self._batch_sampler)
        if self._num_workers > 0:
            from .dataloader import _MultiprocessIter

            yield from _MultiprocessIter(
                self._dataset, batches, self._collate, self._num_workers,
                self._worker_init_fn, self._timeout,
                mp_context=self._mp_context,
            )
        else:
            for idx in batches:
                if obs is None:
                    yield self._collate([self._dataset[i] for i in idx])
                    continue
                t0 = time.perf_counter()
                items = [self._dataset[i] for i in idx]
                obs["fetch"].observe((time.perf_counter() - t0) * 1e3)
                yield _collate_timed(items)

    def __iter__(self):
        obs = _stage_obs()
        gen = self._raw_batches()
        if self._use_buffer and self._num_workers == 0:
            gen = _buffered_gen(gen, capacity=2,
                                depth_gauge=_queue_gauge("dataloader"))
        for arrays in gen:
            t0 = time.perf_counter() if obs is not None else 0.0
            arrays = [np.asarray(a) for a in arrays]
            if obs is not None:
                obs["h2d"].observe((time.perf_counter() - t0) * 1e3)
            yield arrays if self._return_list else dict(zip(self._names, arrays))

    @staticmethod
    def from_generator(feed_list=None, capacity=64, use_double_buffer=True,
                       iterable=True, return_list=False, use_multiprocess=False,
                       drop_last=True):
        return GeneratorLoader(
            feed_list=feed_list, capacity=capacity, iterable=iterable,
            return_list=return_list, drop_last=drop_last,
            use_multiprocess=use_multiprocess,
        )

    @staticmethod
    def from_dataset(dataset, places=None, drop_last=True):
        """Iterate a Dataset (fluid/dataset.py) as feed dicts."""
        return dataset._as_loader(drop_last=drop_last)


class PyReader:
    """Legacy wrapper (reference reader.py:1213): decorate_* map onto the
    GeneratorLoader flavors."""

    def __init__(self, feed_list=None, capacity=64, iterable=True,
                 return_list=False):
        self._loader = GeneratorLoader(feed_list, capacity, iterable, return_list)

    def decorate_sample_generator(self, sample_generator, batch_size,
                                  drop_last=True, places=None):
        self._loader.set_sample_generator(sample_generator, batch_size, drop_last)

    def decorate_sample_list_generator(self, reader, places=None):
        self._loader.set_sample_list_generator(reader)

    def decorate_batch_generator(self, reader, places=None):
        self._loader.set_batch_generator(reader)

    def __iter__(self):
        return iter(self._loader)
