"""Static verification framework over the Program IR (proglint).

The Program IS the source of truth in this design (fluid/framework.py):
the Executor traces a whole block into ONE jitted function, so a
malformed graph — dangling input, dtype clash, stale last-writer left
behind by a rewrite pass — surfaces as a cryptic JAX trace error
hundreds of frames from the op that caused it. This package is the
TPU-native rebuild of the reference's C++ InferShape checks +
`op_callstack` attribution (operator.cc exception enrichment): a pass
manager running pluggable whole-graph checks, each finding carrying
severity, op position, and the USER call stack captured at
`Block.append_op` time.

Three entry points:

  verify-on-compile   FLAGS_program_verify=1 makes Executor._ensure_compiled
                      verify every program before XLA sees it, raising a
                      structured ProgramVerifyError that points at the
                      user's layer call instead of letting XLA fail later.
  pass sandwich       apply_conv_bn_fusion / append_backward verify the
                      program before AND after rewriting (same flag);
                      findings the pass introduced are attributed to it —
                      the MLIR-verifier convention for rewrite pipelines.
  proglint CLI        tools/proglint.py lints any saved or constructed
                      program standalone and exits nonzero on errors.

Check catalog (registered name -> module):

  dangling-ref, use-before-def, maybe-uninitialized   analysis/dataflow.py
  stale-last-writer, dead-op, unused-var              analysis/dataflow.py
  shape-dtype (eval_shape recompute, -1 tolerant)     analysis/typecheck.py
  dtype-clash, fill-truncation                        analysis/typecheck.py
  grad-integrity, grad-shape-mirror                   analysis/gradcheck.py
  subblock-persistable-write, subblock-rng            analysis/structure.py
  device-stage                                        analysis/structure.py

Whole-job checks (not registered — they need state beyond one Program):

  scope-missing-persistable, scope-uninitialized,     analysis/scopecheck.py
  scope-shape-mismatch, scope-dtype-mismatch,           (verify_scope — a
  scope-orphan-var                                       Program vs a live
                                                         Scope/manifest)
  startup-missing-init, startup-orphan-init           analysis/crosscheck.py
  clone-param-mismatch, clone-train-mode,               (verify_pair —
  clone-grad-op, clone-bn-stats                          startup/main +
  ps-table-missing, ps-table-geometry                    train/eval pairs)

Mechanical repair (proglint --fix): analysis/fixes.py `apply_fixes`
runs torn-grads / dead-code / stale-last-writer / startup-init fixers,
re-verifying after each — a fixer that introduces a NEW error raises
attributed `fix:<name>`.

Beyond the checks, the package hosts the static LIVE-RANGE pass
(analysis/liverange.py, ISSUE 11): first-def/last-use and byte size per
Variable, peak simultaneous-bytes estimate with donation awareness, and
the params/optimizer-state/gradients/feeds/activations categorization
that telemetry/memory.py, the OOM doctor and tools/memtop.py consume.
"""
from .core import (  # noqa: F401
    ERROR,
    INFO,
    WARNING,
    CheckContext,
    Finding,
    PassManager,
    ProgramVerifyError,
    all_checks,
    assert_valid,
    format_findings,
    register_check,
    user_frame,
    verify_program,
    walk_blocks,
)
from .sandwich import pass_sandwich  # noqa: F401
from .scopecheck import (  # noqa: F401
    assert_scope_valid,
    persistable_reads,
    verify_scope,
)
from .crosscheck import (  # noqa: F401
    assert_pair_valid,
    check_ps_geometry,
    check_startup_main,
    check_train_eval,
    verify_pair,
)
from .fixes import FIXERS, FixReport, apply_fixes  # noqa: F401
from .liverange import (  # noqa: F401
    BufferInfo,
    LiveRangeAnalysis,
    analyze_live_ranges,
)

# importing the check modules registers their checks with core
from . import dataflow, gradcheck, structure, typecheck  # noqa: F401,E402
