"""Structural hazards: control-flow sub-blocks and pipeline stages.

The functional lowering gives sub-blocks (cond / while_loop / recurrent)
an isolated env: only declared carries/outs escape. Two op classes are
hazards there:

  * writes to persistable vars — the write lands in the sub-block's
    local env and is silently DISCARDED (the reference's per-step Scope
    would have persisted it), e.g. batch_norm running stats inside a
    cond branch;
  * ctx.rng()-drawing ops inside while_loop/recurrent bodies — the body
    is traced ONCE into lax.while/scan, so every iteration replays the
    SAME key (same dropout mask each step), unlike the reference's
    per-step execution.

device-stage covers pipeline programs: device_guard tags must describe
contiguous, fully-annotated forward stages or PipelineOptimizer's
stage model (and any future per-stage GPipe split) is meaningless.
"""
from __future__ import annotations

from .core import WARNING, ERROR, CheckContext, register_check

# ops whose emitters draw from the trace-threaded PRNG (ctx.rng())
_RNG_OPS = frozenset({
    "dropout", "uniform_random", "gaussian_random",
    "truncated_gaussian_random", "uniform_random_batch_size_like",
    "randint", "randperm", "bernoulli", "multinomial", "sampling_id",
})

# sub-block owners whose bodies are traced once and iterated on device
_LOOP_OPS = frozenset({"while_loop", "recurrent"})


@register_check("subblock-persistable-write")
def check_subblock_persistable_write(ctx: CheckContext):
    for view in ctx.views:
        if not view.is_sub:
            continue
        block = view.block
        for i, op in enumerate(block.ops):
            for n in op.output_names():
                v = block._find_var_recursive(n)
                if v is not None and v.persistable:
                    ctx.report(
                        "subblock-persistable-write", ERROR,
                        f"op writes persistable {n!r} inside a "
                        f"{view.owner_op.type!r} sub-block; the "
                        f"functional lowering discards the write (only "
                        f"declared block outputs escape) — hoist the "
                        f"write out of the sub-block or carry it as a "
                        f"loop var",
                        block_idx=block.idx, op_index=i, op=op, var=n)


@register_check("subblock-rng")
def check_subblock_rng(ctx: CheckContext):
    for view in ctx.views:
        if not view.is_sub or view.owner_op.type not in _LOOP_OPS:
            continue
        block = view.block
        for i, op in enumerate(block.ops):
            if op.type in _RNG_OPS and not op.attr("is_test", False):
                ctx.report(
                    "subblock-rng", WARNING,
                    f"{op.type!r} draws from the trace-time PRNG inside "
                    f"a {view.owner_op.type!r} body: the body traces "
                    f"once, so every iteration replays the SAME random "
                    f"draw (identical dropout mask per step). Use a "
                    f"salted per-iteration key or hoist the randomness",
                    block_idx=block.idx, op_index=i, op=op)


@register_check("device-stage")
def check_device_stage(ctx: CheckContext):
    """Pipeline stage tags (device_guard -> attr op_device) on the root
    block must be (a) complete — an untagged op between tagged ones has
    no stage — and (b) contiguous over the FORWARD segment (backward
    naturally revisits stages in reverse; it is excluded). Both WARNING:
    the single-program lowering still runs these programs, but the tags
    lie about a partition."""
    block = ctx.program.global_block()
    fwd_end = len(block.ops)
    for i, op in enumerate(block.ops):
        if any("@GRAD" in n for n in op.output_names()):
            fwd_end = i
            break
    tags = [(i, op.attrs.get("op_device"))
            for i, op in enumerate(block.ops[:fwd_end])]
    tagged = [(i, t) for i, t in tags if t]
    stages = {t for _, t in tagged}
    if len(stages) < 2:
        return
    first_i, last_i = tagged[0][0], tagged[-1][0]
    untagged = [i for i, t in tags if not t and first_i < i < last_i]
    if untagged:
        op = block.ops[untagged[0]]
        ctx.report(
            "device-stage", WARNING,
            f"{len(untagged)} op(s) between stage-tagged ops carry no "
            f"device_guard tag (first at op#{untagged[0]}); every op in "
            f"a pipeline region needs a stage",
            block_idx=block.idx, op_index=untagged[0], op=op)
    seen, closed = [], set()
    for i, t in tagged:
        if not seen or seen[-1] != t:
            if t in closed:
                ctx.report(
                    "device-stage", WARNING,
                    f"stage {t!r} reappears at op#{i} after other "
                    f"stages ran — stages must be contiguous for any "
                    f"per-stage split to be meaningful",
                    block_idx=block.idx, op_index=i, op=block.ops[i])
            if seen:
                closed.add(seen[-1])
            seen.append(t)
