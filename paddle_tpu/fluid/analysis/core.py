"""Core of the static verifier: findings, check registry, pass manager.

A check is a function fn(ctx: CheckContext) registered under a stable
name; it walks ctx.program and reports findings. The PassManager runs a
set of checks and returns the findings sorted most-severe-first. The
whole layer is read-only by contract: no check may mutate the program
(verify_program asserts the version counter did not move).
"""
from __future__ import annotations

import dataclasses
import os
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from .. import framework

ERROR = "error"
WARNING = "warning"
INFO = "info"

_SEV_ORDER = {ERROR: 0, WARNING: 1, INFO: 2}

# frames inside the package are framework plumbing; attribution wants the
# deepest frame OUTSIDE it — the user's layer call (reference op_callstack
# convention: the Python stack minus the C++/framework frames)
_PKG_DIR = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # .../paddle_tpu


def user_frame(callstack) -> Optional[Tuple[str, int, str]]:
    """Deepest (file, line, fn) frame not inside paddle_tpu — the user's
    layer call — or None when the whole stack is framework-internal."""
    for frame in callstack or ():
        fname = frame[0]
        if not fname.startswith(_PKG_DIR + os.sep):
            return tuple(frame)
    return None


@dataclasses.dataclass
class Finding:
    check: str
    severity: str
    message: str
    block_idx: int = 0
    op_index: Optional[int] = None
    op_type: Optional[str] = None
    var: Optional[str] = None
    pass_name: Optional[str] = None
    callstack: Optional[tuple] = None  # ((file, line, fn), ...)

    def key(self):
        """Identity for sandwich diffing: op indices shift under rewrites,
        so the key is positional-free."""
        return (self.check, self.block_idx, self.op_type, self.var,
                self.message)

    def format(self) -> str:
        where = f"block {self.block_idx}"
        if self.op_index is not None:
            where += f" op#{self.op_index}"
        if self.op_type:
            where += f" [{self.op_type}]"
        if self.var:
            where += f" var {self.var!r}"
        head = f"{self.severity.upper()} {self.check}: {self.message} ({where})"
        if self.pass_name:
            head += f" [introduced by pass: {self.pass_name}]"
        uf = user_frame(self.callstack)
        if uf is not None:
            head += f"\n    at {uf[0]}:{uf[1]} in {uf[2]}"
        return head


class ProgramVerifyError(RuntimeError):
    """Raised (flag-gated) when verification finds error-severity
    problems; carries the structured findings so handlers/tests can
    inspect them instead of parsing the message."""

    def __init__(self, findings: Sequence[Finding], where: str = ""):
        self.findings = list(findings)
        errors = [f for f in self.findings if f.severity == ERROR]
        head = (f"program verification failed"
                f"{f' ({where})' if where else ''}: "
                f"{len(errors)} error(s)")
        super().__init__("\n".join([head] + [f.format() for f in errors]))


@dataclasses.dataclass
class BlockView:
    """One block in execution context: `entry_names` are the names the
    runtime seeds the block's env with (sub-blocks see ONLY these plus
    their own ops' outputs — emit_ops raises on anything else)."""
    block: "framework.Block"
    entry_names: frozenset
    owner_op: Optional["framework.Operator"] = None  # None for block 0
    owner_block_idx: int = 0
    owner_op_index: Optional[int] = None

    @property
    def is_sub(self) -> bool:
        return self.owner_op is not None


# op type -> ((block_attr, (seed name-list attrs...)), ...) — the
# sub-block env contract each control-flow emitter establishes
# (ops/control_flow_ops.py)
_SUB_BLOCK_SPECS = {
    "cond": (
        ("true_block", ("captured_names",)),
        ("false_block", ("captured_names",)),
    ),
    "while_loop": (
        ("cond_block", ("captured_names", "loop_var_names")),
        ("body_block", ("captured_names", "loop_var_names")),
    ),
    "recurrent": (
        ("step_block", ("captured_names", "step_input_names",
                        "memory_in_names")),
    ),
}


def walk_blocks(program) -> List[BlockView]:
    """Blocks in execution order: block 0 first, each sub-block at its
    owner op's site with the entry names the emitter will seed. Blocks
    in program.blocks that no op references are skipped (orphans from
    abandoned builders never execute)."""
    views: List[BlockView] = []

    def recurse(block, entry, owner=None, owner_blk=0, owner_idx=None):
        views.append(BlockView(block, frozenset(entry), owner,
                               owner_blk, owner_idx))
        for i, op in enumerate(block.ops):
            spec = _SUB_BLOCK_SPECS.get(op.type)
            if spec is None:
                continue
            for blk_attr, seed_attrs in spec:
                sub = op.attrs.get(blk_attr)
                if not isinstance(sub, framework.Block):
                    continue
                seeds = []
                for a in seed_attrs:
                    seeds.extend(op.attrs.get(a) or ())
                recurse(sub, seeds, op, block.idx, i)

    root = program.global_block()
    recurse(root, ())
    return views


class CheckContext:
    def __init__(self, program, live_out: Iterable[str] = ()):
        self.program = program
        # names the caller declares live (feeds/fetches): consumers the
        # graph itself cannot show, consulted by the dead-code check
        self.live_out = frozenset(live_out)
        self.findings: List[Finding] = []
        self.views = walk_blocks(program)

    def report(self, check: str, severity: str, message: str, *,
               block_idx: int = 0, op_index: Optional[int] = None,
               op=None, var: Optional[str] = None) -> Finding:
        f = Finding(
            check=check, severity=severity, message=message,
            block_idx=block_idx, op_index=op_index,
            op_type=op.type if op is not None else None, var=var,
            callstack=op.attrs.get("__op_callstack__")
            if op is not None else None,
        )
        self.findings.append(f)
        return f


_CHECKS: Dict[str, Callable[[CheckContext], None]] = {}


def register_check(name: str):
    def deco(fn):
        _CHECKS[name] = fn
        return fn

    return deco


def all_checks() -> List[str]:
    return sorted(_CHECKS)


class PassManager:
    """Runs a set of named checks over a program. One CheckContext is
    shared so checks reuse the block walk."""

    def __init__(self, checks: Optional[Sequence[str]] = None,
                 live_out: Iterable[str] = ()):
        self.check_names = list(checks) if checks is not None else None
        self.live_out = frozenset(live_out)

    def run(self, program) -> List[Finding]:
        names = self.check_names
        if names is None:
            names = all_checks()
        unknown = [n for n in names if n not in _CHECKS]
        if unknown:
            raise ValueError(f"unknown check(s) {unknown}; "
                             f"registered: {all_checks()}")
        ctx = CheckContext(program, live_out=self.live_out)
        version = program._version
        for n in names:
            _CHECKS[n](ctx)
        # read-only contract: a check that mutated the program would make
        # "verify" change what gets compiled — exactly the bug class this
        # layer exists to catch
        assert program._version == version, (
            "a verifier check mutated the program (version "
            f"{version} -> {program._version})")
        ctx.findings.sort(key=lambda f: (_SEV_ORDER.get(f.severity, 3),
                                         f.block_idx, f.op_index
                                         if f.op_index is not None else -1))
        return ctx.findings


def verify_program(program, checks: Optional[Sequence[str]] = None,
                   live_out: Iterable[str] = ()) -> List[Finding]:
    """Run the (given or full) check suite; returns findings sorted
    most-severe-first. Never raises on findings — see assert_valid."""
    return PassManager(checks, live_out=live_out).run(program)


def assert_valid(program, live_out: Iterable[str] = (),
                 where: str = "") -> List[Finding]:
    """verify_program, raising ProgramVerifyError when any finding is
    error-severity. Returns the findings (incl. warnings) otherwise."""
    findings = verify_program(program, live_out=live_out)
    if any(f.severity == ERROR for f in findings):
        raise ProgramVerifyError(findings, where=where)
    return findings


def format_findings(findings: Sequence[Finding]) -> str:
    if not findings:
        return "no findings"
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.severity] = counts.get(f.severity, 0) + 1
    summary = ", ".join(f"{counts[s]} {s}(s)" for s in (ERROR, WARNING, INFO)
                        if s in counts)
    return "\n".join([f.format() for f in findings] + [summary])
