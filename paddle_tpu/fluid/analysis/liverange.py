"""Static live-range analysis over the Program IR (ISSUE 11).

Answers "where would the MEMORY go" before XLA ever allocates a byte:
for every Variable in block 0, the first op that defines it and the
last op that reads it, its byte size, and its category — so the peak
simultaneous-bytes estimate, the per-category breakdown and the "which
buffer is fattest at the high-water point" ranking are all pure
functions of the program. telemetry/memory.py cross-checks this static
estimate against XLA's measured buffer assignment
(Executor.memory_analysis) and the OOM doctor ranks its what-ifs with
it; tools/memtop.py is the CLI.

Execution model this mirrors (fluid/executor.py::_compile):

  - feeds and state (persistables / scope vars) are live at step ENTRY;
  - state that is read AND written (donate_names) is DONATED — XLA
    aliases the input buffer to the output, so one name is ONE buffer
    for the whole step (the default; donation=False models the
    diagnostic no-donate modes, where the updating op briefly holds
    both the old and the new buffer);
  - a non-persistable intermediate is live from its producing op to its
    last consuming op (fetch targets stay live to the end);
  - sub-block internals (cond/while bodies) are bounded by their owner
    op's execution — they are charged to the owner op as workspace and
    not tracked per-name here.

Categories (documented contract, memtop/--memz render them):

  params            framework.Parameter instances
  optimizer_state   persistable non-Parameter state (optimizer moments,
                    LR / beta-pow accumulators, BN running stats, guard
                    vars — everything the step carries forward that is
                    not a trainable weight)
  gradients         names containing @GRAD (incl. backward's
                    @GRAD@RENAME@<n> accumulation partials)
  feeds             data vars / fed names (the batch)
  activations       everything else — forward intermediates kept alive
                    for the backward pass; the remat lever

What the static estimate can and cannot see (caveats, also in README):
XLA's fusion DELETES many activations outright (an elementwise chain
never materializes), its buffer assignment reuses dead buffers for new
values, and it adds workspace (scratch, collectives staging) the IR
cannot name — so the static peak is an upper-bound-flavored ESTIMATE,
not an allocator prediction. The measured cross-check in
telemetry/memory.py carries the documented tolerance.

Stdlib + numpy only; never mutates the program.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from .. import framework
from .core import user_frame

PARAMS = "params"
OPTIMIZER_STATE = "optimizer_state"
GRADIENTS = "gradients"
FEEDS = "feeds"
ACTIVATIONS = "activations"

CATEGORIES = (PARAMS, OPTIMIZER_STATE, GRADIENTS, FEEDS, ACTIVATIONS)


@dataclasses.dataclass
class BufferInfo:
    """One Variable's static buffer: size, range, identity."""

    name: str
    bytes: int
    shape: Optional[tuple]
    dtype: str
    category: str
    first_def: int              # producing op index; -1 = live at entry
    last_use: int               # last consuming op index; n_ops = live-out
    op_index: Optional[int]     # owning op (producer, else first consumer)
    op_type: Optional[str]
    layer: Optional[str]        # "file:line in fn" user layer call (PR 5)
    callstack: Optional[tuple] = None
    donated: bool = False
    persistable: bool = False
    batch_scaled: bool = False  # leading dim is the batch (what-if lever)

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d.pop("callstack", None)
        d["shape"] = list(self.shape) if self.shape is not None else None
        return d


@dataclasses.dataclass
class LiveRangeAnalysis:
    """The pass result: per-buffer ranges + the sweep's peak."""

    buffers: List[BufferInfo]
    n_ops: int
    peak_bytes: int                  # max simultaneous live bytes
    peak_op_index: int               # op index where the sweep peaked
    peak_op_type: Optional[str]
    peak_layer: Optional[str]
    live_at_peak: List[str]          # names live at the peak op
    categories: Dict[str, int]       # category -> total bytes
    categories_at_peak: Dict[str, int]
    resident_bytes: int              # entry-live state + feeds
    model_bytes: int                 # params + optimizer_state
    live_bytes_at: List[int]         # per-op live bytes (the sweep curve)
    unsized: List[str]               # vars whose bytes could not be sized
    batch_hint: Optional[int] = None

    def by_name(self) -> Dict[str, BufferInfo]:
        return {b.name: b for b in self.buffers}

    def top(self, k: int = 20, live_at_peak_only: bool = False
            ) -> List[BufferInfo]:
        rows = self.buffers
        if live_at_peak_only:
            live = set(self.live_at_peak)
            rows = [b for b in rows if b.name in live]
        return sorted(rows, key=lambda b: -b.bytes)[:k]


def _dtype_itemsize(dtype) -> int:
    try:
        return int(np.dtype(dtype).itemsize)
    except TypeError:
        return 4  # unknown recorded dtype: assume fp32


def _sized_shape(shape, batch_hint: Optional[int]) -> Optional[tuple]:
    """Concrete shape with -1 dims substituted by the batch hint; None
    when unresolvable."""
    if shape is None:
        return None
    out = []
    for d in shape:
        d = int(d)
        if d < 0:
            if not batch_hint:
                return None
            d = int(batch_hint)
        out.append(d)
    return tuple(out)


def _attr_strings(op) -> List[str]:
    """Names referenced through attrs (sub-block out/carry name lists) —
    consumers the input slots cannot show (mirrors dataflow.py)."""
    out: List[str] = []
    for k, v in op.attrs.items():
        if k.startswith("__"):
            continue
        if isinstance(v, str):
            out.append(v)
        elif isinstance(v, (list, tuple)):
            out.extend(x for x in v if isinstance(x, str))
    return out


def analyze_live_ranges(
    program,
    feed_names: Iterable[str] = (),
    fetch_names: Iterable[str] = (),
    batch_hint: Optional[int] = None,
    shapes: Optional[Dict[str, Sequence[int]]] = None,
    donation: bool = True,
) -> LiveRangeAnalysis:
    """Run the pass over block 0. `shapes` overrides recorded var shapes
    with concrete ones (memtop passes the feed arrays' shapes so -1
    batch dims resolve exactly); remaining -1 dims use `batch_hint`.
    Read-only: the program version must not move (asserted)."""
    if hasattr(program, "_program"):  # CompiledProgram wrapper
        program = program._program
    version = program._version
    block = program.global_block()
    ops = list(block.ops)
    n_ops = len(ops)
    feed_names = set(feed_names)
    fetch_names = set(fetch_names)
    shapes = dict(shapes or {})
    if batch_hint is None:
        # infer from an overriding feed shape vs its recorded -1 dim
        for n, s in shapes.items():
            v = block._find_var_recursive(n)
            if (v is not None and v.shape and s
                    and len(s) == len(v.shape)):
                for rec, got in zip(v.shape, s):
                    if int(rec) == -1:
                        batch_hint = int(got)
                        break
            if batch_hint is not None:
                break

    # -- def/use walk (block 0; mirrors executor._compile's view) -------
    first_def: Dict[str, int] = {}
    last_use: Dict[str, int] = {}
    written: set = set(feed_names)
    state_in: List[str] = []
    for i, op in enumerate(ops):
        for n in op.input_names() + _attr_strings(op):
            if block._find_var_recursive(n) is None:
                continue
            last_use[n] = i
            if n not in written and n not in state_in:
                state_in.append(n)
        for n in op.output_names():
            written.add(n)
            first_def.setdefault(n, i)

    persistable = {v.name for v in program.list_vars() if v.persistable}
    state_out = [n for n in dict.fromkeys(
        n for op in ops for n in op.output_names()) if n in persistable]
    donate = set(state_in) & set(state_out) if donation else set()

    names = sorted(set(first_def) | set(last_use) | feed_names
                   | (set(state_in)))
    buffers: List[BufferInfo] = []
    unsized: List[str] = []
    for name in names:
        v = block._find_var_recursive(name)
        if v is None:
            continue
        shape = _sized_shape(shapes.get(name, v.shape), batch_hint)
        if shape is None:
            unsized.append(name)
            nbytes = 0
        else:
            nbytes = int(np.prod(shape, dtype=np.int64)
                         * _dtype_itemsize(v.dtype)) if shape else \
                _dtype_itemsize(v.dtype)
        is_param = isinstance(v, framework.Parameter)
        is_feed = v.is_data or name in feed_names
        if is_param:
            cat = PARAMS
        elif framework.GRAD_VAR_SUFFIX in name:
            # includes backward's @GRAD@RENAME@<n> accumulation partials
            cat = GRADIENTS
        elif v.persistable:
            cat = OPTIMIZER_STATE
        elif is_feed:
            cat = FEEDS
        else:
            cat = ACTIVATIONS

        # entry-live: feeds and state the step reads (or persistable
        # state at all — it occupies memory whether or not this program
        # touches it first); live-out: persistable state survives the
        # step, fetch targets are materialized for the host
        fd = first_def.get(name, -1)
        if is_feed or name in state_in or v.persistable:
            fd = -1
        lu = last_use.get(name, fd)
        if v.persistable or name in fetch_names:
            lu = n_ops
        lu = max(lu, fd)

        owner_idx: Optional[int] = first_def.get(name)
        if owner_idx is None:
            lo = last_use.get(name)
            owner_idx = lo if lo is not None else None
        op = ops[owner_idx] if owner_idx is not None else None
        cs = op.attrs.get(framework.OP_CALLSTACK_ATTR) if op is not None \
            else None
        uf = user_frame(cs) if cs else None
        buffers.append(BufferInfo(
            name=name, bytes=nbytes, shape=shape,
            dtype=str(np.dtype(v.dtype)) if v.dtype is not None else "?",
            category=cat, first_def=fd, last_use=lu,
            op_index=owner_idx,
            op_type=op.type if op is not None else None,
            layer=f"{uf[0]}:{uf[1]} in {uf[2]}" if uf else None,
            callstack=cs, donated=name in donate,
            persistable=bool(v.persistable),
            batch_scaled=bool(shape and batch_hint
                              and shape[0] == batch_hint),
        ))

    # -- sweep: peak simultaneous bytes ---------------------------------
    # A donated name is ONE buffer across its whole range (input aliases
    # output). Without donation, the writing op holds old + new at once:
    # model that as double bytes at the writer's op index.
    by_name = {b.name: b for b in buffers}
    defs_at: Dict[int, List[BufferInfo]] = {}
    frees_at: Dict[int, List[BufferInfo]] = {}
    entry_bytes = 0
    for b in buffers:
        if b.first_def < 0:
            entry_bytes += b.bytes
        else:
            defs_at.setdefault(b.first_def, []).append(b)
        if b.last_use < n_ops:
            frees_at.setdefault(b.last_use, []).append(b)

    undonated_extra: Dict[int, int] = {}
    if not donation:
        for n in set(state_in) & set(state_out):
            b = by_name.get(n)
            if b is not None:
                w = first_def.get(n)
                if w is not None:
                    undonated_extra[w] = undonated_extra.get(w, 0) + b.bytes

    cur = entry_bytes
    live: set = {b.name for b in buffers if b.first_def < 0}
    peak, peak_idx = cur, -1
    live_at_peak = set(live)
    curve: List[int] = []
    for i in range(n_ops):
        for b in defs_at.get(i, ()):  # outputs materialize during op i
            cur += b.bytes
            live.add(b.name)
        at_op = cur + undonated_extra.get(i, 0)
        curve.append(at_op)
        if at_op > peak:
            peak, peak_idx, live_at_peak = at_op, i, set(live)
        for b in frees_at.get(i, ()):  # last use done -> buffer freed
            cur -= b.bytes
            live.discard(b.name)

    cats = {c: 0 for c in CATEGORIES}
    cats_peak = {c: 0 for c in CATEGORIES}
    for b in buffers:
        cats[b.category] += b.bytes
        if b.name in live_at_peak:
            cats_peak[b.category] += b.bytes
    peak_op = ops[peak_idx] if 0 <= peak_idx < n_ops else None
    peak_uf = user_frame(peak_op.attrs.get(framework.OP_CALLSTACK_ATTR)
                         ) if peak_op is not None else None

    assert program._version == version, (
        "live-range analysis mutated the program "
        f"({version} -> {program._version})")
    return LiveRangeAnalysis(
        buffers=buffers, n_ops=n_ops, peak_bytes=int(peak),
        peak_op_index=peak_idx,
        peak_op_type=peak_op.type if peak_op is not None else None,
        peak_layer=(f"{peak_uf[0]}:{peak_uf[1]} in {peak_uf[2]}"
                    if peak_uf else None),
        live_at_peak=sorted(live_at_peak,
                            key=lambda n: -by_name[n].bytes),
        categories=cats, categories_at_peak=cats_peak,
        resident_bytes=int(entry_bytes),
        model_bytes=int(cats[PARAMS] + cats[OPTIMIZER_STATE]),
        live_bytes_at=curve, unsized=unsized, batch_hint=batch_hint,
    )
