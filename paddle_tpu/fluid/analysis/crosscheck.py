"""Cross-program checks: contracts BETWEEN programs.

Every program pair in a fluid job carries an implicit contract the
single-program verifier cannot see:

  startup/main      every persistable main reads before writing must be
                    written by the startup program (or arrive from a
                    checkpoint restore) — a missed initializer is a
                    None-in-scope crash inside jit on step one.
  train/eval clone  hapi's eval program REBUILDS the network, sharing
                    parameters by NAME: the shared Parameters must agree
                    on shape/dtype, every op holding an `is_test` attr
                    must have it flipped True, no optimizer/@GRAD ops
                    may survive in eval, and eval batch_norm ops must
                    read the SAME moving-stats vars train updates
                    (divergent names silently evaluate with frozen
                    init-time statistics).
  PS geometry       a transpiled program's distributed_lookup_table ops
                    name host/pserver tables; the registered table's
                    (rows, dim) must match what the program's output
                    var shapes expect — a stale table from a previous
                    transpile returns wrongly-sized rows.

Entry points: the check_* functions return PR-5-style findings;
`verify_pair` bundles them; `assert_pair_valid` raises
ProgramVerifyError on error findings. Wired (flag-armed) into
hapi.Model.prepare (the fit/evaluate clones) and
DistributeTranspiler.transpile.
"""
from __future__ import annotations

from typing import Iterable, List, Optional

from .. import framework
from ..dtypes import convert_dtype, runtime_dtype
from .core import ERROR, WARNING, Finding, ProgramVerifyError
from .scopecheck import persistable_reads
from .typecheck import _shape_mismatch

GRAD = framework.GRAD_VAR_SUFFIX

# op types the optimizer layer emits (reference convention: Param +
# Grad input slots, ParamOut output). Eval programs must carry none.
_OPTIMIZER_SLOTS = ("Param", "Grad")


def _written_names(program) -> set:
    out = set()
    for b in program.blocks:
        for op in b.ops:
            out.update(op.output_names())
    return out


def _referenced_names(program) -> set:
    out = set()
    for b in program.blocks:
        for op in b.ops:
            out.update(op.input_names())
            out.update(op.output_names())
    return out


def _cs(op):
    return op.attrs.get(framework.OP_CALLSTACK_ATTR)


# ---------------------------------------------------------------------------
# startup/main pairing
# ---------------------------------------------------------------------------


def check_startup_main(startup, main,
                       restore_provided: Iterable[str] = (),
                       feed_names: Iterable[str] = ()) -> List[Finding]:
    """startup-missing-init (ERROR): a persistable main reads before any
    write that startup never writes and no restore provides.
    startup-orphan-init (WARNING): startup initializes a var main never
    references — debris from an abandoned builder, or a startup paired
    with the wrong main."""
    findings: List[Finding] = []
    provided = _written_names(startup) | {str(n) for n in restore_provided}
    for name, (op_idx, op) in sorted(
            persistable_reads(main, feed_names).items()):
        if name not in provided:
            findings.append(Finding(
                check="startup-missing-init", severity=ERROR,
                message=f"main reads persistable {name!r} before any "
                        f"write, but the startup program never "
                        f"initializes it (and it is not marked "
                        f"restore-provided)",
                op_index=op_idx, op_type=op.type, var=name,
                callstack=_cs(op)))
    referenced = _referenced_names(main)
    for b in startup.blocks:
        for i, op in enumerate(b.ops):
            for n in op.output_names():
                v = b._find_var_recursive(n)
                if (v is not None and v.persistable
                        and n not in referenced):
                    findings.append(Finding(
                        check="startup-orphan-init", severity=WARNING,
                        message=f"startup initializes {n!r}, which the "
                                f"main program never references (wrong "
                                f"pairing, or builder debris)",
                        block_idx=b.idx, op_index=i, op_type=op.type,
                        var=n, callstack=_cs(op)))
    return findings


# ---------------------------------------------------------------------------
# train/eval clone consistency
# ---------------------------------------------------------------------------


def _is_optimizer_op(op) -> bool:
    return all(s in op.inputs for s in _OPTIMIZER_SLOTS) \
        and "ParamOut" in op.outputs


def check_train_eval(train, eval_program) -> List[Finding]:
    """The hapi clone contract (parameters shared by NAME, not object):

    clone-param-mismatch  ERROR  an eval Parameter is absent from train
                                 or disagrees on shape/dtype — they
                                 share scope storage, so eval would
                                 read tensors of the wrong geometry
    clone-train-mode      ERROR  an eval op holding an `is_test` attr
                                 still runs training semantics
                                 (dropout on, BN updating stats)
    clone-grad-op         ERROR  an optimizer op or a @GRAD-touching op
                                 survives in eval — evaluate() would
                                 TRAIN on the eval set
    clone-bn-stats        ERROR  an eval batch_norm's Mean/Variance
                                 input is not a train persistable —
                                 eval would normalize with frozen
                                 init-time statistics instead of the
                                 running stats train maintains
    """
    findings: List[Finding] = []
    train_blk = train.global_block()
    train_persist = {v.name for v in train.list_vars() if v.persistable}
    for ev in eval_program.list_vars():
        if not isinstance(ev, framework.Parameter):
            continue
        tv = train_blk._find_var_recursive(ev.name)
        if tv is None:
            findings.append(Finding(
                check="clone-param-mismatch", severity=ERROR,
                message=f"eval Parameter {ev.name!r} does not exist in "
                        f"the train program — the clones were built "
                        f"without shared unique_name state",
                var=ev.name))
        elif _shape_mismatch(ev.shape, tv.shape) or (
                ev.dtype is not None and tv.dtype is not None
                and runtime_dtype(convert_dtype(ev.dtype))
                != runtime_dtype(convert_dtype(tv.dtype))):
            findings.append(Finding(
                check="clone-param-mismatch", severity=ERROR,
                message=f"Parameter {ev.name!r} disagrees between the "
                        f"clones: train {tuple(tv.shape or ())}/"
                        f"{convert_dtype(tv.dtype).name} vs eval "
                        f"{tuple(ev.shape or ())}/"
                        f"{convert_dtype(ev.dtype).name} — they share "
                        f"scope storage by name",
                var=ev.name))
    for b in eval_program.blocks:
        for i, op in enumerate(b.ops):
            if "is_test" in op.attrs and not op.attrs.get("is_test"):
                findings.append(Finding(
                    check="clone-train-mode", severity=ERROR,
                    message=f"eval op {op.type!r} still has "
                            f"is_test=False — the clone was not "
                            f"flipped to inference semantics",
                    block_idx=b.idx, op_index=i, op_type=op.type,
                    callstack=_cs(op)))
            grads = [n for n in list(op.input_names())
                     + list(op.output_names()) if GRAD in n]
            if _is_optimizer_op(op) or grads:
                findings.append(Finding(
                    check="clone-grad-op", severity=ERROR,
                    message=f"eval program contains "
                            f"{'optimizer' if _is_optimizer_op(op) else 'gradient'} "
                            f"op {op.type!r} — evaluate() would train "
                            f"on the eval set",
                    block_idx=b.idx, op_index=i, op_type=op.type,
                    var=(grads[0] if grads else None),
                    callstack=_cs(op)))
            if op.type in ("batch_norm", "instance_norm"):
                for slot in ("Mean", "Variance"):
                    for n in op.inputs.get(slot) or ():
                        if n not in train_persist:
                            findings.append(Finding(
                                check="clone-bn-stats", severity=ERROR,
                                message=f"eval {op.type} reads "
                                        f"{slot}={n!r}, which is not a "
                                        f"train persistable — the "
                                        f"moving statistics diverged "
                                        f"between the clones",
                                block_idx=b.idx, op_index=i,
                                op_type=op.type, var=n,
                                callstack=_cs(op)))
    return findings


# ---------------------------------------------------------------------------
# PS-table geometry
# ---------------------------------------------------------------------------


def check_ps_geometry(program) -> List[Finding]:
    """Every distributed_lookup_table op must name a table registered in
    this process whose embedding dim matches the op's output var shape
    (ps-table-missing / ps-table-geometry, both ERROR). Programs with no
    distributed ops return [] without importing the PS layer."""
    findings: List[Finding] = []
    ops = [(b, i, op) for b in program.blocks
           for i, op in enumerate(b.ops)
           if op.type == "distributed_lookup_table"]
    if not ops:
        return findings
    from ...distributed import ps

    for b, i, op in ops:
        names = op.attr("table_names", []) or (
            [op.attr("table_name")] if op.attr("table_name") else [])
        for name in names:
            try:
                table = ps.get_table(name)
            except KeyError:
                findings.append(Finding(
                    check="ps-table-missing", severity=ERROR,
                    message=f"op references PS table {name!r}, but no "
                            f"such table is registered in this process "
                            f"(create_table/transpile before running)",
                    block_idx=b.idx, op_index=i, op_type=op.type,
                    var=name, callstack=_cs(op)))
                continue
            dim = getattr(table, "dim", None)
            for out in op.outputs.get("Outputs") or op.output_names():
                v = b._find_var_recursive(out)
                if (v is not None and v.shape and dim is not None
                        and int(v.shape[-1]) not in (-1, int(dim))):
                    findings.append(Finding(
                        check="ps-table-geometry", severity=ERROR,
                        message=f"PS table {name!r} has embedding dim "
                                f"{dim}, but output {out!r} expects "
                                f"{v.shape[-1]} (stale table from a "
                                f"previous transpile?)",
                        block_idx=b.idx, op_index=i, op_type=op.type,
                        var=name, callstack=_cs(op)))
    return findings


# ---------------------------------------------------------------------------
# bundled entry
# ---------------------------------------------------------------------------


def verify_pair(main, startup=None, eval_program=None,
                restore_provided: Iterable[str] = (),
                feed_names: Iterable[str] = ()) -> List[Finding]:
    """Run every cross-program check the given programs allow:
    startup/main pairing when `startup` is given, train/eval clone
    consistency when `eval_program` is given, and PS-table geometry on
    each program. Returns findings most-severe-first."""
    findings: List[Finding] = []
    if startup is not None:
        findings.extend(check_startup_main(
            startup, main, restore_provided=restore_provided,
            feed_names=feed_names))
    if eval_program is not None:
        findings.extend(check_train_eval(main, eval_program))
        findings.extend(check_ps_geometry(eval_program))
    findings.extend(check_ps_geometry(main))
    findings.sort(key=lambda f: (0 if f.severity == ERROR else 1,
                                 f.check, f.var or ""))
    return findings


def assert_pair_valid(main, startup=None, eval_program=None,
                      restore_provided: Iterable[str] = (),
                      feed_names: Iterable[str] = (),
                      where: str = "") -> List[Finding]:
    findings = verify_pair(main, startup=startup,
                           eval_program=eval_program,
                           restore_provided=restore_provided,
                           feed_names=feed_names)
    if any(f.severity == ERROR for f in findings):
        raise ProgramVerifyError(findings, where=where)
    return findings
