"""Grad-graph integrity after append_backward.

The backward builder's contract (fluid/backward.py): every `<var>@GRAD`
(or `@GRAD@RENAME@n` partial) an op consumes was produced by an earlier
op in the same block, and a generic-vjp grad op's input-grad outputs
mirror the forward inputs' metadata (backward copies them; shapes are
never re-traced). A pass that rewrites the forward AFTER backward ran —
or a hand-built grad desc — can break either invariant; the compiled
step then fails deep in the XLA trace or, worse, trains on garbage.
"""
from __future__ import annotations

from .. import framework
from ..dtypes import runtime_dtype
from .core import ERROR, CheckContext, register_check
from .typecheck import _shape_mismatch

GRAD = framework.GRAD_VAR_SUFFIX


@register_check("grad-integrity")
def check_grad_integrity(ctx: CheckContext):
    """Every consumed @GRAD name has an earlier producer. Grad vars are
    never feeds; a persistable @GRAD (DGC error-feedback style buffers)
    is scope state and exempt."""
    for view in ctx.views:
        block = view.block
        produced = set(view.entry_names)
        for i, op in enumerate(block.ops):
            for n in op.input_names():
                if GRAD in n and n not in produced:
                    v = block._find_var_recursive(n)
                    if v is not None and v.persistable:
                        continue
                    ctx.report(
                        "grad-integrity", ERROR,
                        f"gradient {n!r} is consumed but no earlier op "
                        f"produces it — the grad graph is torn (was the "
                        f"forward rewritten after append_backward?)",
                        block_idx=block.idx, op_index=i, op=op, var=n)
            produced.update(op.output_names())


@register_check("grad-shape-mirror")
def check_grad_shape_mirror(ctx: CheckContext):
    """Generic-vjp grad ops (attr __fwd_in_slots__): the grad var of
    forward input X must carry X's (shape, dtype) — backward.py copies
    them instead of re-tracing, so a mismatch means someone edited one
    side of the pair."""
    for view in ctx.views:
        block = view.block
        for i, op in enumerate(block.ops):
            slots = op.attrs.get("__fwd_in_slots__")
            if not op.type.endswith("_grad") or not slots:
                continue
            for slot in slots:
                fwd_names = op.inputs.get(slot) or []
                grad_names = op.outputs.get(slot + GRAD) or []
                for fn_, gn in zip(fwd_names, grad_names):
                    if gn.endswith("@UNUSED"):
                        continue
                    fv = block._find_var_recursive(fn_)
                    gv = block._find_var_recursive(gn)
                    if fv is None or gv is None:
                        continue
                    if gv.shape is None and gv.dtype is None:
                        continue
                    if _shape_mismatch(fv.shape, gv.shape):
                        ctx.report(
                            "grad-shape-mirror", ERROR,
                            f"grad {gn!r} records shape "
                            f"{tuple(gv.shape or ())} but its forward "
                            f"var {fn_!r} is {tuple(fv.shape or ())}",
                            block_idx=block.idx, op_index=i, op=op,
                            var=gn)
                    elif (fv.dtype is not None and gv.dtype is not None
                          and runtime_dtype(fv.dtype)
                          != runtime_dtype(gv.dtype)):
                        ctx.report(
                            "grad-shape-mirror", ERROR,
                            f"grad {gn!r} records dtype {gv.dtype} but "
                            f"its forward var {fn_!r} is {fv.dtype}",
                            block_idx=block.idx, op_index=i, op=op,
                            var=gn)
