"""Dataflow checks: dangling refs, ordering, stale writers, dead code.

These are the bug classes graph REWRITES introduce (a fusion pass that
deletes ops, a backward builder that renames partials): an op reading a
name nothing defines, a producer moved after its consumer, a
`Variable.op` last-writer link pointing at an op no longer in any
block, outputs nothing will ever read.
"""
from __future__ import annotations

from .. import framework
from .core import ERROR, WARNING, CheckContext, register_check


def _producer_indices(block):
    """name -> first op index in `block` producing it."""
    first = {}
    for i, op in enumerate(block.ops):
        for n in op.output_names():
            first.setdefault(n, i)
    return first


@register_check("use-before-def")
def check_use_before_def(ctx: CheckContext):
    """Three findings share this walker:

    dangling-ref        consumed name resolves to NO variable anywhere
    use-before-def      producer exists but runs AFTER the consumer, or
                        (sub-blocks) the name is readable at build time
                        via parent scoping but is NOT in the emitter's
                        env contract (captured/loop/step names) — a
                        guaranteed runtime KeyError in emit_ops
    maybe-uninitialized root-block var with no producer that is neither
                        a data var nor persistable: it must arrive via
                        feed or pre-populated scope, which the program
                        alone cannot prove
    """
    for view in ctx.views:
        block = view.block
        producers = _producer_indices(block)
        defined = set(view.entry_names)
        for i, op in enumerate(block.ops):
            for n in op.input_names():
                if n in defined:
                    continue
                v = block._find_var_recursive(n)
                if v is None:
                    ctx.report(
                        "dangling-ref", ERROR,
                        f"op consumes {n!r}, which no block defines",
                        block_idx=block.idx, op_index=i, op=op, var=n)
                    continue
                if view.is_sub:
                    owner = view.owner_op
                    ctx.report(
                        "use-before-def", ERROR,
                        f"sub-block op reads {n!r}, which is neither "
                        f"captured by the enclosing {owner.type!r} op nor "
                        f"produced earlier in the sub-block — emit_ops "
                        f"will KeyError at trace time",
                        block_idx=block.idx, op_index=i, op=op, var=n)
                    continue
                if v.is_data or v.persistable:
                    continue
                p = producers.get(n)
                if p is not None and p > i:
                    ctx.report(
                        "use-before-def", ERROR,
                        f"{n!r} is consumed at op#{i} but first produced "
                        f"at op#{p}",
                        block_idx=block.idx, op_index=i, op=op, var=n)
                elif p is None:
                    ctx.report(
                        "maybe-uninitialized", WARNING,
                        f"{n!r} has no producer and is not a data/"
                        f"persistable var; it must be fed or already in "
                        f"scope at run time",
                        block_idx=block.idx, op_index=i, op=op, var=n)
            defined.update(op.output_names())


def _live_op_ids(program):
    ids = set()
    for b in program.blocks:
        for op in b.ops:
            ids.add(id(op))
            for sop in op.attrs.get("recompute_sub_ops") or ():
                ids.add(id(sop))
    return ids


@register_check("stale-last-writer")
def check_stale_last_writer(ctx: CheckContext):
    """Variable.op must point at a live op that actually outputs the
    var. A rewrite that deletes or rewires ops without maintaining the
    link breaks backward's producer lookup and pruning — the exact
    breakage conv+BN fusion had before it dropped its dead
    intermediates."""
    live = _live_op_ids(ctx.program)
    for block in ctx.program.blocks:
        for name, v in block.vars.items():
            op = v.op
            if op is None:
                continue
            if id(op) not in live:
                ctx.report(
                    "stale-last-writer", ERROR,
                    f"{name!r} records last-writer op {op.type!r}, which "
                    f"is no longer in any block (removed by a rewrite "
                    f"without updating the link)",
                    block_idx=block.idx, var=name, op=op)
            elif name not in op.output_names():
                ctx.report(
                    "stale-last-writer", ERROR,
                    f"{name!r} records last-writer op {op.type!r}, but "
                    f"that op does not output it (rewired without "
                    f"updating the link)",
                    block_idx=block.idx, var=name, op=op)


def _attr_strings(op):
    """Names referenced through attrs (sub-block out/carry name lists):
    consumers the input slots cannot show."""
    out = []
    for k, v in op.attrs.items():
        if k.startswith("__"):
            continue
        if isinstance(v, str):
            out.append(v)
        elif isinstance(v, (list, tuple)):
            out.extend(x for x in v if isinstance(x, str))
    return out


@register_check("dead-op")
def check_dead_code(ctx: CheckContext):
    """dead-op: every output is non-persistable and nothing consumes it
    (no op input, no attr name list, not in the caller's live set) — the
    op still costs trace+compile time and usually marks a broken rewrite.
    unused-var: a var with neither producer nor consumer (fusion debris).
    Both WARNING: the verifier cannot see fetch lists it was not given
    (pass live_out= / proglint passes feeds+loss)."""
    program = ctx.program
    consumed = set(ctx.live_out)
    for b in program.blocks:
        for op in b.ops:
            consumed.update(op.input_names())
            consumed.update(_attr_strings(op))
    producers = set()
    for view in ctx.views:
        block = view.block
        for i, op in enumerate(block.ops):
            outs = op.output_names()
            producers.update(outs)
            if not outs:
                continue

            def _live(n):
                if n in consumed:
                    return True
                if n.endswith(framework.GRAD_VAR_SUFFIX):
                    # a trainable parameter's gradient is append_backward's
                    # deliverable (params_grads) even before an optimizer
                    # consumes it
                    base = block._find_var_recursive(
                        n[: -len(framework.GRAD_VAR_SUFFIX)])
                    if isinstance(base, framework.Parameter):
                        return True
                v = block._find_var_recursive(n)
                return v is not None and (v.persistable or v.is_data)

            if not any(_live(n) for n in outs):
                ctx.report(
                    "dead-op", WARNING,
                    f"no output of this op ({outs}) is persistable or "
                    f"consumed anywhere; if it is a fetch target, pass "
                    f"it via live_out",
                    block_idx=block.idx, op_index=i, op=op,
                    var=outs[0])
    for view in ctx.views:
        block = view.block
        for name, v in block.vars.items():
            if (v.op is None and name not in producers
                    and name not in consumed and not v.persistable
                    and not v.is_data
                    and not isinstance(v, framework.Parameter)):
                ctx.report(
                    "unused-var", WARNING,
                    f"{name!r} is neither produced nor consumed by any "
                    f"op (debris from a rewrite?)",
                    block_idx=block.idx, var=name)
