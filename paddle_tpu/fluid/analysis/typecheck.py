"""Type/shape checks: recorded IR metadata vs recomputed inference.

The IR records each var's (shape, dtype) from `jax.eval_shape` over the
op's emitter at append time (framework.infer_op_outputs). A rewrite that
splices ops in by hand (fusion, hand-built grad descs) can leave the
recorded metadata inconsistent with what the emitter will actually
produce — XLA then fails deep inside the whole-block trace. This module
re-runs the SAME inference (framework.compute_op_output_metas, -1-dim
tolerant) and cross-checks, plus two dtype lints the inference cannot
see: mixed-width float operands and silently-truncating fill_constants.
"""
from __future__ import annotations

import numpy as np

from .. import framework
from ..dtypes import convert_dtype, is_floating, is_integer, runtime_dtype
from .core import ERROR, INFO, WARNING, CheckContext, register_check

GRAD = framework.GRAD_VAR_SUFFIX


def _shape_mismatch(a, b) -> bool:
    """True when shapes disagree; -1 (dynamic batch) matches anything."""
    if a is None or b is None:
        return False
    if len(a) != len(b):
        return True
    return any(x != -1 and y != -1 and x != y for x, y in zip(a, b))


def _rt(dtype):
    return runtime_dtype(convert_dtype(dtype))


@register_check("shape-dtype")
def check_shape_dtype(ctx: CheckContext):
    from ...ops import registry

    for view in ctx.views:
        block = view.block
        for i, op in enumerate(block.ops):
            spec = registry.get(op.type)
            if spec is None:
                ctx.report(
                    "shape-dtype", ERROR,
                    f"op type {op.type!r} has no registered emitter — "
                    f"the Executor will refuse to compile this block",
                    block_idx=block.idx, op_index=i, op=op)
                continue
            if op.type.endswith("_grad") or spec.generic_vjp:
                continue  # grad convention checked in gradcheck
            try:
                metas = framework.compute_op_output_metas(block, op)
            except Exception as e:  # noqa: BLE001 — report, don't crash
                ctx.report(
                    "shape-dtype", INFO,
                    f"output metas not recomputable ({type(e).__name__}: "
                    f"{e})", block_idx=block.idx, op_index=i, op=op)
                continue
            if metas is None:
                continue
            for slot, names in op.outputs.items():
                ms = metas.get(slot)
                if ms is None:
                    continue
                for n, (shape, dt) in zip(names, ms):
                    v = block._find_var_recursive(n)
                    if v is None:
                        continue  # dangling-ref owns that finding
                    if (dt is not None and v.dtype is not None
                            and _rt(v.dtype) != _rt(dt)):
                        ctx.report(
                            "shape-dtype", ERROR,
                            f"{n!r} records dtype "
                            f"{np.dtype(v.dtype).name}, but the emitter "
                            f"produces {np.dtype(dt).name}",
                            block_idx=block.idx, op_index=i, op=op, var=n)
                    if shape is not None and v.shape is not None and \
                            _shape_mismatch(tuple(v.shape), tuple(shape)):
                        ctx.report(
                            "shape-dtype", ERROR,
                            f"{n!r} records shape {tuple(v.shape)}, but "
                            f"the emitter produces {tuple(shape)}",
                            block_idx=block.idx, op_index=i, op=op, var=n)


# multi-operand numeric ops where the IR expects ALIGNED dtypes (AMP
# inserts explicit casts; jnp's silent promotion hides missed ones)
_ALIGNED_OPS = frozenset({
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_max", "elementwise_min",
    "elementwise_pow", "elementwise_mod", "sum",
    "greater_than", "greater_equal", "less_than", "less_equal",
    "equal", "not_equal", "matmul", "mul",
})


@register_check("dtype-clash")
def check_dtype_clash(ctx: CheckContext):
    for view in ctx.views:
        block = view.block
        for i, op in enumerate(block.ops):
            if op.type not in _ALIGNED_OPS:
                continue
            dts = []
            for n in op.input_names():
                v = block._find_var_recursive(n)
                if v is not None and v.dtype is not None:
                    dts.append((n, _rt(v.dtype)))
            if len(dts) < 2:
                continue
            floats = {d.name for _, d in dts if is_floating(d)}
            ints = {d.name for _, d in dts if is_integer(d)}
            bools = [n for n, d in dts if d == np.dtype(bool)]
            pairs = ", ".join(f"{n}:{d.name}" for n, d in dts)
            if len(floats) > 1:
                # mixed float widths silently promote and throw away the
                # low-precision operand's perf win — the missed-AMP-cast
                # bug class
                ctx.report(
                    "dtype-clash", ERROR,
                    f"operands mix float widths {sorted(floats)} "
                    f"({pairs}); insert an explicit cast",
                    block_idx=block.idx, op_index=i, op=op,
                    var=dts[0][0])
            elif floats and ints:
                ctx.report(
                    "dtype-clash", WARNING,
                    f"operands mix integer and float dtypes ({pairs}); "
                    f"jnp promotion decides the result dtype implicitly",
                    block_idx=block.idx, op_index=i, op=op,
                    var=dts[0][0])
            elif bools and (floats or ints):
                ctx.report(
                    "dtype-clash", WARNING,
                    f"bool operand mixed with numeric ({pairs})",
                    block_idx=block.idx, op_index=i, op=op, var=bools[0])


@register_check("fill-truncation")
def check_fill_truncation(ctx: CheckContext):
    """fill_constant with an integer/bool declared dtype and a
    fractional value: jnp.full silently truncates (0.5 -> 0), turning a
    scalar-broadcast expression into the wrong constant. This is the
    bug Variable._binary used to build for `int_var * 0.5`."""
    for view in ctx.views:
        block = view.block
        for i, op in enumerate(block.ops):
            if op.type not in ("fill_constant",
                               "fill_constant_batch_size_like"):
                continue
            try:
                value = float(op.attr("value", 0.0))
            except (TypeError, ValueError):
                continue
            dt = convert_dtype(op.attr("dtype", "float32"))
            if not is_floating(dt) and not value.is_integer():
                ctx.report(
                    "fill-truncation", ERROR,
                    f"fill_constant declares dtype {dt.name} but value "
                    f"{value} is fractional — it will be silently "
                    f"truncated to {int(value)}",
                    block_idx=block.idx, op_index=i, op=op,
                    var=(op.output_names() or [None])[0])
