"""proglint --fix: auto-rewrites for mechanically-fixable findings.

The verifier's findings split into two classes: bugs that need a human
(a shape contract violated, a grad mirrored wrong) and debris a machine
can sweep — exactly the classes a rewrite pass leaves behind when it
forgets to clean up after itself. The fixers here repair the second
class:

  torn-grads          drop ops consuming a producer-less @GRAD (the
                      grad-integrity finding): the forward was rewritten
                      after append_backward and the orphaned grad chain
                      can only KeyError at trace time
  dead-code           sweep dead-op / unused-var findings to a fixpoint
                      (removing a dead op can orphan its inputs' only
                      producer)
  stale-last-writer   recompute Variable.op for vars whose recorded
                      writer was removed or rewired (the freeze_program
                      relink, applied surgically)
  startup-init        append a fill_constant(0) initializer to the
                      startup program for persistables main reads but
                      nothing initializes (NOT semantics-preserving for
                      training quality — it makes a torn job runnable
                      and visible, the value is a placeholder)

Safety protocol (the inverse of `pass_sandwich`, whose contract is
"valid in, valid out" — a fixer's input is broken BY DEFINITION):
verify AFTER each fix and compare against the error set from before it;
any NEW error raises ProgramVerifyError attributed `fix:<name>`.
Pre-existing errors may legitimately remain (a later fixer or the final
lint owns them). The first three fixers are semantics-preserving on the
live (fetch-reachable) graph — `tools/proglint.py --fix` and the ci.sh
round-trip assert bit-identical loss traces for them.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

from .. import framework
from .core import ERROR, Finding, ProgramVerifyError, verify_program
from .dataflow import _attr_strings

GRAD = framework.GRAD_VAR_SUFFIX


@dataclass
class FixReport:
    """One fixer's outcome: what it rewrote, in human-readable lines."""

    name: str
    actions: List[str] = field(default_factory=list)

    @property
    def changed(self) -> bool:
        return bool(self.actions)


# ---------------------------------------------------------------------------
# individual fixers — each mutates in place, returns action lines
# ---------------------------------------------------------------------------


def _remove_op_and_relink(program, block, index: int):
    """Remove block.ops[index] AND repair the last-writer links of its
    outputs (earlier producer in the block, or None) — an op removal
    must not leave the stale-last-writer breakage it would take another
    fixer to clean."""
    op = block.ops[index]
    block._remove_op(index)
    for n in op.output_names():
        v = block._find_var_recursive(n)
        if v is None or v.op is not op:
            continue
        v.op = None
        for cand in reversed(block.ops):
            if n in cand.output_names():
                v.op = cand
                break


def fix_torn_grads(program, live_out: Iterable[str] = ()) -> List[str]:
    """Remove root-block ops consuming a @GRAD name no earlier op
    produces (persistable @GRAD buffers are scope state and exempt).
    Iterates: removing an orphan's consumer can orphan the consumers of
    ITS outputs. Sub-blocks are left alone — a captured grad name there
    is the owner op's contract, not debris."""
    blk = program.global_block()
    actions: List[str] = []
    while True:
        produced: set = set()
        doomed = None
        for i, op in enumerate(blk.ops):
            for n in op.input_names():
                if GRAD not in n or n in produced:
                    continue
                v = blk._find_var_recursive(n)
                if v is not None and v.persistable:
                    continue
                doomed = (i, op, n)
                break
            if doomed:
                break
            produced.update(op.output_names())
        if not doomed:
            break
        i, op, n = doomed
        _remove_op_and_relink(program, blk, i)
        actions.append(f"dropped op#{i} {op.type!r}: consumed torn "
                       f"gradient {n!r} with no producer")
    return actions


def _grad_of_parameter(block, name: str) -> bool:
    if not name.endswith(GRAD):
        return False
    base = block._find_var_recursive(name[: -len(GRAD)])
    return isinstance(base, framework.Parameter)


def fix_dead_code(program, live_out: Iterable[str] = ()) -> List[str]:
    """Sweep dead-op and unused-var findings to a fixpoint, with the
    same liveness the dataflow check uses: an output is live if anything
    consumes it (op input, attr name list, live_out) or it is
    persistable / a data var / a Parameter's gradient."""
    actions: List[str] = []
    live_out = {str(n) for n in live_out}
    while True:
        consumed = set(live_out)
        for b in program.blocks:
            for op in b.ops:
                consumed.update(op.input_names())
                consumed.update(_attr_strings(op))

        def _live(block, n):
            if n in consumed or _grad_of_parameter(block, n):
                return True
            v = block._find_var_recursive(n)
            return v is not None and (v.persistable or v.is_data)

        removed = False
        for b in program.blocks:
            for i in range(len(b.ops) - 1, -1, -1):
                op = b.ops[i]
                outs = op.output_names()
                if outs and not any(_live(b, n) for n in outs):
                    _remove_op_and_relink(program, b, i)
                    actions.append(
                        f"removed dead op#{i} {op.type!r} in block "
                        f"{b.idx} (outputs {outs} never consumed)")
                    removed = True
        if not removed:
            break
    # unused vars: neither produced nor consumed once the ops settled
    touched = set(live_out)
    for b in program.blocks:
        for op in b.ops:
            touched.update(op.input_names())
            touched.update(op.output_names())
            touched.update(_attr_strings(op))
    for b in program.blocks:
        for name in [n for n in b.vars if n not in touched]:
            v = b.vars[name]
            if v.persistable or v.is_data \
                    or isinstance(v, framework.Parameter):
                continue
            del b.vars[name]
            program._bump_version()
            actions.append(f"removed unused var {name!r} from block "
                           f"{b.idx}")
    return actions


def fix_stale_last_writer(program, live_out: Iterable[str] = ()) -> List[str]:
    """Recompute Variable.op for vars whose recorded last writer is no
    longer in any block or no longer outputs them. Only broken links
    are touched — a var legitimately written by a fused op's
    recompute_sub_ops keeps its link."""
    live_ids = set()
    for b in program.blocks:
        for op in b.ops:
            live_ids.add(id(op))
            for sop in op.attrs.get("recompute_sub_ops") or ():
                live_ids.add(id(sop))
    actions: List[str] = []
    for b in program.blocks:
        for name, v in b.vars.items():
            op = v.op
            if op is None:
                continue
            if id(op) in live_ids and name in op.output_names():
                continue
            new_op = None
            for cand in reversed(b.ops):
                if name in cand.output_names():
                    new_op = cand
                    break
            v.op = new_op
            program._bump_version()
            actions.append(
                f"relinked last-writer of {name!r}: stale {op.type!r} "
                f"-> " + (f"{new_op.type!r}" if new_op else "None"))
    return actions


def fix_missing_startup_init(main, startup,
                             restore_provided: Iterable[str] = (),
                             feed_names: Iterable[str] = ()) -> List[str]:
    """Append a fill_constant(0) to `startup` for every persistable the
    main program reads before writing that startup never initializes.
    Vars with unknown or partial shapes cannot be synthesized and are
    reported as skipped (a human owns those)."""
    from .crosscheck import check_startup_main

    actions: List[str] = []
    sblk = startup.global_block()
    for f in check_startup_main(startup, main,
                                restore_provided=restore_provided,
                                feed_names=feed_names):
        if f.check != "startup-missing-init":
            continue
        v = main.global_block()._find_var_recursive(f.var)
        if (v is None or v.shape is None or any(d < 0 for d in v.shape)
                or v.dtype is None):
            actions.append(f"SKIPPED {f.var!r}: shape/dtype unknown, "
                           f"cannot synthesize an initializer")
            continue
        sblk.create_var(name=v.name, shape=tuple(v.shape), dtype=v.dtype,
                        persistable=True)
        sblk.append_op(
            type="fill_constant",
            outputs={"Out": [v.name]},
            attrs={"shape": list(v.shape), "dtype": v.dtype,
                   "value": 0.0})
        actions.append(f"appended fill_constant(0) initializer for "
                       f"{v.name!r} {tuple(v.shape)} to the startup "
                       f"program (placeholder value — review)")
    return actions


# ---------------------------------------------------------------------------
# orchestration
# ---------------------------------------------------------------------------

# name -> (fixer, needs_startup); applied in this order — torn grads
# first (their removal creates dead code), dead-code sweep, then the
# link repair, then the cross-program startup patch
FIXERS = (
    ("torn-grads", fix_torn_grads, False),
    ("dead-code", fix_dead_code, False),
    ("stale-last-writer", fix_stale_last_writer, False),
    ("startup-init", fix_missing_startup_init, True),
)


def _error_keys(program, live_out):
    return {f.key() for f in verify_program(program, live_out=live_out)
            if f.severity == ERROR}


def apply_fixes(program, live_out: Iterable[str] = (), startup=None,
                fixes: Optional[Iterable[str]] = None,
                feed_names: Iterable[str] = (),
                restore_provided: Iterable[str] = ()) -> List[FixReport]:
    """Run the mechanical fixers over `program` in place. `startup`
    enables the cross-program startup-init fixer. `fixes` restricts to a
    subset of FIXERS names. After EACH fixer the program is re-verified:
    an error that was not present before that fixer ran raises
    ProgramVerifyError attributed `fix:<name>` — a fixer may leave
    pre-existing breakage for a later fixer, but may not add its own."""
    wanted = set(fixes) if fixes is not None else None
    unknown = (wanted or set()) - {n for n, _, _ in FIXERS}
    if unknown:
        raise ValueError(f"unknown fix pass(es): {sorted(unknown)}; "
                         f"known: {[n for n, _, _ in FIXERS]}")
    live_out = {str(n) for n in live_out}
    reports: List[FixReport] = []
    for name, fn, needs_startup in FIXERS:
        if wanted is not None and name not in wanted:
            continue
        if needs_startup and startup is None:
            continue
        before = _error_keys(program, live_out)
        if needs_startup:
            actions = fn(program, startup,
                         restore_provided=restore_provided,
                         feed_names=feed_names)
        else:
            actions = fn(program, live_out)
        report = FixReport(name=name, actions=actions)
        reports.append(report)
        if not report.changed:
            continue
        after = verify_program(program, live_out=live_out)
        fresh = [f for f in after
                 if f.severity == ERROR and f.key() not in before]
        if fresh:
            for f in fresh:
                f.pass_name = f"fix:{name}"
            raise ProgramVerifyError(
                fresh, where=f"after fix pass {name!r} — the fix "
                             f"introduced new errors and must not ship")
    return reports
