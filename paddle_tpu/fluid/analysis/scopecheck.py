"""Scope-aware lint: a Program verified against LIVE state.

The PR-5 checks see one Program in isolation; this module checks the
contract between a program and the state it will run against — a live
`Scope`, a checkpoint's array manifest, or a FrozenModel's captured
weights. The bug class is "fails inside jit": a persistable the program
reads that is absent/None in the scope aborts deep in the whole-block
trace, and a shape/dtype-mismatched restore produces an XLA error
hundreds of frames from the var that caused it. Here both surface as
PR-5-style findings naming the var AND the owning layer (the first
consumer op's build-time call stack).

Check catalog (reported, not registered — these need scope state the
`register_check` contract does not carry):

  scope-missing-persistable  ERROR    read-before-write persistable
                                      absent from the scope (run the
                                      startup program / restore first)
  scope-uninitialized        ERROR    present but still None (a
                                      Scope.var() placeholder nothing
                                      ever wrote)
  scope-shape-mismatch       ERROR    scope array shape disagrees with
                                      the var meta (-1 dims tolerant)
  scope-dtype-mismatch       ERROR    scope array dtype disagrees
                                      (runtime-normalized: x64-off
                                      float64 == float32)
  scope-orphan-var           WARNING  scope entry no program var names
                                      (stale state from another program
                                      sharing the scope)

Wired into: Executor first-touch (compile-cache miss) under
FLAGS_program_verify; CheckpointManager restore (mismatch raises
RestoreMismatchError naming the var + layer BEFORE anything touches the
scope); freeze_program (the frozen program must read only its captured
weights + detected state vars — unconditional, like the freeze verify).
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

from .. import framework
from ..dtypes import convert_dtype, runtime_dtype
from .core import ERROR, WARNING, Finding, ProgramVerifyError
from .typecheck import _shape_mismatch

_SEV_ORDER = {ERROR: 0, WARNING: 1}


def _scope_items(scope_or_mapping):
    """Duck-typed view over a live Scope OR a plain {name: array}
    mapping (a checkpoint's state["arrays"]): returns the dict."""
    vars_ = getattr(scope_or_mapping, "vars", None)
    if isinstance(vars_, dict):
        return vars_
    return scope_or_mapping


def persistable_reads(program, feed_names: Iterable[str] = ()
                      ) -> Dict[str, Tuple[int, object]]:
    """Persistables the program READS BEFORE WRITING, in op order —
    the names that must already exist in the scope when the block runs
    (params, BN running stats, optimizer moments, decode caches).
    Returns {name: (op_index, op)} of the first reading op in block 0
    (sub-block reads count at their owner op's site), for finding
    attribution. Feeds and data vars are the caller's to provide and
    are excluded."""
    feeds = {str(n) for n in feed_names}
    written: set = set()
    reads: Dict[str, Tuple[int, object]] = {}

    def note_reads(block, op, site_idx, site_op):
        for n in op.input_names():
            if n in written or n in feeds or n in reads:
                continue
            v = block._find_var_recursive(n)
            if v is None or not v.persistable or v.is_data:
                continue
            reads[n] = (site_idx, site_op)
        # sub-blocks execute inside the owner op, after its inputs are
        # read and before its outputs are written
        from .core import _SUB_BLOCK_SPECS

        for blk_attr, _seeds in _SUB_BLOCK_SPECS.get(op.type, ()):
            sub = op.attrs.get(blk_attr)
            if isinstance(sub, framework.Block):
                for sop in sub.ops:
                    note_reads(sub, sop, site_idx, site_op)

    root = program.global_block()
    for i, op in enumerate(root.ops):
        note_reads(root, op, i, op)
        written.update(op.output_names())
    return reads


def _meta_of(value) -> Tuple[Optional[tuple], Optional[object]]:
    """(shape, dtype) of a scope value without materializing it —
    works for jax/numpy arrays and checkpoint ndarray entries."""
    shape = getattr(value, "shape", None)
    dtype = getattr(value, "dtype", None)
    return (tuple(shape) if shape is not None else None, dtype)


def verify_scope(program, scope, feed_names: Iterable[str] = (),
                 check_orphans: bool = True) -> List[Finding]:
    """Verify `program` against `scope` (a Scope or a {name: array}
    mapping). Returns PR-5-style findings, most severe first."""
    entries = _scope_items(scope)
    findings: List[Finding] = []
    for name, (op_idx, op) in sorted(persistable_reads(
            program, feed_names).items()):
        v = program.global_block()._find_var_recursive(name)
        if name not in entries:
            findings.append(Finding(
                check="scope-missing-persistable", severity=ERROR,
                message=f"program reads persistable {name!r}, which is "
                        f"not in the scope — run the startup program "
                        f"(or restore a checkpoint) first",
                op_index=op_idx, op_type=op.type, var=name,
                callstack=op.attrs.get(framework.OP_CALLSTACK_ATTR)))
            continue
        value = entries[name]
        if value is None:
            findings.append(Finding(
                check="scope-uninitialized", severity=ERROR,
                message=f"persistable {name!r} is in the scope but "
                        f"still None (created but never initialized)",
                op_index=op_idx, op_type=op.type, var=name,
                callstack=op.attrs.get(framework.OP_CALLSTACK_ATTR)))
            continue
        shape, dtype = _meta_of(value)
        if (v is not None and v.shape is not None and shape is not None
                and _shape_mismatch(tuple(v.shape), shape)):
            findings.append(Finding(
                check="scope-shape-mismatch", severity=ERROR,
                message=f"persistable {name!r}: program expects shape "
                        f"{tuple(v.shape)} but the scope holds {shape}",
                op_index=op_idx, op_type=op.type, var=name,
                callstack=op.attrs.get(framework.OP_CALLSTACK_ATTR)))
        elif (v is not None and v.dtype is not None and dtype is not None
              and runtime_dtype(convert_dtype(v.dtype))
              != runtime_dtype(convert_dtype(dtype))):
            findings.append(Finding(
                check="scope-dtype-mismatch", severity=ERROR,
                message=f"persistable {name!r}: program expects dtype "
                        f"{convert_dtype(v.dtype).name} but the scope "
                        f"holds {convert_dtype(dtype).name}",
                op_index=op_idx, op_type=op.type, var=name,
                callstack=op.attrs.get(framework.OP_CALLSTACK_ATTR)))
    if check_orphans:
        named = set()
        for b in program.blocks:
            named.update(b.vars)
        for name in sorted(entries):
            if name not in named:
                findings.append(Finding(
                    check="scope-orphan-var", severity=WARNING,
                    message=f"scope holds {name!r}, which no program "
                            f"var names (stale state from another "
                            f"program sharing this scope?)",
                    var=name))
    findings.sort(key=lambda f: (_SEV_ORDER.get(f.severity, 2),
                                 f.var or ""))
    return findings


def assert_scope_valid(program, scope, feed_names: Iterable[str] = (),
                       check_orphans: bool = True,
                       where: str = "") -> List[Finding]:
    """verify_scope, raising ProgramVerifyError on error findings
    (orphan warnings never raise). Returns the findings otherwise."""
    findings = verify_scope(program, scope, feed_names=feed_names,
                            check_orphans=check_orphans)
    if any(f.severity == ERROR for f in findings):
        raise ProgramVerifyError(findings, where=where)
    return findings
