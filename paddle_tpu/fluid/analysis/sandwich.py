"""Pass-sandwich verification (the MLIR verifier convention).

Wrap a graph rewrite so the program is verified BEFORE and AFTER it
runs; error findings that were not present before are attributed to the
pass and raised. Gated on FLAGS_program_verify: flag-off, the context
manager is a flag lookup and nothing else — the rewrite paths stay
bit-identical to a build without this module.
"""
from __future__ import annotations

import contextlib
from typing import Iterable

from ..flags import flag
from .core import ERROR, ProgramVerifyError, verify_program


@contextlib.contextmanager
def pass_sandwich(program, pass_name: str, live_out: Iterable[str] = ()):
    if not flag("FLAGS_program_verify"):
        yield
        return
    before = verify_program(program, live_out=live_out)
    if any(f.severity == ERROR for f in before):
        # the input was already broken: attribute to the producer of the
        # program, not to this pass — earliest-possible diagnosis
        raise ProgramVerifyError(before,
                                 where=f"input of pass {pass_name!r}")
    seen = {f.key() for f in before}
    yield
    after = verify_program(program, live_out=live_out)
    new_errors = [f for f in after
                  if f.severity == ERROR and f.key() not in seen]
    if new_errors:
        for f in new_errors:
            f.pass_name = pass_name
        raise ProgramVerifyError(new_errors,
                                 where=f"after pass {pass_name!r}")
