"""fluid.layers namespace. Parity: python/paddle/fluid/layers/__init__.py."""
from . import control_flow, detection, distributions, loss, misc, nn, ops, rnn, sequence, tensor, vision  # noqa: F401
from .detection import *  # noqa: F401,F403
from .nn import *  # noqa: F401,F403
from .ops import *  # noqa: F401,F403
from .sequence import *  # noqa: F401,F403
from .tensor import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .rnn import *  # noqa: F401,F403
from .distributions import *  # noqa: F401,F403
from .misc import *  # noqa: F401,F403
from .vision import *  # noqa: F401,F403
from .control_flow import (  # noqa: F401
    Assert,
    DynamicRNN,
    IfElse,
    Print,
    StaticRNN,
    Switch,
    While,
    array_length,
    array_read,
    array_write,
    case,
    cond,
    create_array,
    py_func,
    switch_case,
    tensor_array_to_tensor,
    while_loop,
)
