"""fluid.layers namespace. Parity: python/paddle/fluid/layers/__init__.py."""
from . import control_flow, detection, loss, misc, nn, ops, sequence, tensor, vision  # noqa: F401
from .detection import *  # noqa: F401,F403
from .nn import *  # noqa: F401,F403
from .ops import *  # noqa: F401,F403
from .sequence import *  # noqa: F401,F403
from .tensor import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .misc import *  # noqa: F401,F403
from .vision import *  # noqa: F401,F403
from .control_flow import StaticRNN, case, cond, py_func, switch_case, while_loop  # noqa: F401
