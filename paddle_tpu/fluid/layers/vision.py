"""Vision / image layers.

Parity surface: reference python/paddle/fluid/layers/nn.py — conv3d,
conv3d_transpose, pool3d, adaptive_pool3d, image_resize(+short),
resize_{bilinear,nearest,linear,trilinear}, grid_sampler, affine_grid,
affine_channel, pixel_shuffle, shuffle_channel, space_to_depth,
temporal_shift, lrn, unfold, im2sequence, roi_pool, spectral_norm,
data_norm, crop(_tensor), pad_constant_like, random_crop.
"""
from __future__ import annotations

import numpy as np

from ..framework import Variable
from ..initializer import ConstantInitializer, NormalInitializer
from ..layer_helper import LayerHelper
from ..param_attr import ParamAttr

__all__ = [
    "conv3d", "conv3d_transpose", "pool3d", "adaptive_pool3d",
    "image_resize", "resize_bilinear", "resize_nearest", "resize_trilinear",
    "resize_linear", "image_resize_short", "affine_grid", "grid_sampler",
    "affine_channel", "pixel_shuffle", "shuffle_channel", "space_to_depth",
    "temporal_shift", "lrn", "unfold", "im2sequence",
    "roi_pool", "spectral_norm", "data_norm", "crop_tensor",
    "crop", "pad_constant_like", "random_crop",
]


def _triple(v):
    return [v, v, v] if isinstance(v, int) else list(v)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCDHW"):
    """NCDHW 3D convolution (reference layers/nn.py conv3d)."""
    helper = LayerHelper("conv3d", param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    dtype = input.dtype
    num_channels = input.shape[1]
    fs = _triple(filter_size)
    filter_shape = [num_filters, num_channels // groups] + fs
    std = (2.0 / (fs[0] * fs[1] * fs[2] * num_channels)) ** 0.5
    w = helper.create_parameter(
        helper.param_attr, shape=filter_shape, dtype=dtype,
        default_initializer=NormalInitializer(0.0, std),
    )
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="conv3d",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={"strides": _triple(stride), "paddings": _triple(padding),
               "dilations": _triple(dilation), "groups": groups},
    )
    pre_act = helper.append_bias_op(out, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None):
    helper = LayerHelper("conv3d_transpose", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    num_channels = input.shape[1]
    if filter_size is None:
        if output_size is None:
            raise ValueError("conv3d_transpose: need filter_size or output_size")
        # reference conv3d_transpose: k = out - (in-1)*stride + 2*pad
        outs = _triple(output_size) if not isinstance(output_size, int) else _triple(output_size)
        st, pd = _triple(stride), _triple(padding)
        filter_size = [
            outs[i] - (input.shape[2 + i] - 1) * st[i] + 2 * pd[i]
            for i in range(3)
        ]
        if any(k <= 0 for k in filter_size):
            raise ValueError(
                f"conv3d_transpose: derived non-positive filter_size "
                f"{filter_size} from output_size {outs}"
            )
    fs = _triple(filter_size)
    w = helper.create_parameter(
        helper.param_attr,
        shape=[num_channels, num_filters // (groups or 1)] + fs,
        dtype=dtype,
    )
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="conv3d_transpose",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={"strides": _triple(stride), "paddings": _triple(padding),
               "dilations": _triple(dilation), "groups": groups or 1},
    )
    pre_act = helper.append_bias_op(out, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, name=None, exclusive=True,
           data_format="NCDHW"):
    helper = LayerHelper("pool3d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="pool3d", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"pooling_type": pool_type, "ksize": _triple(pool_size),
               "strides": _triple(pool_stride),
               "paddings": _triple(pool_padding),
               "global_pooling": global_pooling, "exclusive": exclusive},
    )
    return out


def adaptive_pool3d(input, pool_size, pool_type="max",
                    require_index=False, name=None):
    if require_index:
        raise NotImplementedError("adaptive_pool3d require_index")
    helper = LayerHelper("adaptive_pool3d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="pool3d", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"pooling_type": pool_type, "ksize": _triple(pool_size),
               "adaptive": True},
    )
    return out


# ---------------------------------------------------------------------------
# resize family
# ---------------------------------------------------------------------------

_INTERP_OPS = {
    "BILINEAR": ("bilinear_interp", 2),
    "NEAREST": ("nearest_interp", 2),
    "TRILINEAR": ("trilinear_interp", 3),
    "LINEAR": ("linear_interp", 1),
}


def image_resize(input, out_shape=None, scale=None, name=None,
                 resample="BILINEAR", actual_shape=None, align_corners=True,
                 align_mode=1, data_format="NCHW"):
    """Resize spatial dims (reference layers/nn.py image_resize). out_shape
    must be static ints on TPU (XLA static shapes); `scale` computes one."""
    resample = resample.upper()
    if resample not in _INTERP_OPS:
        raise ValueError(f"image_resize: unknown resample {resample}")
    op_type, ndim = _INTERP_OPS[resample]
    spatial = list(input.shape[2:])
    if out_shape is None:
        if scale is None:
            raise ValueError("image_resize: need out_shape or scale")
        out_shape = [int(d * scale) for d in spatial]
    out_shape = [int(v) for v in out_shape]
    if len(out_shape) != ndim:
        raise ValueError(f"{resample} expects {ndim}-D out_shape")
    helper = LayerHelper("image_resize", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    attrs = {"align_corners": align_corners, "align_mode": align_mode}
    if ndim == 1:
        attrs["out_w"] = out_shape[0]
    elif ndim == 2:
        attrs["out_h"], attrs["out_w"] = out_shape
    else:
        attrs["out_d"], attrs["out_h"], attrs["out_w"] = out_shape
    helper.append_op(type=op_type, inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs=attrs)
    return out


def resize_bilinear(input, out_shape=None, scale=None, name=None,
                    actual_shape=None, align_corners=True, align_mode=1,
                    data_format="NCHW"):
    return image_resize(input, out_shape, scale, name, "BILINEAR",
                        actual_shape, align_corners, align_mode)


def resize_nearest(input, out_shape=None, scale=None, name=None,
                   actual_shape=None, align_corners=True,
                   data_format="NCHW"):
    return image_resize(input, out_shape, scale, name, "NEAREST",
                        actual_shape, align_corners)


def resize_trilinear(input, out_shape=None, scale=None, name=None,
                     actual_shape=None, align_corners=True, align_mode=1,
                     data_format="NCDHW"):
    return image_resize(input, out_shape, scale, name, "TRILINEAR",
                        actual_shape, align_corners, align_mode)


def resize_linear(input, out_shape=None, scale=None, name=None,
                  actual_shape=None, align_corners=True, align_mode=1,
                  data_format="NCW"):
    return image_resize(input, out_shape, scale, name, "LINEAR",
                        actual_shape, align_corners, align_mode)


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    """Resize so the SHORT side equals out_short_len, keeping aspect."""
    h, w = input.shape[2], input.shape[3]
    short = min(h, w)
    out = [int(round(h * out_short_len / short)),
           int(round(w * out_short_len / short))]
    return image_resize(input, out_shape=out, resample=resample)


# ---------------------------------------------------------------------------
# sampling / geometric
# ---------------------------------------------------------------------------


def affine_grid(theta, out_shape, name=None):
    helper = LayerHelper("affine_grid", name=name)
    if isinstance(out_shape, Variable):
        raise NotImplementedError("affine_grid: out_shape must be static ints")
    out = helper.create_variable_for_type_inference(theta.dtype)
    helper.append_op(
        type="affine_grid", inputs={"Theta": [theta]},
        outputs={"Output": [out]},
        attrs={"output_shape": [int(v) for v in out_shape]},
    )
    return out


def grid_sampler(x, grid, name=None):
    helper = LayerHelper("grid_sampler", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="grid_sampler", inputs={"X": [x], "Grid": [grid]},
                     outputs={"Output": [out]})
    return out


def affine_channel(x, scale=None, bias=None, data_layout="NCHW", name=None,
                   act=None):
    """Per-channel scale+bias (reference affine_channel_op.cc) — a pure
    composition: reshape scale/bias onto the channel dim."""
    from . import nn as _nn

    ch_dim = 1 if data_layout == "NCHW" else len(x.shape) - 1
    shape = [1] * len(x.shape)
    shape[ch_dim] = x.shape[ch_dim]
    helper = LayerHelper("affine_channel", name=name, act=act)
    out = x
    if scale is not None:
        out = _nn.elementwise_mul(out, _nn.reshape(scale, shape))
    if bias is not None:
        out = _nn.elementwise_add(out, _nn.reshape(bias, shape))
    return helper.append_activation(out)


def pixel_shuffle(x, upscale_factor):
    helper = LayerHelper("pixel_shuffle")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="pixel_shuffle", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"upscale_factor": int(upscale_factor)})
    return out


def shuffle_channel(x, group, name=None):
    helper = LayerHelper("shuffle_channel", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="shuffle_channel", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"group": int(group)})
    return out


def space_to_depth(x, blocksize, name=None):
    helper = LayerHelper("space_to_depth", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="space_to_depth", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"blocksize": int(blocksize)})
    return out


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None):
    helper = LayerHelper("temporal_shift", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="temporal_shift", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"seg_num": int(seg_num),
                            "shift_ratio": float(shift_ratio)})
    return out


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None,
        data_format="NCHW"):
    helper = LayerHelper("lrn", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    mid = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="lrn", inputs={"X": [input]},
                     outputs={"Out": [out], "MidOut": [mid]},
                     attrs={"n": n, "k": k, "alpha": alpha, "beta": beta})
    return out


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    helper = LayerHelper("unfold", name=name)
    if isinstance(kernel_sizes, int):
        kernel_sizes = [kernel_sizes, kernel_sizes]
    if isinstance(strides, int):
        strides = [strides, strides]
    if isinstance(paddings, int):
        paddings = [paddings] * 4
    if isinstance(dilations, int):
        dilations = [dilations, dilations]
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="unfold", inputs={"X": [x]}, outputs={"Y": [out]},
                     attrs={"kernel_sizes": kernel_sizes, "strides": strides,
                            "paddings": paddings, "dilations": dilations})
    return out


def im2sequence(input, filter_size=1, stride=1, padding=0, input_image_size=None,
                out_stride=1, name=None):
    helper = LayerHelper("im2sequence", name=name)
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    if isinstance(stride, int):
        stride = [stride, stride]
    if isinstance(padding, int):
        padding = [padding] * 4
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="im2sequence", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"kernels": filter_size, "strides": stride,
                            "paddings": padding})
    return out


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0, rois_num=None, batch_ids=None, name=None):
    """rois [R, 4]; batch_ids [R] gives each ROI's image index (dense
    replacement for the reference's LoD batching; default all image 0).
    rois_num (per-IMAGE counts, a 2.x convenience) would need a
    data-dependent expansion to per-ROI ids — pass batch_ids instead."""
    if rois_num is not None:
        raise NotImplementedError(
            "roi_pool: per-image rois_num needs dynamic expansion; pass "
            "per-ROI batch_ids (shape [R]) instead"
        )
    helper = LayerHelper("roi_pool", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"X": [input], "ROIs": [rois]}
    if batch_ids is not None:
        inputs["BatchId"] = [batch_ids]
    helper.append_op(type="roi_pool", inputs=inputs, outputs={"Out": [out]},
                     attrs={"pooled_height": int(pooled_height),
                            "pooled_width": int(pooled_width),
                            "spatial_scale": float(spatial_scale)})
    return out


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """Spectrally-normalized weight (reference layers/nn.py spectral_norm):
    U/V power-iteration vectors are persistable state parameters."""
    helper = LayerHelper("spectral_norm", name=name)
    dtype = weight.dtype
    h = weight.shape[dim]
    w = int(np.prod([s for i, s in enumerate(weight.shape) if i != dim]))
    u = helper.create_parameter(
        ParamAttr(name=f"{helper.name}.u", trainable=False), shape=[h],
        dtype=dtype, default_initializer=NormalInitializer(0.0, 1.0),
    )
    v = helper.create_parameter(
        ParamAttr(name=f"{helper.name}.v", trainable=False), shape=[w],
        dtype=dtype, default_initializer=NormalInitializer(0.0, 1.0),
    )
    u.stop_gradient = True
    v.stop_gradient = True
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="spectral_norm", inputs={"Weight": [weight], "U": [u], "V": [v]},
        outputs={"Out": [out]},
        attrs={"dim": int(dim), "power_iters": int(power_iters),
               "eps": float(eps)},
    )
    return out


def data_norm(input, act=None, epsilon=1e-4, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              summary_decay_rate=0.9999999):
    """Accumulator-based normalization (reference layers/nn.py data_norm)."""
    helper = LayerHelper("data_norm", name=name, act=act)
    dtype = input.dtype
    d = input.shape[-1]
    bsize = helper.create_parameter(
        ParamAttr(name=f"{helper.name}.batch_size", trainable=False),
        shape=[d], dtype=dtype,
        default_initializer=ConstantInitializer(1e4))
    bsum = helper.create_parameter(
        ParamAttr(name=f"{helper.name}.batch_sum", trainable=False),
        shape=[d], dtype=dtype, default_initializer=ConstantInitializer(0.0))
    bsq = helper.create_parameter(
        ParamAttr(name=f"{helper.name}.batch_square_sum", trainable=False),
        shape=[d], dtype=dtype, default_initializer=ConstantInitializer(1e4))
    for p in (bsize, bsum, bsq):
        p.stop_gradient = True
    out = helper.create_variable_for_type_inference(dtype)
    means = helper.create_variable_for_type_inference(dtype)
    scales = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="data_norm",
        inputs={"X": [input], "BatchSize": [bsize], "BatchSum": [bsum],
                "BatchSquareSum": [bsq]},
        outputs={"Y": [out], "Means": [means], "Scales": [scales]},
        attrs={"epsilon": epsilon},
    )
    return helper.append_activation(out)


# ---------------------------------------------------------------------------
# crop / pad
# ---------------------------------------------------------------------------


def crop_tensor(x, shape=None, offsets=None, name=None):
    """Static crop (reference crop_tensor): slice `shape` starting at
    `offsets` (default 0s). Dynamic shape/offsets tensors unsupported (XLA
    static shapes)."""
    from . import nn as _nn

    if shape is None:
        raise ValueError("crop_tensor: shape required")
    offsets = offsets or [0] * len(x.shape)
    if isinstance(shape, Variable) or isinstance(offsets, Variable):
        raise NotImplementedError("crop_tensor: static ints only on TPU")
    axes = list(range(len(x.shape)))
    starts = [int(o) for o in offsets]
    ends = [int(o) + int(s) for o, s in zip(offsets, shape)]
    return _nn.slice(x, axes=axes, starts=starts, ends=ends)


def crop(x, shape=None, offsets=None, name=None):
    if isinstance(shape, Variable):
        shape = shape.shape
    return crop_tensor(x, shape=shape, offsets=offsets, name=name)


def pad_constant_like(x, y, pad_value=0.0, name=None):
    """Pad y up to x's shape with pad_value (reference
    pad_constant_like_op.cc)."""
    from . import nn as _nn

    paddings = []
    for xs, ys in zip(x.shape, y.shape):
        paddings += [0, int(xs) - int(ys)]
    return _nn.pad(y, paddings, pad_value=pad_value)


def random_crop(x, shape, seed=None):
    """Random spatial crop via uniform offsets (reference random_crop_op):
    batch-uniform offsets (one crop position per graph build)."""
    import numpy as _np

    rng = _np.random.RandomState(seed)
    offsets = [0] * (len(x.shape) - len(shape)) + [
        int(rng.randint(0, int(xs) - int(s) + 1))
        for xs, s in zip(x.shape[len(x.shape) - len(shape):], shape)
    ]
    full_shape = list(x.shape[: len(x.shape) - len(shape)]) + list(shape)
    return crop_tensor(x, shape=full_shape, offsets=offsets)
