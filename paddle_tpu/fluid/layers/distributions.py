"""Probability distributions (reference layers/distributions.py):
Normal, Uniform, Categorical, MultivariateNormalDiag — sample /
log_prob / entropy / kl_divergence as op compositions.
"""
from __future__ import annotations

import math

import numpy as np

from . import misc as _misc
from . import nn as _nn
from . import ops as _ops
from . import tensor as _tensor

__all__ = ["Distribution", "Normal", "Uniform", "Categorical",
           "MultivariateNormalDiag"]


def _to_var(v, like=None):
    from ..framework import Variable

    if isinstance(v, Variable):
        return v
    arr = np.asarray(v, np.float32)
    if arr.ndim == 0:
        arr = arr.reshape(1)
    return _tensor.assign(arr)


class Distribution:
    def sample(self, shape, seed=0):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        raise NotImplementedError


class Uniform(Distribution):
    """U[low, high) (reference distributions.Uniform)."""

    def __init__(self, low, high):
        self.low = _to_var(low)
        self.high = _to_var(high)

    def sample(self, shape, seed=0):
        u = _misc.uniform_random(list(shape) + list(self.low.shape),
                                 min=0.0, max=1.0, seed=seed)
        span = _nn.elementwise_sub(self.high, self.low)
        return _nn.elementwise_add(_nn.elementwise_mul(u, span), self.low)

    def log_prob(self, value):
        span = _nn.elementwise_sub(self.high, self.low)
        lb = _tensor.cast(_tensor.less_than(self.low, value), "float32")
        ub = _tensor.cast(_tensor.less_than(value, self.high), "float32")
        inside = _nn.elementwise_mul(lb, ub)
        return _ops.log(
            _nn.elementwise_div(
                _nn.elementwise_add(
                    inside,
                    _tensor.fill_constant([1], "float32", 1e-30)),
                span))

    def entropy(self):
        return _ops.log(_nn.elementwise_sub(self.high, self.low))


class Normal(Distribution):
    """N(loc, scale) (reference distributions.Normal)."""

    def __init__(self, loc, scale):
        self.loc = _to_var(loc)
        self.scale = _to_var(scale)

    def sample(self, shape, seed=0):
        z = _misc.gaussian_random(list(shape) + list(self.loc.shape),
                                  mean=0.0, std=1.0, seed=seed)
        return _nn.elementwise_add(
            _nn.elementwise_mul(z, self.scale), self.loc)

    def log_prob(self, value):
        var = _ops.square(self.scale)
        d = _nn.elementwise_sub(value, self.loc)
        return _nn.elementwise_sub(
            _nn.elementwise_sub(
                _nn.scale(_nn.elementwise_div(_ops.square(d), var), -0.5),
                _ops.log(self.scale)),
            _tensor.fill_constant([1], "float32",
                                  0.5 * math.log(2.0 * math.pi)))

    def entropy(self):
        return _nn.elementwise_add(
            _ops.log(self.scale),
            _tensor.fill_constant([1], "float32",
                                  0.5 + 0.5 * math.log(2.0 * math.pi)))

    def kl_divergence(self, other):
        var_ratio = _ops.square(
            _nn.elementwise_div(self.scale, other.scale))
        t1 = _ops.square(
            _nn.elementwise_div(
                _nn.elementwise_sub(self.loc, other.loc), other.scale))
        return _nn.scale(
            _nn.elementwise_sub(
                _nn.elementwise_add(var_ratio, t1),
                _nn.scale(_ops.log(var_ratio), bias=1.0)),
            0.5)


class Categorical(Distribution):
    """Categorical over logits (reference distributions.Categorical)."""

    def __init__(self, logits):
        self.logits = logits

    def _probs(self):
        return _nn.softmax(self.logits)

    def sample(self, shape=None, seed=0):
        from .misc import sampling_id

        return sampling_id(self._probs(), seed=seed)

    def log_prob(self, value):
        logp = _nn.log_softmax(self.logits)
        depth = self.logits.shape[-1]
        oh = _nn.one_hot(value, depth)
        return _nn.reduce_sum(_nn.elementwise_mul(logp, oh), dim=[-1])

    def entropy(self):
        p = self._probs()
        logp = _nn.log_softmax(self.logits)
        return _nn.scale(
            _nn.reduce_sum(_nn.elementwise_mul(p, logp), dim=[-1]), -1.0)

    def kl_divergence(self, other):
        p = self._probs()
        diff = _nn.elementwise_sub(
            _nn.log_softmax(self.logits), _nn.log_softmax(other.logits))
        return _nn.reduce_sum(_nn.elementwise_mul(p, diff), dim=[-1])


class MultivariateNormalDiag(Distribution):
    """N(loc, diag(scale)) (reference distributions.MultivariateNormalDiag)."""

    def __init__(self, loc, scale):
        self.loc = loc          # [.., D]
        self.scale = scale      # [.., D] diag entries

    def sample(self, shape=None, seed=0):
        z = _misc.gaussian_random(list(self.loc.shape), 0.0, 1.0, seed=seed)
        return _nn.elementwise_add(
            _nn.elementwise_mul(z, self.scale), self.loc)

    def entropy(self):
        d = self.loc.shape[-1]
        logdet = _nn.reduce_sum(_ops.log(self.scale), dim=[-1])
        return _nn.scale(
            logdet, bias=0.5 * d * (1.0 + math.log(2.0 * math.pi)))

    def kl_divergence(self, other):
        var1 = _ops.square(self.scale)
        var2 = _ops.square(other.scale)
        d = _nn.elementwise_sub(self.loc, other.loc)
        tr = _nn.reduce_sum(_nn.elementwise_div(var1, var2), dim=[-1])
        quad = _nn.reduce_sum(
            _nn.elementwise_div(_ops.square(d), var2), dim=[-1])
        logdet = _nn.reduce_sum(
            _nn.elementwise_sub(_ops.log(var2), _ops.log(var1)), dim=[-1])
        k = self.loc.shape[-1]
        return _nn.scale(
            _nn.elementwise_add(_nn.elementwise_add(tr, quad),
                                _nn.scale(logdet, 1.0, bias=-float(k))),
            0.5)
